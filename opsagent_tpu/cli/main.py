"""The opsagent CLI.

Capability parity with the reference's cmd/kube-copilot/: root command with
persistent flags --model/--max-tokens/--count-tokens/--verbose/
--max-iterations (main.go:28-32) and subcommands server (server.go), execute
(execute.go), analyze (analyze.go), audit (audit.go), diagnose (diagnose.go),
generate (generate.go), version (version.go). Unlike the reference fork —
which registers only ``server`` (main.go:34) and leaves the other commands as
dead code — every subcommand here is wired up. A new ``serve-engine``
subcommand starts the in-tree TPU serving engine.
"""

from __future__ import annotations

import argparse
import os
import sys

from .. import VERSION
from ..utils.config import load_config
from ..utils.globalstore import set_global
from ..utils.logger import get_logger, init_logger
from ..utils.perf import get_perf_stats


def _add_common(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--model", default="gpt-4", help="model name or tpu://<model>")
    parser.add_argument("--max-tokens", type=int, default=2048)
    parser.add_argument("--count-tokens", action="store_true", default=False)
    parser.add_argument("--verbose", action="store_true", default=False)
    parser.add_argument("--max-iterations", type=int, default=10)
    parser.add_argument("--api-key", default="", help="LLM API key (else env)")
    parser.add_argument("--base-url", default="", help="LLM base URL (else env)")
    parser.add_argument(
        "--metrics", action="store_true", default=False,
        help="print the Prometheus /metrics exposition to stderr after "
             "the run (same text a scrape of a server would return)",
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="opsagent",
        description="Kubernetes AI agent with an in-tree TPU serving engine",
    )
    p.add_argument("--config", default="", help="path to config.yaml")
    sub = p.add_subparsers(dest="command")

    sp = sub.add_parser("server", help="run the REST API server")
    sp.add_argument("--port", type=int, default=None, help="default: config server.port")
    sp.add_argument("--host", default=None, help="default: config server.host")
    sp.add_argument("--jwt-key", default="")
    sp.add_argument("--show-thought", action="store_true", default=False)
    _add_common(sp)

    ex = sub.add_parser("execute", help="execute operations based on prompt instructions")
    ex.add_argument("instructions", nargs="+")
    _add_common(ex)

    an = sub.add_parser("analyze", help="analyze issues for a given resource")
    an.add_argument("--resource", default="pod")
    an.add_argument("--name", required=True)
    an.add_argument("--namespace", default="default")
    _add_common(an)

    au = sub.add_parser("audit", help="audit security issues for a pod")
    au.add_argument("--name", required=True)
    au.add_argument("--namespace", default="default")
    _add_common(au)

    af = sub.add_parser(
        "audit-fanout",
        help="fan one audit out over a synthetic cluster: N batch-class "
             "child sessions sharing one prefix chain through an "
             "in-process fleet, reduced to one deterministic report "
             "(exit 0 all children ok, 1 any finding_unavailable)",
    )
    af.add_argument("--model", default="tiny-test")
    af.add_argument(
        "--resources", type=int, default=64,
        help="synthetic cluster size (= fan-out children)",
    )
    af.add_argument("--seed", type=int, default=0)
    af.add_argument(
        "--issue-fraction", type=float, default=0.25,
        help="fraction of resources given an injected issue",
    )
    af.add_argument(
        "--replicas", type=int, default=2,
        help="in-process decode replicas behind the router",
    )
    af.add_argument(
        "--max-inflight", type=int, default=8,
        help="bounded scatter concurrency (the fan-out admission gate)",
    )
    af.add_argument("--max-tokens", type=int, default=16)
    af.add_argument(
        "--flight-sample", type=int, default=0,
        help=">1: sample admission/dispatch flight kinds 1-in-N during "
             "the wave (flood control)",
    )
    af.add_argument(
        "--json", action="store_true",
        help="print the canonical byte-stable report form",
    )
    af.add_argument(
        "--out", default="", help="also write the canonical report here",
    )

    di = sub.add_parser("diagnose", help="diagnose problems for a pod")
    di.add_argument("--name", required=True)
    di.add_argument("--namespace", default="default")
    _add_common(di)

    ge = sub.add_parser("generate", help="generate manifests and optionally apply")
    ge.add_argument("prompt", nargs="+")
    ge.add_argument("--yes", action="store_true", help="apply without confirmation")
    _add_common(ge)

    sub.add_parser("version", help="print version")

    sc = sub.add_parser(
        "slo-check",
        help="evaluate the declared serving SLOs (bench/CI gate: exit 0 "
             "pass, 1 breach, 2 no data)",
    )
    sc.add_argument(
        "--url", default="",
        help="base URL of a running server; fetches GET /api/slo",
    )
    sc.add_argument(
        "--bench", default="",
        help="BENCH json/jsonl file; reads the extra.slo verdicts "
             "bench.py folded in",
    )
    sc.add_argument(
        "--class", dest="slo_class", default="",
        choices=["", "interactive", "batch", "background"],
        help="gate one SLO class's attainment/burn (from the per-class "
             "report) instead of the global verdicts",
    )

    tp = sub.add_parser(
        "top",
        help="live fleet cockpit: replica table, per-class SLO rows "
             "with history sparklines, anomaly tail (ANSI, no curses)",
    )
    tp.add_argument(
        "--url", default="http://127.0.0.1:8090",
        help="base URL of a fleet router (or a single engine/agent "
             "server — the replica table degrades gracefully)",
    )
    tp.add_argument(
        "--interval", type=float, default=2.0,
        help="seconds between frames",
    )
    tp.add_argument(
        "--frames", type=int, default=0,
        help="render N frames then exit (0 = until interrupted)",
    )
    tp.add_argument(
        "--no-color", action="store_true", default=False,
        help="disable ANSI colors even on a TTY",
    )

    pc = sub.add_parser(
        "perf-check",
        help="compare a fresh bench jsonl against the committed "
             "BENCH_r*_local.jsonl baseline with noise tolerances "
             "(CI gate: exit 0 pass, 1 regression, 2 nothing comparable)",
    )
    pc.add_argument(
        "current",
        help="fresh bench jsonl (result lines), or a fleet router URL "
             "(http://...: live rows from GET /api/fleet/bench)",
    )
    pc.add_argument(
        "--baseline", default="",
        help="baseline jsonl (default: newest committed BENCH_r*_local.jsonl)",
    )
    pc.add_argument(
        "--tolerance", type=float, default=None,
        help="global relative tolerance (default 10%%, TTFT series 25%%)",
    )
    pc.add_argument(
        "--tolerances", default="",
        help="JSON file of {metric substring: tolerance} overrides",
    )

    tl = sub.add_parser(
        "timeline",
        help="render a request's lifecycle timeline as an ASCII Gantt "
             "(queue -> prefill -> decode -> tool-blocked, with the "
             "goodput split)",
    )
    tl.add_argument("request_id", help="request id (chatcmpl-... / req-...)")
    tl.add_argument(
        "--url", default="",
        help="base URL of a running server; fetches "
             "GET /api/timeline/{request_id}",
    )
    tl.add_argument(
        "--token", default="",
        help="bearer token for the agent server's JWT-guarded /api/ tree",
    )
    tl.add_argument(
        "--file", default="",
        help="read the timeline JSON from a file instead (e.g. the "
             "'timeline' line of a flight anomaly dump)",
    )
    tl.add_argument("--width", type=int, default=64, help="gantt bar width")
    tl.add_argument(
        "--json", action="store_true", default=False,
        help="print the raw timeline JSON instead of the gantt",
    )

    fkv = sub.add_parser(
        "fleet-kv",
        help="dump the fleet router's global KV page directory (which "
             "replica owns which prefix chains, tier footprints, "
             "advertisement staleness)",
    )
    fkv.add_argument(
        "--url", default="http://127.0.0.1:8090",
        help="fleet router base URL; fetches GET /api/fleet/directory",
    )
    fkv.add_argument(
        "--limit", type=int, default=256,
        help="max chain rows to fetch (the directory can hold thousands)",
    )
    fkv.add_argument(
        "--json", action="store_true", default=False,
        help="print the raw directory JSON instead of the table",
    )

    ffl = sub.add_parser(
        "fleet-flight",
        help="dump the fleet flight ledger: every replica's flight "
             "ring merged into one replica-tagged, skew-corrected, "
             "time-ordered event stream",
    )
    ffl.add_argument(
        "--url", default="http://127.0.0.1:8090",
        help="fleet router base URL; fetches GET /api/fleet/flight",
    )
    ffl.add_argument(
        "--n", type=int, default=64,
        help="merged event tail length (0 = everything in the rings)",
    )
    ffl.add_argument("--kind", default="", help="filter by event kind")
    ffl.add_argument(
        "--request-id", default="",
        help="filter to one journey's events (implies no tail cap)",
    )
    ffl.add_argument(
        "--json", action="store_true", default=False,
        help="print the raw ledger JSON instead of the table",
    )

    se = sub.add_parser("serve-engine", help="run the TPU serving engine (OpenAI-compatible)")
    se.add_argument("--port", type=int, default=8000)
    se.add_argument("--host", default="0.0.0.0")
    se.add_argument("--model-name", default="tiny-test",
                    help="model preset, or 'auto' to derive the "
                         "architecture from --checkpoint's config.json")
    se.add_argument("--checkpoint", default="", help="safetensors checkpoint dir")
    se.add_argument("--tokenizer", default="", help="HF tokenizer path (else byte tokenizer)")
    se.add_argument("--tp", type=int, default=0, help="tensor-parallel size (0 = all devices)")
    se.add_argument("--sp", type=int, default=1, help="sequence-parallel size for long-context prefill (ragged ring attention)")
    se.add_argument("--ep", type=int, default=1, help="expert-parallel size for MoE models (experts shard over ep)")
    se.add_argument(
        "--speculative-k", type=int, default=0,
        help="prompt-lookup speculative decoding: draft k tokens per decode "
             "iteration from the sequence's own history (exact for greedy). "
             "Measured ~6%% draft acceptance on the agent JSON workload "
             "(PERF.md) — enable only for genuinely repetitive outputs. "
             "0 disables",
    )
    se.add_argument("--max-batch-size", type=int, default=8)
    se.add_argument(
        "--quantize",
        default="",
        choices=("", "int8", "int4"),
        help="weight-only quantization: int8 halves weight HBM traffic "
             "and fits 8B-class models on one v5e chip; int4 (group-wise "
             "scales) halves it again for more decode throughput at some "
             "fidelity cost",
    )
    se.add_argument(
        "--kv-quantize",
        default="",
        choices=("", "int8"),
        help="KV-cache quantization: int8 pages + per-token scales halve "
             "decode-step KV reads (the dominant non-weight HBM term at "
             "serving shapes); not supported for MLA models",
    )
    se.add_argument(
        "--offload",
        action="store_true",
        default=False,
        help="hierarchical KV cache: spill evicted/parked KV pages to a "
             "bounded host-RAM pool (OPSAGENT_KV_HOST_POOL_BYTES, default "
             "1 GiB) and restore them on re-admission instead of "
             "re-prefilling — tool-blocked agent sessions stop pinning "
             "HBM between turns",
    )
    se.add_argument(
        "--async-depth",
        type=int,
        default=2,
        help="mixed-tick dispatch pipeline depth: 2 (default) enqueues "
             "tick t+1 before tick t's tokens are pulled to host "
             "(decode feedback stays device-resident), overlapping "
             "detokenize/stop-scan/streaming with device compute; "
             "1 = synchronous ticks",
    )
    se.add_argument(
        "--platform",
        default="",
        choices=("", "tpu", "cpu"),
        help="force the JAX platform (default: environment's choice)",
    )
    se.add_argument(
        "--profile-dir",
        default="",
        help="capture jax.profiler device traces into this directory "
             "(also enables device.* per-step timings in /api/perf/stats)",
    )
    se.add_argument(
        "--join-fleet", default="",
        help="fleet router base URL (opsagent serve-router): register "
             "this replica, heartbeat load + prefix digests, accept "
             "routed traffic and KV-page transfers",
    )
    se.add_argument(
        "--advertise", default="",
        help="URL the router should reach this replica at "
             "(default: http://<host>:<port>)",
    )
    se.add_argument(
        "--replica-id", default="",
        help="stable replica identity in the fleet (default: random)",
    )
    se.add_argument(
        "--replica-role", default="decode",
        choices=("decode", "prefill", "standby"),
        help="decode replicas serve sessions end-to-end; prefill "
             "replicas take the router's long cold admissions and hand "
             "their KV to a decode replica over the transfer path; "
             "standby replicas are registered but unroutable until the "
             "router's autoscaler promotes them to decode",
    )
    se.add_argument(
        "--restore-snapshot", default="",
        help="boot from an `opsagent snapshot create` directory instead "
             "of fresh init: weights mmap straight to device in recorded "
             "layout and warmup replays the packaged compile cache — "
             "model/engine flags are taken from the snapshot",
    )
    se.add_argument(
        "--compile-cache-dir", default="",
        help="persistent XLA compile cache directory (sets "
             "OPSAGENT_COMPILE_CACHE_DIR; survives restarts, shared "
             "across processes)",
    )

    sr = sub.add_parser(
        "serve-router",
        help="run the fleet router: spreads sessions over N engine "
             "replicas with prefix-affinity + least-loaded placement, "
             "sticky pinning, KV-page session migration, and graceful "
             "drain (serving/fleet)",
    )
    sr.add_argument("--port", type=int, default=8090)
    sr.add_argument("--host", default="0.0.0.0")
    sr.add_argument(
        "--tokenizer", default="",
        help="HF tokenizer path for affinity scoring — MUST match the "
             "replicas' tokenizer (else scores silently zero and "
             "placement degrades to least-loaded); default: the "
             "hermetic byte tokenizer",
    )
    sr.add_argument(
        "--model-name", default="",
        help="model family for chat-template rendering in affinity "
             "scoring (matches the replicas' --model-name)",
    )
    sr.add_argument(
        "--no-affinity", action="store_true", default=False,
        help="disable prefix-affinity scoring (least-loaded only; the "
             "bench fleet-affinity stage's OFF phase)",
    )
    sr.add_argument(
        "--queue-spill", type=int, default=None,
        help="queue depth past which a pinned/affinity replica spills "
             "the route to the rest of the fleet (default: the "
             "replica's registered capacity)",
    )
    sr.add_argument(
        "--prefill-threshold", type=int, default=256,
        help="prompt tokens at which a cold admission goes to a "
             "role=prefill replica first (when one is registered)",
    )
    sr.add_argument(
        "--heartbeat-ttl", type=float, default=None,
        help="seconds without a heartbeat before a replica is reaped "
             "(default 10, or OPSAGENT_FLEET_HEARTBEAT_TTL_S)",
    )
    sr.add_argument(
        "--max-retries", type=int, default=2,
        help="connect-phase re-routes per request before the error "
             "surfaces to the client (failover rides the per-replica "
             "circuit breaker)",
    )
    sr.add_argument(
        "--hedge-queue-depth", type=int, default=None,
        help="TTFT hedging: race a duplicate of a queued cold "
             "non-streaming admission on a second replica once the "
             "chosen replica's queue is this deep (default: off)",
    )
    sr.add_argument(
        "--shed-queue-depth", type=int, default=None,
        help="overload shedding: 429 + Retry-After for new admissions "
             "once EVERY replica's queue is this deep (default: off)",
    )
    sr.add_argument(
        "--autoscale-snapshot", default="",
        help="elastic scale-out: launch standby replicas from this "
             "`opsagent snapshot create` directory when shed pressure "
             "appears, promote them once request-ready (default: off; "
             "pair with --shed-queue-depth, the scale-up signal)",
    )
    sr.add_argument(
        "--autoscale-max-replicas", type=int, default=4,
        help="upper bound on autoscaler-launched replicas",
    )
    sr.add_argument(
        "--autoscale-port-base", type=int, default=8400,
        help="first port for autoscaler-launched engine servers "
             "(sequential from here)",
    )
    sr.add_argument(
        "--autoscale-cooldown", type=float, default=30.0,
        help="seconds between autoscaler launches",
    )

    sn = sub.add_parser(
        "snapshot",
        help="engine snapshot lifecycle: `create` captures a fully-"
             "warmed engine (weights in device layout + compile cache + "
             "KV plan) as a restart artifact; `verify` checks one "
             "without importing jax (serving/snapshot)",
    )
    snsub = sn.add_subparsers(dest="snapshot_cmd", required=True)
    snc = snsub.add_parser(
        "create",
        help="build + warm an engine, then write its snapshot directory",
    )
    snc.add_argument("--out", required=True, help="snapshot directory")
    snc.add_argument("--model", default="tiny-test")
    snc.add_argument("--checkpoint", default="")
    snc.add_argument("--tokenizer", default="")
    snc.add_argument("--tp", type=int, default=0)
    snc.add_argument("--sp", type=int, default=1)
    snc.add_argument("--ep", type=int, default=1)
    snc.add_argument("--max-batch-size", type=int, default=8)
    snc.add_argument("--quantize", default="", choices=("", "int8"))
    snc.add_argument("--kv-quantize", default="", choices=("", "int8"))
    snc.add_argument("--speculative-k", type=int, default=0)
    snc.add_argument("--offload", action="store_true", default=False)
    snc.add_argument("--async-depth", type=int, default=2)
    snc.add_argument(
        "--warmup-level", default="full",
        help="warmup sweep before capture (full/bench/bench-spec/"
             "sessions): whatever compiles here is what restore replays "
             "as cache hits",
    )
    snc.add_argument(
        "--compile-cache-dir", default="",
        help="compile cache to populate and package (default: "
             "OPSAGENT_COMPILE_CACHE_DIR, else a temp dir for the "
             "duration of the capture)",
    )
    snc.add_argument(
        "--platform", default="", choices=("", "tpu", "cpu"),
        help="force the JAX platform (default: environment's choice)",
    )
    snv = snsub.add_parser(
        "verify",
        help="check a snapshot's manifest, fingerprint, and weight-leaf "
             "digests (exit 0 ok / 1 failed / 2 unreadable)",
    )
    snv.add_argument("path", help="snapshot directory")
    snv.add_argument(
        "--quick", action="store_true", default=False,
        help="skip per-leaf content digests (existence + size only)",
    )

    return p


def _cfg_int(value, default: int) -> int:
    return default if value is None else int(value)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    cfg = load_config(args.config or None)
    log_cfg = cfg.get("log", {})
    init_logger(
        level=log_cfg.get("level", "info"),
        fmt=log_cfg.get("format", "json"),
        output=log_cfg.get("output", "stdout"),
        file_path=log_cfg.get("file", "logs/opsagent.log"),
        # Null-in-YAML (a commented-out value) falls back to the default;
        # an explicit 0 is preserved (maxBytes=0 / backupCount=0 are the
        # stdlib's "disable" idioms).
        max_size_mb=_cfg_int(log_cfg.get("max_size_mb"), 10),
        max_backups=_cfg_int(log_cfg.get("max_backups"), 10),
        retention_days=_cfg_int(log_cfg.get("max_age_days"), 7),
        compress=bool(log_cfg.get("compress", True)),
    )
    log = get_logger("cli")

    if args.command is None:
        build_parser().print_help()
        return 1

    if args.command == "version":
        print(f"opsagent {VERSION}")
        return 0

    if args.command == "slo-check":
        from .slocheck import run_slo_check

        return run_slo_check(
            url=args.url, bench=args.bench, slo_class=args.slo_class
        )

    if args.command == "top":
        from .top import run_top

        return run_top(
            args.url,
            interval_s=args.interval,
            frames=args.frames,
            color=False if args.no_color else None,
        )

    if args.command == "perf-check":
        from .perfcheck import run_perf_check

        return run_perf_check(
            args.current, baseline=args.baseline,
            tolerance=args.tolerance, tolerances_file=args.tolerances,
        )

    if args.command == "timeline":
        import json as _json

        from ..obs import timeline as obs_timeline

        if args.file:
            with open(args.file) as f:
                data = _json.load(f)
            # Accept either a bare timeline dict or a flight-dump
            # "timeline" context line ({"kind": "timeline", ...}).
            tl_data = data.get("timeline", data) if isinstance(data, dict) \
                else data
        elif args.url:
            import urllib.request

            req = urllib.request.Request(
                args.url.rstrip("/") + f"/api/timeline/{args.request_id}"
            )
            if args.token:
                req.add_header("Authorization", f"Bearer {args.token}")
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    tl_data = _json.loads(resp.read().decode())
            except Exception as e:  # noqa: BLE001 - CLI surface
                print(f"timeline fetch failed: {e}", file=sys.stderr)
                return 1
        else:
            # Same-process assembly (useful right after an in-process
            # `opsagent execute --model tpu://...` run).
            tl_data = obs_timeline.assemble(args.request_id)
            if tl_data is None:
                print(
                    f"unknown request_id {args.request_id!r} in this "
                    "process; pass --url for a running server or --file "
                    "for a dump",
                    file=sys.stderr,
                )
                return 1
        if args.json:
            print(_json.dumps(tl_data, indent=2))
        elif isinstance(tl_data, dict) and tl_data.get("fleet"):
            # Fleet-scope stitched timeline (router): multi-lane gantt
            # with one row per replica plus the router-side windows.
            print(obs_timeline.render_fleet_gantt(
                tl_data, width=args.width
            ))
        else:
            print(obs_timeline.render_gantt(tl_data, width=args.width))
        return 0

    if args.command == "fleet-kv":
        import json as _json
        import urllib.request

        url = (
            args.url.rstrip("/")
            + f"/api/fleet/directory?limit={args.limit}"
        )
        try:
            with urllib.request.urlopen(  # noqa: S310 - operator URL
                url, timeout=10
            ) as resp:
                snap = _json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 - CLI surface
            print(f"directory fetch failed: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(_json.dumps(snap, indent=2))
            return 0
        st = snap.get("stats", {})
        print(
            f"directory: {st.get('chains', 0)} chains over "
            f"{st.get('replicas', 0)} replicas | lookups "
            f"{st.get('lookups', 0)} (hits {st.get('hits', 0)}, misses "
            f"{st.get('misses', 0)}), stale evictions "
            f"{st.get('stale_evictions', 0)}"
        )
        replicas = snap.get("replicas", [])
        if replicas:
            print(f"\n{'replica':<16} {'role':<8} {'state':<9} "
                  f"{'digests':>8} {'pool pages':>11} {'hb age':>8}")
            for r in replicas:
                digests = str(r.get("digest_count", 0))
                if r.get("digest_truncated"):
                    digests += "+"
                print(
                    f"{r.get('id', '?'):<16} {r.get('role', '?'):<8} "
                    f"{r.get('state', '?'):<9} {digests:>8} "
                    f"{r.get('host_pool_pages', 0):>11} "
                    f"{r.get('heartbeat_age_s', 0):>7.1f}s"
                )
        rows = snap.get("rows", [])
        if rows:
            print(f"\n{'chain':<14} {'owners (freshest first)'}")
            for row in rows:
                owners = ", ".join(
                    f"{o.get('id', '?')} ({o.get('age_s', 0):.1f}s)"
                    for o in row.get("owners", [])
                )
                print(f"{row.get('chain', '?')[:12]:<14} {owners}")
            if snap.get("truncated"):
                print(f"... truncated at {len(rows)} rows "
                      f"(raise --limit for more)")
        return 0

    if args.command == "fleet-flight":
        import json as _json
        import urllib.request
        from urllib.parse import quote

        url = (
            args.url.rstrip("/")
            + f"/api/fleet/flight?n={args.n}"
            + (f"&kind={quote(args.kind)}" if args.kind else "")
            + (
                f"&request_id={quote(args.request_id)}"
                if args.request_id else ""
            )
        )
        try:
            with urllib.request.urlopen(  # noqa: S310 - operator URL
                url, timeout=15
            ) as resp:
                ledger = _json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 - CLI surface
            print(f"fleet flight fetch failed: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(_json.dumps(ledger, indent=2))
            return 0
        offsets = ledger.get("clock_offset_s", {})
        if offsets:
            print("clock offsets: " + ", ".join(
                f"{r}={o * 1e3:+.1f}ms" for r, o in sorted(offsets.items())
            ))
        events = ledger.get("events", [])
        print(f"{len(events)} events from "
              f"{len(ledger.get('replicas', []))} replicas\n")
        for e in events:
            wall = e.get("wall_corrected", e.get("wall", 0.0))
            extras = " ".join(
                f"{k}={v}" for k, v in e.items()
                if k not in ("kind", "source", "replica", "wall",
                             "wall_corrected", "ts", "id")
            )
            print(f"{wall:>17.6f} {e.get('source', '?'):<10} "
                  f"{e.get('kind', '?'):<18} {extras}")
        return 0

    if args.command == "server":
        # Precedence: flag > env (how k8s Secrets are injected,
        # deploy/kubernetes/deployment-prod.yaml) > config file.
        jwt_key = (
            args.jwt_key
            or os.environ.get("OPSAGENT_JWT_KEY", "")
            or cfg.get("jwt", {}).get("key", "")
        )
        set_global("jwtKey", jwt_key)
        set_global("showThought", args.show_thought)
        from ..server.app import run_server

        srv_cfg = cfg.get("server", {})
        run_server(
            host=args.host or srv_cfg.get("host", "0.0.0.0"),
            port=args.port or srv_cfg.get("port", 8080),
        )
        return 0

    if args.command == "serve-engine":
        if args.profile_dir:
            # One env var drives both the trace destination and the
            # device.* per-step timings (utils/profiling.py reads it).
            os.environ["OPSAGENT_PROFILE_DIR"] = args.profile_dir
            os.environ.setdefault("OPSAGENT_DEVICE_TIMING", "1")
        if args.platform:
            # jax may already be imported (TPU-plugin sitecustomize), so the
            # config update is the only reliable override.
            import jax

            jax.config.update("jax_platforms", args.platform)
        try:
            from ..serving.api import run_engine_server
        except ImportError as e:
            print(f"serving engine unavailable: {e}", file=sys.stderr)
            return 1

        run_engine_server(
            host=args.host,
            port=args.port,
            model_name=args.model_name,
            checkpoint=args.checkpoint,
            tokenizer=args.tokenizer,
            tp=args.tp,
            sp=args.sp,
            ep=args.ep,
            max_batch_size=args.max_batch_size,
            quantize=args.quantize,
            kv_quantize=args.kv_quantize,
            speculative_k=args.speculative_k,
            offload=args.offload,
            async_depth=args.async_depth,
            join_fleet=args.join_fleet,
            advertise=args.advertise,
            replica_id=args.replica_id,
            replica_role=args.replica_role,
            restore_snapshot=args.restore_snapshot,
            compile_cache_dir=args.compile_cache_dir,
        )
        return 0

    if args.command == "serve-router":
        # The router never builds an engine — only a tokenizer for
        # affinity scoring and the HTTP/registry plumbing.
        from ..serving.fleet.router import run_router_server

        run_router_server(
            host=args.host,
            port=args.port,
            tokenizer=args.tokenizer,
            model_name=args.model_name,
            affinity=not args.no_affinity,
            queue_spill=args.queue_spill,
            prefill_threshold=args.prefill_threshold,
            heartbeat_ttl_s=args.heartbeat_ttl,
            max_retries=args.max_retries,
            hedge_queue_depth=args.hedge_queue_depth,
            shed_queue_depth=args.shed_queue_depth,
            autoscale_snapshot=args.autoscale_snapshot,
            autoscale_max_replicas=args.autoscale_max_replicas,
            autoscale_port_base=args.autoscale_port_base,
            autoscale_cooldown_s=args.autoscale_cooldown,
        )
        return 0

    if args.command == "snapshot":
        import json as _json

        if args.snapshot_cmd == "verify":
            # jax-free on purpose: manifest.py only touches stdlib, so
            # this runs on any CI box that can read the artifact.
            from ..serving.snapshot.manifest import (
                SnapshotError,
                verify_snapshot,
            )

            try:
                report = verify_snapshot(args.path, quick=args.quick)
            except SnapshotError as e:
                print(f"snapshot unreadable: {e}", file=sys.stderr)
                return 2
            print(_json.dumps(report, indent=2))
            return 0 if report["ok"] else 1

        # snapshot create: build + warm a real engine, then capture it.
        # Every compile must land in the persistent cache for the
        # snapshot to carry it, so drop the min-compile-time floor
        # before jax spins up.
        os.environ.setdefault("OPSAGENT_COMPILE_CACHE_MIN_S", "0")
        if args.compile_cache_dir:
            os.environ["OPSAGENT_COMPILE_CACHE_DIR"] = args.compile_cache_dir
        elif not (
            os.environ.get("OPSAGENT_COMPILE_CACHE_DIR")
            or os.environ.get("OPSAGENT_COMPILE_CACHE")
        ):
            import tempfile

            os.environ["OPSAGENT_COMPILE_CACHE_DIR"] = tempfile.mkdtemp(
                prefix="opsagent-snapshot-cache-"
            )
        if args.platform:
            import jax

            jax.config.update("jax_platforms", args.platform)
        from ..models.config import resolve_model
        from ..serving.engine import Engine, EngineConfig

        model_name, model_cfg = resolve_model(args.model, args.checkpoint)
        eng_cfg = EngineConfig(
            model=model_name,
            checkpoint=args.checkpoint,
            tokenizer=args.tokenizer,
            tp=args.tp,
            sp=args.sp,
            ep=args.ep,
            max_batch_size=args.max_batch_size,
            quantize=args.quantize,
            kv_quantize=args.kv_quantize,
            speculative_k=args.speculative_k,
            offload=args.offload,
            async_depth=args.async_depth,
            warmup=False,
        )
        eng = Engine(eng_cfg, model_cfg=model_cfg)
        eng.warmup(args.warmup_level)
        man = eng.snapshot(args.out)
        print(_json.dumps({
            "path": os.path.abspath(args.out),
            "fingerprint": man["fingerprint"],
            "leaves": len(man["leaves"]),
            "compile_cache_entries": man["compile_cache"]["entries"],
        }, indent=2))
        return 0

    from ..utils.term import render_markdown

    if args.command == "execute":
        from ..agent.prompts import REACT_SYSTEM_PROMPT, REFORMAT_PROMPT
        from ..agent.react import assistant_with_config
        from ..workflows import assistant_flow

        from .. import obs

        instructions = " ".join(args.instructions)
        messages = [
            {"role": "system", "content": REACT_SYSTEM_PROMPT},
            {"role": "user", "content": f"Here are the instructions: {instructions}"},
        ]
        # Root the request trace here so verbose runs can print the span
        # summary afterwards (the ReAct loop would otherwise self-mint an
        # ID this layer never learns).
        with obs.trace_request(obs.new_request_id("cli")) as tr:
            response, _ = assistant_with_config(
                args.model, messages, args.max_tokens, args.count_tokens,
                args.verbose, args.max_iterations, args.api_key, args.base_url,
            )
        # Second LLM pass purely to reformat, as the reference does
        # (execute.go:280-281).
        try:
            from ..llm.client import ChatClient

            client = ChatClient(api_key=args.api_key, base_url=args.base_url)
            result = assistant_flow(args.model, REFORMAT_PROMPT + response, client=client)
        except Exception:  # noqa: BLE001 - reformat is best-effort
            result = response
        print(render_markdown(result))
        if args.verbose:
            print(get_perf_stats().format_table(), file=sys.stderr)
            print(obs.format_tree(tr.to_dict()), file=sys.stderr)
        if args.metrics:
            print(obs.metrics_text(), file=sys.stderr, end="")
        return 0

    if args.command == "analyze":
        from ..k8s import get_yaml
        from ..workflows import analysis_flow

        manifest = get_yaml(args.resource, args.name, args.namespace)
        result = analysis_flow(args.model, manifest)
        print(render_markdown(result))
        return 0

    if args.command == "audit":
        from ..workflows import audit_flow

        result = audit_flow(args.model, args.name, args.namespace)
        print(render_markdown(result))
        return 0

    if args.command == "audit-fanout":
        from .fanout import run_audit_fanout

        return run_audit_fanout(
            model=args.model,
            resources=args.resources,
            seed=args.seed,
            issue_fraction=args.issue_fraction,
            replicas=args.replicas,
            max_inflight=args.max_inflight,
            max_tokens=args.max_tokens,
            flight_sample=args.flight_sample,
            as_json=args.json,
            out=args.out,
        )

    if args.command == "diagnose":
        from ..agent.prompts import DIAGNOSE_SYSTEM_PROMPT
        from ..agent.react import assistant_with_config

        messages = [
            {"role": "system", "content": DIAGNOSE_SYSTEM_PROMPT},
            {
                "role": "user",
                "content": (
                    f"Diagnose the Pod '{args.name}' in namespace "
                    f"'{args.namespace}'."
                ),
            },
        ]
        from .. import obs

        with obs.trace_request(obs.new_request_id("cli")) as tr:
            response, _ = assistant_with_config(
                args.model, messages, args.max_tokens, args.count_tokens,
                args.verbose, args.max_iterations, args.api_key, args.base_url,
            )
        from ..utils.jsonrepair import extract_field

        final = extract_field(response, "final_answer") or response
        print(render_markdown(final))
        if args.verbose:
            print(obs.format_tree(tr.to_dict()), file=sys.stderr)
        if args.metrics:
            print(obs.metrics_text(), file=sys.stderr, end="")
        return 0

    if args.command == "generate":
        from ..utils.yamlutil import extract_yaml
        from ..workflows import generator_flow

        prompt = " ".join(args.prompt)
        result = generator_flow(args.model, prompt)
        manifests = extract_yaml(result)
        print(render_markdown(result))
        if not args.yes:
            try:
                answer = input("Apply these manifests to the cluster? (y/N) ")
            except EOFError:
                answer = "n"
            if answer.strip().lower() not in ("y", "yes"):
                log.info("apply skipped")
                return 0
        from ..k8s import apply_yaml

        applied = apply_yaml(manifests)
        for item in applied:
            print(f"applied: {item}")
        return 0

    return 1


if __name__ == "__main__":
    sys.exit(main())
