from .rope import apply_rope, rope_table
from .attention import (
    causal_prefill_attention,
    paged_decode_attention,
    write_kv_pages,
)

__all__ = [
    "apply_rope",
    "rope_table",
    "causal_prefill_attention",
    "paged_decode_attention",
    "write_kv_pages",
]
