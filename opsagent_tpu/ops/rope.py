"""Rotary position embeddings (llama-family convention) with long-context
frequency scaling: Llama-3.1's "llama3" wavelength-banded interpolation
and YaRN (DeepSeek-V2/V3), including YaRN's mscale factor folded into the
cos/sin tables. Formulas mirror the HF reference implementations
(modeling_llama._compute_llama3_parameters, modeling_deepseek's yarn
rotary embedding) so scaled checkpoints reproduce their training-time
position encoding."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def yarn_get_mscale(scale: float, mscale: float) -> float:
    """YaRN attention-magnitude correction (HF yarn_get_mscale)."""
    if scale <= 1.0 or mscale == 0.0:
        return 1.0
    return 0.1 * mscale * math.log(scale) + 1.0


def _scaled_freqs(head_dim: int, theta: float, scaling) -> tuple[jnp.ndarray, float]:
    """(inverse frequencies [head_dim//2], cos/sin magnitude factor)."""
    half = head_dim // 2
    inv = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    if scaling is None:
        return inv, 1.0
    if scaling.rope_type == "llama3":
        # Wavelength-banded: low-frequency dims fully interpolated
        # (divided by factor), high-frequency dims untouched, smooth
        # ramp between (HF _compute_llama3_parameters).
        orig = float(scaling.original_max_position)
        wavelen = 2.0 * math.pi / inv
        low_wl = orig / scaling.low_freq_factor
        high_wl = orig / scaling.high_freq_factor
        smooth = (
            (orig / wavelen - scaling.low_freq_factor)
            / (scaling.high_freq_factor - scaling.low_freq_factor)
        )
        banded = jnp.where(
            wavelen > low_wl,
            inv / scaling.factor,
            jnp.where(
                wavelen < high_wl,
                inv,
                (1.0 - smooth) * inv / scaling.factor + smooth * inv,
            ),
        )
        return banded, 1.0
    if scaling.rope_type == "yarn":
        # NTK-by-parts: dims rotating faster than beta_fast at the
        # original window keep their frequency (extrapolation), dims
        # slower than beta_slow interpolate (divide by factor), linear
        # ramp between (HF yarn_find_correction_range / ramp mask).
        dim = head_dim
        orig = float(scaling.original_max_position)

        def correction_dim(num_rot: float) -> float:
            return (
                dim * math.log(orig / (num_rot * 2.0 * math.pi))
            ) / (2.0 * math.log(theta))

        low = max(math.floor(correction_dim(scaling.beta_fast)), 0)
        high = min(math.ceil(correction_dim(scaling.beta_slow)), dim - 1)
        ramp = jnp.clip(
            (jnp.arange(half, dtype=jnp.float32) - low)
            / max(high - low, 1e-3),
            0.0, 1.0,
        )
        extrap_mask = 1.0 - ramp
        yarned = (
            inv / scaling.factor * (1.0 - extrap_mask) + inv * extrap_mask
        )
        att = yarn_get_mscale(
            scaling.factor, scaling.mscale
        ) / yarn_get_mscale(scaling.factor, scaling.mscale_all_dim)
        return yarned, att
    raise ValueError(f"unknown rope scaling type {scaling.rope_type!r}")


def rope_table(
    positions: jax.Array, head_dim: int, theta: float, scaling=None
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for given positions.

    positions: [..., S] int32 -> (cos, sin): [..., S, head_dim//2] f32.
    ``scaling`` is an optional ``config.RopeScalingConfig``.
    """
    freqs, att = _scaled_freqs(head_dim, theta, scaling)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., S, half]
    return jnp.cos(angles) * att, jnp.sin(angles) * att


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs (x[..2i], x[..2i+1]) split as first/second half (the
    llama "rotate_half" convention used by HF checkpoints).

    x: [B, S, H, D]; cos/sin: [B, S, D//2] (broadcast over heads).
    """
    half = x.shape[-1] // 2
    x1 = x[..., :half]
    x2 = x[..., half:]
    c = cos[..., None, :]  # [B, S, 1, half]
    s = sin[..., None, :]
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
