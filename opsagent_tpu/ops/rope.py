"""Rotary position embeddings (llama-family convention)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_table(positions: jax.Array, head_dim: int, theta: float) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for given positions.

    positions: [..., S] int32 -> (cos, sin): [..., S, head_dim//2] f32.
    """
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [..., S, half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate pairs (x[..2i], x[..2i+1]) split as first/second half (the
    llama "rotate_half" convention used by HF checkpoints).

    x: [B, S, H, D]; cos/sin: [B, S, D//2] (broadcast over heads).
    """
    half = x.shape[-1] // 2
    x1 = x[..., :half]
    x2 = x[..., half:]
    c = cos[..., None, :]  # [B, S, 1, half]
    s = sin[..., None, :]
    out1 = x1 * c - x2 * s
    out2 = x2 * c + x1 * s
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
