"""Pallas quantized matmul with double-buffered weight-tile streaming.

The decode/mixed hot path is weight-streaming-bound (PERF.md roofline:
~9.8 ms/step of weight bytes at 8B int8) and the XLA path serializes that
stream with compute: every ``x @ w.dequantize()`` waits for its operand
tiles. This kernel applies the same manual ``make_async_copy`` DMA
discipline the paged-attention kernels (ops/paged_attention_pallas.py)
use for KV pages to the WEIGHTS: int8 / self-packed-int4 tiles stream
HBM->VMEM through two double-buffered slots, so tile i+1's DMA runs under
tile i's MXU dot and the stream hides behind compute instead of adding to
it. Group-wise scales (models/quant.py layouts) are applied in-register
per tile — no dequantized HBM copy ever materializes.

Numerics mirror the XLA oracle (``llama._mm``) tile-by-tile: each weight
tile is dequantized to f32, cast to the activation dtype, and fed to an
f32-accumulating dot — elementwise identical math, only the contraction's
reduction ORDER differs (tiled partial sums vs one long sum), which is
the same fidelity class as the paged Pallas kernels vs the XLA gather.

Interpret mode (``interpret=True`` or ``OPSAGENT_PALLAS_INTERPRET=1``)
runs the identical kernel body on CPU so tiny test models exercise the
path end-to-end; compiled mode is the opt-in
``EngineConfig.weight_stream="pallas-dma"`` backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Contraction-axis tile for int8 weights (int4 tiles are one scale group
# each). 256 int8 rows x a 512-lane out tile = 128 KB per slot — two
# slots plus the x block fit VMEM with room for the accumulator.
IN_TILE = 256
OUT_TILE = 512


def _out_tile(out: int) -> int:
    """Largest 128-multiple divisor of ``out`` up to OUT_TILE; falls back
    to the whole axis for tiny (CPU-test) widths."""
    for t in range(min(OUT_TILE, out), 127, -128):
        if out % t == 0:
            return t
    return out


def _kernel_int8(
    x_ref,      # [T, In] VMEM (activations, full contraction axis)
    s_ref,      # [1, OUT_T] VMEM (per-output-channel scale tile)
    q_hbm,      # [In, Out] int8, HBM-resident (memory_space=ANY)
    o_ref,      # [T, OUT_T] VMEM
    q_buf,      # [2, IN_T, OUT_T] int8 VMEM scratch (the two DMA slots)
    sem,        # DMA semaphores (2,)
    *,
    in_tile: int,
    n_in: int,
    In: int,
):
    """Per-output-tile int8 quant matmul, contraction streamed through two
    DMA slots. The last tile CLAMPS its start (like the grid attention
    kernels clamp page indices) so a ragged contraction axis re-reads a
    few rows instead of reading out of bounds; the re-read rows are zeroed
    in the x slice, so their products vanish."""
    j = pl.program_id(0)
    out_t = o_ref.shape[1]

    def start(i):
        return jnp.minimum(i * in_tile, In - in_tile)

    def dma(slot, i):
        return pltpu.make_async_copy(
            q_hbm.at[pl.ds(start(i), in_tile), pl.ds(j * out_t, out_t)],
            q_buf.at[slot],
            sem.at[slot],
        )

    dma(0, 0).start()
    scale = s_ref[0, :][None, :].astype(jnp.float32)        # [1, OUT_T]

    def body(i, acc):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_in)
        def _prefetch():
            dma(1 - slot, i + 1).start()

        dma(slot, i).wait()
        st = start(i)
        xs = x_ref[:, pl.ds(st, in_tile)]                   # [T, IN_T]
        # Ragged tail: columns the previous tile already covered
        # (global col < i*in_tile) are zeroed so the clamped re-read
        # contributes nothing.
        col = st + jax.lax.broadcasted_iota(
            jnp.int32, (1, in_tile), 1
        )
        xs = jnp.where(col >= i * in_tile, xs, jnp.zeros_like(xs))
        # Mirror the oracle's elementwise math: dequantize to f32,
        # cast to the activation dtype, f32-accumulating dot.
        wt = (q_buf[slot].astype(jnp.float32) * scale).astype(xs.dtype)
        return acc + jax.lax.dot_general(
            xs, wt,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    acc = jax.lax.fori_loop(
        0, n_in, body,
        jnp.zeros((x_ref.shape[0], out_t), jnp.float32),
    )
    o_ref[:] = acc.astype(o_ref.dtype)


def _kernel_int4(
    x_ref,      # [T, In] VMEM
    s_ref,      # [G, 1, OUT_T] VMEM (group scales for this out tile)
    q_hbm,      # [In//2, Out] packed int8, HBM-resident
    o_ref,      # [T, OUT_T] VMEM
    q_buf,      # [2, g//2, OUT_T] int8 VMEM scratch
    sem,
    *,
    group: int,
    n_groups: int,
):
    """Per-output-tile int4 quant matmul: one scale GROUP per DMA step, so
    each streamed tile owns exactly one scale row — the group-wise scale
    applies as a broadcast multiply with no cross-group bookkeeping.
    ``group`` always divides the contraction axis (quantize_weight4
    derives it as a divisor), so there is no ragged tail here."""
    j = pl.program_id(0)
    out_t = o_ref.shape[1]
    half = group // 2

    def dma(slot, i):
        return pltpu.make_async_copy(
            q_hbm.at[pl.ds(i * half, half), pl.ds(j * out_t, out_t)],
            q_buf.at[slot],
            sem.at[slot],
        )

    dma(0, 0).start()

    def body(i, acc):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_groups)
        def _prefetch():
            dma(1 - slot, i + 1).start()

        dma(slot, i).wait()
        packed = q_buf[slot]                                # [g/2, OUT_T]
        # Nibble unpack, exactly quant.QuantizedLinear4.dequantize:
        # arithmetic shifts sign-extend; stack on -2 interleaves
        # (even, odd) rows back into contraction order.
        low = jax.lax.shift_right_arithmetic(
            jax.lax.shift_left(packed, jnp.int8(4)), jnp.int8(4)
        )
        high = jax.lax.shift_right_arithmetic(packed, jnp.int8(4))
        w = jnp.stack([low, high], axis=-2)                 # [g/2, 2, OUT_T]
        w = w.astype(jnp.float32).reshape(group, out_t)
        xs = x_ref[:, pl.ds(i * group, group)]              # [T, g]
        wt = (w * s_ref[i, 0, :][None, :]).astype(xs.dtype)
        return acc + jax.lax.dot_general(
            xs, wt,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    acc = jax.lax.fori_loop(
        0, n_groups, body,
        jnp.zeros((x_ref.shape[0], out_t), jnp.float32),
    )
    o_ref[:] = acc.astype(o_ref.dtype)


def supports(w) -> bool:
    """Whether ``w`` is a quantized leaf this kernel family can stream:
    a 2D QuantizedLinear, or a 2D QuantizedLinear4 whose scale group is
    even (the packed layout pairs rows, so an odd group would split a
    byte across two scale groups). Stacked/MoE 3D leaves and anything
    else stay on the XLA dequant path."""
    from ..models.quant import QuantizedLinear, QuantizedLinear4

    if isinstance(w, QuantizedLinear4):
        if w.q.ndim != 2:
            return False
        In = 2 * w.q.shape[0]
        return (In // w.scale.shape[-3]) % 2 == 0
    if isinstance(w, QuantizedLinear):
        return w.q.ndim == 2
    return False


@functools.partial(jax.jit, static_argnames=("interpret",))
def quant_matmul_pallas(
    x: jax.Array,   # [T, In] activations (any float dtype)
    w,              # models.quant.QuantizedLinear | QuantizedLinear4 (2D)
    interpret: bool = False,
) -> jax.Array:
    """``x @ w.dequantize().astype(x.dtype)`` with the weight stream
    double-buffered HBM->VMEM instead of serialized with the dot.

    Grid is one step per output tile; within a step the contraction axis
    streams through two DMA slots (int8: IN_TILE rows per slot; int4: one
    scale group per slot, packed two-per-byte). Returns [T, Out] in
    ``x.dtype``.
    """
    from ..models.quant import QuantizedLinear, QuantizedLinear4

    if x.ndim != 2:
        raise ValueError(f"x must be [T, In], got {x.shape}")
    if w.q.ndim != 2:
        raise ValueError(
            f"quant_matmul_pallas needs a 2D weight, got q{w.q.shape} "
            f"(stacked/MoE leaves stay on the XLA dequant path)"
        )
    T = x.shape[0]

    if isinstance(w, QuantizedLinear4):
        half, Out = w.q.shape
        In = 2 * half
        G = w.scale.shape[-3]
        group = In // G
        if x.shape[1] != In:
            raise ValueError(f"x In={x.shape[1]} != weight In={In}")
        out_t = _out_tile(Out)
        kernel = functools.partial(
            _kernel_int4, group=group, n_groups=G
        )
        in_specs = [
            pl.BlockSpec((T, In), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (G, 1, out_t), lambda j: (0, 0, j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        scratch = [
            pltpu.VMEM((2, group // 2, out_t), jnp.int8),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        weight_bytes = half * Out + 4 * G * Out
    elif isinstance(w, QuantizedLinear):
        In, Out = w.q.shape
        if x.shape[1] != In:
            raise ValueError(f"x In={x.shape[1]} != weight In={In}")
        in_tile = min(IN_TILE, In)
        n_in = pl.cdiv(In, in_tile)
        out_t = _out_tile(Out)
        kernel = functools.partial(
            _kernel_int8, in_tile=in_tile, n_in=n_in, In=In
        )
        in_specs = [
            pl.BlockSpec((T, In), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, out_t), lambda j: (0, j), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        scratch = [
            pltpu.VMEM((2, in_tile, out_t), jnp.int8),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        weight_bytes = In * Out + 4 * Out
    else:
        raise TypeError(f"unsupported quantized weight: {type(w)!r}")

    return pl.pallas_call(
        kernel,
        grid=(Out // out_t,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (T, out_t), lambda j: (0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((T, Out), x.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * T * In * Out,
            bytes_accessed=(
                weight_bytes
                + T * (In + Out) * x.dtype.itemsize
            ),
            transcendentals=0,
        ),
    )(x, w.scale, w.q)


def quant_matmul_pallas_tp(
    x: jax.Array,
    w,
    mesh,
    interpret: bool = False,
) -> jax.Array:
    """Column-parallel TP form: ``w`` sharded on its OUTPUT axis over the
    mesh's tp axis, ``x`` replicated — each shard streams only its own
    weight columns and emits its own output columns; no collective. The
    engine currently resolves weight_stream to xla at tp > 1 (row-parallel
    projections would need a psum epilogue); this form exists so the
    sharded kernel stays covered ahead of that wiring."""
    from jax.sharding import PartitionSpec as Pspec

    from ..models.quant import QuantizedLinear4
    from .attention import _shard_map

    if isinstance(w, QuantizedLinear4):
        w_spec = type(w)(
            Pspec(None, "tp"), Pspec(None, None, "tp")
        )
    else:
        w_spec = type(w)(Pspec(None, "tp"), Pspec(None, "tp"))

    def shard_fn(xs, ws):
        return quant_matmul_pallas(xs, ws, interpret=interpret)

    return _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(Pspec(), w_spec),
        out_specs=Pspec(None, "tp"),
    )(x, w)
