"""Pallas TPU kernels: ragged paged-attention for the decode step.

The XLA reference (``ops.attention.paged_decode_attention``) gathers every
sequence's pages into a dense ``[B, MaxP*P, K, D]`` tensor each decode step —
HBM traffic proportional to the page-table CAPACITY, not to the tokens
actually resident. TWO kernels stream only the owned pages instead:

- ``paged_decode_attention_pallas``: grid ``(B, MaxP)``, one page per grid
  step via the automatic Pallas pipeline (scalar-prefetched page table
  drives the k/v BlockSpec index maps). Simple, but pays a pipeline step
  per PAGE SLOT — overhead-bound at decode shapes (VERDICT r2 weak #3).
- ``paged_decode_attention_pallas_dma``: grid ``(B,)``, pages streamed
  through two VMEM slots with manually double-buffered ``make_async_copy``
  DMAs. One grid step per sequence; unowned page slots cost nothing.

Two more kernels generalize the pair to RAGGED queries (per-row q_len,
causal inside the chunk) for the engine's mixed prefill+decode step:
``paged_ragged_attention_pallas`` (grid form) and
``paged_ragged_attention_pallas_dma`` (manual-DMA form, the mixed hot
path's bytes-diet kernel: int8 ``QuantizedPages`` stream through the
double-buffered DMAs at half the bytes) — see their docstrings.

Both use a flash-attention-style online softmax so nothing is
materialized.

Grid: ``(B, MaxP)`` — page axis innermost so the f32 accumulators in VMEM
scratch carry across a sequence's pages. Each grid step DMAs one whole page
``[P, K, D]`` (all kv heads at once); blocks therefore span full trailing
axes, which satisfies the TPU tiling rule (last two block dims divisible by
(8, 128) OR equal to the array's). Pages past a sequence's length clamp
their index map to the last valid page: the pipeline sees an unchanged block
index and skips the refetch, so ragged sequences pay only for the pages they
own.

Correctness oracle: ``ops.attention.paged_decode_attention`` (compared in
interpret mode on CPU and compiled on TPU). No Go counterpart exists in the
reference — this replaces its remote-LLM HTTPS hop (pkg/llms/openai.go:69).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    # scalar prefetch
    table_ref,     # [B, MaxP] int32 page indices (-1 = unassigned)
    lengths_ref,   # [B] int32 tokens in cache (incl. the one being written)
    base_ref,      # [1] int32 flat-page offset (layer * N; 0 without layers)
    # blocks + scratch, order depending on ``quantized``:
    #   q_ref [1, H, D]; k_ref/v_ref [1, P, K, D] (one page, all kv heads);
    #   with quantized, k_sc_ref/v_sc_ref [1, 1, P*K] (this page's
    #   pre-gathered f32 scale plane); o_ref [1, H, D]; then scratch
    #   acc [H, D] f32, m/l [H, 128] f32 (running max / denominator,
    #   lane-broadcast).
    *refs,
    page_size: int,
    num_kv_heads: int,
    quantized: bool = False,
):
    if quantized:
        (q_ref, k_ref, v_ref, k_sc_ref, v_sc_ref, o_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        k_sc_ref = v_sc_ref = None
    b = pl.program_id(0)
    p = pl.program_id(1)
    P = page_size
    K = num_kv_heads
    H = q_ref.shape[1]
    G = H // K
    length = lengths_ref[b]
    num_pages = pl.cdiv(length, P)

    @pl.when(p == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(p < num_pages)
    def _accumulate():
        D = q_ref.shape[-1]
        scale = D ** -0.5
        # One big MXU dot against ALL kv heads' keys at once (with P*K=128
        # this is a single full MXU tile), then select each query head's own
        # group on the VPU. K× redundant MXU FLOPs, but the decode step is
        # HBM-bandwidth-bound and the MXU is otherwise idle — this beats K
        # sublane-misaligned [G,D]x[D,P] dots by a wide margin.
        q = q_ref[0].astype(jnp.float32) * scale           # [H, D]
        kf = k_ref[0].reshape(P * K, D)                    # [P*K, D] row p*K+k
        vf = v_ref[0].reshape(P * K, D)
        if quantized:
            # int8 values <= 127 are exact in f32; the MXU dot runs on
            # converted operands rather than a mixed int8 x f32 dot.
            kf = kf.astype(jnp.float32)
        s_full = jax.lax.dot_general(
            q, kf,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [H, P*K]
        if quantized:
            # Column c = (token c//K, kv head c%K) — the flat scale
            # plane's exact order, so applying the K scale in score space
            # is a lane-wise multiply identical to dequantizing the page
            # (the scale is constant per column). Same math as the
            # manual-DMA kernels (_kernel_dma).
            s_full = s_full * k_sc_ref[0, 0][None, :]
        # Column c holds (token p*P + c//K, kv head c%K). Mask columns whose
        # kv head is not this query head's group (and out-of-range tokens) to
        # -inf and run the online softmax directly in the [H, P*K] domain —
        # masked columns contribute exp(-inf)=0, so the probs matrix is
        # already laid out for one dot against vf. No lane-splitting
        # reshapes, which Mosaic cannot lower.
        col = jax.lax.broadcasted_iota(jnp.int32, (H, P * K), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (H, P * K), 0)
        sel = (col % K == row // G) & (p * P + col // K < length)
        s = jnp.where(sel, s_full, NEG_INF)                # [H, P*K]

        m_prev = m_ref[:, :1]                              # [H, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                    # [H, 1]
        probs = jnp.exp(s - m_new)                         # [H, P*K]
        l_new = alpha[:, 0] * l_ref[:, 0] + jnp.sum(probs, axis=-1)
        pv = probs
        if quantized:
            # V scale folds into the probs the same way (per-column).
            pv = probs * v_sc_ref[0, 0][None, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pv, vf.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(p == pl.num_programs(1) - 1)
    def _finalize():
        l = l_ref[:, :1]                                   # [H, 1]
        safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_ref[:] / safe).astype(o_ref.dtype)


def _page_index(b, p, table_ref, lengths_ref, base_ref, *, page_size):
    """Block index of the page to DMA for grid step (b, p); clamps
    past-the-end steps to the last valid page so the pipeline sees an
    unchanged index and skips the refetch. ``base_ref`` offsets into the
    layer's region when the pages carry a flattened layer axis."""
    num_pages = pl.cdiv(lengths_ref[b], page_size)
    last = jnp.maximum(num_pages - 1, 0)
    page = table_ref[b, jnp.minimum(p, last)]
    return (jnp.maximum(page, 0) + base_ref[0], 0, 0, 0)


def _scale_index(b, p, table_ref, lengths_ref, base_ref, *, page_size):
    """Block index into the pre-gathered ``[B, MaxP, P*K]`` scale planes
    for grid step (b, p): the slot axis is clamped exactly like
    ``_page_index`` so past-the-end steps see an unchanged index and the
    pipeline skips the refetch — the scale block can therefore never come
    from a different page slot than the k/v blocks beside it."""
    num_pages = pl.cdiv(lengths_ref[b], page_size)
    last = jnp.maximum(num_pages - 1, 0)
    return (b, jnp.minimum(p, last), 0)


def _kernel_dma(
    # scalar prefetch
    table_ref,     # [B, MaxP] int32 page indices (-1 = unassigned)
    lengths_ref,   # [B] int32 tokens in cache (incl. the one being written)
    base_ref,      # [1] int32 flat-page offset (layer * N; 0 without layers)
    # blocks + scratch, order depending on ``quantized`` (see unpack below)
    *refs,
    page_size: int,
    num_kv_heads: int,
    max_pages: int,
    quantized: bool = False,
):
    """One grid step per SEQUENCE; its pages stream through two VMEM slots
    via manually double-buffered DMAs. Versus the (B, MaxP) grid kernel
    this removes the per-page pipeline step overhead that made that kernel
    lose to the XLA gather at decode shapes (VERDICT r2 weak #3): the grid
    is B steps total, page DMAs are issued one ahead of compute, and pages
    past a sequence's length cost NOTHING (no step, no DMA) rather than a
    clamped-index pipeline step.

    ``quantized``: pages are int8 and two extra VMEM blocks carry the
    pre-gathered, pre-FLATTENED per-token-per-head f32 scales for THIS
    sequence ([1, MaxP, P*K] each — the scale planes are 1/D of the page
    bytes, so the caller's XLA gather of them is noise). The scales ride
    the automatic BlockSpec pipeline (lane dim P*K, naturally
    128-aligned) rather than manual DMAs, and are applied in SCORE space,
    not value space: column c of the [H, P*K] score matrix is (token
    c//K, kv head c%K) — exactly the flat scale vector's order — so
    ``s = (q . K_int8) * k_scale[None, :]`` and ``acc += (probs *
    v_scale[None, :]) . V_int8`` are plain lane-wise multiplies,
    mathematically identical to dequantizing the pages (the scale is
    constant per column) while avoiding the [P, K] -> [P, K, D]
    broadcast whose lane->sublane relayout Mosaic lowers badly or not
    at all."""
    if quantized:
        (q_ref, k_hbm, v_hbm, k_sc_ref, v_sc_ref, o_ref,
         k_buf, v_buf, k_sem, v_sem, acc_ref, m_ref, l_ref) = refs
    else:
        (q_ref, k_hbm, v_hbm, o_ref,
         k_buf, v_buf, k_sem, v_sem, acc_ref, m_ref, l_ref) = refs
        k_sc_ref = v_sc_ref = None
    b = pl.program_id(0)
    P = page_size
    K = num_kv_heads
    H = q_ref.shape[1]
    G = H // K
    D = q_ref.shape[-1]
    length = lengths_ref[b]
    # Pages this sequence actually owns, clamped to the table width: a
    # length beyond MaxP*P (tolerated by the grid kernel via index
    # clamping) must not drive table reads past [B, MaxP] or start a
    # prefetch DMA the loop never waits on.
    n = jnp.minimum(pl.cdiv(length, P), max_pages)

    def k_dma(slot, i):
        page = jnp.maximum(table_ref[b, i], 0) + base_ref[0]
        return pltpu.make_async_copy(
            k_hbm.at[page], k_buf.at[slot], k_sem.at[slot]
        )

    def v_dma(slot, i):
        page = jnp.maximum(table_ref[b, i], 0) + base_ref[0]
        return pltpu.make_async_copy(
            v_hbm.at[page], v_buf.at[slot], v_sem.at[slot]
        )

    @pl.when(n > 0)
    def _warmup():
        k_dma(0, 0).start()
        v_dma(0, 0).start()

    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * (D ** -0.5)          # [H, D]

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n)
        def _prefetch():
            k_dma(1 - slot, i + 1).start()
            v_dma(1 - slot, i + 1).start()

        k_dma(slot, i).wait()
        v_dma(slot, i).wait()

        kf = k_buf[slot].reshape(P * K, D)
        vf = v_buf[slot].reshape(P * K, D)
        if quantized:
            # int8 values <= 127 are exact in f32; the MXU dot runs on
            # converted operands rather than a mixed int8 x f32 dot.
            kf = kf.astype(jnp.float32)
        s_full = jax.lax.dot_general(
            q, kf,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                   # [H, P*K]
        if quantized:
            # Column c = (token c//K, kv head c%K) — the flat scale
            # vector's exact order, so applying the K scale in score
            # space is a lane-wise multiply identical to dequantizing
            # the page (the scale is constant per column).
            s_full = s_full * k_sc_ref[0, i][None, :]
        col = jax.lax.broadcasted_iota(jnp.int32, (H, P * K), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (H, P * K), 0)
        sel = (col % K == row // G) & (i * P + col // K < length)
        s = jnp.where(sel, s_full, NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(s - m_new)
        l_new = alpha[:, 0] * l_ref[:, 0] + jnp.sum(probs, axis=-1)
        pv = probs
        if quantized:
            # V scale folds into the probs the same way (per-column).
            pv = probs * v_sc_ref[0, i][None, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pv, vf.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
        return 0

    jax.lax.fori_loop(0, n, body, 0)

    l = l_ref[:, :1]
    safe = jnp.where(l > 0.0, l, 1.0)
    o_ref[0] = (acc_ref[:] / safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas_dma(
    q: jax.Array,           # [B, H, D] (one new token per sequence)
    k_pages: jax.Array,     # [N, P, K, D] — or [L, N, P, K, D] with layer
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP] int32
    lengths: jax.Array,     # [B] int32 (incl. the token being decoded)
    interpret: bool = False,
    layer: jax.Array | None = None,  # [] int32 with the layer-axis form
) -> jax.Array:
    """Manual-DMA paged decode attention: grid (B,), double-buffered page
    streaming. Same contract as ``paged_decode_attention_pallas``.

    Requires ``head_dim % 128 == 0``: Mosaic's manual-DMA memref slices
    must be 128-aligned on the minormost dim (r04 on-chip: head_dim=64
    fails to compile). Callers with smaller heads should use the grid
    kernel or the xla gather (engine auto-falls-back).

    Accepts ``ops.attention.QuantizedPages`` (int8 values + per-token
    scales): the int8 pages stream through the manual DMAs exactly like
    bf16 ones (HALF the bytes), while THIS sequence's scale planes — 1/D
    of the page bytes — are XLA-gathered outside, flattened to
    [B, MaxP, P*K], and pipelined into VMEM as ordinary blocks; the
    kernel applies them as per-column multiplies in score/probs space
    (mathematically identical to dequantizing the pages — see
    ``_kernel_dma``). This composes the kernel's
    read-only-resident-pages win with KV quantization's bytes-per-token
    win."""
    from .attention import QuantizedPages

    if q.shape[-1] % 128 != 0 and not interpret:
        raise ValueError(
            f"pallas-dma needs head_dim % 128 == 0, got {q.shape[-1]}; "
            f"use impl='pallas' or 'xla'"
        )
    k_scale = v_scale = None
    if isinstance(k_pages, QuantizedPages):
        k_pages, k_scale = k_pages.q, k_pages.scale
        v_pages, v_scale = v_pages.q, v_pages.scale
    if k_pages.ndim == 5:
        Lr, N, P, K, D = k_pages.shape
        k_pages = k_pages.reshape(Lr * N, P, K, D)
        v_pages = v_pages.reshape(Lr * N, P, K, D)
        if k_scale is not None:
            k_scale = k_scale.reshape(Lr * N, P, K)
            v_scale = v_scale.reshape(Lr * N, P, K)
        base = (layer if layer is not None else 0) * N
    else:
        N, P, K, D = k_pages.shape
        base = 0
    B, H, _ = q.shape
    MaxP = page_table.shape[1]
    base_arr = jnp.full((1,), base, jnp.int32)
    quantized = k_scale is not None

    in_specs = [
        pl.BlockSpec(
            (1, H, D), lambda b, t, ln, ba: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        # Per-sequence scale planes, gathered OUTSIDE the kernel (tiny:
        # 4 bytes per D int8 values), FLATTENED to [B, MaxP, P*K] so the
        # lane dim is naturally 128-aligned and the kernel applies them
        # as per-column multiplies in score space (see _kernel_dma), and
        # pipelined per grid step.
        # Same index math as the kernel's DMA (max(slot, 0) + base), so
        # the value and scale planes can never come from different pages
        # for an unassigned (-1) slot; such slots are masked anyway, but
        # the invariant should hold structurally, not by masking luck.
        safe_table = jnp.maximum(page_table, 0) + base
        sc_spec = pl.BlockSpec(
            (1, MaxP, P * K), lambda b, t, ln, ba: (b, 0, 0),
            memory_space=pltpu.VMEM,
        )
        in_specs += [sc_spec, sc_spec]
        operands += [
            k_scale[safe_table].reshape(B, MaxP, P * K),
            v_scale[safe_table].reshape(B, MaxP, P * K),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, H, D), lambda b, t, ln, ba: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, P, K, D), k_pages.dtype),
            pltpu.VMEM((2, P, K, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel_dma, page_size=P, num_kv_heads=K, max_pages=MaxP,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * B * H * D * MaxP * P,
            bytes_accessed=(
                B * MaxP * P * K * D * 2 * k_pages.dtype.itemsize
                + B * H * D * 2 * q.dtype.itemsize
            ),
            transcendentals=B * H * MaxP * P,
        ),
    )(
        page_table.astype(jnp.int32), lengths.astype(jnp.int32), base_arr,
        *operands,
    )
    return out


def _kernel_ragged(
    # scalar prefetch
    table_ref,     # [B, MaxP] int32 page indices (-1 = unassigned)
    start_ref,     # [B] int32 tokens already in cache (queries begin here)
    qlens_ref,     # [B] int32 valid query rows (0 = inactive row)
    base_ref,      # [1] int32 flat-page offset (layer * N; 0 without layers)
    # blocks + scratch, order depending on ``quantized``:
    #   q_ref [1, S, H, D]; k_ref/v_ref [1, P, K, D] (one page, all kv
    #   heads); with quantized, k_sc_ref/v_sc_ref [1, 1, P*K] (this
    #   page's pre-gathered f32 scale plane); o_ref [1, S, H, D]; then
    #   scratch acc [S*H, D] f32, m/l [S*H, 128] f32.
    *refs,
    page_size: int,
    num_kv_heads: int,
    quantized: bool = False,
):
    """Ragged-query sibling of ``_kernel``: S query rows per sequence with
    a per-row valid count, so q_len=1 decode rows and q_len=chunk prefill
    rows stream pages through ONE program (the mixed-step op). Queries
    flatten to [S*H, D] — row r is (position r // H, head r % H) — and the
    causal-inside-the-chunk mask composes with the GQA group select in the
    same [S*H, P*K] score domain the decode kernel uses. Fully-masked rows
    (s >= q_len, or a q_len=0 row) keep finite accumulators (exp(0)
    columns) and emit garbage the host discards.

    ``quantized``: pages are int8 and two extra blocks carry this page
    slot's pre-gathered, pre-flattened [1, 1, P*K] f32 scale planes,
    pipelined with the SAME clamped slot index map as the pages; scales
    apply as per-column multiplies in score/probs space exactly like the
    manual-DMA kernels (see ``_kernel_dma``)."""
    if quantized:
        (q_ref, k_ref, v_ref, k_sc_ref, v_sc_ref, o_ref,
         acc_ref, m_ref, l_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
        k_sc_ref = v_sc_ref = None
    b = pl.program_id(0)
    p = pl.program_id(1)
    P = page_size
    K = num_kv_heads
    S = q_ref.shape[1]
    H = q_ref.shape[2]
    G = H // K
    start = start_ref[b]
    qlen = qlens_ref[b]
    total = start + qlen           # cache tokens incl. this chunk's writes
    num_pages = pl.cdiv(total, P)

    @pl.when(p == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(p < num_pages)
    def _accumulate():
        D = q_ref.shape[-1]
        scale = D ** -0.5
        q = q_ref[0].reshape(S * H, D).astype(jnp.float32) * scale
        kf = k_ref[0].reshape(P * K, D)
        vf = v_ref[0].reshape(P * K, D)
        if quantized:
            kf = kf.astype(jnp.float32)
        s_full = jax.lax.dot_general(
            q, kf,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [S*H, P*K]
        if quantized:
            # Per-column K scale in score space (see _kernel_dma).
            s_full = s_full * k_sc_ref[0, 0][None, :]
        # Column c holds (token p*P + c//K, kv head c%K); row r holds
        # (query position start + r//H, query head r%H). Select the GQA
        # group AND the ragged causal window in one mask.
        col = jax.lax.broadcasted_iota(jnp.int32, (S * H, P * K), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (S * H, P * K), 0)
        t = p * P + col // K
        qpos = start + row // H
        sel = (
            (col % K == (row % H) // G)
            & (t <= qpos)
            & (t < total)
            & (row // H < qlen)
        )
        s = jnp.where(sel, s_full, NEG_INF)                # [S*H, P*K]

        m_prev = m_ref[:, :1]                              # [S*H, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                    # [S*H, 1]
        probs = jnp.exp(s - m_new)                         # [S*H, P*K]
        l_new = alpha[:, 0] * l_ref[:, 0] + jnp.sum(probs, axis=-1)
        pv = probs
        if quantized:
            # V scale folds into the probs the same way (per-column).
            pv = probs * v_sc_ref[0, 0][None, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pv, vf.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(p == pl.num_programs(1) - 1)
    def _finalize():
        l = l_ref[:, :1]                                   # [S*H, 1]
        safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_ref[:] / safe).reshape(
            S, H, q_ref.shape[-1]
        ).astype(o_ref.dtype)


def _page_index_ragged(
    b, p, table_ref, start_ref, qlens_ref, base_ref, *, page_size
):
    """``_page_index`` for the ragged kernel: the valid page count is
    derived from start + q_len rather than a single lengths vector;
    past-the-end steps clamp to the last valid page so the pipeline skips
    the refetch."""
    num_pages = pl.cdiv(start_ref[b] + qlens_ref[b], page_size)
    last = jnp.maximum(num_pages - 1, 0)
    page = table_ref[b, jnp.minimum(p, last)]
    return (jnp.maximum(page, 0) + base_ref[0], 0, 0, 0)


def _scale_index_ragged(
    b, p, table_ref, start_ref, qlens_ref, base_ref, *, page_size
):
    """``_scale_index`` for the ragged kernel (valid page count from
    start + q_len)."""
    num_pages = pl.cdiv(start_ref[b] + qlens_ref[b], page_size)
    last = jnp.maximum(num_pages - 1, 0)
    return (b, jnp.minimum(p, last), 0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_ragged_attention_pallas(
    q: jax.Array,           # [B, S, H, D] right-padded ragged queries
    k_pages: jax.Array,     # [N, P, K, D] — or [L, N, P, K, D] with layer
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP] int32
    start: jax.Array,       # [B] int32 tokens already in cache per row
    q_lens: jax.Array,      # [B] int32 valid query rows (0 = inactive)
    interpret: bool = False,
    layer: jax.Array | None = None,  # [] int32 with the layer-axis form
) -> jax.Array:
    """Ragged paged attention, Pallas TPU: grid ``(B, MaxP)`` streaming
    one page per pipeline step like ``paged_decode_attention_pallas``,
    but with S query rows per sequence and a per-row valid count — the
    kernel form of the mixed prefill+decode step (PAPERS.md: Ragged Paged
    Attention). VMEM cost scales with S (q block + [S*H, D] f32
    accumulator), so S should stay a modest mixed-chunk bucket, not a
    full prefill bucket. Correctness oracle:
    ``ops.attention.paged_ragged_attention``.

    Accepts ``ops.attention.QuantizedPages``: int8 pages flow through the
    same per-page BlockSpec pipeline at half the bytes, while each page
    slot's f32 scale plane — XLA-gathered outside, flattened to
    [B, MaxP, P*K], and pipelined with the SAME clamped slot index map as
    the pages — applies as per-column multiplies in score/probs space
    (see ``_kernel_ragged``). This closes the sweep gap where
    pallas + int8 KV silently resolved to xla at engine init."""
    from .attention import QuantizedPages

    k_scale = v_scale = None
    if isinstance(k_pages, QuantizedPages):
        k_pages, k_scale = k_pages.q, k_pages.scale
        v_pages, v_scale = v_pages.q, v_pages.scale
    if k_pages.ndim == 5:
        Lr, N, P, K, D = k_pages.shape
        k_pages = k_pages.reshape(Lr * N, P, K, D)
        v_pages = v_pages.reshape(Lr * N, P, K, D)
        if k_scale is not None:
            k_scale = k_scale.reshape(Lr * N, P, K)
            v_scale = v_scale.reshape(Lr * N, P, K)
        base = (layer if layer is not None else 0) * N
    else:
        N, P, K, D = k_pages.shape
        base = 0
    B, S, H, _ = q.shape
    MaxP = page_table.shape[1]
    base_arr = jnp.full((1,), base, jnp.int32)
    quantized = k_scale is not None

    page_map = functools.partial(_page_index_ragged, page_size=P)
    in_specs = [
        pl.BlockSpec(
            (1, S, H, D), lambda b, p, t, st, ql, ba: (b, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec((1, P, K, D), page_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, P, K, D), page_map, memory_space=pltpu.VMEM),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        # Per-page scale planes, gathered OUTSIDE the kernel (4 bytes per
        # D int8 values) with the same max(slot, 0) + base index math as
        # the page maps, flattened so the lane dim is 128-aligned, and
        # pipelined one page slot at a time alongside the k/v blocks.
        safe_table = jnp.maximum(page_table, 0) + base
        sc_map = functools.partial(_scale_index_ragged, page_size=P)
        sc_spec = pl.BlockSpec(
            (1, 1, P * K), sc_map, memory_space=pltpu.VMEM
        )
        in_specs += [sc_spec, sc_spec]
        operands += [
            k_scale[safe_table].reshape(B, MaxP, P * K),
            v_scale[safe_table].reshape(B, MaxP, P * K),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, MaxP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, S, H, D), lambda b, p, t, st, ql, ba: (b, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((S * H, D), jnp.float32),
            pltpu.VMEM((S * H, 128), jnp.float32),
            pltpu.VMEM((S * H, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel_ragged, page_size=P, num_kv_heads=K,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, H, D), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * B * S * H * D * MaxP * P,
            bytes_accessed=(
                B * MaxP * P * K * D * 2 * k_pages.dtype.itemsize
                + B * S * H * D * 2 * q.dtype.itemsize
            ),
            transcendentals=B * S * H * MaxP * P,
        ),
    )(
        page_table.astype(jnp.int32), start.astype(jnp.int32),
        q_lens.astype(jnp.int32), base_arr,
        *operands,
    )
    return out


def _kernel_ragged_dma(
    # scalar prefetch
    table_ref,     # [B, MaxP] int32 page indices (-1 = unassigned)
    start_ref,     # [B] int32 tokens already in cache (queries begin here)
    qlens_ref,     # [B] int32 valid query rows (0 = inactive row)
    base_ref,      # [1] int32 flat-page offset (layer * N; 0 without layers)
    # blocks + scratch, order depending on ``quantized`` (see unpack below)
    *refs,
    page_size: int,
    num_kv_heads: int,
    max_pages: int,
    quantized: bool = False,
):
    """``_kernel_dma``'s machinery under ``_kernel_ragged``'s mask: one
    grid step per SEQUENCE, its pages double-buffered through two VMEM
    slots, with S query rows per sequence and a per-row valid count — so
    q_len=1 decode rows, q_len=chunk prefill rows, and q_len>1 ffwd
    forced-run appends all stream through ONE program that reads only the
    pages each row owns. Queries flatten to [S*H, D] (row r = position
    r // H, head r % H) and the causal-inside-the-chunk mask composes
    with the GQA group select in the same [S*H, P*K] score domain.

    Inactive rows (q_len == 0) stream NOTHING — n = 0 skips the warmup
    DMA and the loop, l stays 0, and the safe divide emits zeros the host
    discards. Rows with s >= q_len under an n > 0 sequence keep finite
    accumulators (exp(0) columns) and emit garbage, same as the grid
    kernel.

    ``quantized`` works exactly as in ``_kernel_dma``: int8 pages stream
    through the DMAs at half the bytes while this sequence's
    pre-flattened [1, MaxP, P*K] f32 scale planes ride the automatic
    BlockSpec pipeline and apply as per-column multiplies in score/probs
    space (column c = (token c//K, kv head c%K) — the flat scale vector's
    exact order — so the multiply is mathematically identical to
    dequantizing the page)."""
    if quantized:
        (q_ref, k_hbm, v_hbm, k_sc_ref, v_sc_ref, o_ref,
         k_buf, v_buf, k_sem, v_sem, acc_ref, m_ref, l_ref) = refs
    else:
        (q_ref, k_hbm, v_hbm, o_ref,
         k_buf, v_buf, k_sem, v_sem, acc_ref, m_ref, l_ref) = refs
        k_sc_ref = v_sc_ref = None
    b = pl.program_id(0)
    P = page_size
    K = num_kv_heads
    S = q_ref.shape[1]
    H = q_ref.shape[2]
    G = H // K
    D = q_ref.shape[-1]
    start = start_ref[b]
    qlen = qlens_ref[b]
    total = start + qlen           # cache tokens incl. this chunk's writes
    # Pages this row actually owns, clamped to the table width (same
    # guard as _kernel_dma: a length beyond MaxP*P must not drive table
    # reads past [B, MaxP] or start a DMA the loop never waits on).
    n = jnp.where(
        qlen > 0, jnp.minimum(pl.cdiv(total, P), max_pages), 0
    )

    def k_dma(slot, i):
        page = jnp.maximum(table_ref[b, i], 0) + base_ref[0]
        return pltpu.make_async_copy(
            k_hbm.at[page], k_buf.at[slot], k_sem.at[slot]
        )

    def v_dma(slot, i):
        page = jnp.maximum(table_ref[b, i], 0) + base_ref[0]
        return pltpu.make_async_copy(
            v_hbm.at[page], v_buf.at[slot], v_sem.at[slot]
        )

    @pl.when(n > 0)
    def _warmup():
        k_dma(0, 0).start()
        v_dma(0, 0).start()

    acc_ref[:] = jnp.zeros_like(acc_ref)
    m_ref[:] = jnp.full_like(m_ref, NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].reshape(S * H, D).astype(jnp.float32) * (D ** -0.5)

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n)
        def _prefetch():
            k_dma(1 - slot, i + 1).start()
            v_dma(1 - slot, i + 1).start()

        k_dma(slot, i).wait()
        v_dma(slot, i).wait()

        kf = k_buf[slot].reshape(P * K, D)
        vf = v_buf[slot].reshape(P * K, D)
        if quantized:
            # int8 values <= 127 are exact in f32; the MXU dot runs on
            # converted operands rather than a mixed int8 x f32 dot.
            kf = kf.astype(jnp.float32)
        s_full = jax.lax.dot_general(
            q, kf,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                   # [S*H, P*K]
        if quantized:
            s_full = s_full * k_sc_ref[0, i][None, :]
        # Column c holds (token i*P + c//K, kv head c%K); row r holds
        # (query position start + r//H, query head r%H). Select the GQA
        # group AND the ragged causal window in one mask.
        col = jax.lax.broadcasted_iota(jnp.int32, (S * H, P * K), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (S * H, P * K), 0)
        t = i * P + col // K
        qpos = start + row // H
        sel = (
            (col % K == (row % H) // G)
            & (t <= qpos)
            & (t < total)
            & (row // H < qlen)
        )
        s = jnp.where(sel, s_full, NEG_INF)                 # [S*H, P*K]

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        probs = jnp.exp(s - m_new)
        l_new = alpha[:, 0] * l_ref[:, 0] + jnp.sum(probs, axis=-1)
        pv = probs
        if quantized:
            # V scale folds into the probs the same way (per-column).
            pv = probs * v_sc_ref[0, i][None, :]
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pv, vf.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
        return 0

    jax.lax.fori_loop(0, n, body, 0)

    l = l_ref[:, :1]
    safe = jnp.where(l > 0.0, l, 1.0)
    o_ref[0] = (acc_ref[:] / safe).reshape(S, H, D).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_ragged_attention_pallas_dma(
    q: jax.Array,           # [B, S, H, D] right-padded ragged queries
    k_pages: jax.Array,     # [N, P, K, D] — or [L, N, P, K, D] with layer
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP] int32
    start: jax.Array,       # [B] int32 tokens already in cache per row
    q_lens: jax.Array,      # [B] int32 valid query rows (0 = inactive)
    interpret: bool = False,
    layer: jax.Array | None = None,  # [] int32 with the layer-axis form
) -> jax.Array:
    """Manual-DMA ragged paged attention: grid ``(B,)``, double-buffered
    page streaming, per-row query lengths — the mixed-step hot-path form
    of ``paged_decode_attention_pallas_dma`` (same contract as
    ``paged_ragged_attention_pallas``; correctness oracle
    ``ops.attention.paged_ragged_attention``).

    Requires ``head_dim % 128 == 0``: Mosaic's manual-DMA memref slices
    must be 128-aligned on the minormost dim (r04 on-chip: head_dim=64
    fails to compile). Callers with smaller heads should use the grid
    kernel or the xla gather (engine auto-falls-back).

    Accepts ``ops.attention.QuantizedPages``: int8 pages stream through
    the manual DMAs at HALF the bytes, while this sequence's scale planes
    — 1/D of the page bytes — are XLA-gathered outside, flattened to
    [B, MaxP, P*K], and pipelined into VMEM as ordinary blocks; the
    kernel applies them as per-column multiplies in score/probs space
    (mathematically identical to dequantizing the pages — see
    ``_kernel_ragged_dma``). int8 pages are therefore NEVER materialized
    as a dequantized contiguous gather anywhere on this path."""
    from .attention import QuantizedPages

    if q.shape[-1] % 128 != 0 and not interpret:
        raise ValueError(
            f"pallas-dma needs head_dim % 128 == 0, got {q.shape[-1]}; "
            f"use impl='pallas' or 'xla'"
        )
    k_scale = v_scale = None
    if isinstance(k_pages, QuantizedPages):
        k_pages, k_scale = k_pages.q, k_pages.scale
        v_pages, v_scale = v_pages.q, v_pages.scale
    if k_pages.ndim == 5:
        Lr, N, P, K, D = k_pages.shape
        k_pages = k_pages.reshape(Lr * N, P, K, D)
        v_pages = v_pages.reshape(Lr * N, P, K, D)
        if k_scale is not None:
            k_scale = k_scale.reshape(Lr * N, P, K)
            v_scale = v_scale.reshape(Lr * N, P, K)
        base = (layer if layer is not None else 0) * N
    else:
        N, P, K, D = k_pages.shape
        base = 0
    B, S, H, _ = q.shape
    MaxP = page_table.shape[1]
    base_arr = jnp.full((1,), base, jnp.int32)
    quantized = k_scale is not None

    in_specs = [
        pl.BlockSpec(
            (1, S, H, D), lambda b, t, st, ql, ba: (b, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        # Per-sequence scale planes, gathered OUTSIDE the kernel (tiny:
        # 4 bytes per D int8 values), FLATTENED to [B, MaxP, P*K] so the
        # lane dim is naturally 128-aligned, applied as per-column
        # multiplies in score space (see _kernel_ragged_dma). Same index
        # math as the kernel's DMA (max(slot, 0) + base), so value and
        # scale planes can never come from different pages for an
        # unassigned (-1) slot.
        safe_table = jnp.maximum(page_table, 0) + base
        sc_spec = pl.BlockSpec(
            (1, MaxP, P * K), lambda b, t, st, ql, ba: (b, 0, 0),
            memory_space=pltpu.VMEM,
        )
        in_specs += [sc_spec, sc_spec]
        operands += [
            k_scale[safe_table].reshape(B, MaxP, P * K),
            v_scale[safe_table].reshape(B, MaxP, P * K),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, S, H, D), lambda b, t, st, ql, ba: (b, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, P, K, D), k_pages.dtype),
            pltpu.VMEM((2, P, K, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.VMEM((S * H, D), jnp.float32),
            pltpu.VMEM((S * H, 128), jnp.float32),
            pltpu.VMEM((S * H, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel_ragged_dma, page_size=P, num_kv_heads=K,
            max_pages=MaxP, quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, H, D), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * B * S * H * D * MaxP * P,
            bytes_accessed=(
                B * MaxP * P * K * D * 2 * k_pages.dtype.itemsize
                + B * S * H * D * 2 * q.dtype.itemsize
            ),
            transcendentals=B * S * H * MaxP * P,
        ),
    )(
        page_table.astype(jnp.int32), start.astype(jnp.int32),
        q_lens.astype(jnp.int32), base_arr,
        *operands,
    )
    return out


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas(
    q: jax.Array,           # [B, H, D] (one new token per sequence)
    k_pages: jax.Array,     # [N, P, K, D] — or [L, N, P, K, D] with layer
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP] int32
    lengths: jax.Array,     # [B] int32 (incl. the token being decoded)
    interpret: bool = False,
    layer: jax.Array | None = None,  # [] int32 with the layer-axis form
) -> jax.Array:
    """Grid-form paged decode attention. Accepts
    ``ops.attention.QuantizedPages`` exactly like the ragged grid kernel:
    int8 pages ride the per-page BlockSpec pipeline at half the bytes,
    per-page [1, 1, P*K] scale planes ride beside them on the same
    clamped slot index map, applied in score/probs space."""
    from .attention import QuantizedPages

    k_scale = v_scale = None
    if isinstance(k_pages, QuantizedPages):
        k_pages, k_scale = k_pages.q, k_pages.scale
        v_pages, v_scale = v_pages.q, v_pages.scale
    if k_pages.ndim == 5:
        # Whole-cache form: flatten [L, N] -> [L*N] pages (free reshape) and
        # offset the scalar-prefetched page lookups by layer * N, so the
        # layer scan can carry ONE cache array without per-layer slicing.
        Lr, N, P, K, D = k_pages.shape
        k_pages = k_pages.reshape(Lr * N, P, K, D)
        v_pages = v_pages.reshape(Lr * N, P, K, D)
        if k_scale is not None:
            k_scale = k_scale.reshape(Lr * N, P, K)
            v_scale = v_scale.reshape(Lr * N, P, K)
        base = (layer if layer is not None else 0) * N
    else:
        N, P, K, D = k_pages.shape
        base = 0
    B, H, _ = q.shape
    MaxP = page_table.shape[1]
    base_arr = jnp.full((1,), base, jnp.int32)
    quantized = k_scale is not None

    page_map = functools.partial(_page_index, page_size=P)
    in_specs = [
        pl.BlockSpec(
            (1, H, D), lambda b, p, t, ln, ba: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec((1, P, K, D), page_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, P, K, D), page_map, memory_space=pltpu.VMEM),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        safe_table = jnp.maximum(page_table, 0) + base
        sc_map = functools.partial(_scale_index, page_size=P)
        sc_spec = pl.BlockSpec(
            (1, 1, P * K), sc_map, memory_space=pltpu.VMEM
        )
        in_specs += [sc_spec, sc_spec]
        operands += [
            k_scale[safe_table].reshape(B, MaxP, P * K),
            v_scale[safe_table].reshape(B, MaxP, P * K),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, MaxP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, H, D), lambda b, p, t, ln, ba: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((H, D), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel, page_size=P, num_kv_heads=K, quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 2 * B * H * D * MaxP * P,
            bytes_accessed=(
                B * MaxP * P * K * D * 2 * k_pages.dtype.itemsize
                + B * H * D * 2 * q.dtype.itemsize
            ),
            transcendentals=B * H * MaxP * P,
        ),
    )(
        page_table.astype(jnp.int32), lengths.astype(jnp.int32), base_arr,
        *operands,
    )
    return out
