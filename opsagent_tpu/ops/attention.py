"""Attention ops: prefill (causal GQA) and paged-KV decode.

These are the XLA reference implementations — correct on any backend and the
ground truth for the Pallas TPU kernels in ``paged_attention_pallas.py``.
Softmax accumulates in float32 regardless of the activation dtype (bf16 on
TPU) for numerical parity with the fused kernels.

The paged layout: KV lives in fixed-size pages ``[num_pages, page_size,
num_kv_heads, head_dim]``; a sequence owns a row of the page table
``[max_pages_per_seq]`` holding page indices. This is the structure the
continuous-batching scheduler allocates against (SURVEY.md section 7 step 5 /
the Ragged-Paged-Attention design in PAPERS.md).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


@jax.tree_util.register_pytree_node_class
class QuantizedPages:
    """int8 KV pages + per-(slot, token, head) float32 scales.

    At the 8B bench shape KV reads (~4 GB/step at 4k context, B=32) rival
    the int4 weight stream (PERF.md), so halving them is the next decode
    lever after weight quantization. ``q`` keeps the page layout
    [L, N, P, K, D] (or [N, P, K, D]) in int8; ``scale`` drops the D axis:
    one symmetric absmax scale per written token per kv head — 4 bytes per
    D-row, ~3 % traffic overhead at D=128, and near-lossless for attention
    (per-token scaling keeps rounding error local, the same locality
    argument as group-wise int4 weights).

    A registered pytree node, so it flows through lax.scan carries,
    shard_params, donation, and engine restart plumbing exactly like a
    plain page array. Readers dequantize AFTER their page gather — XLA
    fuses the convert+multiply into the attention matmul's operand read,
    so HBM sees int8 pages + small scales, never a dequantized copy."""

    def __init__(self, q: jax.Array, scale: jax.Array):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype


def quantize_kv_rows(new: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[B, S, K, D] fresh K/V -> (int8 values, [B, S, K] f32 scales):
    symmetric absmax over the head dim, the write-side half of
    ``QuantizedPages``."""
    absmax = jnp.max(jnp.abs(new.astype(jnp.float32)), axis=-1)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.round(new.astype(jnp.float32) / scale[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def _dequantize_gathered(seq: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Gathered int8 [..., K, D] + scales [..., K] -> compute dtype."""
    return (seq.astype(jnp.float32) * scale[..., None]).astype(dtype)


def paged_attention_backend() -> str:
    """Which decode-attention implementation to use: "xla" (gather-based),
    "pallas" ((B, MaxP) grid kernel), or "pallas-dma" (manual
    double-buffered page streaming). Env OPSAGENT_PAGED_BACKEND overrides.

    Default is "xla" EVERYWHERE — by measurement, not preference: the
    r01 on-chip comparison had the gather beating the grid kernel at
    decode shapes (per-page pipeline-step overhead), and the committed
    headline numbers are xla numbers. "pallas-dma" now covers BOTH hot
    paths — decode (``paged_decode_attention_pallas_dma``) and the
    mixed ragged step (``paged_ragged_attention_pallas_dma``), each
    streaming int8 ``QuantizedPages`` at half the bytes — and the bench
    ragged-backend sweep (xla vs pallas vs pallas-dma × KV dtype ×
    weight quant) promotes it into the headline the moment an on-chip
    run shows it winning; the default flips only on that evidence.
    Interpret-mode tests cover semantics, not Mosaic lowering or speed,
    and head_dim % 128 != 0 still rejects (r04 on-chip: Mosaic
    manual-DMA alignment)."""
    choice = os.environ.get("OPSAGENT_PAGED_BACKEND", "auto")
    if choice in ("pallas", "pallas-dma", "xla"):
        return choice
    if choice != "auto":
        raise ValueError(
            f"OPSAGENT_PAGED_BACKEND={choice!r}: expected pallas, "
            f"pallas-dma, xla, or auto"
        )
    return "xla"


def pallas_interpret() -> bool:
    """Whether the Pallas kernels should run in interpret mode
    (OPSAGENT_PALLAS_INTERPRET=1): the CPU escape hatch that lets the
    bench ragged-backend sweep smoke and CI exercise the pallas /
    pallas-dma dispatch paths end-to-end off-TPU, where a compiled
    pallas_call cannot lower. Read at trace time by the ``*_auto``
    dispatchers; never set it on real hardware (interpret mode is
    orders of magnitude slower and skips Mosaic entirely)."""
    return os.environ.get("OPSAGENT_PALLAS_INTERPRET", "") == "1"


def _shard_map(fn, mesh: Mesh, in_specs, out_specs):
    # check_vma/check_rep off: pallas_call does not annotate its outputs'
    # varying-mesh-axes metadata, and the head axis is fully data-parallel
    # here (no cross-shard reduction to validate anyway).
    try:
        from jax import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def _pallas_kernel_fn(impl: str):
    from .paged_attention_pallas import (
        paged_decode_attention_pallas,
        paged_decode_attention_pallas_dma,
    )

    return (
        paged_decode_attention_pallas_dma if impl == "pallas-dma"
        else paged_decode_attention_pallas
    )


def _ragged_pallas_kernel_fn(impl: str):
    from .paged_attention_pallas import (
        paged_ragged_attention_pallas,
        paged_ragged_attention_pallas_dma,
    )

    return (
        paged_ragged_attention_pallas_dma if impl == "pallas-dma"
        else paged_ragged_attention_pallas
    )


def paged_decode_attention_pallas_tp(
    q: jax.Array,           # [B, H, D] — H sharded over tp
    k_pages: jax.Array,     # [N, P, K, D] or [L, N, P, K, D] — K over tp
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP] replicated
    lengths: jax.Array,     # [B] replicated
    mesh: Mesh,
    layer: jax.Array | None = None,
    interpret: bool = False,
    impl: str = "pallas",
) -> jax.Array:
    """The Pallas decode kernel under tensor parallelism.

    A bare pallas_call is opaque to the pjit partitioner, so it is wrapped
    in shard_map over the ``tp`` mesh axis: q's heads and the KV pages' kv
    heads are both tp-sharded (models.llama param/cache specs), every
    device runs the kernel on its own H/tp query heads against its own
    K/tp kv heads — the GQA group structure is preserved per shard and NO
    collective is needed (the head axis is fully data-parallel here; the
    all-reduce happens later at the wo row-parallel matmul)."""
    kernel = _pallas_kernel_fn(impl)

    spec_q = P(None, "tp", None)
    five_d = k_pages.ndim == 5
    spec_kv = (
        P(None, None, None, "tp", None) if five_d
        else P(None, None, "tp", None)
    )
    if isinstance(k_pages, QuantizedPages):
        # Scale planes shard with their values' kv-head axis (one fewer
        # trailing dim); the spec pytree mirrors the QuantizedPages leaf.
        spec_sc = (
            P(None, None, None, "tp") if five_d else P(None, None, "tp")
        )
        spec_kv = QuantizedPages(spec_kv, spec_sc)
    if layer is None:
        layer = jnp.int32(0)

    def local(q, kp, vp, table, ln, ly):
        return kernel(q, kp, vp, table, ln, interpret=interpret, layer=ly)

    mapped = _shard_map(
        local, mesh,
        in_specs=(spec_q, spec_kv, spec_kv, P(None, None), P(None), P()),
        out_specs=spec_q,
    )
    return mapped(q, k_pages, v_pages, page_table, lengths, layer)


def paged_decode_attention_auto(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,
    lengths: jax.Array,
    impl: str = "xla",
    layer: jax.Array | None = None,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Impl-dispatched paged decode attention (impl from
    ``paged_attention_backend``, resolved at trace time by the caller).
    With a mesh whose tp axis is >1, the Pallas path runs shard_mapped
    over tp (see ``paged_decode_attention_pallas_tp``). int8+scale
    ``QuantizedPages`` flow through EVERY impl: the XLA gather, the
    manual-DMA kernel, and the (B, MaxP) grid kernel all carry a
    score-space scale path now."""
    if impl.startswith("pallas"):
        interpret = pallas_interpret()
        if mesh is not None and mesh.shape.get("tp", 1) > 1:
            return paged_decode_attention_pallas_tp(
                q, k_pages, v_pages, page_table, lengths, mesh, layer=layer,
                impl=impl, interpret=interpret,
            )
        return _pallas_kernel_fn(impl)(
            q, k_pages, v_pages, page_table, lengths, layer=layer,
            interpret=interpret,
        )
    return paged_decode_attention(
        q, k_pages, v_pages, page_table, lengths, layer=layer
    )


def causal_prefill_attention(
    q: jax.Array,        # [B, S, H, D]
    k: jax.Array,        # [B, S, K, D]
    v: jax.Array,        # [B, S, K, D]
    lengths: jax.Array | None = None,  # [B] valid lengths (right padding)
) -> jax.Array:
    """Causal grouped-query attention over the in-flight (fresh) K/V."""
    B, S, H, D = q.shape
    K = k.shape[2]
    G = H // K
    scale = 1.0 / (D ** 0.5)
    qg = q.reshape(B, S, K, G, D)
    # MXU-native matmul in the input dtype, f32 accumulation.
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    pos_q = jnp.arange(S)[:, None]
    pos_t = jnp.arange(S)[None, :]
    mask = pos_t <= pos_q  # [S, S]
    mask = mask[None, None, None, :, :]
    if lengths is not None:
        tvalid = (jnp.arange(S)[None, :] < lengths[:, None])[:, None, None, None, :]
        mask = jnp.logical_and(mask, tvalid)
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgst,btkd->bskgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, S, H, D).astype(q.dtype)


def write_kv_pages(
    k_pages: jax.Array,     # [N, P, K, D] — or [L, N, P, K, D] with layer
    v_pages: jax.Array,     # like k_pages
    k_new: jax.Array,       # [B, S, K, D]
    v_new: jax.Array,       # [B, S, K, D]
    page_table: jax.Array,  # [B, MaxP] int32 page indices (-1 = unassigned)
    start: jax.Array,       # [B] int32 write offset (tokens already in cache)
    valid_len: jax.Array | None = None,  # [B] number of valid new tokens
    layer: jax.Array | None = None,  # [] int32 when pages carry a layer axis
) -> tuple[jax.Array, jax.Array]:
    """Scatter freshly-computed K/V into their sequences' pages.

    Token t of sequence b lands at flat slot ``page_table[b, (start[b]+t)//P]
    * P + (start[b]+t) % P`` (offset by ``layer * N * P`` when the pages
    carry a leading layer axis). Out-of-range/padded tokens get an
    out-of-bounds index and are dropped by the scatter (negative indices
    would WRAP under JAX indexing semantics, so the sentinel is past-the-end).

    The whole-cache-with-layer form exists so the layer stack can thread ONE
    cache array through ``lax.scan`` as a loop carry: the scatter then
    updates the carry in place, where per-layer stacked scan outputs would
    copy the entire cache every step (~GBs/step at serving shapes).
    """
    k_pages = write_pages(
        k_pages, k_new, page_table, start, valid_len=valid_len, layer=layer
    )
    v_pages = write_pages(
        v_pages, v_new, page_table, start, valid_len=valid_len, layer=layer
    )
    return k_pages, v_pages


def write_pages(
    pages: jax.Array,       # [N, P, K, D] — or [L, N, P, K, D] with layer
    new: jax.Array,         # [B, S, K, D]
    page_table: jax.Array,  # [B, MaxP] int32 page indices (-1 = unassigned)
    start: jax.Array,       # [B] int32 write offset (tokens already in cache)
    valid_len: jax.Array | None = None,  # [B] number of valid new tokens
    layer: jax.Array | None = None,  # [] int32 when pages carry a layer axis
) -> jax.Array:
    """Single-array page scatter (``write_kv_pages`` for one side; the MLA
    latent cache writes only one array per token).

    ``QuantizedPages`` targets quantize the fresh rows on write (absmax
    over the head dim) and scatter values and scales with the same flat
    indices, so the drop-sentinel/validity logic is shared."""
    if isinstance(pages, QuantizedPages):
        q_new, s_new = quantize_kv_rows(new)
        return QuantizedPages(
            write_pages(
                pages.q, q_new, page_table, start,
                valid_len=valid_len, layer=layer,
            ),
            _write_scale_pages(
                pages.scale, s_new, page_table, start,
                valid_len=valid_len, layer=layer,
            ),
        )
    if pages.ndim == 5:
        L, N, P, K, D = pages.shape
        total = L * N
        base = (layer if layer is not None else 0) * N
    else:
        N, P, K, D = pages.shape
        total = N
        base = 0
    B, S = new.shape[:2]
    flat = _flat_slot_indices(
        page_table, start, S, P, base, total, valid_len
    ).reshape(B * S)
    shape = pages.shape
    pf = pages.reshape(total * P, K, D)
    pf = pf.at[flat].set(new.reshape(B * S, K, D), mode="drop")
    return pf.reshape(shape)


def _flat_slot_indices(
    page_table: jax.Array,  # [B, MaxP] int32 page indices (-1 = unassigned)
    start: jax.Array,       # [B] int32 write offsets
    S: int,                 # tokens per row being written
    P: int,                 # page size
    base,                   # layer * N flat-page offset (0 without layers)
    total: int,             # total flat pages
    valid_len: jax.Array | None,
) -> jax.Array:
    """[B, S] flat cache-slot index per written token, shared by the value
    and scale planes so the drop-sentinel/validity logic cannot diverge.
    Token t of row b lands at ``(page_table[b, (start+t)//P] + base) * P +
    (start+t) % P``; unassigned (-1) pages and tokens past ``valid_len``
    get ``total * P`` — one past the end, dropped by the scatter (negative
    indices would WRAP under JAX indexing semantics, so the sentinel is
    past-the-end)."""
    oob = total * P
    pos = start[:, None] + jnp.arange(S)[None, :]          # [B, S]
    page_idx = jnp.take_along_axis(
        page_table, jnp.clip(pos // P, 0, page_table.shape[1] - 1), axis=1
    )                                                       # [B, S]
    flat = (page_idx + base) * P + pos % P                  # [B, S]
    if valid_len is not None:
        ok = jnp.arange(S)[None, :] < valid_len[:, None]
        return jnp.where(ok & (page_idx >= 0), flat, oob)
    return jnp.where(page_idx >= 0, flat, oob)


def _write_scale_pages(
    pages: jax.Array,       # [N, P, K] — or [L, N, P, K] with layer
    new: jax.Array,         # [B, S, K] per-token scales
    page_table: jax.Array,
    start: jax.Array,
    valid_len: jax.Array | None = None,
    layer: jax.Array | None = None,
) -> jax.Array:
    """``write_pages`` for the scale planes of ``QuantizedPages`` (same
    flat slot math via ``_flat_slot_indices``, one fewer axis)."""
    if pages.ndim == 4:
        L, N, P, K = pages.shape
        total = L * N
        base = (layer if layer is not None else 0) * N
    else:
        N, P, K = pages.shape
        total = N
        base = 0
    B, S = new.shape[:2]
    flat = _flat_slot_indices(
        page_table, start, S, P, base, total, valid_len
    ).reshape(B * S)
    shape = pages.shape
    pf = pages.reshape(total * P, K)
    pf = pf.at[flat].set(new.reshape(B * S, K), mode="drop")
    return pf.reshape(shape)


def _gather_kv(
    k_pages, v_pages, page_table: jax.Array, layer, dtype
) -> tuple[jax.Array, jax.Array]:
    """Shared page gather for the XLA readers: [B, MaxP] table ->
    contiguous ([B, L, K, D], [B, L, K, D]) sequence views, L = MaxP * P.
    Handles the optional leading layer axis (flatten + ``layer * N``
    offset) and ``QuantizedPages`` (gather int8 values + scales, then
    dequantize — XLA fuses the convert/multiply into the consuming
    einsum's operand read)."""
    k_scale = v_scale = None
    if isinstance(k_pages, QuantizedPages):
        k_pages, k_scale = k_pages.q, k_pages.scale
        v_pages, v_scale = v_pages.q, v_pages.scale
    if k_pages.ndim == 5:
        Lr, N, P, K, D = k_pages.shape
        base = (layer if layer is not None else 0) * N
        k_pages = k_pages.reshape(Lr * N, P, K, D)
        v_pages = v_pages.reshape(Lr * N, P, K, D)
        if k_scale is not None:
            k_scale = k_scale.reshape(Lr * N, P, K)
            v_scale = v_scale.reshape(Lr * N, P, K)
        nmax = Lr * N - 1
    else:
        N, P, K, D = k_pages.shape
        base = 0
        nmax = N - 1
    B = page_table.shape[0]
    L = page_table.shape[1] * P
    safe_table = jnp.clip(page_table + base, 0, nmax)
    k_seq = k_pages[safe_table].reshape(B, L, K, D)
    v_seq = v_pages[safe_table].reshape(B, L, K, D)
    if k_scale is not None:
        ks = k_scale[safe_table].reshape(B, L, K)
        vs = v_scale[safe_table].reshape(B, L, K)
        k_seq = _dequantize_gathered(k_seq, ks, dtype)
        v_seq = _dequantize_gathered(v_seq, vs, dtype)
    return k_seq, v_seq


def paged_ragged_attention(
    q: jax.Array,           # [B, S, H, D] queries (right-padded per row)
    k_pages: jax.Array,     # [N, P, K, D] — or [L, N, P, K, D] with layer
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP]
    start: jax.Array,       # [B] tokens already in cache (queries begin here)
    q_lens: jax.Array,      # [B] valid query rows per sequence (0 = inactive)
    layer: jax.Array | None = None,  # [] int32 with the layer-axis form
) -> jax.Array:
    """Ragged-query paged attention: every batch row carries its own query
    length, so q_len=1 decode rows and q_len=chunk prefill rows run in ONE
    program (PAPERS.md: Ragged Paged Attention, arxiv 2604.15464) — the op
    under the engine's mixed prefill+decode step, where chunked prefill
    rides the decode dispatch's weight stream instead of buying its own.

    Row b's fresh K/V has already been written into pages at offset
    ``start[b]``; query s attends causally to every cached position
    t <= start[b] + s (causal masking INSIDE the chunk) and nothing past
    ``start[b] + q_lens[b]``. Rows with q_lens == 0 produce garbage output
    (finite — all-masked softmax degrades to uniform) that callers
    discard. Gather-based XLA reference; the Pallas page-streaming variant
    is ``paged_ragged_attention_pallas`` behind
    ``paged_ragged_attention_auto``."""
    k_seq, v_seq = _gather_kv(k_pages, v_pages, page_table, layer, q.dtype)
    B, S, H, _ = q.shape
    K, D = k_seq.shape[-2:]
    G = H // K
    L = k_seq.shape[1]
    scale = 1.0 / (D ** 0.5)
    qg = q.reshape(B, S, K, G, D)
    scores = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k_seq, preferred_element_type=jnp.float32
    ) * scale
    pos_t = jnp.arange(L)[None, None, :]                   # [1, 1, L]
    pos_q = (start[:, None] + jnp.arange(S)[None, :])[:, :, None]  # [B, S, 1]
    mask = (pos_t <= pos_q) & (pos_t < (start + q_lens)[:, None, None])
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgst,btkd->bskgd",
        probs.astype(v_seq.dtype),
        v_seq,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, S, H, D).astype(q.dtype)


def paged_prefix_attention(
    q: jax.Array,           # [B, S, H, D] tail queries (right-padded)
    k_pages: jax.Array,     # [N, P, K, D] — or [L, N, P, K, D] with layer
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP]
    start: jax.Array,       # [B] cached-prefix lengths (tail begins here)
    lengths: jax.Array,     # [B] valid TAIL lengths
    layer: jax.Array | None = None,  # [] int32 with the layer-axis form
) -> jax.Array:
    """Tail-prefill attention over paged KV holding [prefix + tail] — the
    prefix-cache admission path. Prefix attention IS ragged paged
    attention (per-row write offset + per-row valid tail length), so this
    is the same op under its admission-era name."""
    return paged_ragged_attention(
        q, k_pages, v_pages, page_table, start, lengths, layer=layer
    )


def paged_ragged_attention_pallas_tp(
    q: jax.Array,           # [B, S, H, D] — H sharded over tp
    k_pages: jax.Array,     # [N, P, K, D] or [L, N, P, K, D] — K over tp
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP] replicated
    start: jax.Array,       # [B] replicated
    q_lens: jax.Array,      # [B] replicated
    mesh: Mesh,
    layer: jax.Array | None = None,
    interpret: bool = False,
    impl: str = "pallas",
) -> jax.Array:
    """The ragged Pallas kernels under tensor parallelism: shard_mapped
    over ``tp`` exactly like ``paged_decode_attention_pallas_tp`` — query
    heads and kv heads are both tp-sharded, the GQA group structure is
    preserved per shard, and no collective is needed (the all-reduce
    happens later at the wo row-parallel matmul). ``impl`` picks the grid
    kernel ("pallas") or the manual-DMA streamer ("pallas-dma"); with
    ``QuantizedPages`` the scale planes shard with their values' kv-head
    axis, mirroring the decode TP wrapper."""
    kernel = _ragged_pallas_kernel_fn(impl)

    spec_q = P(None, None, "tp", None)
    five_d = k_pages.ndim == 5
    spec_kv = (
        P(None, None, None, "tp", None) if five_d
        else P(None, None, "tp", None)
    )
    if isinstance(k_pages, QuantizedPages):
        # Scale planes shard with their values' kv-head axis (one fewer
        # trailing dim); the spec pytree mirrors the QuantizedPages leaf.
        spec_sc = (
            P(None, None, None, "tp") if five_d else P(None, None, "tp")
        )
        spec_kv = QuantizedPages(spec_kv, spec_sc)
    if layer is None:
        layer = jnp.int32(0)

    def local(q, kp, vp, table, st, ql, ly):
        return kernel(
            q, kp, vp, table, st, ql, interpret=interpret, layer=ly
        )

    mapped = _shard_map(
        local, mesh,
        in_specs=(
            spec_q, spec_kv, spec_kv, P(None, None), P(None), P(None), P()
        ),
        out_specs=spec_q,
    )
    return mapped(q, k_pages, v_pages, page_table, start, q_lens, layer)


def paged_ragged_attention_auto(
    q: jax.Array,           # [B, S, H, D]
    k_pages: jax.Array,
    v_pages: jax.Array,
    page_table: jax.Array,  # [B, MaxP]
    start: jax.Array,       # [B]
    q_lens: jax.Array,      # [B]
    impl: str = "xla",
    layer: jax.Array | None = None,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Impl-dispatched ragged paged attention (the mixed-step analogue of
    ``paged_decode_attention_auto``). "pallas-dma" dispatches to the
    ragged manual-DMA streamer (``paged_ragged_attention_pallas_dma``)
    and "pallas" to the (B, MaxP) grid kernel — BOTH natively stream
    int8 ``QuantizedPages`` at half the bytes with score-space scales,
    so quantized pages on the mixed hot path are never materialized as a
    dequantized contiguous gather under any pallas impl."""
    if impl.startswith("pallas"):
        interpret = pallas_interpret()
        if mesh is not None and mesh.shape.get("tp", 1) > 1:
            return paged_ragged_attention_pallas_tp(
                q, k_pages, v_pages, page_table, start, q_lens, mesh,
                layer=layer, impl=impl, interpret=interpret,
            )
        return _ragged_pallas_kernel_fn(impl)(
            q, k_pages, v_pages, page_table, start, q_lens, layer=layer,
            interpret=interpret,
        )
    return paged_ragged_attention(
        q, k_pages, v_pages, page_table, start, q_lens, layer=layer
    )


def paged_decode_attention(
    q: jax.Array,           # [B, H, D] (one new token per sequence)
    k_pages: jax.Array,     # [N, P, K, D] — or [L, N, P, K, D] with layer
    v_pages: jax.Array,     # like k_pages
    page_table: jax.Array,  # [B, MaxP]
    lengths: jax.Array,     # [B] total tokens in cache (incl. the new one)
    layer: jax.Array | None = None,  # [] int32 with the layer-axis form
) -> jax.Array:
    """Decode-step attention over paged KV (gather-based XLA reference).

    Gathers each sequence's pages into a contiguous [B, MaxP*P] view and
    masks positions >= length. The Pallas kernel avoids this materialized
    gather; results must match to ~1e-2 in bf16 / 1e-5 in f32.
    """
    k_seq, v_seq = _gather_kv(k_pages, v_pages, page_table, layer, q.dtype)
    B, H, _ = q.shape
    K, D = k_seq.shape[-2:]
    G = H // K
    L = k_seq.shape[1]
    scale = 1.0 / (D ** 0.5)
    qg = q.reshape(B, K, G, D)
    scores = jnp.einsum(
        "bkgd,blkd->bkgl", qg, k_seq, preferred_element_type=jnp.float32
    ) * scale
    valid = (jnp.arange(L)[None, :] < lengths[:, None])[:, None, None, :]
    scores = jnp.where(valid, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bkgl,blkd->bkgd",
        probs.astype(v_seq.dtype),
        v_seq,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, H, D).astype(q.dtype)
