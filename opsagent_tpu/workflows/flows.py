"""The five workflows."""

from __future__ import annotations

from ..agent.funcall import (
    kubectl_function,
    trivy_function,
    run_function_agent,
)
from ..agent.react import assistant_with_config
from ..agent.prompts import (
    ANALYSIS_PROMPT,
    AUDIT_PROMPT,
    GENERATE_PROMPT,
    ASSISTANT_PROMPT,
    ASSISTANT_PROMPT_CN,
)
from ..llm.client import ChatClient, new_client_from_env

MAX_TURNS = 30


def analysis_flow(model: str, manifest: str, client: ChatClient | None = None) -> str:
    """Analyze a Kubernetes manifest (single-step flow, kubectl available)."""
    client = client or new_client_from_env()
    user_input = f"Analyze this Kubernetes manifest:\n\n```yaml\n{manifest}\n```"
    result, _ = run_function_agent(
        client,
        model,
        ANALYSIS_PROMPT,
        user_input,
        [kubectl_function()],
        max_turns=MAX_TURNS,
    )
    return result


def audit_flow(
    model: str, pod: str, namespace: str = "default", client: ChatClient | None = None
) -> str:
    """Security-audit a Pod: manifest review + trivy image scanning."""
    client = client or new_client_from_env()
    user_input = f"Audit the Pod '{pod}' in namespace '{namespace}'."
    result, _ = run_function_agent(
        client,
        model,
        AUDIT_PROMPT,
        user_input,
        [kubectl_function(), trivy_function()],
        max_turns=MAX_TURNS,
    )
    return result


def generator_flow(model: str, prompt: str, client: ChatClient | None = None) -> str:
    """Generate Kubernetes manifests (pure generation, no tools)."""
    client = client or new_client_from_env()
    result, _ = run_function_agent(
        client,
        model,
        GENERATE_PROMPT,
        prompt,
        [],
        max_turns=1,
    )
    return result


def assistant_flow(model: str, instructions: str, client: ChatClient | None = None) -> str:
    """Generic instruction-following flow with kubectl available."""
    client = client or new_client_from_env()
    result, _ = run_function_agent(
        client,
        model,
        ASSISTANT_PROMPT,
        instructions,
        [kubectl_function()],
        max_turns=MAX_TURNS,
    )
    return result


def assistant_flow_with_config(
    model: str,
    instructions: str,
    api_key: str = "",
    base_url: str = "",
) -> tuple[str, list[dict]]:
    """ReAct-loop variant with per-request credentials (reference
    assistant.go:174-185: maxTokens=2048, maxIterations=10, CN prompt)."""
    messages = [
        {"role": "system", "content": ASSISTANT_PROMPT_CN},
        {"role": "user", "content": instructions},
    ]
    return assistant_with_config(
        model,
        messages,
        max_tokens=2048,
        count_tokens=True,
        verbose=False,
        max_iterations=10,
        api_key=api_key,
        base_url=base_url,
    )
