"""Workflow layer: task-shaped flows over the function-calling agent.

Capability parity with the reference's pkg/workflows/: ``analysis_flow``
(analyze.go:47), ``audit_flow`` (audit.go:58), ``generator_flow``
(generate.go:56), ``assistant_flow`` / ``assistant_flow_with_config``
(assistant.go:69,163). The reference's ``AssistantFlow`` accidentally passes
the analysis prompt instead of its own (assistant.go:96); this rebuild uses
the correct assistant prompt.
"""

from .flows import (
    analysis_flow,
    audit_flow,
    generator_flow,
    assistant_flow,
    assistant_flow_with_config,
)

__all__ = [
    "analysis_flow",
    "audit_flow",
    "generator_flow",
    "assistant_flow",
    "assistant_flow_with_config",
]
