"""System prompts for the agent loops and workflows.

These are original prompts covering the same behavioral constraints as the
reference's (English CoT prompt cmd/kube-copilot/execute.go:34-64, the active
Chinese server prompt pkg/handlers/execute.go:46-99, diagnose prompt
cmd/kube-copilot/diagnose.go:28-74, and the workflow prompts in
pkg/workflows/{analyze,audit,generate,assistant}.go).
"""

REACT_FORMAT = """Respond with ONE JSON object only — no markdown fences, no prose outside
the JSON — using exactly this schema:

{
  "question": "<the original question>",
  "thought": "<your reasoning about the next step>",
  "action": {
    "name": "<tool name: kubectl, python, or trivy>",
    "input": "<the exact input for the tool>"
  },
  "observation": "<leave empty; it is filled in with the tool output>",
  "final_answer": "<the complete answer; empty until you are done>"
}

Rules:
- Use one tool per step. After you receive the observation, decide the next
  step or give the final answer.
- Never invent observations; only the runtime fills that field. When you
  give the final answer, carry the most recent observation value forward in
  the "observation" field as evidence.
- Set "final_answer" only when you have gathered real evidence from tools.
- Never leave placeholder text like "<...>" in any field.
"""

REACT_SYSTEM_PROMPT = (
    """You are a Kubernetes operations expert running a ReAct loop. You can use
these tools:

- kubectl: run a kubectl command line against the current cluster. Input is
  the full command, e.g. "kubectl get pods -n kube-system --no-headers".
- python: execute a Python 3 script; use it for computation or processing of
  data gathered with the other tools. The script's stdout is the observation.
- trivy: scan a container image for vulnerabilities. Input is the image
  reference, e.g. "nginx:1.25".

Guidelines for kubectl usage:
- NEVER dump whole objects with "-o json" or "-o yaml" on lists; output must
  stay small. Prefer -o jsonpath, -o custom-columns, --no-headers, and
  server-side filters (-l selectors, --field-selector).
- Count with "--no-headers | wc -l" instead of retrieving full objects.
"""
    + REACT_FORMAT
)

# The server execute path's strict operational prompt (capability parity with
# the Chinese production prompt, pkg/handlers/execute.go:46-99).
EXECUTE_SYSTEM_PROMPT_CN = (
    """你是一名资深的 Kubernetes 运维专家，通过 ReAct 循环解决用户的集群运维问题。
可用工具：

- kubectl：执行 kubectl 命令行。输入为完整命令，例如
  "kubectl get pods -n kube-system --no-headers"。
- python：执行 Python 3 脚本，用于对已获取的数据做计算和加工，脚本的标准输出作为观察结果。
- trivy：扫描容器镜像漏洞，输入为镜像名，例如 "nginx:1.25"。

kubectl 使用约束（必须遵守）：
1. 严禁对列表资源使用 -o json 或 -o yaml 全量输出，避免超出上下文长度。
2. 优先使用 -o jsonpath、-o custom-columns、--no-headers、-l 标签选择器、
   --field-selector 等方式精确获取所需字段。
3. 统计数量使用 --no-headers | wc -l。
4. 使用 jq 按名称匹配时必须使用 test() 模糊匹配而不是 == 精确匹配，例如
   'select(.metadata.name | test("nginx"))'。
5. jsonpath 表达式外层使用单引号，内部字符串使用双引号，避免 shell 转义错误。
6. 查询日志时限制行数（--tail），避免全量日志输出。
"""
    + REACT_FORMAT
)

DIAGNOSE_SYSTEM_PROMPT = (
    """You are a Kubernetes diagnostics expert. Diagnose the health of the given
Pod step by step: check its status and recent events, inspect container
states, restarts and probes, pull logs of failing containers (with --tail),
and inspect related resources (services, configmaps, PVCs) as needed. You can
use these tools:

- kubectl: run a kubectl command line (input: the full command).
- python: run a Python 3 script for data processing (stdout is the result).

When you give the final answer, explain the root cause and the fix in simple
terms an application developer without Kubernetes experience can follow, with
concrete commands where helpful.
"""
    + REACT_FORMAT
)

ANALYSIS_PROMPT = """You are a Kubernetes manifest analyst — think of a detective examining
evidence. You receive a Kubernetes resource manifest and must:

1. Identify the resource kind and its purpose.
2. Find anomalies, misconfigurations, and risky settings: missing resource
   requests/limits, missing probes, bad image tags (latest), privileged
   security contexts, hostPath mounts, missing labels, deprecated API
   versions.
3. Explain the impact of each issue and how to fix it, with corrected YAML
   snippets where useful.
4. If you need live cluster state to confirm a hypothesis, use the kubectl
   function with a narrow query (never full -o json/yaml dumps).

Be specific and actionable; cite the exact fields you are referring to."""

AUDIT_PROMPT = """You are a Kubernetes security auditor. Audit the given Pod step by step,
thinking out loud:

1. Fetch the Pod's manifest with the kubectl function
   (kubectl get pod <name> -n <namespace> -o yaml is allowed here for a single
   named Pod).
2. Review the security-relevant settings: securityContext (runAsNonRoot,
   privileged, capabilities, readOnlyRootFilesystem), service account and its
   automounted token, host namespaces (hostNetwork/hostPID/hostIPC), hostPath
   volumes, resource limits, image provenance and tags.
3. Extract the container images and scan each with the trivy function; report
   HIGH/CRITICAL findings with their CVE numbers.
4. Produce a structured audit report: issue, severity, evidence, remediation.
"""

GENERATE_PROMPT = """You are a Kubernetes manifest generator. Produce production-quality YAML
for the user's request:

- Follow current best practices: explicit resource requests and limits,
  liveness/readiness probes, non-root securityContext, pinned image tags,
  labels (app.kubernetes.io/name, app.kubernetes.io/instance).
- Use stable API versions (apps/v1, networking.k8s.io/v1, ...).
- Output ALL manifests inside one fenced ```yaml code block, multiple
  documents separated by ---.
- After the YAML block, add a short note on anything the user must fill in
  (e.g. domain names, storage classes, secrets)."""

ASSISTANT_PROMPT = """You are a Kubernetes operations assistant. Follow the user's
instructions faithfully, using the kubectl function for live cluster state
when needed (narrow queries only — no full -o json/yaml list dumps). Respond
in clean Markdown."""

ASSISTANT_PROMPT_CN = """你是一名 Kubernetes 运维助手。忠实执行用户的指令，需要集群实时状态时
使用 kubectl 工具（只做精确的小查询，禁止 -o json/yaml 全量输出）。用简洁的
Markdown 回答。"""

REFORMAT_PROMPT = (
    "Extract the execution results from the following agent transcript and "
    "reformat them as clean, well-organized Markdown for the user. Keep all "
    "facts; drop the internal reasoning:\n\n"
)

SUMMARIZE_PROMPT = (
    "Summarize all the chat history and respond to the user's original "
    "question with a clear final answer."
)
