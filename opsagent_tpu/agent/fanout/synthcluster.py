"""Deterministic synthetic Kubernetes cluster with injected issues.

The fan-out subsystem needs a cluster it can audit end to end where the
RIGHT answer is known in advance: tests assert recall == 1.0 against the
injected issues and byte-identical reduce reports across runs, and the
bench stage scores a real fleet serving workload against the same ground
truth. Everything here is a pure function of ``(resources, seed,
issue_fraction)`` — same inputs, same pods, same evidence text, same
ground truth — so two audits of the same cluster must agree to the byte.

Four issue archetypes are injected, each with evidence shaped like the
``kubectl describe pod`` output a real probe would return:

========== ========== ==========================================
archetype  severity   evidence signature
========== ========== ==========================================
oomkill    critical   ``Last State: Terminated / Reason: OOMKilled``
crashloop  high       ``Waiting / Reason: CrashLoopBackOff`` + back-off
privileged high       ``securityContext: privileged: true``
bad_probe  medium     ``Readiness probe failed`` warning events
========== ========== ==========================================

``detect_findings`` is the deterministic rule layer over that evidence:
the schema-constrained LLM decode is the serving workload the fan-out
measures, while the findings that score recall come from rules a random
-weight test checkpoint cannot get wrong.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any

# Closed severity enum: the opsagent_fanout_findings_total label and the
# reduce sort both key on it (metrics cardinality guard rejects strays).
SEVERITIES = (
    "critical", "high", "medium", "low", "none", "unavailable",
)

ISSUE_SEVERITY = {
    "oomkill": "critical",
    "crashloop": "high",
    "privileged": "high",
    "bad_probe": "medium",
}

_NAMESPACES = (
    "payments", "search", "ingest", "auth", "billing", "media",
    "edge", "mlserve",
)
_APPS = ("api", "worker", "gateway", "cache", "indexer", "relay")
_IMAGES = (
    "registry.local/app:v1.42", "registry.local/app:v1.43",
    "registry.local/sidecar:v0.9", "registry.local/base:v2.1",
)
_SUFFIX = "abcdefhkmnpqrstvwxz246789"  # k8s-ish pod hash alphabet


def severity_rank(severity: str) -> int:
    """Stable sort key: most severe first, unknown values last."""
    try:
        return SEVERITIES.index(severity)
    except ValueError:
        return len(SEVERITIES)


@dataclass(frozen=True)
class PodSpec:
    namespace: str
    deployment: str
    name: str
    node: str
    image: str
    restarts: int
    issue: str | None  # archetype key, or None for a healthy pod

    @property
    def resource(self) -> str:
        return f"{self.namespace}/{self.name}"


class SynthCluster:
    """Seeded synthetic cluster inventory + per-resource probe evidence."""

    def __init__(
        self,
        resources: int = 64,
        seed: int = 0,
        issue_fraction: float = 0.25,
    ):
        if resources < 1:
            raise ValueError("resources must be >= 1")
        self.resources = int(resources)
        self.seed = int(seed)
        self.issue_fraction = float(issue_fraction)
        rng = random.Random(f"synthcluster:{self.seed}")
        archetypes = sorted(ISSUE_SEVERITY)
        n_issues = min(
            self.resources,
            max(1, round(self.resources * self.issue_fraction)),
        )
        bad = set(rng.sample(range(self.resources), n_issues))
        nodes = [f"node-{i:02d}" for i in range(max(2, self.resources // 16))]
        pods: list[PodSpec] = []
        seen: set[str] = set()
        issue_i = 0
        for i in range(self.resources):
            ns = _NAMESPACES[i % len(_NAMESPACES)]
            app = rng.choice(_APPS)
            dep = f"{ns}-{app}"
            while True:
                name = (
                    f"{app}-{''.join(rng.choices(_SUFFIX, k=5))}"
                    f"-{''.join(rng.choices(_SUFFIX, k=5))}"
                )
                if f"{ns}/{name}" not in seen:
                    break
            seen.add(f"{ns}/{name}")
            issue = None
            if i in bad:
                issue = archetypes[issue_i % len(archetypes)]
                issue_i += 1
            pods.append(PodSpec(
                namespace=ns,
                deployment=dep,
                name=name,
                node=rng.choice(nodes),
                image=rng.choice(_IMAGES),
                restarts=(
                    rng.randint(7, 99) if issue == "crashloop"
                    else rng.randint(0, 2)
                ),
                issue=issue,
            ))
        self.pods = pods
        self._by_resource = {p.resource: p for p in pods}

    # -- inventory (the shared audit context) -------------------------------
    def inventory_text(self) -> str:
        """One compact line for the shared prompt prefix: deliberately a
        SUMMARY, not the pod list — the per-resource detail arrives via
        the probe, so the shared prefix stays identical for every child."""
        namespaces = sorted({p.namespace for p in self.pods})
        return (
            f"Cluster synth-{self.seed}: {len(self.pods)} pods across "
            f"{len(namespaces)} namespaces ({', '.join(namespaces)})."
        )

    def work_items(self) -> list[str]:
        """Per-resource audit shards, in a deterministic order."""
        return [p.resource for p in self.pods]

    # -- ground truth -------------------------------------------------------
    def ground_truth(self) -> list[dict[str, Any]]:
        """The injected issues as finding rows, reduce-sorted."""
        rows = [
            {
                "resource": p.resource,
                "issue": p.issue,
                "severity": ISSUE_SEVERITY[p.issue],
            }
            for p in self.pods if p.issue is not None
        ]
        rows.sort(key=lambda f: (
            severity_rank(f["severity"]), f["resource"], f["issue"],
        ))
        return rows

    # -- probe evidence -----------------------------------------------------
    def describe(self, resource: str) -> str:
        """``kubectl describe pod``-shaped evidence for one resource —
        what the child's Conveyor probe returns mid-decode."""
        p = self._by_resource.get(resource)
        if p is None:
            return f'Error from server (NotFound): pod "{resource}" not found'
        lines = [
            f"Name:         {p.name}",
            f"Namespace:    {p.namespace}",
            f"Node:         {p.node}",
            f"Controlled By: Deployment/{p.deployment}",
            "Containers:",
            "  main:",
            f"    Image:         {p.image}",
            f"    Restart Count: {p.restarts}",
        ]
        if p.issue == "privileged":
            lines += [
                "    Security Context:",
                "      privileged: true",
                "    State:          Running",
            ]
        elif p.issue == "crashloop":
            lines += [
                "    State:          Waiting",
                "      Reason:       CrashLoopBackOff",
                "    Last State:     Terminated",
                "      Reason:       Error",
                "      Exit Code:    1",
            ]
        elif p.issue == "oomkill":
            lines += [
                "    State:          Running",
                "    Last State:     Terminated",
                "      Reason:       OOMKilled",
                "      Exit Code:    137",
            ]
        else:
            lines += ["    State:          Running"]
        lines += ["Conditions:", "  Ready  " + (
            "False" if p.issue in ("crashloop", "bad_probe") else "True"
        ), "Events:"]
        if p.issue == "crashloop":
            lines.append(
                "  Warning  BackOff  Back-off restarting failed container "
                f"main in pod {p.name}"
            )
        elif p.issue == "oomkill":
            lines.append(
                "  Warning  Evicted  container main exceeded its memory "
                "limit"
            )
        elif p.issue == "bad_probe":
            lines.append(
                "  Warning  Unhealthy  Readiness probe failed: HTTP probe "
                "failed with statuscode: 503"
            )
        else:
            lines.append("  <none>")
        return "\n".join(lines)


def detect_findings(evidence: str, resource: str) -> list[dict[str, Any]]:
    """Deterministic triage rules over probe evidence. Ordered by the
    evidence signature's specificity; one finding per matched archetype."""
    out: list[dict[str, Any]] = []

    def add(issue: str, detail: str) -> None:
        out.append({
            "resource": resource,
            "issue": issue,
            "severity": ISSUE_SEVERITY[issue],
            "detail": detail,
        })

    if "Reason:       OOMKilled" in evidence or "Exit Code:    137" in evidence:
        add("oomkill", "container terminated by the OOM killer (exit 137)")
    if "CrashLoopBackOff" in evidence or "Back-off restarting" in evidence:
        add("crashloop", "container in restart back-off")
    if "privileged: true" in evidence:
        add("privileged", "container runs with privileged security context")
    if "probe failed" in evidence.lower():
        add("bad_probe", "readiness/liveness probe failing")
    return out
