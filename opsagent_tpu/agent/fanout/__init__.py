"""Cluster-scale audit fan-out: one audit request exploded into N
per-resource agent sessions and reduced back into a single report.

- ``synthcluster``: deterministic seeded synthetic cluster (namespaces /
  deployments / pods / events) with injected issue archetypes, so tests
  and bench score recall against a known ground truth.
- ``orchestrator``: the plan / scatter / reduce pipeline over a fleet
  router — batch-class children sharing one system+context prefix chain,
  Conveyor-style probe launches overlapping each child's decode, and a
  deterministic merge with per-child failure containment.
"""

from .orchestrator import FanoutConfig, FanoutReport, run_audit  # noqa: F401
from .synthcluster import SynthCluster, detect_findings  # noqa: F401
