"""Fan-out/reduce audit orchestrator: plan -> scatter -> reduce.

One audit request over an N-resource cluster becomes N batch-class child
sessions through the fleet router, then one deterministic report:

- **plan**: shard the cluster inventory into per-resource work items that
  all share one system-prompt + cluster-context prefix. The shared token
  prefix is measured once (page-aligned, the unit the KV trie matches)
  and becomes the denominator of the fan-out's prefix-hit accounting.
- **scatter**: prime each live decode replica with one prefix-bearing
  request so the shared pages are trie-resident BEFORE the admission
  wave (the no-thundering-herd guarantee: without it, N simultaneous
  admissions each re-prefill the same prefix), then submit the children
  as ``slo_class="batch"`` sessions with bounded in-flight concurrency.
  Each child launches its probe (``kubectl describe``-shaped evidence
  from the synthetic cluster) the moment its completion is dispatched —
  the Conveyor overlap, probe latency hidden behind the child's decode —
  and decodes schema-constrained findings JSON so grammar fast-forward
  eats the structural tokens.
- **reduce**: merge per-child findings with a stable
  ``(severity, resource, issue)`` sort into one report whose canonical
  JSON form is byte-identical across runs. Failure containment is
  per-child: a child that stays shed/failed after bounded retries
  becomes a ``finding_unavailable`` row — an audit is never silently
  missing a resource.

The accounting deliberately reads COUNTER DELTAS (prefix-hit tokens),
not flight-ring events: flood-control sampling of high-volume flight
kinds during the admission wave must not be able to corrupt the
fan-out's own numbers.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from ... import obs
from .synthcluster import SynthCluster, detect_findings, severity_rank

# Schema the children decode under (grammar ffwd forces the structure;
# only the value bytes cost forward passes).
FINDING_SCHEMA = {
    "type": "object",
    "properties": {
        "resource": {"type": "string"},
        "status": {"type": "string"},
    },
    "required": ["resource", "status"],
}

_SYSTEM_TEMPLATE = (
    "You are OpsAgent, auditing a Kubernetes cluster for operational "
    "risk. {inventory} Inspect the assigned resource with the probe "
    "evidence and report its status as JSON."
)


@dataclass
class FanoutConfig:
    """Knobs of one fan-out run. Defaults suit in-process test fleets;
    the CLI/bench override sizes from their own flags."""

    max_inflight: int = 8        # bounded scatter concurrency (the gate)
    max_tokens: int = 16         # per-child decode budget
    retries: int = 2             # per-child re-submissions before giving up
    retry_backoff_s: float = 0.05
    prime: bool = True           # pre-warm the shared prefix per replica
    constrained: bool = True     # schema-constrained findings decode
    probe_overlap: bool = True   # Conveyor-style probe launch at dispatch
    flight_sample: int = 0       # >1: sample admission/dispatch flight
    # kinds at 1-in-N while the wave is in flight (flood control)


@dataclass
class FanoutReport:
    """One finished fan-out. ``report``/``canonical`` are deterministic
    (byte-identical across runs of the same cluster); ``stats`` carries
    the run's timings and serving-side accounting and is not."""

    fanout_id: str
    report: dict[str, Any]
    canonical: str
    stats: dict[str, Any] = field(default_factory=dict)

    @property
    def findings(self) -> list[dict[str, Any]]:
        return self.report["findings"]

    def recall(self, cluster: SynthCluster) -> float:
        """Fraction of injected issues present in the reduced report."""
        truth = {
            (f["resource"], f["issue"]) for f in cluster.ground_truth()
        }
        if not truth:
            return 1.0
        got = {(f["resource"], f["issue"]) for f in self.findings}
        return len(truth & got) / len(truth)


# Process-wide active-fan-out accounting behind the obs gauges (top's
# fan-out row reads these through the history sampler).
_active_lock = threading.Lock()
_active = 0


def _set_active(delta: int) -> None:
    global _active
    with _active_lock:
        _active = max(0, _active + delta)
        obs.FANOUT_ACTIVE.set(float(_active))


def _child_body(
    system: str, resource: str, fanout_id: str, cfg: FanoutConfig,
) -> dict[str, Any]:
    body: dict[str, Any] = {
        "messages": [
            {"role": "system", "content": system},
            {
                "role": "user",
                "content": f"Audit resource {resource}.",
            },
        ],
        "max_tokens": cfg.max_tokens,
        "temperature": 0.0,
        "slo_class": "batch",
        "fanout_id": fanout_id,
    }
    if cfg.constrained:
        body["response_format"] = {
            "type": "json_schema",
            "json_schema": {"name": "finding", "schema": FINDING_SCHEMA},
        }
    return body


def _shared_prefix_tokens(
    router: Any, bodies: list[dict[str, Any]],
) -> tuple[int, int]:
    """(aligned_tokens, page_size) of the prompt prefix every child
    shares, measured the way the KV trie matches it: the common token
    prefix of two child prompts, rounded DOWN to full pages of the
    smallest live page size (a partial page never hits)."""
    page = 0
    for info in router.registry.alive(role="decode"):
        page = min(page, info.page_size) if page else info.page_size
    if len(bodies) < 2 or page <= 0:
        return 0, max(1, page)
    a = router.tokenize(bodies[0]) or []
    b = router.tokenize(bodies[1]) or []
    common = 0
    for x, y in zip(a, b):
        if x != y:
            break
        common += 1
    # The engine matches prompt_ids[:n-1] (the last token is always
    # decoded), so the shareable span is bounded by the shorter prompt
    # minus one.
    common = min(common, max(0, len(a) - 1), max(0, len(b) - 1))
    return (common // page) * page, page


def run_audit(
    router: Any,
    cluster: SynthCluster,
    cfg: FanoutConfig | None = None,
) -> FanoutReport:
    """Run one fan-out audit over ``cluster`` through ``router``.
    Blocking; safe to call from any thread."""
    cfg = cfg or FanoutConfig()
    fanout_id = obs.new_request_id("fanout")
    system = _SYSTEM_TEMPLATE.format(inventory=cluster.inventory_text())
    items = cluster.work_items()
    bodies = [_child_body(system, r, fanout_id, cfg) for r in items]
    aligned, page = _shared_prefix_tokens(router, bodies)
    obs.flight.record(
        "fanout_plan", fanout_id=fanout_id, children=len(items),
        shared_prefix_tokens=aligned, page_size=page,
    )
    obs.FANOUT_CHILDREN_TOTAL.set(float(len(items)))
    obs.FANOUT_CHILDREN_DONE.set(0.0)
    _set_active(+1)
    rec = obs.flight.get_recorder()
    sampled_kinds = ("admission", "dispatch", "ttft", "route_decision")
    if cfg.flight_sample > 1:
        for kind in sampled_kinds:
            rec.set_sample_rate(kind, cfg.flight_sample)
    t0 = time.perf_counter()
    try:
        if cfg.prime:
            primes = _prime_replicas(router, system, fanout_id, cfg)
        else:
            primes = 0
        hits0 = obs.PREFIX_HIT_TOKENS.value()
        t_scatter = time.perf_counter()
        results = _scatter(router, items, bodies, cluster, cfg)
        scatter_s = time.perf_counter() - t_scatter
        hit_tokens = obs.PREFIX_HIT_TOKENS.value() - hits0
    finally:
        if cfg.flight_sample > 1:
            for kind in sampled_kinds:
                rec.set_sample_rate(kind, 0)
        _set_active(-1)

    # -- reduce -------------------------------------------------------------
    t_reduce = time.perf_counter()
    rows: list[dict[str, Any]] = []
    outcomes = {"ok": 0, "shed": 0, "failed": 0}
    for r in results:
        outcomes[r["outcome"]] = outcomes.get(r["outcome"], 0) + 1
        if r["outcome"] == "ok":
            rows.extend(r["findings"])
        else:
            # Failure containment: the resource stays in the report.
            rows.append({
                "resource": r["resource"],
                "issue": "finding_unavailable",
                "severity": "unavailable",
                "detail": f"child {r['outcome']}",
            })
    rows.sort(key=lambda f: (
        severity_rank(f["severity"]), f["resource"], f["issue"],
    ))
    by_severity: dict[str, int] = {}
    for f in rows:
        by_severity[f["severity"]] = by_severity.get(f["severity"], 0) + 1
    report = {
        "cluster": {
            "name": f"synth-{cluster.seed}",
            "resources": len(items),
            "seed": cluster.seed,
        },
        "findings": rows,
        "summary": {
            "resources": len(items),
            "audited": outcomes.get("ok", 0),
            "unavailable": len(items) - outcomes.get("ok", 0),
            "findings": sum(
                n for s, n in by_severity.items() if s != "unavailable"
            ),
            "by_severity": by_severity,
        },
    }
    canonical = json.dumps(
        report, sort_keys=True, separators=(",", ":"), ensure_ascii=False,
    )
    reduce_s = time.perf_counter() - t_reduce
    total_s = time.perf_counter() - t0

    # -- per-fan-out accounting --------------------------------------------
    n = len(items)
    denom = n * aligned
    hit_rate = min(1.0, hit_tokens / denom) if denom else 0.0
    avoided_children = (
        min(n, int(hit_tokens // aligned)) if aligned else 0
    )
    for outcome, count in outcomes.items():
        if count:
            obs.FANOUT_CHILDREN.inc(count, outcome=outcome)
    for severity, count in by_severity.items():
        obs.FANOUT_FINDINGS.inc(count, severity=severity)
    if hit_tokens > 0:
        obs.FANOUT_REPREFILL_AVOIDED.inc(hit_tokens)
    obs.FANOUT_REDUCE_SECONDS.observe(reduce_s)
    obs.FANOUT_PREFIX_HIT_RATE.set(hit_rate)
    obs.flight.record(
        "fanout_reduce", fanout_id=fanout_id, children=n,
        findings=len(rows), reduce_s=round(reduce_s, 4),
        audit_s=round(total_s, 4), prefix_hit_rate=round(hit_rate, 4),
        avoided_children=avoided_children, outcomes=outcomes,
    )
    stats = {
        "fanout_id": fanout_id,
        "children": n,
        "outcomes": outcomes,
        "primes": primes,
        "audit_s": total_s,
        "scatter_s": scatter_s,
        "reduce_s": reduce_s,
        "shared_prefix_tokens": aligned,
        "prefix_hit_tokens": int(hit_tokens),
        "prefix_hit_rate": hit_rate,
        "avoided_children": avoided_children,
    }
    return FanoutReport(
        fanout_id=fanout_id, report=report, canonical=canonical,
        stats=stats,
    )


def _prime_replicas(
    router: Any, system: str, fanout_id: str, cfg: FanoutConfig,
) -> int:
    """Land the shared prefix on every live decode replica before the
    wave: one forced single-token request per replica inserts the prefix
    pages into that replica's trie, so child #1..N all hit instead of
    racing to re-prefill it N times (and the pagestore directory learns
    an owner for cross-replica fault-in)."""
    primed = 0
    for info in router.registry.alive(role="decode"):
        body = {
            "messages": [
                {"role": "system", "content": system},
                {"role": "user", "content": "Audit resource warmup."},
            ],
            "max_tokens": 1,
            "temperature": 0.0,
            "slo_class": "batch",
            "fanout_id": fanout_id,
        }
        try:
            router.complete(body, force_replica=info.replica_id)
            primed += 1
        except Exception:  # noqa: BLE001 - priming is an optimization
            obs.flight.record(
                "fanout_prime_failed", fanout_id=fanout_id,
                replica=info.replica_id,
            )
    return primed


def _scatter(
    router: Any,
    items: list[str],
    bodies: list[dict[str, Any]],
    cluster: SynthCluster,
    cfg: FanoutConfig,
) -> list[dict[str, Any]]:
    from concurrent.futures import ThreadPoolExecutor

    done_lock = threading.Lock()
    done = 0

    def child(idx: int) -> dict[str, Any]:
        nonlocal done
        resource = items[idx]
        body = bodies[idx]
        outcome = "failed"
        evidence = ""
        for attempt in range(cfg.retries + 1):
            probe: dict[str, Any] = {}
            probe_thread = None
            t_launch = time.perf_counter()
            if cfg.probe_overlap:
                # Conveyor at fleet granularity: the probe fires the
                # moment the completion is dispatched, so its latency
                # overlaps the child's decode instead of following it.
                def run_probe() -> None:
                    probe["evidence"] = cluster.describe(resource)
                    probe["t_end"] = time.perf_counter()

                probe_thread = threading.Thread(
                    target=run_probe, daemon=True
                )
                obs.TOOL_EARLY_LAUNCHES.inc(tool="kubectl")
                probe_thread.start()
            try:
                router.complete(dict(body))
                outcome = "ok"
            except Exception as e:  # noqa: BLE001 - contained per child
                shed = getattr(e, "retry_after_s", None) is not None or \
                    type(e).__name__ == "OverloadError"
                outcome = "shed" if shed else "failed"
                if probe_thread is not None:
                    probe_thread.join()
                if attempt < cfg.retries:
                    time.sleep(cfg.retry_backoff_s * (attempt + 1))
                    continue
                break
            t_done = time.perf_counter()
            if probe_thread is not None:
                probe_thread.join()
                evidence = probe["evidence"]
                overlap = max(
                    0.0, min(probe["t_end"], t_done) - t_launch
                )
                obs.TOOL_OVERLAP_SECONDS.inc(overlap)
            else:
                evidence = cluster.describe(resource)
            break
        with done_lock:
            done += 1
            obs.FANOUT_CHILDREN_DONE.set(float(done))
        findings = (
            detect_findings(evidence, resource) if outcome == "ok" else []
        )
        return {
            "resource": resource,
            "outcome": outcome,
            "findings": findings,
        }

    workers = max(1, int(cfg.max_inflight))
    with ThreadPoolExecutor(max_workers=workers) as ex:
        return list(ex.map(child, range(len(items))))
