"""The ReAct agent core: the central tool-dispatch loop.

Capability parity with the reference's pkg/assistants/simple.go (the live
~330 lines): ``Assistant`` (simple.go:287) / ``AssistantWithConfig``
(simple.go:292) run a JSON-formatted ReAct loop against a chat model, with the
reference's full robustness ladder:

- unparseable FIRST reply is treated as the final answer (simple.go:375-381);
- iteration cap (simple.go:407-412);
- a ``final_answer`` is accepted only when it is not template/placeholder text
  AND at least one observation has been made (simple.go:414-419);
- tool failures become observations ("Tool X failed with error ...",
  simple.go:455), unknown tools likewise (simple.go:481);
- observations are truncated to 1024 tokens (simple.go:495);
- the updated ToolPrompt is marshaled back as a **user** message
  (simple.go:497-501) — this wire quirk is preserved because the serving
  engine's prefix cache keys on it;
- an unparseable mid-loop reply triggers one summarization turn and a
  best-effort ``final_answer`` extraction (simple.go:558-599).

The loop returns the model's final raw reply; consumers (the execute
handler's 4-stage parse ladder, the CLI) extract ``final_answer`` themselves.
"""

from __future__ import annotations

import re
import time
from typing import Any

from .. import obs
from ..llm.client import ChatClient
from ..serving import faults
from ..llm.tokens import constrict_messages, constrict_prompt, get_token_limits
from ..tools import ToolPrompt, get_tools, ToolError
from ..utils.jsonrepair import extract_field
from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats, trace_func
from . import conveyor
from .prompts import SUMMARIZE_PROMPT

log = get_logger("agent")

OBSERVATION_TOKEN_LIMIT = 1024

_PLACEHOLDER = re.compile(r"<[^<>\n]{0,80}>")


def is_template_value(value: str) -> bool:
    """Reject placeholder/template final answers (reference
    simple.go:624-657): empty, contains ``<final_answer``-style markers or any
    ``<...>`` placeholder, or is implausibly short."""
    v = value.strip()
    if not v:
        return True
    if "<final_answer" in v or "final_answer>" in v:
        return True
    if _PLACEHOLDER.search(v):
        return True
    if len(v) < 10:
        return True
    return False


def assistant(
    model: str,
    messages: list[dict[str, Any]],
    max_tokens: int = 2048,
    count_tokens: bool = False,
    verbose: bool = False,
    max_iterations: int = 10,
) -> tuple[str, list[dict[str, Any]]]:
    """Run the ReAct loop with credentials from the environment."""
    return assistant_with_config(
        model, messages, max_tokens, count_tokens, verbose, max_iterations, "", ""
    )


def assistant_with_config(
    model: str,
    messages: list[dict[str, Any]],
    max_tokens: int = 2048,
    count_tokens: bool = False,
    verbose: bool = False,
    max_iterations: int = 10,
    api_key: str = "",
    base_url: str = "",
) -> tuple[str, list[dict[str, Any]]]:
    """Run the ReAct loop; returns (final raw reply, full chat history).

    ``messages`` must hold the system prompt and the user instruction; the
    list is extended in place with every turn so callers can reconstruct the
    tool history afterwards (as the execute handler does).
    """
    stop = trace_func("agent.loop")
    # Span-tree root: reuse the caller's active trace (the execute handler
    # roots one on the HTTP request ID); a direct CLI/library call gets its
    # own request-scoped trace so llm_turn / tool_exec spans always land
    # somewhere retrievable.
    if obs.current_span() is not None:
        import contextlib

        tracer = contextlib.nullcontext()
    else:
        tracer = obs.trace_request(obs.new_request_id("agent"))
    try:
        with tracer:
            return _react_loop(
                model, messages, max_tokens, count_tokens, verbose,
                max_iterations, api_key, base_url,
            )
    finally:
        stop()


def _react_loop(
    model: str,
    chat_history: list[dict[str, Any]],
    max_tokens: int,
    count_tokens: bool,
    verbose: bool,
    max_iterations: int,
    api_key: str,
    base_url: str,
) -> tuple[str, list[dict[str, Any]]]:
    ps = get_perf_stats()
    client = ChatClient(api_key=api_key, base_url=base_url)
    tools = get_tools()
    # A completion budget >= the model's context window would leave zero room
    # for the prompt (and the constrictor would evict history to nothing).
    max_tokens = min(max_tokens, max(256, get_token_limits(model) // 2))

    # Against the in-tree engine, constrain decoding to the ToolPrompt JSON
    # schema on device — replies are valid by construction, so the repair
    # ladder below becomes dead code on this path (SURVEY.md §7 step 6).
    # Remote providers keep free-form output + repair (reference behavior).
    toolprompt_rf = None
    if (model or "").startswith("tpu://") or (base_url or "").startswith("tpu://"):
        from ..serving.constrained import TOOLPROMPT_SCHEMA

        toolprompt_rf = {
            "type": "json_schema",
            "json_schema": {"schema": TOOLPROMPT_SCHEMA},
        }

    def call(
        msgs: list[dict[str, Any]],
        response_format: dict[str, Any] | None = None,
    ) -> str:
        sendable = constrict_messages(msgs, model, max_tokens) if count_tokens else msgs
        obs.AGENT_ITERATIONS.inc()
        # The llm_turn span is the bridge into the engine: against the
        # in-process tpu:// provider the frontend sees this as the current
        # span and nests its generate/queue/prefill/decode children here.
        with ps.timer("agent.llm_turn"), obs.span("llm_turn"):
            return client.chat(
                model, max_tokens, sendable, response_format=response_format
            )

    # Conveyor tool overlap (agent/conveyor.py): when the turn decodes
    # under the ToolPrompt constraint in-process, stream it and launch the
    # tool the moment its argument fields close — the JSON tail decodes
    # while the subprocess already runs. The launch is validated against
    # the full parse below; any divergence cancels it and re-runs the
    # classic blocking path, so transcripts are byte-identical on vs off.
    use_conveyor = toolprompt_rf is not None and conveyor.enabled()

    def call_turn(
        msgs: list[dict[str, Any]],
    ) -> tuple[str, "conveyor.TurnConveyor | None"]:
        if not use_conveyor:
            return call(msgs, response_format=toolprompt_rf), None
        sendable = constrict_messages(msgs, model, max_tokens) if count_tokens else msgs
        obs.AGENT_ITERATIONS.inc()
        with ps.timer("agent.llm_turn"), obs.span("llm_turn"):
            turn = conveyor.TurnConveyor(
                tools, model=model, park_messages=sendable,
                schema=toolprompt_rf["json_schema"]["schema"],
            )
            try:
                streamed = conveyor.stream_constrained_turn(
                    model, max_tokens, sendable, toolprompt_rf,
                    turn.on_delta,
                )
            except BaseException:
                # The engine call failed; a speculative launch must not
                # outlive the turn it bet on.
                turn.abort()
                raise
            turn.finish_stream()
        return streamed, turn

    def consume_launch(
        turn: "conveyor.TurnConveyor", name: str
    ) -> str | None:
        """Collect an early launch's observation; None = launch errored
        (the caller falls back to the classic blocking relaunch)."""
        launch = turn.launch
        assert launch is not None
        t_wait = time.perf_counter()
        try:
            # The tool_exec span covers only the RESIDUAL wait — the
            # overlapped part of the tool's runtime was decode time, not
            # blocked time, and the goodput ledger sees it the same way.
            with ps.timer(f"agent.tool.{name}"), \
                    obs.span("tool_exec", tool=name):
                observation = launch.result()
        except Exception as e:  # noqa: BLE001 - incl. injected faults
            obs.TOOL_CALLS.inc(tool=name, outcome="error")
            turn.record_exit("error", str(e))
            if verbose:
                log.info("conveyor launch failed (%s); falling back", e)
            return None
        residual = time.perf_counter() - t_wait
        obs.attribution.record_goodput(residual, "tool_blocked")
        overlap = turn.overlap_s()
        obs.TOOL_OVERLAP_SECONDS.inc(overlap)
        obs.TOOL_CALLS.inc(tool=name, outcome="ok")
        turn.record_exit("ok", overlap_s=overlap)
        return observation

    reply, turn = call_turn(chat_history)
    chat_history.append({"role": "assistant", "content": reply})
    if verbose:
        log.info("initial reply: %s", reply[:500])

    try:
        prompt = ToolPrompt.from_json(reply)
    except ValueError:
        # Unparseable first reply: treat the raw text as the final answer.
        if turn is not None:
            turn.abort()
        return reply, chat_history

    iterations = 0
    while True:
        iterations += 1
        if iterations > max_iterations:
            log.warning("iteration cap %d reached", max_iterations)
            if turn is not None:
                turn.abort()
            return reply, chat_history

        if prompt.final_answer and not is_template_value(prompt.final_answer):
            if prompt.observation.strip():
                if turn is not None:
                    turn.abort()
                return reply, chat_history
            if verbose:
                log.info("final_answer offered without observation; continuing")

        name = prompt.action.name.strip()
        tool_input = prompt.action.input
        launch = turn.launch if turn is not None else None
        observation: str | None = None
        if name and name in tools and launch is not None:
            if launch.matches(name, tool_input):
                # The launched prefix IS the parsed call: collect the
                # overlapped execution (None = launch errored; the
                # classic block below relaunches inline).
                observation = consume_launch(turn, name)
            else:
                # Launched prefix ≠ final parse (the stream-side extract
                # and the repair-ladder parse disagreed): cancel the bet,
                # run the classic path. The flight ring records both
                # pairs — the cancelled early launch and the relaunch.
                turn.abort()
                launch = None
        elif launch is not None:
            # The parsed reply doesn't dispatch a registered tool at all
            # (final answer / unknown tool): abandon the speculation.
            turn.abort()
            launch = None
        if observation is not None:
            pass  # conveyor launch delivered the observation
        elif name and name in tools:
            if verbose:
                log.info("tool %s input=%r", name, tool_input[:200])
            # Tool-time parking (hierarchical KV tier): the subprocess the
            # tool is about to exec blocks this session for seconds; an
            # in-tree engine can copy the session's KV pages to host RAM
            # and free the HBM for queued prompts — the next turn restores
            # them instead of re-prefilling. No-op for remote providers
            # and engines without the offload tier. A conveyor turn
            # already parked at LAUNCH time — don't double-count.
            parked_tokens = 0
            if (model or "").startswith("tpu://") and not (
                turn is not None and turn.launch is not None
            ):
                try:
                    from ..serving.api import park_session

                    parked_tokens = park_session(model, chat_history)
                except Exception:  # noqa: BLE001 - parking is best-effort
                    parked_tokens = 0
            t_tool = time.perf_counter()
            # Tool ENTRY and EXIT are separate flight events (phase=
            # enter/exit): the exit carries duration + outcome, so a
            # timeline can bound the tool-blocked window exactly and
            # park/unpark pairs (parked_tokens on the enter) are
            # auditable against the restore that follows.
            _cur = obs.current_span()
            _rid = _cur.trace.request_id if _cur is not None else None
            enter_ev = {"tool": name, "phase": "enter", "request_id": _rid}
            if parked_tokens:
                enter_ev["parked_tokens"] = parked_tokens
            obs.flight.record("tool_exec", **enter_ev)

            def _tool_flight(outcome: str, error: str = "") -> None:
                dt = time.perf_counter() - t_tool
                ev = {
                    "tool": name, "phase": "exit", "outcome": outcome,
                    "duration_ms": round(dt * 1e3, 3),
                    "request_id": _rid,
                }
                if parked_tokens:
                    ev["parked_tokens"] = parked_tokens
                if error:
                    ev["error"] = error
                obs.flight.record("tool_exec", **ev)
                obs.attribution.record_goodput(dt, "tool_blocked")

            try:
                with ps.timer(f"agent.tool.{name}"), \
                        obs.span("tool_exec", tool=name):
                    faults.maybe_raise(
                        "tool.exec", ToolError,
                        "injected tool subprocess failure", tool=name,
                    )
                    faults.maybe_raise(
                        "tool.timeout", TimeoutError,
                        "injected tool subprocess timeout", tool=name,
                    )
                    observation = tools[name](tool_input)
                obs.TOOL_CALLS.inc(tool=name, outcome="ok")
                _tool_flight("ok")
            except ToolError as e:
                obs.TOOL_CALLS.inc(tool=name, outcome="error")
                _tool_flight("error", str(e))
                observation = (
                    f"Tool {name} failed with error {e}. "
                    "Considering refine the inputs for the tool."
                )
            except Exception as e:  # noqa: BLE001 - tool bugs become observations
                obs.TOOL_CALLS.inc(tool=name, outcome="error")
                _tool_flight("error", str(e))
                observation = (
                    f"Tool {name} failed with error {e}. "
                    "Considering refine the inputs for the tool."
                )
        elif name:
            observation = (
                f"Tool {name} is not available. Considering switch to other tools."
            )
        else:
            observation = (
                "No action was specified. Specify a tool action or give the "
                "final_answer."
            )

        prompt.observation = constrict_prompt(observation, OBSERVATION_TOKEN_LIMIT)
        chat_history.append({"role": "user", "content": prompt.to_json()})

        reply, turn = call_turn(chat_history)
        chat_history.append({"role": "assistant", "content": reply})
        if verbose:
            log.info("iteration %d reply: %s", iterations, reply[:500])

        try:
            prompt = ToolPrompt.from_json(reply)
        except ValueError:
            # Mid-loop unparseable reply: one summarization turn, then a
            # best-effort final_answer extraction.
            if turn is not None:
                turn.abort()
            chat_history.append({"role": "user", "content": SUMMARIZE_PROMPT})
            reply = call(chat_history)
            chat_history.append({"role": "assistant", "content": reply})
            final = extract_field(reply, "final_answer") or reply
            return final, chat_history
