"""Native function-calling agent loop.

The reference has a second, parallel LLM-calling path built on the external
swarm-go library (pkg/workflows/swarm.go): tools exposed as typed OpenAI
``tools``/``tool_calls`` functions rather than the hand-rolled ReAct JSON.
This module is the in-tree equivalent: a loop that sends tool schemas, lets
the model emit ``tool_calls``, executes them, and feeds ``role: tool``
results back until the model answers in plain text.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable

from ..llm.client import ChatClient
from ..tools import ToolError
from ..utils.logger import get_logger

log = get_logger("funcall")


@dataclass
class AgentFunction:
    """A typed tool exposed through the OpenAI tools schema
    (counterpart of swarm.NewAgentFunction, reference swarm.go:14-77)."""

    name: str
    description: str
    parameters: dict[str, Any]  # JSON schema for the arguments object
    fn: Callable[..., str] = field(repr=False, default=lambda: "")

    def schema(self) -> dict[str, Any]:
        return {
            "type": "function",
            "function": {
                "name": self.name,
                "description": self.description,
                "parameters": self.parameters,
            },
        }

    def invoke(self, arguments: str) -> str:
        try:
            kwargs = json.loads(arguments) if arguments.strip() else {}
        except json.JSONDecodeError as e:
            return f"invalid function arguments: {e}"
        if not isinstance(kwargs, dict):
            return "function arguments must be a JSON object"
        try:
            return self.fn(**kwargs)
        except ToolError as e:
            return f"Tool {self.name} failed with error {e}."
        except TypeError as e:
            return f"Bad arguments for {self.name}: {e}"


def kubectl_function() -> AgentFunction:
    from ..tools.kubectl import kubectl

    return AgentFunction(
        name="kubectl",
        description=(
            "Run a kubectl command against the current cluster. Provide the "
            "full command line; pipes are allowed. Prefer narrow queries "
            "(jsonpath/custom-columns/--no-headers) over full -o json/yaml dumps."
        ),
        parameters={
            "type": "object",
            "properties": {
                "command": {
                    "type": "string",
                    "description": "The kubectl command line to execute",
                }
            },
            "required": ["command"],
        },
        fn=lambda command: kubectl(command),
    )


def python_function() -> AgentFunction:
    from ..tools.python_tool import python_repl

    return AgentFunction(
        name="python",
        description="Execute a Python 3 script; its stdout is returned.",
        parameters={
            "type": "object",
            "properties": {
                "script": {"type": "string", "description": "Python 3 source"}
            },
            "required": ["script"],
        },
        fn=lambda script: python_repl(script),
    )


def trivy_function() -> AgentFunction:
    from ..tools.trivy import trivy

    return AgentFunction(
        name="trivy",
        description="Scan a container image for vulnerabilities with trivy.",
        parameters={
            "type": "object",
            "properties": {
                "image": {"type": "string", "description": "Image reference"}
            },
            "required": ["image"],
        },
        fn=lambda image: trivy(image),
    )


def run_function_agent(
    client: ChatClient,
    model: str,
    instructions: str,
    user_input: str,
    functions: list[AgentFunction],
    max_turns: int = 30,
    max_tokens: int = 2048,
) -> tuple[str, list[dict[str, Any]]]:
    """Run the tool_calls loop; returns (final text, chat history)."""
    messages: list[dict[str, Any]] = [
        {"role": "system", "content": instructions},
        {"role": "user", "content": user_input},
    ]
    by_name = {f.name: f for f in functions}
    tools = [f.schema() for f in functions] or None
    for _ in range(max_turns):
        resp = client.chat_completion(
            model, messages, max_tokens=max_tokens, tools=tools
        )
        choices = resp.get("choices") or []
        if not choices:
            return "", messages
        msg = choices[0].get("message", {})
        messages.append(msg)
        tool_calls = msg.get("tool_calls") or []
        if not tool_calls:
            return msg.get("content") or "", messages
        for tc in tool_calls:
            fn_name = tc.get("function", {}).get("name", "")
            args = tc.get("function", {}).get("arguments", "")
            func = by_name.get(fn_name)
            if func is None:
                result = f"Tool {fn_name} is not available."
            else:
                log.info("tool_call %s(%s)", fn_name, args[:200])
                result = func.invoke(args)
            messages.append(
                {
                    "role": "tool",
                    "tool_call_id": tc.get("id", ""),
                    "content": result,
                }
            )
    return "(max turns reached)", messages
