from .react import assistant, assistant_with_config
from .funcall import AgentFunction, run_function_agent

__all__ = ["assistant", "assistant_with_config", "AgentFunction", "run_function_agent"]
