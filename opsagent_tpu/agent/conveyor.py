"""Conveyor tool overlap: launch tools mid-decode from the constrained
stream.

The ReAct loop's turn latency used to be decode time PLUS tool time,
serially: the whole ToolPrompt JSON decodes, then ``subprocess.run``
blocks. But the serving path decodes under the ToolPrompt FSM
(serving/constrained.py), which pins the JSON's *shape*: properties
arrive in schema declaration order (``action.name`` before
``action.input``, both before ``observation``/``final_answer``), strings
cannot contain a raw ``"`` or newline (only escapes), and whitespace is
bounded. So the instant the bytes closing ``action.input`` stream out,
the tool call is fully determined while the JSON *tail* is still
decoding — that tail is the overlap window this module exploits.

Three pieces:

- ``StreamParser`` — an incremental JSON event parser fed by decode
  deltas. Single-pass with no backtracking *because* of the DFA
  guarantees above. Emits ``tool_name_closed`` / ``arg_closed(field)`` /
  ``call_closed`` events.

- ``ToolLaunch`` — one early tool execution on the async ``ToolProcess``
  executor (tools/proc.py): a worker thread runs the registry callable
  inside a ``proc.cancel_scope`` so ``cancel()`` can group-kill any
  subprocess the callable spawned. The ``tool.exec`` / ``tool.timeout``
  fault points fire inside the worker, exactly where the classic
  blocking path fires them.

- ``TurnConveyor`` — the per-LLM-turn driver the ReAct loop feeds:
  watches parser events, and at launch readiness (known tool name + the
  wire fields its LAUNCH_FIELDS ride in, see tools.wire_fields_for)
  parks the session's KV (moved here from tool *entry*: pages free while
  the tail still decodes), records the ``tool_exec`` enter flight event
  stamped ``launch_offset_ms``, and starts the ``ToolLaunch``.

Correctness contract: the launch is a *prefix bet*. On ``call_closed``
the loop validates the fully-parsed call against the launched prefix;
mismatch (or a launch error) cancels the early process and falls back to
the classic blocking path, so transcripts are byte-identical conveyor-on
vs conveyor-off.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from .. import obs
from ..llm.client import LLMError
from ..serving import faults
from ..tools import Tool, ToolError, wire_fields_for
from ..utils.logger import get_logger
from ..utils.perf import get_perf_stats

log = get_logger("agent.conveyor")


def enabled() -> bool:
    """Conveyor launches are on unless OPSAGENT_CONVEYOR=0 (the bench
    A/B reads this per turn, so a phase flip needs no re-import)."""
    import os

    return os.environ.get("OPSAGENT_CONVEYOR", "1") != "0"


# -- incremental JSON event parser -----------------------------------------


@dataclass
class Event:
    kind: str  # tool_name_closed | arg_closed | call_closed | field_closed
    path: tuple[str, ...] = ()
    field: str = ""
    value: Any = None


@dataclass
class _Frame:
    kind: str  # "obj" | "arr"
    key: str | None = None
    expect: str = "key"  # obj: key|colon|value|comma ; arr: value|comma
    scalar: list[str] = field(default_factory=list)


def _call_path(schema: dict | None) -> tuple[str, ...]:
    """Locate the nested tool-call object in the schema: the property
    whose value is an object with a ``name`` property (``action`` in
    TOOLPROMPT_SCHEMA). The same declaration order the DFA compiles
    (schema_to_regex emits properties in order) guarantees its fields
    close in that order on the stream."""
    for key, sub in ((schema or {}).get("properties") or {}).items():
        if isinstance(sub, dict) and "name" in (sub.get("properties") or {}):
            return (key,)
    return ("action",)


class StreamParser:
    """Incremental, split-anywhere JSON parser over the constrained
    decode stream. ``feed`` accepts deltas of any granularity (a token's
    detokenization can split escapes and multi-byte text arbitrarily)
    and returns the events the new bytes completed."""

    def __init__(self, schema: dict | None = None) -> None:
        self._stack: list[_Frame] = []
        self._str: list[str] | None = None
        self._str_role = "value"
        self._esc = False
        self._closed = False
        self._call_path = _call_path(schema)

    def feed(self, text: str) -> list[Event]:
        events: list[Event] = []
        for ch in text:
            self._step(ch, events)
        return events

    # -- internals ---------------------------------------------------------

    def _path(self) -> tuple[str, ...]:
        return tuple(f.key or "" for f in self._stack)

    def _step(self, ch: str, events: list[Event]) -> None:
        if self._closed:
            return
        if self._str is not None:
            if self._esc:
                self._str.append(ch)
                self._esc = False
                return
            if ch == "\\":
                self._str.append(ch)
                self._esc = True
                return
            if ch == '"':
                raw = "".join(self._str)
                self._str = None
                try:
                    value = json.loads(f'"{raw}"')
                except json.JSONDecodeError:
                    value = raw
                self._close_string(value, events)
                return
            self._str.append(ch)
            return

        frame = self._stack[-1] if self._stack else None
        if frame is not None and frame.scalar:
            # Non-string scalar (number/true/false/null) in flight: any
            # structural delimiter closes it.
            if ch not in ",}]" and not ch.isspace():
                frame.scalar.append(ch)
                return
            raw = "".join(frame.scalar)
            frame.scalar.clear()
            try:
                value = json.loads(raw)
            except json.JSONDecodeError:
                value = raw
            self._emit_value(value, events)
            frame.expect = "comma"
            # fall through: ch still needs structural handling

        if ch.isspace():
            return
        if ch == '"':
            self._str = []
            self._esc = False
            self._str_role = (
                "key"
                if frame is not None
                and frame.kind == "obj"
                and frame.expect == "key"
                else "value"
            )
            return
        if ch == "{":
            self._stack.append(_Frame("obj", expect="key"))
            return
        if ch == "[":
            self._stack.append(_Frame("arr", expect="value"))
            return
        if ch in "}]":
            if not self._stack:
                return
            closed = self._stack.pop()
            if not self._stack:
                self._closed = True
                events.append(Event("call_closed"))
                return
            parent = self._stack[-1]
            if closed.kind == "obj" and self._path() == self._call_path:
                # The tool-call object itself closed (all args final).
                events.append(Event("field_closed", self._path()))
            parent.expect = "comma"
            return
        if ch == ":":
            if frame is not None:
                frame.expect = "value"
            return
        if ch == ",":
            if frame is not None:
                frame.expect = "key" if frame.kind == "obj" else "value"
            return
        if frame is not None and frame.expect == "value":
            frame.scalar.append(ch)

    def _close_string(self, value: str, events: list[Event]) -> None:
        frame = self._stack[-1] if self._stack else None
        if frame is None:
            return
        if frame.kind == "obj" and self._str_role == "key":
            frame.key = value
            frame.expect = "colon"
            return
        self._emit_value(value, events)
        frame.expect = "comma"

    def _emit_value(self, value: Any, events: list[Event]) -> None:
        path = self._path()
        call = self._call_path
        if path == call + ("name",):
            events.append(Event("tool_name_closed", path, "name", value))
        elif len(path) == len(call) + 1 and path[: len(call)] == call:
            events.append(Event("arg_closed", path, path[-1], value))
        else:
            events.append(Event("field_closed", path, path[-1], value))


# -- async tool launch -----------------------------------------------------


class ToolLaunch:
    """One conveyor tool execution on a worker thread.

    The worker wraps the registry callable in a ``proc.cancel_scope`` so
    subprocesses it spawns (via tools/proc.py) are killable from the
    loop thread on a mismatch-cancel. The ``tool.exec``/``tool.timeout``
    fault points fire inside the worker — the same injection surface the
    classic blocking path has, now covering the async executor.
    """

    def __init__(self, name: str, tool_input: str, fn: Tool) -> None:
        from ..tools import proc

        self.name = name
        self.input = tool_input
        self.t_launch = time.perf_counter()
        self.t_done: float | None = None
        self.cancelled = False
        self._procs: list[Any] = []
        self._proc_mod = proc
        self._result: str | None = None
        self._error: BaseException | None = None
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(fn,), daemon=True,
            name=f"conveyor-{name}",
        )
        self._thread.start()

    def _run(self, fn: Tool) -> None:
        try:
            with self._proc_mod.cancel_scope(self._procs):
                faults.maybe_raise(
                    "tool.exec", ToolError,
                    "injected tool subprocess failure", tool=self.name,
                )
                faults.maybe_raise(
                    "tool.timeout", TimeoutError,
                    "injected tool subprocess timeout", tool=self.name,
                )
                self._result = fn(self.input)
        except BaseException as e:  # noqa: BLE001 - delivered via result()
            self._error = e
        finally:
            self.t_done = time.perf_counter()
            self._done.set()

    def matches(self, name: str, tool_input: str) -> bool:
        return self.name == name and self.input == tool_input

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def error(self) -> BaseException | None:
        return self._error if self._done.is_set() else None

    def result(self) -> str:
        """Block for the worker; re-raise its failure, else the
        observation."""
        self._done.wait()
        if self._error is not None:
            raise self._error
        return self._result or ""

    def cancel(self) -> None:
        """Mismatch/abandon: group-kill every subprocess the callable
        spawned; the worker unwinds on its own."""
        self.cancelled = True
        for p in list(self._procs):
            try:
                p.cancel()
            except Exception:  # noqa: BLE001 - best-effort reaping
                pass


# -- per-turn driver -------------------------------------------------------


class TurnConveyor:
    """Watches one LLM turn's decode stream and launches the tool at
    readiness-close. Owned by the ReAct loop; ``on_delta`` runs on the
    stream-consuming thread between chunk pulls."""

    def __init__(
        self,
        tools: dict[str, Tool],
        model: str = "",
        park_messages: list[dict[str, Any]] | None = None,
        schema: dict | None = None,
    ) -> None:
        self.parser = StreamParser(schema)
        self.tools = tools
        self.model = model
        self.park_messages = park_messages
        self.launch: ToolLaunch | None = None
        self.parked_tokens = 0
        self.request_id = None
        self.t0 = time.perf_counter()
        self.t_stream_end: float | None = None
        self._name: str | None = None
        self._fields: dict[str, str] = {}
        cur = obs.current_span()
        if cur is not None:
            self.request_id = cur.trace.request_id

    def on_delta(self, text: str) -> None:
        for ev in self.parser.feed(text):
            if ev.kind == "tool_name_closed":
                self._name = str(ev.value)
            elif ev.kind == "arg_closed" and isinstance(ev.value, str):
                self._fields[ev.field] = ev.value
            self._maybe_launch()

    def finish_stream(self) -> None:
        self.t_stream_end = time.perf_counter()
        if self.launch is not None:
            obs.TOOL_LAUNCH_LEAD_SECONDS.observe(
                max(0.0, self.t_stream_end - self.launch.t_launch),
                tool=self.launch.name,
            )

    def overlap_s(self) -> float:
        """Seconds the tool ran concurrently with decode: launch →
        min(tool end, stream end). Callable once both ends are known."""
        if self.launch is None:
            return 0.0
        t_end = self.t_stream_end or time.perf_counter()
        t_done = self.launch.t_done or time.perf_counter()
        return max(0.0, min(t_done, t_end) - self.launch.t_launch)

    def abort(self, outcome: str = "cancelled") -> None:
        """Cancel an in-flight launch and close its flight pair."""
        if self.launch is None:
            return
        self.launch.cancel()
        self.record_exit(outcome)

    def record_exit(
        self, outcome: str, error: str = "", overlap_s: float | None = None
    ) -> None:
        """Close the launch's flight pair (the enter was recorded at
        launch time, stamped launch_offset_ms)."""
        assert self.launch is not None
        dt = (self.launch.t_done or time.perf_counter()) - self.launch.t_launch
        ev: dict[str, Any] = {
            "tool": self.launch.name, "phase": "exit", "outcome": outcome,
            "duration_ms": round(dt * 1e3, 3), "conveyor": True,
            "request_id": self.request_id,
        }
        if overlap_s is not None:
            ev["overlap_ms"] = round(overlap_s * 1e3, 3)
        if self.parked_tokens:
            ev["parked_tokens"] = self.parked_tokens
        if error:
            ev["error"] = error
        obs.flight.record("tool_exec", **ev)

    def _maybe_launch(self) -> None:
        if self.launch is not None or not self._name:
            return
        name = self._name
        if name not in self.tools:
            return
        if not wire_fields_for(name) <= set(self._fields):
            return
        tool_input = self._fields.get("input", "")
        # Tool-time parking moves from tool ENTRY to tool LAUNCH: the
        # divergent prior-generation subtree frees while the JSON tail is
        # still decoding (the live turn's own chain stays — its pages are
        # refcounted by the running sequence).
        if (self.model or "").startswith("tpu://") and self.park_messages:
            try:
                from ..serving.api import park_session

                self.parked_tokens = park_session(
                    self.model, self.park_messages
                )
            except Exception:  # noqa: BLE001 - parking is best-effort
                self.parked_tokens = 0
        launch_offset_ms = round((time.perf_counter() - self.t0) * 1e3, 3)
        enter_ev: dict[str, Any] = {
            "tool": name, "phase": "enter", "request_id": self.request_id,
            "launch_offset_ms": launch_offset_ms, "conveyor": True,
        }
        if self.parked_tokens:
            enter_ev["parked_tokens"] = self.parked_tokens
        obs.flight.record("tool_exec", **enter_ev)
        obs.TOOL_EARLY_LAUNCHES.inc(tool=name)
        self.launch = ToolLaunch(name, tool_input, self.tools[name])


# -- in-process constrained streaming --------------------------------------


def stream_constrained_turn(
    model: str,
    max_tokens: int,
    messages: list[dict[str, Any]],
    response_format: dict[str, Any] | None,
    on_delta: Callable[[str], None],
) -> str:
    """Drive the in-process tpu:// engine's SSE stream, feeding content
    deltas to ``on_delta`` as they arrive; returns the full reply text.

    Builds the SAME request body ChatClient.chat_completion sends on the
    non-stream path (greedy temperature, identical fields), so the
    streamed text is byte-identical to what the blocking call returns —
    the conveyor-off transcript equality rests on this.
    """
    from ..serving.api import get_stack

    target = model.split("://", 1)[-1]
    body: dict[str, Any] = {
        "model": target,
        "messages": messages,
        "max_tokens": max_tokens,
        "temperature": 1e-45,
    }
    if response_format:
        body["response_format"] = response_format
    parts: list[str] = []
    try:
        stack = get_stack(target)
        with get_perf_stats().timer("llm.chat.tpu"):
            for chunk in stack.chat_completion_stream(body):
                err = chunk.get("error")
                if err:
                    raise LLMError(
                        f"tpu engine error: {err.get('message', err)}"
                    )
                choices = chunk.get("choices") or []
                if not choices:
                    continue
                delta = choices[0].get("delta") or {}
                piece = delta.get("content")
                if piece:
                    parts.append(piece)
                    on_delta(piece)
    except LLMError:
        raise
    except Exception as e:  # noqa: BLE001 - mirror _tpu_provider_factory
        raise LLMError(f"tpu engine error: {e}") from e
    return "".join(parts)
