"""Performance statistics registry.

Named timers and metrics with min/max/avg/p95/p99 aggregation, exposed over
``GET /api/perf/stats`` and printed by verbose CLI runs.

Capability parity with the reference's pkg/utils/perf.go (singleton perf.go:33,
timers perf.go:64-139, aggregation perf.go:168-210, HTTP accessors
perf.go:296-335). On the TPU side this registry also carries the serving
engine's first-class gauges (tokens/sec/chip, TTFT; SURVEY.md section 5).
The richer Prometheus-facing instruments live in ``opsagent_tpu.obs``;
this registry is bridged into that exposition (obs/metrics.py) so
``/metrics`` and ``/api/perf/stats`` tell one story.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Iterator

# Per-series sample window. Sustained traffic used to grow every series
# without bound (the old list held every observation forever — at ~1 kB/s
# of floats per busy series that is an OOM on a long-lived server); the
# window bounds memory while count/avg/min/max stay exact via running
# aggregates. Percentiles are computed over the window, i.e. they are
# RECENT percentiles — the more useful flavor for a serving dashboard
# anyway (a p99 dominated by hour-old warmup samples is noise).
SERIES_WINDOW = 4096


class _Series:
    __slots__ = ("values", "unit", "count", "total", "vmin", "vmax")

    def __init__(self, unit: str = "ms", window: int = SERIES_WINDOW) -> None:
        self.values: deque[float] = deque(maxlen=window)
        self.unit = unit
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")

    def add(self, value: float) -> None:
        self.values.append(value)
        self.count += 1
        self.total += value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value

    def summary(self) -> dict[str, Any]:
        if self.count == 0:
            return {"count": 0, "unit": self.unit}
        vs = sorted(self.values)
        n = len(vs)

        def pct(p: float) -> float:
            idx = min(n - 1, max(0, int(round(p * (n - 1)))))
            return vs[idx]

        return {
            "count": self.count,
            "unit": self.unit,
            "min": self.vmin,
            "max": self.vmax,
            "avg": self.total / self.count,
            "p50": pct(0.50),
            "p95": pct(0.95),
            "p99": pct(0.99),
        }


class PerfStats:
    """Thread-safe registry of named timers, metrics and gauges."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self._gauges: dict[str, float] = {}
        # name -> stack of start times: overlapping same-name timers from
        # concurrent requests pair LIFO instead of clobbering a single slot.
        self._active: dict[str, list[float]] = {}
        self.enabled = True

    # -- timers ------------------------------------------------------------
    # One recording path for every timer flavor: start/stop pairs, the
    # ``timer`` context manager, and ``trace_func`` all end in
    # ``record_metric(name, elapsed_ms, "ms")``, so the aggregation,
    # enable gating, and units cannot drift between entry points.
    def start_timer(self, name: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._active.setdefault(name, []).append(time.perf_counter())

    def stop_timer(self, name: str) -> float:
        if not self.enabled:
            return 0.0
        now = time.perf_counter()
        with self._lock:
            stack = self._active.get(name)
            if not stack:
                return 0.0
            t0 = stack.pop()
        ms = (now - t0) * 1e3
        self.record_metric(name, ms, "ms")
        return ms

    @contextmanager
    def timer(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record_metric(name, (time.perf_counter() - t0) * 1e3, "ms")

    # -- metrics / gauges --------------------------------------------------
    def record_metric(self, name: str, value: float, unit: str = "") -> None:
        if not self.enabled:
            return
        with self._lock:
            self._series.setdefault(name, _Series(unit)).add(value)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    # -- accessors ---------------------------------------------------------
    def get_stats(self) -> dict[str, Any]:
        with self._lock:
            out: dict[str, Any] = {
                name: s.summary() for name, s in self._series.items()
            }
            if self._gauges:
                out["gauges"] = dict(self._gauges)
            return out

    def reset(self) -> None:
        """Clear aggregated series and gauges. In-flight ``start_timer``
        stacks are deliberately KEPT: a reset landing mid-request used to
        orphan the open timer (its ``stop_timer`` found an empty stack and
        silently recorded nothing); now the pair still completes and lands
        in the post-reset window."""
        with self._lock:
            self._series.clear()
            self._gauges.clear()

    def format_table(self) -> str:
        stats = self.get_stats()
        gauges = stats.pop("gauges", {})
        lines = [
            f"{'operation':<44} {'count':>6} {'avg':>9} {'p95':>9} {'p99':>9} {'max':>9} unit"
        ]
        for name in sorted(stats):
            s = stats[name]
            if s.get("count", 0) == 0:
                continue
            lines.append(
                f"{name:<44} {s['count']:>6} {s['avg']:>9.2f} {s['p95']:>9.2f} "
                f"{s['p99']:>9.2f} {s['max']:>9.2f} {s['unit']}"
            )
        for name in sorted(gauges):
            lines.append(f"{name:<44} {'gauge':>6} {gauges[name]:>9.2f}")
        return "\n".join(lines)


_singleton: PerfStats | None = None
_singleton_lock = threading.Lock()


def get_perf_stats() -> PerfStats:
    global _singleton
    if _singleton is None:
        with _singleton_lock:
            if _singleton is None:
                _singleton = PerfStats()
    return _singleton


def trace_func(name: str) -> Callable[[], None]:
    """Start a timer and return the stopper; mirrors ``defer TraceFunc()()``
    instrumentation style (reference pkg/utils/perf.go:288-293). The start
    time lives in the closure, so concurrent traces of the same name are
    each timed correctly."""
    ps = get_perf_stats()
    t0 = time.perf_counter()

    def stop() -> None:
        ps.record_metric(name, (time.perf_counter() - t0) * 1e3, "ms")

    return stop
