"""Layered configuration: YAML file -> defaults, plus env overrides.

Capability parity with the reference's pkg/utils/config.go (viper with search
path ``configs/`` then ``.``, defaults for jwt/server/log/perf when the file is
missing, config.go:21-32) and configs/config.yaml.
"""

from __future__ import annotations

import copy
import os
import threading
from typing import Any

try:
    import yaml
except ImportError:  # pragma: no cover - pyyaml is a baked dependency of flax
    yaml = None

DEFAULTS: dict[str, Any] = {
    "jwt": {"key": "opsagent-default-jwt-key"},
    "server": {"port": 8080, "host": "0.0.0.0"},
    "log": {
        "level": "info",
        "format": "json",
        "output": "stdout",
        "file": "logs/opsagent.log",
        "max_size_mb": 10,
        "max_backups": 10,
        "max_age_days": 7,
        "compress": True,
    },
    "perf": {"enabled": True},
    "serving": {
        "model": "",
        "checkpoint": "",
        "tokenizer": "",
        "port": 8000,
        "page_size": 16,
        "max_pages": 2048,
        "max_batch_size": 32,
        "prefill_buckets": [128, 512, 2048, 8192],
        "decode_buckets": [1, 8, 32],
    },
}

_lock = threading.Lock()
_config: dict[str, Any] | None = None


def _deep_merge(base: dict[str, Any], over: dict[str, Any]) -> dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def load_config(path: str | None = None) -> dict[str, Any]:
    """Load config from ``path``, or search ``configs/config.yaml`` then
    ``./config.yaml``; missing file yields pure defaults."""
    global _config
    candidates = (
        [path]
        if path
        else [
            os.path.join("configs", "config.yaml"),
            "config.yaml",
        ]
    )
    loaded: dict[str, Any] = {}
    for cand in candidates:
        if cand and os.path.isfile(cand) and yaml is not None:
            with open(cand, "r", encoding="utf-8") as f:
                data = yaml.safe_load(f) or {}
            if isinstance(data, dict):
                loaded = data
            break
    cfg = _deep_merge(DEFAULTS, loaded)
    with _lock:
        _config = cfg
    return copy.deepcopy(cfg)


def get_config() -> dict[str, Any]:
    with _lock:
        if _config is None:
            pass
        else:
            return copy.deepcopy(_config)
    return load_config()


def reset_config() -> None:
    """Test helper."""
    global _config
    with _lock:
        _config = None
