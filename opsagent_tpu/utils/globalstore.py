"""Process-wide key/value store bridging config, CLI flags and handlers.

Capability parity with the reference's pkg/utils/global.go:15-27 (an
RWMutex-guarded map holding jwtKey / showThought / logger singletons).
"""

from __future__ import annotations

import threading
from typing import Any

_lock = threading.RLock()
_store: dict[str, Any] = {}


def set_global(key: str, value: Any) -> None:
    with _lock:
        _store[key] = value


def get_global(key: str, default: Any = None) -> Any:
    with _lock:
        return _store.get(key, default)


def delete_global(key: str) -> None:
    with _lock:
        _store.pop(key, None)


def clear_globals() -> None:
    """Test helper: reset the store."""
    with _lock:
        _store.clear()
