"""Logging: JSON file logs with size rotation + colored console output.

Capability parity with the reference's pkg/utils/logger.go (zap + lumberjack:
10MB/10 backups/7 days rotation logger.go:53-67, JSON file core + colored
console core logger.go:149-173, package-level helpers logger.go:199-221),
built on the stdlib ``logging`` package.
"""

from __future__ import annotations

import json
import logging
import logging.handlers
import os
import sys
import threading
import time
from typing import Any

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_COLORS = {
    logging.DEBUG: "\033[36m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[35m",
}
_RESET = "\033[0m"


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry: dict[str, Any] = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(record.created)),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            entry.update(extra)
        return json.dumps(entry, ensure_ascii=False)


class ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        color = _COLORS.get(record.levelno, "")
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        base = f"{ts} {color}{record.levelname:<5}{_RESET} {record.getMessage()}"
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


_lock = threading.Lock()
_initialized = False


class DailyRotatingFileHandler(logging.handlers.RotatingFileHandler):
    """Size rotation WITHIN a day plus daily filename rotation and
    age-based retention, matching the reference's lumberjack setup
    (10MB/10 backups/7-day MaxAge/compress, logger.go:53-67) combined
    with its daily filename reset (logger.go:70-98: the log file is
    reopened under a new date-stamped name when the day changes).

    ``logs/opsagent.log`` becomes ``logs/opsagent-YYYY-MM-DD.log``; when
    the calendar date changes the handler switches to the new day's file
    and prunes any log artifacts older than ``retention_days``. Rotated
    same-day backups are gzip-compressed when ``compress`` is set
    (lumberjack Compress, logger.go:66)."""

    def __init__(
        self,
        file_path: str,
        max_bytes: int = 10 * 1024 * 1024,
        backup_count: int = 10,
        retention_days: int = 7,
        compress: bool = True,
    ):
        self._base = file_path
        self._retention = retention_days
        self._day = time.strftime("%Y-%m-%d")
        super().__init__(
            self._dated(), maxBytes=max_bytes, backupCount=backup_count,
            delay=True,
        )
        if compress:
            # The stdlib namer/rotator hooks keep the .N.gz names
            # consistent through the backup shift loop (renaming the
            # backups out-of-band instead would leave doRollover's shift
            # finding nothing, silently dropping all but one backup).
            self.namer = lambda name: name + ".gz"
            self.rotator = self._gzip_rotate
        # Enforce retention at startup too: short-lived processes (every
        # CLI invocation) never cross midnight in-process, so rollover
        # alone would never prune.
        self.prune()

    def _dated(self) -> str:
        root, ext = os.path.splitext(self._base)
        return f"{root}-{self._day}{ext}"

    def shouldRollover(self, record: logging.LogRecord) -> bool:  # noqa: N802
        if time.strftime("%Y-%m-%d") != self._day:
            return True
        return bool(super().shouldRollover(record))

    def doRollover(self) -> None:  # noqa: N802
        today = time.strftime("%Y-%m-%d")
        if today != self._day:
            # Day changed: reopen under the new date-stamped name (no
            # backup shuffle — each day keeps its own file) and prune.
            if self.stream:
                self.stream.close()
                self.stream = None
            self._day = today
            self.baseFilename = os.path.abspath(self._dated())
            self.prune()
            return
        super().doRollover()

    def prune(self) -> None:
        """Delete log artifacts older than retention_days (lumberjack
        MaxAge equivalent; <= 0 means never expire, as MaxAge=0 does).

        Only files THIS handler writes are eligible: the date-stamped
        daily file plus its .N size-rollover / .gz compression suffixes.
        A bare prefix glob would also match unrelated same-prefix logs
        (e.g. opsagent-http.log next to opsagent.log) and delete another
        subsystem's data once it aged past retention. listdir+regex
        rather than glob: a log dir containing glob metacharacters
        ("logs[prod]/") would silently match nothing and disable
        retention."""
        import re

        if self._retention <= 0:
            return
        root, ext = os.path.splitext(self._base)
        own = re.compile(
            re.escape(os.path.basename(root))
            + r"-\d{4}-\d{2}-\d{2}"
            + re.escape(ext)
            + r"(\.\d+)?(\.gz)?$"
        )
        cutoff = time.time() - self._retention * 86400.0
        logdir = os.path.dirname(self._base) or "."
        try:
            entries = os.listdir(logdir)
        except OSError:
            return
        for fname in entries:
            if not own.fullmatch(fname):
                continue
            p = os.path.join(logdir, fname)
            try:
                if os.path.getmtime(p) < cutoff:
                    os.remove(p)
            except OSError:  # racing writers / already gone
                pass

    @staticmethod
    def _gzip_rotate(source: str, dest: str) -> None:
        import gzip
        import shutil

        try:
            with open(source, "rb") as src, gzip.open(dest, "wb") as dst:
                shutil.copyfileobj(src, dst)
            os.remove(source)
        except OSError:  # fall back to a plain rename
            try:
                # A partially written .gz must not enter the backup shift
                # chain as a corrupt artifact.
                os.remove(dest)
            except OSError:
                pass
            if os.path.exists(source):
                os.replace(source, dest.removesuffix(".gz"))


def init_logger(
    level: str = "info",
    fmt: str = "json",
    output: str = "stdout",
    file_path: str = "logs/opsagent.log",
    max_size_mb: int = 10,
    max_backups: int = 10,
    retention_days: int = 7,
    compress: bool = True,
) -> logging.Logger:
    """Initialize the root 'opsagent' logger: rotating JSON file and/or
    colored console, mirroring the reference's tee of both cores."""
    global _initialized
    logger = logging.getLogger("opsagent")
    with _lock:
        logger.setLevel(_LEVELS.get(level.lower(), logging.INFO))
        logger.handlers.clear()
        logger.propagate = False
        if output in ("stdout", "both"):
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(
                JSONFormatter() if fmt == "json" and output != "both" else ColorFormatter()
            )
            logger.addHandler(h)
        if output in ("file", "both"):
            os.makedirs(os.path.dirname(file_path) or ".", exist_ok=True)
            fh = DailyRotatingFileHandler(
                file_path,
                max_bytes=max_size_mb * 1024 * 1024,
                backup_count=max_backups,
                retention_days=retention_days,
                compress=compress,
            )
            fh.setFormatter(JSONFormatter())
            logger.addHandler(fh)
        _initialized = True
    return logger


def get_logger(name: str = "") -> logging.Logger:
    if not _initialized:
        init_logger()
    if name:
        return logging.getLogger("opsagent").getChild(name)
    return logging.getLogger("opsagent")
