"""Logging: JSON file logs with size rotation + colored console output.

Capability parity with the reference's pkg/utils/logger.go (zap + lumberjack:
10MB/10 backups/7 days rotation logger.go:53-67, JSON file core + colored
console core logger.go:149-173, package-level helpers logger.go:199-221),
built on the stdlib ``logging`` package.
"""

from __future__ import annotations

import json
import logging
import logging.handlers
import os
import sys
import threading
import time
from typing import Any

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

_COLORS = {
    logging.DEBUG: "\033[36m",
    logging.INFO: "\033[32m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[35m",
}
_RESET = "\033[0m"


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry: dict[str, Any] = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(record.created)),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            entry.update(extra)
        return json.dumps(entry, ensure_ascii=False)


class ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        color = _COLORS.get(record.levelno, "")
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        base = f"{ts} {color}{record.levelname:<5}{_RESET} {record.getMessage()}"
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


_lock = threading.Lock()
_initialized = False


def init_logger(
    level: str = "info",
    fmt: str = "json",
    output: str = "stdout",
    file_path: str = "logs/opsagent.log",
    max_size_mb: int = 10,
    max_backups: int = 10,
) -> logging.Logger:
    """Initialize the root 'opsagent' logger: rotating JSON file and/or
    colored console, mirroring the reference's tee of both cores."""
    global _initialized
    logger = logging.getLogger("opsagent")
    with _lock:
        logger.setLevel(_LEVELS.get(level.lower(), logging.INFO))
        logger.handlers.clear()
        logger.propagate = False
        if output in ("stdout", "both"):
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(
                JSONFormatter() if fmt == "json" and output != "both" else ColorFormatter()
            )
            logger.addHandler(h)
        if output in ("file", "both"):
            os.makedirs(os.path.dirname(file_path) or ".", exist_ok=True)
            fh = logging.handlers.RotatingFileHandler(
                file_path,
                maxBytes=max_size_mb * 1024 * 1024,
                backupCount=max_backups,
            )
            fh.setFormatter(JSONFormatter())
            logger.addHandler(fh)
        _initialized = True
    return logger


def get_logger(name: str = "") -> logging.Logger:
    if not _initialized:
        init_logger()
    if name:
        return logging.getLogger("opsagent").getChild(name)
    return logging.getLogger("opsagent")
