"""Tolerant JSON parsing for LLM output.

The ReAct loop's survival armor: remote (and local) models emit JSON wrapped in
markdown fences, with unescaped newlines inside strings, trailing commas, or
stray prose around the object. This module recovers a parseable object from
such output.

Capability parity with the reference's pkg/utils/json.go (CleanJSON
json.go:16-120, ParseJSON json.go:129-145, ExtractField json.go:155-190); the
implementation is original.
"""

from __future__ import annotations

import json
import re
from typing import Any


def _strip_code_fences(s: str) -> str:
    """Remove markdown code fences (```json ... ```)."""
    m = re.search(r"```(?:json)?\s*\n?(.*?)```", s, re.DOTALL)
    if m:
        return m.group(1)
    return s


def _extract_braced(s: str) -> str:
    """Extract the substring from the first '{' to its balanced closing '}'.

    Falls back to first-'{'..last-'}' when braces never balance (e.g. the
    model stopped mid-object).
    """
    start = s.find("{")
    if start < 0:
        return s
    depth = 0
    in_str = False
    esc = False
    for i in range(start, len(s)):
        c = s[i]
        if esc:
            esc = False
            continue
        if c == "\\":
            esc = True
            continue
        if c == '"':
            in_str = not in_str
            continue
        if in_str:
            continue
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return s[start : i + 1]
    end = s.rfind("}")
    if end > start:
        return s[start : end + 1]
    return s[start:]


def _escape_newlines_in_strings(s: str) -> str:
    """Escape literal newlines/tabs that appear inside JSON string literals."""
    out: list[str] = []
    in_str = False
    esc = False
    for c in s:
        if esc:
            out.append(c)
            esc = False
            continue
        if c == "\\":
            out.append(c)
            esc = True
            continue
        if c == '"':
            in_str = not in_str
            out.append(c)
            continue
        if in_str and c == "\n":
            out.append("\\n")
        elif in_str and c == "\r":
            out.append("\\r")
        elif in_str and c == "\t":
            out.append("\\t")
        else:
            out.append(c)
    return "".join(out)


_TRAILING_COMMA = re.compile(r",\s*([}\]])")


def _remove_trailing_commas(s: str) -> str:
    return _TRAILING_COMMA.sub(r"\1", s)


def _close_unterminated(s: str) -> str:
    """Best-effort close of an object the model stopped generating mid-way."""
    depth = 0
    in_str = False
    esc = False
    for c in s:
        if esc:
            esc = False
            continue
        if c == "\\":
            esc = True
            continue
        if c == '"':
            in_str = not in_str
            continue
        if in_str:
            continue
        if c == "{" or c == "[":
            depth += 1
        elif c == "}" or c == "]":
            depth -= 1
    if in_str:
        s = s + '"'
    if depth > 0:
        s = s + "}" * depth
    return s


def clean_json(s: str) -> str:
    """Normalize sloppy LLM output into (hopefully) parseable JSON text.

    Steps: strip code fences -> extract the balanced braced region -> escape
    raw newlines inside strings -> drop trailing commas -> close unterminated
    braces/strings.
    """
    s = _strip_code_fences(s)
    s = _extract_braced(s)
    s = _escape_newlines_in_strings(s)
    s = _remove_trailing_commas(s)
    s = _close_unterminated(s)
    return s.strip()


def parse_json(s: str) -> Any:
    """Parse JSON, strictly first, then after ``clean_json`` repair.

    Raises ``ValueError`` when even the repaired text does not parse.
    """
    try:
        return json.loads(s)
    except (json.JSONDecodeError, TypeError):
        pass
    cleaned = clean_json(s)
    try:
        return json.loads(cleaned)
    except json.JSONDecodeError as e:
        raise ValueError(f"unparseable JSON after repair: {e}") from e


def extract_field(s: str, field: str) -> str:
    """Extract a top-level string field from JSON-ish text.

    Tries full parse (strict then repaired) and a dict lookup; falls back to a
    regex over the raw text that tolerates escaped quotes in the value.
    Returns "" when the field cannot be found.
    """
    for attempt in (s, None):
        try:
            obj = json.loads(s) if attempt is not None else json.loads(clean_json(s))
        except (json.JSONDecodeError, TypeError):
            continue
        if isinstance(obj, dict) and field in obj:
            v = obj[field]
            if isinstance(v, str):
                return v
            return json.dumps(v, ensure_ascii=False)
    # Regex fallback: "field" : "value with \" escapes"
    pat = re.compile(
        r'"' + re.escape(field) + r'"\s*:\s*"((?:[^"\\]|\\.)*)"', re.DOTALL
    )
    m = pat.search(s)
    if m:
        raw = m.group(1)
        try:
            return json.loads('"' + raw + '"')
        except json.JSONDecodeError:
            return raw
    # Non-string value fallback: "field": {...} / [...] / number / bool
    pat2 = re.compile(r'"' + re.escape(field) + r'"\s*:\s*([\[{].*?[\]}]|[^,}\]]+)', re.DOTALL)
    m = pat2.search(s)
    if m:
        return m.group(1).strip()
    return ""
