from .jsonrepair import clean_json, parse_json, extract_field
from .globalstore import set_global, get_global, delete_global
from .yamlutil import extract_yaml
from .perf import PerfStats, get_perf_stats, trace_func
from .config import load_config, get_config
from .logger import get_logger, init_logger

__all__ = [
    "clean_json",
    "parse_json",
    "extract_field",
    "set_global",
    "get_global",
    "delete_global",
    "extract_yaml",
    "PerfStats",
    "get_perf_stats",
    "trace_func",
    "load_config",
    "get_config",
    "get_logger",
    "init_logger",
]
