"""Terminal markdown rendering.

Capability parity with the reference's pkg/utils/term.go:11-30 (glamour
rendering at terminal width). Implemented as a lightweight ANSI renderer:
headers, bold, inline code, fenced code blocks, bullets, rules.
"""

from __future__ import annotations

import re
import shutil
import sys

_BOLD = "\033[1m"
_DIM = "\033[2m"
_CYAN = "\033[36m"
_YELLOW = "\033[33m"
_RESET = "\033[0m"


def _inline(s: str, color: bool) -> str:
    if not color:
        return s
    s = re.sub(r"\*\*(.+?)\*\*", _BOLD + r"\1" + _RESET, s)
    s = re.sub(r"`([^`]+)`", _CYAN + r"\1" + _RESET, s)
    return s


def render_markdown(text: str, width: int | None = None, color: bool | None = None) -> str:
    if color is None:
        color = sys.stdout.isatty()
    if width is None:
        width = min(shutil.get_terminal_size((100, 24)).columns, 120)
    out: list[str] = []
    in_code = False
    for line in text.splitlines():
        if line.strip().startswith("```"):
            in_code = not in_code
            out.append((_DIM if color else "") + "-" * 4 + (_RESET if color else ""))
            continue
        if in_code:
            out.append(("  " + line) if not color else ("  " + _YELLOW + line + _RESET))
            continue
        m = re.match(r"^(#{1,6})\s+(.*)$", line)
        if m:
            title = m.group(2)
            out.append((_BOLD + title + _RESET) if color else title.upper())
            continue
        if re.match(r"^\s*[-*]\s+", line):
            out.append(re.sub(r"^(\s*)[-*]\s+", r"\1• ", _inline(line, color)))
            continue
        if re.match(r"^\s*(---+|\*\*\*+)\s*$", line):
            out.append("-" * min(width, 40))
            continue
        out.append(_inline(line, color))
    return "\n".join(out)
