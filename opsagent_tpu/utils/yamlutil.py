"""YAML extraction from LLM output.

Capability parity with the reference's pkg/utils/yaml.go:22-36 (fenced
```yaml``` code-block extraction used by the generate workflow).
"""

from __future__ import annotations

import re

_FENCE = re.compile(r"```(?:yaml|yml)?\s*\n(.*?)```", re.DOTALL)


def extract_yaml(s: str) -> str:
    """Return the contents of the first fenced YAML block, or the input
    unchanged when no fence is present."""
    m = _FENCE.search(s)
    if m:
        return m.group(1).strip() + "\n"
    return s
