"""Device-level profiling: ``jax.profiler`` traces and per-step device
timings, layered on the request-level perf registry.

The reference instruments the host path only — named start/stop timers
aggregated to min/max/avg/p95/p99 (reference pkg/utils/perf.go:168-210),
exposed at GET /api/perf/stats (reference pkg/api/router.go:104). On TPU
that misses where the time actually goes: host wall-clock around a dispatch
measures the *enqueue*, not the device, because XLA execution is async.
This module adds the two device-side views SURVEY §5 calls for:

1. **Traces** — ``trace()`` wraps a region in a ``jax.profiler`` capture
   (TensorBoard/xprof format: per-op device timelines, HLO, memory). Opt-in
   via ``OPSAGENT_PROFILE_DIR`` or an explicit ``logdir``; no-op otherwise,
   so production serving pays nothing.
2. **Per-step device timings** — ``device_timer()`` blocks on the step's
   output arrays and records the *synchronous* elapsed time into the perf
   registry under a ``device.`` prefix, so ``/api/perf/stats`` shows device
   step time next to the host-side dispatch/pull timers. Blocking defeats
   the engine's dispatch pipelining, so this is opt-in via
   ``OPSAGENT_DEVICE_TIMING=1`` — a measurement mode, not a serving mode.

``annotate()`` names host regions inside an active trace (shows up on the
trace timeline), and is free when no trace is running.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Iterator

import jax

from .logger import get_logger
from .perf import get_perf_stats

log = get_logger("profiling")

_ENV_DIR = "OPSAGENT_PROFILE_DIR"
_ENV_TIMING = "OPSAGENT_DEVICE_TIMING"


def profile_dir() -> str | None:
    """The configured trace directory, or None when tracing is off."""
    return os.environ.get(_ENV_DIR) or None


def device_timing_enabled() -> bool:
    return os.environ.get(_ENV_TIMING, "") not in ("", "0", "false")


@contextlib.contextmanager
def trace(logdir: str | None = None) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace of the enclosed region into
    ``logdir`` (or ``$OPSAGENT_PROFILE_DIR``). No-op when neither is set.

    The capture includes device timelines for every XLA program launched
    inside the region — the tool for answering "where do the ms/step go"
    that host timers cannot (they only see the async enqueue).
    """
    logdir = logdir or profile_dir()
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    log.info(f"jax.profiler trace started -> {logdir}")
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info(f"jax.profiler trace written -> {logdir}")


def annotate(name: str) -> contextlib.AbstractContextManager:
    """Name a host region on the profiler timeline (TraceAnnotation).
    Free when no trace is active; safe to leave in the hot path."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def device_timer(name: str, outputs: list[Any]) -> Iterator[None]:
    """Measure the device time of one dispatched step.

    Appends the step's output arrays to ``outputs`` inside the body; on
    exit (when enabled) blocks until they are ready and records the
    synchronous wall time as ``device.<name>`` in the perf registry. When
    ``OPSAGENT_DEVICE_TIMING`` is unset this is a plain pass-through — no
    sync, no pipeline stall.
    """
    if not device_timing_enabled():
        yield
        return
    import time

    t0 = time.perf_counter()
    yield
    for out in outputs:
        jax.block_until_ready(out)
    get_perf_stats().record_metric(
        f"device.{name}", (time.perf_counter() - t0) * 1e3, "ms"
    )


MAX_CAPTURE_SECONDS = 120.0


def timed_capture(seconds: float, logdir: str | None = None) -> str:
    """Capture a ``jax.profiler`` device trace of the NEXT ``seconds`` of
    whatever the process is doing — the on-demand form behind
    ``POST /api/debug/profile?seconds=N``: live traffic keeps flowing
    while the capture runs, so the trace shows the real serving mix
    (dispatch composition, compiles, host gaps) instead of a synthetic
    bench loop. Blocking: run from a worker thread, never the event loop.

    Raises ``ValueError`` for a silly duration, ``RuntimeError`` when no
    trace directory is configured (``--profile-dir`` /
    ``$OPSAGENT_PROFILE_DIR`` — operator-configured only, so a network
    client cannot mint an arbitrary-filesystem-write primitive), and
    whatever ``jax.profiler.start_trace`` raises when a capture is
    already running (the caller maps that to 409)."""
    if not 0 < seconds <= MAX_CAPTURE_SECONDS:
        raise ValueError(
            f"seconds must be in (0, {MAX_CAPTURE_SECONDS:.0f}], "
            f"got {seconds}"
        )
    logdir = logdir or profile_dir()
    if not logdir:
        raise RuntimeError(
            "profiling not enabled: start the server with --profile-dir "
            "(or set OPSAGENT_PROFILE_DIR)"
        )
    import time

    jax.profiler.start_trace(logdir)
    log.info(f"on-demand profile capture started ({seconds}s) -> {logdir}")
    try:
        time.sleep(seconds)
    finally:
        jax.profiler.stop_trace()
        log.info(f"on-demand profile capture written -> {logdir}")
    return logdir


def save_device_memory_profile(path: str) -> None:
    """Dump the current device memory profile (pprof format) — which
    buffers hold HBM right now. Pairs with the allocator's page
    accounting for leak hunts."""
    jax.profiler.save_device_memory_profile(path)
