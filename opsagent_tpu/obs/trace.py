"""Per-request trace spans.

A request ID is minted at ingress (HTTP middleware, the chat-completions
frontend, or the ReAct loop when called directly) and the request's life is
recorded as a span tree: queue-wait -> prefill -> per-block decode ->
detokenize -> tool-exec. The tree is retrievable at
``GET /api/trace/{request_id}`` while the request runs and after it
finishes (bounded ring of recent traces), and each completed trace emits
one structured JSON log event.

Propagation works two ways, because the serving stack crosses threads:

- **contextvars** carry the current span within a thread of execution
  (the ReAct loop's tool calls, the frontend's detokenize step), so
  ``span("tool_exec")`` nests under whatever is active.
- **explicit handles** carry it across the scheduler/engine thread
  boundary: the frontend attaches the request's span to the scheduler
  ``Request``, the scheduler passes it into ``engine.begin_request``, and
  the engine records phase children on the ``Sequence``'s handle. The
  scheduler thread has no ambient context — a contextvar set in the HTTP
  thread would silently not propagate there.

Everything no-ops when no trace is active, so the engine's hot loop pays
one ``is None`` check per instrumented site for untraced traffic (bench,
tests, direct engine use).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Iterator

from ..utils.logger import get_logger

log = get_logger("obs.trace")

# -- tail-based retention -----------------------------------------------------
# At million-session volume a keep-everything ring is useless: the 512
# slots hold the last few seconds of HEALTHY traffic and the one request
# you want to investigate is long gone. Tail-based sampling inverts it:
# the retention decision happens at request FINISH, when we know whether
# anything went wrong. Anomalous requests (SLO breach, error, failover,
# engine restart) are always kept; healthy ones survive a probability-p
# draw (OPSAGENT_TRACE_SAMPLE, default 1.0 = keep all — the single-box
# dev default). opsagent_trace_retention_total{decision} proves the
# policy on the scrape.
_ENV_SAMPLE = "OPSAGENT_TRACE_SAMPLE"
_sample_p: float | None = None     # None = read the env on first use
# Request ids marked anomalous from OUTSIDE the trace's own thread (the
# router's failover path marks the journey id between legs; the resumed
# leg's fresh Trace under the same id must inherit the flag). Bounded:
# ids are unbounded, this set must not be.
_anomalous_ids: "OrderedDict[str, float]" = OrderedDict()
_anomalous_lock = threading.Lock()
_ANOMALOUS_CAP = 4096


def sample_probability() -> float:
    global _sample_p
    if _sample_p is None:
        try:
            _sample_p = min(
                1.0, max(0.0, float(os.environ.get(_ENV_SAMPLE, "1.0")))
            )
        except ValueError:
            _sample_p = 1.0
    return _sample_p


def set_sample_probability(p: float | None) -> None:
    """Programmatic override (bench/tests); None re-reads the env."""
    global _sample_p
    _sample_p = None if p is None else min(1.0, max(0.0, float(p)))


def mark_anomalous(request_id: str | None, reason: str = "") -> None:
    """Flag a request's trace as anomalous so tail-based retention always
    keeps it. Safe for unknown/absent ids; the flag also outlives the
    current trace object so a failover's resumed leg (a fresh Trace under
    the same journey id) inherits it."""
    if not request_id:
        return
    with _anomalous_lock:
        _anomalous_ids[request_id] = time.time()
        _anomalous_ids.move_to_end(request_id)
        while len(_anomalous_ids) > _ANOMALOUS_CAP:
            _anomalous_ids.popitem(last=False)
    t = _store.get(request_id)
    if t is not None:
        t.anomalous = True
        if reason:
            t.anomaly_reason = t.anomaly_reason or reason


def _is_marked(request_id: str) -> bool:
    with _anomalous_lock:
        return request_id in _anomalous_ids


def reset_retention() -> None:
    """Test-isolation hook: forget marks and the sampling override."""
    global _sample_p
    _sample_p = None
    with _anomalous_lock:
        _anomalous_ids.clear()


class Span:
    """One timed phase. ``t0``/``t1`` are ``time.perf_counter`` readings;
    ``t1`` is None while the span is open. Mutations lock on the owning
    trace so scheduler-thread and HTTP-thread children never race."""

    __slots__ = ("name", "t0", "t1", "attrs", "children", "trace")

    def __init__(self, name: str, trace: "Trace", t0: float | None = None):
        self.name = name
        self.trace = trace
        self.t0 = time.perf_counter() if t0 is None else t0
        self.t1: float | None = None
        self.attrs: dict[str, Any] = {}
        self.children: list[Span] = []

    # -- recording -----------------------------------------------------------
    def child(
        self, name: str, t0: float, t1: float, **attrs: Any
    ) -> "Span":
        """Attach an already-completed child span (engine-side phases are
        timed with plain floats and attached after the fact)."""
        s = Span(name, self.trace, t0=t0)
        s.t1 = t1
        s.attrs.update(attrs)
        with self.trace._lock:
            self.children.append(s)
        return s

    def start_child(self, name: str, **attrs: Any) -> "Span":
        s = Span(name, self.trace)
        s.attrs.update(attrs)
        with self.trace._lock:
            self.children.append(s)
        return s

    def close(self, **attrs: Any) -> None:
        with self.trace._lock:
            if self.t1 is None:
                self.t1 = time.perf_counter()
            self.attrs.update(attrs)

    def set(self, **attrs: Any) -> None:
        with self.trace._lock:
            self.attrs.update(attrs)

    # -- reading -------------------------------------------------------------
    def duration_s(self, now: float | None = None) -> float:
        end = self.t1 if self.t1 is not None else (now or time.perf_counter())
        return max(0.0, end - self.t0)

    def to_dict(self, origin: float) -> dict[str, Any]:
        with self.trace._lock:
            children = list(self.children)
            attrs = dict(self.attrs)
            t1 = self.t1
        d: dict[str, Any] = {
            "name": self.name,
            "start_ms": round((self.t0 - origin) * 1e3, 3),
            "duration_ms": round(self.duration_s() * 1e3, 3),
        }
        if t1 is None:
            d["open"] = True
        if attrs:
            d["attrs"] = attrs
        if children:
            d["children"] = [c.to_dict(origin) for c in children]
        return d


class Trace:
    """One request's span tree, rooted at the ingress span."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._lock = threading.RLock()
        self.started_at = time.time()
        self.root = Span("request", self)
        self.finished = False
        # Tail-based retention state: anomalous traces are always kept;
        # slo_class is stamped at ingress and read by the engine/scheduler
        # observation sites through their span handle (span.trace).
        self.anomalous = _is_marked(request_id)
        self.anomaly_reason = ""
        self.slo_class = ""

    def finish(self, **attrs: Any) -> None:
        """Close the root, emit the structured JSON log event, and apply
        the tail-based retention policy (here rather than in
        ``trace_request`` so directly-managed traces — the OpenAI
        frontend owns its Trace without the context manager — get the
        same decision). Safe to call more than once (only the first
        closes/logs/decides)."""
        with self._lock:
            if self.finished:
                return
            self.finished = True
        self.root.close(**attrs)
        phases = self.phase_totals_ms()
        log.info(
            "trace %s done in %.1f ms",
            self.request_id,
            self.root.duration_s() * 1e3,
            extra={
                "fields": {
                    "event": "trace",
                    "request_id": self.request_id,
                    "duration_ms": round(self.root.duration_s() * 1e3, 3),
                    "phases_ms": phases,
                }
            },
        )
        _store.finalize(self)

    def phase_totals_ms(self) -> dict[str, float]:
        """Wall milliseconds per DIRECT child phase of the root, summed by
        name. Direct children partition the request (children of children
        may overlap — pipelined decode blocks — so only the top level is a
        meaningful sum)."""
        with self._lock:
            children = list(self.root.children)
        out: dict[str, float] = {}
        for c in children:
            out[c.name] = round(
                out.get(c.name, 0.0) + c.duration_s() * 1e3, 3
            )
        return out

    def to_dict(self) -> dict[str, Any]:
        d = {
            "request_id": self.request_id,
            "started_at": self.started_at,
            "finished": self.finished,
            "duration_ms": round(self.root.duration_s() * 1e3, 3),
            "phases_ms": self.phase_totals_ms(),
            "root": self.root.to_dict(self.root.t0),
        }
        if self.slo_class:
            d["slo_class"] = self.slo_class
        if self.anomalous:
            d["anomalous"] = True
            if self.anomaly_reason:
                d["anomaly_reason"] = self.anomaly_reason
        return d


class TraceStore:
    """Bounded ring of recent traces keyed by request ID. Traces register
    at START so in-flight requests are inspectable; eviction prefers the
    oldest HEALTHY trace so anomalous ones outlive healthy churn (an
    all-anomalous ring still evicts oldest-first — the bound is hard)."""

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, Trace]" = OrderedDict()

    def add(self, trace: Trace) -> None:
        with self._lock:
            self._traces[trace.request_id] = trace
            self._traces.move_to_end(trace.request_id)
            while len(self._traces) > self.capacity:
                victim = None
                for rid, t in self._traces.items():
                    if not t.anomalous:
                        victim = rid
                        break
                if victim is None:
                    self._traces.popitem(last=False)
                else:
                    self._traces.pop(victim, None)

    def get(self, request_id: str) -> Trace | None:
        with self._lock:
            return self._traces.get(request_id)

    def discard(self, request_id: str) -> None:
        with self._lock:
            self._traces.pop(request_id, None)

    def finalize(self, trace: Trace) -> str:
        """Apply the tail-based retention policy to a finished trace and
        return the decision. Anomalous (flagged on the trace or marked by
        id from another thread, e.g. the router's failover path) is always
        kept; healthy traces survive a probability-p draw."""
        if trace.anomalous or _is_marked(trace.request_id):
            trace.anomalous = True
            decision = "kept_anomalous"
        else:
            p = sample_probability()
            if p >= 1.0 or random.random() < p:
                decision = "kept_sampled"
            else:
                decision = "dropped"
                self.discard(trace.request_id)
        try:
            from . import TRACE_RETENTION

            TRACE_RETENTION.inc(decision=decision)
        except Exception:
            pass
        return decision

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


_store = TraceStore()
_current: "contextvars.ContextVar[Span | None]" = contextvars.ContextVar(
    "opsagent_current_span", default=None
)


def get_store() -> TraceStore:
    return _store


def current_span() -> Span | None:
    return _current.get()


def new_request_id(prefix: str = "req") -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


def get_trace(request_id: str) -> dict[str, Any] | None:
    t = _store.get(request_id)
    return None if t is None else t.to_dict()


def class_of(handle: Any, default: str = "") -> str:
    """SLO class of the trace behind a span/trace handle (engine and
    scheduler sites hold a Span; the ingress stamped slo_class on its
    Trace). Returns ``default`` for untraced traffic."""
    if handle is None:
        return default
    trace = getattr(handle, "trace", handle)
    return getattr(trace, "slo_class", "") or default


def current_class(default: str = "") -> str:
    """SLO class of the context's active trace (ReAct-loop side)."""
    return class_of(_current.get(), default)


@contextlib.contextmanager
def trace_request(request_id: str | None = None) -> Iterator[Trace]:
    """Root a new trace for one request and make its root span current
    for this thread of execution. Finishes (and logs) on exit."""
    t = Trace(request_id or new_request_id())
    _store.add(t)
    token = _current.set(t.root)
    try:
        yield t
    finally:
        _current.reset(token)
        t.finish()


def format_tree(trace_dict: dict[str, Any]) -> str:
    """Human-readable span tree (verbose CLI runs print this to stderr):

        request 812.4 ms  [req-ab12...]
          llm_turn 530.1 ms
            generate 528.9 ms
              queue_wait 1.2 ms
              prefill 102.3 ms
              decode 424.0 ms (tokens=37)
          tool_exec 281.0 ms (tool=kubectl)
    """
    lines = [
        f"trace {trace_dict.get('request_id', '?')} "
        f"{trace_dict.get('duration_ms', 0.0):.1f} ms"
    ]

    def walk(node: dict[str, Any], depth: int) -> None:
        attrs = node.get("attrs") or {}
        tag = ""
        if attrs:
            inner = ", ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
            tag = f" ({inner})"
        lines.append(
            f"{'  ' * depth}{node.get('name', '?')} "
            f"{node.get('duration_ms', 0.0):.1f} ms{tag}"
        )
        for c in node.get("children", []):
            walk(c, depth + 1)

    root = trace_dict.get("root")
    if root:
        walk(root, 1)
    return "\n".join(lines)


@contextlib.contextmanager
def span(name: str, parent: Span | None = None, **attrs: Any) -> Iterator[Span | None]:
    """Open a child span under ``parent`` (or the context's current span)
    and make it current. Yields None (and records nothing) when no trace
    is active — instrumented code needs no feature flag."""
    p = parent if parent is not None else _current.get()
    if p is None:
        yield None
        return
    s = p.start_child(name, **attrs)
    token = _current.set(s)
    try:
        yield s
    finally:
        _current.reset(token)
        s.close()
