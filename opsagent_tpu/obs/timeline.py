"""Goodput ledger, part 2: per-request lifecycle timelines.

Traces (obs/trace.py) hold one request's span tree; the flight ring
(obs/flight.py) holds the engine's event stream. Neither alone answers
the operator question "where did THIS request's wall clock go — queue,
prefill, decode, or blocked on a tool?". This module assembles both into
one timeline per request ID:

- **phases**: a non-overlapping, gap-free segmentation of the request's
  wall clock (queued -> prefill -> decode -> tool_blocked -> ...), built
  from the trace spans with flight tool-entry/exit events bounding the
  tool windows exactly, and residual time labeled ``host`` (chat
  translation, detokenize-adjacent work) so coverage is complete rather
  than silently partial;
- **goodput**: the per-request fraction split (decode_active vs
  tool_blocked vs queued vs prefill vs host) — the number ROADMAP item 2
  (Conveyor-style tool overlap) will move;
- **events**: the flight-ring events attributable to the request
  (admission / dispatch / ttft / tool enter+exit / park / restore /
  finish), stitched ACROSS engine generations: a restart re-admits the
  request under a new seq_id with the same request ID, and both
  generations' events land in one timeline.

Served at ``GET /api/timeline/{request_id}`` on both servers and
rendered by ``opsagent timeline`` as an ASCII Gantt. Everything here is
read-side host work — no instrumentation is added to the hot path.
"""

from __future__ import annotations

import time
from typing import Any

from .flight import get_recorder
from .trace import Span, get_store

# Span names that map onto timeline phases. Children of "decode"
# (decode_block / mixed_step / decode_step) stay inside their parent —
# they overlap by design under pipelining and would shred the sweep.
PHASE_OF_SPAN = {
    "queue_wait": "queued",
    "prefill": "prefill",
    "decode": "decode_active",
    "tool_exec": "tool_blocked",
    "detokenize": "host",
}

# Flight-event kinds attributable to a request via request_id or its
# seq_ids (dispatch events carry seq-id lists, not request ids).
_SEQ_LIST_KEYS = ("seq_ids", "decode_seq_ids", "prefill_seq_ids")


def _collect_span_intervals(
    span: Span, out: list[tuple[str, float, float, dict]], now: float
) -> None:
    phase = PHASE_OF_SPAN.get(span.name)
    if phase is not None:
        t1 = span.t1 if span.t1 is not None else now
        attrs = dict(span.attrs)
        attrs["span"] = span.name
        out.append((phase, span.t0, t1, attrs))
        if span.name != "decode":
            return  # mapped leaves don't nest further phases
    for child in list(span.children):
        _collect_span_intervals(child, out, now)


def _tool_windows_from_events(
    events: list[dict[str, Any]]
) -> list[tuple[str, float, float, dict]]:
    """Pair tool_exec enter/exit flight events into exact tool-blocked
    windows. Unpaired enters (tool still running) extend to the last
    event's ts — visibly open rather than dropped."""
    out: list[tuple[str, float, float, dict]] = []
    open_enters: list[dict[str, Any]] = []
    last_ts = max((e["ts"] for e in events), default=0.0)
    for e in events:
        if e.get("kind") != "tool_exec":
            continue
        if e.get("phase") == "enter":
            open_enters.append(e)
        elif e.get("phase") == "exit" and open_enters:
            ent = open_enters.pop()
            attrs = {
                "tool": e.get("tool"),
                "outcome": e.get("outcome"),
                "source": "flight",
            }
            # Conveyor launches mark both halves of the pair; the enter
            # additionally carries how far into decode the launch fired.
            if ent.get("conveyor") or e.get("conveyor"):
                attrs["conveyor"] = True
            if ent.get("launch_offset_ms") is not None:
                attrs["launch_offset_ms"] = ent["launch_offset_ms"]
            out.append(("tool_blocked", ent["ts"], e["ts"], attrs))
    for ent in open_enters:
        out.append((
            "tool_blocked", ent["ts"], last_ts,
            {"tool": ent.get("tool"), "open": True, "source": "flight"},
        ))
    return out


def _sweep(
    intervals: list[tuple[str, float, float, dict]],
    t0: float,
    t1: float,
    min_gap_s: float = 1e-4,
) -> list[dict[str, Any]]:
    """Turn possibly-overlapping phase intervals into a non-overlapping,
    gap-free segmentation of [t0, t1]: intervals are clipped against the
    sweep cursor in start order (same-phase duplicates — a tool window
    seen as both a span and a flight pair — merge naturally), and any
    residue between mapped segments becomes a ``host`` segment, so the
    phases partition the request's wall clock completely."""
    segs: list[dict[str, Any]] = []
    cursor = t0

    def emit(phase: str, a: float, b: float, attrs: dict | None = None):
        if b - a <= 0:
            return
        if (
            segs
            and segs[-1]["phase"] == phase
            and abs(segs[-1]["_t1"] - a) < 1e-9
            and not attrs
        ):
            segs[-1]["_t1"] = b
            return
        segs.append({"phase": phase, "_t0": a, "_t1": b,
                     **({"attrs": attrs} if attrs else {})})

    for phase, a, b, attrs in sorted(intervals, key=lambda x: (x[1], -x[2])):
        a = max(a, cursor, t0)
        b = min(max(b, a), t1)
        if b - a <= 0:
            continue
        if a - cursor > min_gap_s:
            emit("host", cursor, a)
        elif a > cursor:
            a = cursor  # swallow sub-threshold gap into this segment
        emit(phase, a, b, attrs if attrs else None)
        cursor = b
    if t1 - cursor > min_gap_s:
        emit("host", cursor, t1)
    for s in segs:
        s["start_ms"] = round((s.pop("_t0") - t0) * 1e3, 3)
        end = s.pop("_t1")
        s["end_ms"] = round((end - t0) * 1e3, 3)
        s["duration_ms"] = round(s["end_ms"] - s["start_ms"], 3)
    return segs


def _relevant_events(
    request_id: str, events: list[dict[str, Any]]
) -> tuple[list[dict[str, Any]], set[int], int]:
    """Flight events attributable to this request, the seq_ids it wore
    (one per engine generation it was admitted under), and the number of
    engine restarts observed inside its event window."""
    seq_ids: set[int] = set()
    for e in events:
        if e.get("request_id") == request_id and "seq_id" in e:
            seq_ids.add(e["seq_id"])
    picked: list[dict[str, Any]] = []
    for e in events:
        if e.get("request_id") == request_id:
            picked.append(e)
            continue
        if e.get("seq_id") in seq_ids and "request_id" not in e:
            picked.append(e)
            continue
        if any(
            seq_ids.intersection(e.get(k) or ()) for k in _SEQ_LIST_KEYS
        ):
            picked.append(e)
    restarts = 0
    if picked:
        lo = min(e["ts"] for e in picked)
        hi = max(e["ts"] for e in picked)
        for e in events:
            if e.get("kind") == "anomaly" and e.get("reason") == "engine_restart":
                if lo <= e["ts"] <= hi:
                    restarts += 1
                    picked.append(e)
    picked.sort(key=lambda e: e["ts"])
    return picked, seq_ids, restarts


def assemble(request_id: str) -> dict[str, Any] | None:
    """Build the timeline for one request from the live trace store and
    flight ring. Returns None when NOTHING is known about the id (no
    trace and no flight events). Works mid-flight (open spans extend to
    now) and across engine restarts (seq_ids accumulate per generation,
    and the trace's re-admission spans segment the second prefill/decode
    pass like the first)."""
    now = time.perf_counter()
    trace = get_store().get(request_id)
    events, seq_ids, restarts = _relevant_events(
        request_id, get_recorder().snapshot()
    )
    if trace is None and not events:
        return None

    intervals: list[tuple[str, float, float, dict]] = []
    if trace is not None:
        t0 = trace.root.t0
        t1 = trace.root.t1 if trace.root.t1 is not None else now
        _collect_span_intervals(trace.root, intervals, now)
    else:
        # Trace evicted (ring of 512): reconstruct coarse phases from the
        # flight events alone — admission->ttft is prefill, ttft->finish
        # decode, per engine generation.
        t0 = min(e["ts"] for e in events)
        t1 = max(e["ts"] for e in events)
        adm = {e["seq_id"]: e["ts"] for e in events
               if e.get("kind") == "admission"}
        ttft = {e["seq_id"]: e["ts"] for e in events
                if e.get("kind") == "ttft"}
        fin = {e["seq_id"]: e["ts"] for e in events
               if e.get("kind") == "finish"}
        for sid, a in adm.items():
            ft = ttft.get(sid)
            if ft is not None:
                intervals.append(("prefill", a, ft, {"seq_id": sid}))
                end = fin.get(sid, t1)
                intervals.append(("decode_active", ft, end, {"seq_id": sid}))
    tool_ivs = [
        iv for iv in _tool_windows_from_events(events)
        if t0 <= iv[1] <= t1 or t0 <= iv[2] <= t1
    ]
    intervals.extend(tool_ivs)
    phases = _sweep(intervals, t0, t1)

    # Conveyor overlap: the stretch of each early-launched tool window
    # that ran concurrently with decode. The sweep hides it by design
    # (its phases partition wall clock, and concurrent time IS decode),
    # so it is surfaced as separate windows rather than a phase.
    decode_ivs = [(a, b) for ph, a, b, _ in intervals
                  if ph == "decode_active"]
    overlap_windows: list[dict[str, Any]] = []
    for _ph, a, b, attrs in tool_ivs:
        if not attrs.get("conveyor"):
            continue
        for da, db in decode_ivs:
            oa, ob = max(a, da), min(b, db)
            if ob - oa > 1e-6:
                overlap_windows.append({
                    "tool": attrs.get("tool"),
                    "start_ms": round((oa - t0) * 1e3, 3),
                    "end_ms": round((ob - t0) * 1e3, 3),
                    "duration_ms": round((ob - oa) * 1e3, 3),
                })
    overlap_windows.sort(key=lambda w: w["start_ms"])
    tool_overlap_ms = round(
        sum(w["duration_ms"] for w in overlap_windows), 3
    )

    total_ms = max(1e-9, (t1 - t0) * 1e3)
    by_phase: dict[str, float] = {}
    for s in phases:
        by_phase[s["phase"]] = by_phase.get(s["phase"], 0.0) + s["duration_ms"]
    goodput = {
        p: round(by_phase.get(p, 0.0) / total_ms, 4)
        for p in ("decode_active", "tool_blocked", "queued", "prefill", "host")
    }
    goodput["coverage"] = round(sum(by_phase.values()) / total_ms, 4)

    ev_out = []
    for e in events:
        d = dict(e)
        d["t_ms"] = round((d.pop("ts") - t0) * 1e3, 3)
        ev_out.append(d)
    return {
        "request_id": request_id,
        "duration_ms": round(total_ms, 3),
        "finished": trace.finished if trace is not None else None,
        # Distinct engine generations this request's events span: one
        # plus observed restarts. seq_ids alone cannot tell (one agent
        # request legitimately wears one seq_id per llm turn).
        "engine_generations": restarts + 1,
        "engine_restarts": restarts,
        "seq_ids": sorted(seq_ids),
        "goodput": goodput,
        "phases": phases,
        "tool_overlap_ms": tool_overlap_ms,
        "overlap_windows": overlap_windows,
        "events": ev_out,
    }


# -- rendering ----------------------------------------------------------------
_BAR = "#"
_PAD = "."


def render_gantt(timeline: dict[str, Any], width: int = 64) -> str:
    """ASCII Gantt of a timeline dict (the `opsagent timeline` CLI body;
    pure string math so tests drive it without a terminal)."""
    total = max(1e-9, float(timeline.get("duration_ms", 0.0)))
    lines = [
        f"timeline {timeline.get('request_id', '?')}  "
        f"{total:.1f} ms total"
        + (
            f"  ({timeline['engine_generations']} engine generations)"
            if timeline.get("engine_generations", 1) > 1 else ""
        )
    ]
    g = timeline.get("goodput", {})
    if g:
        lines.append(
            "goodput: "
            + "  ".join(
                f"{p} {100.0 * g.get(p, 0.0):.1f}%"
                for p in (
                    "decode_active", "tool_blocked", "queued", "prefill",
                    "host",
                )
                if g.get(p)
            )
            + f"  (coverage {100.0 * g.get('coverage', 0.0):.1f}%)"
        )
    overlaps = timeline.get("overlap_windows") or []
    name_w = max(
        [len(p.get("phase", "")) for p in timeline.get("phases", [])]
        + ([len("tool_overlap")] if overlaps else [])
        + [5]
    )

    def _row(name: str, start_ms: float, end_ms: float, dur_ms: float,
             tag: str) -> str:
        a = int(round(start_ms / total * width))
        b = int(round(end_ms / total * width))
        b = min(width, max(b, a + 1))
        bar = _PAD * a + _BAR * (b - a) + _PAD * (width - b)
        return f"{name:<{name_w}s} |{bar}| {dur_ms:8.1f} ms{tag}"

    for seg in timeline.get("phases", []):
        attrs = seg.get("attrs") or {}
        tag = f" tool={attrs['tool']}" if attrs.get("tool") else ""
        lines.append(_row(
            seg["phase"], seg["start_ms"], seg["end_ms"],
            seg["duration_ms"], tag,
        ))
    # Conveyor windows: tool run time hidden under decode, drawn as extra
    # rows so the tool bar visibly overlaps the decode span above.
    for w in overlaps:
        tag = f" tool={w['tool']}" if w.get("tool") else ""
        lines.append(_row(
            "tool_overlap", w["start_ms"], w["end_ms"],
            w["duration_ms"], tag,
        ))
    if overlaps:
        lines.append(
            f"tool overlap hidden behind decode: "
            f"{timeline.get('tool_overlap_ms', 0.0):.1f} ms"
        )
    return "\n".join(lines)
