"""Goodput ledger, part 2: per-request lifecycle timelines.

Traces (obs/trace.py) hold one request's span tree; the flight ring
(obs/flight.py) holds the engine's event stream. Neither alone answers
the operator question "where did THIS request's wall clock go — queue,
prefill, decode, or blocked on a tool?". This module assembles both into
one timeline per request ID:

- **phases**: a non-overlapping, gap-free segmentation of the request's
  wall clock (queued -> prefill -> decode -> tool_blocked -> ...), built
  from the trace spans with flight tool-entry/exit events bounding the
  tool windows exactly, and residual time labeled ``host`` (chat
  translation, detokenize-adjacent work) so coverage is complete rather
  than silently partial;
- **goodput**: the per-request fraction split (decode_active vs
  tool_blocked vs queued vs prefill vs host) — the number ROADMAP item 2
  (Conveyor-style tool overlap) will move;
- **events**: the flight-ring events attributable to the request
  (admission / dispatch / ttft / tool enter+exit / park / restore /
  finish), stitched ACROSS engine generations: a restart re-admits the
  request under a new seq_id with the same request ID, and both
  generations' events land in one timeline.

Served at ``GET /api/timeline/{request_id}`` on both servers and
rendered by ``opsagent timeline`` as an ASCII Gantt. Everything here is
read-side host work — no instrumentation is added to the hot path.
"""

from __future__ import annotations

import time
from typing import Any

from .flight import get_recorder
from .trace import Span, get_store

# Span names that map onto timeline phases. Children of "decode"
# (decode_block / mixed_step / decode_step) stay inside their parent —
# they overlap by design under pipelining and would shred the sweep.
PHASE_OF_SPAN = {
    "queue_wait": "queued",
    "prefill": "prefill",
    "decode": "decode_active",
    "tool_exec": "tool_blocked",
    "detokenize": "host",
}

# Flight-event kinds attributable to a request via request_id or its
# seq_ids (dispatch events carry seq-id lists, not request ids).
_SEQ_LIST_KEYS = ("seq_ids", "decode_seq_ids", "prefill_seq_ids")


def _collect_span_intervals(
    span: Span, out: list[tuple[str, float, float, dict]], now: float
) -> None:
    phase = PHASE_OF_SPAN.get(span.name)
    if phase is not None:
        t1 = span.t1 if span.t1 is not None else now
        attrs = dict(span.attrs)
        attrs["span"] = span.name
        out.append((phase, span.t0, t1, attrs))
        if span.name != "decode":
            return  # mapped leaves don't nest further phases
    for child in list(span.children):
        _collect_span_intervals(child, out, now)


def _tool_windows_from_events(
    events: list[dict[str, Any]]
) -> list[tuple[str, float, float, dict]]:
    """Pair tool_exec enter/exit flight events into exact tool-blocked
    windows. Unpaired enters (tool still running) extend to the last
    event's ts — visibly open rather than dropped."""
    out: list[tuple[str, float, float, dict]] = []
    open_enters: list[dict[str, Any]] = []
    last_ts = max((e["ts"] for e in events), default=0.0)
    for e in events:
        if e.get("kind") != "tool_exec":
            continue
        if e.get("phase") == "enter":
            open_enters.append(e)
        elif e.get("phase") == "exit" and open_enters:
            ent = open_enters.pop()
            attrs = {
                "tool": e.get("tool"),
                "outcome": e.get("outcome"),
                "source": "flight",
            }
            # Conveyor launches mark both halves of the pair; the enter
            # additionally carries how far into decode the launch fired.
            if ent.get("conveyor") or e.get("conveyor"):
                attrs["conveyor"] = True
            if ent.get("launch_offset_ms") is not None:
                attrs["launch_offset_ms"] = ent["launch_offset_ms"]
            out.append(("tool_blocked", ent["ts"], e["ts"], attrs))
    for ent in open_enters:
        out.append((
            "tool_blocked", ent["ts"], last_ts,
            {"tool": ent.get("tool"), "open": True, "source": "flight"},
        ))
    return out


def _sweep(
    intervals: list[tuple[str, float, float, dict]],
    t0: float,
    t1: float,
    min_gap_s: float = 1e-4,
) -> list[dict[str, Any]]:
    """Turn possibly-overlapping phase intervals into a non-overlapping,
    gap-free segmentation of [t0, t1]: intervals are clipped against the
    sweep cursor in start order (same-phase duplicates — a tool window
    seen as both a span and a flight pair — merge naturally), and any
    residue between mapped segments becomes a ``host`` segment, so the
    phases partition the request's wall clock completely."""
    segs: list[dict[str, Any]] = []
    cursor = t0

    def emit(phase: str, a: float, b: float, attrs: dict | None = None):
        if b - a <= 0:
            return
        if (
            segs
            and segs[-1]["phase"] == phase
            and abs(segs[-1]["_t1"] - a) < 1e-9
            and not attrs
        ):
            segs[-1]["_t1"] = b
            return
        segs.append({"phase": phase, "_t0": a, "_t1": b,
                     **({"attrs": attrs} if attrs else {})})

    for phase, a, b, attrs in sorted(intervals, key=lambda x: (x[1], -x[2])):
        a = max(a, cursor, t0)
        b = min(max(b, a), t1)
        if b - a <= 0:
            continue
        if a - cursor > min_gap_s:
            emit("host", cursor, a)
        elif a > cursor:
            a = cursor  # swallow sub-threshold gap into this segment
        emit(phase, a, b, attrs if attrs else None)
        cursor = b
    if t1 - cursor > min_gap_s:
        emit("host", cursor, t1)
    for s in segs:
        s["start_ms"] = round((s.pop("_t0") - t0) * 1e3, 3)
        end = s.pop("_t1")
        s["end_ms"] = round((end - t0) * 1e3, 3)
        s["duration_ms"] = round(s["end_ms"] - s["start_ms"], 3)
    return segs


def _relevant_events(
    request_id: str, events: list[dict[str, Any]]
) -> tuple[list[dict[str, Any]], set[int], int]:
    """Flight events attributable to this request, the seq_ids it wore
    (one per engine generation it was admitted under), and the number of
    engine restarts observed inside its event window."""
    seq_ids: set[int] = set()
    for e in events:
        if e.get("request_id") == request_id and "seq_id" in e:
            seq_ids.add(e["seq_id"])
    picked: list[dict[str, Any]] = []
    for e in events:
        if e.get("request_id") == request_id:
            picked.append(e)
            continue
        if e.get("seq_id") in seq_ids and "request_id" not in e:
            picked.append(e)
            continue
        if any(
            seq_ids.intersection(e.get(k) or ()) for k in _SEQ_LIST_KEYS
        ):
            picked.append(e)
    restarts = 0
    if picked:
        lo = min(e["ts"] for e in picked)
        hi = max(e["ts"] for e in picked)
        for e in events:
            if e.get("kind") == "anomaly" and e.get("reason") == "engine_restart":
                if lo <= e["ts"] <= hi:
                    restarts += 1
                    picked.append(e)
    picked.sort(key=lambda e: e["ts"])
    return picked, seq_ids, restarts


def assemble(request_id: str) -> dict[str, Any] | None:
    """Build the timeline for one request from the live trace store and
    flight ring. Returns None when NOTHING is known about the id (no
    trace and no flight events). Works mid-flight (open spans extend to
    now) and across engine restarts (seq_ids accumulate per generation,
    and the trace's re-admission spans segment the second prefill/decode
    pass like the first)."""
    now = time.perf_counter()
    trace = get_store().get(request_id)
    events, seq_ids, restarts = _relevant_events(
        request_id, get_recorder().snapshot()
    )
    if trace is None and not events:
        return None

    intervals: list[tuple[str, float, float, dict]] = []
    if trace is not None:
        t0 = trace.root.t0
        t1 = trace.root.t1 if trace.root.t1 is not None else now
        _collect_span_intervals(trace.root, intervals, now)
    else:
        # Trace evicted (ring of 512): reconstruct coarse phases from the
        # flight events alone — admission->ttft is prefill, ttft->finish
        # decode, per engine generation.
        t0 = min(e["ts"] for e in events)
        t1 = max(e["ts"] for e in events)
        adm = {e["seq_id"]: e["ts"] for e in events
               if e.get("kind") == "admission"}
        ttft = {e["seq_id"]: e["ts"] for e in events
                if e.get("kind") == "ttft"}
        fin = {e["seq_id"]: e["ts"] for e in events
               if e.get("kind") == "finish"}
        for sid, a in adm.items():
            ft = ttft.get(sid)
            if ft is not None:
                intervals.append(("prefill", a, ft, {"seq_id": sid}))
                end = fin.get(sid, t1)
                intervals.append(("decode_active", ft, end, {"seq_id": sid}))
    if intervals:
        # Fleet hops can nest a later leg's spans under an already-closed
        # root (an in-process failover leg adopts the journey's existing
        # trace), so the request window must cover every collected
        # interval, not just the root span.
        t0 = min([t0] + [iv[1] for iv in intervals])
        t1 = max([t1] + [iv[2] for iv in intervals])
    tool_ivs = [
        iv for iv in _tool_windows_from_events(events)
        if t0 <= iv[1] <= t1 or t0 <= iv[2] <= t1
    ]
    intervals.extend(tool_ivs)
    phases = _sweep(intervals, t0, t1)

    # Conveyor overlap: the stretch of each early-launched tool window
    # that ran concurrently with decode. The sweep hides it by design
    # (its phases partition wall clock, and concurrent time IS decode),
    # so it is surfaced as separate windows rather than a phase.
    decode_ivs = [(a, b) for ph, a, b, _ in intervals
                  if ph == "decode_active"]
    overlap_windows: list[dict[str, Any]] = []
    for _ph, a, b, attrs in tool_ivs:
        if not attrs.get("conveyor"):
            continue
        for da, db in decode_ivs:
            oa, ob = max(a, da), min(b, db)
            if ob - oa > 1e-6:
                overlap_windows.append({
                    "tool": attrs.get("tool"),
                    "start_ms": round((oa - t0) * 1e3, 3),
                    "end_ms": round((ob - t0) * 1e3, 3),
                    "duration_ms": round((ob - oa) * 1e3, 3),
                })
    overlap_windows.sort(key=lambda w: w["start_ms"])
    tool_overlap_ms = round(
        sum(w["duration_ms"] for w in overlap_windows), 3
    )

    total_ms = max(1e-9, (t1 - t0) * 1e3)
    by_phase: dict[str, float] = {}
    for s in phases:
        by_phase[s["phase"]] = by_phase.get(s["phase"], 0.0) + s["duration_ms"]
    goodput = {
        p: round(by_phase.get(p, 0.0) / total_ms, 4)
        for p in ("decode_active", "tool_blocked", "queued", "prefill", "host")
    }
    goodput["coverage"] = round(sum(by_phase.values()) / total_ms, 4)

    ev_out = []
    for e in events:
        d = dict(e)
        d["t_ms"] = round((d.pop("ts") - t0) * 1e3, 3)
        ev_out.append(d)

    # Wall-clock anchor for cross-process stitching: perf_counter spans
    # are process-local, so the fleet stitcher needs the absolute wall
    # instant of this timeline's origin. Derived from any flight event
    # (which carries both clocks), else from the current instant — both
    # clocks advance at the same rate, so the conversion holds.
    anchor = next((e for e in events if "wall" in e), None)
    if anchor is not None:
        t0_wall = anchor["wall"] - (anchor["ts"] - t0)
    else:
        t0_wall = time.time() - (now - t0)

    # Replica-tagged hop windows: the router stamps every dispatched leg
    # with a fleet hop, the frontend tags the adopted span tree with the
    # serving replica (root attrs for leg 1, nested fleet_hop spans for
    # later legs), and the stitcher uses these windows to split a shared
    # in-process trace into per-replica lanes.
    fleet_legs: list[dict[str, Any]] = []
    if trace is not None:
        if trace.root.attrs.get("replica"):
            fleet_legs.append({
                "replica": str(trace.root.attrs["replica"]),
                "hop": str(trace.root.attrs.get("hop", "route")),
                "start_ms": 0.0,
                "end_ms": round((t1 - t0) * 1e3, 3),
            })
        for c in list(trace.root.children):
            if c.name != "fleet_hop":
                continue
            c1 = c.t1 if c.t1 is not None else now
            fleet_legs.append({
                "replica": str(c.attrs.get("replica", "")),
                "hop": str(c.attrs.get("hop", "")),
                "start_ms": round((c.t0 - t0) * 1e3, 3),
                "end_ms": round((c1 - t0) * 1e3, 3),
            })
    return {
        "request_id": request_id,
        "t0_wall": t0_wall,
        "fleet_legs": fleet_legs,
        "duration_ms": round(total_ms, 3),
        "finished": trace.finished if trace is not None else None,
        # Distinct engine generations this request's events span: one
        # plus observed restarts. seq_ids alone cannot tell (one agent
        # request legitimately wears one seq_id per llm turn).
        "engine_generations": restarts + 1,
        "engine_restarts": restarts,
        "seq_ids": sorted(seq_ids),
        "goodput": goodput,
        "phases": phases,
        "tool_overlap_ms": tool_overlap_ms,
        "overlap_windows": overlap_windows,
        "events": ev_out,
    }


# -- rendering ----------------------------------------------------------------
_BAR = "#"
_PAD = "."


def render_gantt(timeline: dict[str, Any], width: int = 64) -> str:
    """ASCII Gantt of a timeline dict (the `opsagent timeline` CLI body;
    pure string math so tests drive it without a terminal)."""
    total = max(1e-9, float(timeline.get("duration_ms", 0.0)))
    lines = [
        f"timeline {timeline.get('request_id', '?')}  "
        f"{total:.1f} ms total"
        + (
            f"  ({timeline['engine_generations']} engine generations)"
            if timeline.get("engine_generations", 1) > 1 else ""
        )
    ]
    g = timeline.get("goodput", {})
    if g:
        lines.append(
            "goodput: "
            + "  ".join(
                f"{p} {100.0 * g.get(p, 0.0):.1f}%"
                for p in (
                    "decode_active", "tool_blocked", "queued", "prefill",
                    "host",
                )
                if g.get(p)
            )
            + f"  (coverage {100.0 * g.get('coverage', 0.0):.1f}%)"
        )
    overlaps = timeline.get("overlap_windows") or []
    name_w = max(
        [len(p.get("phase", "")) for p in timeline.get("phases", [])]
        + ([len("tool_overlap")] if overlaps else [])
        + [5]
    )

    def _row(name: str, start_ms: float, end_ms: float, dur_ms: float,
             tag: str) -> str:
        a = int(round(start_ms / total * width))
        b = int(round(end_ms / total * width))
        b = min(width, max(b, a + 1))
        bar = _PAD * a + _BAR * (b - a) + _PAD * (width - b)
        return f"{name:<{name_w}s} |{bar}| {dur_ms:8.1f} ms{tag}"

    for seg in timeline.get("phases", []):
        attrs = seg.get("attrs") or {}
        tag = f" tool={attrs['tool']}" if attrs.get("tool") else ""
        lines.append(_row(
            seg["phase"], seg["start_ms"], seg["end_ms"],
            seg["duration_ms"], tag,
        ))
    # Conveyor windows: tool run time hidden under decode, drawn as extra
    # rows so the tool bar visibly overlaps the decode span above.
    for w in overlaps:
        tag = f" tool={w['tool']}" if w.get("tool") else ""
        lines.append(_row(
            "tool_overlap", w["start_ms"], w["end_ms"],
            w["duration_ms"], tag,
        ))
    if overlaps:
        lines.append(
            f"tool overlap hidden behind decode: "
            f"{timeline.get('tool_overlap_ms', 0.0):.1f} ms"
        )
    return "\n".join(lines)


# -- fleet stitching -----------------------------------------------------------
def _union_ms(spans: list[tuple[float, float]]) -> float:
    """Total length of the union of [a, b] intervals (units in == out)."""
    total = 0.0
    end = float("-inf")
    for a, b in sorted(spans):
        if b <= end:
            continue
        total += b - max(a, end)
        end = b
    return total


def _lane_from_hops(
    hops: list[dict[str, Any]], wall: float
) -> str | None:
    """The replica of the latest router hop dispatched at or before
    ``wall`` — the time-partition fallback when a segment carries no
    replica tag of its own."""
    lane = None
    for h in sorted(hops, key=lambda h: h.get("wall", 0.0)):
        if h.get("replica") and h.get("wall", 0.0) <= wall + 1e-6:
            lane = h["replica"]
    if lane is None and hops:
        lane = hops[0].get("replica")
    return lane


def stitch_fleet(
    request_id: str,
    sources: dict[str, dict[str, Any]],
    journey: dict[str, Any] | None = None,
    offsets: dict[str, float] | None = None,
    reaped: list[str] | None = None,
    events: list[dict[str, Any]] | None = None,
) -> dict[str, Any]:
    """Merge per-replica ``assemble()`` timelines into one fleet journey.

    ``sources`` maps a replica id — or the sentinel ``"_shared"`` for an
    in-process fleet whose replicas share this process's trace store — to
    that replica's timeline dict. Remote segments are shifted onto the
    router's clock by subtracting ``offsets[replica]`` (replica wall
    MINUS router wall, registry.clock_offsets) before ordering, so two
    replicas' lanes interleave correctly even with skewed wall clocks;
    the ``"_shared"`` source is already on the router's clock and is
    split into lanes by its replica-tagged ``fleet_legs`` windows (hop
    time-partition as fallback). ``journey`` is the router's participants
    record (t0_wall / shape / replicas / hops), ``events`` the merged
    flight events already attributed to this request (router windows —
    failover, hedge, retry, fault-in — are derived from them), and
    ``reaped`` names participants the registry no longer knows: their
    segments are lost and the stitch degrades to the survivors, loudly.
    """
    offsets = offsets or {}
    journey = journey or {}
    events = events or []
    reaped = list(reaped or [])
    hops = list(journey.get("hops") or [])

    def _ev_wall(e: dict[str, Any]) -> float:
        return e.get("wall_corrected", e.get("wall", 0.0))

    # Per-source phase segments -> absolute router-frame wall seconds.
    raw: list[dict[str, Any]] = []
    for src, tl in sources.items():
        t0w = tl.get("t0_wall")
        if t0w is None:
            continue
        legs = sorted(
            tl.get("fleet_legs") or [], key=lambda g: g["start_ms"]
        )
        src_off = 0.0 if src == "_shared" else offsets.get(src, 0.0)
        for seg in tl.get("phases", []):
            mid = (seg["start_ms"] + seg["end_ms"]) / 2.0
            lane = None
            if src != "_shared":
                lane = src
            else:
                # Innermost (latest-starting) replica-tagged leg window
                # containing the segment midpoint: failover legs nest
                # inside the journey root's window, so the latest match
                # is the replica that actually ran this segment.
                for leg in legs:
                    if (
                        leg.get("replica")
                        and leg["start_ms"] - 1e-6 <= mid
                        <= leg["end_ms"] + 1e-6
                    ):
                        lane = leg["replica"]
                if lane is None:
                    lane = _lane_from_hops(hops, t0w + mid / 1e3)
            raw.append({
                "replica": lane or "?",
                "phase": seg["phase"],
                "a": t0w + seg["start_ms"] / 1e3 - src_off,
                "b": t0w + seg["end_ms"] / 1e3 - src_off,
                **(
                    {"attrs": seg["attrs"]} if seg.get("attrs") else {}
                ),
            })

    # Router-side windows from the journey's flight events: what the
    # replicas' own lanes can never show (the gap between a dying leg
    # and its failover re-dispatch, hedge launches, peer fault-in
    # fetch windows, the routing interval before the first dispatch).
    windows: list[dict[str, Any]] = []
    evs = sorted(events, key=_ev_wall)
    hop_walls = sorted(h.get("wall", 0.0) for h in hops)

    def _next_hop_after(w: float) -> float | None:
        for hw in hop_walls:
            if hw > w:
                return hw
        return None

    jt0 = journey.get("t0_wall")
    if jt0 is not None and hop_walls:
        windows.append({
            "kind": "routing", "a": jt0, "b": max(jt0, hop_walls[0]),
        })
    open_fault: list[dict[str, Any]] = []
    for e in evs:
        k, w = e.get("kind"), _ev_wall(e)
        if k == "failover":
            nxt = _next_hop_after(w)
            windows.append({
                "kind": "failover", "a": w,
                "b": nxt if nxt is not None else w,
                "replica": e.get("replica"),
            })
        elif k == "fleet_retry":
            nxt = _next_hop_after(w)
            windows.append({
                "kind": "retry", "a": w,
                "b": nxt if nxt is not None else w,
                "replica": e.get("replica"),
            })
        elif k == "fleet_hedge":
            windows.append({
                "kind": "hedge", "a": w, "b": w,
                "primary": e.get("primary"), "backup": e.get("backup"),
            })
        elif k == "page_fault_in":
            if e.get("phase") == "enter":
                open_fault.append(e)
            elif e.get("phase") == "exit" and open_fault:
                ent = open_fault.pop()
                windows.append({
                    "kind": "fault_in", "a": _ev_wall(ent), "b": w,
                    "replica": e.get("replica"),
                    "outcome": e.get("outcome"),
                    "pages": e.get("pages", 0),
                })

    anchors = [s["a"] for s in raw] + [w["a"] for w in windows]
    ends = [s["b"] for s in raw] + [w["b"] for w in windows]
    if jt0 is not None and (anchors or ends):
        anchors.append(jt0)
    if not anchors:
        return {
            "request_id": request_id, "fleet": True,
            "shape": journey.get("shape", "direct"),
            "replicas": [], "reaped": reaped, "clock_offset_ms": {},
            "t0_wall": jt0, "duration_ms": 0.0,
            "goodput": {"coverage": 0.0}, "coverage": 0.0,
            "lanes": {}, "segments": [], "windows": [], "events": [],
        }
    T0 = min(anchors)
    T1 = max(ends) if ends else T0
    total_ms = max(1e-9, (T1 - T0) * 1e3)

    def _rel(x: float) -> float:
        return round((x - T0) * 1e3, 3)

    ordered = sorted(raw, key=lambda s: (s["a"], s["b"]))
    lanes: dict[str, list[dict[str, Any]]] = {}
    for s in ordered:
        seg = {
            "replica": s["replica"], "phase": s["phase"],
            "start_ms": _rel(s["a"]), "end_ms": _rel(s["b"]),
            "duration_ms": round((s["b"] - s["a"]) * 1e3, 3),
        }
        if "attrs" in s:
            seg["attrs"] = s["attrs"]
        lanes.setdefault(s["replica"], []).append(seg)

    # Flattened, monotonic, non-overlapping segment list: the stitched
    # cross-replica ordering. Residual overlaps (clock-offset estimate
    # jitter, or a hedge loser's concurrent probe) clamp to the previous
    # segment's end; a fully-swallowed segment drops out.
    flat: list[dict[str, Any]] = []
    cursor = float("-inf")
    for s in ordered:
        a, b = max(s["a"], cursor), s["b"]
        if b - a <= 1e-9:
            continue
        flat.append({
            "replica": s["replica"], "phase": s["phase"],
            "start_ms": _rel(a), "end_ms": _rel(b),
            "duration_ms": round((b - a) * 1e3, 3),
        })
        cursor = b

    cov_union = _union_ms(
        [(s["a"], s["b"]) for s in raw]
        + [(w["a"], w["b"]) for w in windows]
    )
    coverage = (
        round(min(1.0, cov_union / (T1 - T0)), 4) if T1 > T0 else 1.0
    )
    by_phase: dict[str, float] = {}
    for s in flat:
        by_phase[s["phase"]] = (
            by_phase.get(s["phase"], 0.0) + s["duration_ms"]
        )
    goodput = {
        p: round(v / total_ms, 4) for p, v in sorted(by_phase.items())
    }
    goodput["coverage"] = coverage

    win_out = []
    for w in sorted(windows, key=lambda w: (w["a"], w["b"])):
        d = {k: v for k, v in w.items() if k not in ("a", "b")}
        d["start_ms"] = _rel(w["a"])
        d["end_ms"] = _rel(w["b"])
        d["duration_ms"] = round((w["b"] - w["a"]) * 1e3, 3)
        win_out.append(d)
    ev_out = []
    for e in evs:
        d = dict(e)
        d.pop("ts", None)
        d["t_ms"] = _rel(_ev_wall(e))
        ev_out.append(d)

    replicas = [
        r for r in (journey.get("replicas") or []) if r in lanes
    ]
    replicas += [r for r in lanes if r not in replicas]
    return {
        "request_id": request_id,
        "fleet": True,
        "shape": journey.get("shape", "direct"),
        "replicas": replicas,
        "reaped": reaped,
        "clock_offset_ms": {
            r: round(offsets.get(r, 0.0) * 1e3, 3) for r in replicas
        },
        "t0_wall": T0,
        "duration_ms": round(total_ms, 3),
        "goodput": goodput,
        "coverage": coverage,
        "lanes": lanes,
        "segments": flat,
        "windows": win_out,
        "events": ev_out,
    }


def render_fleet_gantt(stitched: dict[str, Any], width: int = 64) -> str:
    """ASCII multi-lane Gantt of a stitched fleet journey: one lane of
    rows per participating replica plus the router/fleet windows, all on
    one shared (skew-corrected) time axis."""
    total = max(1e-9, float(stitched.get("duration_ms", 0.0)))
    replicas = stitched.get("replicas") or []
    lines = [
        f"fleet journey {stitched.get('request_id', '?')}  "
        f"{total:.1f} ms total  shape={stitched.get('shape', 'direct')}  "
        f"replicas={len(replicas)}"
    ]
    reaped = stitched.get("reaped") or []
    if reaped:
        lines.append(
            "degraded: participant(s) reaped, segments lost: "
            + ", ".join(reaped)
        )
    offs = stitched.get("clock_offset_ms") or {}
    if any(offs.values()):
        lines.append(
            "clock offsets vs router: "
            + "  ".join(f"{r} {v:+.3f} ms" for r, v in offs.items())
        )
    g = stitched.get("goodput", {})
    if g:
        lines.append(
            "goodput: "
            + "  ".join(
                f"{p} {100.0 * v:.1f}%"
                for p, v in g.items() if p != "coverage" and v
            )
            + f"  (coverage {100.0 * g.get('coverage', 0.0):.1f}%)"
        )
    lanes = stitched.get("lanes") or {}
    windows = stitched.get("windows") or []
    name_w = max(
        [len(s["phase"]) for segs in lanes.values() for s in segs]
        + [len(w["kind"]) for w in windows] + [5]
    )

    def _row(name: str, start_ms: float, end_ms: float, dur_ms: float,
             tag: str = "") -> str:
        a = int(round(start_ms / total * width))
        b = int(round(end_ms / total * width))
        b = min(width, max(b, a + 1))
        bar = _PAD * a + _BAR * (b - a) + _PAD * (width - b)
        return f"  {name:<{name_w}s} |{bar}| {dur_ms:8.1f} ms{tag}"

    for r in replicas:
        lines.append(f"lane {r}:")
        for seg in lanes.get(r, []):
            lines.append(_row(
                seg["phase"], seg["start_ms"], seg["end_ms"],
                seg["duration_ms"],
            ))
    if windows:
        lines.append("router/fleet windows:")
        for w in windows:
            tag = ""
            if w.get("replica"):
                tag = f" replica={w['replica']}"
            if w["kind"] == "fault_in" and w.get("pages") is not None:
                tag += f" pages={w['pages']}"
            lines.append(_row(
                w["kind"], w["start_ms"], w["end_ms"],
                w["duration_ms"], tag,
            ))
    return "\n".join(lines)
