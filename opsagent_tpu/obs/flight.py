"""Serving flight recorder: a bounded ring of structured engine /
scheduler / server events, auto-dumped to JSONL on anomaly.

PR 1's metrics answer "how slow" (histograms, counters) and traces answer
"where inside one request" — neither answers "what happened in the seconds
BEFORE it got slow": what the dispatch composition was, whether a
post-warmup compile landed, whether the engine restarted, which requests
were admitted and in what order. The round-5 verdict's two standing
failures (7.5 s sessions p50 TTFT, 23 s cold restart) were both diagnosed
after the fact from scattered logs; the flight recorder keeps that
context resident so the diagnosis is one endpoint read.

Design:

- **Bounded + cheap**: one ``deque(maxlen=...)`` append under a lock per
  event; events are plain dicts (monotonic ``ts`` for ordering, wall
  ``wall`` for correlating with external logs). The hot loop records one
  event per *device dispatch*, not per token, so the overhead is noise
  next to the dispatch itself.
- **Recorded from every layer**: admission / dispatch composition /
  preemption / prefix eviction / finish (engine), restart + request
  errors (scheduler), tool execution (agent loop), compile events
  (compile watchdog below).
- **Dumpable**: ``GET /api/debug/flight`` on both servers returns the
  ring; on anomaly the ring is written to a JSONL file under
  ``$OPSAGENT_FLIGHT_DIR`` (default ``logs/flight``) so a crash or
  restart cannot lose the context that explains it.

Anomaly triggers (each rate-limited so a storm cannot fill the disk):

- a **post-warmup XLA compile** (the r04 sessions pathology: serving
  windows silently paying ~1 s remote-compile round trips);
- **TTFT over threshold** (``$OPSAGENT_SLO_TTFT_MS``, default 500 — the
  north-star p50 target doubles as the per-request alarm line);
- an **engine restart** (slice-restart recovery engaged);
- a **request error** (admission failure / stream-callback death).

The compile watchdog also lives here: ``jax.monitoring`` listeners feed
labeled compile counters/histograms so "zero post-warmup compiles" is a
live ``/metrics`` gauge (``opsagent_post_warmup_compiles``) instead of a
test-only assertion. ``Engine.warmup`` wraps its body in
``warmup_phase()``; compiles before the first completed warmup count as
phase "startup", compiles inside it as "warmup", and anything after is
"serving" — an anomaly.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Iterator

from ..utils.logger import get_logger

log = get_logger("obs.flight")

_ENV_DIR = "OPSAGENT_FLIGHT_DIR"
_ENV_CAPACITY = "OPSAGENT_FLIGHT_CAPACITY"
_ENV_DUMP_INTERVAL = "OPSAGENT_FLIGHT_DUMP_INTERVAL_S"
_ENV_TTFT_MS = "OPSAGENT_SLO_TTFT_MS"
_ENV_SAMPLE = "OPSAGENT_FLIGHT_SAMPLE"
_ENV_ANOMALY_HOLD = "OPSAGENT_FLIGHT_ANOMALY_HOLD_S"

DEFAULT_CAPACITY = 2048
DEFAULT_DUMP_INTERVAL_S = 5.0
DEFAULT_ANOMALY_HOLD_S = 2.0


def _parse_sample_spec(spec: str) -> dict[str, int]:
    """``"admission=8,dispatch=16"`` -> per-kind keep-1-in-N rates.
    Rates <= 1 (and junk) are dropped: 1-in-1 is just "record"."""
    rates: dict[str, int] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        kind, _, val = part.partition("=")
        try:
            rate = int(val)
        except ValueError:
            continue
        if kind.strip() and rate > 1:
            rates[kind.strip()] = rate
    return rates


def flight_dir() -> str:
    return os.environ.get(_ENV_DIR) or "logs/flight"


def ttft_threshold_s() -> float:
    """The per-request TTFT alarm line in seconds (the p50 SLO target
    doubles as the anomaly trigger: any single request past it is worth a
    ring dump, because p50 breaches are made of such requests)."""
    try:
        return float(os.environ.get(_ENV_TTFT_MS, "500")) / 1e3
    except ValueError:
        return 0.5


class FlightRecorder:
    """Thread-safe bounded event ring with anomaly-triggered JSONL dumps."""

    def __init__(
        self,
        capacity: int | None = None,
        dump_interval_s: float | None = None,
    ):
        if capacity is None:
            try:
                capacity = int(os.environ.get(_ENV_CAPACITY, ""))
            except ValueError:
                capacity = 0
            capacity = capacity or DEFAULT_CAPACITY
        self.capacity = capacity
        self.dump_interval_s = (
            DEFAULT_DUMP_INTERVAL_S if dump_interval_s is None
            else dump_interval_s
        )
        self._lock = threading.Lock()
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._seq = 0              # monotonically increasing event id
        self._dropped = 0          # events evicted by the ring bound
        self._anomalies = 0
        self._last_dump_s = 0.0    # perf_counter of the last JSONL dump
        self.last_dump_path: str | None = None
        # Flood control: a fan-out admission wave emits thousands of
        # admission/dispatch events in seconds — enough to wrap the ring
        # and evict the anomaly context it exists to keep. Per-kind
        # keep-1-in-N sampling throttles the high-volume kinds; for
        # ``anomaly_hold_s`` after any anomaly the sampling is suspended
        # so anomaly-adjacent events are always retained verbatim.
        self._sample_rates = _parse_sample_spec(
            os.environ.get(_ENV_SAMPLE, "")
        )
        self._kind_seen: dict[str, int] = {}
        self._sampled_out: dict[str, int] = {}
        self._retain_until = 0.0   # perf_counter deadline of the hold-off
        try:
            self.anomaly_hold_s = float(
                os.environ.get(_ENV_ANOMALY_HOLD, "")
            )
        except ValueError:
            self.anomaly_hold_s = DEFAULT_ANOMALY_HOLD_S

    # -- recording ---------------------------------------------------------
    def set_sample_rate(self, kind: str, rate: int) -> None:
        """Keep 1 in ``rate`` events of ``kind`` (rate <= 1 restores
        full recording). The fan-out orchestrator raises rates on the
        high-volume kinds for the duration of its admission wave."""
        with self._lock:
            if rate > 1:
                self._sample_rates[kind] = int(rate)
            else:
                self._sample_rates.pop(kind, None)
                self._kind_seen.pop(kind, None)

    def record(self, kind: str, **fields: Any) -> dict[str, Any]:
        """Append one event. ``fields`` must be JSON-serializable (the
        dump path str()s anything that is not, rather than losing the
        ring to one exotic attr). Kinds under a sample rate are recorded
        1-in-N (suppressed events are counted in stats, not ringed),
        except inside the post-anomaly hold-off window, where everything
        is retained."""
        ev = {
            "ts": time.perf_counter(),
            "wall": time.time(),
            "kind": kind,
        }
        ev.update(fields)
        with self._lock:
            rate = self._sample_rates.get(kind)
            if rate and ev["ts"] >= self._retain_until:
                seen = self._kind_seen.get(kind, 0)
                self._kind_seen[kind] = seen + 1
                if seen % rate != 0:
                    self._sampled_out[kind] = \
                        self._sampled_out.get(kind, 0) + 1
                    return ev
            self._seq += 1
            ev["id"] = self._seq
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(ev)
        return ev

    def anomaly(
        self, reason: str, count: bool = True, **fields: Any
    ) -> str | None:
        """Record an anomaly event and dump the ring to JSONL (rate-
        limited). Returns the dump path, or None when rate-limited /
        dump-failed. Never raises: the flight recorder must not add a
        failure mode to the path it is observing. ``count=False`` skips
        the opsagent_anomalies_total increment — for callers that run at
        SCRAPE time (the SLO collector), where mutating a scrape-visible
        counter would make consecutive renders of an idle registry
        disagree."""
        # Anomaly-adjacent events must survive flood control: suspend
        # per-kind sampling for the hold-off window so the events that
        # explain (and follow) the anomaly land in the ring verbatim.
        with self._lock:
            self._retain_until = max(
                self._retain_until,
                time.perf_counter() + self.anomaly_hold_s,
            )
        ev = self.record("anomaly", reason=reason, **fields)
        if count:
            try:
                from . import ANOMALIES

                ANOMALIES.inc(reason=reason)
            except Exception:  # noqa: BLE001
                pass
        # Anomalies pin traces: tail-based retention must keep the trace
        # of any request that breached/errored, and every per-request
        # anomaly caller already passes request_id here — one hook covers
        # ttft_breach, request_error, admission_failed, and slo_breach.
        try:
            from . import trace as _trace

            _trace.mark_anomalous(fields.get("request_id"), reason=reason)
        except Exception:  # noqa: BLE001
            pass
        now = time.perf_counter()
        with self._lock:
            if now - self._last_dump_s < self.dump_interval_s:
                return None
            self._last_dump_s = now
        try:
            return self._dump_jsonl(reason, ev)
        except Exception as e:  # noqa: BLE001 - observability must not kill serving
            log.warning("flight dump failed: %s", e)
            return None

    def _dump_jsonl(self, reason: str, trigger: dict[str, Any]) -> str:
        d = flight_dir()
        os.makedirs(d, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        path = os.path.join(
            d, f"flight-{stamp}-{trigger['id']}-{_slug(reason)}.jsonl"
        )
        events = self.snapshot()
        head = {
            "kind": "dump_header",
            "reason": reason,
            "trigger_id": trigger["id"],
            "wall": time.time(),
            "events": len(events),
            "dropped": self._dropped,
            "capacity": self.capacity,
        }
        with open(path, "w") as f:
            f.write(json.dumps(head, default=str) + "\n")
            for ev in events:
                f.write(json.dumps(ev, default=str) + "\n")
            for extra in self._dump_context(trigger):
                f.write(json.dumps(extra, default=str) + "\n")
        self.last_dump_path = path
        log.warning(
            "flight recorder dumped %d events to %s (reason: %s)",
            len(events), path, reason,
        )
        return path

    def _dump_context(self, trigger: dict[str, Any]) -> list[dict[str, Any]]:
        """Postmortem context appended to every anomaly dump so the JSONL
        is self-contained: the goodput ledger's attribution snapshot
        (bytes-by-kind, MFU, drift — where THIS window's device time was
        going), and, when the trigger names a request, that request's
        assembled timeline (SLO-breach and TTFT-breach dumps then carry
        the whole story: ring + attribution + per-phase wall clock).
        Best-effort: a failure here must never lose the event dump."""
        out: list[dict[str, Any]] = []
        try:
            from . import attribution

            out.append({
                "kind": "attribution_snapshot", **attribution.snapshot(),
            })
        except Exception:  # noqa: BLE001
            pass
        try:
            from . import history as _history

            # The last 60 s of every tracked series at the 1 s tier: the
            # lead-up to the anomaly (goodput collapse, queue growth, a
            # shed burst) rides the dump, so a postmortem needs no live
            # scrape to see the trajectory.
            h = _history.get_history().query(since=60.0, step=1.0)
            if any(s["points"] for s in h.get("series", {}).values()):
                out.append({"kind": "history", **h})
        except Exception:  # noqa: BLE001
            pass
        rid = trigger.get("request_id")
        if rid:
            try:
                from . import timeline

                tl = timeline.assemble(rid)
                if tl is not None:
                    tl.pop("events", None)  # the ring is already the dump
                    out.append({"kind": "timeline", **tl})
            except Exception:  # noqa: BLE001
                pass
            if _journey_provider is not None:
                # Fleet context: when a router lives in this process its
                # participants map knows which replicas served this
                # request and through which hops — the cross-replica
                # journey of the triggering request rides in the dump.
                try:
                    j = _journey_provider(rid)
                    if j:
                        out.append({
                            "kind": "fleet_journey", "request_id": rid,
                            **j,
                        })
                except Exception:  # noqa: BLE001
                    pass
        return out

    # -- reading -----------------------------------------------------------
    def snapshot(
        self, n: int | None = None, kind: str | None = None
    ) -> list[dict[str, Any]]:
        """The newest-last event list; ``n`` caps to the newest n after
        the optional kind filter."""
        with self._lock:
            events: Iterator[dict[str, Any]] | list = list(self._ring)
        if kind:
            events = [e for e in events if e.get("kind") == kind]
        if n is not None and n >= 0:
            events = list(events)[-n:]
        return list(events)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "events": len(self._ring),
                "capacity": self.capacity,
                "total_recorded": self._seq,
                "dropped": self._dropped,
                "sampled_out": dict(self._sampled_out),
                "sample_rates": dict(self._sample_rates),
                "last_dump_path": self.last_dump_path,
            }

    def reset(self) -> None:
        """Test-isolation hook: clear the ring, the dump rate limit, and
        the flood-control state (rates re-read from the environment)."""
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._dropped = 0
            self._last_dump_s = 0.0
            self.last_dump_path = None
            self._sample_rates = _parse_sample_spec(
                os.environ.get(_ENV_SAMPLE, "")
            )
            self._kind_seen.clear()
            self._sampled_out.clear()
            self._retain_until = 0.0


_recorder: FlightRecorder | None = None
_recorder_lock = threading.Lock()

# Optional fleet-journey lookup (request_id -> journey dict or None).
# A FleetRouter in this process registers its participants map here so
# anomaly dumps carry the cross-replica story of the triggering request.
_journey_provider: Any = None


def set_journey_provider(fn: Any) -> None:
    global _journey_provider
    _journey_provider = fn


def get_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record(kind: str, **fields: Any) -> None:
    """Module-level convenience onto the process-wide recorder."""
    get_recorder().record(kind, **fields)


def anomaly(reason: str, **fields: Any) -> str | None:
    return get_recorder().anomaly(reason, **fields)


def _slug(s: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "-" for c in s)[:48]


def request_id_of(span: Any) -> str | None:
    """The request ID behind an engine/scheduler span handle (obs.trace
    Span), or None. Events carry it so a dump can be filtered to one
    request's life."""
    try:
        return span.trace.request_id if span is not None else None
    except AttributeError:
        return None


# -- compile watchdog ---------------------------------------------------------
#
# jax.monitoring fires one duration event per real backend compile
# (never on jit-cache hits) and plain events for the persistent
# compilation cache's hit/miss bookkeeping. The listeners below turn
# those into labeled /metrics instruments and flight-ring events, and
# flag any compile that lands AFTER a completed warmup as an anomaly —
# the r04 sessions pathology (serving windows paying ~1 s remote-compile
# round trips) becomes a dump + counter instead of log archaeology.

_COMPILE_DURATION_EVENT = "/jax/core/compile/backend_compile_duration"
_CACHE_EVENT_PREFIX = "/jax/compilation_cache/"

_warmup_depth = 0          # >0 while Engine.warmup() runs (any engine)
_warmed = False            # at least one warmup completed in this process
_watch_lock = threading.Lock()
_listeners_installed = False


def compile_phase() -> str:
    """Phase label for a compile landing now: "warmup" inside a warmup
    call, "serving" after the first completed warmup (the anomalous
    case), "startup" before any warmup (unwarmed engines compile lazily
    by design)."""
    with _watch_lock:
        if _warmup_depth > 0:
            return "warmup"
        return "serving" if _warmed else "startup"


class warmup_phase:
    """Context manager bracketing Engine.warmup(): compiles inside count
    as phase "warmup"; on exit the process is marked warmed, so later
    compiles are "serving" anomalies. Re-entrant across engines."""

    def __enter__(self) -> "warmup_phase":
        global _warmup_depth
        with _watch_lock:
            _warmup_depth += 1
        return self

    def __exit__(self, *exc: Any) -> None:
        global _warmup_depth, _warmed
        with _watch_lock:
            _warmup_depth = max(0, _warmup_depth - 1)
            if exc[0] is None:
                _warmed = True


def warmed() -> bool:
    with _watch_lock:
        return _warmed


def reset_compile_watchdog() -> None:
    """Test-isolation hook: forget the warmed state so one test's warmup
    cannot turn every later test's lazy compile into an anomaly."""
    global _warmup_depth, _warmed
    with _watch_lock:
        _warmup_depth = 0
        _warmed = False


def _on_duration_event(name: str, *args: Any, **kwargs: Any) -> None:
    if name != _COMPILE_DURATION_EVENT:
        return
    duration = 0.0
    if args:
        try:
            duration = float(args[0])
        except (TypeError, ValueError):
            duration = 0.0
    phase = compile_phase()
    try:
        from . import COMPILE_SECONDS, COMPILES, POST_WARMUP_COMPILES

        COMPILES.inc(phase=phase)
        COMPILE_SECONDS.observe(duration, phase=phase)
        if phase == "serving":
            POST_WARMUP_COMPILES.inc()
    except Exception:  # noqa: BLE001 - never break jax's compile path
        return
    rec = get_recorder()
    rec.record("compile", phase=phase, duration_s=round(duration, 4))
    if phase == "serving":
        rec.anomaly("post_warmup_compile", duration_s=round(duration, 4))


def _on_plain_event(name: str, **kwargs: Any) -> None:
    if not name.startswith(_CACHE_EVENT_PREFIX):
        return
    try:
        from . import COMPILE_CACHE_EVENTS

        # e.g. cache_hits / cache_misses / task_disabled_cache
        COMPILE_CACHE_EVENTS.inc(event=name[len(_CACHE_EVENT_PREFIX):])
    except Exception:  # noqa: BLE001
        return


def install_compile_watchdog() -> None:
    """Register the jax.monitoring listeners once per process.
    jax.monitoring has no public deregistration, so this must be
    idempotent; the listeners themselves are no-ops for event names they
    do not own."""
    global _listeners_installed
    with _watch_lock:
        if _listeners_installed:
            return
        _listeners_installed = True
    try:
        import jax

        jax.monitoring.register_event_duration_secs_listener(
            _on_duration_event
        )
        jax.monitoring.register_event_listener(_on_plain_event)
    except Exception as e:  # noqa: BLE001 - jax-less import contexts
        log.warning("compile watchdog unavailable: %s", e)
