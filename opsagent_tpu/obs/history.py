"""Telemetry time machine: an in-process, memory-bounded history of the
key metric families, with tiered downsampling.

Every other observability surface in-tree is instantaneous — ``/metrics``
is a point-in-time scrape, ``/api/slo`` evaluates the current histograms,
the flight ring is a bounded event buffer. The questions operators
actually ask ("did goodput degrade by class during that burst?",
"per-class SLO attainment over the last hour") need the *time dimension*,
which normally means an external Prometheus nobody runs in CI. This
module keeps a small, dependency-free slice of it resident:

- A background sampler (or an explicit ``sample(now)`` call — tests walk
  a synthetic clock) snapshots selected series once per second.
- **Tiered downsampling**: 1 s resolution for the last 5 minutes, 10 s
  for the last hour, 60 s beyond — older points are merged, never
  silently dropped, until the byte bound evicts the oldest 60 s points.
- **Counters are stored as deltas** (the increment over each point's
  interval), so rates are exact at every tier: a 10 s point's delta is
  the sum of the ten 1 s deltas it replaced, and ``delta / step`` is the
  true mean rate of that interval. Gauges downsample by mean.
- Served as ``GET /api/metrics/history?series=&since=&step=`` on both
  servers, and fleet-aggregated (skew-corrected via the heartbeat
  ClockSync offsets) on the router.

Timestamps are wall-clock (``time.time()``) so the router can apply the
same ``wall - offset`` correction the fleet flight ledger and timeline
stitcher already use for cross-replica ordering.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from ..utils.logger import get_logger

log = get_logger("obs.history")

_ENV_BYTES = "OPSAGENT_HISTORY_BYTES"
_ENV_INTERVAL = "OPSAGENT_HISTORY_INTERVAL_S"

# (step_seconds, horizon_seconds): points older than a tier's horizon are
# rolled up into the next tier. The last tier has no horizon — it is
# bounded by DEFAULT_MAX_BYTES instead (oldest points evicted).
TIER_SPECS: tuple[tuple[float, float | None], ...] = (
    (1.0, 300.0),
    (10.0, 3600.0),
    (60.0, None),
)
DEFAULT_MAX_BYTES = 2 * 1024 * 1024
DEFAULT_INTERVAL_S = 1.0
# Conservative resident-size estimate of one [ts, value] point (two
# floats + list + deque slot overhead) — the byte bound is a budget, not
# an accounting exercise.
POINT_BYTES = 120


def _env_float(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


@dataclass
class _Series:
    name: str
    kind: str                      # "counter" (stored as deltas) | "gauge"
    fn: Callable[[], float | None]
    # One deque of [ts, value] per tier, oldest first. ts is the END of
    # the point's interval.
    tiers: list[deque] = field(default_factory=list)
    last_raw: float | None = None  # counters: previous cumulative value

    def __post_init__(self) -> None:
        if self.kind not in ("counter", "gauge"):
            raise ValueError(f"series kind {self.kind!r}")
        self.tiers = [deque() for _ in TIER_SPECS]


class TelemetryHistory:
    """Memory-bounded multi-series history ring with tiered downsampling.

    Thread-safe; ``sample``/``query`` take explicit ``now`` values so
    tests can walk a synthetic 90-minute clock without sleeping.
    """

    def __init__(
        self,
        max_bytes: int | None = None,
        interval_s: float | None = None,
    ):
        self.max_bytes = int(
            max_bytes if max_bytes is not None
            else _env_float(_ENV_BYTES, DEFAULT_MAX_BYTES)
        )
        self.interval_s = float(
            interval_s if interval_s is not None
            else _env_float(_ENV_INTERVAL, DEFAULT_INTERVAL_S)
        )
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self._samples = 0
        self._evicted = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- registration ------------------------------------------------------
    def register(
        self, name: str, kind: str, fn: Callable[[], float | None]
    ) -> None:
        """Idempotent: re-registering a name keeps the existing ring (the
        reader callable is refreshed — modules reload across tests)."""
        with self._lock:
            s = self._series.get(name)
            if s is not None:
                s.fn = fn
                s.kind = kind
                return
            self._series[name] = _Series(name=name, kind=kind, fn=fn)

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    # -- sampling ----------------------------------------------------------
    def sample(self, now: float | None = None) -> None:
        """Take one sweep: read every series, append tier-0 points, roll
        tiers, enforce the byte bound. Reader failures skip the series —
        history must never add a failure mode to what it observes."""
        if now is None:
            now = time.time()
        readings: list[tuple[_Series, float]] = []
        with self._lock:
            series = list(self._series.values())
        for s in series:
            try:
                raw = s.fn()
            except Exception:  # noqa: BLE001
                continue
            if raw is None:
                continue
            readings.append((s, float(raw)))
        with self._lock:
            for s, raw in readings:
                if s.kind == "counter":
                    prev, s.last_raw = s.last_raw, raw
                    if prev is None:
                        continue  # first sweep: no interval to delta over
                    s.tiers[0].append([now, max(0.0, raw - prev)])
                else:
                    s.tiers[0].append([now, raw])
            self._samples += 1
            self._rollup(now)
            self._enforce_bytes()
        self._export_gauges()

    def _rollup(self, now: float) -> None:
        """Promote points past each tier's horizon into the next tier,
        aligned to the coarser step. Counters sum their deltas (rates
        stay exact); gauges average. Caller holds the lock."""
        for s in self._series.values():
            for i in range(len(TIER_SPECS) - 1):
                _, horizon = TIER_SPECS[i]
                coarse_step = TIER_SPECS[i + 1][0]
                dq = s.tiers[i]
                while dq and now - dq[0][0] > horizon:
                    bucket = math.floor(dq[0][0] / coarse_step)
                    pts = []
                    while dq and math.floor(
                        dq[0][0] / coarse_step
                    ) == bucket:
                        pts.append(dq.popleft())
                    ts = pts[-1][0]
                    if s.kind == "counter":
                        v = sum(p[1] for p in pts)
                    else:
                        v = sum(p[1] for p in pts) / len(pts)
                    s.tiers[i + 1].append([ts, v])

    def _enforce_bytes(self) -> None:
        """Evict the oldest coarsest points while over budget. Caller
        holds the lock."""
        while self._bytes_locked() > self.max_bytes:
            oldest: _Series | None = None
            oldest_ts = math.inf
            for s in self._series.values():
                dq = s.tiers[-1]
                if dq and dq[0][0] < oldest_ts:
                    oldest_ts = dq[0][0]
                    oldest = s
            if oldest is None:
                # Nothing left in the coarse tier: evict from the next
                # finer tier that has points (a pathological byte bound).
                for tier in range(len(TIER_SPECS) - 2, -1, -1):
                    cands = [
                        s for s in self._series.values() if s.tiers[tier]
                    ]
                    if cands:
                        oldest = min(
                            cands, key=lambda s: s.tiers[tier][0][0]
                        )
                        oldest.tiers[tier].popleft()
                        self._evicted += 1
                        break
                else:
                    return
                continue
            oldest.tiers[-1].popleft()
            self._evicted += 1

    def _bytes_locked(self) -> int:
        n = sum(
            len(dq) for s in self._series.values() for dq in s.tiers
        )
        return n * POINT_BYTES

    def _export_gauges(self) -> None:
        try:
            from . import HISTORY_BYTES, HISTORY_POINTS, HISTORY_SAMPLES

            HISTORY_SAMPLES.inc()
            with self._lock:
                for i, (step, _) in enumerate(TIER_SPECS):
                    HISTORY_POINTS.set(
                        sum(
                            len(s.tiers[i])
                            for s in self._series.values()
                        ),
                        tier=f"{int(step)}s",
                    )
                HISTORY_BYTES.set(self._bytes_locked())
        except Exception:  # noqa: BLE001
            pass

    # -- querying ----------------------------------------------------------
    def query(
        self,
        series: list[str] | None = None,
        since: float = 300.0,
        step: float | None = None,
        now: float | None = None,
    ) -> dict[str, Any]:
        """Points for ``series`` (all when empty) newer than ``now -
        since``, merged across tiers oldest-first. With ``step``, points
        are re-bucketed to that resolution (counters sum deltas, gauges
        average) — asking for a coarser step than the native tier is
        exact for counters by construction."""
        if now is None:
            now = time.time()
        cutoff = now - max(0.0, since)
        out: dict[str, Any] = {}
        with self._lock:
            wanted = (
                [n for n in series if n in self._series]
                if series else sorted(self._series)
            )
            for name in wanted:
                s = self._series[name]
                pts = [
                    [p[0], p[1]]
                    for dq in reversed(s.tiers)
                    for p in dq
                    if p[0] >= cutoff
                ]
                pts.sort(key=lambda p: p[0])
                if step and step > 0:
                    pts = _rebucket(pts, step, s.kind)
                out[name] = {"kind": s.kind, "points": pts}
        return {
            "now": now,
            "since": since,
            "step": step,
            "tiers": [
                {"step_s": t[0], "horizon_s": t[1]} for t in TIER_SPECS
            ],
            "series": out,
        }

    def rate(
        self,
        name: str,
        window_s: float = 60.0,
        now: float | None = None,
        min_points: int = 2,
    ) -> float | None:
        """Mean per-second rate of a counter series over the trailing
        window: summed deltas over the covered span. None when fewer than
        ``min_points`` points cover the window (no fake rates from one
        sweep)."""
        if now is None:
            now = time.time()
        with self._lock:
            s = self._series.get(name)
            if s is None or s.kind != "counter":
                return None
            cutoff = now - window_s
            pts = [
                p for dq in s.tiers for p in dq if p[0] >= cutoff
            ]
        if len(pts) < min_points:
            return None
        pts.sort(key=lambda p: p[0])
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        # The first point's delta covers the interval BEFORE its
        # timestamp; drop it so the numerator matches the span.
        total = sum(p[1] for p in pts[1:])
        return max(0.0, total) / span

    def window_sum(
        self, name: str, window_s: float = 60.0, now: float | None = None
    ) -> float:
        """Summed counter deltas over the trailing window (0.0 when the
        series is unknown or empty)."""
        if now is None:
            now = time.time()
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return 0.0
            cutoff = now - window_s
            return sum(
                p[1] for dq in s.tiers for p in dq if p[0] >= cutoff
            )

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "series": len(self._series),
                "samples": self._samples,
                "evicted": self._evicted,
                "bytes": self._bytes_locked(),
                "max_bytes": self.max_bytes,
                "points_per_tier": [
                    sum(
                        len(s.tiers[i]) for s in self._series.values()
                    )
                    for i in range(len(TIER_SPECS))
                ],
                "running": self._thread is not None,
            }

    # -- background sampler ------------------------------------------------
    def start(self) -> None:
        """Idempotent background 1 Hz sampler (servers call this beside
        the SLO watchdog's start)."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="telemetry-history"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 - sampler must survive
                log.exception("history sample failed")

    def reset(self) -> None:
        """Test-isolation hook: drop every point and counter baseline
        (registered series and the running sampler survive)."""
        with self._lock:
            for s in self._series.values():
                for dq in s.tiers:
                    dq.clear()
                s.last_raw = None
            self._samples = 0
            self._evicted = 0


def _rebucket(
    pts: list[list[float]], step: float, kind: str
) -> list[list[float]]:
    """Re-bucket sorted [ts, value] points to ``step`` resolution: one
    point per occupied bucket, stamped at the bucket's end."""
    out: list[list[float]] = []
    acc: list[float] = []
    bucket: float | None = None
    for ts, v in pts:
        b = math.floor(ts / step)
        if bucket is not None and b != bucket:
            out.append(_close_bucket(bucket, step, acc, kind))
            acc = []
        bucket = b
        acc.append(v)
    if bucket is not None and acc:
        out.append(_close_bucket(bucket, step, acc, kind))
    return out


def _close_bucket(
    bucket: float, step: float, acc: list[float], kind: str
) -> list[float]:
    v = sum(acc) if kind == "counter" else sum(acc) / len(acc)
    return [(bucket + 1) * step, v]


# -- default series -----------------------------------------------------------
def _counter_total(c: Any) -> float:
    """Sum of every child of a labeled counter (the all-labels total)."""
    with c._lock:
        return float(sum(c._children.values()))


def _hist_quantile_ms(hist: Any, q: float, **labels: str):
    from . import slo

    v = slo.histogram_quantile(hist, q, **labels)
    return None if v is None else v * 1e3


def install_default_series(h: TelemetryHistory) -> None:
    """Register the selected families the tentpole names: goodput split,
    TTFT/ITL quantiles, occupancy/queue gauges, shed/failover/hedge
    rates, attribution MFU/HBM-util/drift, pagestore hits, per-class
    traffic. Idempotent."""
    import functools

    from . import (
        ANOMALIES,
        BATCH_OCCUPANCY,
        CLASS_ITL_SECONDS,
        CLASS_REQUESTS,
        CLASS_TTFT_SECONDS,
        DECODE_TOKENS,
        ENGINE_REQUESTS,
        FANOUT_ACTIVE,
        FANOUT_CHILDREN,
        FANOUT_CHILDREN_DONE,
        FANOUT_CHILDREN_TOTAL,
        FANOUT_PREFIX_HIT_RATE,
        FLEET_FAILOVERS,
        FLEET_HEDGES,
        FLEET_RETRIES,
        FLEET_SHED,
        ITL_SECONDS,
        KV_PAGE_UTILIZATION,
        PAGESTORE_LOOKUPS,
        PAGESTORE_REMOTE_HITS,
        RUNNING_SEQUENCES,
        SLO_CLASSES,
        TTFT_SECONDS,
        attribution,
    )

    for phase in ("queued", "prefill", "decode_active", "tool_blocked"):
        h.register(
            f"goodput.{phase}", "counter",
            functools.partial(
                attribution.GOODPUT_SECONDS.value, phase=phase
            ),
        )
    h.register("decode_tokens", "counter", DECODE_TOKENS.value)
    h.register(
        "requests.completed", "counter",
        functools.partial(ENGINE_REQUESTS.value, outcome="completed"),
    )
    h.register(
        "requests.bad", "counter",
        lambda: sum(
            ENGINE_REQUESTS.value(outcome=o)
            for o in ("error", "timeout", "admission_failed")
        ),
    )
    h.register(
        "fleet.shed", "counter", functools.partial(_counter_total, FLEET_SHED)
    )
    h.register("fleet.failovers", "counter", FLEET_FAILOVERS.value)
    h.register("fleet.retries", "counter", FLEET_RETRIES.value)
    h.register(
        "fleet.hedges", "counter",
        functools.partial(_counter_total, FLEET_HEDGES),
    )
    h.register("pagestore.lookups", "counter", PAGESTORE_LOOKUPS.value)
    h.register(
        "pagestore.remote_hits", "counter", PAGESTORE_REMOTE_HITS.value
    )
    h.register(
        "anomalies", "counter", functools.partial(_counter_total, ANOMALIES)
    )
    h.register(
        "ttft_p50_ms", "gauge",
        functools.partial(_hist_quantile_ms, TTFT_SECONDS, 0.5),
    )
    h.register(
        "ttft_p95_ms", "gauge",
        functools.partial(_hist_quantile_ms, TTFT_SECONDS, 0.95),
    )
    h.register(
        "itl_p50_ms", "gauge",
        functools.partial(_hist_quantile_ms, ITL_SECONDS, 0.5),
    )
    h.register(
        "itl_p95_ms", "gauge",
        functools.partial(_hist_quantile_ms, ITL_SECONDS, 0.95),
    )
    h.register("kv_page_utilization", "gauge", KV_PAGE_UTILIZATION.value)
    h.register("batch_occupancy", "gauge", BATCH_OCCUPANCY.value)
    h.register("running_sequences", "gauge", RUNNING_SEQUENCES.value)
    h.register("attr.mfu", "gauge", attribution.ATTR_MFU.value)
    h.register(
        "attr.hbm_utilization", "gauge", attribution.ATTR_HBM_UTIL.value
    )
    h.register("attr.drift", "gauge", attribution.ATTR_MODEL_DRIFT.value)
    for cls in SLO_CLASSES:
        h.register(
            f"class.{cls}.completed", "counter",
            functools.partial(
                CLASS_REQUESTS.value,
                **{"class": cls, "outcome": "completed"},
            ),
        )
        h.register(
            f"class.{cls}.bad", "counter",
            functools.partial(_class_bad, CLASS_REQUESTS, cls),
        )
        h.register(
            f"class.{cls}.ttft_p95_ms", "gauge",
            functools.partial(
                _hist_quantile_ms, CLASS_TTFT_SECONDS, 0.95,
                **{"class": cls},
            ),
        )
        h.register(
            f"class.{cls}.itl_p95_ms", "gauge",
            functools.partial(
                _hist_quantile_ms, CLASS_ITL_SECONDS, 0.95,
                **{"class": cls},
            ),
        )

    # Audit fan-out cockpit row (opsagent top): active fan-outs, children
    # done/planned of the newest one, its shared-prefix hit rate, and the
    # all-outcome child completion rate.
    h.register("fanout.active", "gauge", FANOUT_ACTIVE.value)
    h.register(
        "fanout.children_planned", "gauge", FANOUT_CHILDREN_TOTAL.value
    )
    h.register("fanout.children_done", "gauge", FANOUT_CHILDREN_DONE.value)
    h.register(
        "fanout.prefix_hit_rate", "gauge", FANOUT_PREFIX_HIT_RATE.value
    )
    h.register(
        "fanout.children", "counter",
        functools.partial(_counter_total, FANOUT_CHILDREN),
    )


def _class_bad(counter: Any, cls: str) -> float:
    return sum(
        counter.value(**{"class": cls, "outcome": o})
        for o in ("error", "timeout", "admission_failed", "shed")
    )


_history: TelemetryHistory | None = None
_history_lock = threading.Lock()


def get_history() -> TelemetryHistory:
    """The process-wide store, with the default series installed."""
    global _history
    if _history is None:
        with _history_lock:
            if _history is None:
                h = TelemetryHistory()
                install_default_series(h)
                _history = h
    return _history


def query(**kwargs: Any) -> dict[str, Any]:
    """Module-level convenience onto the process-wide store."""
    return get_history().query(**kwargs)


def parse_query(q: Any) -> dict[str, Any]:
    """``?series=&since=&step=`` URL-query strings -> ``query()`` kwargs
    (shared by both servers and the router so the grammar cannot drift).
    ``series`` is comma-separated; raises ValueError on malformed
    numbers."""
    kwargs: dict[str, Any] = {}
    series = (q.get("series") or "").strip()
    if series:
        kwargs["series"] = [s.strip() for s in series.split(",") if s.strip()]
    if q.get("since"):
        kwargs["since"] = float(q["since"])
    if q.get("step"):
        kwargs["step"] = float(q["step"])
    return kwargs


def reset() -> None:
    """Test-isolation hook: clear the singleton's points (no-op when the
    store was never created)."""
    if _history is not None:
        _history.reset()
