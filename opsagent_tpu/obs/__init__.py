"""Serving observability: Prometheus ``/metrics`` exposition, per-request
trace spans, and the shared instrument handles the engine/scheduler/server
layers record into.

Import surface:

- ``get_registry()`` / ``metrics_text()`` / ``metrics_snapshot()`` — the
  process-wide metrics registry and its exposition/snapshot forms.
- ``trace_request`` / ``span`` / ``current_span`` / ``get_trace`` — the
  per-request span-tree API (obs/trace.py).
- Module-level instrument handles (``TTFT_SECONDS`` etc.) — created once
  at import; every layer records into the same child samples.

The instrument names are the contract ``docs/observability.md`` documents;
renaming one is a dashboard-breaking change.
"""

from __future__ import annotations

from .metrics import (  # noqa: F401
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    escape_label_value,
    get_registry,
)
from .trace import (  # noqa: F401
    Span,
    Trace,
    current_span,
    format_tree,
    get_store,
    get_trace,
    new_request_id,
    span,
    trace_request,
)

_reg = get_registry()

# -- flight recorder + compile watchdog + SLO watchdog ------------------------
COMPILES = _reg.counter(
    "opsagent_xla_compiles_total",
    "Real XLA backend compiles by phase (startup/warmup/serving); "
    "phase=serving after a completed warmup is the anomaly",
    labelnames=("phase",),
)
COMPILE_SECONDS = _reg.histogram(
    "opsagent_xla_compile_seconds",
    "XLA backend compile wall time per executable, by phase",
    labelnames=("phase",),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
             60.0, 120.0),
)
POST_WARMUP_COMPILES = _reg.gauge(
    "opsagent_post_warmup_compiles",
    "XLA compiles AFTER a completed warmup — the live form of the "
    "zero-post-warmup-compiles invariant (healthy value: 0)",
)
# Materialize the healthy value: an absent gauge and "zero anomalous
# compiles" must not look the same on a scrape.
POST_WARMUP_COMPILES.set(0.0)
COMPILE_CACHE_EVENTS = _reg.counter(
    "opsagent_compile_cache_events_total",
    "Persistent compilation cache bookkeeping events "
    "(jax.monitoring /jax/compilation_cache/*)",
    labelnames=("event",),
)
ANOMALIES = _reg.counter(
    "opsagent_anomalies_total",
    "Flight-recorder anomaly triggers by reason (each one dumps the "
    "event ring to JSONL, rate-limited)",
    labelnames=("reason",),
)

# -- engine step telemetry ----------------------------------------------------
TTFT_SECONDS = _reg.histogram(
    "opsagent_ttft_seconds",
    "Time to first token per admitted request (admission to first sample)",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0),
)
ITL_SECONDS = _reg.histogram(
    "opsagent_inter_token_latency_seconds",
    "Latency between consecutive accepted tokens of one sequence",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5),
)
DECODE_TOKENS = _reg.counter(
    "opsagent_decode_tokens_total", "Tokens produced by decode steps"
)
PREFILL_TOKENS = _reg.counter(
    "opsagent_prefill_tokens_total", "Prompt tokens prefilled (cache misses)"
)
PREFIX_HIT_TOKENS = _reg.counter(
    "opsagent_prefix_hit_tokens_total",
    "Prompt tokens served from the prefix cache instead of prefill",
)
DECODE_DISPATCHES = _reg.counter(
    "opsagent_decode_dispatches_total",
    "Device decode dispatches by kind (block, single, speculative, mixed)",
    labelnames=("kind",),
)
MIXED_DECODE_LANES = _reg.histogram(
    "opsagent_mixed_dispatch_decode_lanes",
    "Decode lanes advanced per mixed prefill+decode dispatch",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64),
)
MIXED_PREFILL_TOKENS = _reg.histogram(
    "opsagent_mixed_dispatch_prefill_tokens",
    "Prefill chunk tokens piggybacked per mixed dispatch's weight stream",
    buckets=(0, 8, 16, 32, 64, 128, 256, 512),
)
MIXED_BUDGET_UTILIZATION = _reg.histogram(
    "opsagent_mixed_step_budget_utilization",
    "Fraction of max_step_tokens used per mixed dispatch (0..1)",
    buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
)
# -- async mixed serving runtime (serving/async_runtime.py) -------------------
STEP_HOST_GAP_SECONDS = _reg.histogram(
    "opsagent_step_host_gap_seconds",
    "Host-side gap between consecutive mixed-tick device dispatches "
    "(enqueue-return to next enqueue — time the device can go idle "
    "waiting on host work), by tick mode (sync = async_depth 1, "
    "async = one-step-lookahead pipeline)",
    labelnames=("mode",),
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.025, 0.05, 0.1, 0.25, 1.0),
)
ASYNC_INFLIGHT_DEPTH = _reg.gauge(
    "opsagent_async_inflight_depth",
    "Mixed-tick dispatches currently in flight (dispatched, uncommitted)",
)
ASYNC_COMMITS = _reg.counter(
    "opsagent_async_commits_total",
    "Async mixed ticks committed (token pull + host post-processing)",
)
ASYNC_OVERLAPPED_COMMITS = _reg.counter(
    "opsagent_async_overlapped_commits_total",
    "Commits whose host work ran while a newer dispatch was still in "
    "flight on device — the overlap the async runtime exists for",
)
ASYNC_OVERSHOOT_TOKENS = _reg.counter(
    "opsagent_async_overshoot_tokens_total",
    "Lookahead tokens discarded because their row had already finished "
    "(stop/EOS detection lags one tick; the page booking is rolled back)",
)
ASYNC_FALLBACKS = _reg.counter(
    "opsagent_async_fallbacks_total",
    "Async mixed ticks that settled the pipeline and fell back to a "
    "sync lane, by reason (hosted / fsm_mismatch / carry_break / "
    "ffwd_ineligible = constrained row that cannot fast-forward: "
    "hosted mask, no dense tables, or logprobs requested)",
    labelnames=("reason",),
)

FFWD_TOKENS = _reg.counter(
    "opsagent_ffwd_tokens_total",
    "Tokens emitted by grammar fast-forward (singleton-mask FSM states) "
    "without a per-token forward pass",
)
FFWD_RUNS = _reg.counter(
    "opsagent_ffwd_runs_total",
    "Forced-token runs spliced as multi-token appends by the grammar "
    "fast-forward path",
)
FFWD_SKIPPED_DISPATCHES = _reg.counter(
    "opsagent_ffwd_skipped_dispatches_total",
    "Decode dispatches the grammar fast-forward made unnecessary (one "
    "per forced token: that token would otherwise have cost a full "
    "forward pass)",
)

KV_PAGE_UTILIZATION = _reg.gauge(
    "opsagent_kv_page_utilization",
    "Fraction of KV-cache pages in use (0..1)",
)
KV_PAGES_FREE = _reg.gauge(
    "opsagent_kv_pages_free", "KV-cache pages currently free"
)
BATCH_OCCUPANCY = _reg.gauge(
    "opsagent_batch_occupancy",
    "Running decode sequences over max_batch_size (0..1)",
)
RUNNING_SEQUENCES = _reg.gauge(
    "opsagent_running_sequences", "Sequences the engine currently tracks"
)
PREEMPTIONS = _reg.counter(
    "opsagent_preemptions_total",
    "Sequences force-finished because the KV page budget ran out",
)
PREFIX_EVICTIONS = _reg.counter(
    "opsagent_prefix_evictions_total", "Prefix-cache trie leaf evictions"
)

# -- hierarchical KV cache: host-RAM offload tier -----------------------------
OFFLOAD_PAGES = _reg.counter(
    "opsagent_offload_pages_total",
    "KV pages moved between HBM and the host pool, by direction "
    "(out = device->host spill, in = host->device restore)",
    labelnames=("dir",),
)
OFFLOAD_BYTES = _reg.counter(
    "opsagent_offload_bytes_total",
    "Bytes moved between HBM and the host pool, by direction",
    labelnames=("dir",),
)
OFFLOAD_PARKS = _reg.counter(
    "opsagent_offload_parks_total",
    "Session parking events by trigger (tool = ReAct tool-exec window, "
    "pressure = admission-pressure eviction of a cold session)",
    labelnames=("trigger",),
)
OFFLOAD_RESTORE_SECONDS = _reg.histogram(
    "opsagent_offload_restore_seconds",
    "Host->device KV restore latency per admission (copy, not re-prefill)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5),
)
OFFLOAD_REPREFILL_AVOIDED = _reg.counter(
    "opsagent_offload_reprefill_avoided_tokens_total",
    "Prompt tokens restored from the host pool instead of re-prefilled",
)
OFFLOAD_RESTORE_FALLBACKS = _reg.counter(
    "opsagent_offload_restore_fallbacks_total",
    "Parked-session admissions that fell back to re-prefill because the "
    "host pool had dropped their pages (each is a flight-ring anomaly)",
)
HOST_POOL_BYTES = _reg.gauge(
    "opsagent_kv_host_pool_bytes", "Host-RAM KV pool bytes resident"
)
HOST_POOL_CAPACITY = _reg.gauge(
    "opsagent_kv_host_pool_capacity_bytes",
    "Host-RAM KV pool byte bound (OPSAGENT_KV_HOST_POOL_BYTES)",
)
HOST_POOL_PAGES = _reg.gauge(
    "opsagent_kv_host_pool_pages", "Host-RAM KV pool pages resident"
)
HOST_POOL_DROPS = _reg.counter(
    "opsagent_kv_host_pool_drops_total",
    "Host-pool pages LRU-dropped under the byte bound",
)

# -- fleet serving: replica router + session migration (serving/fleet) --------
FLEET_REPLICAS = _reg.gauge(
    "opsagent_fleet_replicas",
    "Registered engine replicas by role (decode/prefill) and state "
    "(active/draining)",
    labelnames=("role", "state"),
)
FLEET_ROUTE_DECISIONS = _reg.counter(
    "opsagent_fleet_route_decisions_total",
    "Router placement decisions by winning policy (pinned = sticky "
    "session->replica map, affinity = longest-cached-prefix over the "
    "replica trie digests, least_loaded = goodput/queue fallback, "
    "spill = pinned/affinity replica over its queue bound, forced = "
    "operator/test override, prefill = disaggregated prefill lane)",
    labelnames=("policy",),
)
FLEET_AFFINITY_PAGES = _reg.histogram(
    "opsagent_fleet_affinity_hit_pages",
    "Cached-prefix pages the chosen replica already held for the routed "
    "prompt (the re-prefill the placement avoided, in pages)",
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128),
)
FLEET_MIGRATIONS = _reg.counter(
    "opsagent_fleet_session_migrations_total",
    "Session migrations over the KV-page transfer path, by reason "
    "(misroute = affinity miss onto a replica without the pages, "
    "drain = graceful replica drain, prefill_handoff = disaggregated "
    "prefill lane -> decode replica)",
    labelnames=("reason",),
)
FLEET_TRANSFER_PAGES = _reg.counter(
    "opsagent_fleet_kv_transfer_pages_total",
    "KV pages shipped replica-to-replica (host-pool chain entries)",
)
FLEET_TRANSFER_BYTES = _reg.counter(
    "opsagent_fleet_kv_transfer_bytes_total",
    "Bytes of KV page payload shipped replica-to-replica",
)
FLEET_TRANSFER_SECONDS = _reg.histogram(
    "opsagent_fleet_kv_transfer_seconds",
    "Wall time of one replica-to-replica chain transfer (export + import)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5),
)
FLEET_SPILLOVERS = _reg.counter(
    "opsagent_fleet_queue_spillovers_total",
    "Routes bounced off a preferred replica because its queue depth "
    "exceeded the spill bound",
)
FLEET_REQUESTS = _reg.counter(
    "opsagent_fleet_requests_total",
    "Requests routed through the fleet front-end by outcome "
    "(completed / error / shed)",
    labelnames=("outcome",),
)

# -- failure containment: fault injection, failover, shedding -----------------
FAULT_INJECTIONS = _reg.counter(
    "opsagent_fault_injections_total",
    "Deterministic fault injections fired, by fault point "
    "(serving/faults.py; OPSAGENT_FAULTS spec)",
    labelnames=("point",),
)
FLEET_FAILOVERS = _reg.counter(
    "opsagent_fleet_failovers_total",
    "Mid-request failovers: a request re-submitted to a surviving "
    "replica after its serving replica failed (streams resume from the "
    "last emitted offset, dedup on re-submit)",
)
FLEET_RETRIES = _reg.counter(
    "opsagent_fleet_retries_total",
    "Bounded connect-phase retries against fleet replicas "
    "(exponential backoff + jitter)",
)
FLEET_HEDGES = _reg.counter(
    "opsagent_fleet_hedges_total",
    "TTFT hedges: a queued cold admission raced on a second replica, "
    "first completion wins; labeled by the request's SLO class",
    labelnames=("class",),
)
FLEET_EJECTIONS = _reg.counter(
    "opsagent_fleet_ejections_total",
    "Circuit-breaker ejections (replica health healthy -> suspect -> "
    "ejected; half-open probes readmit)",
)
FLEET_SHED = _reg.counter(
    "opsagent_fleet_shed_total",
    "Requests shed by router admission control above the overload "
    "watermark (429 + Retry-After), by SLO class of the shed request",
    labelnames=("class",),
)
FLEET_REPLICA_HEALTH = _reg.gauge(
    "opsagent_fleet_replica_health",
    "Registered replicas by circuit-breaker health state",
    labelnames=("state",),
)
FLEET_KV_IMPORT_REJECTS = _reg.counter(
    "opsagent_fleet_kv_import_rejects_total",
    "KV transfer records rejected at import (payload digest or "
    "structure mismatch); the receiver re-prefills instead",
)

# -- fleet request journeys: cross-replica trace propagation ------------------
FLEET_HOP_SECONDS = _reg.histogram(
    "opsagent_fleet_hop_seconds",
    "Wall time of one replica hop of a routed request, by hop kind "
    "(route = non-streaming completion, stream = streaming completion, "
    "failover = mid-SSE resume on a survivor, hedge = TTFT hedge probe, "
    "prefill = disaggregated prefill handoff, fault_in = pagestore peer "
    "fetch, migrate = session KV migration)",
    labelnames=("hop",),
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0),
)
FLEET_JOURNEYS = _reg.counter(
    "opsagent_fleet_journeys_total",
    "Completed fleet request journeys by shape (direct = one replica "
    "start to finish, retried = connect-phase re-route, hedged = a "
    "backup probe raced, failover = resumed on a survivor mid-request; "
    "a journey counts once under its most eventful shape), by shape "
    "and SLO class",
    labelnames=("shape", "class"),
)
FLEET_CLOCK_SKEW = _reg.gauge(
    "opsagent_fleet_clock_skew_seconds",
    "EWMA estimate of a replica's wall clock minus the router's wall "
    "clock, from heartbeat timestamp echoes (the offset the fleet "
    "timeline stitcher subtracts before ordering cross-replica "
    "segments)",
    labelnames=("replica",),
)

# -- fleet-global KV: page directory + peer-to-peer fault-in ------------------
PAGESTORE_LOOKUPS = _reg.counter(
    "opsagent_pagestore_lookups_total",
    "Chain-key lookups against the fleet page directory at admission "
    "(one per missing page-aligned prefix chain)",
)
PAGESTORE_REMOTE_HITS = _reg.counter(
    "opsagent_pagestore_remote_hits_total",
    "KV page chains faulted in peer-to-peer and landed in the local "
    "host pool (the remote tier between host-pool-hit and re-prefill)",
)
PAGESTORE_FETCH_BYTES = _reg.counter(
    "opsagent_pagestore_fetch_bytes_total",
    "Bytes of KV page payload fetched peer-to-peer by the page store",
)
PAGESTORE_FETCH_SECONDS = _reg.histogram(
    "opsagent_pagestore_fetch_seconds",
    "Wall time of one admission page fault-in (directory lookup "
    "excluded; fetch + verify + host-pool landing)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0),
)
PAGESTORE_STALE_ENTRIES = _reg.counter(
    "opsagent_pagestore_stale_entries_total",
    "Directory rows evicted because the advertised peer could not "
    "produce the chain (LRU-evicted between heartbeats, 404, or "
    "digest reject)",
)
PAGESTORE_FALLBACKS = _reg.counter(
    "opsagent_pagestore_fallbacks_total",
    "Admissions that degraded to local re-prefill after a page-store "
    "attempt, by reason (no_owner / miss / timeout / error / "
    "lookup_error)",
    labelnames=("reason",),
)

# -- cold start: engine snapshot/restore + elastic autoscaling ----------------
SNAPSHOT_OPS = _reg.counter(
    "opsagent_snapshot_ops_total",
    "Engine snapshot operations by kind (write = snapshot created, "
    "restore = engine restored, refused = fingerprint/device/leaf-order "
    "mismatch rejected)",
    labelnames=("op",),
)
SNAPSHOT_WRITE_SECONDS = _reg.histogram(
    "opsagent_snapshot_write_seconds",
    "Wall time to write one engine snapshot (weights device_get + leaf "
    "files + compile-cache copy + manifest)",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
)
SNAPSHOT_RESTORE_SECONDS = _reg.histogram(
    "opsagent_snapshot_restore_seconds",
    "Wall time from reading a snapshot manifest to a request-ready "
    "engine (mmap + device_put + cache-hit warmup sweep)",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
)
SNAPSHOT_BYTES = _reg.gauge(
    "opsagent_snapshot_bytes",
    "Size of the last snapshot written, by part (weights / "
    "compile_cache)",
    labelnames=("part",),
)
FLEET_SCALE_EVENTS = _reg.counter(
    "opsagent_fleet_scale_events_total",
    "Autoscaler actions by direction (up = standby replica launched "
    "from the snapshot, promote = request-ready standby admitted to "
    "decode rotation, down = idle autoscaled replica drained)",
    labelnames=("direction",),
)

# -- request lifecycle --------------------------------------------------------
ENGINE_REQUESTS = _reg.counter(
    "opsagent_engine_requests_total",
    "Engine generation requests by outcome",
    labelnames=("outcome",),
)
QUEUE_WAIT_SECONDS = _reg.histogram(
    "opsagent_queue_wait_seconds",
    "Scheduler admission queue wait per request",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0),
)
HTTP_REQUESTS = _reg.counter(
    "opsagent_http_requests_total",
    "HTTP requests by method, path, and status",
    labelnames=("method", "path", "status"),
)
HTTP_LATENCY_SECONDS = _reg.histogram(
    "opsagent_http_request_duration_seconds",
    "HTTP request wall time by path",
    labelnames=("path",),
)
AGENT_ITERATIONS = _reg.counter(
    "opsagent_agent_iterations_total", "ReAct loop iterations"
)
TOOL_CALLS = _reg.counter(
    "opsagent_agent_tool_calls_total",
    "Agent tool invocations by tool and outcome",
    labelnames=("tool", "outcome"),
)
TOOL_OVERLAP_SECONDS = _reg.counter(
    "opsagent_tool_overlap_seconds_total",
    "Seconds of tool execution hidden behind decode by conveyor "
    "launches (launch to min(tool end, stream end))",
)
TOOL_EARLY_LAUNCHES = _reg.counter(
    "opsagent_tool_early_launches_total",
    "Conveyor tool launches fired mid-decode at readiness-close",
    labelnames=("tool",),
)
TOOL_LAUNCH_LEAD_SECONDS = _reg.histogram(
    "opsagent_tool_launch_lead_seconds",
    "Lead time a conveyor launch gained over the classic path "
    "(launch to stream end)",
    labelnames=("tool",),
    buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)

# -- SLO classes + telemetry history + trace retention ------------------------
# The class enum is closed: every request is exactly one of these, and
# the metrics-conformance cardinality guard rejects any other value on
# the scrape (free-form class labels would melt it like request ids).
SLO_CLASSES = ("interactive", "batch", "background")
CLASS_REQUESTS = _reg.counter(
    "opsagent_class_requests_total",
    "Requests by SLO class and outcome (completed / error / timeout / "
    "admission_failed / shed) — the per-class attainment numerator and "
    "denominator",
    labelnames=("class", "outcome"),
)
CLASS_TTFT_SECONDS = _reg.histogram(
    "opsagent_class_ttft_seconds",
    "Time to first token per admitted request, split by SLO class "
    "(the unlabeled opsagent_ttft_seconds stays the all-traffic view)",
    labelnames=("class",),
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0),
)
CLASS_ITL_SECONDS = _reg.histogram(
    "opsagent_class_itl_seconds",
    "Inter-token latency split by SLO class",
    labelnames=("class",),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5),
)
CLASS_GOODPUT_SECONDS = _reg.counter(
    "opsagent_class_goodput_seconds_total",
    "Request wall seconds by SLO class and goodput phase (the per-class "
    "split of opsagent_goodput_seconds_total)",
    labelnames=("class", "phase"),
)
# -- audit fan-out: plan/scatter/reduce over the fleet (agent/fanout) ---------
FANOUT_CHILDREN = _reg.counter(
    "opsagent_fanout_children_total",
    "Fan-out child sessions by outcome (ok / shed / failed; shed and "
    "failed children become finding_unavailable rows, never lost audits)",
    labelnames=("outcome",),
)
FANOUT_FINDINGS = _reg.counter(
    "opsagent_fanout_findings_total",
    "Findings merged by the fan-out reduce phase, by severity "
    "(closed enum: critical/high/medium/low/none/unavailable)",
    labelnames=("severity",),
)
FANOUT_REPREFILL_AVOIDED = _reg.counter(
    "opsagent_fanout_reprefill_avoided_tokens_total",
    "Shared-prefix prompt tokens fan-out children served from cache "
    "instead of re-prefilling (the fleet-global-KV win the fan-out "
    "exists to harvest)",
)
FANOUT_REDUCE_SECONDS = _reg.histogram(
    "opsagent_fanout_reduce_seconds",
    "Wall time of one fan-out reduce phase (merge + stable sort + "
    "canonical report)",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5),
)
FANOUT_ACTIVE = _reg.gauge(
    "opsagent_fanout_active",
    "Fan-out audits currently in flight in this process",
)
FANOUT_CHILDREN_TOTAL = _reg.gauge(
    "opsagent_fanout_children_planned",
    "Children planned by the most recent fan-out (top's done/total row)",
)
FANOUT_CHILDREN_DONE = _reg.gauge(
    "opsagent_fanout_children_done",
    "Children finished (any outcome) of the most recent fan-out",
)
FANOUT_PREFIX_HIT_RATE = _reg.gauge(
    "opsagent_fanout_prefix_hit_rate",
    "Shared-prefix hit rate of the most recent fan-out (prefix-cache "
    "tokens hit over children x shared-prefix tokens, 0..1)",
)

TRACE_RETENTION = _reg.counter(
    "opsagent_trace_retention_total",
    "Tail-based trace retention decisions at request finish "
    "(kept_anomalous = SLO breach/error/failover, always kept; "
    "kept_sampled = healthy, won the sample draw; dropped = healthy, "
    "lost it)",
    labelnames=("decision",),
)
HISTORY_SAMPLES = _reg.counter(
    "opsagent_history_samples_total",
    "Sampling sweeps the telemetry history store has taken",
)
HISTORY_POINTS = _reg.gauge(
    "opsagent_history_points",
    "Points resident in the telemetry history ring, by downsample tier "
    "(1s / 10s / 60s)",
    labelnames=("tier",),
)
HISTORY_BYTES = _reg.gauge(
    "opsagent_history_bytes",
    "Estimated resident bytes of the telemetry history ring (bounded "
    "by OPSAGENT_HISTORY_BYTES)",
)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def metrics_text() -> str:
    """The exposition document for a GET /metrics scrape."""
    return get_registry().render()


def metrics_snapshot() -> dict:
    """Compact dict of every sample (bench.py folds this into BENCH
    JSON)."""
    return get_registry().snapshot()


# Imported AFTER the instrument handles exist: both modules record into
# them. ``flight`` owns the event ring + compile watchdog, ``slo`` the
# declared-objective evaluation; the watchdog's listeners register at
# import so no compile anywhere in the process escapes the count, and the
# SLO gauges join the scrape as a collector. ``attribution`` (the
# roofline cost ledger + goodput counters) and ``timeline`` (per-request
# phase assembly over the flight ring + trace store) complete the
# goodput-ledger surface.
from . import flight  # noqa: E402,F401
from . import slo  # noqa: E402,F401
from . import attribution  # noqa: E402,F401
from . import timeline  # noqa: E402,F401
from . import history  # noqa: E402,F401

flight.install_compile_watchdog()
_reg.add_collector(lambda: slo.get_watchdog().collect())
