"""SLO watchdog: declared serving objectives evaluated live from the
PR-1 histograms.

The north-star metrics (BASELINE.json: p50 TTFT per agent tool-call turn
< 500 ms; >= 2000 tok/s/chip decode) were, until this module, computed
OFFLINE by bench.py after a run — the server itself never knew whether it
was meeting them. The watchdog closes that loop: the same histograms the
engine already records (``opsagent_ttft_seconds``,
``opsagent_inter_token_latency_seconds``, ``opsagent_engine_requests_total``,
``opsagent_decode_tokens_total``) are folded into declared SLOs with
pass/fail and a burn rate, exposed three ways:

- ``GET /api/slo`` on both servers — JSON verdicts;
- ``opsagent_slo_*`` gauges on ``/metrics`` (a scrape-time collector, so
  dashboards can alert on ``opsagent_slo_pass == 0``);
- ``opsagent slo-check`` in the CLI — a bench/CI gate (exit 1 on breach).

Quantiles are estimated from the cumulative histogram buckets with the
standard Prometheus ``histogram_quantile`` linear interpolation — the
estimate and the raw count/sum ride the verdict so a reader can check the
arithmetic against the same ``/metrics`` samples.

Burn rate follows the SRE convention "how fast is the budget burning":
``observed / target`` for lower-is-better objectives (latency, error
rate) and ``target / observed`` for higher-is-better ones (throughput),
so burn > 1.0 always means "violating" and 2.0 means "twice as bad as
allowed".

Targets are env-tunable (defaults in parentheses):

- ``OPSAGENT_SLO_TTFT_MS``   — p50 TTFT (500; also the flight recorder's
  per-request anomaly threshold, so the alarm line and the SLO agree)
- ``OPSAGENT_SLO_ITL_MS``    — p50 inter-token latency (100)
- ``OPSAGENT_SLO_ERROR_RATE``— failed / total engine requests (0.01)
- ``OPSAGENT_SLO_TOK_S_CHIP``— decode tokens/sec/chip (0 = disabled;
  set to 2000 on the TPU bench — meaningless on a CPU test box)

Throughput needs a *rate*, which a counter alone cannot give: the
watchdog keeps a short ring of (time, counter) snapshots, refreshed by a
background thread on servers (``SLOWatchdog.start``) or implicitly by
each ``evaluate()`` call, and rates over the most recent window. Before
two snapshots >= 1 s apart exist the throughput SLO reports
``"insufficient data"`` instead of a fake pass.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any

from ..utils.logger import get_logger

log = get_logger("obs.slo")

_ENV_TTFT = "OPSAGENT_SLO_TTFT_MS"
_ENV_ITL = "OPSAGENT_SLO_ITL_MS"
_ENV_ERR = "OPSAGENT_SLO_ERROR_RATE"
_ENV_TOKS = "OPSAGENT_SLO_TOK_S_CHIP"

_RATE_WINDOW_S = 60.0
_MAX_SNAPSHOTS = 64


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# -- SLO classes -------------------------------------------------------------
# Every request lands in exactly one class; the label is enum-only (the
# metrics-conformance cardinality guard rejects anything else). Explicit
# ``slo_class`` in the request body wins; otherwise the scenario decides
# (a human is waiting on a diagnosis; an audit sweep is throughput work).
_SCENARIO_CLASSES = {
    "diagnose": "interactive",
    "analyze": "interactive",
    "execute": "interactive",
    "audit": "batch",
}


def classify(
    body: Any = None, scenario: str = "", default: str = "interactive"
) -> str:
    """SLO class for one request: ``interactive`` | ``batch`` |
    ``background``. ``body`` may be the request dict (its ``slo_class``
    field wins when valid; its ``scenario`` field feeds the fallback)."""
    from . import SLO_CLASSES

    if isinstance(body, dict):
        explicit = str(body.get("slo_class") or "").strip().lower()
        if explicit in SLO_CLASSES:
            return explicit
        scenario = scenario or str(body.get("scenario") or "")
    mapped = _SCENARIO_CLASSES.get(scenario.strip().lower())
    if mapped:
        return mapped
    return default if default in SLO_CLASSES else "interactive"


@dataclass(frozen=True)
class SLO:
    name: str
    description: str
    target: float
    unit: str
    # "lt": observed must stay BELOW target; "gt": ABOVE target.
    direction: str = "lt"


def declared_slos() -> list[SLO]:
    slos = [
        SLO(
            "ttft_p50_ms",
            "p50 time-to-first-token per engine request "
            "(opsagent_ttft_seconds)",
            _env_float(_ENV_TTFT, 500.0),
            "ms",
        ),
        SLO(
            "itl_p50_ms",
            "p50 inter-token latency "
            "(opsagent_inter_token_latency_seconds)",
            _env_float(_ENV_ITL, 100.0),
            "ms",
        ),
        SLO(
            "error_rate",
            "failed / total engine requests "
            "(opsagent_engine_requests_total)",
            _env_float(_ENV_ERR, 0.01),
            "ratio",
        ),
    ]
    toks = _env_float(_ENV_TOKS, 0.0)
    if toks > 0:
        slos.append(
            SLO(
                "decode_tok_s_chip",
                "decode tokens/sec/chip over the recent window "
                "(opsagent_decode_tokens_total)",
                toks,
                "tok/s/chip",
                direction="gt",
            )
        )
    return slos


def histogram_quantile(hist: Any, q: float, **labels: str) -> float | None:
    """Prometheus-style quantile estimate from an obs.metrics.Histogram's
    cumulative buckets (linear interpolation within the bucket holding
    the quantile rank; the +Inf bucket clamps to the largest finite
    bound). None when the histogram has no samples."""
    with hist._lock:
        child = hist._children.get(hist._key(labels or None))
        if child is None:
            return None
        counts, total, _ = list(child[0]), child[1], child[2]
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    lo = 0.0
    for i, b in enumerate(hist.buckets):
        c = counts[i]
        if cum + c >= rank:
            if c == 0:
                return b
            return lo + (b - lo) * (rank - cum) / c
        cum += c
        lo = b
    # Rank falls in the +Inf overflow bucket: clamp to the largest finite
    # bound (the Prometheus convention — nothing to interpolate toward).
    return hist.buckets[-1]


class SLOWatchdog:
    """Continuous SLO evaluation over the process-wide obs registry."""

    def __init__(self, interval_s: float = 5.0):
        self.interval_s = interval_s
        self._lock = threading.Lock()
        # (perf_counter, decode_tokens_total) snapshots, oldest first.
        self._snaps: list[tuple[float, float]] = []
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._last: list[dict[str, Any]] = []
        self._breached_since: dict[str, float] = {}
        self.take_snapshot()

    # -- rate bookkeeping --------------------------------------------------
    def take_snapshot(self) -> None:
        from . import DECODE_TOKENS

        now = time.perf_counter()
        with self._lock:
            self._snaps.append((now, DECODE_TOKENS.value()))
            # Keep the window bounded; retain at least two points.
            while len(self._snaps) > _MAX_SNAPSHOTS or (
                len(self._snaps) > 2
                and now - self._snaps[1][0] > _RATE_WINDOW_S
            ):
                self._snaps.pop(0)

    def _decode_rate(self) -> float | None:
        """tokens/sec over the most recent window. Rides TelemetryHistory
        when its sampler has points (servers run it at 1 Hz, so the rate
        is live ~2 s after boot instead of "UNKNOWN until two ad-hoc
        snapshots >= 1 s apart"); falls back to the watchdog's own
        snapshot pair when the sampler is off (bare evaluate() calls)."""
        from . import history as _history

        r = _history.get_history().rate("decode_tokens", _RATE_WINDOW_S)
        if r is not None:
            return r
        with self._lock:
            snaps = list(self._snaps)
        if len(snaps) < 2:
            return None
        (t0, c0), (t1, c1) = snaps[0], snaps[-1]
        if t1 - t0 < 1.0:
            return None
        return max(0.0, c1 - c0) / (t1 - t0)

    # -- evaluation --------------------------------------------------------
    def evaluate(self) -> dict[str, Any]:
        """All declared SLOs -> verdicts. Each verdict carries the
        observed value, the raw histogram count/sum it came from, pass
        (True/False, or None when there is no data yet), and burn_rate
        (> 1.0 = violating)."""
        from . import ENGINE_REQUESTS, ITL_SECONDS, TTFT_SECONDS

        self.take_snapshot()
        out: list[dict[str, Any]] = []
        for slo in declared_slos():
            v: dict[str, Any] = {
                "name": slo.name,
                "description": slo.description,
                "target": slo.target,
                "unit": slo.unit,
                "direction": slo.direction,
            }
            if slo.name == "ttft_p50_ms":
                p50 = histogram_quantile(TTFT_SECONDS, 0.5)
                v["count"] = TTFT_SECONDS.count()
                v["sum"] = round(TTFT_SECONDS.sum(), 6)
                v["value"] = None if p50 is None else round(p50 * 1e3, 3)
            elif slo.name == "itl_p50_ms":
                p50 = histogram_quantile(ITL_SECONDS, 0.5)
                v["count"] = ITL_SECONDS.count()
                v["sum"] = round(ITL_SECONDS.sum(), 6)
                v["value"] = None if p50 is None else round(p50 * 1e3, 3)
            elif slo.name == "error_rate":
                by = {
                    "completed": ENGINE_REQUESTS.value(outcome="completed"),
                    "error": ENGINE_REQUESTS.value(outcome="error"),
                    "timeout": ENGINE_REQUESTS.value(outcome="timeout"),
                    "admission_failed": ENGINE_REQUESTS.value(
                        outcome="admission_failed"
                    ),
                }
                total = sum(by.values())
                bad = total - by["completed"]
                v["count"] = int(total)
                v["value"] = (
                    None if total == 0 else round(bad / total, 6)
                )
            elif slo.name == "decode_tok_s_chip":
                rate = self._decode_rate()
                chips = _chip_count()
                v["chips"] = chips
                v["value"] = (
                    None if rate is None else round(rate / chips, 3)
                )
                if rate is None:
                    v["note"] = "insufficient data (need a rate window)"
            value = v.get("value")
            if value is None:
                v["pass"] = None
                v["burn_rate"] = None
            elif slo.direction == "lt":
                v["pass"] = value < slo.target
                v["burn_rate"] = round(value / slo.target, 4) \
                    if slo.target > 0 else None
            else:
                v["pass"] = value > slo.target
                # value == 0 would be an infinite burn; None keeps the
                # JSON strict-parser-safe (pass=False already says it all).
                v["burn_rate"] = round(slo.target / value, 4) \
                    if value > 0 else None
            self._track_breach(v)
            out.append(v)
        with self._lock:
            self._last = out
        return {
            "slos": out,
            "classes": self.class_report(),
            "error_budget": _env_float(_ENV_ERR, 0.01),
            "pass": all(v["pass"] is not False for v in out),
            "evaluated_at": time.time(),
        }

    def class_report(self) -> list[dict[str, Any]]:
        """Per-SLO-class attainment + burn rate, windowed over
        TelemetryHistory (5 m and 1 h) rather than instantaneous. Only
        classes that have seen traffic appear; attainment is
        completed / (completed + bad) where bad covers error, timeout,
        admission_failed, and shed; burn rate is the SRE convention
        (1 - attainment) / error_budget, > 1.0 = burning faster than the
        budget allows."""
        from . import (
            CLASS_ITL_SECONDS,
            CLASS_REQUESTS,
            CLASS_TTFT_SECONDS,
            SLO_CLASSES,
        )
        from . import history as _history

        budget = _env_float(_ENV_ERR, 0.01)
        h = _history.get_history()
        rows: list[dict[str, Any]] = []
        for cls in SLO_CLASSES:
            by = {
                outcome: CLASS_REQUESTS.value(
                    **{"class": cls, "outcome": outcome}
                )
                for outcome in (
                    "completed", "error", "timeout",
                    "admission_failed", "shed",
                )
            }
            total = sum(by.values())
            if total <= 0:
                continue
            bad = total - by["completed"]
            ttft = histogram_quantile(
                CLASS_TTFT_SECONDS, 0.95, **{"class": cls}
            )
            itl = histogram_quantile(
                CLASS_ITL_SECONDS, 0.95, **{"class": cls}
            )
            row: dict[str, Any] = {
                "class": cls,
                "requests": int(total),
                "bad": int(bad),
                "attainment": round(by["completed"] / total, 6),
                "ttft_p95_ms": (
                    None if ttft is None else round(ttft * 1e3, 3)
                ),
                "itl_p95_ms": (
                    None if itl is None else round(itl * 1e3, 3)
                ),
                "outcomes": {k: int(v) for k, v in by.items() if v},
                "windows": {},
            }
            for label, win in (("5m", 300.0), ("1h", 3600.0)):
                done = h.window_sum(f"class.{cls}.completed", win)
                wbad = h.window_sum(f"class.{cls}.bad", win)
                wtotal = done + wbad
                if wtotal <= 0:
                    continue
                att = done / wtotal
                row["windows"][label] = {
                    "requests": int(wtotal),
                    "attainment": round(att, 6),
                    "burn_rate": (
                        round((1.0 - att) / budget, 4)
                        if budget > 0 else None
                    ),
                }
            rows.append(row)
        return rows

    def _track_breach(self, v: dict[str, Any]) -> None:
        """Breach bookkeeping: a flight-ring ANOMALY on each pass->fail
        transition (with the verdict attached, so the dump shows WHAT
        breached — and, via the dump's appended attribution snapshot,
        where the device bytes were going when it happened), plus
        breached_for_s while it lasts. The anomaly path is rate-limited
        by the recorder, so a flapping SLO cannot fill the disk."""
        name = v["name"]
        now = time.perf_counter()
        if v["pass"] is False:
            first = self._breached_since.setdefault(name, now)
            v["breached_for_s"] = round(now - first, 3)
            if first == now:
                from .flight import get_recorder, record

                record(
                    "slo_breach", slo=name, value=v.get("value"),
                    target=v["target"], burn_rate=v.get("burn_rate"),
                )
                # Dump the ring (+ attribution/timeline context) so the
                # breach is a self-contained postmortem artifact.
                # count=False: this can run inside a /metrics scrape, and
                # a scrape must not mutate scrape-visible counters.
                get_recorder().anomaly(
                    "slo_breach", count=False, slo=name,
                    value=v.get("value"), target=v["target"],
                    burn_rate=v.get("burn_rate"),
                )
        else:
            self._breached_since.pop(name, None)

    # -- /metrics collector ------------------------------------------------
    def collect(self) -> list[str]:
        """Scrape-time exposition: opsagent_slo_pass / _burn_rate /
        _value gauges per SLO (evaluated fresh, so the scrape and the
        endpoint can never disagree)."""
        from .metrics import escape_label_value

        res = self.evaluate()
        lines = [
            "# HELP opsagent_slo_pass declared SLO pass (1) / fail (0) / "
            "no data (-1)",
            "# TYPE opsagent_slo_pass gauge",
        ]
        burns: list[str] = []
        values: list[str] = []
        for v in res["slos"]:
            tag = f'{{slo="{escape_label_value(v["name"])}"}}'
            ok = v["pass"]
            lines.append(
                f"opsagent_slo_pass{tag} "
                f"{-1 if ok is None else (1 if ok else 0)}"
            )
            if v.get("burn_rate") is not None:
                burns.append(
                    f"opsagent_slo_burn_rate{tag} {v['burn_rate']}"
                )
            if v.get("value") is not None:
                values.append(f"opsagent_slo_value{tag} {v['value']}")
        # One contiguous group per metric family (the exposition format
        # forbids interleaving families).
        if burns:
            lines.append("# TYPE opsagent_slo_burn_rate gauge")
            lines.extend(burns)
        if values:
            lines.append("# TYPE opsagent_slo_value gauge")
            lines.extend(values)
        return lines

    # -- background loop ---------------------------------------------------
    def start(self) -> None:
        """Background refresher (servers): keeps the rate window warm and
        the breach transitions timely even when nobody scrapes."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="slo-watchdog"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 - the watchdog must survive
                log.exception("slo evaluation failed")

    def reset(self) -> None:
        """Test-isolation hook: drop rate snapshots and breach state."""
        with self._lock:
            self._snaps.clear()
        self._breached_since.clear()
        self.take_snapshot()


def _chip_count() -> int:
    try:
        import jax

        return max(1, len(jax.devices()))
    except Exception:  # noqa: BLE001
        return 1


_watchdog: SLOWatchdog | None = None
_watchdog_lock = threading.Lock()


def get_watchdog() -> SLOWatchdog:
    global _watchdog
    if _watchdog is None:
        with _watchdog_lock:
            if _watchdog is None:
                _watchdog = SLOWatchdog()
    return _watchdog


def evaluate() -> dict[str, Any]:
    """Module-level convenience: evaluate every declared SLO now."""
    return get_watchdog().evaluate()
