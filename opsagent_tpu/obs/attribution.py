"""Goodput ledger, part 1: continuous roofline attribution.

PERF.md's r04 conclusion — decode is device-bound against a ~2.9 ms
weight-stream floor plus ~3.4 ms of KV scatter/gather — came from ONE
offline ``jax.profiler`` trace. This module makes that attribution
continuous: a static cost model (derived from the model config and the
engine's quantization choices) prices every device dispatch from its
batch composition, on host, with no device work and nothing jitted — the
zero-post-warmup-compiles invariant is untouched because attribution
never sees an array.

The model is a ROOFLINE: per dispatch it answers "how many HBM bytes did
this step *have* to move" (weights streamed, KV read, KV written, logits
materialized) and "how many useful model FLOPs did it perform", assuming
perfectly-coalesced access. Reality is worse — the r04 trace showed the
KV page-write scatter costs ~1.4 ms to move kilobytes — and that gap is
the point: ``opsagent_attr_model_drift_ratio`` (measured / modeled step
time) is the live number that says how far the kernels sit from the
bytes floor, so an int4/int8-KV PR can watch its denominator move
without re-running a manual trace.

Known approximations (documented, deliberate):

- Parameter count uses ``ModelConfig.num_params()`` (dense-architecture
  arithmetic): MoE all-expert decode streams more, MLA projections
  differ. The drift gauge absorbs the error for such models.
- Prefill attention FLOPs use the exact causal sum per chunk
  (``chunk*start + chunk*(chunk+1)/2`` attended positions); KV-read
  bytes assume each resident token's K/V is streamed once per dispatch
  (the paged kernels' design goal — the XLA gather can read page-table
  capacity instead, which again shows up as drift).
- Block/speculative decode scans stream weights once per SCAN STEP
  (``n_steps`` times per dispatch), regardless of how few lanes carry a
  budget — inactive lanes still ride the stream.

Per-request goodput rides here too: ``opsagent_goodput_seconds_total``
accumulates wall seconds by lifecycle phase (queued / prefill /
decode_active / tool_blocked), recorded from the scheduler, engine, and
agent loop, so "what fraction of serving wall clock was useful decode"
is a scrape-side division (obs/timeline.py computes the same split per
request).
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import deque
from typing import Any

from .metrics import get_registry

_reg = get_registry()

# -- instruments (names are a docs/observability.md contract) ----------------
ATTR_BYTES = _reg.counter(
    "opsagent_attr_bytes_total",
    "Modeled HBM bytes moved by device dispatches, by kind (weights = "
    "serial parameter stream, weights_prefetch = parameter stream moved "
    "by the double-buffered pallas-dma weight pipeline (overlapped with "
    "compute), kv_read / kv_write = paged-cache traffic, other = "
    "logit materialization + offload page copies). Roofline arithmetic "
    "from the dispatch composition — no device measurement involved",
    labelnames=("kind",),
)
ATTR_STEP_BYTES = _reg.gauge(
    "opsagent_attr_step_bytes",
    "Modeled bytes of the MOST RECENT device dispatch, by kind — the "
    "live bytes-per-step split (weights vs KV-read vs KV-write vs other)",
    labelnames=("kind",),
)
ATTR_FLOPS = _reg.counter(
    "opsagent_attr_flops_total",
    "Modeled useful model FLOPs (2*params per processed token plus exact "
    "causal attention terms)",
)
ATTR_DISPATCHES = _reg.counter(
    "opsagent_attr_dispatches_total",
    "Dispatches priced by the attribution cost model, by op",
    labelnames=("op",),
)
ATTR_MODELED_STEP_SECONDS = _reg.gauge(
    "opsagent_attr_modeled_step_seconds",
    "Roofline-modeled wall time of the most recent dispatch "
    "(modeled bytes / configured HBM bandwidth)",
)
ATTR_MEASURED_STEP_SECONDS = _reg.histogram(
    "opsagent_attr_measured_step_seconds",
    "Measured dispatch+pull wall time for synchronously-pulled ops "
    "(mixed tick, single step) — the numerator of the drift ratio",
    labelnames=("op",),
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5),
)
ATTR_MODEL_DRIFT = _reg.gauge(
    "opsagent_attr_model_drift_ratio",
    "EMA of measured / modeled step time on synchronously-measured "
    "dispatches: 1.0 = running at the bytes roofline; large values mean "
    "the kernels (or host gaps) sit far above the bytes floor",
)
ATTR_MFU = _reg.gauge(
    "opsagent_attr_mfu",
    "Model FLOP utilization over the rate window: modeled useful FLOP/s "
    "divided by OPSAGENT_PEAK_TFLOPS (default 197, v5e bf16)",
)
ATTR_HBM_UTIL = _reg.gauge(
    "opsagent_attr_hbm_utilization",
    "Modeled HBM-bandwidth utilization over the rate window: modeled "
    "bytes/s divided by OPSAGENT_HBM_GBPS (default 820, v5e)",
)
GOODPUT_SECONDS = _reg.counter(
    "opsagent_goodput_seconds_total",
    "Request wall seconds by lifecycle phase (queued = admission queue, "
    "prefill = admission to first token, decode_active = first token to "
    "finish, tool_blocked = agent tool subprocess window). The goodput "
    "split: decode_active over the total is the fraction of serving "
    "wall clock spent producing tokens",
    labelnames=("phase",),
)

_ENV_HBM = "OPSAGENT_HBM_GBPS"
_ENV_TFLOPS = "OPSAGENT_PEAK_TFLOPS"
DEFAULT_HBM_GBPS = 820.0      # v5e HBM bandwidth (PERF.md roofline)
DEFAULT_PEAK_TFLOPS = 197.0   # v5e bf16 peak
RATE_WINDOW_S = 60.0

_BYTE_KINDS = ("weights", "weights_prefetch", "kv_read", "kv_write", "other")


def _env_float(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, ""))
        return v if v > 0 else default
    except ValueError:
        return default


def prefill_attn_positions(start: int, chunk: int) -> int:
    """Exact causal attended-position count for one prefill chunk: query
    token j (0-based within the chunk) attends start + j + 1 positions."""
    return chunk * start + chunk * (chunk + 1) // 2


class Attribution:
    """Static roofline cost model for ONE engine's dispatches.

    All methods are cheap host float math under a small lock; safe to
    call from the engine's dispatch loop. Construction derives the
    per-dispatch byte/FLOP coefficients once from the model config and
    the engine's quantization choices.
    """

    def __init__(
        self,
        *,
        num_params: int,
        num_layers: int,
        num_heads: int,
        num_kv_heads: int,
        head_dim: int,
        vocab_size: int,
        dtype_bytes: int = 2,
        quantize: str = "",
        kv_quantize: str = "",
        weight_stream: str = "",
        mla_latent_dim: int = 0,
        hbm_gbps: float | None = None,
        peak_tflops: float | None = None,
    ):
        self.num_params = int(num_params)
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.vocab_size = vocab_size
        # Weight bytes streamed per forward pass. int8: 1 byte/param plus
        # ~2 % per-channel scales (PERF.md: "~8 GB int8 (+1-2 % scales)");
        # int4: a packed nibble plus one f32 scale per 128-group.
        if quantize == "int8":
            bpp = 1.02
        elif quantize == "int4":
            bpp = 0.5 + 4.0 / 128.0
        else:
            bpp = float(dtype_bytes)
        self.weight_stream_bytes = self.num_params * bpp
        # "pallas-dma": the quant-matmul kernels stream weight tiles
        # through double-buffered DMA slots, overlapping the parameter
        # stream with compute. Bytes land under kind="weights_prefetch"
        # and modeled_s becomes the overlapped roofline
        # max(bytes/bw, flops/peak) instead of the serial bytes/bw.
        self.weight_stream = weight_stream or "xla"
        # KV bytes per resident token across ALL layers. Standard paged
        # cache: k + v planes of [num_kv_heads, head_dim]; int8 pages add
        # one f32 scale per token per head per plane. MLA latent cache:
        # one shared latent vector per token (the ~85x compression).
        if mla_latent_dim:
            per_layer = mla_latent_dim * dtype_bytes
        elif kv_quantize == "int8":
            per_layer = 2 * num_kv_heads * (head_dim * 1 + 4)
        else:
            per_layer = 2 * num_kv_heads * head_dim * dtype_bytes
        self.kv_token_bytes = num_layers * per_layer
        # "other": the logits each sampled row materializes (f32 [V] per
        # query token that reaches the sampler).
        self.logits_bytes = vocab_size * 4
        self.hbm_bytes_s = _env_float(_ENV_HBM, hbm_gbps or DEFAULT_HBM_GBPS) * 1e9
        self.peak_flops_s = (
            _env_float(_ENV_TFLOPS, peak_tflops or DEFAULT_PEAK_TFLOPS) * 1e12
        )
        self._lock = threading.Lock()
        self._window: deque[tuple[float, float, float]] = deque()
        self._cum_flops = 0.0
        self._cum_bytes = 0.0
        self._drift_ema: float | None = None
        self.dispatches = 0

    @classmethod
    def for_engine(
        cls, model_cfg: Any, engine_cfg: Any, weight_stream: str = ""
    ) -> "Attribution":
        """Derive the cost model from an Engine's (model_cfg, cfg) pair.
        ``weight_stream`` is the engine's RESOLVED impl ("xla" or
        "pallas-dma"), not the raw config string — the engine passes it
        after applying its own fallback gates."""
        import numpy as np

        try:
            dtype_bytes = int(np.dtype(engine_cfg.dtype).itemsize)
        except TypeError:
            dtype_bytes = 2
        mla = getattr(model_cfg, "mla", None)
        latent = (
            mla.latent_dim if mla is not None and mla.latent_cache else 0
        )
        return cls(
            num_params=model_cfg.num_params(),
            num_layers=model_cfg.num_layers,
            num_heads=model_cfg.num_heads,
            num_kv_heads=model_cfg.num_kv_heads,
            head_dim=model_cfg.head_dim_,
            vocab_size=model_cfg.vocab_size,
            dtype_bytes=dtype_bytes,
            quantize=getattr(engine_cfg, "quantize", ""),
            kv_quantize=getattr(engine_cfg, "kv_quantize", ""),
            weight_stream=weight_stream,
            mla_latent_dim=latent,
        )

    # -- pricing -------------------------------------------------------------
    def cost(
        self,
        *,
        weight_streams: float = 1.0,
        q_tokens: int = 0,
        kv_read_tokens: int = 0,
        kv_write_tokens: int = 0,
        attn_q_ctx: int = 0,
        copy_bytes: float = 0.0,
    ) -> dict[str, float]:
        """The closed-form arithmetic: bytes by kind, FLOPs, and the
        bandwidth-roofline modeled seconds for one dispatch. Pure — the
        unit tests drive this directly against hand arithmetic."""
        b_weights = weight_streams * self.weight_stream_bytes
        b_kv_read = kv_read_tokens * self.kv_token_bytes
        b_kv_write = kv_write_tokens * self.kv_token_bytes
        b_other = q_tokens * self.logits_bytes + copy_bytes
        total = b_weights + b_kv_read + b_kv_write + b_other
        flops = (
            2.0 * self.num_params * q_tokens
            + 4.0 * self.num_heads * self.head_dim * self.num_layers
            * attn_q_ctx
        )
        overlapped = self.weight_stream == "pallas-dma"
        # Overlap-aware roofline: under pallas-dma the weight stream is
        # double-buffered behind compute, so a dispatch's floor is the
        # SLOWER of "move every byte" and "do every FLOP" rather than
        # their serial bytes-only sum — the same total bytes, but the
        # kernel earns credit for hiding DMA issue latency only up to
        # the bandwidth/compute roofline, never below it.
        modeled_s = total / self.hbm_bytes_s
        if overlapped:
            modeled_s = max(modeled_s, flops / self.peak_flops_s)
        return {
            "weights": 0.0 if overlapped else b_weights,
            "weights_prefetch": b_weights if overlapped else 0.0,
            "kv_read": b_kv_read,
            "kv_write": b_kv_write,
            "other": b_other,
            "total": total,
            "flops": flops,
            "modeled_s": modeled_s,
        }

    def dispatch(
        self,
        op: str,
        *,
        weight_streams: float = 1.0,
        q_tokens: int = 0,
        kv_read_tokens: int = 0,
        kv_write_tokens: int = 0,
        attn_q_ctx: int = 0,
        copy_bytes: float = 0.0,
        measured_s: float | None = None,
    ) -> dict[str, float]:
        """Price one dispatch and fold it into the ledger: cumulative
        byte/FLOP counters, the live bytes-per-step split, the MFU / HBM
        utilization rate-window gauges, and (when the caller measured the
        dispatch synchronously) the modeled-vs-measured drift. Never
        raises into the serving path."""
        c = self.cost(
            weight_streams=weight_streams,
            q_tokens=q_tokens,
            kv_read_tokens=kv_read_tokens,
            kv_write_tokens=kv_write_tokens,
            attn_q_ctx=attn_q_ctx,
            copy_bytes=copy_bytes,
        )
        try:
            self._record(op, c, measured_s)
        except Exception:  # noqa: BLE001 - the ledger must not kill serving
            pass
        return c

    def _record(
        self, op: str, c: dict[str, float], measured_s: float | None
    ) -> None:
        ATTR_DISPATCHES.inc(op=op)
        for kind in _BYTE_KINDS:
            if c[kind]:
                ATTR_BYTES.inc(c[kind], kind=kind)
            ATTR_STEP_BYTES.set(c[kind], kind=kind)
        ATTR_FLOPS.inc(c["flops"])
        ATTR_MODELED_STEP_SECONDS.set(c["modeled_s"])
        now = time.perf_counter()
        with self._lock:
            self.dispatches += 1
            self._cum_flops += c["flops"]
            self._cum_bytes += c["total"]
            self._window.append((now, self._cum_flops, self._cum_bytes))
            while (
                len(self._window) > 2
                and now - self._window[0][0] > RATE_WINDOW_S
            ):
                self._window.popleft()
            t0, f0, b0 = self._window[0]
            dt = now - t0
            # Materialized even before the window has two points: an
            # absent gauge and "no recent work" must not look the same.
            ATTR_MFU.set(
                (self._cum_flops - f0) / dt / self.peak_flops_s
                if dt > 0 else 0.0
            )
            ATTR_HBM_UTIL.set(
                (self._cum_bytes - b0) / dt / self.hbm_bytes_s
                if dt > 0 else 0.0
            )
            if measured_s is not None and c["modeled_s"] > 0:
                ATTR_MEASURED_STEP_SECONDS.observe(measured_s, op=op)
                ratio = measured_s / c["modeled_s"]
                if math.isfinite(ratio):
                    ema = self._drift_ema
                    self._drift_ema = (
                        ratio if ema is None else 0.9 * ema + 0.1 * ratio
                    )
                    ATTR_MODEL_DRIFT.set(self._drift_ema)

    # -- reading -------------------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        """Compact dict for bench `extra.attribution` and flight dumps."""
        with self._lock:
            drift = self._drift_ema
            cum_f, cum_b = self._cum_flops, self._cum_bytes
            n = self.dispatches
        return {
            "weight_stream": self.weight_stream,
            "weight_stream_bytes": round(self.weight_stream_bytes),
            "kv_token_bytes": round(self.kv_token_bytes),
            "hbm_gbps": round(self.hbm_bytes_s / 1e9, 1),
            "peak_tflops": round(self.peak_flops_s / 1e12, 1),
            "dispatches": n,
            "bytes_total": round(cum_b),
            "flops_total": round(cum_f),
            "bytes_by_kind": {
                k: round(ATTR_BYTES.value(kind=k)) for k in _BYTE_KINDS
            },
            "mfu": round(ATTR_MFU.value(), 6),
            "hbm_utilization": round(ATTR_HBM_UTIL.value(), 6),
            "modeled_last_step_s": round(
                ATTR_MODELED_STEP_SECONDS.value(), 6
            ),
            "drift_ema": None if drift is None else round(drift, 3),
        }


# -- process-wide access ------------------------------------------------------
# One engine per process is the deployed shape; the LAST constructed
# engine's ledger answers snapshot()/record_copy() so bench extras and
# flight dumps need no handle plumbing.
_current: Attribution | None = None
_current_lock = threading.Lock()


def set_current(attr: Attribution) -> None:
    global _current
    with _current_lock:
        _current = attr


def current() -> Attribution | None:
    return _current


def snapshot() -> dict[str, Any]:
    """The current ledger's snapshot, or the bare counters when no engine
    has registered one (CLI-only processes)."""
    attr = current()
    if attr is not None:
        return attr.snapshot()
    return {
        "dispatches": 0,
        "bytes_by_kind": {
            k: round(ATTR_BYTES.value(kind=k)) for k in _BYTE_KINDS
        },
    }


def record_copy(nbytes: float, direction: str, seconds: float | None = None) -> None:
    """Offload-tier page-copy attribution (serving/offload/copy.py hooks):
    device<->host page traffic rides the same HBM the decode stream uses,
    so it lands in the ledger as kind="other". Never raises."""
    try:
        ATTR_BYTES.inc(max(0.0, float(nbytes)), kind="other")
        ATTR_DISPATCHES.inc(op=f"offload_{direction}")
        if seconds is not None:
            ATTR_MEASURED_STEP_SECONDS.observe(
                seconds, op=f"offload_{direction}"
            )
        attr = current()
        if attr is not None:
            now = time.perf_counter()
            with attr._lock:
                attr._cum_bytes += float(nbytes)
                attr._window.append(
                    (now, attr._cum_flops, attr._cum_bytes)
                )
    except Exception:  # noqa: BLE001
        pass


def record_goodput(seconds: float, phase: str, slo_class: str = "") -> None:
    """Accumulate request wall seconds into the goodput split. Phases:
    queued / prefill / decode_active / tool_blocked. When the caller
    knows the request's SLO class the same seconds also land in the
    per-class split (opsagent_class_goodput_seconds_total), so "did
    goodput degrade by class during that burst?" is answerable. Never
    raises."""
    try:
        if seconds > 0:
            GOODPUT_SECONDS.inc(float(seconds), phase=phase)
            if slo_class:
                from . import CLASS_GOODPUT_SECONDS

                CLASS_GOODPUT_SECONDS.inc(
                    float(seconds), **{"class": slo_class, "phase": phase}
                )
    except Exception:  # noqa: BLE001
        pass
