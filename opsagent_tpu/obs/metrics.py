"""Dependency-free Prometheus metrics core.

Labeled counters, gauges, and fixed-bucket histograms with text-format
exposition (the ``text/plain; version=0.0.4`` wire format Prometheus
scrapes), mounted as ``GET /metrics`` on both the agent server
(server/app.py) and the serving API (serving/api.py).

Design constraints:

- **No client library**: the container has no prometheus_client, so the
  registry implements the tiny slice of the exposition format the serving
  stack needs (counter / gauge / histogram, labels, HELP/TYPE headers,
  cumulative ``le`` buckets, label-value escaping).
- **Hot-path cheap**: ``Counter.inc`` / ``Histogram.observe`` are a dict
  lookup plus a float add under a per-metric lock — safe to call from the
  engine's dispatch loop, the scheduler thread, and HTTP handlers at once.
- **Idempotent registration**: ``registry.counter(name, ...)`` returns the
  existing instrument when the name is already registered (modules are
  imported in unpredictable orders across tests and entrypoints).
- **Collectors**: callables run at scrape time append extra exposition
  text — used to bridge the legacy PerfStats registry (utils/perf.py) so
  ``/api/perf/stats`` and ``/metrics`` stay consistent without dual
  instrumentation at every call site.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Any, Callable, Iterable

# Default latency buckets (seconds): wide enough to cover a tunneled-TPU
# dispatch (~70 ms RTT) and a cold multi-second prefill in one scheme.
DEFAULT_TIME_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_METRIC_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"
)


def escape_label_value(value: str) -> str:
    """Escape a label value per the exposition format: backslash, double
    quote, and newline must be escaped; everything else passes through."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _format_value(v: float) -> str:
    """Render a sample value: integers without a trailing .0 (Prometheus
    accepts both; the compact form diffs cleanly in golden tests)."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    """Base: one named instrument holding per-label-set children."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...]):
        if not name or not set(name) <= _METRIC_NAME_OK or name[0].isdigit():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], Any] = {}

    def _key(self, labels: dict[str, str] | None) -> tuple[str, ...]:
        labels = labels or {}
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def collect(self) -> list[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0.0)

    def collect(self) -> list[str]:
        with self._lock:
            items = sorted(self._children.items())
        return [
            f"{self.name}{_label_str(self.labelnames, k)} {_format_value(v)}"
            for k, v in items
        ]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0.0)

    def collect(self) -> list[str]:
        with self._lock:
            items = sorted(self._children.items())
        return [
            f"{self.name}{_label_str(self.labelnames, k)} {_format_value(v)}"
            for k, v in items
        ]


class Histogram(_Metric):
    """Fixed-bucket histogram: per-bucket counts are NON-cumulative in
    memory (one increment per observe) and summed cumulatively at collect
    time, so ``observe`` stays O(log buckets)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: tuple[str, ...] = (),
        buckets: Iterable[float] = DEFAULT_TIME_BUCKETS,
    ):
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = tuple(bs)

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        idx = bisect_left(self.buckets, float(value))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                # [per-bucket counts..., +Inf overflow], total count, sum
                child = [[0] * (len(self.buckets) + 1), 0, 0.0]
                self._children[key] = child
            child[0][idx] += 1
            child[1] += 1
            child[2] += float(value)

    def count(self, **labels: str) -> int:
        with self._lock:
            child = self._children.get(self._key(labels))
            return 0 if child is None else child[1]

    def sum(self, **labels: str) -> float:
        with self._lock:
            child = self._children.get(self._key(labels))
            return 0.0 if child is None else child[2]

    def collect(self) -> list[str]:
        with self._lock:
            items = sorted(
                (k, (list(v[0]), v[1], v[2]))
                for k, v in self._children.items()
            )
        out: list[str] = []
        for key, (counts, total, vsum) in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                names = self.labelnames + ("le",)
                vals = key + (_format_value(b),)
                out.append(
                    f"{self.name}_bucket{_label_str(names, vals)} {cum}"
                )
            names = self.labelnames + ("le",)
            out.append(
                f"{self.name}_bucket{_label_str(names, key + ('+Inf',))} "
                f"{total}"
            )
            out.append(
                f"{self.name}_sum{_label_str(self.labelnames, key)} "
                f"{_format_value(vsum)}"
            )
            out.append(
                f"{self.name}_count{_label_str(self.labelnames, key)} {total}"
            )
        return out


class Registry:
    """Named instruments + scrape-time collectors -> exposition text."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list[Callable[[], list[str]]] = []

    def _get_or_make(self, cls, name: str, help: str, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"{name} already registered as {existing.kind}"
                    )
                return existing
            m = cls(name, help, tuple(labelnames), **kw)
            self._metrics[name] = m
            return m

    def counter(
        self, name: str, help: str = "", labelnames: Iterable[str] = ()
    ) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Iterable[str] = ()
    ) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Iterable[str] = (),
        buckets: Iterable[float] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        return self._get_or_make(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def add_collector(self, fn: Callable[[], list[str]]) -> None:
        """Register a scrape-time callable returning extra exposition
        lines (each a complete line, no trailing newline). Idempotent by
        identity."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def render(self) -> str:
        """The full exposition document (ends with a newline)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
            collectors = list(self._collectors)
        lines: list[str] = []
        for m in metrics:
            samples = m.collect()
            if not samples:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(samples)
        for fn in collectors:
            try:
                lines.extend(fn())
            except Exception:  # noqa: BLE001 - one bad collector must not
                continue       # take down the whole scrape
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict[str, Any]:
        """Compact machine-readable dump: counters/gauges as
        ``{name{labels}: value}``; histograms as count/sum pairs. Used by
        bench.py to fold the scrape into BENCH_*.json."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict[str, Any] = {}
        for m in metrics:
            with m._lock:
                children = dict(m._children)
            for key, v in sorted(children.items()):
                tag = m.name + _label_str(m.labelnames, key)
                if isinstance(m, Histogram):
                    out[tag + "_count"] = v[1]
                    out[tag + "_sum"] = round(v[2], 6)
                else:
                    out[tag] = round(v, 6) if isinstance(v, float) else v
        return out

    def reset(self) -> None:
        """Drop every child sample (instruments and collectors stay
        registered). Test isolation hook."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                m._children.clear()


_default: Registry | None = None
_default_lock = threading.Lock()


def get_registry() -> Registry:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                reg = Registry()
                _install_perf_bridge(reg)
                _default = reg
    return _default


def _install_perf_bridge(reg: Registry) -> None:
    """Bridge the legacy PerfStats registry into the scrape: every named
    series appears as ``opsagent_perf{series=...,stat=...}`` gauges, so
    dashboards see the host-path timers next to the first-class engine
    instruments while ``GET /api/perf/stats`` keeps working unchanged."""

    def collect() -> list[str]:
        from ..utils.perf import get_perf_stats

        stats = get_perf_stats().get_stats()
        gauges = stats.pop("gauges", {})
        lines = [
            "# HELP opsagent_perf legacy PerfStats series "
            "(bridged; see /api/perf/stats)",
            "# TYPE opsagent_perf gauge",
        ]
        n = len(lines)
        for name in sorted(stats):
            s = stats[name]
            if not s.get("count"):
                continue
            for stat in ("count", "avg", "p50", "p95", "p99", "max"):
                if stat in s:
                    lines.append(
                        f'opsagent_perf{{series="{escape_label_value(name)}"'
                        f',stat="{stat}",unit="{escape_label_value(s.get("unit", ""))}"}}'
                        f" {_format_value(float(s[stat]))}"
                    )
        for name in sorted(gauges):
            lines.append(
                f'opsagent_perf{{series="{escape_label_value(name)}"'
                f',stat="gauge",unit=""}} {_format_value(float(gauges[name]))}'
            )
        return lines if len(lines) > n else []

    reg.add_collector(collect)
