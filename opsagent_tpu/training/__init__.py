from .trainer import (  # noqa: F401
    TrainConfig,
    cross_entropy_loss,
    init_train_state,
    make_train_step,
    train_param_specs,
)
from .checkpoint import (  # noqa: F401
    latest_step,
    restore_train_state,
    save_train_state,
)
