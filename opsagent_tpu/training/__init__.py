from .trainer import (  # noqa: F401
    TrainConfig,
    cross_entropy_loss,
    init_train_state,
    make_train_step,
)
