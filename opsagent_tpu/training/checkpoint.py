"""Training checkpoint/resume via orbax (SURVEY §5 checkpoint/resume).

The reference has no checkpointing at all — its agent is stateless per
request and its "model" is a remote API (SURVEY §5: checkpoint/resume
"none"). In the TPU-native framework the model and optimizer live in-tree,
so fine-tuning runs need durable, sharding-aware state: save writes each
device's shards (works multi-host — every process writes its own), and
restore reads bytes DIRECTLY into the target sharding, so an 8B+ state
never materializes unsharded on one host.

Layout: ``<dir>/step_<N>/`` orbax checkpoints; ``latest_step`` scans the
directory, so resume-after-crash is "restore latest, keep stepping".
Save is atomic (orbax writes to a tmp dir and renames), so a crash
mid-save never corrupts the previous checkpoint.
"""

from __future__ import annotations

import os
import re
from typing import Any

import jax
import orbax.checkpoint as ocp


def _step_dir(dirpath: str, step: int) -> str:
    return os.path.join(os.path.abspath(dirpath), f"step_{step}")


def latest_step(dirpath: str) -> int | None:
    """Highest completed checkpoint step in ``dirpath``, or None."""
    if not os.path.isdir(dirpath):
        return None
    steps = [
        int(m.group(1))
        for name in os.listdir(dirpath)
        if (m := re.fullmatch(r"step_(\d+)", name))
    ]
    return max(steps) if steps else None


def save_train_state(
    dirpath: str, step: int, params: Any, opt_state: Any
) -> str:
    """Write params + optimizer state for ``step``; returns the path.
    Each process writes its own shards; the rename commit is atomic."""
    path = _step_dir(dirpath, step)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(
        path, {"params": params, "opt_state": opt_state}, force=True
    )
    ckptr.wait_until_finished()
    return path


def restore_train_state(
    dirpath: str, params_like: Any, opt_state_like: Any,
    step: int | None = None,
) -> tuple[Any, Any, int]:
    """Restore (params, opt_state, step). ``params_like``/``opt_state_like``
    are live (or abstract) trees carrying the target shapes, dtypes AND
    shardings — typically fresh ``init_train_state`` output — so every
    array is read straight into its mesh placement; mesh topology may even
    differ from the one that saved (orbax reshards on read).
    """
    if step is None:
        step = latest_step(dirpath)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {dirpath}")
    tmpl = {"params": params_like, "opt_state": opt_state_like}
    # Mesh from the first mesh-sharded leaf; template leaves without a
    # NamedSharding (e.g. optimizer step counters, which jit leaves
    # uncommitted single-device) restore as mesh-replicated — a restored
    # array is COMMITTED to its sharding, and a single-device commit would
    # clash with the mesh-spanning params inside the jitted train step.
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = next(
        (
            x.sharding.mesh
            for x in jax.tree.leaves(tmpl)
            if isinstance(getattr(x, "sharding", None), NamedSharding)
        ),
        None,
    )

    def abstract(x):
        s = getattr(x, "sharding", None)
        if not isinstance(s, NamedSharding) and mesh is not None:
            s = NamedSharding(mesh, PartitionSpec())
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(
        _step_dir(dirpath, step), jax.tree.map(abstract, tmpl)
    )
    return restored["params"], restored["opt_state"], step
