"""Sharded training step (fine-tuning path) for the llama-family models.

The reference has no training capability at all — its "model" is a remote
HTTPS API (reference pkg/llms/openai.go:69). In the TPU-native framework the
model is in-tree, so fine-tuning the served model (e.g. on recorded ops
transcripts to specialize tool-calling) becomes a first-class capability.

Design, tpu-first:

- One jitted train step: loss -> grad -> optax update. Everything inside is
  a single XLA program; no per-layer Python.
- Sharding is declarative: params/opt-state carry the same Megatron-style
  PartitionSpecs as serving (``models.llama.param_specs``); the batch is
  sharded over ``dp`` and the sequence over ``sp``. XLA inserts the psum for
  the gradient all-reduce over dp and the attention collectives over sp.
- Rematerialization (``jax.checkpoint``) on the scanned layer body trades
  FLOPs for HBM, which is what makes long-sequence fine-tuning fit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama
from ..models.config import ModelConfig
from ..parallel.mesh import shard_params


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 1e-5
    weight_decay: float = 0.01
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    remat: bool = True
    # Weight of the MoE router load-balance loss (Switch-style E·Σ f_e·P_e):
    # without it top-k routing is winner-take-all and experts die during
    # fine-tuning. Ignored (aux is 0) for dense models.
    moe_aux_weight: float = 0.01
    # Ring attention over the sp axis (context parallelism): K/V shards
    # rotate via ppermute instead of XLA's default all-gather of the whole
    # sequence — peak memory O(S/sp) per device, enabling sequences that
    # cannot fit gathered. No-op on meshes with sp=1.
    ring_attention: bool = False
    # Microbatches for GPipe pipelining when the mesh has pp > 1 (see
    # parallel/pipeline.py). Bubble fraction = (pp-1)/(microbatches+pp-1).
    pp_microbatches: int = 4


def cross_entropy_loss(
    logits: jax.Array,    # [B, S, V] float32
    targets: jax.Array,   # [B, S] int32
    mask: jax.Array,      # [B, S] float/bool — 0 for padding positions
) -> jax.Array:
    """Token-mean masked cross entropy, accumulated in float32."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(
            tc.learning_rate, b1=tc.b1, b2=tc.b2, weight_decay=tc.weight_decay
        ),
    )


def init_train_state(
    cfg: ModelConfig,
    tc: TrainConfig,
    mesh: Mesh,
    key: jax.Array,
    dtype: jnp.dtype = jnp.bfloat16,
    params: Any | None = None,
) -> tuple[Any, Any]:
    """(params, opt_state), both placed on the mesh. The optimizer moments
    are created with ``zeros_like`` over already-sharded params, so they
    inherit the parameter shardings with no extra spec tree."""
    if params is None:
        params = llama.init_params(cfg, key, dtype=dtype)
    params = shard_params(params, train_param_specs(cfg, mesh), mesh)
    opt_state = jax.jit(make_optimizer(tc).init)(params)
    return params, opt_state


def train_param_specs(cfg: ModelConfig, mesh: Mesh) -> Any:
    """Parameter PartitionSpecs for training on this mesh: pp-staged layer
    stacks when the mesh pipelines, the serving specs otherwise. Validates
    pipelineability HERE so unsupported configs fail with a clear error at
    state-init time, not a cryptic device_put divisibility failure."""
    pp = mesh.shape.get("pp", 1)
    if pp > 1:
        if cfg.moe is not None:
            _, lm = llama._layer_split(cfg)
            if lm % pp:
                raise ValueError(
                    f"moe layers {lm} not divisible by pp={pp}"
                )
        elif cfg.num_layers % pp:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by pp={pp}"
            )
        from ..parallel.pipeline import param_specs_pp

        return param_specs_pp(cfg)
    return llama.param_specs(cfg)


def make_train_step(
    cfg: ModelConfig,
    tc: TrainConfig,
    mesh: Mesh,
    dtype: jnp.dtype = jnp.bfloat16,
):
    """Build the jitted train step.

    step(params, opt_state, tokens [B,S], loss_mask [B,S]) ->
        (params, opt_state, metrics dict)

    ``tokens`` is next-token-shifted internally; ``loss_mask`` marks which
    *target* positions count (e.g. assistant turns only, for transcript
    fine-tuning). Data enters sharded P(dp, sp).
    """
    opt = make_optimizer(tc)
    data_sharding = NamedSharding(mesh, P("dp", "sp"))
    prefill_attn = None
    if tc.ring_attention and mesh.shape.get("sp", 1) > 1:
        from ..parallel.ring import make_ring_attention

        prefill_attn = make_ring_attention(mesh)

    if mesh.shape.get("pp", 1) > 1:
        # GPipe microbatch pipeline over the pp axis (parallel/pipeline.py);
        # params must carry param_specs_pp (init_train_state does). With
        # sp > 1 the stage runs ring attention over the sp axis inside
        # the pipeline's own shard_map (pp x sp composition — long-context
        # training across pipeline stages).
        from ..parallel.pipeline import make_pipeline_loss

        loss_fn = make_pipeline_loss(
            cfg, mesh, tc.pp_microbatches, dtype=dtype, remat=tc.remat,
            moe_aux_weight=tc.moe_aux_weight,
        )
    else:
        def loss_fn(params, tokens, loss_mask):
            # Attention runs over the full (evenly sp-shardable) sequence;
            # the next-token shift happens on the logits. Slicing tokens to
            # an odd length BEFORE the model makes XLA pad the sp shards
            # unevenly, and the padded attention lanes (scores -1e30,
            # squared in the backward) overflow to inf -> NaN grads.
            # Shift-at-the-loss avoids it.
            logits, aux = llama.forward_full(
                params, cfg, tokens, dtype=dtype, remat=tc.remat,
                return_aux=True, prefill_attn=prefill_attn,
            )
            ce = cross_entropy_loss(
                logits[:, :-1], tokens[:, 1:], loss_mask[:, 1:]
            )
            return ce + tc.moe_aux_weight * aux, (ce, aux)

    def step(params, opt_state, tokens, loss_mask):
        (_, (ce, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, tokens, loss_mask
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {
            "loss": ce, "moe_aux": aux, "grad_norm": gnorm,
        }

    jitted = jax.jit(
        step,
        in_shardings=(None, None, data_sharding, data_sharding),
        donate_argnums=(0, 1),
    )

    def run(params, opt_state, tokens, loss_mask):
        with mesh:
            return jitted(params, opt_state, tokens, loss_mask)

    return run
