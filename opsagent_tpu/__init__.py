"""opsagent_tpu: a TPU-native Kubernetes AI agent framework.

Two halves, one wire format (OpenAI chat.completions + tool_calls):

- The agent/host layer (``agent/``, ``tools/``, ``workflows/``, ``server/``,
  ``cli/``, ``k8s/``, ``llm/``, ``utils/``) reproduces the capability surface of
  the reference Go agent (myysophia/OpsAgent, see SURVEY.md): a ReAct loop over
  kubectl/python/trivy/jq tools, a JWT-protected REST API, and a CLI.

- The TPU serving engine (``models/``, ``ops/``, ``parallel/``, ``serving/``)
  replaces the reference's remote LLM providers (reference pkg/llms/openai.go)
  with an in-tree JAX/XLA inference engine: tensor-parallel sharding over a
  device mesh, paged KV cache with a Pallas kernel, continuous batching, and
  on-device constrained decoding of function-call JSON, reachable through a
  ``tpu://`` model provider.

JAX is imported lazily: the agent layer works without touching the accelerator.
"""

__version__ = "0.1.0"

# CLI-facing version string (reference: cmd/kube-copilot/server.go:29 uses
# "v1.0.2" while pkg/handlers/version.go:8 says "v1.0.18"; we use one).
VERSION = "v0.1.0"
