#!/usr/bin/env python
"""End-to-end agent run on REAL open-weights checkpoints.

Proves the capability the reference buys from a remote GPT-4 call
(reference pkg/handlers/execute.go:205): a locally-served model answering
a k8s ops question through the full in-tree stack —

    HF safetensors checkpoint + HF tokenizer
      -> models.loader -> serving.Engine (paged KV, constrained decode)
      -> ServingStack (chat template, OpenAI wire)
      -> tpu:// provider -> ReAct agent loop
      -> kubectl REPLAY tool (canned transcripts; no cluster needed)
      -> final answer,

with zero external API calls. Writes a markdown transcript of every agent
turn (model output, tool call, observation) for the record.

Usage:
    python scripts/run_real_checkpoint.py \
        --checkpoint /path/to/Llama-3-8B-Instruct \
        --model-name llama-3-8b-instruct \
        [--tokenizer /path/...] [--quantize int8] \
        [--instruction "count namespaces"] \
        [--transcript transcripts/real_run.md]

The checkpoint dir must hold HF-format .safetensors (single file or
index-sharded) and tokenizer files. On a 16 GB v5e chip an 8B model needs
--quantize int8. Exits non-zero if the agent fails to produce a final
answer. The same flow runs hermetically (tiny model, byte tokenizer) in
tests/test_real_checkpoint.py when no checkpoint is available.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint", default=os.environ.get("OPSAGENT_CHECKPOINT", ""))
    ap.add_argument("--model-name",
                    default=os.environ.get("OPSAGENT_MODEL_NAME", "auto"),
                    help="preset name, or 'auto' to derive the architecture "
                         "from the checkpoint dir's config.json "
                         "(models.config.config_from_hf)")
    ap.add_argument("--tokenizer", default="", help="defaults to the checkpoint dir")
    ap.add_argument("--quantize", default="", choices=("", "int8", "int4"))
    ap.add_argument("--tp", type=int, default=0)
    ap.add_argument("--num-pages", type=int, default=0,
                    help="KV page pool size (0 = engine default); raise "
                         "for long prompts / verbose tokenizers")
    ap.add_argument("--max-pages-per-seq", type=int, default=0,
                    help="per-sequence page cap (0 = engine default)")
    ap.add_argument("--instruction", default="count namespaces")
    ap.add_argument("--max-iterations", type=int, default=5)
    ap.add_argument("--transcript", default="")
    args = ap.parse_args()

    if not args.checkpoint:
        print("no --checkpoint / OPSAGENT_CHECKPOINT given", file=sys.stderr)
        return 2

    import jax.numpy as jnp

    from opsagent_tpu.serving.api import ServingStack, install_stack
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    from opsagent_tpu.models.config import resolve_model

    model_name, model_cfg = resolve_model(args.model_name, args.checkpoint)
    if model_cfg is not None:
        print(f"config.json -> {model_name}: {model_cfg.num_layers}L "
              f"d={model_cfg.hidden_size} heads={model_cfg.num_heads}/"
              f"{model_cfg.num_kv_heads} vocab={model_cfg.vocab_size}",
              file=sys.stderr)

    t0 = time.perf_counter()
    overrides = {}
    if args.num_pages:
        overrides["num_pages"] = args.num_pages
    if args.max_pages_per_seq:
        overrides["max_pages_per_seq"] = args.max_pages_per_seq
    engine = Engine(EngineConfig(
        model=model_name,
        checkpoint=args.checkpoint,
        tokenizer=args.tokenizer or args.checkpoint,
        quantize=args.quantize,
        tp=args.tp,
        dtype=jnp.bfloat16,
        **overrides,
    ), model_cfg=model_cfg)
    print(f"engine up (weights loaded+sharded) in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    stack = ServingStack(engine)
    install_stack("real", stack)

    # kubectl replay on PATH: the agent's tool layer runs `bash -c`, so a
    # script shadowing kubectl serves canned cluster state.
    from opsagent_tpu.tools.replay import CLUSTER_SCRIPT, install_replay_kubectl

    install_replay_kubectl(CLUSTER_SCRIPT)

    from opsagent_tpu.agent.prompts import REACT_SYSTEM_PROMPT
    from opsagent_tpu.agent.react import assistant_with_config

    messages = [
        {"role": "system", "content": REACT_SYSTEM_PROMPT},
        {"role": "user",
         "content": f"Here are the instructions: {args.instruction}"},
    ]
    t0 = time.perf_counter()
    answer, history = assistant_with_config(
        "tpu://real", messages, 2048, True, True, args.max_iterations, "", ""
    )
    dt = time.perf_counter() - t0
    print(f"agent loop finished in {dt:.1f}s", file=sys.stderr)

    lines = [
        "# Real-checkpoint agent transcript",
        "",
        f"- model: `{args.model_name}`  checkpoint: `{args.checkpoint}`",
        f"- quantize: `{args.quantize or 'none'}`  instruction: "
        f"`{args.instruction}`",
        f"- agent wall time: {dt:.1f}s",
        "",
    ]
    for msg in history:
        role = msg.get("role", "?")
        content = msg.get("content", "")
        lines += [f"## {role}", "", "```", str(content), "```", ""]
    lines += ["## final answer", "", str(answer), ""]
    transcript = "\n".join(lines)
    if args.transcript:
        os.makedirs(os.path.dirname(args.transcript) or ".", exist_ok=True)
        with open(args.transcript, "w", encoding="utf-8") as f:
            f.write(transcript)
        print(f"transcript written to {args.transcript}", file=sys.stderr)
    else:
        print(transcript)

    stack.close()
    ok = bool(answer and answer.strip())
    print(json.dumps({"ok": ok, "answer": answer[:200]}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
