#!/usr/bin/env python
"""Summarize bench JSONL results — terminal decision matrix AND the
generator for PERF.md's measurement table.

Usage:
    python scripts/bench_summary.py tpu_results_*/bench.jsonl
        # terminal summary (tok/s/chip + TTFT side by side, decision
        # answers: fastest 8B variant, kernel verdict, TTFT vs target)
    python scripts/bench_summary.py --perf-md [BENCH_r*_local.jsonl ...]
        # print the markdown measurement table generated from the
        # committed raw lines
    python scripts/bench_summary.py --update-perf [--check]
        # rewrite (or, with --check, verify) the generated block in
        # PERF.md between the BEGIN/END markers

PERF.md's "Measured so far" table is GENERATED from the committed
``BENCH_r*_local.jsonl`` raw lines — the same numbers, one source, so the
copies in PERF.md / BENCH artifacts / the jsonl cannot drift (VERDICT
weak #7: three hand-maintained copies of r04's numbers). A fast-lane test
runs ``--update-perf --check`` so CI catches a hand-edit or a stale
table.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GEN_BEGIN = "<!-- BEGIN bench_summary (generated; do not edit by hand) -->"
GEN_END = "<!-- END bench_summary -->"


def _round_of(path: str) -> str:
    m = re.search(r"BENCH_(r\d+)", os.path.basename(path))
    return m.group(1) if m else os.path.basename(path)


def load_rows(paths: list[str]) -> list[dict]:
    rows = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "metric" in d:
                    d["_round"] = _round_of(path)
                    rows.append(d)
    return rows


def _dedupe(rows: list[dict]) -> list[dict]:
    """The orchestrator's combined headline repeats a stage's metric/value
    with extra cross-stage keys folded in; keep ONE row per
    (round, metric, value) — the first, which is the stage's own line."""
    seen: set[tuple] = set()
    out = []
    for d in rows:
        key = (d["_round"], d["metric"], d.get("value"))
        if key in seen:
            continue
        seen.add(key)
        out.append(d)
    return out


def perf_md_table(paths: list[str]) -> str:
    rows = _dedupe(load_rows(paths))
    lines = [
        "| Round | Metric | Value | Unit | p50 TTFT (ms) | Backend "
        "| vs target |",
        "|---|---|---|---|---|---|---|",
    ]
    for d in rows:
        e = d.get("extra", {})
        vb = d.get("vs_baseline")
        p50 = e.get("p50_ttft_ms")
        lines.append(
            f"| {d['_round']} "
            f"| `{d['metric']}` "
            f"| {d['value']} "
            f"| {d.get('unit', '')} "
            f"| {p50 if p50 is not None else '—'} "
            f"| {e.get('paged_backend') or '—'} "
            f"| {f'{vb}×' if vb is not None else '—'} |"
        )
    return "\n".join(lines)


def update_perf_md(
    perf_path: str, paths: list[str], check: bool = False
) -> int:
    with open(perf_path) as f:
        text = f.read()
    if GEN_BEGIN not in text or GEN_END not in text:
        print(
            f"{perf_path} has no {GEN_BEGIN!r} / {GEN_END!r} markers",
            file=sys.stderr,
        )
        return 1
    head, rest = text.split(GEN_BEGIN, 1)
    _, tail = rest.split(GEN_END, 1)
    new = head + GEN_BEGIN + "\n" + perf_md_table(paths) + "\n" + GEN_END + tail
    if new == text:
        return 0
    if check:
        print(
            f"{perf_path} generated table is out of sync with the "
            f"BENCH_r*_local.jsonl raw lines; run "
            f"`python scripts/bench_summary.py --update-perf`",
            file=sys.stderr,
        )
        return 1
    with open(perf_path, "w") as f:
        f.write(new)
    print(f"updated {perf_path}")
    return 0


def terminal_summary(paths: list[str]) -> int:
    rows = load_rows(paths)
    if not rows:
        print("no result lines found", file=sys.stderr)
        return 1

    print(f"{'metric':58s} {'tok/s/chip':>10s} {'p50(ms)':>8s} "
          f"{'backend':>10s} {'vs_base':>8s}")
    for d in rows:
        e = d.get("extra", {})
        vb = d.get("vs_baseline")
        print(f"{d['metric'][:58]:58s} {d['value']:>10.1f} "
              f"{e.get('p50_ttft_ms', 0) or 0:>8.0f} "
              f"{e.get('paged_backend', '') or '-':>10s} "
              f"{vb if vb is not None else '-':>8}")

    # Decision answers (best-effort from metric names).
    tpu = [d for d in rows if ",tpu]" in d["metric"]]
    # tok/s rows only: agent_turn_ttft rows carry ms values that would
    # otherwise compete with throughputs in the max() below.
    eight_b = [d for d in tpu if "bench-8b" in d["metric"]
               and "concurrent" not in d["metric"]
               and d.get("unit") == "tok/s/chip"]
    if eight_b:
        best = max(eight_b, key=lambda d: d["value"])
        print(f"\nfastest 8B variant: {best['metric']} "
              f"at {best['value']:.0f} tok/s/chip "
              f"({'>=' if best['value'] >= 2000 else '<'} 2000 target)")
        dma = [d for d in eight_b
               if d.get("extra", {}).get("paged_backend") == "pallas-dma"]
        xla = [d for d in eight_b
               if d.get("extra", {}).get("paged_backend") in ("", "xla")]
        if dma and xla:
            print(f"kernel verdict: pallas-dma best "
                  f"{max(d['value'] for d in dma):.0f} vs xla best "
                  f"{max(d['value'] for d in xla):.0f}")
    # Ragged-backend sweep (the MIXED hot path): best cell per RESOLVED
    # impl, with the byte-identical verdict — the decision input for
    # flipping paged_attention_backend()'s default.
    sweep = [d for d in rows
             if d["metric"].startswith("mixed_ragged_throughput")
             and "best_cell" not in d.get("extra", {})]
    if sweep:
        by_impl: dict[str, float] = {}
        for d in sweep:
            impl = d.get("extra", {}).get("attn_impl", "?")
            by_impl[impl] = max(by_impl.get(impl, 0.0), d["value"])
        ident = all(
            d.get("extra", {}).get("outputs_identical") for d in sweep
        )
        print("mixed-ragged sweep: "
              + "; ".join(f"{k} best {v:.0f}"
                          for k, v in sorted(by_impl.items()))
              + f" tok/s/chip over {len(sweep)} cells; outputs "
              f"identical: {ident}")
    sess = [d for d in tpu if "concurrent_sessions" in d["metric"]]
    if sess:
        # Best (lowest-TTFT) row, not positionally last: multiple files
        # may contribute sessions rows in arbitrary order.
        best_sess = min(
            sess, key=lambda d: d.get("extra", {}).get("p50_ttft_ms", 1e12)
        )
        p50 = best_sess.get("extra", {}).get("p50_ttft_ms", 0)
        print(f"sessions p50 TTFT (best of {len(sess)}): {p50:.0f} ms "
              f"({'<' if p50 < 500 else '>='} 500 ms target)")
    sasync = [d for d in tpu if d["metric"].startswith("sessions_async")]
    if sasync:
        d = sasync[-1]
        e = d.get("extra", {})
        print(
            f"async A/B: host-gap p50 {e.get('host_gap_p50_ms', 0)} ms "
            f"(depth=2) vs {e.get('sync_host_gap_p50_ms', 0)} ms "
            f"(depth=1); tok/s/chip {d['value']} vs "
            f"{e.get('sync_tok_s_chip', 0)}; outputs identical: "
            f"{e.get('outputs_identical')}"
        )
    sffwd = [d for d in tpu if d["metric"].startswith("sessions_ffwd")]
    if sffwd:
        d = sffwd[-1]
        e = d.get("extra", {})
        frac = e.get("forced_fraction", 0) or 0
        print(
            f"ffwd A/B: tok/s/chip {d['value']} (on) vs "
            f"{e.get('off_tok_s_chip', 0)} (off); forced fraction "
            f"{frac:.1%} ({e.get('skipped_dispatches', 0)} dispatches "
            f"skipped); outputs identical: {e.get('outputs_identical')}"
        )
    soff = [d for d in tpu if d["metric"].startswith("sessions_offload")]
    if soff:
        e = soff[-1].get("extra", {})
        print(
            f"offload A/B: admission-wait p50 "
            f"{e.get('admission_wait_p50_ms', 0)} ms (on) vs "
            f"{e.get('off_admission_wait_p50_ms', 0)} ms (off); "
            f"re-prefill avoided {e.get('reprefill_avoided_tokens', 0)} tok"
        )
    fleet = [d for d in tpu if d["metric"].startswith("fleet_affinity")]
    if fleet:
        e = fleet[-1].get("extra", {})
        print(
            f"fleet A/B ({e.get('replicas', '?')} replicas): p50 TTFT "
            f"{e.get('p50_ttft_ms', 0)} ms (affinity) vs "
            f"{e.get('off_p50_ttft_ms', 0)} ms (round-robin); "
            f"re-prefill avoided {e.get('reprefill_avoided_tokens', 0)} "
            f"vs {e.get('off_reprefill_avoided_tokens', 0)} tok"
        )
    fgkv = [d for d in tpu if d["metric"].startswith("fleet_global_kv")]
    if fgkv:
        e = fgkv[-1].get("extra", {})
        print(
            f"fleet-global-KV A/B ({e.get('replicas', '?')} replicas "
            f"+{e.get('standby', 0)} standby): "
            f"{e.get('remote_hit_pages', 0)} pages faulted in peer-to-peer "
            f"(vs {e.get('off_remote_hit_pages', 0)} off); re-prefill "
            f"avoided {e.get('reprefill_avoided_tokens', 0)} vs "
            f"{e.get('off_reprefill_avoided_tokens', 0)} tok; moved-turn "
            f"p50 {e.get('p50_moved_ms', 0)} ms (on) vs "
            f"{e.get('off_p50_moved_ms', 0)} ms (off); outputs identical: "
            f"{e.get('outputs_identical')}, standby: "
            f"{e.get('standby_identical')}"
        )
    chaos = [d for d in tpu if d["metric"].startswith("fleet_chaos")]
    if chaos:
        e = chaos[-1].get("extra", {})
        print(
            f"chaos A/B ({e.get('replicas', '?')} replicas, spec "
            f"{e.get('spec', '?')!r}): {e.get('failed_requests', '?')} "
            f"failed requests under {e.get('injected', 0)} injected "
            f"faults ({e.get('failovers', 0)} failovers, "
            f"{e.get('retries', 0)} retries, {e.get('shed', 0)} shed); "
            f"p99 TTFT {e.get('p99_ttft_ms', 0)} ms (chaos) vs "
            f"{e.get('off_p99_ttft_ms', 0)} ms (clean); outputs "
            f"identical: {e.get('outputs_identical')}"
        )
    coldst = [d for d in tpu
              if d["metric"].startswith("cold_start_request_ready")]
    if coldst:
        d = coldst[-1]
        e = d.get("extra", {})
        print(
            f"cold-start A/B: request-ready "
            f"{e.get('restore_request_ready_s', d['value'])} s (snapshot "
            f"restore) vs {e.get('fresh_request_ready_s', 0)} s (fresh "
            f"init) = {e.get('speedup_ratio', 0)}x; outputs identical: "
            f"{e.get('outputs_identical')}; post-warmup compiles on "
            f"restore: {e.get('post_warmup_compiles')}"
        )
    agent = [d for d in tpu if d["metric"].startswith("agent_turn_ttft")]
    if agent:
        best_a = min(agent, key=lambda d: d["value"])
        hr = best_a.get("extra", {}).get("prefix_hit_rate")
        print(f"agent tool-call-turn p50 TTFT (best of {len(agent)}): "
              f"{best_a['value']:.0f} ms "
              f"({'<' if best_a['value'] < 500 else '>='} 500 ms target); "
              f"prefix hit rate {hr}")
    # Conveyor A/B runs on CPU too — match across all rows, not just tpu.
    convey = [d for d in rows if d["metric"].startswith("agent_conveyor")]
    if convey:
        d = convey[-1]
        e = d.get("extra", {})
        print(
            f"conveyor A/B: agent turn p50 {d['value']:.0f} ms (on) vs "
            f"{e.get('off_p50_ms', 0):.0f} ms (off); "
            f"{e.get('overlap_ms_per_turn', 0)} ms/turn tool time hidden "
            f"behind decode ({e.get('early_launches', 0)} early "
            f"launches); transcripts identical: "
            f"{e.get('outputs_identical')}"
        )
    # SLO verdicts folded into the lines (bench.py extra.slo), newest last.
    slo_rows = [d for d in rows if d.get("extra", {}).get("slo")]
    if slo_rows:
        verdicts = slo_rows[-1]["extra"]["slo"].get("slos", [])
        breached = [v["name"] for v in verdicts if v.get("pass") is False]
        print(f"declared SLOs: {len(verdicts)} evaluated, "
              f"{'breached: ' + ', '.join(breached) if breached else 'all passing'}")
    return 0


def _default_local_jsonls() -> list[str]:
    return sorted(glob.glob(os.path.join(REPO, "BENCH_r*_local.jsonl")))


def main(argv: list[str]) -> int:
    check = "--check" in argv
    argv = [a for a in argv if a != "--check"]
    if argv and argv[0] == "--perf-md":
        paths = argv[1:] or _default_local_jsonls()
        print(perf_md_table(paths))
        return 0
    if argv and argv[0] == "--update-perf":
        paths = argv[1:] or _default_local_jsonls()
        return update_perf_md(
            os.path.join(REPO, "PERF.md"), paths, check=check
        )
    return terminal_summary(argv or ["tpu_results_r04/bench.jsonl"])


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
