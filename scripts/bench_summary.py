#!/usr/bin/env python
"""Summarize a bench.jsonl (from bench.py / scripts/tpu_measure.sh) into
the decision matrix PERF.md keys its defaults on.

Usage: python scripts/bench_summary.py tpu_results_*/bench.jsonl

Groups result lines by configuration, prints tok/s/chip + TTFT side by
side, and answers the open questions explicitly: fastest 8B variant
(headline candidate), xla-vs-pallas-dma kernel verdict, sessions p50
TTFT vs the 500 ms target, cold-restart numbers.
"""

from __future__ import annotations

import json
import sys


def main(paths: list[str]) -> int:
    rows = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "metric" in d:
                    rows.append(d)
    if not rows:
        print("no result lines found", file=sys.stderr)
        return 1

    print(f"{'metric':58s} {'tok/s/chip':>10s} {'p50(ms)':>8s} "
          f"{'backend':>10s} {'vs_base':>8s}")
    for d in rows:
        e = d.get("extra", {})
        vb = d.get("vs_baseline")
        print(f"{d['metric'][:58]:58s} {d['value']:>10.1f} "
              f"{e.get('p50_ttft_ms', 0) or 0:>8.0f} "
              f"{e.get('paged_backend', '') or '-':>10s} "
              f"{vb if vb is not None else '-':>8}")

    # Decision answers (best-effort from metric names).
    tpu = [d for d in rows if ",tpu]" in d["metric"]]
    # tok/s rows only: agent_turn_ttft rows carry ms values that would
    # otherwise compete with throughputs in the max() below.
    eight_b = [d for d in tpu if "bench-8b" in d["metric"]
               and "concurrent" not in d["metric"]
               and d.get("unit") == "tok/s/chip"]
    if eight_b:
        best = max(eight_b, key=lambda d: d["value"])
        print(f"\nfastest 8B variant: {best['metric']} "
              f"at {best['value']:.0f} tok/s/chip "
              f"({'>=' if best['value'] >= 2000 else '<'} 2000 target)")
        dma = [d for d in eight_b
               if d.get("extra", {}).get("paged_backend") == "pallas-dma"]
        xla = [d for d in eight_b
               if d.get("extra", {}).get("paged_backend") in ("", "xla")]
        if dma and xla:
            print(f"kernel verdict: pallas-dma best "
                  f"{max(d['value'] for d in dma):.0f} vs xla best "
                  f"{max(d['value'] for d in xla):.0f}")
    sess = [d for d in tpu if "concurrent_sessions" in d["metric"]]
    if sess:
        # Best (lowest-TTFT) row, not positionally last: multiple files
        # may contribute sessions rows in arbitrary order.
        best_sess = min(
            sess, key=lambda d: d.get("extra", {}).get("p50_ttft_ms", 1e12)
        )
        p50 = best_sess.get("extra", {}).get("p50_ttft_ms", 0)
        print(f"sessions p50 TTFT (best of {len(sess)}): {p50:.0f} ms "
              f"({'<' if p50 < 500 else '>='} 500 ms target)")
    agent = [d for d in tpu if d["metric"].startswith("agent_turn_ttft")]
    if agent:
        best_a = min(agent, key=lambda d: d["value"])
        hr = best_a.get("extra", {}).get("prefix_hit_rate")
        print(f"agent tool-call-turn p50 TTFT (best of {len(agent)}): "
              f"{best_a['value']:.0f} ms "
              f"({'<' if best_a['value'] < 500 else '>='} 500 ms target); "
              f"prefix hit rate {hr}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:] or ["tpu_results_r04/bench.jsonl"]))
