#!/usr/bin/env python
"""Decode-step microprofiler: times each device-side component of the
serving hot loop in isolation, so throughput work targets measurement
instead of guesses (VERDICT round-1: "nothing is measured or profiled").

Methodology: on tunneled/async TPU backends ``jax.block_until_ready`` does
NOT block and a device->host sync costs a large fixed RTT, so naive
per-call timing is meaningless. Every measurement here (a) loops the
component N times INSIDE one jitted program (``lax.fori_loop`` with a
data dependence so XLA cannot elide iterations), (b) pulls one scalar to
synchronize, and (c) subtracts the separately measured RTT.

Pieces timed (ms per iteration, medians over --trials runs):
  matmul-floor   the transformer stack's matmuls only — the
                 weight-streaming floor for one decode step
  lm_head        final projection [B, D] @ [D, V]
  write_kv       all layers' paged KV scatter (cache as loop carry)
  attn[xla]      paged decode attention, XLA gather reference, all layers
  attn[pallas]   paged decode attention, Pallas kernel, all layers
  decode_block   the full fused block (decode_loop.decode_block), per step

Optionally wraps a run in a jax.profiler trace (--trace DIR) for
tensorboard/xprof.

Usage: python scripts/profile_decode.py [--model bench-1b] [--batch 32]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def measure_rtt() -> float:
    """Median wall time of dispatch + device->host sync for a tiny op."""
    s = jnp.zeros((4,), jnp.int32)
    g = jax.jit(lambda a: a + 1)
    r = g(s)
    _ = np.asarray(r)
    ts = []
    for _ in range(8):
        t0 = time.perf_counter()
        r = g(r)
        _ = np.asarray(r)
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="bench-1b")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--max-pages", type=int, default=12)
    ap.add_argument("--seq-len", type=int, default=256, help="tokens in cache")
    ap.add_argument("--loops", type=int, default=32)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--trace", default="", help="jax.profiler trace dir")
    args = ap.parse_args()

    from opsagent_tpu.models import llama
    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.ops.attention import paged_decode_attention, write_kv_pages
    from opsagent_tpu.serving.decode_loop import decode_block

    cfg = get_config_preset(args.model)
    on_tpu = jax.devices()[0].platform == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    B, P, MaxP = args.batch, args.page_size, args.max_pages
    N = B * MaxP
    K, D, H = cfg.num_kv_heads, cfg.head_dim_, cfg.num_heads
    d = cfg.hidden_size
    LOOPS = args.loops

    print(f"profile: model={args.model} B={B} dtype={dtype.__name__} "
          f"pages N={N} P={P} MaxP={MaxP} seq_len={args.seq_len}")

    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    cache = llama.make_cache(cfg, N, P, dtype=dtype)
    bytes_param = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    print(f"profile: {bytes_param/1e9:.2f} GB params -> HBM floor "
          f"~{bytes_param/819e9*1e3:.2f} ms/step (v5e 819GB/s)")

    R = measure_rtt()
    print(f"profile: host<->device RTT ~{R*1e3:.1f} ms "
          f"(subtracted from every row)\n")

    used = -(-args.seq_len // P)
    table = np.full((B, MaxP), -1, np.int32)
    for b in range(B):
        table[b, :used] = np.arange(b * used, (b + 1) * used) % N
    table_j = jnp.asarray(table)
    lengths = jnp.full((B,), args.seq_len, jnp.int32)

    results: dict[str, float] = {}

    def loop_time(name, jfn, *fargs):
        r = jfn(*fargs)  # compile + warm
        _ = np.asarray(jax.tree.leaves(r)[0].ravel()[0:1])
        ts = []
        for _ in range(args.trials):
            t0 = time.perf_counter()
            r = jfn(*fargs)
            _ = np.asarray(jax.tree.leaves(r)[0].ravel()[0:1])
            ts.append(time.perf_counter() - t0)
        results[name] = (sorted(ts)[args.trials // 2] - R) / LOOPS * 1e3

    # -- matmul floor (full stack, no attention/cache) -----------------------
    def stack_mm(x, p):
        def body(x, lp):
            h = llama.rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q = h @ lp["wq"]
            x = x + q @ lp["wo"] + (h @ lp["wk"] + h @ lp["wv"]).sum() * 1e-9
            h2 = llama.rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            x = x + (jax.nn.silu(h2 @ lp["wg"]) * (h2 @ lp["wu"])) @ lp["wd"]
            return x, None
        x, _ = jax.lax.scan(body, x, p["layers"])
        return x

    @jax.jit
    def mm_loop(x, p):
        return jax.lax.fori_loop(0, LOOPS, lambda i, x: stack_mm(x, p), x)

    loop_time("matmul-floor", mm_loop, jnp.ones((B, d), dtype), params)

    # -- lm head -------------------------------------------------------------
    @jax.jit
    def head_loop(x, p):
        W = p.get("lm_head", p["embed"].T)

        def body(i, x):
            return x + (x @ W)[:, :d] * 1e-6

        return jax.lax.fori_loop(0, LOOPS, body, x)

    loop_time("lm_head", head_loop, jnp.ones((B, d), dtype), params)

    # -- KV page write, all layers (cache as carry, layer-indexed) -----------
    kn = jnp.ones((B, 1, K, D), dtype)

    @jax.jit
    def wkv_loop(cache, kn):
        def one(i, cache):
            def body(carry, _):
                kc, vc, li = carry
                kc, vc = write_kv_pages(
                    kc, vc, kn, kn, table_j, lengths,
                    jnp.ones((B,), jnp.int32), layer=li,
                )
                return (kc, vc, li + 1), None
            (kc, vc, _), _ = jax.lax.scan(
                body, (cache["k"], cache["v"], jnp.int32(0)), None,
                length=cfg.num_layers,
            )
            return {"k": kc, "v": vc}
        return jax.lax.fori_loop(0, LOOPS, one, cache)

    loop_time("write_kv (all layers)", wkv_loop, cache, kn)

    # -- paged decode attention, all layers, both impls ----------------------
    def attn_all_layers(q, cache, fn):
        def body(carry, _):
            s, li = carry
            o = fn(q, cache["k"], cache["v"], table_j, lengths, li)
            return (s + o.astype(jnp.float32).mean() * 1e-9, li + 1), None
        (s, _), _ = jax.lax.scan(
            body, (jnp.float32(0), jnp.int32(0)), None, length=cfg.num_layers
        )
        return q + s.astype(dtype) * 1e-6

    @jax.jit
    def attn_xla_loop(q, cache):
        fn = lambda q, kc, vc, t, ln, li: paged_decode_attention(
            q, kc, vc, t, ln, layer=li
        )
        return jax.lax.fori_loop(
            0, LOOPS, lambda i, q: attn_all_layers(q, cache, fn), q
        )

    loop_time("attn[xla] (all layers)", attn_xla_loop,
              jnp.ones((B, H, D), dtype), cache)

    if on_tpu:
        from opsagent_tpu.ops.paged_attention_pallas import (
            paged_decode_attention_pallas,
        )

        @jax.jit
        def attn_pl_loop(q, cache):
            fn = lambda q, kc, vc, t, ln, li: paged_decode_attention_pallas(
                q, kc, vc, t, ln, layer=li
            )
            return jax.lax.fori_loop(
                0, LOOPS, lambda i, q: attn_all_layers(q, cache, fn), q
            )

        loop_time("attn[pallas] (all layers)", attn_pl_loop,
                  jnp.ones((B, H, D), dtype), cache)

    # -- full decode block ----------------------------------------------------
    for impl in (("pallas", "xla") if on_tpu else ("xla",)):
        @jax.jit
        def block_loop(p, cache, tok, wr, act, bud, _impl=impl):
            toks, cache, _ = decode_block(
                p, cfg, tok, wr, act, bud, cache, table_j,
                jax.random.PRNGKey(0),
                jnp.zeros((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
                jnp.ones((B,), jnp.float32),
                jnp.int32(1), jnp.int32(0), n_steps=LOOPS, greedy=True,
                dtype=dtype, attn_impl=_impl,
            )
            return toks

        fargs = (params, cache, jnp.zeros((B,), jnp.int32), lengths,
                 jnp.ones((B,), bool), jnp.full((B,), LOOPS, jnp.int32))
        loop_time(f"decode_block[{impl}] per step", block_loop, *fargs)
        if args.trace and impl == "xla":
            with jax.profiler.trace(args.trace):
                r = block_loop(*fargs)
                _ = np.asarray(r.ravel()[0:1])
            print(f"profile: jax.profiler trace written to {args.trace}")

    width = max(len(k) for k in results)
    for k, v in results.items():
        print(f"  {k:<{width}}  {v:8.3f} ms")
    full = results.get("decode_block[xla] per step")
    if full and full > 0:
        print(f"\n  -> {B / full * 1e3:.0f} tok/s at B={B} (compute-bound)")


if __name__ == "__main__":
    main()
