#!/usr/bin/env python
"""Train a tiny in-tree model to BE the ops agent, then serve it.

The full-circle demo the reference cannot do (its "model" is a remote
GPT-4 call, reference pkg/handlers/execute.go:205): using only this
framework —

1. generate ReAct transcripts in the exact wire format the agent loop
   speaks (ToolPrompt JSON in/out, observation-marshaled-as-user-message,
   byte-tokenizer chat template — the same code paths serving uses);
2. fine-tune the tiny llama-family model on them with the in-tree
   sharded train step (training/trainer.py) until it memorizes the
   tool-calling behavior;
3. save an HF-format safetensors checkpoint (models/loader.py);
4. boot the serving engine FROM THAT CHECKPOINT and run the real agent
   loop against it (tpu:// provider, FSM-constrained decoding, kubectl
   replay tool);
5. verify the agent answers the instruction correctly from trained
   weights.

Run: python scripts/train_tiny_agent.py [--steps 800] [--out DIR]
Exits 0 iff the served agent produces the expected final answer.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

# The demo trains and serves on CPU deterministically (hermetic for
# tests; an image-level JAX_PLATFORMS pointing at a TPU plugin would
# otherwise capture it). OPSAGENT_DEMO_PLATFORM overrides to run on a
# chip. Both the env var AND the config update below are needed: a
# TPU-plugin sitecustomize may have imported jax at interpreter boot,
# freezing jax_platforms from the image env (see tests/conftest.py).
_platform = os.environ.get("OPSAGENT_DEMO_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
if _platform == "cpu":
    # Hermetic mode must never touch a pooled TPU; a chip run keeps the
    # pool connection alive.
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

SYS_PROMPT = (
    "You are a k8s ops agent. Reply with ToolPrompt JSON; use the kubectl "
    "tool, then give final_answer."
)
INSTRUCTION = "count namespaces"
KUBECTL_CMD = "kubectl get namespaces --no-headers | wc -l"
FINAL_ANSWER = "There are 3 namespaces in the cluster."

# Each task: one two-turn ReAct episode (tool call -> observation ->
# final answer). ``observation`` must match BYTE-EXACTLY what the replay
# tool emits at serve time (tools/replay.py MULTI_TASK_SCRIPT), or the
# served turn-2 prompt diverges from the trained one. Optional
# ``phrasings`` lists alternative instruction wordings: all but the last
# train (same episode, different question), the LAST is HELD OUT and
# evaluated to probe phrasing robustness beyond memorization.
TASKS_SINGLE = [dict(
    instruction=INSTRUCTION,
    tool="kubectl", tool_input=KUBECTL_CMD, observation="3",
    thought1="I will count namespaces with kubectl.",
    thought2="The observation shows 3 namespaces.",
    obs2="The cluster has 3 namespaces.",
    final=FINAL_ANSWER,
)]

TASKS_MULTI = [dict(
    TASKS_SINGLE[0],
    phrasings=["how many namespaces are there",
               "count the namespaces in the cluster",
               "give me the namespace count",
               "tell me the number of namespaces"],
)] + [
    dict(
        instruction="which pods are crashing",
        phrasings=["list the crashing pods",
                   "find pods stuck in a crash loop",
                   "which pods keep restarting and crashing",
                   "show me pods that keep crashing"],
        tool="kubectl",
        tool_input="kubectl get pods -A | grep CrashLoopBackOff",
        observation="web-2   CrashLoopBackOff",
        thought1="I will grep pod listings for crash loops.",
        thought2="One pod is in CrashLoopBackOff.",
        obs2="web-2 is crash-looping.",
        final="Pod web-2 is in CrashLoopBackOff.",
    ),
    dict(
        instruction="how many nodes are ready",
        phrasings=["count the ready nodes",
                   "how many nodes report ready",
                   "number of nodes in the ready state",
                   "what is the ready node count"],
        tool="kubectl",
        tool_input="kubectl get nodes --no-headers | grep -cw Ready",
        observation="2",
        thought1="I will count Ready nodes with kubectl.",
        thought2="Two nodes report Ready.",
        obs2="2 nodes are Ready.",
        final="2 of the 3 nodes are Ready.",
    ),
    dict(
        instruction="what kubernetes version is the cluster running",
        phrasings=["which k8s version is installed",
                   "what version of kubernetes is this",
                   "tell me the kubernetes server version",
                   "report the cluster version"],
        tool="kubectl",
        tool_input="kubectl version --short",
        observation="Server Version: v1.29.3",
        thought1="I will ask kubectl for the server version.",
        thought2="The server reports its version.",
        obs2="Server version v1.29.3.",
        final="The cluster runs Kubernetes v1.29.3.",
    ),
    dict(
        instruction="how many pods run in the default namespace",
        phrasings=["count pods in the default namespace",
                   "number of pods in namespace default",
                   "how many pods are running in default",
                   "how many pods does default have"],
        tool="kubectl",
        tool_input="kubectl get pods -n default --no-headers | wc -l",
        observation="2",
        thought1="I will count pods in default with kubectl.",
        thought2="There are two pods in default.",
        obs2="2 pods in default.",
        final="There are 2 pods in the default namespace.",
    ),
    dict(
        # Third tool family (jq): the input embeds JSON-in-a-string —
        # the hardest wire shape the FSM-constrained decode must emit
        # byte-exactly (nested quotes escape through two JSON layers).
        instruction="extract the first item name from the status json",
        phrasings=["pull the first item's name out of the status json",
                   "use jq to get the first item name from the status json",
                   "what is the first item's name in the status json",
                   "read the first item name from the status json with jq"],
        tool="jq",
        tool_input='{"items":[{"name":"web-2","status":'
                   '"CrashLoopBackOff"}]} | .items[0].name',
        observation='"web-2"',
        thought1="I will extract the name with the jq tool.",
        thought2="The first item is named web-2.",
        obs2="The first item is web-2.",
        final="The first item in the status json is web-2.",
    ),
    dict(
        instruction="compute 6*7 using python",
        phrasings=["use python to compute 6*7",
                   "run python to calculate 6*7",
                   "calculate 6*7 with the python tool",
                   "what is 6*7, computed with python"],
        tool="python",
        tool_input="print(6*7)",
        observation="42",
        thought1="I will run the expression with the python tool.",
        thought2="The script printed 42.",
        obs2="The result is 42.",
        # >= 10 chars: the loop's template heuristic (react.py
        # is_template_value, reference simple.go:624-657) rejects
        # implausibly short finals like "6*7 = 42.".
        final="The result of 6*7 is 42.",
    ),
]


def train_phrasings(t) -> list[str]:
    """Instruction wordings that TRAIN: the base instruction plus all but
    the last alternative (the last is held out for the robustness probe)."""
    return [t["instruction"], *t.get("phrasings", [])[:-1]]


def heldout_phrasing(t) -> str | None:
    phr = t.get("phrasings", [])
    return phr[-1] if phr else None


def build_convs(tasks=None):
    """Two agent turns per task PER TRAINED PHRASING, serialized with the
    live loop's own wire code (tools.ToolPrompt) — (messages, target
    reply) pairs. The question field carries the phrasing, so the model
    learns the instruction -> episode mapping across wordings."""
    from opsagent_tpu.tools import ToolAction, ToolPrompt

    convs = []
    for t in tasks or TASKS_SINGLE:
        for phrasing in train_phrasings(t):
            user1 = f"Here are the instructions: {phrasing}"
            tp1 = ToolPrompt(
                question=phrasing,
                thought=t["thought1"],
                action=ToolAction(name=t["tool"], input=t["tool_input"]),
            )
            reply1 = tp1.to_json()

            # Turn 2's user message is EXACTLY what the loop marshals
            # back: the turn-1 ToolPrompt with the observation filled in
            # (react.py:193-194).
            tp1_obs = ToolPrompt(
                question=tp1.question, thought=tp1.thought,
                action=tp1.action, observation=t["observation"],
            )
            tp2 = ToolPrompt(
                question=phrasing,
                thought=t["thought2"],
                observation=t["obs2"],
                final_answer=t["final"],
            )
            reply2 = tp2.to_json()

            convs += [
                ([{"role": "system", "content": SYS_PROMPT},
                  {"role": "user", "content": user1}], reply1),
                ([{"role": "system", "content": SYS_PROMPT},
                  {"role": "user", "content": user1},
                  {"role": "assistant", "content": reply1},
                  {"role": "user", "content": tp1_obs.to_json()}], reply2),
            ]
    return convs


def train_bpe_tokenizer(out_dir: str, extra_corpus: tuple[str, ...] = (),
                        vocab_size: int = 512, tasks=None) -> str:
    """Train a REAL byte-level-BPE tokenizer (HF fast-tokenizer format)
    on the agent corpus and save it loadable via AutoTokenizer — the demo
    then exercises the same HFTokenizer path real checkpoints use, not
    the byte fallback. ``extra_corpus`` adds more training text (e.g. the
    full ReAct system prompt, so long prompts compress instead of
    exploding to near-byte token counts). Returns the tokenizer dir."""
    import json as jsonlib

    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

    from opsagent_tpu.serving.chat_template import render_llama3

    corpus = list(extra_corpus)
    for messages, reply in build_convs(tasks):
        corpus.append(render_llama3(messages))
        corpus.append(reply)
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size, special_tokens=["<bos>", "<eos>", "<pad>"],
        show_progress=False,
        # Full byte alphabet: without it, bytes absent from the tiny
        # corpus would be silently DROPPED at encode time (unk is None),
        # so any later prompt/observation edit could train on a lossy
        # target that the string-level FSM check cannot catch.
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(corpus, trainer)
    tok_dir = os.path.join(out_dir, "tokenizer")
    os.makedirs(tok_dir, exist_ok=True)
    tok.save(os.path.join(tok_dir, "tokenizer.json"))
    with open(os.path.join(tok_dir, "tokenizer_config.json"), "w",
              encoding="utf-8") as f:
        jsonlib.dump({
            "tokenizer_class": "PreTrainedTokenizerFast",
            "bos_token": "<bos>", "eos_token": "<eos>", "pad_token": "<pad>",
        }, f)
    return tok_dir


def build_dataset(tok, tasks=None):
    """(token_ids, loss_mask) rows: prompts rendered by the SAME
    apply_chat_template the serving stack uses, targets validated
    reachable under the ToolPrompt FSM the serving path enforces."""
    from opsagent_tpu.serving.chat_template import apply_chat_template
    from opsagent_tpu.serving.constrained import (
        TOOLPROMPT_SCHEMA,
        json_constraint,
    )

    convs = build_convs(tasks)
    con = json_constraint(tok, TOOLPROMPT_SCHEMA)
    for _, reply in convs:
        dfa = con.fsm.dfa
        state = dfa.run(dfa.start, reply.encode())
        assert state >= 0 and dfa.accept[state], (
            f"FSM rejects training target: {reply!r}"
        )

    rows = []
    for messages, reply in convs:
        prompt_ids = apply_chat_template(tok, messages)
        reply_ids = tok.encode(reply) + [tok.eos_id]
        ids = prompt_ids + reply_ids
        mask = [0.0] * len(prompt_ids) + [1.0] * len(reply_ids)
        rows.append((ids, mask))
    return rows


def train_checkpoint(out_dir, steps=600, target_loss=0.01, lr=3e-3,
                     tasks=None):
    """Programmatic train-to-memorization for callers that need a tiny
    agent checkpoint in-process (the agent-conveyor bench stage, the
    conveyor e2e test): the same tiny-test + BPE recipe as ``main()``,
    minus the CLI/serve scaffolding. Falls back to the byte tokenizer
    when the ``tokenizers`` package is absent. Returns
    ``(ckpt_path, tok_path, model_cfg, final_loss, train_s)`` with
    ``tok_path == ""`` on the byte-tokenizer fallback."""
    import dataclasses

    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.models.loader import save_checkpoint
    from opsagent_tpu.parallel.mesh import make_mesh
    from opsagent_tpu.training import (
        TrainConfig,
        init_train_state,
        make_train_step,
    )

    tasks = tasks or TASKS_SINGLE
    cfg = get_config_preset("tiny-test")
    try:
        from opsagent_tpu.serving.tokenizer import load_tokenizer

        tok_path = train_bpe_tokenizer(out_dir, tasks=tasks)
        tok = load_tokenizer(tok_path)
        cfg = dataclasses.replace(cfg, vocab_size=tok.vocab_size)
    except ImportError:
        from opsagent_tpu.serving.tokenizer import ByteTokenizer

        tok_path = ""
        tok = ByteTokenizer(vocab_size=cfg.vocab_size)
    rows = build_dataset(tok, tasks)
    S = 8 * ((max(len(ids) for ids, _ in rows) + 7) // 8)
    tokens = np.full((len(rows), S), tok.pad_id, np.int32)
    mask = np.zeros((len(rows), S), np.float32)
    for i, (ids, m) in enumerate(rows):
        tokens[i, :len(ids)] = ids
        mask[i, :len(m)] = m
    mesh = make_mesh(tp=1, dp=1, sp=1, devices=jax.devices()[:1])
    tc = TrainConfig(learning_rate=lr, weight_decay=0.0, remat=False)
    params, opt_state = init_train_state(
        cfg, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    train_step = make_train_step(cfg, tc, mesh, dtype=jnp.float32)
    tokens_j, mask_j = jnp.asarray(tokens), jnp.asarray(mask)
    t0 = time.perf_counter()
    loss = float("inf")
    for i in range(steps):
        params, opt_state, tmetrics = train_step(
            params, opt_state, tokens_j, mask_j
        )
        if i % 50 == 0 or i == steps - 1:
            loss = float(tmetrics["loss"])
            if loss < target_loss:
                break
    train_s = time.perf_counter() - t0
    ckpt = os.path.join(out_dir, "model.safetensors")
    save_checkpoint(ckpt, params)
    return ckpt, tok_path, cfg, loss, train_s


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--target-loss", type=float, default=0.01)
    ap.add_argument("--out", default="")
    ap.add_argument("--tokenizer", default="bpe", choices=("bpe", "byte"),
                    help="bpe = train a real HF fast tokenizer (the path "
                         "real checkpoints use); byte = the test fallback")
    ap.add_argument("--skip-agent", action="store_true",
                    help="train + save only (no serving run)")
    ap.add_argument("--tasks", default="single", choices=("single", "multi"),
                    help="single = the original count-namespaces episode; "
                         "multi = 6 instructions across kubectl AND the "
                         "python tool (pods/nodes/version/arithmetic), "
                         "each served and checked after training")
    ap.add_argument("--no-probe", action="store_true",
                    help="skip the non-gating held-out-phrasing probes "
                         "(each burns a full agent episode; CI uses this)")
    ap.add_argument("--serve-variants", default="",
                    help="comma list of extra serving configurations to "
                         "re-run the assertions under from the SAME "
                         "checkpoint: kv-int8 (int8 KV cache), int8 "
                         "(weight-only int8), int4 (weight-only int4, "
                         "gated on greedy agreement, see --int4-floor)")
    ap.add_argument("--int4-floor", type=float, default=0.35,
                    help="minimum mean greedy matching-prefix fraction "
                         "the int4 serve must reach vs the fp32 serve of "
                         "the same checkpoint (VERDICT r04 #6). The floor "
                         "separates 'lossy but sane' from 'broken': a "
                         "packing/dequant BUG craters agreement to ~0, "
                         "while legitimate small-group noise on this "
                         "worst-case model (64-wide contractions = "
                         "whole-axis scale groups) stays well above it")
    ap.add_argument("--kv-quantize", default="", choices=("", "int8"),
                    help="after the plain serving run passes, re-serve "
                         "the SAME checkpoint with the int8 KV cache and "
                         "re-run every memorized-agent assertion: greedy "
                         "faithfulness under KV quantization on learned "
                         "weights for one extra serving pass")
    ap.add_argument("--wide", action="store_true",
                    help="4x the model (d=128, f=256, 8 heads): the "
                         "capacity experiment for held-out phrasing "
                         "generalization (slower to train)")
    args = ap.parse_args()
    # Validate serve variants at parse time: a typo must not be found
    # AFTER the training run it would re-serve.
    args.serve_variants = ",".join(
        v.strip() for v in (args.serve_variants or "").split(",")
        if v.strip()
    )
    bad = [v for v in args.serve_variants.split(",")
           if v and v not in ("kv-int8", "int8", "int4")]
    if bad:
        ap.error(f"unknown --serve-variants entries: {', '.join(bad)} "
                 f"(expected kv-int8, int8, int4)")
    tasks = TASKS_MULTI if args.tasks == "multi" else TASKS_SINGLE

    import dataclasses

    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.models.loader import save_checkpoint
    from opsagent_tpu.parallel.mesh import make_mesh
    from opsagent_tpu.serving.tokenizer import ByteTokenizer, load_tokenizer
    from opsagent_tpu.training import (
        TrainConfig,
        init_train_state,
        make_train_step,
    )

    out = args.out or tempfile.mkdtemp(prefix="opsagent-tiny-agent-")
    os.makedirs(out, exist_ok=True)
    cfg = get_config_preset("tiny-test")
    if args.wide:
        cfg = dataclasses.replace(
            cfg, hidden_size=128, intermediate_size=256, num_heads=8,
            num_kv_heads=4,
        )
    if args.tokenizer == "bpe":
        try:
            import tokenizers  # noqa: F401 - probe the optional dep
            import transformers  # noqa: F401
        except ImportError as e:
            print(f"tokenizers/transformers unavailable ({e}); "
                  f"falling back to the byte tokenizer", file=sys.stderr)
            args.tokenizer = "byte"
    if args.tokenizer == "bpe":
        tok_path = train_bpe_tokenizer(out, tasks=tasks)
        tok = load_tokenizer(tok_path)
        # The lm head sizes to the trained vocab (specials included).
        cfg = dataclasses.replace(cfg, vocab_size=tok.vocab_size)
        print(f"bpe tokenizer: vocab {tok.vocab_size} at {tok_path}",
              file=sys.stderr)
    else:
        tok_path = ""
        tok = ByteTokenizer(vocab_size=cfg.vocab_size)
    rows = build_dataset(tok, tasks)
    S = 8 * ((max(len(ids) for ids, _ in rows) + 7) // 8)
    B = len(rows)
    tokens = np.full((B, S), tok.pad_id, np.int32)
    mask = np.zeros((B, S), np.float32)
    for i, (ids, m) in enumerate(rows):
        tokens[i, :len(ids)] = ids
        mask[i, :len(m)] = m
    print(f"dataset: {B} rows, padded to S={S}", file=sys.stderr)

    mesh = make_mesh(tp=1, dp=1, sp=1, devices=jax.devices()[:1])
    tc = TrainConfig(learning_rate=args.lr, weight_decay=0.0, remat=False)
    params, opt_state = init_train_state(
        cfg, tc, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step = make_train_step(cfg, tc, mesh, dtype=jnp.float32)
    tokens_j = jnp.asarray(tokens)
    mask_j = jnp.asarray(mask)

    t0 = time.perf_counter()
    loss = float("inf")
    for i in range(args.steps):
        params, opt_state, metrics = step(params, opt_state, tokens_j, mask_j)
        if i % 50 == 0 or i == args.steps - 1:
            loss = float(metrics["loss"])
            print(f"step {i:4d} loss {loss:.4f} "
                  f"({time.perf_counter()-t0:.0f}s)", file=sys.stderr)
            if loss < args.target_loss:
                break
    print(f"trained to loss {loss:.4f} in {time.perf_counter()-t0:.0f}s",
          file=sys.stderr)

    ckpt = os.path.join(out, "model.safetensors")
    save_checkpoint(ckpt, params)
    print(f"checkpoint saved: {ckpt}", file=sys.stderr)
    if args.skip_agent:
        return 0
    ok = run_agent(ckpt, tok_path, cfg, tasks, probe=not args.no_probe)
    # Re-serve the SAME checkpoint under each requested quantized
    # configuration and rerun the memorized assertions: greedy
    # faithfulness on LEARNED weights at one extra serving pass each
    # (training is the expensive part and happens once). int4's ANSWERS
    # are non-gating — tiny-test's 64-wide contraction axes collapse to
    # whole-axis scale groups, group-wise int4's worst case, so a
    # flipped answer is expected signal — but int4 DOES gate on greedy
    # prefix agreement vs the fp32 serve (--int4-floor; PERF.md "int4
    # fidelity policy"): a packing/dequant bug fails the run.
    variants = [v for v in (args.serve_variants or "").split(",") if v]
    if args.kv_quantize and "kv-int8" not in variants:
        variants.insert(0, "kv-int8")
    for v in variants:
        if not ok:
            break
        kvq = "int8" if v == "kv-int8" else ""
        wq = v if v in ("int8", "int4") else ""
        if not (kvq or wq):
            print(f"unknown serve variant {v!r}", file=sys.stderr)
            return 1
        print(f"re-serving with quantize={wq or '-'} "
              f"kv_quantize={kvq or '-'} [{v}]", file=sys.stderr)
        got = run_agent(ckpt, tok_path, cfg, tasks, probe=False,
                        kv_quantize=kvq, quantize=wq)
        if v == "int4":
            # int4's answer-level pass is NOT the gate at this scale
            # (tiny-test's 64-wide contractions collapse to whole-axis
            # scale groups — group-wise int4's worst case, so a flipped
            # answer is expected signal). The GATE is quantitative
            # greedy agreement vs the fp32 serve (VERDICT r04 #6): a
            # packing/dequant bug craters it to ~0, quantization noise
            # does not.
            agree = greedy_agreement(
                ckpt, tok_path, cfg, tasks, quantize="int4"
            )
            print(f"int4 variant {'PASSED' if got else 'DIVERGED'} "
                  f"(answers non-gating); greedy prefix agreement vs "
                  f"fp32 {agree:.3f} (gate floor {args.int4_floor})",
                  file=sys.stderr)
            if agree < args.int4_floor:
                print(f"int4 agreement {agree:.3f} < floor "
                      f"{args.int4_floor}: FAILED", file=sys.stderr)
                ok = False
        else:
            ok = got
    return 0 if ok else 1


def greedy_agreement(ckpt: str, tok_path: str, cfg, tasks,
                     quantize: str = "", kv_quantize: str = "",
                     max_tokens: int = 64) -> float:
    """Mean greedy matching-prefix fraction of a quantized serve vs the
    fp32 serve of the SAME checkpoint, over each task's turn-1 prompt
    (chat-templated by the serving path's own apply_chat_template).
    Prefix fraction, not positionwise match: greedy divergence compounds,
    so the first differing token ends the credited run — the strictest
    honest scalar for 'how far does the quantized model track fp32'."""
    from opsagent_tpu.serving.chat_template import apply_chat_template
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    def gen(wq: str, kvq: str) -> list[list[int]]:
        eng = Engine(
            EngineConfig(
                model="tiny-test", checkpoint=ckpt, tokenizer=tok_path,
                dtype=jnp.float32, num_pages=256, page_size=16,
                max_pages_per_seq=64, max_batch_size=1,
                prefill_buckets=(128, 512, 1024),
                quantize=wq, kv_quantize=kvq,
            ),
            model_cfg=cfg,
        )
        outs = []
        for t in tasks:
            messages = [
                {"role": "system", "content": SYS_PROMPT},
                {"role": "user",
                 "content": f"Here are the instructions: "
                            f"{t['instruction']}"},
            ]
            ids = apply_chat_template(eng.tokenizer, messages)
            sid = eng.add_request(
                ids, SamplingParams(temperature=0.0, max_tokens=max_tokens)
            )
            while not eng.sequences[sid].done:
                eng.step([sid])
            outs.append(eng.finish(sid))
        return outs

    ref = gen("", "")
    got = gen(quantize, kv_quantize)
    fracs = []
    for a, b in zip(ref, got):
        n = 0
        for x, y in zip(a, b):
            if x != y:
                break
            n += 1
        fracs.append(n / max(1, len(a)))
    return sum(fracs) / max(1, len(fracs))


def run_agent(ckpt: str, tok_path: str, cfg, tasks=None,
              probe: bool = True, kv_quantize: str = "",
              quantize: str = "") -> bool:
    """Serve the trained checkpoint and run the real agent loop on EVERY
    task's instruction, asserting each memorized final answer."""
    from opsagent_tpu.agent.react import assistant_with_config
    from opsagent_tpu.serving import api as serving_api
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.tools import ToolPrompt
    from opsagent_tpu.tools.replay import (
        MULTI_TASK_SCRIPT,
        NAMESPACES_SCRIPT,
        install_replay_kubectl,
    )

    tasks = tasks or TASKS_SINGLE
    install_replay_kubectl(
        MULTI_TASK_SCRIPT if len(tasks) > 1 else NAMESPACES_SCRIPT
    )

    engine = Engine(
        EngineConfig(
            model="tiny-test",
            checkpoint=ckpt,
            tokenizer=tok_path,
            dtype=jnp.float32,
            num_pages=512,
            page_size=16,
            max_pages_per_seq=64,
            max_batch_size=2,
            prefill_buckets=(128, 512, 1024),
            quantize=quantize,
            kv_quantize=kv_quantize,
        ),
        model_cfg=cfg,
    )
    stack = serving_api.ServingStack(engine)
    serving_api.install_stack("tiny-agent", stack)
    def run_one(phrasing: str, t, tag: str = "") -> bool:
        label = f"{phrasing}{tag}"
        messages = [
            {"role": "system", "content": SYS_PROMPT},
            {"role": "user",
             "content": f"Here are the instructions: {phrasing}"},
        ]
        try:
            answer, history = assistant_with_config(
                "tpu://tiny-agent", messages, 256, False, True, 4, "", ""
            )
        except Exception as e:  # noqa: BLE001 - a mis-routed probe can
            # loop until the page budget rejects its grown history; that
            # is a FAILED probe, not a crashed demo. GATING runs re-raise:
            # an engine fault there needs its traceback, not a one-liner.
            if not tag:
                raise
            print(f"[{label}] agent error: {e} FAILED")
            return False
        print(f"--- transcript [{label}] ---", file=sys.stderr)
        for m in history:
            print(f"[{m['role']}] {str(m['content'])[:300]}",
                  file=sys.stderr)
        try:
            final = ToolPrompt.from_json(answer).final_answer
        except ValueError:
            final = ""
        ok = final == t["final"]
        verdict = "PASSED" if ok else f"FAILED (want {t['final']!r})"
        print(f"[{label}] final answer: {final!r} {verdict}")
        return ok

    try:
        all_ok = True
        held_total = held_ok = 0
        for t in tasks:
            for phrasing in train_phrasings(t):
                all_ok = run_one(phrasing, t) and all_ok
            held = heldout_phrasing(t)
            if probe and held is not None:
                # Robustness probe, reported but NOT gating: a tiny
                # 2-layer model is not owed paraphrase generalization.
                held_total += 1
                if run_one(held, t, tag=" (HELD-OUT)"):
                    held_ok += 1
        print(f"agent {'PASSED' if all_ok else 'FAILED'} "
              f"({len(tasks)} tasks)")
        if held_total:
            print(f"held-out phrasings: {held_ok}/{held_total} correct "
                  f"(robustness probe, non-gating)")
        return all_ok
    finally:
        stack.close()
        serving_api.uninstall_stack("tiny-agent")


if __name__ == "__main__":
    sys.exit(main())
