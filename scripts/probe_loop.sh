#!/bin/bash
# Probe-then-bench loop (r04 lesson, VERDICT r04 #9): probe the tunneled
# TPU GENTLY — 120 s cap, 10-min spacing; repeated hard kills of a
# device-holding client can wedge the remote lease — and the moment the
# chip answers, run the full measurement session so zero alive-time is
# wasted. Runs tpu_measure.sh from a frozen worktree snapshot (WT) so
# live-tree edits cannot race a mid-flight bench. One-shot: exits after
# the first completed measurement session.
set -u
WT="${WT:-/root/repo/.bench_wt}"
OUT="${OUT:-/root/repo/tpu_results_r05}"
BUDGET="${OPSAGENT_BENCH_BUDGET:-2400}"
mkdir -p "$OUT"
LOG="$OUT/probe_loop.log"
# Fail fast if the snapshot is missing (gitignored, created out-of-band
# by `git worktree add`): discovering that at the moment the chip
# finally answers would waste the whole alive window.
if [ ! -x "$WT/scripts/tpu_measure.sh" ]; then
  echo "$(date -u +%FT%TZ) FATAL: no measure script at $WT" >> "$LOG"
  exit 1
fi
echo "$(date -u +%FT%TZ) probe loop start (wt=$WT budget=$BUDGET)" >> "$LOG"
while true; do
  ts=$(date -u +%FT%TZ)
  if timeout 120 python -c \
    "import jax; d = jax.devices(); assert d[0].platform == 'tpu', d" \
    >> "$LOG" 2>&1; then
    echo "$ts chip ALIVE -> measurement session" >> "$LOG"
    OUT="$OUT" OPSAGENT_BENCH_BUDGET="$BUDGET" \
      bash "$WT/scripts/tpu_measure.sh" >> "$LOG" 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) measurement session rc=$rc" >> "$LOG"
    # One-shot only on a session that actually MEASURED something: a
    # tunnel flap between the probe and the session's own probe exits
    # nonzero with an empty jsonl — keep watching in that case, or the
    # next alive window would find nothing listening (the r04 failure).
    if [ "$rc" -eq 0 ] && [ -s "$OUT/bench.jsonl" ]; then
      break
    fi
    echo "$(date -u +%FT%TZ) session incomplete; resuming probes" >> "$LOG"
  else
    echo "$ts unreachable; sleeping 600" >> "$LOG"
  fi
  sleep 600
done
