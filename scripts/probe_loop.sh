#!/bin/bash
# Probe-then-bench loop (r04 lesson, VERDICT r04 #9): probe the tunneled
# TPU GENTLY — 120 s cap, 10-min spacing; repeated hard kills of a
# device-holding client can wedge the remote lease — and the moment the
# chip answers, run the full measurement session so zero alive-time is
# wasted. Runs tpu_measure.sh from a frozen worktree snapshot (WT) so
# live-tree edits cannot race a mid-flight bench. One-shot: exits after
# the first completed measurement session.
set -u
REPO="${REPO:-/root/repo}"
WT="${WT:-$REPO/.bench_wt}"
OUT="${OUT:-$REPO/tpu_results_r05}"
BUDGET="${OPSAGENT_BENCH_BUDGET:-2400}"
# Epoch seconds after which the loop must NOT hold the device: the
# driver's end-of-round bench window needs the chip to itself (the r04
# loop had the same guard). 0 disables.
DEADLINE="${PROBE_DEADLINE:-0}"
mkdir -p "$OUT"
LOG="$OUT/probe_loop.log"
# Fail fast if the snapshot is missing (gitignored, created out-of-band
# by `git worktree add`): discovering that at the moment the chip
# finally answers would waste the whole alive window.
if [ ! -x "$WT/scripts/tpu_measure.sh" ]; then
  echo "$(date -u +%FT%TZ) FATAL: no measure script at $WT" >> "$LOG"
  exit 1
fi
echo "$(date -u +%FT%TZ) probe loop start (wt=$WT budget=$BUDGET)" >> "$LOG"
while true; do
  ts=$(date -u +%FT%TZ)
  if [ "$DEADLINE" -gt 0 ] && [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "$ts deadline reached; exiting so the driver's bench window" \
      "owns the device" >> "$LOG"
    break
  fi
  if timeout 120 python -c \
    "import jax; d = jax.devices(); assert d[0].platform == 'tpu', d" \
    >> "$LOG" 2>&1; then
    budget="$BUDGET"
    extras=""
    if [ "$DEADLINE" -gt 0 ]; then
      rem=$(( DEADLINE - $(date +%s) ))
      if [ "$rem" -lt 1500 ]; then
        echo "$ts chip alive but only ${rem}s before the deadline;" \
          "leaving it for the driver" >> "$LOG"
        break
      fi
      # Shrink to fit: the orchestrated stages get at most half the
      # remaining window, and the profile/sweep extras are skipped
      # unless the window absorbs their worst case ON TOP of the bench
      # budget (probe 300 + profile cap 1500 + sweeps ~5x900 ≈ 6300s,
      # rounded up — keep in step with tpu_measure.sh's stage list).
      if [ $(( rem / 2 )) -lt "$budget" ]; then budget=$(( rem / 2 )); fi
      if [ "$rem" -lt $(( budget + 6600 )) ]; then extras=1; fi
    fi
    echo "$ts chip ALIVE -> measurement session (budget ${budget}s" \
      "skip_extras=${extras:-0})" >> "$LOG"
    OUT="$OUT" OPSAGENT_BENCH_BUDGET="$budget" SKIP_EXTRAS="${extras}" \
      bash "$WT/scripts/tpu_measure.sh" >> "$LOG" 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) measurement session rc=$rc" >> "$LOG"
    # One-shot only on a session that actually MEASURED something: a
    # tunnel flap between the probe and the session's own probe exits
    # nonzero with an empty jsonl — keep watching in that case, or the
    # next alive window would find nothing listening (the r04 failure).
    if [ -s "$OUT/bench.jsonl" ]; then
      # Results dirs are gitignored; mirror the artifacts to root-level
      # committed names so the driver's end-of-round sweep preserves
      # them even if no one is around to commit (r04's
      # BENCH_r04_local.jsonl pattern). Monotonic by line count: a later
      # session truncates $OUT/bench.jsonl at its start, so a partial
      # rerun must never clobber a more complete earlier mirror.
      new=$(wc -l < "$OUT/bench.jsonl")
      old=0
      [ -f "$REPO/BENCH_r05_local.jsonl" ] && \
        old=$(wc -l < "$REPO/BENCH_r05_local.jsonl")
      if [ "$new" -ge "$old" ]; then
        cp "$OUT/bench.jsonl" "$REPO/BENCH_r05_local.jsonl"
        [ -f "$OUT/session.log" ] && \
          cp "$OUT/session.log" "$REPO/SESSION_r05.log"
      fi
      [ "$rc" -eq 0 ] && break
    fi
    echo "$(date -u +%FT%TZ) session incomplete; resuming probes" >> "$LOG"
  else
    echo "$ts unreachable; sleeping 600" >> "$LOG"
  fi
  sleep 600
done
