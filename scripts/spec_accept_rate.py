#!/usr/bin/env python
"""Measure the speculative-decoding accept rate on the AGENT workload.

VERDICT r03 #3: prompt-lookup speculation has shipped dormant
(``EngineConfig.speculative_k = 0``) for two rounds because the decision
needs an accept-rate measurement on trained weights re-emitting ReAct
JSON scaffolding — random weights accept ~nothing, so bench stage 4 only
bounds the overhead. This script closes the question:

1. train the tiny in-tree agent model (scripts/train_tiny_agent.py's
   corpus/recipe — real trained weights whose replies repeat the
   ToolPrompt JSON structure already present in the prompt, exactly the
   n-gram-lookup-friendly shape of the production agent loop);
2. run the SAME two-turn agent loop with speculative_k=0 and k=4 over
   fresh engines (greedy, FSM off so speculation engages);
3. report: accept rate (a model/workload property that transfers to
   TPU), decode dispatches per generated token (the host-RTT amortizer
   speculation buys), and wall-clock delta (CPU-only, indicative).

Accept rate is read from the ``engine.spec_step_tokens`` metric: each
live verify step emits 1 + (accepted drafts) tokens, so
``(mean - 1) / k`` is the per-draft accept rate.

Run: python scripts/spec_accept_rate.py [--steps 800] [--k 4]
Prints one JSON line with the measurements.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = os.environ.get("OPSAGENT_DEMO_PLATFORM", "cpu")
if os.environ["JAX_PLATFORMS"] == "cpu":
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp  # noqa: E402


def run_loop(ckpt: str, tok_path: str, cfg, k: int) -> dict:
    """The agent conversation's two turns against a fresh engine.

    Driven through ``chat_completion`` directly (NOT the ReAct loop):
    against tpu:// targets the loop turns on FSM-constrained decoding,
    which disables speculation by design (engine.py gates "spec" on
    fsm_obj is None) — the measurement needs the same prompts/replies
    WITHOUT the FSM, and the trained model emits valid ToolPrompt JSON
    unconstrained."""
    from opsagent_tpu.serving import api as serving_api
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.tools import ToolPrompt
    from opsagent_tpu.utils.perf import get_perf_stats
    from scripts.train_tiny_agent import build_convs

    engine = Engine(
        EngineConfig(
            model="tiny-test",
            checkpoint=ckpt,
            tokenizer=tok_path,
            dtype=jnp.float32,
            num_pages=512,
            page_size=16,
            max_pages_per_seq=64,
            max_batch_size=2,
            prefill_buckets=(128, 512, 1024),
            speculative_k=k,
        ),
        model_cfg=cfg,
    )
    stack = serving_api.ServingStack(engine)
    perf = get_perf_stats()
    perf.reset()
    try:
        # The exact two agent turns (turn 2's user message marshals the
        # observation back as ToolPrompt JSON — the n-gram-rich shape).
        convs = build_convs()
        t0 = time.perf_counter()
        final = ""
        for messages, _expected in convs:
            resp = stack.chat_completion({
                "messages": messages,
                "max_tokens": 256,
                "temperature": 0.0,
            })
            reply = resp["choices"][0]["message"]["content"] or ""
            try:
                final = ToolPrompt.from_json(reply).final_answer or final
            except ValueError:
                pass
        wall = time.perf_counter() - t0
        ok = "3" in final and "namespace" in final.lower()
        stats = perf.get_stats()
        tokens = stats.get("engine.decode_tokens", {})
        dispatch = stats.get("engine.block_dispatch", {})
        spec = stats.get("engine.spec_step_tokens", {})
        produced = tokens.get("sum", 0) or (
            tokens.get("avg", 0) * tokens.get("count", 0)
        )
        return {
            "k": k,
            "ok": ok,
            "wall_s": round(wall, 2),
            "tokens": int(produced),
            "dispatches": int(dispatch.get("count", 0)),
            "spec_steps": int(spec.get("count", 0)),
            "tokens_per_verify_step": round(spec.get("avg", 0.0), 3),
            "accept_rate": (
                round((spec.get("avg", 1.0) - 1.0) / k, 3) if k else None
            ),
        }
    finally:
        stack.close()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    # Train (or reuse) the tiny agent checkpoint via the demo's recipe.
    import subprocess
    import tempfile

    out = args.out or tempfile.mkdtemp(prefix="opsagent-specrate-")
    ckpt = os.path.join(out, "model.safetensors")
    if not os.path.exists(ckpt):
        rc = subprocess.run(
            [sys.executable, os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "train_tiny_agent.py",
            ), "--steps", str(args.steps), "--out", out, "--skip-agent"],
        ).returncode
        if rc:
            print(f"training failed rc={rc}", file=sys.stderr)
            return rc

    import dataclasses

    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.serving.tokenizer import load_tokenizer

    tok_path = os.path.join(out, "tokenizer")
    cfg = get_config_preset("tiny-test")
    if os.path.isdir(tok_path):
        cfg = dataclasses.replace(
            cfg, vocab_size=load_tokenizer(tok_path).vocab_size
        )
    else:
        tok_path = ""

    base = run_loop(ckpt, tok_path, cfg, k=0)
    spec = run_loop(ckpt, tok_path, cfg, k=args.k)
    result = {
        "baseline": base,
        "speculative": spec,
        "dispatch_reduction": (
            round(1.0 - spec["dispatches"] / base["dispatches"], 3)
            if base["dispatches"] else None
        ),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(result), flush=True)
    return 0 if (base["ok"] and spec["ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
