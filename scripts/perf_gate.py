#!/usr/bin/env python
"""Perf-regression gate: compare a fresh bench jsonl against the
committed BENCH_r*_local.jsonl baseline with per-metric noise
tolerances; exit 1 on regression, 2 when nothing is comparable.

    python scripts/perf_gate.py tpu_results_r06/bench.jsonl
    python scripts/perf_gate.py fresh.jsonl --baseline BENCH_r04_local.jsonl \
        --tolerance 0.15
    python scripts/perf_gate.py http://router:8090   # live fleet rows
                                                     # (GET /api/fleet/bench)

Thin shim over ``opsagent_tpu.cli.perfcheck`` (also reachable as
``opsagent perf-check``) so CI can call the gate without installing the
package. jax-free by design.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from opsagent_tpu.cli.perfcheck import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
