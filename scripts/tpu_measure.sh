#!/bin/bash
# One-shot on-TPU measurement session (PERF.md plan): run the moment the
# tunneled chip is reachable. Captures, in order of importance:
#   1. the staged bench (1B bf16, 8B int8 headline, config-5 sessions,
#      speculative overhead, pallas-dma sweep, cold-restart TTFT) — every
#      result line flushes immediately;
#   2. a jax.profiler device trace of the 1B steady state for gap
#      attribution (weight streaming vs attention vs sampling vs host).
# Results land in $OUT (default ./tpu_results_<ts>).
set -u
cd "$(dirname "$0")/.."
OUT="${OUT:-tpu_results_$(date -u +%Y%m%dT%H%M%S)}"
mkdir -p "$OUT"

echo "== probe ==" | tee "$OUT/session.log"
timeout 300 python -c "import jax; d=jax.devices(); print(d[0].platform, len(d))" \
  2>&1 | tail -2 | tee -a "$OUT/session.log"
if ! grep -q "^tpu" <(tail -2 "$OUT/session.log"); then
  echo "tpu unreachable; aborting" | tee -a "$OUT/session.log"
  exit 1
fi

echo "== staged bench (budget ${OPSAGENT_BENCH_BUDGET:-850}s) ==" | tee -a "$OUT/session.log"
python bench.py > "$OUT/bench.jsonl" 2> >(tee -a "$OUT/session.log" >&2)
echo "bench rc=$?" | tee -a "$OUT/session.log"

echo "== profiled 1B steady state ==" | tee -a "$OUT/session.log"
OPSAGENT_PROFILE_DIR="$OUT/trace" OPSAGENT_BENCH_MODEL=bench-1b \
  OPSAGENT_BENCH_STEPS=256 timeout 600 python bench.py \
  >> "$OUT/bench.jsonl" 2>> "$OUT/session.log"
echo "profile rc=$?" | tee -a "$OUT/session.log"

echo "results in $OUT:" | tee -a "$OUT/session.log"
cat "$OUT/bench.jsonl"
