#!/bin/bash
# One-shot on-TPU measurement session (PERF.md plan): run the moment the
# tunneled chip is reachable. Captures, in order of importance:
#   1. the staged bench (1B bf16, 8B int8 headline, config-5 sessions,
#      speculative overhead, pallas-dma sweep, cold-restart TTFT) — every
#      result line flushes immediately;
#   2. a jax.profiler device trace of the 1B steady state for gap
#      attribution (weight streaming vs attention vs sampling vs host).
# Results land in $OUT (default ./tpu_results_<ts>).
set -u
cd "$(dirname "$0")/.."
OUT="${OUT:-tpu_results_$(date -u +%Y%m%dT%H%M%S)}"
mkdir -p "$OUT"

echo "== probe ==" | tee "$OUT/session.log"
timeout 300 python -c "import jax; d=jax.devices(); print(d[0].platform, len(d))" \
  2>&1 | tail -2 | tee -a "$OUT/session.log"
if ! grep -q "^tpu" <(tail -2 "$OUT/session.log"); then
  echo "tpu unreachable; aborting" | tee -a "$OUT/session.log"
  exit 1
fi

echo "== staged bench (budget ${OPSAGENT_BENCH_BUDGET:-850}s) ==" | tee -a "$OUT/session.log"
python bench.py > "$OUT/bench.jsonl" 2> >(tee -a "$OUT/session.log" >&2)
echo "bench rc=$?" | tee -a "$OUT/session.log"

# SKIP_EXTRAS=1 (set by probe_loop.sh near its deadline): the staged
# bench above is the decision matrix; the profile trace and sweep points
# below are refinements a short window should not spend the chip on.
if [ -n "${SKIP_EXTRAS:-}" ]; then
  echo "== extras skipped (deadline window) ==" | tee -a "$OUT/session.log"
else

echo "== profiled 1B steady state ==" | tee -a "$OUT/session.log"
# Generous cap: SIGTERM'ing a device-holding child wedges the remote lease
# (r04 lesson) — the timeout exists only as a last-resort backstop, sized
# at ~3x the expected runtime so it never fires on a healthy run.
OPSAGENT_PROFILE_DIR="$OUT/trace" OPSAGENT_BENCH_MODEL=bench-1b \
  OPSAGENT_BENCH_STEPS=256 timeout 1500 python bench.py \
  >> "$OUT/bench.jsonl" 2>> "$OUT/session.log"
echo "profile rc=$?" | tee -a "$OUT/session.log"

# Page-geometry sweep on the 8B headline (the XLA gather reads full
# table CAPACITY per step, so geometry matters on that backend; the dma
# kernel reads resident pages only). Each point is one short run; a
# failed point just logs and moves on.
echo "== 8B sweep points ==" | tee -a "$OUT/session.log"
sweep() {  # tag env...
  local tag="$1"; shift
  echo "-- sweep $tag" | tee -a "$OUT/session.log"
  env "$@" OPSAGENT_BENCH_MODEL=bench-8b OPSAGENT_BENCH_STEPS=384 \
    timeout 900 python bench.py \
    >> "$OUT/bench.jsonl" 2>> "$OUT/session.log"
  echo "-- sweep $tag rc=$?" | tee -a "$OUT/session.log"
}
sweep page128-kv   OPSAGENT_BENCH_PAGE=128 OPSAGENT_BENCH_MAXPAGES=6 \
                   OPSAGENT_BENCH_KV=int8
sweep page128      OPSAGENT_BENCH_PAGE=128 OPSAGENT_BENCH_MAXPAGES=6
sweep dma-int4-kv  OPSAGENT_PAGED_BACKEND=pallas-dma \
                   OPSAGENT_BENCH_QUANT=int4 OPSAGENT_BENCH_KV=int8
sweep block64-kv   OPSAGENT_BENCH_BLOCK=64 OPSAGENT_BENCH_KV=int8
# North-star shape on the north-star model class: multi-turn agent
# sessions on 8B (the orchestrated run measures it on bench-1b). N=8
# keeps weights + KV pages inside the 16 GB chip.
sweep agent-8b     OPSAGENT_BENCH_MODE=agent OPSAGENT_BENCH_BATCH=8 \
                   OPSAGENT_BENCH_KV=int8

fi  # SKIP_EXTRAS

echo "results in $OUT:" | tee -a "$OUT/session.log"
cat "$OUT/bench.jsonl"
