#!/usr/bin/env python
"""Headline benchmark: paged-decode throughput (tokens/sec/chip).

Runs the serving engine's continuous-batching decode loop at steady state and
reports aggregate decode tokens/sec divided by chip count — the north-star
serving metric from BASELINE.json (target: 2000 tok/s/chip, Llama-3-8B class,
v5e). Prints ONE JSON line on stdout:

    {"metric": "...", "value": N, "unit": "tok/s/chip", "vs_baseline": N}

On a TPU host, a plain `python bench.py` runs BOTH presets in isolated
subprocesses — bench-1b first (guaranteed number), then the bench-8b
headline (int8, the BASELINE 8B-class target) — and prints the 8B result
with the 1B throughput alongside in `extra`. Model/batch are overridable
via env (OPSAGENT_BENCH_MODEL, OPSAGENT_BENCH_BATCH, OPSAGENT_BENCH_STEPS),
which runs that single configuration inline. On a CPU-only host the bench
automatically drops to the tiny test model so it still completes; the
recorded number is only meaningful on TPU.

OPSAGENT_BENCH_MODE=sessions switches to the BASELINE config-5 scenario:
``batch`` concurrent client sessions submitting chat completions through
the full stack (OpenAI translation -> scheduler admission -> chunked
prefill -> pipelined decode), reporting aggregate tok/s/chip and the p50
TTFT clients actually observed.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_TOK_S_PER_CHIP = 2000.0  # BASELINE.json north_star decode target


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    # Plain `python bench.py` on a TPU host orchestrates BOTH presets in
    # subprocesses (1B first for a guaranteed number, then the 8B-class
    # headline). Explicit OPSAGENT_BENCH_MODEL/MODE requests run inline.
    if (
        os.environ.get("OPSAGENT_BENCH_MODEL")
        or os.environ.get("OPSAGENT_BENCH_MODE")
    ):
        run_single()
    elif _probe_platform() == "tpu":
        run_orchestrated()
    else:
        run_single()


def _probe_platform() -> str:
    """Platform of jax.devices()[0], probed in a SUBPROCESS so the parent
    never initializes the TPU client itself — on single-chip tunneled
    setups the parent holding the device would starve the child runs."""
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=300,
        )
        return out.stdout.strip().splitlines()[-1] if out.stdout else "none"
    except Exception:  # noqa: BLE001
        return "none"


def _run_child(model: str, timeout_s: int) -> dict | None:
    """Run one bench preset in a subprocess; return its parsed JSON line.
    Subprocess isolation means a wedged device link or OOM in one preset
    cannot take down the other's already-collected result."""
    import subprocess

    env = dict(os.environ, OPSAGENT_BENCH_MODEL=model)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        log(f"bench[{model}]: TIMED OUT after {timeout_s}s")
        return None
    sys.stderr.write(out.stderr)
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            if "metric" in parsed:
                return parsed
        except json.JSONDecodeError:
            continue
    log(f"bench[{model}]: no JSON result (rc={out.returncode})")
    return None


def run_orchestrated() -> None:
    """TPU default: bench-1b first (the safe, known-good configuration —
    its weights are generated on device, no bulk transfer), then the
    bench-8b headline (BASELINE.md names an 8B-class model). Prints ONE
    JSON line: the 8B result when it completes, with the 1B number
    alongside in extra; the 1B result otherwise."""
    r1b = _run_child("bench-1b", timeout_s=1200)
    r8b = _run_child("bench-8b", timeout_s=1500)
    if r8b is not None:
        if r1b is not None:
            r8b.setdefault("extra", {})["bench_1b_tok_s_chip"] = r1b["value"]
        print(json.dumps(r8b))
    elif r1b is not None:
        r1b.setdefault("extra", {})["bench_8b"] = "failed (see stderr)"
        print(json.dumps(r1b))
    else:
        log("bench: both presets failed")
        sys.exit(1)


def run_single() -> None:
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    n_chips = len(jax.devices())

    model = os.environ.get(
        "OPSAGENT_BENCH_MODEL", "bench-1b" if on_tpu else "tiny-test"
    )
    batch = int(os.environ.get("OPSAGENT_BENCH_BATCH", "32" if on_tpu else "4"))
    steps = int(os.environ.get("OPSAGENT_BENCH_STEPS", "512" if on_tpu else "16"))
    prompt_len = int(os.environ.get("OPSAGENT_BENCH_PROMPT", "128"))
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    # Measured on v5e: the XLA gather attention currently beats the Pallas
    # kernel at decode shapes (the kernel's (B, MaxP) grid is overhead-bound
    # at one page per step); pin the faster impl unless the caller chose.
    os.environ.setdefault("OPSAGENT_PAGED_BACKEND", "xla")

    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    log(f"bench: platform={platform} chips={n_chips} model={model} "
        f"batch={batch} steps={steps}")

    # bench-8b: 16 GB of bf16 weights do not fit the 16 GB chip — serve
    # weight-only int8 (8 GB + scales), which also halves the
    # weight-streaming time that bounds decode.
    quantize = os.environ.get(
        "OPSAGENT_BENCH_QUANT", "int8" if model == "bench-8b" else ""
    )
    # Large pages (fewer gather/grid steps per decode) and a page budget of
    # 128 prompt + 512 generated + slack for the decode pipeline's lookahead
    # (decode_block x (pipeline_depth + 1) tokens are pre-booked).
    cfg = EngineConfig(
        model=model,
        dtype=dtype,
        max_batch_size=batch,
        num_pages=max(512, batch * 12),
        page_size=64,
        max_pages_per_seq=12,
        prefill_buckets=(prompt_len,),
        quantize=quantize,
    )
    t0 = time.perf_counter()
    eng = Engine(cfg)
    init_s = time.perf_counter() - t0
    log(f"bench: engine init (weights+shard) {init_s:.1f}s")
    t0 = time.perf_counter()
    warmup_s = eng.warmup()
    log(f"bench: warmup (all programs compiled) {warmup_s:.1f}s "
        f"(persistent cache makes repeat runs fast)")

    if os.environ.get("OPSAGENT_BENCH_MODE") == "sessions":
        run_sessions(eng, model, batch, steps, prompt_len, platform,
                     n_chips, quantize, init_s, warmup_s)
        return

    rng = np.random.default_rng(0)
    vocab = eng.model_cfg.vocab_size
    sampling = SamplingParams(temperature=0.0, max_tokens=10**9)

    # Admit a full batch. With the warmed engine the FIRST admission is
    # compile-free — its TTFT is the honest cold-request number.
    t0 = time.perf_counter()
    ids = []
    ttfts = []
    for i in range(batch):
        prompt = rng.integers(1, vocab, size=prompt_len).tolist()
        t1 = time.perf_counter()
        sid = eng.add_request(prompt, sampling)
        ttfts.append(time.perf_counter() - t1)
        ids.append(sid)
    log(f"bench: admitted {batch} reqs in {time.perf_counter() - t0:.1f}s; "
        f"first-request TTFT {ttfts[0]*1e3:.0f} ms (warmed, no compile)")

    # Warm up decode (compilation + cache donation settle), then drain the
    # pipeline so warmup tokens don't leak into the timed window.
    eng.step_block(ids)
    eng.drain()

    # Steady-state decode: `steps` tokens per sequence, block dispatches.
    # The final drain pulls the last in-flight blocks so `produced` counts
    # exactly the tokens whose compute falls inside dt.
    # OPSAGENT_PROFILE_DIR=<dir> captures a jax.profiler device trace of
    # exactly the timed window (open in TensorBoard to see where the
    # ms/step go); a no-op otherwise.
    from opsagent_tpu.utils.profiling import trace

    block = eng.cfg.decode_block
    produced = 0
    with trace():
        # Clock inside the trace context: start_trace/stop_trace overhead
        # (trace serialization takes seconds) must not deflate the number.
        t0 = time.perf_counter()
        for _ in range(max(1, steps // block)):
            out = eng.step_block(ids)
            produced += sum(len(v) for v in out.values())
        produced += sum(len(v) for v in eng.drain().values())
        dt = time.perf_counter() - t0

    tok_s = produced / dt
    tok_s_chip = tok_s / n_chips
    # Post-warmup TTFT (compile-free) from the later admissions.
    p50_ttft_ms = float(np.median(ttfts[1:]) * 1e3) if len(ttfts) > 1 else 0.0

    log(f"bench: {produced} tokens in {dt:.2f}s -> {tok_s:.0f} tok/s total, "
        f"{tok_s_chip:.0f} tok/s/chip; p50 TTFT {p50_ttft_ms:.0f} ms")

    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"paged_decode_throughput[{model}{qtag},B={batch},{platform}]",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s_chip / BASELINE_TOK_S_PER_CHIP, 3),
        "extra": {
            "total_tok_s": round(tok_s, 1),
            "p50_ttft_ms": round(p50_ttft_ms, 1),
            "first_ttft_ms": round(ttfts[0] * 1e3, 1),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
        },
    }))


def run_sessions(eng, model, batch, steps, prompt_len, platform, n_chips,
                 quantize, init_s, warmup_s) -> None:
    """BASELINE config 5: ``batch`` concurrent sessions through the FULL
    stack — OpenAI chat translation (templates, usage accounting) ->
    scheduler admission -> chunked prefill -> pipelined decode — each
    generating ``steps // 8`` tokens per round for several rounds in the
    agent-loop shape (re-send the grown history, so the prefix cache
    carries earlier rounds' KV)."""
    import threading

    from opsagent_tpu.serving.api import ServingStack

    stack = ServingStack(eng)
    gen_tokens = max(16, steps // 8)
    rounds = 3
    results: list[dict] = []
    lock = threading.Lock()

    def session(sid: int) -> None:
        # Chat history grows across rounds like a real agent loop — each
        # round re-sends the whole conversation, so the prefix cache
        # carries the earlier rounds' KV. Per-session generator: numpy
        # Generators are not thread-safe, and distinct seeds keep prompts
        # distinct so cross-session prefix hits can't inflate the number.
        rng = np.random.default_rng(1000 + sid)
        words = [f"w{rng.integers(0, 9999)}" for _ in range(prompt_len // 2)]
        messages = [
            {"role": "system", "content": "bench session"},
            {"role": "user", "content": " ".join(words)},
        ]
        for r in range(rounds):
            t0 = time.perf_counter()
            try:
                resp = stack.chat_completion({
                    "messages": messages,
                    "max_tokens": gen_tokens,
                    "temperature": 0.0,
                })
            except Exception as e:  # noqa: BLE001
                with lock:
                    results.append({"err": str(e)})
                return
            dt = time.perf_counter() - t0
            msg = resp["choices"][0]["message"]
            messages.append(
                {"role": "assistant", "content": msg.get("content") or ""}
            )
            messages.append({"role": "user", "content": f"continue {r}"})
            with lock:
                results.append({
                    "tokens": resp["usage"]["completion_tokens"], "wall": dt,
                })

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=session, args=(i,)) for i in range(batch)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    errs = [r for r in results if "err" in r]
    ok = [r for r in results if "tokens" in r]
    produced = sum(r["tokens"] for r in ok)
    tok_s_chip = produced / wall / n_chips
    from opsagent_tpu.utils.perf import get_perf_stats

    stats = get_perf_stats().get_stats()
    ttft = stats.get("engine.ttft", {})
    log(f"bench[sessions]: {batch} sessions x {rounds} rounds, "
        f"{produced} tokens in {wall:.2f}s -> {tok_s_chip:.0f} tok/s/chip; "
        f"p50 TTFT {ttft.get('p50', 0):.0f} ms; errors={len(errs)}")
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"concurrent_sessions[{model}{qtag},N={batch},{platform}]",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s_chip / BASELINE_TOK_S_PER_CHIP, 3),
        "extra": {
            "sessions": batch,
            "rounds": rounds,
            "p50_ttft_ms": round(float(ttft.get("p50", 0)), 1),
            "p99_ttft_ms": round(float(ttft.get("p99", 0)), 1),
            "errors": len(errs),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
        },
    }))
    stack.close()


if __name__ == "__main__":
    main()
