#!/usr/bin/env python
"""Headline benchmark: paged-decode throughput (tokens/sec/chip).

Runs the serving engine's continuous-batching decode loop at steady state and
reports aggregate decode tokens/sec divided by chip count — the north-star
serving metric from BASELINE.json (target: 2000 tok/s/chip, Llama-3-8B class,
v5e). Prints ONE JSON line on stdout:

    {"metric": "...", "value": N, "unit": "tok/s/chip", "vs_baseline": N}

A plain `python bench.py` orchestrates up to fifteen stages in isolated
subprocesses under one wall-clock budget (OPSAGENT_BENCH_BUDGET, default
850 s): the default preset first (bench-1b on TPU, tiny-test elsewhere —
the guaranteed number), then the bench-8b int8 headline, its int4,
int8-KV-pages, and combined int4+int8-KV variants (the fastest 8B
variant becomes the headline), the BASELINE config-5 concurrent-sessions
run, the sessions-mixed A/B (mixed prefill+decode batching on vs. off on
the same workload), the sessions-async A/B (one-step-lookahead async
mixed ticks, async_depth 2 vs. 1, reporting tok/s and host-gap p50 for
both phases plus an identical-output check), the sessions-offload A/B
(hierarchical KV: host-RAM offload tier off vs. on under page pressure),
the fleet-affinity A/B (two engine replicas behind the fleet router:
prefix-affinity + sticky placement vs stateless least-loaded, reporting
re-prefill-avoided tokens and p50 TTFT per phase),
the agent-turns stage (north-star p50 TTFT per tool-call turn), the
pallas-dma kernel comparison (plain and kv-int8), a cold-restart TTFT
probe against the stage-1-primed compilation cache, and last a
speculative-decoding overhead run (its question is already
measurement-closed).
EVERY result line is printed
and flushed the moment it exists (the driver kills this process at an
unknown wall clock; an already-earned number must survive), and a
combined headline line is printed last. If the default preset dies —
e.g. the tunneled TPU is unreachable, which blocks jax backend init
indefinitely (the round-2 rc=124 failure) — a cpu-pinned fallback child
(TPU plugin stripped from its env) still produces a parsed line.

Model/batch are overridable via env (OPSAGENT_BENCH_MODEL,
OPSAGENT_BENCH_BATCH, OPSAGENT_BENCH_STEPS), which runs that single
configuration inline. OPSAGENT_BENCH_MODE=sessions switches to the
BASELINE config-5 scenario: ``batch`` concurrent client sessions
submitting chat completions through the full stack (OpenAI translation
-> scheduler admission -> chunked prefill -> pipelined decode),
reporting aggregate tok/s/chip and the p50 TTFT clients observed.
OPSAGENT_BENCH_MODE=sessions-mixed runs that same workload TWICE against
one engine — mixed prefill+decode batching on, then off — and reports
both (the one-weight-stream-per-tick delta); OPSAGENT_BENCH_MIXED=0
pins the split tick for any other mode.
OPSAGENT_BENCH_MODE=sessions-async runs the workload twice with the
one-step-lookahead async mixed pipeline on (async_depth=2), then with
synchronous ticks (depth=1), same prompt seeds — reporting tok/s,
host-gap p50, and overlapped-commit counts for both phases plus a
byte-identical-output verdict; OPSAGENT_BENCH_ASYNC=<depth> pins the
depth for any other mode.
OPSAGENT_BENCH_MODE=sessions-ffwd runs the sessions workload with every
completion constrained to a JSON schema, twice — grammar fast-forward
on (forced-token runs splice into the KV without forward passes), then
off — same prompt seeds, reporting tok/s, the forced-token fraction,
and skipped dispatches per phase plus a byte-identical-output verdict.
OPSAGENT_BENCH_MODE=fleet-affinity runs the sessions workload over
OPSAGENT_BENCH_REPLICAS (default 2) in-process engine replicas behind
the fleet router, twice — prefix-affinity + sticky placement on, then
stateless least-loaded — reporting p50 TTFT and re-prefill-avoided
tokens for both phases in one JSON line.
OPSAGENT_BENCH_MODE=fleet-chaos runs that fleet workload twice more —
seeded faults off, then on (serving/faults: mid-SSE disconnects at
fixed hit counts) — reporting failed requests (must stay 0: router
failover absorbs the deaths), failovers, shed count, and the p99 TTFT
delta containment costs, in one JSON line.
OPSAGENT_BENCH_MODE=fleet-journey runs the streamed fleet workload with
request journeys on vs off (the obs-overhead A/B) plus one stitched-
timeline smoke: a request forced through mid-SSE failover + peer
fault-in must yield ONE router timeline with lanes from both replicas,
failover/fault_in windows, >= 95% coverage, monotonic segments.
``--perf-gate`` (or OPSAGENT_BENCH_PERF_GATE=1) compares the
orchestrated run's result lines against the committed
BENCH_r*_local.jsonl baseline after the headline is printed and exits 4
on regression — the --slo-strict twin for perf (see
scripts/perf_gate.py / `opsagent perf-check` for the standalone gate).
OPSAGENT_BENCH_MODE=agent runs the north-star agent shape instead:
multi-turn ReAct sessions (observation-as-user-message, full-history
resend) with the prefix cache on, reporting p50 client TTFT per
tool-call turn and the prefix-hit rate.
OPSAGENT_BENCH_MODE=agent-conveyor trains the tiny BPE agent
in-process (seconds on CPU), serves the checkpoint, and runs the
scripted tool episode with conveyor mid-decode tool launches on vs off
— p50 episode wall, overlap seconds banked behind decode, early-launch
count, the byte-identical-transcript verdict, and the
zero-post-warmup-compiles invariant for both phases.
OPSAGENT_BENCH_MODE=cold-start runs the snapshot/restore A/B
(serving/snapshot): fresh-init request-ready vs Engine.from_snapshot
request-ready against empty compile caches, with byte-identical greedy
outputs and the zero-post-warmup-compiles invariant checked on the
restored engine.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from opsagent_tpu.utils.perf import get_perf_stats

BASELINE_TOK_S_PER_CHIP = 2000.0  # BASELINE.json north_star decode target

# The north-star target is defined for an 8B-class model on real TPU
# hardware (BASELINE.md). A ratio against it is only meaningful for that
# class on that platform: a tiny CPU-fallback model "at 2.9x baseline"
# (BENCH_r03) reads as a target hit on any dashboard that doesn't open
# extra.note. Everything else reports vs_baseline: null.
BASELINE_CLASS_MODELS = ("bench-8b", "llama-3-8b-instruct")


def vs_baseline(tok_s_chip: float, model: str, platform: str) -> float | None:
    """Ratio vs the BASELINE.md north star, or None when the ratio would
    be meaningless (platform is not tpu, or the model is not 8B-class)."""
    if platform != "tpu" or model not in BASELINE_CLASS_MODELS:
        return None
    return round(tok_s_chip / BASELINE_TOK_S_PER_CHIP, 3)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def log_perf_table() -> None:
    """Per-phase engine series (prefill chunks, decode dispatches, ttft
    — count/avg/p95/p99/max) to stderr: on chip this lands in
    session.log and localizes first-call overhead (e.g. the r04 ~2 s
    first-request TTFT) without a second instrumented run."""
    log(get_perf_stats().format_table())


def metrics_snapshot() -> dict:
    """Compact dump of the obs registry (the same samples a GET /metrics
    scrape would expose: TTFT/ITL histogram count+sum, decode-token and
    dispatch counters, KV-page gauges), folded into every bench JSON line
    so BENCH_*.json records engine telemetry alongside the latency
    numbers."""
    try:
        from opsagent_tpu.obs import metrics_snapshot as snap

        return snap()
    except Exception:  # noqa: BLE001 - telemetry must never sink a bench
        return {}


def attribution_snapshot() -> dict:
    """The goodput ledger's roofline snapshot (obs/attribution.py):
    modeled bytes by kind, MFU / HBM-utilization over the rate window,
    and the measured-vs-modeled drift EMA — folded into every result
    line so a BENCH artifact carries its own attribution."""
    try:
        from opsagent_tpu.obs import attribution

        return attribution.snapshot()
    except Exception:  # noqa: BLE001 - telemetry must never sink a bench
        return {}


def slo_verdicts() -> dict:
    """The declared-SLO verdicts (obs.slo) over this run's histograms —
    the same evaluation ``GET /api/slo`` serves and ``opsagent
    slo-check --bench`` reads back out of the BENCH JSON."""
    try:
        from opsagent_tpu.obs import slo

        return slo.evaluate()
    except Exception:  # noqa: BLE001 - telemetry must never sink a bench
        return {}


def slo_strict() -> bool:
    return (
        "--slo-strict" in sys.argv[1:]
        or os.environ.get("OPSAGENT_BENCH_SLO_STRICT", "") not in ("", "0")
    )


def perf_gate_enabled() -> bool:
    """``--perf-gate`` (or OPSAGENT_BENCH_PERF_GATE=1): after the
    headline line is printed, compare this run's result lines against
    the committed BENCH_r*_local.jsonl baseline (the slo-strict twin for
    perf regressions; orchestrator-level, since the comparison spans
    stages)."""
    return (
        "--perf-gate" in sys.argv[1:]
        or os.environ.get("OPSAGENT_BENCH_PERF_GATE", "") not in ("", "0")
    )


def exit_if_perf_regression(rows: list) -> None:
    """Under ``--perf-gate``, a regression vs the committed baseline
    fails the orchestrator with exit 4 (distinct from --slo-strict's 3).
    Called AFTER every result line is printed, so no number is ever lost
    to the verdict; exits only on a CONFIRMED regression — disjoint
    metric sets (e.g. a cpu fallback run vs a tpu baseline) pass with a
    note, because absence of evidence is the budget's business."""
    if not perf_gate_enabled():
        return
    try:
        from opsagent_tpu.cli.perfcheck import (
            compare, default_baseline, format_report, load_rows,
        )
    except Exception as e:  # noqa: BLE001
        log(f"bench: --perf-gate unavailable: {e}")
        return
    baseline = default_baseline()
    if not baseline:
        log("bench: --perf-gate: no committed baseline jsonl; skipping")
        return
    report = compare(
        [r for r in rows if r is not None], load_rows(baseline)
    )
    log(f"bench: --perf-gate vs {os.path.basename(baseline)}:")
    log(format_report(report))
    if report["pass"] is False:
        sys.exit(4)


def exit_if_slo_breach(slo: dict) -> None:
    """Under ``--slo-strict`` (or OPSAGENT_BENCH_SLO_STRICT=1), a
    breached declared SLO fails the bench process — the CI-gate form of
    the watchdog. Called AFTER the result line is printed, so the number
    is never lost to the verdict."""
    if not slo_strict():
        return
    failed = [
        v["name"] for v in (slo or {}).get("slos", [])
        if v.get("pass") is False
    ]
    if failed:
        log(f"bench: --slo-strict: SLO breach: {', '.join(failed)}")
        sys.exit(3)


def main() -> None:
    # Plain `python bench.py` orchestrates the presets in subprocesses
    # (guaranteed-fast number first, headline after, sessions last, all
    # under one wall-clock budget). Explicit OPSAGENT_BENCH_MODEL/MODE
    # requests — and orchestrator children — run a single config inline.
    if slo_strict():
        # Children are spawned without argv: carry the flag in the env so
        # every stage applies the same gate.
        os.environ["OPSAGENT_BENCH_SLO_STRICT"] = "1"
    if (
        os.environ.get("_OPSAGENT_BENCH_CHILD")
        or os.environ.get("OPSAGENT_BENCH_MODEL")
        or os.environ.get("OPSAGENT_BENCH_MODE")
    ):
        run_single()
    else:
        run_orchestrated()


def _cpu_env() -> dict:
    """Child env that can NEVER touch the TPU: strips the PJRT-plugin
    sitecustomize trigger and pins the cpu platform. Used for the
    last-resort fallback when the tunneled chip is unreachable (a wedged
    tunnel blocks jax backend init indefinitely — the round-2 failure
    mode), so the driver still records a parsed line proving the
    harness works. ``None`` values mean REMOVE the var from the child env
    (the same mechanism as __graft_entry__'s dryrun child)."""
    return {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": None}


def _run_child_rows(
    env_extra: dict, timeout_s: float, tag: str
) -> list[dict]:
    """Run one bench preset in a subprocess; return EVERY parsed JSON
    row it printed, in print order. Multi-row stages (the ragged sweep
    prints one row per cell) need all of them; single-row stages take
    the last via ``_run_child``.

    Subprocess isolation means a wedged device link or OOM in one preset
    cannot take down the other's already-collected result. The child's
    stderr is INHERITED (progress streams to the driver's tail in real
    time); stdout is captured on a reader thread so a timeout kill still
    yields any JSON the child managed to print."""
    import subprocess
    import threading

    if timeout_s < 60:
        log(f"bench[{tag}]: skipped ({timeout_s:.0f}s left is too little)")
        return []
    env = dict(os.environ, _OPSAGENT_BENCH_CHILD="1")
    for k, v in env_extra.items():
        if v is None:
            env.pop(k, None)
        else:
            env[k] = v
    proc = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=None, text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    lines: list[str] = []

    def _read() -> None:
        for line in proc.stdout:
            lines.append(line)

    reader = threading.Thread(target=_read, daemon=True)
    reader.start()
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log(f"bench[{tag}]: TIMED OUT after {timeout_s:.0f}s, killing")
        proc.kill()
        proc.wait()
    reader.join(timeout=10)
    rows: list[dict] = []
    for line in lines:
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            rows.append(parsed)
    if not rows:
        log(f"bench[{tag}]: no JSON result (rc={proc.returncode})")
    return rows


def _run_child(env_extra: dict, timeout_s: float, tag: str) -> dict | None:
    """Single-row form of ``_run_child_rows``: the LAST parsed row is
    the stage's result (children print their summary line last — the
    same contract the driver applies to the orchestrator itself)."""
    rows = _run_child_rows(env_extra, timeout_s, tag)
    return rows[-1] if rows else None


def run_orchestrated() -> None:
    """Budgeted multi-preset run. The contract with the driver (which
    kills the whole process group at an unknown wall clock) is: flush
    every result line the moment it exists, so a later kill can never
    erase an already-earned number, and print the headline line LAST so
    the driver's last-JSON-line parse picks it up.

    Order: default preset (bench-1b on TPU, tiny-test elsewhere — the
    guaranteed number), then the bench-8b int8 headline and its int4,
    int8-KV, and combined int4+int8-KV variants, the BASELINE config-5
    concurrent-sessions run, the sessions-mixed A/B, the agent-turns
    stage, the pallas-dma kernel comparisons, the cold-restart TTFT
    probe, and the speculative-decoding overhead run
    last; the later stages only start if the
    remaining budget plausibly covers them. Mode/spec env vars are
    stripped from stages
    they don't belong to, so an operator-set OPSAGENT_BENCH_SPEC cannot
    contaminate the baseline stages."""
    budget = float(os.environ.get("OPSAGENT_BENCH_BUDGET", "850"))
    t_start = time.perf_counter()

    def remaining() -> float:
        return budget - (time.perf_counter() - t_start)

    # None-valued entries REMOVE inherited vars (see _run_child): an
    # operator-exported spec/mode/backend var must not contaminate the
    # stages it doesn't belong to (the pallas-dma stage is compared
    # against stage 1's xla default).
    base = {
        "OPSAGENT_BENCH_SPEC": None,
        "OPSAGENT_BENCH_MODE": None,
        "OPSAGENT_PAGED_BACKEND": None,
        "OPSAGENT_BENCH_QUANT": None,
        "OPSAGENT_BENCH_KV": None,
        "OPSAGENT_BENCH_MIXED": None,
        "OPSAGENT_BENCH_ASYNC": None,
        # An operator-exported fault spec must never contaminate the
        # perf stages; the fleet-chaos stage pins its own spec in-process.
        "OPSAGENT_FAULTS": None,
    }

    def stage(env_extra: dict, min_remaining: float, tag: str,
              cap: float | None = None) -> dict | None:
        """One budget-gated preset: run, flush its line immediately."""
        if remaining() <= min_remaining:
            log(f"bench: skipping {tag} ({remaining():.0f}s left)")
            return None
        timeout_s = remaining() - 10
        if cap is not None:
            timeout_s = min(cap, timeout_s)
        r = _run_child({**base, **env_extra}, timeout_s, tag)
        if r is not None:
            print(json.dumps(r), flush=True)
        return r

    def stage_rows(env_extra: dict, min_remaining: float, tag: str,
                   cap: float | None = None) -> list[dict]:
        """Multi-row stage: flush EVERY row the child earned, in order,
        the moment the child exits (the sweep's per-cell rows are each a
        first-class perf-gate series — losing all-but-last would reduce
        the sweep to a single backend's number)."""
        if remaining() <= min_remaining:
            log(f"bench: skipping {tag} ({remaining():.0f}s left)")
            return []
        timeout_s = remaining() - 10
        if cap is not None:
            timeout_s = min(cap, timeout_s)
        rows = _run_child_rows({**base, **env_extra}, timeout_s, tag)
        for r in rows:
            print(json.dumps(r), flush=True)
        return rows

    stage1_cap = float(os.environ.get("OPSAGENT_BENCH_STAGE1_CAP", "390"))
    # Whatever the budget, stage 1 must leave room for the cpu fallback
    # (its cap is 180s + child startup): a wedged-device kill at the full
    # stage-1 cap must never eat the guaranteed-line stage too. Budgets
    # too small to fit both skip the device stage entirely.
    FALLBACK_RESERVE = 220.0
    note = ""
    if remaining() - FALLBACK_RESERVE >= 60.0:
        r1 = stage(
            {}, 0, "default",
            cap=min(stage1_cap, remaining() - FALLBACK_RESERVE),
        )
        if r1 is None:
            note = "cpu fallback: tpu device unreachable during bench window"
    else:
        log(f"bench: {remaining():.0f}s budget cannot fit a device stage "
            f"plus the fallback; running cpu-pinned only")
        r1 = None
        note = "cpu-pinned only: budget too small for device stage + fallback"
    if r1 is None:
        # Device unreachable, preset wedged, or budget too small: a
        # cpu-pinned child (no TPU plugin) still proves the stack end to
        # end and guarantees the driver a parsed line.
        r1 = stage(
            {**_cpu_env(), "OPSAGENT_BENCH_MODEL": "tiny-test"},
            0, "cpu-fallback", cap=180.0,
        )
        if r1 is not None:
            r1.setdefault("extra", {})["note"] = note
    platform = (r1 or {}).get("extra", {}).get("platform", "")
    headline = r1

    on_tpu = platform == "tpu"
    r8b = stage({"OPSAGENT_BENCH_MODEL": "bench-8b"}, 420, "8b") \
        if on_tpu else None
    if r8b is not None:
        headline = r8b
    # int4 variant of the headline: weight streaming halves again vs
    # int8, so if decode is weight-bound this stage should show it (and
    # if not, the delta localizes the bottleneck to KV/attention/host).
    r8b4 = stage(
        {"OPSAGENT_BENCH_MODEL": "bench-8b",
         "OPSAGENT_BENCH_QUANT": "int4"},
        330, "8b-int4",
    ) if on_tpu and r8b is not None else None
    if r8b4 is not None and r8b4["value"] > headline["value"]:
        headline = r8b4
    # int8 KV pages on the int8-weight headline: halves the KV-read term
    # the roofline blames for most of the non-weight step time. Promoted
    # to headline if faster, same promote-if-faster flow as int4.
    r8bkv = stage(
        {"OPSAGENT_BENCH_MODEL": "bench-8b",
         "OPSAGENT_BENCH_KV": "int8"},
        330, "8b-kv-int8",
    ) if on_tpu and r8b is not None else None
    if r8bkv is not None and r8bkv["value"] > headline["value"]:
        headline = r8bkv
    # Both levers compose (weight stream and KV reads are additive HBM
    # terms): measure int4 weights + int8 KV together when each stage
    # produced a number, and promote if fastest.
    r8b4kv = stage(
        {"OPSAGENT_BENCH_MODEL": "bench-8b",
         "OPSAGENT_BENCH_QUANT": "int4",
         "OPSAGENT_BENCH_KV": "int8"},
        330, "8b-int4-kv-int8",
    ) if on_tpu and r8b4 is not None and r8bkv is not None else None
    if r8b4kv is not None and r8b4kv["value"] > headline["value"]:
        headline = r8b4kv
    rsess = stage(
        {"OPSAGENT_BENCH_MODE": "sessions",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        240, "sessions",
    ) if on_tpu else None
    # Mixed-batching A/B on the sessions workload: the same config-5
    # scenario run with the unified mixed prefill+decode tick and with
    # the split tick in ONE child, so the one-weight-stream-per-tick
    # delta (tok/s and p50 TTFT) lands as a first-class BENCH artifact.
    rsessmix = stage(
        {"OPSAGENT_BENCH_MODE": "sessions-mixed",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        240, "sessions-mixed",
    ) if on_tpu else None
    # Async-tick A/B on the same workload: one-step-lookahead mixed
    # pipeline (depth=2) vs synchronous ticks (depth=1) in one child —
    # tok/s + host-gap p50 for both phases, plus the identical-output
    # verdict that proves the lookahead changes WHEN host work happens,
    # never WHAT gets generated.
    rsessasync = stage(
        {"OPSAGENT_BENCH_MODE": "sessions-async",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        240, "sessions-async",
    ) if on_tpu else None
    # Grammar fast-forward A/B: every completion schema-constrained,
    # forced-token runs spliced without forward passes (on) vs paying a
    # dispatch per token (off) — tok/s, forced-token fraction, skipped
    # dispatches, and the byte-identical-output verdict.
    rsessffwd = stage(
        {"OPSAGENT_BENCH_MODE": "sessions-ffwd",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        240, "sessions-ffwd",
    ) if on_tpu else None
    # Hierarchical-KV A/B on the same workload under page pressure:
    # offload tier off vs on (host-pool spill/park/restore) in one child.
    rsessoff = stage(
        {"OPSAGENT_BENCH_MODE": "sessions-offload",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        240, "sessions-offload",
    ) if on_tpu else None
    # Fleet-affinity A/B: the sessions workload over TWO in-process
    # engine replicas behind the FleetRouter — prefix-affinity + sticky
    # placement (comebacks restore from the owning replica's host pool)
    # vs stateless least-loaded placement (comebacks usually re-prefill
    # on the wrong replica). The decision numbers for ROADMAP item 3's
    # fleet front-end.
    rfleet = stage(
        {"OPSAGENT_BENCH_MODE": "fleet-affinity",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        240, "fleet-affinity",
    ) if on_tpu else None
    # Failure-containment A/B: the same fleet workload with seeded faults
    # OFF then ON (mid-SSE disconnects + connect failures). The chaos
    # phase must complete with ZERO failed requests — failovers absorb
    # the injected deaths; what it costs is the reported p99 TTFT delta.
    rchaos = stage(
        {"OPSAGENT_BENCH_MODE": "fleet-chaos",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        230, "fleet-chaos",
    ) if on_tpu else None
    # Fleet-global KV A/B: page directory + peer fault-in ON vs OFF over
    # the same forced-misroute session workload. The ON phase must land
    # second turns on a non-owning replica (and a freshly promoted
    # standby) through the wire-restore path with byte-identical greedy
    # output; the reported delta is re-prefill work avoided.
    rfgkv = stage(
        {"OPSAGENT_BENCH_MODE": "fleet-global-kv",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        240, "fleet-global-kv",
    ) if on_tpu else None
    # Fleet-journey obs-overhead A/B + stitched-timeline smoke: request
    # journeys (ID stamping + participants map + hop metrics) on vs off
    # on the streamed fleet workload, plus one forced failover+fault-in
    # request whose router timeline must stitch lanes from BOTH replicas
    # at >= 95% coverage. The reported value is the overhead percent
    # cross-replica tracing costs the request plane.
    rjourney = stage(
        {"OPSAGENT_BENCH_MODE": "fleet-journey",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        230, "fleet-journey",
    ) if on_tpu else None
    # Telemetry-history overhead A/B + downsample-tier proof: the
    # background sampler at 10x its production rate on vs off on the
    # sessions workload (byte-identical outputs, <= 2% tok/s), plus the
    # synthetic 90-min clock walk proving the 1s/10s/60s tiers and the
    # ring byte bound.
    robsh = stage(
        {"OPSAGENT_BENCH_MODE": "obs-history",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        230, "obs-history",
    ) if on_tpu else None
    # The literal north-star metric (BASELINE: p50 TTFT per tool-call
    # turn): multi-turn ReAct-shaped sessions with the prefix cache on.
    # Reports ms, not tok/s — never a headline candidate; folded into
    # extra below.
    ragent = stage(
        {"OPSAGENT_BENCH_MODE": "agent",
         "OPSAGENT_BENCH_MODEL": "bench-1b"},
        220, "agent-turns",
    ) if on_tpu else None
    # Conveyor tool-overlap A/B: the trained tiny agent's scripted tool
    # episodes with early mid-decode tool launches on vs off — p50
    # episode wall, overlap banked per turn, early-launch count, and the
    # byte-identical-transcript verdict. Trains its own checkpoint
    # in-process, so it runs on CPU too (the only stage besides the
    # default preset that does).
    rconvey = stage(
        {"OPSAGENT_BENCH_MODE": "agent-conveyor"},
        200, "agent-conveyor", cap=300.0,
    )
    # Kernel comparison (PERF.md plan item 2): the manual-DMA Pallas
    # paged-attention backend on the 8B int8 preset — the headline shape,
    # and the one whose head_dim (128) satisfies the kernel's Mosaic
    # alignment requirement (bench-1b's head_dim=64 cannot compile it;
    # r04 on-chip). Value vs the r8b stage (xla gather) decides the
    # default (ops/attention.py).
    rdma = stage(
        {"OPSAGENT_BENCH_MODEL": "bench-8b",
         "OPSAGENT_PAGED_BACKEND": "pallas-dma"},
        330, "pallas-dma",
    ) if on_tpu and r8b is not None else None
    if rdma is not None and rdma["value"] > headline["value"]:
        headline = rdma
    # The dma kernel also has a quantized path (int8 pages streamed, VMEM
    # dequantize): if both parents produced numbers, measure the
    # composition — the strongest candidate configuration when the kernel
    # beats the gather.
    rdmakv = stage(
        {"OPSAGENT_BENCH_MODEL": "bench-8b",
         "OPSAGENT_PAGED_BACKEND": "pallas-dma",
         "OPSAGENT_BENCH_KV": "int8"},
        330, "pallas-dma-kv",
    ) if rdma is not None and r8bkv is not None else None
    if rdmakv is not None and rdmakv["value"] > headline["value"]:
        headline = rdmakv
    # Ragged-backend sweep (ISSUE 15): the MIXED hot path (step_mixed →
    # paged_ragged_attention_auto) timed per backend × KV dtype × weight
    # quant on the bench-8b shape, one tok/s/chip row per cell with
    # self-describing resolved-impl extras. The dma stages above time
    # the legacy block-decode path; this stage times what serving
    # actually runs. Last row is the child's best-cell summary —
    # promote-if-faster like the int4 stage.
    sweep_rows = stage_rows(
        {"OPSAGENT_BENCH_MODE": "ragged-sweep",
         "OPSAGENT_BENCH_MODEL": "bench-8b"},
        320, "ragged-sweep",
    ) if on_tpu and r8b is not None else []
    rsweep = sweep_rows[-1] if sweep_rows else None
    if rsweep is not None and rsweep["value"] > headline["value"]:
        headline = rsweep
    # Cold-restart TTFT proof (VERDICT r03 #9): stage 1 primed the
    # persistent compilation cache; this fresh process re-inits the same
    # preset, so its init_s/warmup_s/first_ttft_ms ARE the
    # cold-process-warm-cache restart numbers against the p50 < 500 ms
    # target. Short decode: only the startup path matters here.
    rcold = stage(
        {"OPSAGENT_BENCH_MODEL": "bench-1b",
         "OPSAGENT_BENCH_STEPS": "64"},
        120, "cold-restart",
    ) if on_tpu else None
    # Cold-start A/B (ROADMAP item 4): fresh-init vs snapshot-restore
    # request-ready time in one child, both against empty compile caches
    # (the restore's cache holds only what the snapshot packaged), with
    # byte-identical greedy outputs and zero post-warmup compiles
    # asserted on the restored engine. The acceptance bar is restore
    # <= 0.5x fresh.
    rcoldstart = stage(
        {"OPSAGENT_BENCH_MODE": "cold-start",
         "OPSAGENT_BENCH_MODEL": "bench-1b",
         "OPSAGENT_BENCH_STEPS": "64"},
        150, "cold-start",
    ) if on_tpu else None
    # Speculative overhead LAST: the question is already answered by
    # measurement (k=4 was -76 % on chip; accept rate 6.6 % on the
    # trained agent; default 0) — under a tight driver budget the
    # decision-relevant stages above must land first.
    SPEC_K = 4
    rspec = stage(
        {"OPSAGENT_BENCH_MODEL": "bench-1b",
         "OPSAGENT_BENCH_SPEC": str(SPEC_K)},
        120, "spec",
    ) if on_tpu else None

    if headline is None:
        log("bench: no preset produced a number")
        sys.exit(1)
    # Combined headline, printed last: the driver records one parsed line.
    extra = dict(headline.get("extra", {}))
    if r1 is not None and headline is not r1:
        extra["bench_1b_tok_s_chip"] = r1["value"]
    if r8b is not None and headline is not r8b:
        extra["bench_8b_int8_tok_s_chip"] = r8b["value"]
    if r8b4 is not None and headline is not r8b4:
        extra["bench_8b_int4_tok_s_chip"] = r8b4["value"]
    if r8bkv is not None and headline is not r8bkv:
        extra["bench_8b_kv_int8_tok_s_chip"] = r8bkv["value"]
    if r8b4kv is not None and headline is not r8b4kv:
        extra["bench_8b_int4_kv_int8_tok_s_chip"] = r8b4kv["value"]
    if rsess is not None:
        extra["sessions_tok_s_chip"] = rsess["value"]
        extra["sessions_p50_ttft_ms"] = rsess.get("extra", {}).get(
            "p50_ttft_ms"
        )
    if rsessmix is not None:
        me = rsessmix.get("extra", {})
        extra["sessions_mixed_tok_s_chip"] = rsessmix["value"]
        extra["sessions_mixed_p50_ttft_ms"] = me.get("p50_ttft_ms")
        extra["sessions_split_tok_s_chip"] = me.get("split_tok_s_chip")
        extra["sessions_split_p50_ttft_ms"] = me.get("split_p50_ttft_ms")
    if rsessasync is not None:
        ae = rsessasync.get("extra", {})
        extra["sessions_async_tok_s_chip"] = rsessasync["value"]
        extra["sessions_async_host_gap_p50_ms"] = ae.get("host_gap_p50_ms")
        extra["sessions_async_sync_tok_s_chip"] = ae.get("sync_tok_s_chip")
        extra["sessions_async_sync_host_gap_p50_ms"] = ae.get(
            "sync_host_gap_p50_ms"
        )
        extra["sessions_async_outputs_identical"] = ae.get(
            "outputs_identical"
        )
    if rsessffwd is not None:
        fwe = rsessffwd.get("extra", {})
        extra["sessions_ffwd_tok_s_chip"] = rsessffwd["value"]
        extra["sessions_ffwd_forced_fraction"] = fwe.get("forced_fraction")
        extra["sessions_ffwd_skipped_dispatches"] = fwe.get(
            "skipped_dispatches"
        )
        extra["sessions_ffwd_off_tok_s_chip"] = fwe.get("off_tok_s_chip")
        extra["sessions_ffwd_outputs_identical"] = fwe.get(
            "outputs_identical"
        )
    if rsessoff is not None:
        oe = rsessoff.get("extra", {})
        extra["sessions_offload_tok_s_chip"] = rsessoff["value"]
        extra["sessions_offload_admission_wait_p50_ms"] = oe.get(
            "admission_wait_p50_ms"
        )
        extra["sessions_offload_off_admission_wait_p50_ms"] = oe.get(
            "off_admission_wait_p50_ms"
        )
        extra["sessions_offload_reprefill_avoided_tokens"] = oe.get(
            "reprefill_avoided_tokens"
        )
    if rfleet is not None:
        fe = rfleet.get("extra", {})
        extra["fleet_affinity_tok_s_chip"] = rfleet["value"]
        extra["fleet_affinity_p50_ttft_ms"] = fe.get("p50_ttft_ms")
        extra["fleet_affinity_reprefill_avoided_tokens"] = fe.get(
            "reprefill_avoided_tokens"
        )
        extra["fleet_off_p50_ttft_ms"] = fe.get("off_p50_ttft_ms")
        extra["fleet_off_reprefill_avoided_tokens"] = fe.get(
            "off_reprefill_avoided_tokens"
        )
    if rchaos is not None:
        che = rchaos.get("extra", {})
        extra["fleet_chaos_failed_requests"] = che.get("failed_requests")
        extra["fleet_chaos_failovers"] = che.get("failovers")
        extra["fleet_chaos_shed"] = che.get("shed")
        extra["fleet_chaos_p99_ttft_ms"] = che.get("p99_ttft_ms")
        extra["fleet_chaos_off_p99_ttft_ms"] = che.get("off_p99_ttft_ms")
        extra["fleet_chaos_outputs_identical"] = che.get(
            "outputs_identical"
        )
    if rjourney is not None:
        je = rjourney.get("extra", {})
        extra["fleet_journey_overhead_pct"] = rjourney["value"]
        extra["fleet_journey_on_tok_s"] = je.get("journeys_on_tok_s")
        extra["fleet_journey_off_tok_s"] = je.get("journeys_off_tok_s")
        extra["fleet_journey_smoke_ok"] = je.get("smoke_ok")
        extra["fleet_journey_smoke_coverage"] = je.get("smoke_coverage")
    if robsh is not None:
        he = robsh.get("extra", {})
        extra["obs_history_overhead_pct"] = robsh["value"]
        extra["obs_history_on_tok_s_chip"] = he.get(
            "sampler_on_tok_s_chip"
        )
        extra["obs_history_off_tok_s_chip"] = he.get(
            "sampler_off_tok_s_chip"
        )
        extra["obs_history_outputs_identical"] = he.get(
            "outputs_identical"
        )
        extra["obs_history_tiers_ok"] = (he.get("tiers") or {}).get("ok")
    if rfgkv is not None:
        ge = rfgkv.get("extra", {})
        extra["fleet_global_kv_remote_hit_pages"] = ge.get(
            "remote_hit_pages"
        )
        extra["fleet_global_kv_reprefill_avoided_tokens"] = ge.get(
            "reprefill_avoided_tokens"
        )
        extra["fleet_global_kv_outputs_identical"] = ge.get(
            "outputs_identical"
        )
        extra["fleet_global_kv_standby_identical"] = ge.get(
            "standby_identical"
        )
        extra["fleet_global_kv_p50_moved_ms"] = ge.get("p50_moved_ms")
        extra["fleet_global_kv_off_p50_moved_ms"] = ge.get(
            "off_p50_moved_ms"
        )
        extra["fleet_global_kv_fallbacks"] = ge.get("fallbacks")
    if ragent is not None:
        ae = ragent.get("extra", {})
        extra["agent_turn_p50_ttft_ms"] = ragent["value"]
        extra["agent_turn1_p50_ttft_ms"] = ae.get("turn1_p50_ttft_ms")
        extra["agent_prefix_hit_rate"] = ae.get("prefix_hit_rate")
    if rconvey is not None:
        ve = rconvey.get("extra", {})
        extra["agent_conveyor_p50_ms"] = rconvey["value"]
        extra["agent_conveyor_off_p50_ms"] = ve.get("off_p50_ms")
        extra["agent_conveyor_overlap_ms_per_turn"] = ve.get(
            "overlap_ms_per_turn"
        )
        extra["agent_conveyor_early_launches"] = ve.get("early_launches")
        extra["agent_conveyor_outputs_identical"] = ve.get(
            "outputs_identical"
        )
    if rspec is not None:
        extra[f"spec{SPEC_K}_overhead_tok_s_chip"] = rspec["value"]
    if rdma is not None and headline is not rdma:
        extra["pallas_dma_tok_s_chip"] = rdma["value"]
    if rdmakv is not None and headline is not rdmakv:
        extra["pallas_dma_kv_int8_tok_s_chip"] = rdmakv["value"]
    if rsweep is not None:
        se = rsweep.get("extra", {})
        if headline is not rsweep:
            extra["ragged_sweep_best_tok_s_chip"] = rsweep["value"]
        extra["ragged_sweep_best_cell"] = se.get("best_cell")
        extra["ragged_sweep_outputs_identical"] = se.get(
            "outputs_identical"
        )
        extra["ragged_sweep_cells"] = se.get("cells")
    if rcold is not None:
        ce = rcold.get("extra", {})
        extra["cold_restart_first_ttft_ms"] = ce.get("first_ttft_ms")
        extra["cold_restart_init_s"] = ce.get("init_s")
        extra["cold_restart_warmup_s"] = ce.get("warmup_s")
    if rcoldstart is not None:
        cse = rcoldstart.get("extra", {})
        extra["cold_start_fresh_request_ready_s"] = cse.get(
            "fresh_request_ready_s"
        )
        extra["cold_start_restore_request_ready_s"] = cse.get(
            "restore_request_ready_s"
        )
        extra["cold_start_speedup_ratio"] = cse.get("speedup_ratio")
        extra["cold_start_outputs_identical"] = cse.get(
            "outputs_identical"
        )
        extra["cold_start_post_warmup_compiles"] = cse.get(
            "post_warmup_compiles"
        )
    out = dict(headline, extra=extra)
    print(json.dumps(out), flush=True)
    # The children already gated themselves; re-check the headline's
    # folded verdicts so the ORCHESTRATOR's exit code is the CI signal.
    exit_if_slo_breach(extra.get("slo") or {})
    # Perf-regression gate LAST (exit 4): every earned number is already
    # printed, so the verdict can never eat a result line.
    exit_if_perf_regression([
        r1, r8b, r8b4, r8bkv, r8b4kv, rsess, rsessmix, rsessasync,
        rsessoff, rfleet, rchaos, rfgkv, ragent, rconvey, rdma, rdmakv,
        rcold, rcoldstart, rspec, robsh, *sweep_rows,
    ])


def run_single() -> None:
    log("bench: acquiring device (backend init; hangs here = tunnel down)")
    platform = jax.devices()[0].platform
    log(f"bench: device ready ({platform})")
    on_tpu = platform == "tpu"
    n_chips = len(jax.devices())

    model = os.environ.get(
        "OPSAGENT_BENCH_MODEL", "bench-1b" if on_tpu else "tiny-test"
    )
    batch = int(os.environ.get("OPSAGENT_BENCH_BATCH", "32" if on_tpu else "4"))
    steps = int(os.environ.get("OPSAGENT_BENCH_STEPS", "512" if on_tpu else "16"))
    prompt_len = int(os.environ.get("OPSAGENT_BENCH_PROMPT", "128"))
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    # Measured on v5e: the XLA gather attention currently beats the Pallas
    # kernel at decode shapes (the kernel's (B, MaxP) grid is overhead-bound
    # at one page per step); pin the faster impl unless the caller chose.
    os.environ.setdefault("OPSAGENT_PAGED_BACKEND", "xla")

    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    log(f"bench: platform={platform} chips={n_chips} model={model} "
        f"batch={batch} steps={steps}")

    # bench-8b: 16 GB of bf16 weights do not fit the 16 GB chip — serve
    # weight-only int8 (8 GB + scales), which also halves the
    # weight-streaming time that bounds decode.
    quantize = os.environ.get(
        "OPSAGENT_BENCH_QUANT", "int8" if model == "bench-8b" else ""
    )
    # Large pages (fewer gather/grid steps per decode) and a page budget of
    # 128 prompt + 512 generated + slack for the decode pipeline's lookahead
    # (decode_block x (pipeline_depth + 1) tokens are pre-booked).
    spec_k = int(os.environ.get("OPSAGENT_BENCH_SPEC", "0"))
    mode = os.environ.get("OPSAGENT_BENCH_MODE", "")
    if mode == "agent-conveyor":
        # Trains its own tiny checkpoint and builds its own engine (BPE
        # tokenizer, trained weights) — intercept before the shared
        # construction below.
        run_agent_conveyor(platform, n_chips)
        return
    if mode == "ragged-sweep":
        # Builds one engine per (backend x KV dtype x weight quant) cell
        # with its own geometry — intercept before the shared
        # construction below.
        run_ragged_sweep(platform, n_chips, model, batch, steps,
                         prompt_len)
        return
    if mode in ("sessions", "agent", "sessions-mixed", "sessions-offload",
                "sessions-async", "sessions-ffwd", "fleet-affinity",
                "fleet-chaos", "fleet-global-kv", "fleet-journey",
                "audit-fanout", "obs-history", "cold-start"):
        # Full-stack modes measure concurrency/TTFT; keep speculation out
        # of them (their warmup level does not compile the spec program).
        spec_k = 0
    # Mixed prefill+decode batching (EngineConfig.mixed_batching):
    # OPSAGENT_BENCH_MIXED=0 pins the split prefill/decode tick; the
    # sessions-mixed stage measures both in one child.
    mixed_on = os.environ.get("OPSAGENT_BENCH_MIXED", "") != "0"
    # One-step-lookahead async mixed ticks (EngineConfig.async_depth):
    # OPSAGENT_BENCH_ASYNC pins a depth; the sessions-mixed A/B forces
    # synchronous ticks — its question is one-weight-stream-per-tick,
    # not the lookahead (the sessions-async stage owns that A/B), and
    # pinning keeps its split phase an apples-to-apples comparison.
    async_depth = int(os.environ.get("OPSAGENT_BENCH_ASYNC", "2") or 2)
    if mode == "sessions-mixed":
        async_depth = 1
    kv_quantize = os.environ.get("OPSAGENT_BENCH_KV", "")
    # Page geometry, overridable for on-chip sweeps: the XLA gather reads
    # the FULL page-table capacity (max_pages x page_size) per step
    # regardless of resident tokens, so capacity directly scales the
    # KV-read term the roofline blames; the Pallas kernels read only
    # resident pages. OPSAGENT_BENCH_PAGE/OPSAGENT_BENCH_MAXPAGES let a
    # sweep probe that tradeoff without code edits.
    page_size = int(os.environ.get("OPSAGENT_BENCH_PAGE", "64"))
    decode_block = int(os.environ.get("OPSAGENT_BENCH_BLOCK", "32"))
    if mode == "agent":
        # The agent history grows by ~(generated + observation) tokens
        # per turn; size the per-seq page budget for the FINAL turn's
        # full history (plus decode lookahead), not the linear-decode
        # shape. Estimate CONSERVATIVELY in byte-tokenizer terms (the
        # bench presets' worst case: a "w1234" word is ~6-7 tokens, and
        # chat-template framing adds ~100+ per message): measured actuals
        # at the defaults are ~336 initial + ~378/turn; these bounds give
        # ~486 + ~480/turn, so late turns can never hit OutOfPages and
        # silently drop the slowest histories out of the reported p50.
        agent_turns = int(os.environ.get("OPSAGENT_BENCH_TURNS", "4"))
        agent_gen = max(16, steps // 8)
        est_history = (
            150 + 7 * (16 + prompt_len // 4)
            + agent_turns * (agent_gen + 7 * 48 + 80)
        )
        # Fold in the decode lookahead the fail-fast guard below adds to
        # `need` (decode_block x (pipeline_depth + 1); 4x bounds any
        # pipeline_depth <= 3), so the auto-sized geometry can never fail
        # its own guard at a swept decode_block/page_size.
        default_maxpages = (
            -(-(est_history + decode_block * 4) // page_size) + 4
        )
    else:
        default_maxpages = 12
    max_pages = int(
        os.environ.get("OPSAGENT_BENCH_MAXPAGES", str(default_maxpages))
    )
    num_pages = max(512 * 64 // page_size, batch * max_pages)
    if mode == "sessions-offload":
        # The offload A/B only measures anything under HBM PRESSURE: size
        # the page pool so the sessions' grown histories cannot all stay
        # trie-resident — the off phase re-prefills evicted content, the
        # on phase restores it from the host pool.
        num_pages = max(int(batch * max_pages * 0.6), max_pages * 2)
    cfg = EngineConfig(
        model=model,
        dtype=dtype,
        max_batch_size=batch,
        num_pages=num_pages,
        page_size=page_size,
        max_pages_per_seq=max_pages,
        prefill_buckets=(prompt_len,),
        quantize=quantize,
        kv_quantize=kv_quantize,
        speculative_k=spec_k,
        decode_block=decode_block,
        mixed_batching=mixed_on,
        async_depth=async_depth,
        offload=(mode in ("sessions-offload", "fleet-affinity",
                          "fleet-chaos", "fleet-global-kv",
                          "fleet-journey", "audit-fanout")),
    )
    # Fail fast on undersized sweep points: OutOfPages mid-window would
    # force-finish sequences ('length') and quietly deflate the metric.
    # Lookahead slack from the EFFECTIVE config, so a changed
    # pipeline_depth default cannot silently undersize the guard.
    lookahead = cfg.decode_block * (cfg.pipeline_depth + 1)
    # The linear-decode guard: prompt + steps tokens per sequence. Agent
    # mode's per-seq need is the history estimate already folded into
    # default_maxpages above (and its per-turn generation is short).
    need = (
        prompt_len + steps + lookahead if mode != "agent"
        else est_history + lookahead
    )
    if cfg.page_size * cfg.max_pages_per_seq < need:
        raise SystemExit(
            f"bench: page geometry {cfg.page_size}x{cfg.max_pages_per_seq} "
            f"holds {cfg.page_size * cfg.max_pages_per_seq} tokens < "
            f"{need} needed (prompt {prompt_len} + steps {steps} + "
            f"lookahead {lookahead}); raise OPSAGENT_BENCH_MAXPAGES or "
            f"lower OPSAGENT_BENCH_STEPS"
        )
    if mode == "cold-start":
        # Builds its own engines (fresh then restored) — intercept before
        # the shared construction below.
        run_cold_start(cfg, model, batch, steps, prompt_len, platform,
                       n_chips, quantize)
        return
    t0 = time.perf_counter()
    eng = Engine(cfg)
    init_s = time.perf_counter() - t0
    log(f"bench: engine init (weights+shard) {init_s:.1f}s")
    # Only compile the programs this bench dispatches ("bench"/"sessions"
    # warmup levels): full warmup's program cross-product is what timed
    # out the round-2 driver gate. The agent mode drives the same
    # full-stack path as sessions (scheduler admission -> chunked prefill
    # -> pipelined decode), so it shares that warmup level.
    t0 = time.perf_counter()
    if mode in ("sessions", "agent", "sessions-mixed", "sessions-offload",
                "sessions-async", "sessions-ffwd", "fleet-affinity",
                "fleet-chaos", "fleet-global-kv", "fleet-journey",
                "audit-fanout", "obs-history"):
        level = "sessions"
    elif spec_k > 0:
        level = "bench-spec"
    else:
        level = "bench"
    warmup_s = eng.warmup(level)
    log(f"bench: warmup {warmup_s:.1f}s "
        f"(persistent cache makes repeat runs fast)")

    if mode == "sessions":
        run_sessions(eng, model, batch, steps, prompt_len, platform,
                     n_chips, quantize, init_s, warmup_s)
        return
    if mode == "sessions-mixed":
        run_sessions_mixed(eng, model, batch, steps, prompt_len, platform,
                           n_chips, quantize, init_s, warmup_s)
        return
    if mode == "sessions-async":
        run_sessions_async(eng, model, batch, steps, prompt_len, platform,
                           n_chips, quantize, init_s, warmup_s)
        return
    if mode == "sessions-ffwd":
        run_sessions_ffwd(eng, model, batch, steps, prompt_len, platform,
                          n_chips, quantize, init_s, warmup_s)
        return
    if mode == "sessions-offload":
        run_sessions_offload(eng, model, batch, steps, prompt_len, platform,
                             n_chips, quantize, init_s, warmup_s)
        return
    if mode == "fleet-affinity":
        run_fleet_affinity(eng, cfg, model, batch, steps, prompt_len,
                           platform, n_chips, quantize, init_s, warmup_s)
        return
    if mode == "fleet-chaos":
        run_fleet_chaos(eng, cfg, model, batch, steps, prompt_len,
                        platform, n_chips, quantize, init_s, warmup_s)
        return
    if mode == "fleet-global-kv":
        run_fleet_global_kv(eng, cfg, model, batch, steps, prompt_len,
                            platform, n_chips, quantize, init_s, warmup_s)
        return
    if mode == "fleet-journey":
        run_fleet_journey(eng, cfg, model, batch, steps, prompt_len,
                          platform, n_chips, quantize, init_s, warmup_s)
        return
    if mode == "audit-fanout":
        run_audit_fanout(eng, cfg, model, batch, steps, prompt_len,
                         platform, n_chips, quantize, init_s, warmup_s)
        return
    if mode == "obs-history":
        run_obs_history(eng, model, batch, steps, prompt_len, platform,
                        n_chips, quantize, init_s, warmup_s)
        return
    if mode == "agent":
        # turns/gen_tokens are THE values the page-budget guard above was
        # sized from — passed through, never recomputed, so the guard and
        # the workload cannot desynchronize.
        run_agent_turns(eng, model, batch, prompt_len, platform,
                        n_chips, quantize, init_s, warmup_s,
                        turns=agent_turns, gen_tokens=agent_gen)
        return

    rng = np.random.default_rng(0)
    vocab = eng.model_cfg.vocab_size
    sampling = SamplingParams(temperature=0.0, max_tokens=10**9)

    # Admit a full batch. With the warmed engine the FIRST admission is
    # compile-free — its TTFT is the honest cold-request number.
    t0 = time.perf_counter()
    ids = []
    ttfts = []
    for i in range(batch):
        prompt = rng.integers(1, vocab, size=prompt_len).tolist()
        t1 = time.perf_counter()
        sid = eng.add_request(prompt, sampling)
        ttfts.append(time.perf_counter() - t1)
        ids.append(sid)
    log(f"bench: admitted {batch} reqs in {time.perf_counter() - t0:.1f}s; "
        f"first-request TTFT {ttfts[0]*1e3:.0f} ms (warmed, no compile)")

    # Warm up decode (compilation + cache donation settle), then drain the
    # pipeline so warmup tokens don't leak into the timed window.
    eng.step_block(ids)
    eng.drain()

    # Steady-state decode: `steps` tokens per sequence, block dispatches.
    # The final drain pulls the last in-flight blocks so `produced` counts
    # exactly the tokens whose compute falls inside dt.
    # OPSAGENT_PROFILE_DIR=<dir> captures a jax.profiler device trace of
    # exactly the timed window (open in TensorBoard to see where the
    # ms/step go); a no-op otherwise.
    from opsagent_tpu.utils.profiling import trace

    block = eng.cfg.decode_block
    produced = 0
    with trace():
        # Clock inside the trace context: start_trace/stop_trace overhead
        # (trace serialization takes seconds) must not deflate the number.
        t0 = time.perf_counter()
        for _ in range(max(1, steps // block)):
            out = eng.step_block(ids)
            produced += sum(len(v) for v in out.values())
        produced += sum(len(v) for v in eng.drain().values())
        dt = time.perf_counter() - t0

    tok_s = produced / dt
    tok_s_chip = tok_s / n_chips
    # Post-warmup TTFT (compile-free) from the later admissions.
    p50_ttft_ms = float(np.median(ttfts[1:]) * 1e3) if len(ttfts) > 1 else 0.0

    log(f"bench: {produced} tokens in {dt:.2f}s -> {tok_s:.0f} tok/s total, "
        f"{tok_s_chip:.0f} tok/s/chip; p50 TTFT {p50_ttft_ms:.0f} ms")

    log_perf_table()

    qtag = f",{quantize}" if quantize else ""
    if kv_quantize:
        qtag += f",kv-{kv_quantize}"
    if spec_k:
        qtag += f",spec{spec_k}"
    print(json.dumps({
        "metric": f"paged_decode_throughput[{model}{qtag},B={batch},{platform}]",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": vs_baseline(tok_s_chip, model, platform),
        "extra": {
            "total_tok_s": round(tok_s, 1),
            "p50_ttft_ms": round(p50_ttft_ms, 1),
            "first_ttft_ms": round(ttfts[0] * 1e3, 1),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "decode_block": eng.cfg.decode_block,
            "page_size": eng.cfg.page_size,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    exit_if_slo_breach(slo_verdicts())


def run_cold_start(cfg, model, batch, steps, prompt_len, platform,
                   n_chips, quantize) -> None:
    """Cold-start A/B (ROADMAP item 4): fresh-init request-ready time vs
    snapshot-restore request-ready time in one child, greedy outputs
    verified byte-identical across the two engines.

    Phase 1 builds + warms an engine against an EMPTY persistent compile
    cache (the honest first-boot cost), drives a short greedy decode,
    then snapshots it. ``jax.clear_caches()`` drops the in-process
    executable caches before phase 2, so the restore cannot coast on
    them: phase 2 restores into a SECOND empty cache dir whose only
    content is what the snapshot packaged — exactly what a scale-out
    replica on a new host experiences."""
    import gc
    import shutil
    import tempfile

    from opsagent_tpu import obs
    from opsagent_tpu.serving.engine import Engine
    from opsagent_tpu.serving.sampler import SamplingParams

    work = tempfile.mkdtemp(prefix="opsagent-coldstart-")
    cache_a = os.path.join(work, "cache-fresh")
    cache_b = os.path.join(work, "cache-restore")
    snapdir = os.path.join(work, "snapshot")
    os.makedirs(cache_a)
    os.makedirs(cache_b)
    # Every warmed program must land in the persistent cache for the
    # snapshot to package it — drop the min-compile-time floor.
    os.environ["OPSAGENT_COMPILE_CACHE_MIN_S"] = "0"
    os.environ["OPSAGENT_COMPILE_CACHE_DIR"] = cache_a

    t0 = time.perf_counter()
    eng = Engine(cfg)
    eng.warmup("bench")
    fresh_s = time.perf_counter() - t0
    log(f"bench: fresh init -> request-ready {fresh_s:.1f}s")

    rng = np.random.default_rng(0)
    vocab = eng.model_cfg.vocab_size
    prompts = [rng.integers(1, vocab, size=prompt_len).tolist()
               for _ in range(batch)]
    sampling = SamplingParams(temperature=0.0, max_tokens=steps)
    fresh_out = eng.generate(prompts, sampling)

    man = eng.snapshot(snapdir)
    del eng
    gc.collect()
    jax.clear_caches()

    os.environ["OPSAGENT_COMPILE_CACHE_DIR"] = cache_b
    t0 = time.perf_counter()
    eng2 = Engine.from_snapshot(snapdir, warmup="bench")
    restore_s = time.perf_counter() - t0
    preseeded = eng2.init_stats.get("compile_cache_preseeded", 0)
    log(f"bench: snapshot restore -> request-ready {restore_s:.1f}s "
        f"({preseeded} compile-cache entries pre-seeded)")

    gauge0 = obs.POST_WARMUP_COMPILES.value()
    restore_out = eng2.generate(prompts, sampling)
    post_compiles = obs.POST_WARMUP_COMPILES.value() - gauge0
    identical = fresh_out == restore_out
    speedup = fresh_s / restore_s if restore_s > 0 else 0.0
    log(f"bench: cold-start speedup {speedup:.1f}x, outputs identical: "
        f"{identical}, post-warmup compiles on restore: {post_compiles}")

    qtag = f",{quantize}" if quantize else ""
    if cfg.kv_quantize:
        qtag += f",kv-{cfg.kv_quantize}"
    print(json.dumps({
        "metric": f"cold_start_request_ready[{model}{qtag},{platform}]",
        "value": round(restore_s, 2),
        "unit": "request_ready_s",
        "extra": {
            "fresh_request_ready_s": round(fresh_s, 2),
            "restore_request_ready_s": round(restore_s, 2),
            "speedup_ratio": round(speedup, 2),
            "outputs_identical": identical,
            "post_warmup_compiles": post_compiles,
            "restore_weights_load_s": eng2.init_stats.get("weights_load_s"),
            "restore_warmup_s": eng2.init_stats.get("warmup_s"),
            "compile_cache_preseeded": preseeded,
            "snapshot_leaves": len(man["leaves"]),
            "snapshot_compile_cache_entries":
                man["compile_cache"]["entries"],
            "snapshot_fingerprint": man["fingerprint"],
            "chips": n_chips,
            "platform": platform,
        },
    }), flush=True)
    shutil.rmtree(work, ignore_errors=True)


def run_ragged_sweep(platform, n_chips, model, batch, steps,
                     prompt_len) -> None:
    """Ragged-backend sweep (ROADMAP item 1): time the MIXED hot path —
    sync ``step_mixed`` ticks, the program serving actually runs — across
    attention backend x KV page dtype x weight quant x weight-stream
    cells on one model shape, one self-describing tok/s/chip row per
    cell. The weight-stream axis rides the xla attention backend only
    (the double-buffered quant-matmul prefetch is orthogonal to the
    attention kernel under test) and needs quantized weights, so it adds
    one pallas-dma cell per quantized weight mode — plus, off-chip, the
    int8 oracle cell that anchors its byte-identity check.

    Each cell builds its own engine (the backend env var and quant modes
    are engine-construction inputs), warms exactly the mixed program
    family ("bench-mixed" level), admits ``batch`` identical greedy
    prompts through chunked mixed admission, then times ``steps``
    decode-only mixed ticks. Within a (weight, KV) group the xla cell is
    the oracle: every other backend's full greedy token streams must be
    byte-identical, and that verdict rides each row's extra. Off-chip
    the Pallas cells run in interpret mode (no Mosaic on CPU), which is
    exactly what the CI smoke exercises; on chip the rows answer the
    r04 open question — whether streaming int8 pages through the ragged
    DMA kernel tracks the attribution model's halved bytes floor.

    Rows are flushed the moment they exist (driver-kill contract), and
    the LAST line is a copy of the best cell with the per-cell values
    folded into extra — the orchestrator's promote-if-faster input."""
    import gc

    from opsagent_tpu import obs
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    on_tpu = platform == "tpu"
    budget = float(os.environ.get(
        "OPSAGENT_BENCH_SWEEP_BUDGET", "600" if on_tpu else "240"
    ))
    t_start = time.perf_counter()
    if not on_tpu:
        # No Mosaic off-chip: run the Pallas cells in interpret mode so
        # the full chain (engine impl gate -> auto dispatcher -> ragged
        # DMA kernel) still executes end to end on CPU.
        os.environ["OPSAGENT_PALLAS_INTERPRET"] = "1"
    backends = ("xla", "pallas", "pallas-dma")
    kv_modes = ("", "int8")
    # Off-chip cells keep fp32 weights: the question CPU answers is
    # dispatch-equivalence, not throughput, and weight quant doubles the
    # cell count without touching the attention path under test.
    weight_modes = ("int8", "int4") if on_tpu else ("",)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    steps = min(steps, 256)
    chunk = 64 if on_tpu else 16
    buckets = tuple(sorted({4, chunk}))
    page_size = int(os.environ.get("OPSAGENT_BENCH_PAGE", "64"))
    # +1 page of slack over prompt+generated: the settle tick plus the
    # decode rows' one-token booking must never hit OutOfPages (which
    # would truncate rows and quietly deflate the number).
    max_pages = -(-(prompt_len + steps + 2) // page_size) + 1
    num_pages = max(batch * max_pages, 64)
    sampling = SamplingParams(temperature=0.0, max_tokens=10**9)

    cells = [
        (wq, kv, backend, "xla", False)
        for wq in weight_modes for kv in kv_modes for backend in backends
    ]
    # Weight-stream axis: one pallas-dma prefetch cell per quantized
    # weight mode (xla attention, plain KV — the weight path is the axis
    # under test). The prefetch kernel is single-shard for now, so these
    # cells pin tp=1 and bring their OWN tp=1 xla oracle: greedy byte
    # identity is only meaningful against the same reduction layout, and
    # the baseline grid above runs on every chip.
    ws_weights = ("int8", "int4") if on_tpu else ("int8",)
    for wq in ws_weights:
        cells.append((wq, "", "xla", "xla", True))
        cells.append((wq, "", "xla", "pallas-dma", True))
    rows: list[dict] = []
    oracle: dict[tuple, list[list[int]]] = {}
    groups_ok: dict[tuple, bool] = {}
    for wq, kv, backend, ws, single in cells:
        label = f"{backend}/{wq or 'bf16'}/kv-{kv or 'bf16'}"
        if single:
            label += f"/ws-{ws}"
        elapsed = time.perf_counter() - t_start
        if rows and elapsed > budget:
            log(f"bench[ragged-sweep]: {elapsed:.0f}s > {budget:.0f}s "
                f"budget; dropping {label} and later cells")
            break
        os.environ["OPSAGENT_PAGED_BACKEND"] = backend
        cfg = EngineConfig(
            model=model,
            dtype=dtype,
            tp=1 if single else 0,
            max_batch_size=batch,
            num_pages=num_pages,
            page_size=page_size,
            max_pages_per_seq=max_pages,
            prefill_buckets=(prompt_len,),
            quantize=wq,
            kv_quantize=kv,
            weight_stream=ws,
            mixed_batching=True,
            async_depth=1,
            mixed_buckets=buckets,
        )
        eng = Engine(cfg)
        warmup_s = eng.warmup("bench-mixed")
        compiles0 = obs.POST_WARMUP_COMPILES.value()
        rng = np.random.default_rng(0)
        vocab = eng.model_cfg.vocab_size
        ids = [
            eng.begin_request(
                rng.integers(1, vocab, size=prompt_len).tolist(), sampling
            )
            for _ in range(batch)
        ]
        while eng._prefilling:
            chunks = {}
            for sid in list(eng._prefilling):
                done, total = eng.prefill_progress(sid)
                chunks[sid] = min(chunk, total - done)
            eng.step_mixed([], chunks)
        # One settle tick outside the window (donation/layout settle),
        # then `steps` timed decode-only mixed ticks — every tick is ONE
        # dispatch advancing all `batch` lanes through the cell's kernel.
        eng.step_mixed(ids, {})
        produced = 0
        t0 = time.perf_counter()
        for _ in range(steps):
            out, _ = eng.step_mixed(ids, {})
            produced += sum(len(v) for v in out.values())
        dt = time.perf_counter() - t0
        post_compiles = int(obs.POST_WARMUP_COMPILES.value() - compiles0)
        tok_s = produced / dt
        cell_chips = 1 if single else n_chips
        tok_s_chip = tok_s / cell_chips
        outputs = [list(eng.sequences[s].tokens) for s in ids]
        # tp=1 weight-stream cells form their own oracle group: greedy
        # byte identity only holds within one reduction layout.
        group = (wq, kv, single)
        if backend == "xla" and ws == "xla":
            oracle[group] = outputs
            identical = True
        else:
            identical = outputs == oracle.get(group)
        groups_ok[group] = groups_ok.get(group, True) and identical
        info = eng.impl_info()
        # ws lands in the metric only for the single-shard weight-stream
        # cells (oracle + prefetch), so every pre-existing cell keeps its
        # baseline-comparable metric name.
        ws_tag = f",ws-{ws}" if single else ""
        row = {
            "metric": (
                f"mixed_ragged_throughput[{model},{wq or 'bf16'},"
                f"kv-{kv or 'bf16'},{backend}{ws_tag},B={batch},"
                f"{platform}]"
            ),
            "value": round(tok_s_chip, 1),
            "unit": "tok/s/chip",
            "vs_baseline": None,
            "extra": {
                "total_tok_s": round(tok_s, 1),
                "requested_backend": backend,
                "requested_weight_stream": ws,
                **info,
                "outputs_identical": identical,
                "post_warmup_compiles": post_compiles,
                "warmup_s": round(warmup_s, 1),
                "steps": steps,
                "interpret": not on_tpu,
                "paged_backend": info["attn_impl"],
                "chips": cell_chips,
                "platform": platform,
            },
        }
        print(json.dumps(row), flush=True)
        rows.append(row)
        log(f"bench[ragged-sweep/{label}]: resolved={info['attn_impl']} "
            f"ws={info['weight_stream']} {tok_s_chip:.0f} tok/s/chip, "
            f"identical={identical}, post-warmup compiles {post_compiles}")
        for sid in ids:
            eng.finish(sid)
        del eng
        gc.collect()
    if not rows:
        raise SystemExit("bench[ragged-sweep]: no cell produced a number")
    # Best-cell summary LAST: the orchestrator's last-JSON-line parse
    # (and promote-if-faster fold) reads this row.
    best = max(rows, key=lambda r: r["value"])
    summary = dict(best, extra=dict(best["extra"]))
    summary["extra"].update({
        "best_cell": best["metric"],
        "cells": len(rows),
        "outputs_identical": all(groups_ok.values()),
        "cell_tok_s_chip": {r["metric"]: r["value"] for r in rows},
    })
    print(json.dumps(summary), flush=True)


def run_sessions(eng, model, batch, steps, prompt_len, platform, n_chips,
                 quantize, init_s, warmup_s) -> None:
    """BASELINE config 5: ``batch`` concurrent sessions through the FULL
    stack — OpenAI chat translation (templates, usage accounting) ->
    scheduler admission -> chunked prefill -> pipelined decode — each
    generating ``steps // 8`` tokens per round for several rounds in the
    agent-loop shape (re-send the grown history, so the prefix cache
    carries earlier rounds' KV)."""
    import threading

    from opsagent_tpu.serving.api import ServingStack

    stack = ServingStack(eng)
    gen_tokens = max(16, steps // 8)
    rounds = 3
    results: list[dict] = []
    lock = threading.Lock()

    def session(sid: int) -> None:
        # Chat history grows across rounds like a real agent loop — each
        # round re-sends the whole conversation, so the prefix cache
        # carries the earlier rounds' KV. Per-session generator: numpy
        # Generators are not thread-safe, and distinct seeds keep prompts
        # distinct so cross-session prefix hits can't inflate the number.
        rng = np.random.default_rng(1000 + sid)
        words = [f"w{rng.integers(0, 9999)}" for _ in range(prompt_len // 2)]
        messages = [
            {"role": "system", "content": "bench session"},
            {"role": "user", "content": " ".join(words)},
        ]
        for r in range(rounds):
            t0 = time.perf_counter()
            try:
                resp = stack.chat_completion({
                    "messages": messages,
                    "max_tokens": gen_tokens,
                    "temperature": 0.0,
                })
            except Exception as e:  # noqa: BLE001
                with lock:
                    results.append({"err": str(e)})
                return
            dt = time.perf_counter() - t0
            msg = resp["choices"][0]["message"]
            messages.append(
                {"role": "assistant", "content": msg.get("content") or ""}
            )
            messages.append({"role": "user", "content": f"continue {r}"})
            with lock:
                results.append({
                    "tokens": resp["usage"]["completion_tokens"], "wall": dt,
                })

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=session, args=(i,)) for i in range(batch)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    errs = [r for r in results if "err" in r]
    ok = [r for r in results if "tokens" in r]
    produced = sum(r["tokens"] for r in ok)
    tok_s_chip = produced / wall / n_chips
    stats = get_perf_stats().get_stats()
    ttft = stats.get("engine.ttft", {})
    log(f"bench[sessions]: {batch} sessions x {rounds} rounds, "
        f"{produced} tokens in {wall:.2f}s -> {tok_s_chip:.0f} tok/s/chip; "
        f"p50 TTFT {ttft.get('p50', 0):.0f} ms; errors={len(errs)}")
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"concurrent_sessions[{model}{qtag},N={batch},{platform}]",
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "vs_baseline": vs_baseline(tok_s_chip, model, platform),
        "extra": {
            "sessions": batch,
            "rounds": rounds,
            "p50_ttft_ms": round(float(ttft.get("p50", 0)), 1),
            "p99_ttft_ms": round(float(ttft.get("p99", 0)), 1),
            "errors": len(errs),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    stack.close()
    exit_if_slo_breach(slo_verdicts())


def _drive_sessions_streaming(stack, batch, rounds, gen_tokens, prompt_len,
                              seed_base: int, park: bool = False,
                              extra_body: dict | None = None) -> dict:
    """Run ``batch`` concurrent multi-round chat sessions with STREAMING
    completions, measuring client-observed TTFT per round (first yielded
    chunk, error-checked). Returns {produced, wall, ttfts, errors, texts}
    — self-contained client-side measurement, so two phases in one
    process cannot contaminate each other through global perf-stat
    snapshots; ``texts`` maps (session, round) to the full completion
    text (the sessions-async A/B's identical-output check).
    ``park=True`` parks each session's KV to the host tier between rounds
    (ServingStack.park — the tool-execution window of a real agent
    turn)."""
    import threading

    results: list[dict] = []
    errors: list[str] = []
    texts: dict[tuple[int, int], str] = {}
    lock = threading.Lock()

    def session(sid: int) -> None:
        rng = np.random.default_rng(seed_base + sid)
        words = [f"w{rng.integers(0, 9999)}" for _ in range(prompt_len // 2)]
        messages = [
            {"role": "system", "content": "bench session"},
            {"role": "user", "content": " ".join(words)},
        ]
        for r in range(rounds):
            if park and r:
                # The inter-round gap is where a real agent blocks on its
                # tool subprocess: hand the HBM back for other sessions'
                # admissions; this round's admission restores the chain.
                stack.park(messages)
            t0 = time.perf_counter()
            try:
                gen = stack.chat_completion_stream({
                    "messages": messages,
                    "max_tokens": gen_tokens,
                    "temperature": 0.0,
                    "stream": True,
                    **(extra_body or {}),
                })
                first = next(gen)
                if "error" in first:
                    raise RuntimeError(first["error"]["message"])
                ttft = time.perf_counter() - t0
                parts: list[str] = []
                n_tok = 0
                for ch in gen:
                    if "error" in ch:
                        raise RuntimeError(ch["error"]["message"])
                    delta = ch["choices"][0]["delta"]
                    if delta.get("content"):
                        parts.append(delta["content"])
                        n_tok += 1
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"round {r + 1}: {e}")
                return
            messages.append(
                {"role": "assistant", "content": "".join(parts)}
            )
            messages.append({"role": "user", "content": f"continue {r}"})
            with lock:
                results.append({"ttft": ttft, "tokens": n_tok})
                texts[(sid, r)] = "".join(parts)

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=session, args=(i,)) for i in range(batch)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {
        "produced": sum(r["tokens"] for r in results),
        "wall": time.perf_counter() - t0,
        "ttfts": [r["ttft"] for r in results],
        "errors": errors,
        "texts": texts,
    }


def run_sessions_mixed(eng, model, batch, steps, prompt_len, platform,
                       n_chips, quantize, init_s, warmup_s) -> None:
    """The mixed-batching A/B stage: the BASELINE config-5 concurrent-
    sessions workload run TWICE against the same engine — once with the
    unified mixed prefill+decode tick (one weight stream per tick), once
    with the split prefill-then-decode tick — so the delta is a
    first-class BENCH artifact, not a cross-round comparison. Distinct
    prompt seeds per phase keep phase 2 from riding phase 1's prefix
    cache. Reports the mixed numbers as the headline value with the split
    phase in extra."""
    from opsagent_tpu.serving.api import ServingStack

    gen_tokens = max(16, steps // 8)
    rounds = 3
    phases: dict[str, dict] = {}
    for tag, flag, seed in (("mixed", True, 5000), ("split", False, 9000)):
        eng.cfg.mixed_batching = flag
        stack = ServingStack(eng)
        try:
            phases[tag] = _drive_sessions_streaming(
                stack, batch, rounds, gen_tokens, prompt_len, seed
            )
        finally:
            stack.close()
        r = phases[tag]
        p50 = float(np.median(r["ttfts"]) * 1e3) if r["ttfts"] else 0.0
        r["p50_ttft_ms"] = p50
        r["p99_ttft_ms"] = (
            float(np.percentile(r["ttfts"], 99) * 1e3) if r["ttfts"] else 0.0
        )
        r["tok_s_chip"] = r["produced"] / max(1e-9, r["wall"]) / n_chips
        log(f"bench[sessions-mixed/{tag}]: {batch} sessions x {rounds} "
            f"rounds, {r['produced']} tokens in {r['wall']:.2f}s -> "
            f"{r['tok_s_chip']:.0f} tok/s/chip; p50 TTFT {p50:.0f} ms; "
            f"errors={len(r['errors'])}")
    mixed, split = phases["mixed"], phases["split"]
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"sessions_mixed[{model}{qtag},N={batch},{platform}]",
        "value": round(mixed["tok_s_chip"], 1),
        "unit": "tok/s/chip",
        "vs_baseline": vs_baseline(mixed["tok_s_chip"], model, platform),
        "extra": {
            "sessions": batch,
            "rounds": rounds,
            "p50_ttft_ms": round(mixed["p50_ttft_ms"], 1),
            "p99_ttft_ms": round(mixed["p99_ttft_ms"], 1),
            "split_tok_s_chip": round(split["tok_s_chip"], 1),
            "split_p50_ttft_ms": round(split["p50_ttft_ms"], 1),
            "split_p99_ttft_ms": round(split["p99_ttft_ms"], 1),
            "ttft_delta_ms": round(
                split["p50_ttft_ms"] - mixed["p50_ttft_ms"], 1
            ),
            "tok_s_chip_delta": round(
                mixed["tok_s_chip"] - split["tok_s_chip"], 1
            ),
            "errors": len(mixed["errors"]) + len(split["errors"]),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    exit_if_slo_breach(slo_verdicts())


def run_sessions_async(eng, model, batch, steps, prompt_len, platform,
                       n_chips, quantize, init_s, warmup_s) -> None:
    """The async-tick A/B stage: the concurrent-sessions workload run
    TWICE against the same engine — first with the one-step-lookahead
    async mixed pipeline (async_depth=2: tick t+1 dispatches before tick
    t's tokens are pulled, host post-processing overlaps device compute),
    then with synchronous ticks (depth=1, today's behavior). SAME prompt
    seeds both phases: byte-identical output text is part of the async
    contract (the lookahead changes WHEN host work happens, never WHAT
    gets generated), and running the sync phase second hands IT the
    prefix-cache advantage — a handicap against the async phase's tok/s,
    so an async win here is conservative. Decision numbers per phase:
    tok/s/chip, p50 TTFT, host-gap p50 (the time the device can idle
    between mixed dispatches — the thing the overlap shrinks), and the
    overlapped-commit count proving host work actually ran while a newer
    dispatch was in flight."""
    from opsagent_tpu.serving.api import ServingStack

    gen_tokens = max(16, steps // 8)
    rounds = 3
    phases: dict[str, dict] = {}
    for tag, depth in (("async", 2), ("sync", 1)):
        eng.cfg.async_depth = depth
        get_perf_stats().reset()
        snap0 = metrics_snapshot()
        stack = ServingStack(eng)
        try:
            phases[tag] = _drive_sessions_streaming(
                stack, batch, rounds, gen_tokens, prompt_len, 4000
            )
        finally:
            stack.close()
        r = phases[tag]
        r["p50_ttft_ms"] = (
            float(np.median(r["ttfts"]) * 1e3) if r["ttfts"] else 0.0
        )
        r["tok_s_chip"] = r["produced"] / max(1e-9, r["wall"]) / n_chips
        hg = get_perf_stats().get_stats().get("engine.step_host_gap", {})
        r["host_gap_p50_ms"] = float(hg.get("p50", 0.0))
        snap1 = metrics_snapshot()
        r["overlapped_commits"] = int(
            snap1.get("opsagent_async_overlapped_commits_total", 0)
            - snap0.get("opsagent_async_overlapped_commits_total", 0)
        )
        r["async_commits"] = int(
            snap1.get("opsagent_async_commits_total", 0)
            - snap0.get("opsagent_async_commits_total", 0)
        )
        log(f"bench[sessions-async/{tag}]: {batch} sessions x {rounds} "
            f"rounds, {r['produced']} tokens in {r['wall']:.2f}s -> "
            f"{r['tok_s_chip']:.0f} tok/s/chip; p50 TTFT "
            f"{r['p50_ttft_ms']:.0f} ms; host-gap p50 "
            f"{r['host_gap_p50_ms']:.2f} ms; overlapped commits "
            f"{r['overlapped_commits']}; errors={len(r['errors'])}")
    a, s = phases["async"], phases["sync"]
    identical = a["texts"] == s["texts"] and not a["errors"] and not s["errors"]
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"sessions_async[{model}{qtag},N={batch},{platform}]",
        "value": round(a["tok_s_chip"], 1),
        "unit": "tok/s/chip",
        "vs_baseline": vs_baseline(a["tok_s_chip"], model, platform),
        "extra": {
            "sessions": batch,
            "rounds": rounds,
            "p50_ttft_ms": round(a["p50_ttft_ms"], 1),
            "host_gap_p50_ms": round(a["host_gap_p50_ms"], 3),
            "overlapped_commits": a["overlapped_commits"],
            "async_commits": a["async_commits"],
            "sync_tok_s_chip": round(s["tok_s_chip"], 1),
            "sync_p50_ttft_ms": round(s["p50_ttft_ms"], 1),
            "sync_host_gap_p50_ms": round(s["host_gap_p50_ms"], 3),
            "host_gap_delta_ms": round(
                s["host_gap_p50_ms"] - a["host_gap_p50_ms"], 3
            ),
            "tok_s_chip_delta": round(
                a["tok_s_chip"] - s["tok_s_chip"], 1
            ),
            "outputs_identical": identical,
            "errors": len(a["errors"]) + len(s["errors"]),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    exit_if_slo_breach(slo_verdicts())


def run_sessions_ffwd(eng, model, batch, steps, prompt_len, platform,
                      n_chips, quantize, init_s, warmup_s) -> None:
    """The grammar fast-forward A/B stage: the concurrent-sessions
    workload with EVERY completion constrained to the ToolPrompt JSON
    schema (the warmup-pre-specialized one, so both phases run
    compile-free), run TWICE against the same engine — fast-forward ON
    (forced-token runs splice into the paged KV as multi-token appends,
    no forward pass per forced token), then OFF (every token pays a
    dispatch). SAME prompt seeds both phases: byte-identical output text
    is the correctness half of the contract (a forced token is what the
    masked sampler would have picked anyway), and the OFF phase running
    second hands it the prefix-cache advantage — a handicap against the
    ON phase's tok/s. Decision numbers per phase: tok/s/chip, the
    forced-token fraction (what share of produced tokens needed no
    forward pass), and skipped dispatch counts."""
    from opsagent_tpu.serving.api import ServingStack
    from opsagent_tpu.serving.constrained import TOOLPROMPT_SCHEMA

    gen_tokens = max(16, steps // 8)
    rounds = 3
    rf = {"response_format": {"type": "json_schema", "json_schema": {
        "name": "toolprompt", "schema": TOOLPROMPT_SCHEMA,
    }}}
    phases: dict[str, dict] = {}
    for tag, on in (("on", True), ("off", False)):
        eng.cfg.grammar_ffwd = on
        get_perf_stats().reset()
        snap0 = metrics_snapshot()
        stack = ServingStack(eng)
        try:
            phases[tag] = _drive_sessions_streaming(
                stack, batch, rounds, gen_tokens, prompt_len, 6000,
                extra_body=rf,
            )
        finally:
            stack.close()
        r = phases[tag]
        r["p50_ttft_ms"] = (
            float(np.median(r["ttfts"]) * 1e3) if r["ttfts"] else 0.0
        )
        r["tok_s_chip"] = r["produced"] / max(1e-9, r["wall"]) / n_chips
        snap1 = metrics_snapshot()
        for short, metric in (
            ("ffwd_tokens", "opsagent_ffwd_tokens_total"),
            ("ffwd_runs", "opsagent_ffwd_runs_total"),
            ("skipped_dispatches",
             "opsagent_ffwd_skipped_dispatches_total"),
        ):
            r[short] = int(snap1.get(metric, 0) - snap0.get(metric, 0))
        r["forced_fraction"] = round(
            r["ffwd_tokens"] / max(1, r["produced"]), 3
        )
        log(f"bench[sessions-ffwd/{tag}]: {batch} sessions x {rounds} "
            f"rounds, {r['produced']} tokens in {r['wall']:.2f}s -> "
            f"{r['tok_s_chip']:.0f} tok/s/chip; forced fraction "
            f"{r['forced_fraction']:.1%} ({r['ffwd_tokens']} tokens in "
            f"{r['ffwd_runs']} runs, {r['skipped_dispatches']} dispatches "
            f"skipped); errors={len(r['errors'])}")
    a, b = phases["on"], phases["off"]
    identical = a["texts"] == b["texts"] and not a["errors"] and not b["errors"]
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"sessions_ffwd[{model}{qtag},N={batch},{platform}]",
        "value": round(a["tok_s_chip"], 1),
        "unit": "tok/s/chip",
        "vs_baseline": vs_baseline(a["tok_s_chip"], model, platform),
        "extra": {
            "sessions": batch,
            "rounds": rounds,
            "p50_ttft_ms": round(a["p50_ttft_ms"], 1),
            "forced_fraction": a["forced_fraction"],
            "ffwd_tokens": a["ffwd_tokens"],
            "ffwd_runs": a["ffwd_runs"],
            "skipped_dispatches": a["skipped_dispatches"],
            "off_tok_s_chip": round(b["tok_s_chip"], 1),
            "off_p50_ttft_ms": round(b["p50_ttft_ms"], 1),
            "off_skipped_dispatches": b["skipped_dispatches"],
            "tok_s_chip_delta": round(
                a["tok_s_chip"] - b["tok_s_chip"], 1
            ),
            "outputs_identical": identical,
            "errors": len(a["errors"]) + len(b["errors"]),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    exit_if_slo_breach(slo_verdicts())


def run_sessions_offload(eng, model, batch, steps, prompt_len, platform,
                         n_chips, quantize, init_s, warmup_s) -> None:
    """The hierarchical-KV A/B stage: the concurrent-sessions workload
    under HBM page pressure (num_pages was sized below the sessions'
    aggregate history) run TWICE against the same engine — offload tier
    OFF (evictions drop content, every comeback re-prefills), then ON
    (evictions spill to the host pool, sessions park between rounds like
    a tool-blocked agent turn, comebacks restore with a page copy). Both
    phases land in ONE JSON line: admission-wait p50 and
    re-prefill-avoided token counts are the decision numbers the offload
    tier exists for."""
    from opsagent_tpu.serving.api import ServingStack

    gen_tokens = max(16, steps // 8)
    rounds = 3
    mgr = eng.offload
    assert mgr is not None, "sessions-offload needs EngineConfig.offload"

    def _avoided() -> float:
        snap = metrics_snapshot()
        return float(
            snap.get("opsagent_offload_reprefill_avoided_tokens_total", 0.0)
        )

    phases: dict[str, dict] = {}
    # OFF first: the ON phase's host pool then holds only its own spills.
    for tag, flag, seed in (("off", False, 3000), ("on", True, 7000)):
        if flag:
            eng.offload = mgr
            eng.alloc.set_spill(eng._spill_page)
        else:
            eng.offload = None
            eng.alloc.set_spill(None)
        get_perf_stats().reset()
        avoided0 = _avoided()
        stack = ServingStack(eng)
        try:
            phases[tag] = _drive_sessions_streaming(
                stack, batch, rounds, gen_tokens, prompt_len, seed,
                park=flag,
            )
        finally:
            stack.close()
        r = phases[tag]
        r["p50_ttft_ms"] = (
            float(np.median(r["ttfts"]) * 1e3) if r["ttfts"] else 0.0
        )
        qw = get_perf_stats().get_stats().get("scheduler.queue_wait", {})
        r["admission_wait_p50_ms"] = float(qw.get("p50", 0.0))
        r["reprefill_avoided_tokens"] = int(_avoided() - avoided0)
        r["tok_s_chip"] = r["produced"] / max(1e-9, r["wall"]) / n_chips
        log(f"bench[sessions-offload/{tag}]: {batch} sessions x {rounds} "
            f"rounds, {r['produced']} tokens in {r['wall']:.2f}s -> "
            f"{r['tok_s_chip']:.0f} tok/s/chip; p50 TTFT "
            f"{r['p50_ttft_ms']:.0f} ms; admission-wait p50 "
            f"{r['admission_wait_p50_ms']:.1f} ms; re-prefill avoided "
            f"{r['reprefill_avoided_tokens']} tok; "
            f"errors={len(r['errors'])}")
    on, off = phases["on"], phases["off"]
    pool = mgr.stats()
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"sessions_offload[{model}{qtag},N={batch},{platform}]",
        "value": round(on["tok_s_chip"], 1),
        "unit": "tok/s/chip",
        "vs_baseline": vs_baseline(on["tok_s_chip"], model, platform),
        "extra": {
            "sessions": batch,
            "rounds": rounds,
            "p50_ttft_ms": round(on["p50_ttft_ms"], 1),
            "admission_wait_p50_ms": round(on["admission_wait_p50_ms"], 2),
            "reprefill_avoided_tokens": on["reprefill_avoided_tokens"],
            "off_tok_s_chip": round(off["tok_s_chip"], 1),
            "off_p50_ttft_ms": round(off["p50_ttft_ms"], 1),
            "off_admission_wait_p50_ms": round(
                off["admission_wait_p50_ms"], 2
            ),
            "off_reprefill_avoided_tokens": off["reprefill_avoided_tokens"],
            "admission_wait_delta_ms": round(
                off["admission_wait_p50_ms"] - on["admission_wait_p50_ms"], 2
            ),
            "host_pool_pages": pool["pages"],
            "host_pool_bytes": pool["bytes"],
            "host_pool_drops": pool["drops"],
            "restored_tokens": pool["restored_tokens"],
            "errors": len(on["errors"]) + len(off["errors"]),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    exit_if_slo_breach(slo_verdicts())


def run_fleet_affinity(eng, cfg, model, batch, steps, prompt_len, platform,
                       n_chips, quantize, init_s, warmup_s) -> None:
    """The fleet-affinity A/B stage (serving/fleet): N in-process engine
    replicas behind the FleetRouter, the concurrent-sessions workload
    with tool-window parking between rounds, run TWICE — prefix-affinity
    routing ON (sticky pinning + longest-cached-prefix placement: a
    session's comeback lands on the replica holding its KV and restores
    from the host pool), then OFF (stateless least-loaded placement: a
    comeback lands wherever occupancy is lowest and usually re-prefills
    its whole history). Decision numbers per phase: p50 client TTFT and
    re-prefill-avoided tokens summed over the fleet — what prefix-
    affinity routing is worth at fleet scale."""
    import threading
    from dataclasses import replace as dc_replace

    from opsagent_tpu.serving.api import ServingStack
    from opsagent_tpu.serving.engine import Engine
    from opsagent_tpu.serving.fleet.router import FleetRouter

    n_replicas = int(os.environ.get("OPSAGENT_BENCH_REPLICAS", "2"))
    gen_tokens = max(16, steps // 8)
    rounds = 3
    engines = [eng]
    for i in range(1, n_replicas):
        e = Engine(dc_replace(cfg, seed=cfg.seed))
        e.warmup("sessions")
        engines.append(e)
    stacks = [ServingStack(e) for e in engines]

    def drive(router, seed_base: int) -> dict:
        results: list[dict] = []
        errors: list[str] = []
        lock = threading.Lock()

        def session(sid: int) -> None:
            rng = np.random.default_rng(seed_base + sid)
            words = [
                f"w{rng.integers(0, 9999)}" for _ in range(prompt_len // 2)
            ]
            messages = [
                {"role": "system", "content": "fleet bench"},
                {"role": "user", "content": " ".join(words)},
            ]
            owner = None
            for r in range(rounds):
                if r and owner is not None:
                    # Tool window: the session's replica parks its KV to
                    # the host tier; the comeback restores ONLY if the
                    # router sends the turn back to that replica.
                    info = router.registry.get(owner)
                    if info is not None and info.handle is not None:
                        try:
                            info.handle.park_tokens(
                                info.handle.tokenize(
                                    {"messages": messages}
                                )
                            )
                        except Exception:  # noqa: BLE001
                            pass
                t0 = time.perf_counter()
                try:
                    gen = router.complete_stream({
                        "messages": messages,
                        "max_tokens": gen_tokens,
                        "temperature": 0.0,
                        "stream": True,
                    })
                    first = next(gen)
                    if "error" in first:
                        raise RuntimeError(first["error"]["message"])
                    ttft = time.perf_counter() - t0
                    owner = router.owner_of(first.get("id", "")) or owner
                    parts: list[str] = []
                    n_tok = 0
                    for ch in gen:
                        if "error" in ch:
                            raise RuntimeError(ch["error"]["message"])
                        delta = ch["choices"][0]["delta"]
                        if delta.get("content"):
                            parts.append(delta["content"])
                            n_tok += 1
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"round {r + 1}: {e}")
                    return
                messages.append(
                    {"role": "assistant", "content": "".join(parts)}
                )
                messages.append(
                    {"role": "user", "content": f"continue {r}"}
                )
                with lock:
                    results.append({"ttft": ttft, "tokens": n_tok})

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=session, args=(i,))
            for i in range(batch)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return {
            "produced": sum(r["tokens"] for r in results),
            "wall": time.perf_counter() - t0,
            "ttfts": [r["ttft"] for r in results],
            "errors": errors,
        }

    def fleet_avoided() -> int:
        return sum(
            e.offload.restored_tokens for e in engines
            if e.offload is not None
        )

    phases: dict[str, dict] = {}
    for tag, flag, seed in (("affinity", True, 11000), ("off", False, 15000)):
        router = FleetRouter(
            affinity=flag, sticky=flag,
            placement="affinity" if flag else "round_robin",
        )
        for i, stack in enumerate(stacks):
            router.add_local(stack, f"bench-r{i}")
        avoided0 = fleet_avoided()
        phases[tag] = drive(router, seed)
        r = phases[tag]
        r["p50_ttft_ms"] = (
            float(np.median(r["ttfts"]) * 1e3) if r["ttfts"] else 0.0
        )
        r["reprefill_avoided_tokens"] = fleet_avoided() - avoided0
        r["tok_s_chip"] = r["produced"] / max(1e-9, r["wall"]) / n_chips
        log(f"bench[fleet-affinity/{tag}]: {batch} sessions x {rounds} "
            f"rounds over {n_replicas} replicas, {r['produced']} tokens "
            f"in {r['wall']:.2f}s -> {r['tok_s_chip']:.0f} tok/s/chip; "
            f"p50 TTFT {r['p50_ttft_ms']:.0f} ms; re-prefill avoided "
            f"{r['reprefill_avoided_tokens']} tok; "
            f"errors={len(r['errors'])}")
    on, off = phases["affinity"], phases["off"]
    snap = metrics_snapshot()
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": (
            f"fleet_affinity[{model}{qtag},N={batch},R={n_replicas},"
            f"{platform}]"
        ),
        "value": round(on["tok_s_chip"], 1),
        "unit": "tok/s/chip",
        "vs_baseline": vs_baseline(on["tok_s_chip"], model, platform),
        "extra": {
            "replicas": n_replicas,
            "sessions": batch,
            "rounds": rounds,
            "p50_ttft_ms": round(on["p50_ttft_ms"], 1),
            "reprefill_avoided_tokens": on["reprefill_avoided_tokens"],
            "off_tok_s_chip": round(off["tok_s_chip"], 1),
            "off_p50_ttft_ms": round(off["p50_ttft_ms"], 1),
            "off_reprefill_avoided_tokens": off[
                "reprefill_avoided_tokens"
            ],
            "ttft_delta_ms": round(
                off["p50_ttft_ms"] - on["p50_ttft_ms"], 1
            ),
            "route_decisions": {
                k[len("opsagent_fleet_route_decisions_total"):] or "total": v
                for k, v in snap.items()
                if k.startswith("opsagent_fleet_route_decisions_total")
            },
            "kv_transfer_pages": snap.get(
                "opsagent_fleet_kv_transfer_pages_total", 0
            ),
            "errors": len(on["errors"]) + len(off["errors"]),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "metrics": snap,
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    for s in stacks:
        s.close()
    exit_if_slo_breach(slo_verdicts())


def run_fleet_global_kv(eng, cfg, model, batch, steps, prompt_len,
                        platform, n_chips, quantize, init_s,
                        warmup_s) -> None:
    """The fleet-global-KV A/B stage (serving/fleet/pagestore): the page
    directory + peer fault-in path ON vs OFF (legacy eager-push
    migration). Per session: turn 1 lands on replica A (the owner), the
    second turn is FORCED onto replica B with zero affinity — with the
    directory on, B faults the chain in peer-to-peer and restores over
    the wire; the same turn is then replayed on never-moved A and the
    greedy outputs must be byte-identical. The ON phase also promotes a
    standby replica mid-run and forces a third turn onto it (the
    scale-up story: a freshly promoted replica is instantly useful for
    EXISTING sessions). Decision numbers per phase: fleet-summed
    re-prefill-avoided tokens, pagestore remote-hit pages, p50 moved-
    turn latency, and the identical-output flags."""
    from dataclasses import replace as dc_replace

    from opsagent_tpu import obs as obs_mod
    from opsagent_tpu.serving.api import ServingStack
    from opsagent_tpu.serving.engine import Engine
    from opsagent_tpu.serving.fleet.router import FleetRouter

    n_replicas = int(os.environ.get("OPSAGENT_BENCH_REPLICAS", "2"))
    gen_tokens = max(16, steps // 8)
    engines = [eng]
    for _ in range(1, n_replicas + 1):   # +1: the standby replica
        e = Engine(dc_replace(cfg, seed=cfg.seed))
        e.warmup("sessions")
        engines.append(e)
    stacks = [ServingStack(e) for e in engines]

    def fleet_avoided() -> int:
        return sum(
            e.offload.restored_tokens for e in engines
            if e.offload is not None
        )

    def drive(router, seed_base: int, standby_id: str | None) -> dict:
        moved_ms: list[float] = []
        errors: list[str] = []
        identical = True
        standby_identical = True
        for sid in range(batch):
            rng = np.random.default_rng(seed_base + sid)
            words = [
                f"w{rng.integers(0, 9999)}" for _ in range(prompt_len // 2)
            ]
            messages = [
                {"role": "system", "content": "fleet global kv bench"},
                {"role": "user", "content": " ".join(words)},
            ]

            def turn(msgs, force):
                resp = router.complete(
                    {
                        "messages": msgs, "max_tokens": gen_tokens,
                        "temperature": 0.0,
                    },
                    force_replica=force,
                )
                return resp["choices"][0]["message"]["content"] or ""

            try:
                # Turn 1 establishes ownership on replica 0.
                t1 = turn(messages, "bench-r0")
                messages += [
                    {"role": "assistant", "content": t1},
                    {"role": "user", "content": f"continue {sid}"},
                ]
                # Turn 2 forced onto a NON-owner: the directory-on
                # phase faults the chain in; both phases must match the
                # never-moved replay on replica 0.
                t0 = time.perf_counter()
                moved = turn(messages, "bench-r1")
                moved_ms.append((time.perf_counter() - t0) * 1e3)
                stayed = turn(messages, "bench-r0")
                if moved != stayed:
                    identical = False
                if standby_id is not None:
                    # Turn 3 onto the freshly promoted standby.
                    messages += [
                        {"role": "assistant", "content": stayed},
                        {"role": "user", "content": "and then?"},
                    ]
                    t3_standby = turn(messages, standby_id)
                    t3_owner = turn(messages, "bench-r0")
                    if t3_standby != t3_owner:
                        standby_identical = False
            except Exception as e:  # noqa: BLE001
                errors.append(f"session {sid}: {e}")
        return {
            "moved_ms": moved_ms,
            "errors": errors,
            "identical": identical,
            "standby_identical": standby_identical,
        }

    def pagestore_counters() -> dict:
        snap = metrics_snapshot()
        return {
            "remote_hits": snap.get(
                "opsagent_pagestore_remote_hits_total", 0.0
            ),
            "fetch_bytes": snap.get(
                "opsagent_pagestore_fetch_bytes_total", 0.0
            ),
            "stale": snap.get(
                "opsagent_pagestore_stale_entries_total", 0.0
            ),
            "fallbacks": sum(
                v for k, v in snap.items()
                if k.startswith("opsagent_pagestore_fallbacks_total")
            ),
        }

    phases: dict[str, dict] = {}
    for tag, flag, seed in (("on", True, 21000), ("off", False, 25000)):
        router = FleetRouter(sticky=False, pagestore=flag)
        for i, stack in enumerate(stacks[: n_replicas]):
            router.add_local(stack, f"bench-r{i}")
        standby_id = None
        if flag:
            # The scale-up leg: register the spare as a standby, promote
            # it into the decode set mid-phase — its first-ever turns
            # must restore existing sessions' chains over the wire.
            standby_id = "bench-standby"
            router.add_local(stacks[n_replicas], standby_id,
                             role="standby")
            router.registry.set_role(standby_id, "decode")
        avoided0 = fleet_avoided()
        ps0 = pagestore_counters()
        compiles0 = obs_mod.POST_WARMUP_COMPILES.value()
        t0 = time.perf_counter()
        phases[tag] = drive(router, seed, standby_id)
        r = phases[tag]
        r["wall"] = time.perf_counter() - t0
        r["reprefill_avoided_tokens"] = fleet_avoided() - avoided0
        ps1 = pagestore_counters()
        r["pagestore"] = {
            k: ps1[k] - ps0[k] for k in ps1
        }
        r["post_compiles"] = (
            obs_mod.POST_WARMUP_COMPILES.value() - compiles0
        )
        r["directory"] = router.registry.directory.stats()
        r["p50_moved_ms"] = (
            float(np.median(r["moved_ms"])) if r["moved_ms"] else 0.0
        )
        log(f"bench[fleet-global-kv/{tag}]: {batch} sessions moved onto "
            f"non-owners; identical={r['identical']} "
            f"standby_identical={r['standby_identical']} "
            f"remote_hit_pages={r['pagestore']['remote_hits']:.0f} "
            f"re-prefill avoided {r['reprefill_avoided_tokens']} tok; "
            f"p50 moved-turn {r['p50_moved_ms']:.0f} ms; "
            f"post-warmup compiles {r['post_compiles']:.0f}; "
            f"errors={len(r['errors'])}")
    on, off = phases["on"], phases["off"]
    # Remote hits per phase: the ON phase restores over the wire
    # (directory + fault-in); the OFF phase may still avoid re-prefill
    # via the legacy eager push, but never through the page store.
    total_tokens = batch * gen_tokens * 4  # 2 turns + replay legs, approx
    tok_s_chip = total_tokens / max(1e-9, on["wall"]) / n_chips
    snap = metrics_snapshot()
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": (
            f"fleet_global_kv[{model}{qtag},N={batch},R={n_replicas}+1,"
            f"{platform}]"
        ),
        "value": round(tok_s_chip, 1),
        "unit": "tok/s/chip",
        "extra": {
            "replicas": n_replicas,
            "standby": 1,
            "sessions": batch,
            "remote_hit_pages": on["pagestore"]["remote_hits"],
            "fetch_bytes": on["pagestore"]["fetch_bytes"],
            "stale_entries": on["pagestore"]["stale"],
            "fallbacks": on["pagestore"]["fallbacks"],
            "outputs_identical": on["identical"],
            "standby_identical": on["standby_identical"],
            "off_outputs_identical": off["identical"],
            "reprefill_avoided_tokens": on["reprefill_avoided_tokens"],
            "off_reprefill_avoided_tokens": off[
                "reprefill_avoided_tokens"
            ],
            "off_remote_hit_pages": off["pagestore"]["remote_hits"],
            "p50_moved_ms": round(on["p50_moved_ms"], 1),
            "off_p50_moved_ms": round(off["p50_moved_ms"], 1),
            "post_compiles": on["post_compiles"],
            "directory": on["directory"],
            "errors": len(on["errors"]) + len(off["errors"]),
            "error_detail": (on["errors"] + off["errors"])[:4],
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            "metrics": snap,
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    for s in stacks:
        s.close()
    exit_if_slo_breach(slo_verdicts())


def run_audit_fanout(eng, cfg, model, batch, steps, prompt_len, platform,
                     n_chips, quantize, init_s, warmup_s) -> None:
    """The audit-fanout stage (agent/fanout): one cluster-scale audit as
    a fan-out/reduce workload over OPSAGENT_BENCH_REPLICAS (default 2)
    in-process replicas behind the fleet router. The seeded synthetic
    cluster gives ground truth, so the stage scores RECALL (must be 1.0)
    alongside the serving numbers: end-to-end audit latency (the
    headline, lower-better), per-fan-out shared-prefix hit rate
    (higher-better, its own result row), goodput (children/s), and the
    fraction of children whose prefill was served from the primed shared
    prefix. The audit runs TWICE — pass 1 warms the fan-out shape and
    pins the canonical report bytes, pass 2 is measured (post-warmup
    compiles over it must be zero) with a concurrent INTERACTIVE probe
    streaming against the same fleet: batch-class children must not
    starve interactive TTFT (reported as p50_ttft_ms so the perf gate
    ratchets it)."""
    import threading
    from dataclasses import replace as dc_replace

    from opsagent_tpu import obs as obs_mod
    from opsagent_tpu.agent.fanout import (
        FanoutConfig, SynthCluster, run_audit,
    )
    from opsagent_tpu.serving.api import ServingStack
    from opsagent_tpu.serving.engine import Engine
    from opsagent_tpu.serving.fleet.router import FleetRouter

    n_replicas = int(os.environ.get("OPSAGENT_BENCH_REPLICAS", "2"))
    resources = int(os.environ.get(
        "OPSAGENT_BENCH_FANOUT_RESOURCES", str(max(8, batch * 4))
    ))
    gen_tokens = max(8, steps // 8)
    engines = [eng]
    for _ in range(1, n_replicas):
        e = Engine(dc_replace(cfg, seed=cfg.seed))
        e.warmup("sessions")
        engines.append(e)
    stacks = [ServingStack(e) for e in engines]
    router = FleetRouter(sticky=False)
    for i, s in enumerate(stacks):
        router.add_local(s, f"bench-r{i}")
    cluster = SynthCluster(resources=resources, seed=0)
    fcfg = FanoutConfig(
        max_inflight=max(2, batch), max_tokens=gen_tokens,
    )

    rep1 = run_audit(router, cluster, fcfg)
    compiles0 = obs_mod.POST_WARMUP_COMPILES.value()
    ttft_ms: list[float] = []
    probe_errors: list[str] = []
    stop = threading.Event()

    def interactive_probe() -> None:
        n = 0
        while not stop.is_set():
            n += 1
            t0 = time.perf_counter()
            try:
                gen = router.complete_stream({
                    "messages": [
                        {"role": "user", "content": f"fleet status {n}"},
                    ],
                    "max_tokens": 4, "temperature": 0.0, "stream": True,
                    "slo_class": "interactive",
                })
                first = next(gen)
                if "error" in first:
                    raise RuntimeError(first["error"]["message"])
                ttft_ms.append((time.perf_counter() - t0) * 1e3)
                for ch in gen:
                    if "error" in ch:
                        raise RuntimeError(ch["error"]["message"])
            except Exception as e:  # noqa: BLE001 - probe outcome IS data
                probe_errors.append(f"{type(e).__name__}: {e}")
            stop.wait(0.05)

    probe = threading.Thread(target=interactive_probe, daemon=True)
    probe.start()
    rep2 = run_audit(router, cluster, fcfg)
    stop.set()
    probe.join(timeout=30.0)
    post_compiles = obs_mod.POST_WARMUP_COMPILES.value() - compiles0

    s1, s2 = rep1.stats, rep2.stats
    byte_identical = rep1.canonical == rep2.canonical
    recall = rep2.recall(cluster)
    audit_s = float(s2["audit_s"])
    goodput = resources / max(1e-9, audit_s)
    failed = resources - int(s2["outcomes"].get("ok", 0))
    p50_ttft = float(np.median(ttft_ms)) if ttft_ms else 0.0
    snap = metrics_snapshot()
    qtag = f",{quantize}" if quantize else ""
    tag = f"{model}{qtag},N={resources},R={n_replicas},{platform}"
    extra = {
        "replicas": n_replicas,
        "resources": resources,
        "children_ok": int(s2["outcomes"].get("ok", 0)),
        "failed_children": failed,
        "outcomes": s2["outcomes"],
        "recall": recall,
        "byte_identical": byte_identical,
        "goodput_children_s": round(goodput, 2),
        "prefix_hit_rate": s2["prefix_hit_rate"],
        "avoided_children": s2["avoided_children"],
        "shared_prefix_tokens": s2["shared_prefix_tokens"],
        "prefix_hit_tokens": s2["prefix_hit_tokens"],
        "scatter_s": round(float(s2["scatter_s"]), 3),
        "reduce_s": round(float(s2["reduce_s"]), 4),
        "warm_audit_ratio": round(
            audit_s / max(1e-9, float(s1["audit_s"])), 3
        ),
        "post_compiles": post_compiles,
        "p50_ttft_ms": round(p50_ttft, 1),
        "interactive_probes": len(ttft_ms),
        "probe_errors": len(probe_errors),
        "probe_error_detail": probe_errors[:4],
        "init_s": round(init_s, 1),
        "warmup_s": round(warmup_s, 1),
        "chips": n_chips,
        "platform": platform,
        "metrics": snap,
        "attribution": attribution_snapshot(),
        "slo": slo_verdicts(),
    }
    print(json.dumps({
        "metric": f"audit_fanout[{tag}]",
        "value": round(audit_s, 3),
        "unit": "audit_latency_s",
        "extra": extra,
    }), flush=True)
    # The hit rate gets its own row so the perf gate ratchets BOTH
    # directions: latency cannot creep up, the shared-prefix path cannot
    # silently degrade into per-child re-prefill.
    print(json.dumps({
        "metric": f"audit_fanout_prefix_hit[{tag}]",
        "value": round(float(s2["prefix_hit_rate"]), 4),
        "unit": "prefix_hit_rate",
        "extra": {"avoided_children": s2["avoided_children"],
                  "resources": resources},
    }), flush=True)
    log(f"bench[audit-fanout]: {resources} resources over {n_replicas} "
        f"replicas in {audit_s:.2f}s (goodput {goodput:.1f} children/s); "
        f"recall={recall:.2f} prefix_hit={s2['prefix_hit_rate']:.2f} "
        f"avoided={s2['avoided_children']}/{resources} "
        f"byte_identical={byte_identical} failed={failed} "
        f"post-warmup compiles {post_compiles:.0f}; interactive p50 TTFT "
        f"{p50_ttft:.0f} ms over {len(ttft_ms)} probes")
    log_perf_table()
    for s in stacks:
        s.close()
    exit_if_slo_breach(slo_verdicts())


def run_fleet_chaos(eng, cfg, model, batch, steps, prompt_len, platform,
                    n_chips, quantize, init_s, warmup_s) -> None:
    """The fleet-chaos A/B stage (serving/faults + router failover): two
    in-process engine replicas behind the FleetRouter, the concurrent-
    sessions streaming workload run TWICE — seeded faults OFF (reference
    run), then ON (mid-SSE disconnects + connect-phase failures from the
    deterministic injector). The failure-containment claim measured:
    the chaos phase finishes with ZERO failed requests (failovers resume
    every broken stream on the surviving replica, byte-identically under
    greedy decode); what containment costs is the p99 TTFT delta."""
    import threading
    from dataclasses import replace as dc_replace

    from opsagent_tpu.serving import faults
    from opsagent_tpu.serving.api import ServingStack
    from opsagent_tpu.serving.engine import Engine
    from opsagent_tpu.serving.fleet.router import FleetRouter

    n_replicas = int(os.environ.get("OPSAGENT_BENCH_REPLICAS", "2"))
    gen_tokens = max(16, steps // 8)
    rounds = 2
    engines = [eng]
    for _ in range(1, n_replicas):
        e = Engine(dc_replace(cfg, seed=cfg.seed))
        e.warmup("sessions")
        engines.append(e)
    stacks = [ServingStack(e) for e in engines]
    # Default spec: kill stream pulls and a connect at fixed hit counts —
    # same spec, same workload, same flight-event sequence every run.
    spec = os.environ.get(
        "OPSAGENT_BENCH_CHAOS_SPEC",
        "fleet.stream_disconnect@7;fleet.stream_disconnect@29;"
        "fleet.stream_disconnect@63",
    )

    def drive(router, seed_base: int) -> dict:
        texts: dict[int, list[str]] = {}
        ttfts: list[float] = []
        errors: list[str] = []
        lock = threading.Lock()

        def session(sid: int) -> None:
            rng = np.random.default_rng(seed_base + sid)
            words = [
                f"w{rng.integers(0, 9999)}" for _ in range(prompt_len // 2)
            ]
            messages = [
                {"role": "system", "content": "chaos bench"},
                {"role": "user", "content": " ".join(words)},
            ]
            for r in range(rounds):
                t0 = time.perf_counter()
                try:
                    gen = router.complete_stream({
                        "messages": messages,
                        "max_tokens": gen_tokens,
                        "temperature": 0.0,
                        "stream": True,
                    })
                    first = next(gen)
                    if "error" in first:
                        raise RuntimeError(first["error"]["message"])
                    ttft = time.perf_counter() - t0
                    parts: list[str] = []
                    for ch in gen:
                        if "error" in ch:
                            raise RuntimeError(ch["error"]["message"])
                        delta = ch["choices"][0]["delta"]
                        if delta.get("content"):
                            parts.append(delta["content"])
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"session {sid} round {r + 1}: {e}")
                    return
                reply = "".join(parts)
                messages.append({"role": "assistant", "content": reply})
                messages.append({"role": "user", "content": f"go {r}"})
                with lock:
                    texts.setdefault(sid, []).append(reply)
                    ttfts.append(ttft)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=session, args=(i,))
            for i in range(batch)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return {
            "texts": texts, "ttfts": ttfts, "errors": errors,
            "wall": time.perf_counter() - t0,
            "produced": sum(len(t) for ts in texts.values() for t in ts),
        }

    def counter(snap: dict, name: str) -> float:
        return sum(v for k, v in snap.items() if k.startswith(name))

    phases: dict[str, dict] = {}
    for tag, chaotic in (("off", False), ("chaos", True)):
        router = FleetRouter()
        for i, stack in enumerate(stacks):
            router.add_local(stack, f"chaos-r{i}")
        if chaotic:
            faults.configure(spec)
        else:
            faults.reset()
        before = metrics_snapshot()
        phases[tag] = drive(router, seed_base=21000)  # SAME seeds per phase
        faults.reset()
        after = metrics_snapshot()
        r = phases[tag]
        r["p99_ttft_ms"] = (
            float(np.percentile(r["ttfts"], 99) * 1e3) if r["ttfts"]
            else 0.0
        )
        for fam, key in (
            ("opsagent_fleet_failovers_total", "failovers"),
            ("opsagent_fleet_retries_total", "retries"),
            ("opsagent_fleet_shed_total", "shed"),
            ("opsagent_fault_injections_total", "injected"),
        ):
            r[key] = int(counter(after, fam) - counter(before, fam))
        log(f"bench[fleet-chaos/{tag}]: {batch} sessions x {rounds} "
            f"rounds, {r['produced']} replies in {r['wall']:.2f}s; "
            f"p99 TTFT {r['p99_ttft_ms']:.0f} ms; injected={r['injected']} "
            f"failovers={r['failovers']} retries={r['retries']} "
            f"shed={r['shed']} errors={len(r['errors'])}")
    off, chaos = phases["off"], phases["chaos"]
    identical = off["texts"] == chaos["texts"]
    snap = metrics_snapshot()
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": (
            f"fleet_chaos[{model}{qtag},N={batch},R={n_replicas},"
            f"{platform}]"
        ),
        "value": len(chaos["errors"]),
        "unit": "failed_requests",
        "vs_baseline": None,
        "extra": {
            "replicas": n_replicas,
            "sessions": batch,
            "rounds": rounds,
            "spec": spec,
            "failed_requests": len(chaos["errors"]),
            "off_failed_requests": len(off["errors"]),
            "injected": chaos["injected"],
            "failovers": chaos["failovers"],
            "retries": chaos["retries"],
            "shed": chaos["shed"],
            "p99_ttft_ms": round(chaos["p99_ttft_ms"], 1),
            "off_p99_ttft_ms": round(off["p99_ttft_ms"], 1),
            "outputs_identical": identical,
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            "metrics": snap,
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    for s in stacks:
        s.close()
    exit_if_slo_breach(slo_verdicts())


def run_fleet_journey(eng, cfg, model, batch, steps, prompt_len, platform,
                      n_chips, quantize, init_s, warmup_s) -> None:
    """The fleet-journey observability stage (ISSUE 16): two in-process
    replicas behind the FleetRouter. Two parts. (1) Obs-overhead A/B:
    the concurrent streamed sessions workload with journeys ON then OFF
    (no ID stamping, no participants map) — the reported delta is what
    cross-replica tracing costs on the request plane. (2) Stitched-
    timeline smoke: one request forced through a mid-SSE failover plus a
    pagestore peer fault-in must come back from the router as ONE
    stitched timeline with segment lanes from BOTH replicas, failover +
    fault_in windows, >= 95% coverage, and monotonic non-overlapping
    segments after skew correction — with byte-identical greedy text."""
    import threading
    from dataclasses import replace as dc_replace

    from opsagent_tpu.serving import faults
    from opsagent_tpu.serving.api import ServingStack
    from opsagent_tpu.serving.engine import Engine
    from opsagent_tpu.serving.fleet.router import FleetRouter

    gen_tokens = max(16, steps // 8)
    e2 = Engine(dc_replace(cfg, seed=cfg.seed))
    e2.warmup("sessions")
    stacks = [ServingStack(eng), ServingStack(e2)]

    def drive(router, seed_base: int) -> dict:
        chunks_total = [0]
        errors: list[str] = []
        lock = threading.Lock()

        def session(sid: int) -> None:
            rng = np.random.default_rng(seed_base + sid)
            words = [
                f"w{rng.integers(0, 9999)}" for _ in range(prompt_len // 2)
            ]
            n = 0
            try:
                for ch in router.complete_stream({
                    "messages": [
                        {"role": "system", "content": "journey bench"},
                        {"role": "user", "content": " ".join(words)},
                    ],
                    "max_tokens": gen_tokens, "temperature": 0.0,
                    "stream": True,
                }):
                    if "error" in ch:
                        raise RuntimeError(ch["error"]["message"])
                    if ch["choices"][0]["delta"].get("content"):
                        n += 1
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"session {sid}: {e}")
                return
            with lock:
                chunks_total[0] += n

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=session, args=(i,))
            for i in range(batch)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return {
            "wall": wall, "errors": errors,
            "tok_s": chunks_total[0] / wall if wall > 0 else 0.0,
        }

    # (1) Obs-overhead A/B — distinct prompt seeds per phase so both
    # phases prefill cold (neither inherits the other's prefix cache).
    # A discarded warmup pass absorbs first-drive lazy-init costs
    # (thread spin-up, tokenizer caches) that would otherwise be billed
    # entirely to whichever phase runs first.
    warm_router = FleetRouter()
    for i, stack in enumerate(stacks):
        warm_router.add_local(stack, f"jr{i}")
    drive(warm_router, seed_base=30000)
    phases: dict[str, dict] = {}
    for tag, journeys, seed_base in (
        ("on", True, 31000), ("off", False, 32000),
    ):
        router = FleetRouter(journeys=journeys)
        for i, stack in enumerate(stacks):
            router.add_local(stack, f"jr{i}")
        phases[tag] = drive(router, seed_base=seed_base)
        r = phases[tag]
        log(f"bench[fleet-journey/{tag}]: {batch} streamed sessions in "
            f"{r['wall']:.2f}s ({r['tok_s']:.1f} chunk/s) "
            f"errors={len(r['errors'])}")
    on, off = phases["on"], phases["off"]
    overhead_pct = (
        (off["tok_s"] - on["tok_s"]) / off["tok_s"] * 100.0
        if off["tok_s"] > 0 else 0.0
    )

    # (2) Stitched-timeline smoke: failover + peer fault-in in ONE
    # journey, stitched from both replicas through the router.
    router = FleetRouter()   # journeys + pagestore directory on
    for i, stack in enumerate(stacks):
        router.add_local(stack, f"jr{i}")
    # Each turn must SEAL full KV pages (page_size is 64 at bench
    # geometry) or the directory has nothing for jr1 to fault in — size
    # both user turns at a few pages' worth of tokens, and generate
    # across multiple decode blocks so the injected disconnect lands
    # mid-flight. The failover push (migrate_chain) ships the chain
    # ahead of the resume; transfer.truncate@1 drops its first record
    # in transit, so the resuming replica's admission must repair the
    # hole through the page directory — a true peer fault-in on the
    # SAME journey as the failover.
    nfill = max(24, cfg.page_size // 2)
    filler = " ".join(f"ctx{i}" for i in range(nfill))
    filler2 = " ".join(f"doc{i}" for i in range(nfill))
    gen2 = max(32, cfg.decode_block * 2)
    messages = [
        {"role": "system", "content": "journey smoke"},
        {"role": "user", "content": f"first turn here {filler}"},
    ]
    r1 = router.complete(
        {"messages": messages, "max_tokens": 8, "temperature": 0},
        force_replica="jr0",
    )
    turn2 = list(messages) + [
        {"role": "assistant",
         "content": r1["choices"][0]["message"]["content"] or ""},
        {"role": "user", "content": f"second turn now {filler2}"},
    ]
    faults.configure("fleet.stream_disconnect@5;transfer.truncate@1")
    chunks = list(router.complete_stream({
        "messages": turn2, "max_tokens": gen2, "temperature": 0,
        "stream": True,
    }))
    faults.reset()
    text = "".join(
        c["choices"][0]["delta"].get("content") or "" for c in chunks
    )
    # Reference is a fault-free STREAM (forced jr0), computed AFTER the
    # faulted run so it cannot pre-park the turn-2 chain on jr0: the
    # seam comparison is stream-vs-stream — the non-stream body can
    # legitimately differ in how a trailing incomplete UTF-8 sequence
    # renders at EOS.
    want = "".join(
        c["choices"][0]["delta"].get("content") or ""
        for c in router.complete_stream(
            {"messages": turn2, "max_tokens": gen2, "temperature": 0,
             "stream": True},
            force_replica="jr0",
        )
    )
    jid = chunks[0].get("id", "")
    tl = router.timeline(jid) or {}
    seg_lanes = {s["replica"] for s in tl.get("segments", [])}
    win_kinds = {w["kind"] for w in tl.get("windows", [])}
    monotonic = all(
        cur["start_ms"] >= prev["end_ms"] - 1e-6
        for prev, cur in zip(tl.get("segments", []),
                             tl.get("segments", [])[1:])
    )
    smoke_ok = (
        text == want
        and tl.get("fleet") is True
        and len(seg_lanes) >= 2
        and "failover" in win_kinds
        and "fault_in" in win_kinds
        and tl.get("coverage", 0.0) >= 0.95
        and monotonic
    )
    log(f"bench[fleet-journey/smoke]: shape={tl.get('shape')} "
        f"lanes={sorted(seg_lanes)} windows={sorted(win_kinds)} "
        f"coverage={tl.get('coverage', 0.0):.3f} monotonic={monotonic} "
        f"identical={text == want} ok={smoke_ok}")
    if not smoke_ok:
        log(f"bench[fleet-journey/smoke]: FAILED timeline={tl}")

    snap = metrics_snapshot()
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"fleet_journey[{model}{qtag},N={batch},{platform}]",
        "value": round(overhead_pct, 2),
        "unit": "overhead_pct",
        "vs_baseline": None,
        "extra": {
            "sessions": batch,
            "journeys_on_tok_s": round(on["tok_s"], 2),
            "journeys_off_tok_s": round(off["tok_s"], 2),
            "on_errors": len(on["errors"]),
            "off_errors": len(off["errors"]),
            "smoke_ok": smoke_ok,
            "smoke_shape": tl.get("shape"),
            "smoke_replica_lanes": sorted(seg_lanes),
            "smoke_windows": sorted(win_kinds),
            "smoke_coverage": tl.get("coverage", 0.0),
            "smoke_monotonic": monotonic,
            "smoke_identical": text == want,
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            "metrics": snap,
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    for s in stacks:
        s.close()
    if not smoke_ok:
        raise SystemExit("bench: fleet-journey stitched-timeline smoke "
                         "failed (see log above)")
    exit_if_slo_breach(slo_verdicts())


def _verify_history_tiers() -> dict:
    """Walk a synthetic 90-minute clock through TelemetryHistory (no
    sleeping, no engine): prove the 1 s / 10 s / 60 s downsample tiers,
    exact counter-delta conservation across rollups (rates stay true at
    every tier), and — in a second tiny-budget pass — that the ring's
    byte bound actually evicts. Returns the verdict dict folded into the
    stage's extras; ``ok`` gates the stage exit code."""
    from opsagent_tpu.obs.history import TIER_SPECS, TelemetryHistory

    total = [0.0]
    gauge_val = [0.0]
    step_inc = 7.0
    n_sweeps = 90 * 60
    t0 = 1_700_000_000.0

    def walk(h) -> float:
        total[0] = 0.0
        for i in range(n_sweeps):
            total[0] += step_inc
            gauge_val[0] = float(i % 32)
            h.sample(now=t0 + i)
        return t0 + n_sweeps - 1

    # Pass 1: generous budget — no eviction, so conservation is exact.
    h = TelemetryHistory(max_bytes=8 * 1024 * 1024, interval_s=1.0)
    h.register("tokens", "counter", lambda: total[0])
    h.register("occupancy", "gauge", lambda: gauge_val[0])
    now = walk(h)
    st = h.stats()
    per_tier = st["points_per_tier"]
    # Tier shape: the fine tier only spans its horizon; the coarse tiers
    # hold the rest (2 series share each tier count).
    fine_ok = per_tier[0] <= 2 * (TIER_SPECS[0][1] + TIER_SPECS[1][0])
    spread_ok = per_tier[1] > 0 and per_tier[2] > 0
    q = h.query(series=["tokens"], since=n_sweeps + 60.0, now=now)
    pts = q["series"]["tokens"]["points"]
    # First sweep has no interval to delta over: n_sweeps - 1 deltas.
    want_total = step_inc * (n_sweeps - 1)
    conserved = abs(sum(p[1] for p in pts) - want_total) < 1e-6
    # Re-bucketed to 60 s, interior buckets must carry exactly 60 deltas.
    q60 = h.query(
        series=["tokens"], since=n_sweeps + 60.0, step=60.0, now=now
    )
    mid = q60["series"]["tokens"]["points"][2:-2]
    step60_ok = bool(mid) and all(
        abs(p[1] - 60 * step_inc) < 1e-6 for p in mid
    )
    rate = h.rate("tokens", window_s=3600.0, now=now)
    rate_ok = rate is not None and abs(rate - step_inc) < 0.05
    # Pass 2: a budget far below the walk's footprint must evict — and
    # the resident estimate must stay under it.
    h2 = TelemetryHistory(max_bytes=16 * 1024, interval_s=1.0)
    h2.register("tokens", "counter", lambda: total[0])
    h2.register("occupancy", "gauge", lambda: gauge_val[0])
    walk(h2)
    st2 = h2.stats()
    bound_ok = st2["evicted"] > 0 and st2["bytes"] <= st2["max_bytes"]
    return {
        "ok": all(
            (fine_ok, spread_ok, conserved, step60_ok, rate_ok, bound_ok)
        ),
        "fine_tier_bounded": fine_ok,
        "coarse_tiers_populated": spread_ok,
        "deltas_conserved": conserved,
        "step60_exact": step60_ok,
        "rate_1h": None if rate is None else round(rate, 4),
        "rate_ok": rate_ok,
        "byte_bound_ok": bound_ok,
        "bounded_bytes": st2["bytes"],
        "bounded_evicted": st2["evicted"],
        "points_per_tier": per_tier,
    }


def run_obs_history(eng, model, batch, steps, prompt_len, platform,
                    n_chips, quantize, init_s, warmup_s) -> None:
    """The telemetry-history overhead stage (ISSUE 18): the concurrent
    streamed sessions workload with the background history sampler ON
    (at 10x the production 1 Hz rate, so the bound is conservative) then
    OFF, same prompt seeds — byte-identical outputs are the correctness
    half, and a shared warmup drive pre-populates the prefix cache so
    neither phase rides a cache advantage. Overhead must be <= 2 % tok/s.
    The synthetic-clock tier walk (_verify_history_tiers) rides along as
    the downsampling/byte-bound proof."""
    from opsagent_tpu import obs
    from opsagent_tpu.serving.api import ServingStack

    tiers = _verify_history_tiers()
    log(f"bench[obs-history/tiers]: ok={tiers['ok']} "
        f"rate_1h={tiers['rate_1h']} "
        f"bounded_bytes={tiers['bounded_bytes']} "
        f"evicted={tiers['bounded_evicted']}")

    gen_tokens = max(16, steps // 8)
    rounds = 3
    seed = 41000
    h = obs.history.get_history()
    sampler_interval_s = 0.1
    stack = ServingStack(eng)
    phases: dict[str, dict] = {}
    try:
        # Discarded warmup drive, SAME seeds as the measured phases: it
        # absorbs lazy-init costs AND leaves the prefix cache warm for
        # both phases equally (temperature 0 makes the grown histories
        # identical), so the A/B delta isolates the sampler.
        _drive_sessions_streaming(
            stack, batch, rounds, gen_tokens, prompt_len, seed
        )
        for tag in ("on", "off"):
            if tag == "on":
                h.interval_s = sampler_interval_s
                h.start()
            get_perf_stats().reset()
            try:
                phases[tag] = _drive_sessions_streaming(
                    stack, batch, rounds, gen_tokens, prompt_len, seed
                )
            finally:
                if tag == "on":
                    h.stop()
                    h.interval_s = float(
                        os.environ.get("OPSAGENT_HISTORY_INTERVAL_S", "")
                        or 1.0
                    )
            r = phases[tag]
            r["tok_s_chip"] = (
                r["produced"] / max(1e-9, r["wall"]) / n_chips
            )
            log(f"bench[obs-history/{tag}]: {batch} sessions x {rounds} "
                f"rounds, {r['produced']} tokens in {r['wall']:.2f}s -> "
                f"{r['tok_s_chip']:.0f} tok/s/chip; "
                f"errors={len(r['errors'])}")
    finally:
        stack.close()
    hist_stats = h.stats()
    on, off = phases["on"], phases["off"]
    overhead_pct = (
        (off["tok_s_chip"] - on["tok_s_chip"]) / off["tok_s_chip"] * 100.0
        if off["tok_s_chip"] > 0 else 0.0
    )
    identical = (
        on["texts"] == off["texts"]
        and not on["errors"] and not off["errors"]
    )
    live_bound_ok = hist_stats["bytes"] <= hist_stats["max_bytes"]
    ok = (
        tiers["ok"] and identical and live_bound_ok
        and overhead_pct <= 2.0
    )
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"obs_history[{model}{qtag},N={batch},{platform}]",
        "value": round(overhead_pct, 2),
        "unit": "overhead_pct",
        "vs_baseline": None,
        "extra": {
            "sessions": batch,
            "rounds": rounds,
            "sampler_on_tok_s_chip": round(on["tok_s_chip"], 1),
            "sampler_off_tok_s_chip": round(off["tok_s_chip"], 1),
            "sampler_interval_s": sampler_interval_s,
            "sampler_samples": hist_stats["samples"],
            "history_series": hist_stats["series"],
            "history_bytes": hist_stats["bytes"],
            "history_max_bytes": hist_stats["max_bytes"],
            "live_byte_bound_ok": live_bound_ok,
            "outputs_identical": identical,
            "tiers": tiers,
            "errors": len(on["errors"]) + len(off["errors"]),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    log_perf_table()
    if not ok:
        raise SystemExit(
            f"bench: obs-history smoke failed (tiers_ok={tiers['ok']} "
            f"identical={identical} live_bound={live_bound_ok} "
            f"overhead={overhead_pct:.2f}% > 2%)"
        )
    exit_if_slo_breach(slo_verdicts())


def run_agent_turns(eng, model, batch, prompt_len, platform, n_chips,
                    quantize, init_s, warmup_s, turns: int,
                    gen_tokens: int) -> None:
    """The literal north-star shape (BASELINE: "p50 TTFT per tool-call
    turn"): ``batch`` concurrent ReAct agent sessions, each running
    several tool-call turns in the reference's wire format — the
    assistant emits a Thought/Action, the tool observation comes back as
    a USER message (reference simple.go observation-as-user-message),
    and every turn re-sends the WHOLE grown history (the O(n^2) resend
    at reference pkg/assistants/simple.go:497-515). The prefix cache is
    the mechanism under test: turn N's prompt extends turn N-1's
    prompt+reply, so all but the newest messages of each re-prefill
    should be page-aligned trie hits. Reports client-observed streaming
    TTFT — p50 over tool-call turns (turn >= 2, the north-star number)
    with turn 1 (cold prefill) separate — plus the measured prefix-hit
    rate over the whole window."""
    import threading

    from opsagent_tpu.serving.api import ServingStack

    stack = ServingStack(eng)
    results: list[dict] = []   # one entry per completed turn
    errors: list[str] = []
    lock = threading.Lock()
    tok = eng.tokenizer
    # Snapshot through stack.engine (the scheduler's CURRENT engine), not
    # the local ``eng``: a mid-bench slice-restart rebuild swaps in a
    # fresh allocator, and diffing the dead engine's frozen counter would
    # silently zero the reported hit rate (ADVICE r05).
    hit0 = stack.engine.alloc.hit_tokens
    pre0 = get_perf_stats().get_stats().get("engine.prefill_tokens", {})
    prefill0 = pre0.get("count", 0) * pre0.get("avg", 0.0)

    def session(sid: int) -> None:
        # Distinct per-session prompts (own seed) so cross-session prefix
        # hits cannot inflate the hit rate; only a session's OWN history
        # should hit the trie.
        rng = np.random.default_rng(2000 + sid)

        def words(n: int) -> str:
            return " ".join(f"w{rng.integers(0, 9999)}" for _ in range(n))

        messages = [
            {"role": "system",
             "content": "You are a Kubernetes ops agent. " + words(16)},
            {"role": "user",
             "content": "diagnose pods: " + words(max(8, prompt_len // 4))},
        ]
        for turn in range(turns):
            body = {
                "messages": messages,
                "max_tokens": gen_tokens,
                "temperature": 0.0,
                "stream": True,
            }
            t0 = time.perf_counter()
            try:
                gen = stack.chat_completion_stream(body)
                # The first yielded chunk (role delta) is gated on the
                # engine's first real token, so time-to-first-yield IS the
                # client-observed TTFT — but ONLY for a successful turn: a
                # failed request also yields its error payload promptly,
                # and recording that as TTFT would count an errored turn
                # as a fast success (ADVICE r05).
                first = next(gen)
                if "error" in first:
                    raise RuntimeError(first["error"]["message"])
                ttft = time.perf_counter() - t0
                parts: list[str] = []
                for ch in gen:
                    if "error" in ch:
                        raise RuntimeError(ch["error"]["message"])
                    delta = ch["choices"][0]["delta"]
                    if delta.get("content"):
                        parts.append(delta["content"])
                wall = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"turn {turn + 1}: {e}")
                return
            text = "".join(parts)
            messages.append({"role": "assistant", "content": text})
            # Tool observation as a user message (the reference wire
            # format), distinct per session+turn like a real kubectl read.
            messages.append({
                "role": "user",
                "content": "Observation:\nNAME READY STATUS\n" + words(48),
            })
            with lock:
                results.append({
                    "turn": turn + 1,  # 1-based: turn 1 = cold prefill
                    "ttft": ttft,
                    "wall": wall,
                    "tokens": len(tok.encode(text)),
                })

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=session, args=(i,)) for i in range(batch)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    tool_turns = [r["ttft"] for r in results if r["turn"] >= 2]
    first_turns = [r["ttft"] for r in results if r["turn"] == 1]
    p50_tool_ms = float(np.median(tool_turns) * 1e3) if tool_turns else 0.0
    p99_tool_ms = (
        float(np.percentile(tool_turns, 99) * 1e3) if tool_turns else 0.0
    )
    p50_first_ms = float(np.median(first_turns) * 1e3) if first_turns else 0.0
    produced = sum(r["tokens"] for r in results)
    # Prefix-hit accounting over the timed window: the allocator counts
    # trie-borrowed tokens; engine.prefill_tokens counts what was actually
    # prefilled (the misses). hits / (hits + misses) = the hit rate the
    # agent loop achieved.
    hits = stack.engine.alloc.hit_tokens - hit0
    pre1 = get_perf_stats().get_stats().get("engine.prefill_tokens", {})
    prefilled = pre1.get("count", 0) * pre1.get("avg", 0.0) - prefill0
    hit_rate = hits / max(1.0, hits + prefilled)

    log(f"bench[agent]: {batch} sessions x {turns} turns, "
        f"{len(results)} turns done in {wall:.1f}s; "
        f"tool-call-turn p50 TTFT {p50_tool_ms:.0f} ms "
        f"(turn-1 {p50_first_ms:.0f} ms); prefix hit rate {hit_rate:.2f}; "
        f"errors={len(errors)}")
    qtag = f",{quantize}" if quantize else ""
    print(json.dumps({
        "metric": f"agent_turn_ttft[{model}{qtag},N={batch},{platform}]",
        "value": round(p50_tool_ms, 1),
        "unit": "ms",
        "vs_baseline": None,
        "extra": {
            "sessions": batch,
            "turns": turns,
            "turns_completed": len(results),
            "turn1_p50_ttft_ms": round(p50_first_ms, 1),
            "p99_ttft_ms": round(p99_tool_ms, 1),
            "prefix_hit_rate": round(hit_rate, 3),
            "completion_tokens": produced,
            "agg_tok_s_chip": round(produced / wall / n_chips, 1),
            "errors": len(errors),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            **eng.impl_info(),
            "paged_backend": eng.attn_impl,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    if errors:
        log(f"bench[agent]: first error: {errors[0]}")
    log_perf_table()
    stack.close()
    exit_if_slo_breach(slo_verdicts())


def run_agent_conveyor(platform, n_chips) -> None:
    """The conveyor tool-overlap A/B stage: can the agent loop hide tool
    execution behind the decode of the constrained stream's tail?

    Random weights cannot drive this (an untrained model never closes
    the JSON fields the launch gate watches), so the stage first trains
    the tiny BPE agent IN-PROCESS to memorization on the
    count-namespaces episode (seconds on CPU: loss < 0.01 typically by
    step ~50), serves the checkpoint, and runs the scripted episode
    ``episodes`` times with conveyor launches ON then OFF against the
    same engine. The replayed kubectl is wrapped with a fixed artificial
    delay (identical in both phases) so the tool has a real execution
    window for the conveyor to overlap with the post-action decode
    (observation/final_answer fields). Decision numbers per phase: p50
    episode wall (one tool-call turn + one final-answer turn — the unit
    "ms/turn" is per scripted tool turn), overlap seconds banked, early
    launch count, byte-identical transcripts across phases (the launch
    is a prefix bet; correctness means it never changes WHAT the agent
    says), and zero post-warmup compiles in both phases."""
    import shutil
    import tempfile

    scripts_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts"
    )
    sys.path.insert(0, scripts_dir)
    try:
        from train_tiny_agent import (
            INSTRUCTION,
            SYS_PROMPT,
            train_checkpoint,
        )
    finally:
        sys.path.remove(scripts_dir)

    from opsagent_tpu import obs
    from opsagent_tpu import tools as tools_pkg
    from opsagent_tpu.agent.react import assistant_with_config
    from opsagent_tpu.serving import api as serving_api
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.tools.replay import (
        NAMESPACES_SCRIPT,
        install_replay_kubectl,
    )

    episodes = int(os.environ.get("OPSAGENT_BENCH_AGENT_EPISODES", "6"))
    train_steps = int(os.environ.get("OPSAGENT_BENCH_TRAIN_STEPS", "600"))
    tool_delay_s = (
        float(os.environ.get("OPSAGENT_BENCH_TOOL_DELAY_MS", "150")) / 1e3
    )
    work = tempfile.mkdtemp(prefix="opsagent-bench-conveyor-")

    # -- train to memorization (the same recipe scripts/train_tiny_agent
    # uses; the BPE tokenizer keeps prompts compact and exercises the
    # HFTokenizer path real checkpoints use) ------------------------------
    ckpt, tok_path, cfg, loss, train_s = train_checkpoint(
        work, steps=train_steps
    )
    log(f"bench[agent-conveyor]: trained to loss {loss:.4f} "
        f"in {train_s:.1f}s")

    # -- serve the checkpoint; pace the replayed kubectl so the tool has
    # an execution window the conveyor can hide --------------------------
    install_replay_kubectl(NAMESPACES_SCRIPT, os.path.join(work, "bin"))
    real_kubectl = tools_pkg.get_tools()["kubectl"]

    def paced_kubectl(arg: str) -> str:
        time.sleep(tool_delay_s)
        return real_kubectl(arg)

    tools_pkg.copilot_tools["kubectl"] = paced_kubectl

    t0 = time.perf_counter()
    eng = Engine(
        EngineConfig(
            model="tiny-test",
            checkpoint=ckpt,
            tokenizer=tok_path,
            dtype=jnp.float32,
            num_pages=512,
            page_size=16,
            max_pages_per_seq=64,
            max_batch_size=2,
            prefill_buckets=(128, 512, 1024),
        ),
        model_cfg=cfg,
    )
    init_s = time.perf_counter() - t0
    # "sessions" warmup pre-specializes the ToolPrompt FSM tables and the
    # forced-token fast-forward program: both phases must decode
    # compile-free.
    warmup_s = eng.warmup("sessions")
    log(f"bench[agent-conveyor]: engine init {init_s:.1f}s "
        f"warmup {warmup_s:.1f}s")

    messages0 = [
        {"role": "system", "content": SYS_PROMPT},
        {"role": "user",
         "content": f"Here are the instructions: {INSTRUCTION}"},
    ]
    conveyor_prev = os.environ.get("OPSAGENT_CONVEYOR")
    phases: dict[str, dict] = {}
    try:
        for tag, on in (("on", True), ("off", False)):
            os.environ["OPSAGENT_CONVEYOR"] = "1" if on else "0"
            get_perf_stats().reset()
            overlap0 = obs.TOOL_OVERLAP_SECONDS.value()
            early0 = obs.TOOL_EARLY_LAUNCHES.value(tool="kubectl")
            compiles0 = obs.POST_WARMUP_COMPILES.value()
            stack = serving_api.ServingStack(eng)
            serving_api.install_stack("bench-conveyor", stack)
            walls: list[float] = []
            transcripts: list[str] = []
            errors: list[str] = []
            try:
                for _ in range(episodes):
                    te = time.perf_counter()
                    try:
                        _answer, history = assistant_with_config(
                            "tpu://bench-conveyor",
                            [dict(m) for m in messages0],
                            256, False, False, 4, "", "",
                        )
                    except Exception as e:  # noqa: BLE001
                        errors.append(str(e))
                        continue
                    walls.append(time.perf_counter() - te)
                    transcripts.append(json.dumps(
                        [(m["role"], m["content"]) for m in history]
                    ))
            finally:
                serving_api.uninstall_stack("bench-conveyor")
                stack.close()
            r = {
                "p50_ms": (
                    float(np.median(walls) * 1e3) if walls else 0.0
                ),
                "overlap_s": obs.TOOL_OVERLAP_SECONDS.value() - overlap0,
                "early_launches": int(
                    obs.TOOL_EARLY_LAUNCHES.value(tool="kubectl") - early0
                ),
                "post_warmup_compiles": int(
                    obs.POST_WARMUP_COMPILES.value() - compiles0
                ),
                "walls": walls,
                "transcripts": transcripts,
                "errors": errors,
            }
            phases[tag] = r
            log(f"bench[agent-conveyor/{tag}]: {len(walls)}/{episodes} "
                f"episodes, p50 {r['p50_ms']:.0f} ms/turn; "
                f"{r['early_launches']} early launches, "
                f"{r['overlap_s'] * 1e3:.0f} ms overlapped; "
                f"post-warmup compiles {r['post_warmup_compiles']}; "
                f"errors={len(errors)}")
    finally:
        if conveyor_prev is None:
            os.environ.pop("OPSAGENT_CONVEYOR", None)
        else:
            os.environ["OPSAGENT_CONVEYOR"] = conveyor_prev
        tools_pkg.copilot_tools["kubectl"] = real_kubectl

    a, b = phases["on"], phases["off"]
    identical = (
        a["transcripts"] == b["transcripts"]
        and not a["errors"] and not b["errors"]
    )
    print(json.dumps({
        "metric": f"agent_conveyor[tiny-agent,{platform}]",
        "value": round(a["p50_ms"], 1),
        "unit": "ms/turn",
        "vs_baseline": None,
        "extra": {
            "episodes": episodes,
            "train_loss": round(loss, 4),
            "train_s": round(train_s, 1),
            "tool_delay_ms": round(tool_delay_s * 1e3, 1),
            "overlap_ms_per_turn": round(
                a["overlap_s"] / max(1, len(a["walls"])) * 1e3, 1
            ),
            "overlap_s_total": round(a["overlap_s"], 4),
            "early_launches": a["early_launches"],
            "off_p50_ms": round(b["p50_ms"], 1),
            "off_overlap_s_total": round(b["overlap_s"], 4),
            "off_early_launches": b["early_launches"],
            "p50_delta_ms": round(b["p50_ms"] - a["p50_ms"], 1),
            "outputs_identical": identical,
            "post_warmup_compiles_on": a["post_warmup_compiles"],
            "post_warmup_compiles_off": b["post_warmup_compiles"],
            "errors": len(a["errors"]) + len(b["errors"]),
            "init_s": round(init_s, 1),
            "warmup_s": round(warmup_s, 1),
            "chips": n_chips,
            "platform": platform,
            "metrics": metrics_snapshot(),
            "attribution": attribution_snapshot(),
            "slo": slo_verdicts(),
        },
    }), flush=True)
    if a["errors"] or b["errors"]:
        log(f"bench[agent-conveyor]: first error: "
            f"{(a['errors'] or b['errors'])[0]}")
    log_perf_table()
    shutil.rmtree(work, ignore_errors=True)
    exit_if_slo_breach(slo_verdicts())


if __name__ == "__main__":
    main()
