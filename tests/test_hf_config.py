"""HF ``config.json`` <-> ModelConfig derivation (models/config.py).

``config_from_hf`` makes any HF llama/qwen2 checkpoint DIRECTORY servable
without a hand-written preset — the engine reads the architecture from
the checkpoint's own metadata, the way the reference reads nothing at all
(its model is a remote API, reference pkg/llms/openai.go:69). The slow
test drives scripts/run_real_checkpoint.py end to end on a synthesized
HF-format directory: config.json + model.safetensors + fast-tokenizer
files, exactly the layout of a real Llama/Qwen release.
"""

import dataclasses
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_hf_config_roundtrip_llama():
    from opsagent_tpu.models.config import (
        RopeScalingConfig,
        config_from_hf,
        get_config_preset,
        hf_config_dict,
    )

    base = get_config_preset("tiny-test")
    cfg = dataclasses.replace(
        base,
        rope_scaling=RopeScalingConfig(
            rope_type="llama3", factor=8.0, original_max_position=8192,
            low_freq_factor=1.0, high_freq_factor=4.0,
        ),
    )
    hf = hf_config_dict(cfg)
    assert hf["model_type"] == "llama"
    # Write to a dir and re-derive.
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "config.json"), "w") as f:
            json.dump(hf, f)
        back = config_from_hf(d, name=cfg.name)
    for fld in ("vocab_size", "hidden_size", "intermediate_size",
                "num_layers", "num_heads", "num_kv_heads", "rope_theta",
                "rms_norm_eps", "attn_bias", "tie_embeddings",
                "max_position", "rope_scaling"):
        assert getattr(back, fld) == getattr(cfg, fld), fld


def test_hf_config_qwen2_and_yarn(tmp_path):
    from opsagent_tpu.models.config import config_from_hf

    hf = {
        "model_type": "qwen2",
        "vocab_size": 1000,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-6,
        "tie_word_embeddings": True,
        "max_position_embeddings": 32768,
        "rope_scaling": {
            "type": "yarn", "factor": 4.0,
            "original_max_position_embeddings": 4096,
            "beta_fast": 32, "beta_slow": 1, "mscale": 1.0,
        },
    }
    with open(tmp_path / "config.json", "w") as f:
        json.dump(hf, f)
    cfg = config_from_hf(str(tmp_path))
    assert cfg.attn_bias  # qwen2 => qkv biases
    assert cfg.tie_embeddings
    assert cfg.rope_scaling.rope_type == "yarn"
    assert cfg.rope_scaling.factor == 4.0
    assert cfg.max_position == 32768


def test_hf_config_rejects_unknown_family(tmp_path):
    from opsagent_tpu.models.config import config_from_hf

    with open(tmp_path / "config.json", "w") as f:
        json.dump({"model_type": "mixtral"}, f)
    with pytest.raises(ValueError, match="mixtral"):
        config_from_hf(str(tmp_path))


def test_hf_config_deepseek_v2_matches_preset(tmp_path):
    """A V2-Lite-shaped config.json derives the SAME ModelConfig the
    hand-written preset carries (which mirrors the HF fields 1:1) — MLA,
    MoE, and YaRN scaling included."""
    from opsagent_tpu.models.config import config_from_hf, get_config_preset

    hf = {
        "model_type": "deepseek_v2",
        "vocab_size": 102400,
        "hidden_size": 2048,
        "intermediate_size": 10944,
        "moe_intermediate_size": 1408,
        "num_hidden_layers": 27,
        "num_attention_heads": 16,
        "num_key_value_heads": 16,
        "rope_theta": 10000.0,
        "rms_norm_eps": 1e-6,
        "max_position_embeddings": 163840,
        "n_routed_experts": 64,
        "num_experts_per_tok": 6,
        "n_shared_experts": 2,
        "first_k_dense_replace": 1,
        "moe_layer_freq": 1,
        "norm_topk_prob": False,
        "scoring_func": "softmax",
        "q_lora_rank": None,
        "kv_lora_rank": 512,
        "qk_nope_head_dim": 128,
        "qk_rope_head_dim": 64,
        "v_head_dim": 128,
        "rope_scaling": {
            "type": "yarn", "factor": 40.0,
            "original_max_position_embeddings": 4096,
            "beta_fast": 32, "beta_slow": 1,
            "mscale": 0.707, "mscale_all_dim": 0.707,
        },
    }
    with open(tmp_path / "config.json", "w") as f:
        json.dump(hf, f)
    cfg = config_from_hf(str(tmp_path))
    want = get_config_preset("deepseek-v2-lite")
    for fld in ("vocab_size", "hidden_size", "intermediate_size",
                "num_layers", "num_heads", "num_kv_heads", "head_dim_",
                "rope_theta", "rms_norm_eps", "max_position",
                "moe_layer_start", "moe", "mla", "rope_scaling"):
        assert getattr(cfg, fld) == getattr(want, fld), fld


def test_hf_config_deepseek_v3_router_fields(tmp_path):
    from opsagent_tpu.models.config import config_from_hf

    hf = {
        "model_type": "deepseek_v3",
        "vocab_size": 129280, "hidden_size": 7168,
        "intermediate_size": 18432, "moe_intermediate_size": 2048,
        "num_hidden_layers": 61, "num_attention_heads": 128,
        "rms_norm_eps": 1e-6, "max_position_embeddings": 163840,
        "n_routed_experts": 256, "num_experts_per_tok": 8,
        "n_shared_experts": 1, "first_k_dense_replace": 3,
        "norm_topk_prob": True, "routed_scaling_factor": 2.5,
        "scoring_func": "sigmoid", "n_group": 8, "topk_group": 4,
        "q_lora_rank": 1536, "kv_lora_rank": 512,
        "qk_nope_head_dim": 128, "qk_rope_head_dim": 64,
        "v_head_dim": 128,
    }
    with open(tmp_path / "config.json", "w") as f:
        json.dump(hf, f)
    cfg = config_from_hf(str(tmp_path))
    assert cfg.moe.scoring_func == "sigmoid"
    assert cfg.moe.norm_topk_prob and cfg.moe.routed_scaling_factor == 2.5
    assert (cfg.moe.n_group, cfg.moe.topk_group) == (8, 4)
    assert cfg.mla.q_lora_rank == 1536 and cfg.mla.latent_cache
    assert cfg.num_kv_heads == 128  # MLA: no GQA
    assert cfg.moe_layer_start == 3
    assert cfg.head_dim_ == 192


@pytest.mark.slow
def test_run_real_checkpoint_script_auto_config(tmp_path):
    """scripts/run_real_checkpoint.py with --model-name auto on a
    synthesized HF-layout dir (config.json drives the architecture): the
    full loader -> engine -> agent-loop -> kubectl-replay path the real
    8B run takes, hermetic on CPU with random weights (the ToolPrompt
    FSM guarantees schema-valid JSON regardless of weights)."""
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from train_tiny_agent import train_bpe_tokenizer

    from opsagent_tpu.models import llama
    from opsagent_tpu.models.config import (
        config_from_hf,
        get_config_preset,
        hf_config_dict,
    )
    from opsagent_tpu.models.loader import save_checkpoint
    from opsagent_tpu.serving.tokenizer import load_tokenizer

    from opsagent_tpu.agent.prompts import REACT_SYSTEM_PROMPT

    ckpt_dir = tmp_path / "tiny-hf-release"
    ckpt_dir.mkdir()
    # Include the real system prompt in the tokenizer corpus so the
    # agent-loop prompt stays a few hundred tokens, not ~12k near-bytes.
    tok_dir = train_bpe_tokenizer(
        str(ckpt_dir), extra_corpus=(REACT_SYSTEM_PROMPT,), vocab_size=2048
    )
    # Real HF releases keep tokenizer files at the dir root.
    for fn in os.listdir(tok_dir):
        shutil.move(os.path.join(tok_dir, fn), ckpt_dir / fn)
    os.rmdir(tok_dir)
    tok = load_tokenizer(str(ckpt_dir))

    cfg = dataclasses.replace(
        get_config_preset("tiny-test"), vocab_size=tok.vocab_size
    )
    with open(ckpt_dir / "config.json", "w") as f:
        json.dump(hf_config_dict(cfg), f)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    save_checkpoint(str(ckpt_dir / "model.safetensors"), params)

    # Sanity: the auto-derived config matches what the weights were built
    # from (name comes from the dir).
    derived = config_from_hf(str(ckpt_dir))
    assert derived.vocab_size == cfg.vocab_size
    assert derived.name == "tiny-hf-release"

    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "run_real_checkpoint.py"),
            "--checkpoint", str(ckpt_dir),
            "--model-name", "auto",
            "--max-iterations", "2",
            # The toy BPE tokenizer (trained on the 2-conv corpus only)
            # spends ~12k tokens on the ReAct system prompt; give the KV
            # pool room for it.
            "--num-pages", "2048",
            "--max-pages-per-seq", "1024",
            "--transcript", str(tmp_path / "transcript.md"),
        ],
        capture_output=True, text=True, timeout=3000, env=env, cwd=REPO,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]
    last = out.stdout.strip().splitlines()[-1]
    assert json.loads(last)["ok"] is True
    assert "config.json -> tiny-hf-release" in out.stderr
    assert (tmp_path / "transcript.md").exists()


def test_hf_config_rejects_unknown_scoring_func(tmp_path):
    from opsagent_tpu.models.config import config_from_hf

    with open(tmp_path / "config.json", "w") as f:
        json.dump({
            "model_type": "deepseek_v3", "vocab_size": 100,
            "hidden_size": 64, "intermediate_size": 128,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "n_routed_experts": 8, "num_experts_per_tok": 2,
            "kv_lora_rank": 16, "qk_nope_head_dim": 8,
            "qk_rope_head_dim": 8, "v_head_dim": 8,
            "scoring_func": "mystery",
        }, f)
    with pytest.raises(ValueError, match="mystery"):
        config_from_hf(str(tmp_path))


def test_resolve_model_policy(tmp_path):
    """One shared resolution policy (serve-engine + run_real_checkpoint):
    presets pass through; auto derives from config.json and the
    checkpoint's own metadata is authoritative even when the dir's
    basename collides with a preset name."""
    from opsagent_tpu.models.config import (
        get_config_preset,
        hf_config_dict,
        resolve_model,
    )

    assert resolve_model("tiny-test") == ("tiny-test", None)
    with pytest.raises(ValueError, match="requires --checkpoint"):
        resolve_model("auto")

    # A dir NAMED like a preset but carrying different dims: the derived
    # config must win (a renamed snapshot / fine-tune with other dims).
    ckpt = tmp_path / "tiny-test"
    ckpt.mkdir()
    cfg = dataclasses.replace(
        get_config_preset("tiny-test"), vocab_size=777, hidden_size=96,
        intermediate_size=192, num_heads=6, num_kv_heads=3, head_dim=0,
    )
    with open(ckpt / "config.json", "w") as f:
        json.dump(hf_config_dict(cfg), f)
    name, derived = resolve_model("auto", str(ckpt))
    assert name == "tiny-test"
    assert derived is not None and derived.vocab_size == 777
    assert derived.hidden_size == 96


def test_restart_factory_keeps_auto_model_cfg():
    """ADVICE-style regression: the slice-restart factory must carry the
    resolved model_cfg — an auto-derived (non-preset) architecture has no
    preset to fall back to, so a recovery rebuild without it would die in
    get_config_preset on the checkpoint-dir name."""
    import dataclasses as dc

    import jax.numpy as jnp

    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.serving.api import ServingStack
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    cfg = dc.replace(get_config_preset("tiny-test"), name="no-such-preset")
    eng = Engine(
        EngineConfig(
            model="no-such-preset", dtype=jnp.float32, tp=1,
            num_pages=16, page_size=8, max_pages_per_seq=4,
            max_batch_size=2, prefill_buckets=(16,),
        ),
        model_cfg=cfg,
    )
    stack = ServingStack(eng)
    try:
        rebuilt = stack.scheduler._engine_factory()
        assert rebuilt.model_cfg.name == "no-such-preset"
    finally:
        stack.close()


def test_hf_config_dict_roundtrips_moe_mla():
    """Export side: a V3-shaped (MLA + sigmoid MoE) and a MoE-only config
    roundtrip through hf_config_dict -> config_from_hf. The only allowed
    delta is mla.latent_cache: derivation always serves V2/V3 with the
    compressed latent pages."""
    from opsagent_tpu.models.config import (
        MLAConfig,
        MoEConfig,
        config_from_hf,
        get_config_preset,
        hf_config_dict,
    )

    v3ish = dataclasses.replace(
        get_config_preset("tiny-mla"),
        num_layers=3,
        moe=MoEConfig(
            num_experts=4, num_experts_per_token=2, num_shared_experts=1,
            expert_intermediate_size=32, norm_topk_prob=True,
            routed_scaling_factor=2.5, scoring_func="sigmoid",
            n_group=2, topk_group=1,
        ),
        moe_layer_start=1,
    )
    moe_only = get_config_preset("tiny-moe")

    import tempfile

    for cfg, want_mt in ((v3ish, "deepseek_v3"), (moe_only, "deepseek")):
        hf = hf_config_dict(cfg)
        assert hf["model_type"] == want_mt
        with tempfile.TemporaryDirectory() as d:
            with open(os.path.join(d, "config.json"), "w") as f:
                json.dump(hf, f)
            back = config_from_hf(d, name=cfg.name)
        assert back.moe == cfg.moe
        if cfg.mla:
            assert back.mla == dataclasses.replace(
                cfg.mla, latent_cache=True
            )
            assert back.num_kv_heads == cfg.num_heads
        for fld in ("vocab_size", "hidden_size", "intermediate_size",
                    "num_layers", "num_heads", "moe_layer_start",
                    "max_position"):
            assert getattr(back, fld) == getattr(cfg, fld), fld


@pytest.mark.slow
def test_run_real_checkpoint_script_deepseek_auto(tmp_path):
    """The auto path on a synthesized DeepSeek-V3-SHAPED release dir:
    config.json (MLA + sigmoid MoE) -> config_from_hf -> loader (HF
    deepseek weight names incl. router e_score_correction_bias) ->
    latent-cache engine -> FSM-constrained agent loop. The same flow a
    real V2-Lite/V3 download takes, at toy scale with random weights.

    The heaviest test in the suite (~10 min solo: full production warmup
    of an MLA MoE engine on CPU). It passes solo reliably but can starve
    past its subprocess timeout when run under a fully loaded
    ``pytest -n`` box — run it in the slow lane / a lightly loaded
    worker, not sandwiched into a saturated parallel session."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from train_tiny_agent import train_bpe_tokenizer
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))

    from opsagent_tpu.agent.prompts import REACT_SYSTEM_PROMPT
    from opsagent_tpu.models import llama
    from opsagent_tpu.models.config import (
        MoEConfig,
        config_from_hf,
        get_config_preset,
        hf_config_dict,
    )
    from opsagent_tpu.models.loader import save_checkpoint
    from opsagent_tpu.serving.tokenizer import load_tokenizer

    ckpt_dir = tmp_path / "tiny-v3-release"
    ckpt_dir.mkdir()
    tok_dir = train_bpe_tokenizer(
        str(ckpt_dir), extra_corpus=(REACT_SYSTEM_PROMPT,), vocab_size=2048
    )
    for fn in os.listdir(tok_dir):
        shutil.move(os.path.join(tok_dir, fn), ckpt_dir / fn)
    os.rmdir(tok_dir)
    tok = load_tokenizer(str(ckpt_dir))

    cfg = dataclasses.replace(
        get_config_preset("tiny-mla"),
        vocab_size=tok.vocab_size,
        num_layers=3,
        max_position=16384,
        moe=MoEConfig(
            num_experts=4, num_experts_per_token=2, num_shared_experts=1,
            expert_intermediate_size=32, norm_topk_prob=True,
            routed_scaling_factor=2.5, scoring_func="sigmoid",
            n_group=2, topk_group=1,
        ),
        moe_layer_start=1,
    )
    with open(ckpt_dir / "config.json", "w") as f:
        json.dump(hf_config_dict(cfg), f)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    params["moe_layers"]["router_bias"] = jnp.asarray(
        np.linspace(-1, 1, 2 * 4).reshape(2, 4), jnp.float32
    )
    save_checkpoint(str(ckpt_dir / "model.safetensors"), params, cfg=cfg)

    derived = config_from_hf(str(ckpt_dir))
    assert derived.mla is not None and derived.mla.latent_cache
    assert derived.moe is not None

    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "run_real_checkpoint.py"),
            "--checkpoint", str(ckpt_dir),
            "--model-name", "auto",
            "--max-iterations", "1",
            "--num-pages", "2048",
            "--max-pages-per-seq", "1024",
            "--transcript", str(tmp_path / "transcript.md"),
        ],
        capture_output=True, text=True, timeout=3000, env=env, cwd=REPO,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]
    last = out.stdout.strip().splitlines()[-1]
    assert json.loads(last)["ok"] is True
    assert "config.json -> tiny-v3-release" in out.stderr


def test_hf_config_dict_preserves_attn_bias_on_moe():
    """A Qwen2-MoE-style config (moe set, attn_bias=True) exports as the
    deepseek family but must keep attention_bias, or the re-imported
    model would silently drop the q/k/v bias params."""
    from opsagent_tpu.models.config import (
        config_from_hf,
        get_config_preset,
        hf_config_dict,
    )

    cfg = dataclasses.replace(get_config_preset("tiny-moe"), attn_bias=True)
    hf = hf_config_dict(cfg)
    assert hf["model_type"] == "deepseek" and hf["attention_bias"] is True
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        with open(os.path.join(d, "config.json"), "w") as f:
            json.dump(hf, f)
        back = config_from_hf(d, name=cfg.name)
    assert back.attn_bias is True


def test_hf_config_mistral_family(tmp_path):
    """Mistral releases are llama-shaped (same weight names, GQA, silu)
    once sliding-window attention is off: v0.3/Nemo-class configs
    (sliding_window: null, explicit head_dim, rope_theta 1e6) must
    derive; a v0.1-class ACTIVE window must be rejected loudly rather
    than served with wrong (full) attention."""
    from opsagent_tpu.models.config import config_from_hf

    hf = {
        "model_type": "mistral",
        "architectures": ["MistralForCausalLM"],
        "vocab_size": 32768,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "head_dim": 32,          # Nemo-style: explicit, != hidden/heads
        "rope_theta": 1000000.0,
        "rms_norm_eps": 1e-5,
        "sliding_window": None,  # v0.3-class: window disabled
        "max_position_embeddings": 32768,
    }
    with open(tmp_path / "config.json", "w") as f:
        json.dump(hf, f)
    cfg = config_from_hf(str(tmp_path))
    assert not cfg.attn_bias          # mistral has no qkv biases
    assert cfg.num_kv_heads == 2      # GQA preserved
    assert cfg.head_dim == 32         # explicit head_dim honored
    assert cfg.rope_theta == 1000000.0

    # A window >= the position window is equivalent to disabled.
    hf["sliding_window"] = 32768
    with open(tmp_path / "config.json", "w") as f:
        json.dump(hf, f)
    assert config_from_hf(str(tmp_path)).num_layers == 2

    # v0.1-class active window: reject, never silently full-attend.
    hf["sliding_window"] = 4096
    with open(tmp_path / "config.json", "w") as f:
        json.dump(hf, f)
    with pytest.raises(ValueError, match="sliding-window"):
        config_from_hf(str(tmp_path))


def test_hf_config_qwen2_sliding_window_gate(tmp_path):
    """Qwen2 carries sliding_window fields gated by use_sliding_window:
    false (every shipped Qwen2.5 release) must derive; true with an
    active window must be rejected like mistral."""
    from opsagent_tpu.models.config import config_from_hf

    hf = {
        "model_type": "qwen2",
        "vocab_size": 1000,
        "hidden_size": 64,
        "intermediate_size": 128,
        "num_hidden_layers": 2,
        "num_attention_heads": 4,
        "num_key_value_heads": 2,
        "max_position_embeddings": 32768,
        "sliding_window": 4096,
        "use_sliding_window": False,
    }
    with open(tmp_path / "config.json", "w") as f:
        json.dump(hf, f)
    assert config_from_hf(str(tmp_path)).num_layers == 2

    hf["use_sliding_window"] = True
    with open(tmp_path / "config.json", "w") as f:
        json.dump(hf, f)
    with pytest.raises(ValueError, match="sliding-window"):
        config_from_hf(str(tmp_path))


def test_hf_config_qwen3_family():
    """Qwen3 derives with qk_norm on, no attn biases, and the explicit
    head_dim honored — against the REAL fixture config.json transformers
    wrote (tests/fixtures/tiny-qwen3-hf), not a hand-mocked dict."""
    from opsagent_tpu.models.config import config_from_hf

    path = os.path.join(REPO, "tests", "fixtures", "tiny-qwen3-hf")
    if not os.path.isdir(path):
        pytest.skip("qwen3 fixture not generated")
    cfg = config_from_hf(path)
    assert cfg.qk_norm
    assert not cfg.attn_bias
    assert cfg.head_dim == 32 and cfg.head_dim_ == 32
    assert cfg.num_kv_heads == 2


def test_hf_config_qwen3_moe_family(tmp_path):
    """Qwen3-MoE derives from the real fixture config (qk_norm + softmax
    top-k MoE, every layer sparse), roundtrips through hf_config_dict's
    qwen3_moe export, and rejects the interleaved-dense layouts the
    stacked tree cannot express."""
    import dataclasses
    import shutil

    from opsagent_tpu.models.config import config_from_hf, hf_config_dict

    src = os.path.join(REPO, "tests", "fixtures", "tiny-qwen3-moe-hf")
    if not os.path.isdir(src):
        pytest.skip("qwen3-moe fixture not generated")
    cfg = config_from_hf(src)
    assert cfg.qk_norm and cfg.moe is not None
    assert cfg.moe.scoring_func == "softmax"
    assert cfg.moe.num_shared_experts == 0
    assert cfg.moe.norm_topk_prob
    assert cfg.moe_layer_start == 0

    out = hf_config_dict(cfg)
    assert out["model_type"] == "qwen3_moe"
    with open(tmp_path / "config.json", "w") as f:
        json.dump(out, f)
    back = config_from_hf(str(tmp_path), name=cfg.name)
    assert dataclasses.asdict(back) == dataclasses.asdict(cfg)

    # Interleaved dense layers: reject, the stacked tree is contiguous.
    with open(os.path.join(src, "config.json")) as f:
        hf = json.load(f)
    hf["mlp_only_layers"] = [1]
    bad = tmp_path / "interleaved"
    bad.mkdir()
    with open(bad / "config.json", "w") as f:
        json.dump(hf, f)
    with pytest.raises(ValueError, match="mlp_only_layers"):
        config_from_hf(str(bad))
