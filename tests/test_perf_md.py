"""PERF.md's measurement table is generated, not hand-maintained: the
committed table must match what scripts/bench_summary.py regenerates
from the committed BENCH_r*_local.jsonl raw lines (VERDICT weak #7 —
three drifting copies of the r04 numbers)."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench_summary  # noqa: E402


def test_perf_md_table_in_sync():
    rc = bench_summary.main(["--update-perf", "--check"])
    assert rc == 0, (
        "PERF.md generated table out of sync; run "
        "`python scripts/bench_summary.py --update-perf`"
    )


def test_perf_md_table_covers_every_committed_line(tmp_path):
    paths = bench_summary._default_local_jsonls()
    assert paths, "no BENCH_r*_local.jsonl committed"
    table = bench_summary.perf_md_table(paths)
    rows = bench_summary._dedupe(bench_summary.load_rows(paths))
    assert rows
    for d in rows:
        assert f"`{d['metric']}`" in table
        assert str(d["value"]) in table


def test_update_rewrites_stale_block(tmp_path):
    stale = (
        "# header\n"
        f"{bench_summary.GEN_BEGIN}\nstale row\n{bench_summary.GEN_END}\n"
        "tail\n"
    )
    p = tmp_path / "PERF.md"
    p.write_text(stale)
    src = tmp_path / "BENCH_r99_local.jsonl"
    src.write_text(
        '{"metric": "m[x,tpu]", "value": 1.0, "unit": "tok/s/chip", '
        '"extra": {"p50_ttft_ms": 9.0, "paged_backend": "xla"}}\n'
    )
    assert bench_summary.update_perf_md(str(p), [str(src)], check=True) == 1
    assert bench_summary.update_perf_md(str(p), [str(src)]) == 0
    out = p.read_text()
    assert "stale row" not in out
    assert "`m[x,tpu]`" in out and "r99" in out
    assert out.startswith("# header\n") and out.endswith("tail\n")
    # Idempotent: a second check now passes.
    assert bench_summary.update_perf_md(str(p), [str(src)], check=True) == 0
