"""One-step-lookahead async mixed ticks (ISSUE-5 acceptance gates).

Covers, on the tiny CPU engine:

- greedy-token EQUIVALENCE of the async pipeline (async_depth=2) vs the
  synchronous tick (depth=1): plain rows, stop-string rows (the one
  overshoot token discarded, no page leak — checked through allocator
  accounting), and constrained rows with dense device FSM tables;
- hosted-mask rows (plain-callable mask_fn) falling back to the sync
  lane — the async pipeline must never dispatch for them;
- ZERO post-warmup XLA compiles across async compositions including the
  carry-chained program's FSM variant (the r04 invariant extended);
- the overlap observables actually firing (overlapped commits,
  device-resident lookahead lanes feeding a prompt's first decode steps
  before the scheduler learns the admission completed).
"""

import jax
import jax.numpy as jnp
import numpy as np

from opsagent_tpu import obs
from opsagent_tpu.serving.constrained import (
    TOOLPROMPT_SCHEMA,
    json_constraint,
)
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Request, Scheduler

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=128, max_pages_per_seq=24, max_batch_size=4,
    prefill_buckets=(8, 16), decode_block=4,
    mixed_buckets=(4, 8, 16), max_step_tokens=32,
)

# Count real XLA compiles process-wide (the same pattern as
# test_mixed_batching): the monitoring event fires once per backend
# compile and never on jit-cache hits; tests diff around their window.
_COMPILES: list[str] = []


def _on_event(name: str, *a, **kw) -> None:
    if name == "/jax/core/compile/backend_compile_duration":
        _COMPILES.append(name)


jax.monitoring.register_event_duration_secs_listener(_on_event)


def _metric(name: str) -> float:
    return float(obs.metrics_snapshot().get(name, 0.0))


def _drain_all(eng, sids):
    live = [s for s in sids if not eng.sequences[s].done]
    while live:
        eng.step_block(sorted(live))
        live = [s for s in live if not eng.sequences[s].done]
    eng.drain()


def _drive_async(eng, decode_sid, admit_sid):
    """Drive the engine's async API directly: one step_mixed_async call
    per tick, chunking ``admit_sid``'s prompt while ``decode_sid`` (when
    given) rides as a decode lane. Returns the decode tokens collected
    from committed results."""
    collected: list[int] = []
    n = 0
    while admit_sid in eng._prefilling or eng.async_pending():
        chunks = {}
        if admit_sid in eng._prefilling:
            done, total = eng.prefill_progress(admit_sid)
            if total - done > 0:
                chunks = {admit_sid: min(total - done, 16)}
        dids = []
        if decode_sid is not None and not eng.sequences[decode_sid].done:
            dids = [decode_sid]
        d_out, p_out = eng.step_mixed_async(dids, chunks)
        if decode_sid is not None:
            collected.extend(d_out.get(decode_sid, []))
        res = p_out.get(admit_sid)
        if isinstance(res, Exception):
            raise res
        n += 1
        assert n < 200, "async driving made no progress"
    return collected


def test_async_scheduler_matches_sync_greedy():
    """End-to-end through the scheduler: concurrent short + multi-chunk
    prompts decoded under the async tick (depth=2) must be
    token-identical to the synchronous (depth=1) oracle — and the async
    pipeline must actually have engaged."""
    prompts = [
        [257, 9, 8, 7],
        [257] + list(range(1, 40)),      # multiple chunks
        [257, 5, 5, 5, 5, 5],
    ]
    budgets = [12, 6, 9]
    sync = Engine(EngineConfig(async_depth=1, **BASE))
    want = [
        sync.generate([p], SamplingParams(max_tokens=n))[0]
        for p, n in zip(prompts, budgets)
    ]

    eng = Engine(EngineConfig(async_depth=2, **BASE))
    c0 = _metric("opsagent_async_commits_total")
    sched = Scheduler(eng)
    sched.start()
    try:
        reqs = [
            sched.submit(Request(p, SamplingParams(max_tokens=n)))
            for p, n in zip(prompts, budgets)
        ]
        for r in reqs:
            assert r.done.wait(180)
            assert not r.error, r.error
        assert [r.tokens for r in reqs] == want
    finally:
        sched.stop()
    assert _metric("opsagent_async_commits_total") > c0


def test_async_direct_matches_sync_and_overlaps():
    """Engine-level: driving admission through step_mixed_async while a
    decode lane rides along must reproduce both sequences' synchronous
    generations exactly; the decode lane advances DURING admission via
    the device-resident carry, and at depth 2 at least one commit's host
    work runs while a newer dispatch is in flight."""
    short = [257, 9, 8, 7]
    long_prompt = [257] + list(range(1, 40))
    sync = Engine(EngineConfig(async_depth=1, **BASE))
    want_short = sync.generate([short], SamplingParams(max_tokens=12))[0]
    want_long = sync.generate([long_prompt], SamplingParams(max_tokens=6))[0]

    eng = Engine(EngineConfig(async_depth=2, **BASE))
    ov0 = _metric("opsagent_async_overlapped_commits_total")
    a = eng.add_request(short, SamplingParams(max_tokens=12))
    b = eng.begin_request(long_prompt, SamplingParams(max_tokens=6))
    collected = list(eng.sequences[a].tokens)
    collected += _drive_async(eng, a, b)
    # The decode lane advanced during admission (lookahead piggybacking).
    assert len(collected) > 1
    _drain_all(eng, [a, b])
    assert eng.finish(a) == want_short
    assert eng.finish(b) == want_long
    assert _metric("opsagent_async_overlapped_commits_total") > ov0


def test_async_stop_string_overshoot_discarded_no_page_leak():
    """Stop-string detection lags one tick under the lookahead: the
    finished row's overshoot token must be DISCARDED (tokens identical
    to the synchronous oracle, finish_reason 'stop') and its page
    booking rolled back — page conservation holds and no pages stay
    owned after finish."""
    prompt = [257, 9, 8, 7]
    sync = Engine(EngineConfig(async_depth=1, **BASE))
    free_run = sync.generate([prompt], SamplingParams(max_tokens=12))[0]
    tok = sync.tokenizer
    # Derive a stop string by first-occurrence scan over the unstopped
    # oracle (the test_engine technique): the decoded text of the first
    # token whose text has not appeared earlier, at index >= 2 so the
    # stop triggers mid-generation with ticks still in flight.
    stop_text = None
    for j in range(2, len(free_run) - 1):
        t = tok.decode([free_run[j]])
        if t and t not in tok.decode(free_run[:j]):
            stop_text = t
            break
    assert stop_text is not None, "no derivable stop string"
    sampling = SamplingParams(max_tokens=12, stop=(stop_text,))
    want = sync.generate([prompt], sampling)[0]
    assert len(want) < len(free_run)  # the stop actually bites

    eng = Engine(EngineConfig(async_depth=2, **BASE))
    acc0 = eng.alloc.accounting()
    o0 = _metric("opsagent_async_overshoot_tokens_total")
    sid = eng.add_request(prompt, sampling)
    n = 0
    while not eng.sequences[sid].done:
        eng.step_mixed_async([sid], {})
        n += 1
        assert n < 100
    eng.drain()
    got = eng.finish(sid)
    assert got == want
    # The tick after the stop token's was already dispatched when the
    # stop committed: its token must have been discarded.
    assert _metric("opsagent_async_overshoot_tokens_total") > o0
    acc1 = eng.alloc.accounting()
    assert acc1["total"] == acc0["total"] == BASE["num_pages"]
    assert acc1["owned"] == 0, acc1


def test_async_constrained_device_tables_equivalence():
    """A JsonConstraint whose FSM has dense device tables rides the
    async lane (mask from on-device state) and must generate exactly the
    synchronous hosted-mask oracle's tokens; the async pipeline must
    have engaged for the tick to count."""
    p_con = [257, 3, 1, 4]
    p_plain = [257] + list(range(1, 30))

    def run(depth):
        eng = Engine(EngineConfig(async_depth=depth, **BASE))
        assert json_constraint(
            eng.tokenizer, TOOLPROMPT_SCHEMA
        ).fsm.dense_tables() is not None
        sched = Scheduler(eng)
        sched.start()
        try:
            rc = sched.submit(Request(
                p_con, SamplingParams(max_tokens=24),
                mask_fn=json_constraint(eng.tokenizer, TOOLPROMPT_SCHEMA),
            ))
            rp = sched.submit(Request(p_plain, SamplingParams(max_tokens=8)))
            assert rc.done.wait(180) and rp.done.wait(180)
            assert not rc.error and not rp.error, (rc.error, rp.error)
        finally:
            sched.stop()
        return rc.tokens, rp.tokens

    want_con, want_plain = run(1)
    c0 = _metric("opsagent_async_commits_total")
    got_con, got_plain = run(2)
    assert got_con == want_con
    assert got_plain == want_plain
    assert _metric("opsagent_async_commits_total") > c0


def test_hosted_mask_rows_fall_back_to_sync_lane():
    """A plain-callable mask (no dense device tables) must route every
    involved tick to the sync lanes: zero async dispatches, a recorded
    'hosted' fallback, and a correct result."""
    eng = Engine(EngineConfig(async_depth=2, **BASE))
    sync = Engine(EngineConfig(async_depth=1, **BASE))
    prompt = [257, 3, 1, 4, 1, 5]
    want = sync.generate([prompt], SamplingParams(max_tokens=6))[0]

    def mask_all(generated):
        # Allow-all: constrains nothing, so the unconstrained oracle
        # applies — but the ENGINE cannot know it is trivial.
        return np.ones((eng.model_cfg.vocab_size,), bool)

    c0 = _metric("opsagent_async_commits_total")
    f0 = _metric('opsagent_async_fallbacks_total{reason="hosted"}')
    sched = Scheduler(eng)
    sched.start()
    try:
        r = sched.submit(Request(
            prompt, SamplingParams(max_tokens=6), mask_fn=mask_all
        ))
        assert r.done.wait(180)
        assert not r.error, r.error
        assert r.tokens == want
    finally:
        sched.stop()
    assert _metric("opsagent_async_commits_total") == c0
    assert _metric('opsagent_async_fallbacks_total{reason="hosted"}') > f0


def test_zero_compiles_after_warmup_across_async_compositions():
    """The r04 invariant extended to the carry-chained async program:
    after a full warmup, NO async composition — varying decode-lane
    counts, chunk sizes across every bucket, lookahead lanes, stop
    strings, a dense-table constrained row (the warmup-pre-specialized
    ToolPrompt schema) — may trigger an XLA compile."""
    eng = Engine(EngineConfig(async_depth=2, **BASE))
    eng.warmup("full")
    n0 = len(_COMPILES)
    rng = np.random.default_rng(3)
    sids: list[int] = []
    for i, plen in enumerate((3, 7, 13, 21, 37)):
        prompt = [257] + [int(t) for t in rng.integers(1, 400, plen - 1)]
        mask = (
            json_constraint(eng.tokenizer, TOOLPROMPT_SCHEMA)
            if i == 2 else None
        )
        stop = ("zq!7",) if i == 3 else ()   # never generated: max_tokens ends it
        b = eng.begin_request(
            prompt, SamplingParams(max_tokens=6, stop=stop), mask_fn=mask
        )
        while b in eng._prefilling or eng.async_pending():
            chunks = {}
            if b in eng._prefilling:
                done, total = eng.prefill_progress(b)
                if total - done > 0:
                    chunks = {b: min(total - done, 16)}
            lanes = [s for s in sids if not eng.sequences[s].done][:2]
            eng.step_mixed_async(lanes, chunks)
        sids.append(b)
    _drain_all(eng, sids)
    for s in sids:
        eng.finish(s)
    assert len(_COMPILES) == n0, (
        f"{len(_COMPILES) - n0} post-warmup compiles in async dispatches"
    )


def test_depth_one_routes_to_sync_tick():
    """async_depth=1 is 'today's behavior': the scheduler's mixed tick
    runs the synchronous step_mixed path and the async pipeline never
    dispatches."""
    eng = Engine(EngineConfig(async_depth=1, **BASE))
    c0 = _metric("opsagent_async_commits_total")
    m0 = _metric('opsagent_decode_dispatches_total{kind="mixed"}')
    sched = Scheduler(eng)
    sched.start()
    try:
        r = sched.submit(Request(
            [257] + list(range(1, 20)), SamplingParams(max_tokens=4)
        ))
        assert r.done.wait(180)
        assert not r.error, r.error
    finally:
        sched.stop()
    assert _metric("opsagent_async_commits_total") == c0
    assert _metric('opsagent_decode_dispatches_total{kind="mixed"}') > m0
