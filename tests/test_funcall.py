"""Tests for the native function-calling agent loop and workflows."""

import json

from opsagent_tpu.agent.funcall import AgentFunction, run_function_agent
from opsagent_tpu.llm.client import ChatClient
from opsagent_tpu.workflows import analysis_flow, generator_flow


def echo_function(log):
    return AgentFunction(
        name="kubectl",
        description="run kubectl",
        parameters={
            "type": "object",
            "properties": {"command": {"type": "string"}},
            "required": ["command"],
        },
        fn=lambda command: (log.append(command), f"ran: {command}")[1],
    )


def tool_call_msg(name, args, call_id="call_1"):
    return {
        "role": "assistant",
        "content": None,
        "tool_calls": [
            {
                "id": call_id,
                "type": "function",
                "function": {"name": name, "arguments": json.dumps(args)},
            }
        ],
    }


def test_function_agent_roundtrip(scripted_llm):
    log = []
    fake = scripted_llm(
        [
            tool_call_msg("kubectl", {"command": "get pods"}),
            {"role": "assistant", "content": "2 pods are running."},
        ]
    )
    client = ChatClient(api_key="k", base_url="")
    out, history = run_function_agent(
        client, "fake://m", "instructions", "how many pods?", [echo_function(log)]
    )
    assert out == "2 pods are running."
    assert log == ["get pods"]
    tool_msg = fake.requests[1]["messages"][-1]
    assert tool_msg["role"] == "tool"
    assert tool_msg["content"] == "ran: get pods"
    assert tool_msg["tool_call_id"] == "call_1"
    # tool schemas were offered
    assert fake.requests[0]["tools"][0]["function"]["name"] == "kubectl"


def test_function_agent_unknown_function(scripted_llm):
    fake = scripted_llm(
        [
            tool_call_msg("helm", {}),
            {"role": "assistant", "content": "ok"},
        ]
    )
    client = ChatClient(api_key="k")
    out, _ = run_function_agent(client, "fake://m", "i", "u", [])
    assert out == "ok"
    assert "not available" in fake.requests[1]["messages"][-1]["content"]


def test_function_agent_bad_arguments(scripted_llm):
    log = []
    fake = scripted_llm(
        [
            {
                "role": "assistant",
                "content": None,
                "tool_calls": [
                    {
                        "id": "c",
                        "type": "function",
                        "function": {"name": "kubectl", "arguments": "{broken"},
                    }
                ],
            },
            {"role": "assistant", "content": "done"},
        ]
    )
    client = ChatClient(api_key="k")
    out, _ = run_function_agent(client, "fake://m", "i", "u", [echo_function(log)])
    assert out == "done"
    assert "invalid function arguments" in fake.requests[1]["messages"][-1]["content"]
    assert log == []


def test_analysis_flow(scripted_llm):
    fake = scripted_llm([{"role": "assistant", "content": "Looks fine."}])
    client = ChatClient(api_key="k")
    out = analysis_flow("fake://m", "kind: Pod\nmetadata:\n  name: x", client=client)
    assert out == "Looks fine."
    sent = fake.requests[0]["messages"][1]["content"]
    assert "kind: Pod" in sent


def test_generator_flow_no_tools(scripted_llm):
    fake = scripted_llm(
        [{"role": "assistant", "content": "```yaml\nkind: Deployment\n```"}]
    )
    client = ChatClient(api_key="k")
    out = generator_flow("fake://m", "an nginx deployment", client=client)
    assert "Deployment" in out
    assert "tools" not in fake.requests[0]
