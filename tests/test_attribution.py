"""Goodput-ledger attribution tests: the static roofline cost model's
closed-form arithmetic (checked against independent hand arithmetic, the
acceptance criterion), the /metrics exposure of the opsagent_attr_*
split, drift tracking, and the engine integration (every dispatch kind
feeds the ledger without touching device state)."""

import jax.numpy as jnp

from opsagent_tpu import obs
from opsagent_tpu.obs import attribution
from opsagent_tpu.obs.attribution import Attribution, prefill_attn_positions


def _bench8b_int8() -> Attribution:
    # The PERF.md worked example: bench-8b (Llama-3-8B architecture)
    # served weight-only int8 with bf16 KV pages.
    from opsagent_tpu.models.config import get_config_preset

    cfg = get_config_preset("bench-8b")
    return Attribution(
        num_params=cfg.num_params(),
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        num_kv_heads=cfg.num_kv_heads,
        head_dim=cfg.head_dim_,
        vocab_size=cfg.vocab_size,
        dtype_bytes=2,
        quantize="int8",
    )


def test_closed_form_weight_stream_matches_hand_arithmetic():
    """Independent arithmetic for the 8B int8 weight stream (the PERF.md
    roofline's ~8 GB + 2 % scales), computed from the published
    architecture numbers, must equal the model's coefficient."""
    d, f, v, L = 4096, 14336, 128256, 32
    q_size = 32 * 128          # num_heads * head_dim
    kv_size = 8 * 128          # num_kv_heads * head_dim
    per_layer = (
        d * q_size + 2 * d * kv_size + q_size * d   # attention projections
        + 3 * d * f                                  # SwiGLU mlp
        + 2 * d                                      # rms norms
    )
    params = L * per_layer + 2 * v * d + d           # + embed/lm_head/final
    a = _bench8b_int8()
    assert a.num_params == params
    assert abs(params / 1e9 - 8.03) < 0.01           # the 8B class
    assert a.weight_stream_bytes == params * 1.02    # int8 + 2% scales
    # At the v5e default 820 GB/s this is the ~10 ms/step weight floor
    # PERF.md's 16.9 ms/step measurement sits on.
    floor_ms = a.weight_stream_bytes / 820e9 * 1e3
    assert 9.5 < floor_ms < 10.5


def test_closed_form_kv_and_dispatch_totals():
    """One decode dispatch's modeled byte split must equal first-
    principles arithmetic: B=32 rows, 384 attended tokens each, GQA-8
    heads of dim 128, bf16 pages, 32 layers."""
    a = _bench8b_int8()
    kv_per_token = 32 * 2 * 8 * 128 * 2   # L * (k+v) * kv_heads * dim * bf16
    assert a.kv_token_bytes == kv_per_token
    B, ctx = 32, 384
    c = a.cost(
        q_tokens=B,
        kv_read_tokens=B * ctx,
        kv_write_tokens=B,
        attn_q_ctx=B * ctx,
    )
    assert c["weights"] == a.weight_stream_bytes
    assert c["kv_read"] == B * ctx * kv_per_token
    assert c["kv_write"] == B * kv_per_token
    assert c["other"] == B * 128256 * 4   # f32 logits per sampled row
    assert c["total"] == (
        c["weights"] + c["kv_read"] + c["kv_write"] + c["other"]
    )
    assert abs(c["modeled_s"] - c["total"] / 820e9) < 1e-12
    # FLOPs: 2*P per processed token + the exact attention terms.
    assert c["flops"] == (
        2.0 * a.num_params * B + 4.0 * 32 * 128 * 32 * (B * ctx)
    )


def test_kv_int8_and_int4_coefficients():
    from opsagent_tpu.models.config import get_config_preset

    cfg = get_config_preset("bench-8b")
    a8 = Attribution(
        num_params=cfg.num_params(), num_layers=32, num_heads=32,
        num_kv_heads=8, head_dim=128, vocab_size=cfg.vocab_size,
        dtype_bytes=2, quantize="int4", kv_quantize="int8",
    )
    # int4: packed nibble + f32 scale per 128-group.
    assert a8.weight_stream_bytes == cfg.num_params() * (0.5 + 4.0 / 128.0)
    # int8 KV: 1 byte per element + one f32 scale per token per head per
    # k/v plane.
    assert a8.kv_token_bytes == 32 * 2 * 8 * (128 + 4)


def test_prefill_attn_positions_exact_causal_sum():
    # chunk of 4 starting at 10: queries attend 11, 12, 13, 14 positions.
    assert prefill_attn_positions(10, 4) == 11 + 12 + 13 + 14
    assert prefill_attn_positions(0, 1) == 1
    assert prefill_attn_positions(0, 0) == 0


def test_dispatch_updates_metrics_and_drift():
    a = _bench8b_int8()
    c = a.dispatch(
        "single", q_tokens=32, kv_read_tokens=32 * 384,
        kv_write_tokens=32, attn_q_ctx=32 * 384,
        measured_s=0.0169,
    )
    # Counters carry the modeled split; /metrics exposes every family.
    assert attribution.ATTR_BYTES.value(kind="weights") == c["weights"]
    assert attribution.ATTR_BYTES.value(kind="kv_read") == c["kv_read"]
    assert attribution.ATTR_DISPATCHES.value(op="single") == 1
    # Measured 16.9 ms vs the ~12 ms modeled floor: drift > 1 (the r04
    # finding — kernels sit above the pure-bytes roofline).
    drift = attribution.ATTR_MODEL_DRIFT.value()
    assert 1.0 < drift < 2.0
    text = obs.metrics_text()
    for family in (
        "opsagent_attr_bytes_total",
        "opsagent_attr_step_bytes",
        "opsagent_attr_dispatches_total",
        "opsagent_attr_modeled_step_seconds",
        "opsagent_attr_measured_step_seconds",
        "opsagent_attr_model_drift_ratio",
        "opsagent_attr_mfu",
        "opsagent_attr_hbm_utilization",
    ):
        assert family in text, family
    # Rate gauges engage from the second window point.
    a.dispatch("single", q_tokens=32, kv_read_tokens=32 * 384,
               kv_write_tokens=32, attn_q_ctx=32 * 384)
    assert attribution.ATTR_HBM_UTIL.value() > 0.0
    assert attribution.ATTR_MFU.value() > 0.0


def test_goodput_counter_and_snapshot():
    attribution.record_goodput(0.25, "decode_active")
    attribution.record_goodput(0.10, "tool_blocked")
    attribution.record_goodput(-1.0, "queued")  # ignored, never negative
    assert attribution.GOODPUT_SECONDS.value(phase="decode_active") == 0.25
    assert attribution.GOODPUT_SECONDS.value(phase="tool_blocked") == 0.10
    assert attribution.GOODPUT_SECONDS.value(phase="queued") == 0.0
    assert "opsagent_goodput_seconds_total" in obs.metrics_text()
    a = _bench8b_int8()
    a.dispatch("mixed", q_tokens=4, kv_read_tokens=40, kv_write_tokens=4,
               attn_q_ctx=40)
    snap = a.snapshot()
    assert snap["dispatches"] == 1
    assert snap["bytes_total"] > 0
    assert set(snap["bytes_by_kind"]) == {
        "weights", "weights_prefetch", "kv_read", "kv_write", "other",
    }


def test_engine_dispatches_feed_the_ledger():
    """Every engine dispatch path prices itself: admission prefill,
    block decode, the single fused step, and the mixed tick all land in
    opsagent_attr_dispatches_total without any device-side change."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=128, max_pages_per_seq=16, max_batch_size=4,
        prefill_buckets=(32,), max_new_tokens_default=8,
    )
    eng = Engine(cfg)
    assert attribution.current() is eng.attr

    # Admission prefill + pipelined block decode.
    sid = eng.add_request([257, 1, 2, 3], SamplingParams(max_tokens=4))
    while not eng.sequences[sid].done:
        eng.step_block([sid])
    eng.drain()
    eng.finish(sid)
    assert attribution.ATTR_DISPATCHES.value(op="prefill_chunk") >= 1
    assert attribution.ATTR_DISPATCHES.value(op="block") >= 1
    assert attribution.ATTR_BYTES.value(kind="weights") > 0
    assert attribution.ATTR_BYTES.value(kind="kv_read") > 0
    assert attribution.ATTR_BYTES.value(kind="kv_write") > 0

    # The fused single step (hosted rows' path).
    sid = eng.add_request([257, 5, 6, 7], SamplingParams(max_tokens=2))
    if not eng.sequences[sid].done:
        eng.step([sid])
    eng.finish(sid)
    assert attribution.ATTR_DISPATCHES.value(op="single") >= 1
    # The single step is synchronously pulled, so it feeds the drift
    # measurement too.
    assert attribution.ATTR_MEASURED_STEP_SECONDS.count(op="single") >= 1

    # Mixed prefill+decode tick.
    d_sid = eng.add_request([257, 8, 9, 10], SamplingParams(max_tokens=8))
    p_sid = eng.begin_request([257, 11, 12, 13], SamplingParams(max_tokens=2))
    eng.step_mixed([d_sid], {p_sid: 3})
    assert attribution.ATTR_DISPATCHES.value(op="mixed") >= 1


def test_engine_attribution_closed_form_agreement():
    """The acceptance check: a known dispatch composition's counter
    deltas equal the cost model's closed-form arithmetic computed from
    the tiny-test config by hand."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    cfg = EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=128, max_pages_per_seq=16, max_batch_size=4,
        prefill_buckets=(32,), max_new_tokens_default=8,
    )
    eng = Engine(cfg)
    w0 = attribution.ATTR_BYTES.value(kind="weights")
    r0 = attribution.ATTR_BYTES.value(kind="kv_read")
    wr0 = attribution.ATTR_BYTES.value(kind="kv_write")
    prompt = [257, 1, 2, 3, 4, 5]     # 6 tokens -> one 32-bucket chunk
    eng.add_request(prompt, SamplingParams(max_tokens=2))
    # tiny-test: 2 layers, 2 kv heads, head_dim 64/4=16, f32 pages.
    kv_token = 2 * 2 * 2 * 16 * 4
    assert (
        attribution.ATTR_BYTES.value(kind="weights") - w0
        == eng.attr.weight_stream_bytes
    )
    assert attribution.ATTR_BYTES.value(kind="kv_read") - r0 == 6 * kv_token
    assert attribution.ATTR_BYTES.value(kind="kv_write") - wr0 == 6 * kv_token
