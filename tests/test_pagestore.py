"""Fleet-global KV page store (serving/fleet/pagestore): the directory
over heartbeat digests, the peer-to-peer fault-in client, and the tier
order HBM trie -> host pool -> peer fetch -> re-prefill.

The acceptance gates (ISSUE 12): (a) a session started on replica A
whose next turn is forced onto replica B — with zero affinity help —
faults the chain in through the directory and produces greedy output
byte-identical to the never-moved run, with
``opsagent_pagestore_remote_hits_total`` increasing; (b) under an
injected ``pagestore.fetch_timeout`` the same request completes via
local re-prefill with no client-visible error; (c) stale directory rows
(peer evicted the chain between heartbeat and fetch) are evicted, never
retried.
"""

import asyncio
import urllib.error

import jax.numpy as jnp
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu import obs
from opsagent_tpu.serving import faults
from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.fleet.pagestore import (
    PageDirectory,
    PageStoreClient,
)
from opsagent_tpu.serving.fleet.registry import (
    ReplicaInfo,
    ReplicaRegistry,
)
from opsagent_tpu.serving.fleet.router import (
    FleetRouter,
    build_router_app,
)
from opsagent_tpu.serving.fleet.transfer import (
    pack_entries,
    records_nbytes,
)
from opsagent_tpu.serving.offload.pool import HostPagePool, chain_key_hex

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=256, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(16, 32, 64), decode_block=4, seed=0,
    offload=True,
)


def _close(stacks):
    for s in stacks:
        s.close()


# -- directory ----------------------------------------------------------------
class TestPageDirectory:
    def test_update_owners_freshest_first(self):
        d = PageDirectory()
        d.update("a", ["k1", "k2"])
        d.update("b", ["k2", "k3"])
        out = d.owners(["k1", "k2", "k3", "k4"])
        assert out["k1"] == ["a"]
        # b advertised k2 after a: freshest advertisement ranks first.
        assert out["k2"] == ["b", "a"]
        assert out["k3"] == ["b"]
        assert "k4" not in out
        st = d.stats()
        assert st["chains"] == 3 and st["replicas"] == 2
        assert st["hits"] == 3 and st["misses"] == 1

    def test_update_is_wholesale_replacement(self):
        d = PageDirectory()
        d.update("a", ["k1", "k2"])
        d.update("a", ["k2", "k3"])  # heartbeat: k1 aged out of the pool
        out = d.owners(["k1", "k2", "k3"])
        assert "k1" not in out and out["k2"] == ["a"]
        d.update("a", [])            # drained replica advertises nothing
        assert d.owners(["k2", "k3"]) == {}
        assert d.stats()["chains"] == 0

    def test_remove_replica_keeps_other_owners(self):
        d = PageDirectory()
        d.update("a", ["k1", "k2"])
        d.update("b", ["k2"])
        assert d.remove_replica("a") == 2
        out = d.owners(["k1", "k2"])
        assert "k1" not in out and out["k2"] == ["b"]
        assert d.remove_replica("ghost") == 0

    def test_invalidate_evicts_single_row(self):
        d = PageDirectory()
        d.update("a", ["k1", "k2"])
        assert d.invalidate("k1", "a")
        assert not d.invalidate("k1", "a")  # already gone
        out = d.owners(["k1", "k2"])
        # Only the stale row died; the replica's other rows stay valid.
        assert "k1" not in out and out["k2"] == ["a"]
        assert d.stats()["stale_evictions"] == 1

    def test_snapshot_rows_and_truncation(self):
        d = PageDirectory()
        d.update("a", [f"k{i}" for i in range(5)])
        snap = d.snapshot(limit=3)
        assert len(snap["rows"]) == 3 and snap["truncated"]
        row = snap["rows"][0]
        assert row["owners"][0]["id"] == "a"
        assert row["owners"][0]["age_s"] >= 0


# -- registry feeds the directory ---------------------------------------------
class TestRegistryDirectory:
    def test_register_heartbeat_and_deregister_update_directory(self):
        reg = ReplicaRegistry()
        reg.register(
            ReplicaInfo(replica_id="a", url="http://x", digests={"k1"})
        )
        assert reg.directory.owners(["k1"])["k1"] == ["a"]
        reg.heartbeat("a", digests=["k2"])
        out = reg.directory.owners(["k1", "k2"])
        assert "k1" not in out and out["k2"] == ["a"]
        reg.deregister("a")
        assert reg.directory.owners(["k2"]) == {}

    def test_reap_invalidates_directory(self):
        import time

        reg = ReplicaRegistry(ttl_s=0.2)
        reg.register(
            ReplicaInfo(replica_id="a", url="http://x", digests={"k1"})
        )
        time.sleep(0.3)
        reg.alive()  # reap pass
        assert reg.get("a") is None
        assert reg.directory.owners(["k1"]) == {}

    def test_drain_removes_and_undrain_restores(self):
        reg = ReplicaRegistry()
        reg.register(
            ReplicaInfo(replica_id="a", url="http://x", digests={"k1"})
        )
        reg.set_draining("a")
        assert reg.directory.owners(["k1"]) == {}
        reg.set_draining("a", False)
        assert reg.directory.owners(["k1"])["k1"] == ["a"]


# -- router HTTP surface: directory routes ------------------------------------
def test_directory_http_endpoints_round_trip():
    """POST /fleet/directory/lookup (the fault-in client's resolver:
    owners WITH urls, asker excluded, draining skipped) and GET
    /api/fleet/directory (the ``opsagent fleet-kv`` operator view)."""
    router = FleetRouter()
    app = build_router_app(router)

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post("/fleet/register", json={
                "replica_id": "remote-1", "url": "http://127.0.0.1:1",
                "model": "tiny-test", "capacity": 2, "page_size": 4,
                "digests": ["aa", "bb"],
            })
            assert r.status == 200
            r = await client.post("/fleet/register", json={
                "replica_id": "remote-2", "url": "http://127.0.0.1:2",
                "model": "tiny-test", "capacity": 2, "page_size": 4,
                "digests": ["bb"],
            })
            assert r.status == 200

            r = await client.post(
                "/fleet/directory/lookup", json={"keys": ["aa", "zz"]}
            )
            assert r.status == 200
            owners = (await r.json())["owners"]
            assert owners["aa"] == [
                {"id": "remote-1", "url": "http://127.0.0.1:1"}
            ]
            assert "zz" not in owners

            # The asking replica is excluded: a replica never fetches
            # from itself.
            r = await client.post(
                "/fleet/directory/lookup?replica=remote-1",
                json={"keys": ["aa"]},
            )
            assert (await r.json())["owners"] == {}

            # Both owners of a shared chain, then drain one: it stops
            # being advertised as a fault-in source.
            r = await client.post(
                "/fleet/directory/lookup", json={"keys": ["bb"]}
            )
            ids = {o["id"] for o in (await r.json())["owners"]["bb"]}
            assert ids == {"remote-1", "remote-2"}
            router.registry.set_draining("remote-2")
            r = await client.post(
                "/fleet/directory/lookup", json={"keys": ["bb"]}
            )
            ids = {o["id"] for o in (await r.json())["owners"]["bb"]}
            assert ids == {"remote-1"}

            r = await client.post(
                "/fleet/directory/lookup", data=b"not json"
            )
            assert r.status == 400

            r = await client.get("/api/fleet/directory?limit=1")
            assert r.status == 200
            snap = await r.json()
            assert snap["stats"]["chains"] >= 1
            assert len(snap["rows"]) == 1 and snap["truncated"]
            rep = {row["id"]: row for row in snap["replicas"]}
            assert rep["remote-1"]["digest_count"] == 2
            assert rep["remote-2"]["state"] == "draining"

            # The router /healthz carries the directory stats block.
            r = await client.get("/healthz")
            assert "directory" in (await r.json())
        finally:
            await client.close()

    asyncio.new_event_loop().run_until_complete(scenario())


def test_fleet_kv_cli_renders_directory(capsys, monkeypatch):
    """``opsagent fleet-kv --url <router>``: the operator's view of the
    fleet page directory, fetched over urllib from a real port."""
    import sys as _sys
    import threading

    from opsagent_tpu.cli.main import main as cli_main

    router = FleetRouter()
    router.registry.register(ReplicaInfo(
        replica_id="remote-1", url="http://127.0.0.1:1",
        digests={"aa" * 16, "bb" * 16},
    ))
    app = build_router_app(router)
    loop = asyncio.new_event_loop()
    box = {}

    async def _start():
        from aiohttp import web

        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        box["runner"] = runner
        box["port"] = runner.addresses[0][1]

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    asyncio.run_coroutine_threadsafe(_start(), loop).result(timeout=30)
    try:
        url = f"http://127.0.0.1:{box['port']}"
        monkeypatch.setattr(
            _sys, "argv", ["opsagent", "fleet-kv", "--url", url]
        )
        assert cli_main() == 0
        out = capsys.readouterr().out
        assert "directory: 2 chains" in out
        assert "remote-1" in out
        # --json prints the raw snapshot.
        monkeypatch.setattr(
            _sys, "argv",
            ["opsagent", "fleet-kv", "--url", url, "--json"],
        )
        assert cli_main() == 0
        import json as _json

        snap = _json.loads(capsys.readouterr().out)
        assert snap["stats"]["chains"] == 2
        # Unreachable router: clean error on stderr, exit 1.
        monkeypatch.setattr(
            _sys, "argv",
            ["opsagent", "fleet-kv", "--url", "http://127.0.0.1:9"],
        )
        assert cli_main() == 1
        assert "directory fetch failed" in capsys.readouterr().err
    finally:
        async def _stop():
            await box["runner"].cleanup()

        asyncio.run_coroutine_threadsafe(_stop(), loop).result(timeout=30)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)


# -- fault-in client (stubbed peers) ------------------------------------------
def _source_pool():
    """A peer's host pool holding a 3-page chain, plus the matching
    records template."""
    pool = HostPagePool(page_size=4, capacity_bytes=1 << 20)
    toks = list(range(500, 512))
    rng = np.random.default_rng(0)
    for i in range(3):
        tree = {
            "k": rng.standard_normal((2, 4, 1, 8)).astype(np.float32),
            "v": rng.standard_normal((2, 4, 1, 8)).astype(np.float32),
        }
        assert pool.put(toks[: (i + 1) * 4], tree)
    return pool, toks


def _template():
    return {"k": np.zeros((1,)), "v": np.zeros((1,))}


def _client(dst, lookup, fetch, **kw):
    return PageStoreClient(
        self_id="me", page_size=4, pool=dst, template=_template,
        lookup=lookup, fetch=fetch, **kw,
    )


class TestPageStoreClient:
    def test_fault_in_lands_chain_in_local_pool(self):
        src, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=lambda o, t, sp, ts: pack_entries(
                src.match(t, start_page=sp)
            ),
        )
        assert c.fault_in(toks, start_page=0) == 3
        assert len(dst.match(toks)) == 3
        assert set(dst.digests()) == set(src.digests())
        assert c.stats()["remote_hit_pages"] == 3
        assert c.stats()["fallbacks"] == 0

    def test_partial_chain_fetch_starts_past_local_pages(self):
        src, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=lambda o, t, sp, ts: pack_entries(
                src.match(t, start_page=sp)
            ),
        )
        # Pages 0..1 already local (trie/pool tier): only page 2 fetches.
        assert c.fault_in(toks, start_page=2) == 1
        assert dst.num_pages == 1

    def test_self_is_never_a_peer(self):
        _, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "me"}] for k in keys},
            fetch=lambda o, t, sp, ts: pytest.fail("fetched from self"),
        )
        assert c.fault_in(toks, start_page=0) == 0
        assert c.stats()["fallbacks"] == 1  # reason=no_owner

    def test_timeout_degrades_to_reprefill_not_raise(self):
        _, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)

        def fetch(o, t, sp, ts):
            raise TimeoutError("peer wedged")

        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=fetch,
        )
        before = obs.metrics_snapshot().get(
            'opsagent_pagestore_fallbacks_total{reason="timeout"}', 0.0
        )
        assert c.fault_in(toks, start_page=0) == 0
        assert dst.num_pages == 0
        assert obs.metrics_snapshot().get(
            'opsagent_pagestore_fallbacks_total{reason="timeout"}', 0.0
        ) > before

    def test_second_peer_tried_after_first_fails(self):
        src, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)

        def fetch(o, t, sp, ts):
            if o["id"] == "p1":
                raise TimeoutError("p1 wedged")
            return pack_entries(src.match(t, start_page=sp))

        c = _client(
            dst,
            lookup=lambda keys: {
                k: [{"id": "p1"}, {"id": "p2"}] for k in keys
            },
            fetch=fetch,
        )
        assert c.fault_in(toks, start_page=0) == 3

    def test_empty_result_is_stale_signal_and_evicts_rows(self):
        """The directory said the peer owns the chain; the peer says it
        does not (LRU eviction between heartbeat and fetch). Clean miss:
        rows evicted, no retry against the same peer."""
        _, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        evicted = []
        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=lambda o, t, sp, ts: [],
            on_stale=lambda k, rid: evicted.append((k, rid)),
        )
        assert c.fault_in(toks, start_page=0) == 0
        assert c.stats()["stale_entries"] == 3  # one per claimed chain
        assert {rid for _, rid in evicted} == {"peer"}
        assert {k for k, _ in evicted} == {
            chain_key_hex(toks[: (i + 1) * 4]) for i in range(3)
        }

    def test_http_404_is_stale_signal(self):
        _, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        evicted = []

        def fetch(o, t, sp, ts):
            raise urllib.error.HTTPError(
                "http://peer", 404, "gone", None, None
            )

        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=fetch,
            on_stale=lambda k, rid: evicted.append(k),
        )
        assert c.fault_in(toks, start_page=0) == 0
        assert len(evicted) == 3

    def test_digest_rejected_records_are_stale_not_imported(self):
        src, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)

        def fetch(o, t, sp, ts):
            records = pack_entries(src.match(t, start_page=sp))
            for r in records:
                r["digest"] = "00" * 16  # corrupt peer
            return records

        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=fetch,
        )
        assert c.fault_in(toks, start_page=0) == 0
        assert dst.num_pages == 0
        assert c.stats()["stale_entries"] == 3

    def test_size_bound_drops_tail_pages_keeps_leading(self):
        src, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        full = pack_entries(src.match(toks))
        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=lambda o, t, sp, ts: pack_entries(
                src.match(t, start_page=sp)
            ),
            max_bytes=records_nbytes(full[:1]),
        )
        # Only the leading page fits the budget; it still lands (a
        # partial chain restores its leading pages, the rest re-prefills).
        assert c.fault_in(toks, start_page=0) >= 1
        assert len(dst.match(toks)) >= 1

    def test_injected_fetch_timeout_fault_point(self):
        src, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=lambda o, t, sp, ts: pack_entries(
                src.match(t, start_page=sp)
            ),
        )
        faults.configure("pagestore.fetch_timeout@1+")
        try:
            assert c.fault_in(toks, start_page=0) == 0
            assert dst.num_pages == 0
        finally:
            faults.reset()
        # Injector off again: the same fetch now lands.
        assert c.fault_in(toks, start_page=0) == 3

    def test_injected_stale_entry_fault_point(self):
        src, toks = _source_pool()
        dst = HostPagePool(page_size=4, capacity_bytes=1 << 20)
        evicted = []
        c = _client(
            dst,
            lookup=lambda keys: {k: [{"id": "peer"}] for k in keys},
            fetch=lambda o, t, sp, ts: pack_entries(
                src.match(t, start_page=sp)
            ),
            on_stale=lambda k, rid: evicted.append(k),
        )
        faults.configure("pagestore.stale_entry@1")
        try:
            assert c.fault_in(toks, start_page=0) == 0
            assert len(evicted) == 3
        finally:
            faults.reset()


# -- digest cap (satellite 1) -------------------------------------------------
def test_prefix_digest_cap_env_truncates_newest_win(monkeypatch):
    stack = ServingStack(Engine(EngineConfig(**BASE)))
    try:
        eng = stack.engine
        stack.chat_completion({
            "messages": [
                {"role": "system", "content": "digest cap test " * 4},
                {"role": "user", "content": "a prompt long enough to "
                                            "span several KV pages"},
            ],
            "max_tokens": 4, "temperature": 0,
        })
        uncapped = eng.prefix_digests()
        assert len(uncapped) > 2
        assert not eng.digests_truncated()
        monkeypatch.setenv("OPSAGENT_FLEET_DIGEST_CAP", "2")
        capped = eng.prefix_digests()
        assert len(capped) == 2
        assert eng.digests_truncated()
        # Newest content wins: the cap keeps the advertisement's tail.
        assert capped == uncapped[-2:]
        # Explicit arg overrides the env.
        assert len(eng.prefix_digests(cap=1)) == 1
        # The registry snapshot surfaces the clipped advertisement.
        router = FleetRouter()
        router.add_local(stack, "r0")
        router.registry.refresh_local()
        row = router.registry.snapshot()["replicas"][0]
        assert row["digest_truncated"] is True
        assert row["digest_count"] == 2
    finally:
        _close([stack])


# -- acceptance: forced non-owner fault-in, byte-identical ---------------------
def test_forced_nonowner_faults_in_and_matches_never_moved_run():
    """Session on replica A; next turns forced onto replica B and onto a
    freshly promoted standby (zero affinity): both fault the chain in
    through the directory and produce output byte-identical to the
    single-replica run — and the old misroute push-migration stays cold
    (affinity is a locality optimization now, not a correctness crutch)."""
    ref_stack = ServingStack(Engine(EngineConfig(**BASE)))
    try:
        messages = [
            {"role": "system", "content": "pagestore acceptance"},
            {"role": "user", "content": "first turn here"},
        ]
        r1 = ref_stack.chat_completion(
            {"messages": messages, "max_tokens": 8, "temperature": 0}
        )
        turn1_text = r1["choices"][0]["message"]["content"] or ""
        turn2_msgs = list(messages) + [
            {"role": "assistant", "content": turn1_text},
            {"role": "user", "content": "second turn now"},
        ]
        r2 = ref_stack.chat_completion(
            {"messages": turn2_msgs, "max_tokens": 8, "temperature": 0}
        )
        want_turn2 = r2["choices"][0]["message"]["content"] or ""
        turn3_msgs = list(turn2_msgs) + [
            {"role": "assistant",
             "content": r2["choices"][0]["message"]["content"] or ""},
            {"role": "user", "content": "third turn please"},
        ]
        r3 = ref_stack.chat_completion(
            {"messages": turn3_msgs, "max_tokens": 8, "temperature": 0}
        )
        want_turn3 = r3["choices"][0]["message"]["content"] or ""
    finally:
        ref_stack.close()

    router = FleetRouter()  # pagestore directory ON by default
    stacks = []
    for i in range(2):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    standby = ServingStack(Engine(EngineConfig(**BASE)))
    stacks.append(standby)
    router.add_local(standby, "standby", role="standby")
    try:
        snap0 = obs.metrics_snapshot()
        hits0 = snap0.get("opsagent_pagestore_remote_hits_total", 0.0)
        mig0 = snap0.get(
            'opsagent_fleet_session_migrations_total{reason="misroute"}',
            0.0,
        )
        resp = router.complete(
            {"messages": messages, "max_tokens": 8, "temperature": 0},
            force_replica="r0",
        )
        assert (resp["choices"][0]["message"]["content"] or "") == \
            turn1_text
        # Turn 2 forced onto the NON-owner: the directory (fed by r0's
        # digests at route-time refresh) resolves the chain, r1 fetches
        # it peer-to-peer, and the ordinary host-restore path lands it.
        target = router.registry.get("r1").handle
        tgt0 = target.stack.engine.offload.restored_tokens
        resp2 = router.complete(
            {"messages": turn2_msgs, "max_tokens": 8, "temperature": 0},
            force_replica="r1",
        )
        assert resp2["fleet"]["replica"] == "r1"
        assert (resp2["choices"][0]["message"]["content"] or "") == \
            want_turn2
        snap1 = obs.metrics_snapshot()
        assert snap1.get(
            "opsagent_pagestore_remote_hits_total", 0.0
        ) > hits0
        assert target.stack.engine.offload.restored_tokens > tgt0
        assert target.stack.engine.pagestore.stats()[
            "remote_hit_pages"
        ] > 0
        # The legacy eager-push migration stayed cold: the receiver
        # PULLED via fault-in instead.
        assert snap1.get(
            'opsagent_fleet_session_migrations_total{reason="misroute"}',
            0.0,
        ) == mig0
        # Directory bookkeeping is visible on the router surface.
        assert router.registry.snapshot()["directory"]["hits"] > 0
        # Turn 3 on a replica that did not even EXIST as a decode target
        # when the session started: promote the standby, force the turn.
        router.registry.set_role("standby", "decode")
        sb = router.registry.get("standby").handle
        sb0 = sb.stack.engine.offload.restored_tokens
        resp3 = router.complete(
            {"messages": turn3_msgs, "max_tokens": 8, "temperature": 0},
            force_replica="standby",
        )
        assert (resp3["choices"][0]["message"]["content"] or "") == \
            want_turn3
        assert sb.stack.engine.offload.restored_tokens > sb0
    finally:
        _close(stacks)


def test_fetch_timeout_fault_degrades_to_reprefill_no_client_error():
    """Injected pagestore.fetch_timeout on every fetch: the moved turn
    must complete with byte-identical output via local re-prefill — the
    peer-fetch tier is an optimization, never load-bearing."""
    ref_stack = ServingStack(Engine(EngineConfig(**BASE)))
    try:
        messages = [
            {"role": "system", "content": "pagestore timeout test"},
            {"role": "user", "content": "first turn here"},
        ]
        r1 = ref_stack.chat_completion(
            {"messages": messages, "max_tokens": 8, "temperature": 0}
        )
        turn2_msgs = list(messages) + [
            {"role": "assistant",
             "content": r1["choices"][0]["message"]["content"] or ""},
            {"role": "user", "content": "second turn now"},
        ]
        r2 = ref_stack.chat_completion(
            {"messages": turn2_msgs, "max_tokens": 8, "temperature": 0}
        )
        want_turn2 = r2["choices"][0]["message"]["content"] or ""
    finally:
        ref_stack.close()

    router = FleetRouter()
    stacks = []
    for i in range(2):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    try:
        router.complete(
            {"messages": messages, "max_tokens": 8, "temperature": 0},
            force_replica="r0",
        )
        hits0 = obs.metrics_snapshot().get(
            "opsagent_pagestore_remote_hits_total", 0.0
        )
        to0 = obs.metrics_snapshot().get(
            'opsagent_pagestore_fallbacks_total{reason="timeout"}', 0.0
        )
        faults.configure("pagestore.fetch_timeout@1+")
        try:
            resp2 = router.complete(
                {"messages": turn2_msgs, "max_tokens": 8,
                 "temperature": 0},
                force_replica="r1",
            )
        finally:
            faults.reset()
        # No client-visible error; output identical via re-prefill.
        assert (resp2["choices"][0]["message"]["content"] or "") == \
            want_turn2
        snap = obs.metrics_snapshot()
        assert snap.get(
            "opsagent_pagestore_remote_hits_total", 0.0
        ) == hits0
        assert snap.get(
            'opsagent_pagestore_fallbacks_total{reason="timeout"}', 0.0
        ) > to0
    finally:
        _close(stacks)
