"""Multi-host DCN smoke (VERDICT r03 stretch #10): two OS processes join
one JAX runtime through ``parallel.mesh.init_distributed`` (the env-var
path a real TPU pod uses), build a mesh spanning BOTH processes'
devices, and run a jitted computation whose all-reduce crosses the
process boundary — proving the DCN half of the comm backend executes,
not just imports.

On TPU pods the same ``jax.distributed.initialize`` call rides the pod
metadata and the collectives ride ICI/DCN; here each process hosts two
virtual CPU devices and the collective rides gloo over TCP
(``jax_cpu_collectives_implementation`` — XLA:CPU's default "none"
rejects multiprocess computations outright) — same code path in this
framework, different collective wire.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import jax

# Cross-process computations on XLA:CPU need a real collectives backend
# (the default "none" raises "Multiprocess computations aren't
# implemented on the CPU backend"); gloo rides plain TCP. Must be set
# before backend init.
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from opsagent_tpu.parallel.mesh import init_distributed, make_mesh

nproc = init_distributed()  # reads JAX_COORDINATOR_ADDRESS / _ID / _NUM
assert nproc == 2, nproc
assert jax.process_count() == 2
devs = jax.devices()
local = jax.local_device_count()
assert len(devs) == 2 * local, (len(devs), local)

# dp mesh over EVERY device of BOTH processes; the psum the loss below
# induces is a cross-process all-reduce.
mesh = make_mesh(dp=len(devs), tp=1)
sharding = NamedSharding(mesh, P("dp"))
n = len(devs)

# Each process materializes its local shards; value = global position.
x = jax.make_array_from_callback(
    (n,), sharding, lambda idx: np.arange(n, dtype=np.float32)[idx]
)
total = jax.jit(
    lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P())
)(x)
expect = n * (n - 1) / 2
assert float(total) == expect, (float(total), expect)
print(f"proc {jax.process_index()}: global sum over {n} devices ok",
      flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_dcn_smoke():
    port = _free_port()
    procs = []
    for pid in range(2):
        env = {
            k: v for k, v in os.environ.items()
            if k != "PALLAS_AXON_POOL_IPS"  # no TPU plugin in children
        }
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        )
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip(),
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", CHILD], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert "global sum over 4 devices ok" in out
