"""Engine snapshot/restore subsystem (serving/snapshot): manifest
integrity, fingerprint refusal, the mmap restore path, and the
acceptance gates (ISSUE 10): a restored engine reaches request-ready
with ZERO post-warmup compiles and produces byte-identical greedy
output vs the fresh-init engine it was captured from — in fp and
int8-KV configs (plus the int8-weights config, which exercises the
already-quantized restore path: restore must apply quantize SPECS
without re-quantizing the leaves)."""

import gc
import json
import os

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu import obs
from opsagent_tpu.serving.engine import (
    Engine,
    EngineConfig,
    enable_compilation_cache,
)
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.snapshot import (
    MANIFEST_NAME,
    SnapshotError,
    read_manifest,
    verify_snapshot,
)
from opsagent_tpu.serving.snapshot.manifest import write_manifest

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=256, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(16,), decode_block=4, seed=0,
)

PROMPTS = [list(range(1, 13)), list(range(40, 54))]
GREEDY = SamplingParams(temperature=0.0, max_tokens=8)


@pytest.fixture()
def cache_env(tmp_path, monkeypatch):
    """Isolated persistent compile cache + zero min-compile threshold,
    so every warmed program lands in the snapshot's cache artifact."""
    monkeypatch.setenv("OPSAGENT_COMPILE_CACHE_MIN_S", "0")
    monkeypatch.setenv(
        "OPSAGENT_COMPILE_CACHE_DIR", str(tmp_path / "cache-fresh")
    )
    # Earlier tests' in-process executables would otherwise let this
    # test's writer engine skip compiles entirely, leaving its isolated
    # persistent cache dir empty (snapshot then packages 0 entries).
    jax.clear_caches()
    return tmp_path


def _snap(tmp_path, **overrides):
    """(engine, snapshot_dir, manifest): warmed engine captured."""
    eng = Engine(EngineConfig(**{**BASE, **overrides}))
    eng.warmup("bench")
    snapdir = str(tmp_path / "snap")
    man = eng.snapshot(snapdir)
    return eng, snapdir, man


def _teardown_and_restore(eng, snapdir, tmp_path, monkeypatch, warmup):
    """Drop the writer engine (and the in-process executable caches, so
    the restore cannot coast on them), then restore into a second cache
    dir holding only what the snapshot packaged."""
    del eng
    gc.collect()
    jax.clear_caches()
    monkeypatch.setenv(
        "OPSAGENT_COMPILE_CACHE_DIR", str(tmp_path / "cache-restore")
    )
    return Engine.from_snapshot(snapdir, warmup=warmup)


# -- manifest / verify ---------------------------------------------------------
class TestWriteVerify:
    def test_roundtrip_manifest_and_verify(self, tmp_path, cache_env):
        eng, snapdir, man = _snap(tmp_path)
        assert man["format"] == 1
        assert man["engine"]["page_size"] == BASE["page_size"]
        assert man["model"]["vocab_size"] == eng.model_cfg.vocab_size
        assert len(man["leaves"]) == len(
            jax.tree_util.tree_leaves(eng.params)
        )
        # Warmed under MIN_S=0: the compile cache artifact is non-empty.
        assert man["compile_cache"]["entries"] > 0
        assert man["kv_plan"]["num_pages"] == BASE["num_pages"]
        rep = verify_snapshot(snapdir)
        assert rep["ok"] and not rep["errors"]
        assert rep["fingerprint"] == man["fingerprint"]
        quick = verify_snapshot(snapdir, quick=True)
        assert quick["ok"]
        assert obs.SNAPSHOT_OPS.value(op="write") == 1

    def test_verify_catches_flipped_leaf_byte(self, tmp_path, cache_env):
        _eng, snapdir, man = _snap(tmp_path)
        fpath = os.path.join(snapdir, man["leaves"][3]["file"])
        with open(fpath, "r+b") as f:
            b = f.read(1)
            f.seek(0)
            f.write(bytes([b[0] ^ 0xFF]))
        rep = verify_snapshot(snapdir)
        assert not rep["ok"]
        assert any("digest" in e for e in rep["errors"])
        # Quick mode skips content digests, so the flip slips through —
        # that is the documented tradeoff, pinned here.
        assert verify_snapshot(snapdir, quick=True)["ok"]

    def test_verify_catches_edited_config(self, tmp_path, cache_env):
        _eng, snapdir, man = _snap(tmp_path)
        man["engine"]["page_size"] = 8
        write_manifest(snapdir, man)
        rep = verify_snapshot(snapdir)
        assert not rep["ok"]
        assert not rep["fingerprint_ok"]

    def test_missing_manifest_is_unreadable(self, tmp_path):
        with pytest.raises(SnapshotError):
            read_manifest(str(tmp_path))


# -- restore -------------------------------------------------------------------
class TestRestore:
    def test_restore_byte_identical_zero_compiles(
        self, tmp_path, cache_env, monkeypatch
    ):
        eng, snapdir, _man = _snap(tmp_path)
        fresh = eng.generate(PROMPTS, GREEDY)
        eng2 = _teardown_and_restore(
            eng, snapdir, tmp_path, monkeypatch, warmup="bench"
        )
        assert eng2.init_stats["restore_source"] == os.path.abspath(snapdir)
        assert eng2.init_stats["compile_cache_preseeded"] > 0
        # Request-ready means serving compiles NOTHING: the gauge must
        # not move across a full admission + decode.
        gauge0 = obs.POST_WARMUP_COMPILES.value()
        restored = eng2.generate(PROMPTS, GREEDY)
        assert obs.POST_WARMUP_COMPILES.value() == gauge0
        assert restored == fresh
        assert obs.SNAPSHOT_OPS.value(op="restore") == 1

    def test_restore_int8_kv_identical_zero_compiles(
        self, tmp_path, cache_env, monkeypatch
    ):
        eng, snapdir, man = _snap(tmp_path, kv_quantize="int8")
        assert man["engine"]["kv_quantize"] == "int8"
        fresh = eng.generate(PROMPTS, GREEDY)
        eng2 = _teardown_and_restore(
            eng, snapdir, tmp_path, monkeypatch, warmup="bench"
        )
        gauge0 = obs.POST_WARMUP_COMPILES.value()
        restored = eng2.generate(PROMPTS, GREEDY)
        assert obs.POST_WARMUP_COMPILES.value() == gauge0
        assert restored == fresh

    def test_restore_int8_weights_not_double_quantized(
        self, tmp_path, cache_env, monkeypatch
    ):
        # The quantized engine snapshots ALREADY-quantized leaves (q +
        # scale per linear); restore must rebuild quantize SPECS for the
        # sharding but never run quantize_params again — double
        # quantization would silently corrupt every weight.
        from opsagent_tpu.serving.snapshot.writer import spec_leaf_paths

        eng, snapdir, man = _snap(tmp_path, quantize="int8")
        n_fp_leaves = len(spec_leaf_paths(eng.model_cfg, ""))
        assert len(man["leaves"]) > n_fp_leaves  # q + scale leaves
        fresh = eng.generate(PROMPTS, GREEDY)
        eng2 = _teardown_and_restore(
            eng, snapdir, tmp_path, monkeypatch, warmup="bench"
        )
        restored = eng2.generate(PROMPTS, GREEDY)
        assert restored == fresh

    def test_fingerprint_mismatch_refused(
        self, tmp_path, cache_env, monkeypatch
    ):
        eng, snapdir, man = _snap(tmp_path)
        man["engine"]["page_size"] = 8  # config edit after capture
        write_manifest(snapdir, man)
        del eng
        gc.collect()
        with pytest.raises(SnapshotError, match="fingerprint"):
            Engine.from_snapshot(snapdir)
        assert obs.SNAPSHOT_OPS.value(op="refused") == 1

    def test_device_count_mismatch_refused(
        self, tmp_path, cache_env, monkeypatch
    ):
        from opsagent_tpu.serving.snapshot.manifest import fingerprint

        _eng, snapdir, man = _snap(tmp_path)
        # Relative to whatever the host really has (conftest forces 8
        # CPU devices) so the claim is guaranteed to mismatch.
        man["jax"]["n_devices"] = len(jax.devices()) + 1
        man["fingerprint"] = fingerprint(man["model"], man["engine"])
        write_manifest(snapdir, man)
        with pytest.raises(SnapshotError, match="devices"):
            Engine.from_snapshot(snapdir)

    def test_leaf_order_drift_refused(
        self, tmp_path, cache_env, monkeypatch
    ):
        _eng, snapdir, man = _snap(tmp_path)
        man["leaves"][0], man["leaves"][1] = (
            man["leaves"][1], man["leaves"][0],
        )
        write_manifest(snapdir, man)
        with pytest.raises(SnapshotError, match="leaf order"):
            Engine.from_snapshot(snapdir)

    def test_truncated_leaf_refused(self, tmp_path, cache_env):
        _eng, snapdir, man = _snap(tmp_path)
        fpath = os.path.join(snapdir, man["leaves"][0]["file"])
        with open(fpath, "r+b") as f:
            f.truncate(os.path.getsize(fpath) - 4)
        with pytest.raises(SnapshotError, match="truncated|bytes"):
            Engine.from_snapshot(snapdir)


# -- env / compile-cache wiring ------------------------------------------------
class TestCompileCacheEnv:
    def test_dir_env_overrides(self, tmp_path, monkeypatch):
        target = str(tmp_path / "cc")
        monkeypatch.setenv("OPSAGENT_COMPILE_CACHE_DIR", target)
        assert enable_compilation_cache() == target

    def test_legacy_name_still_accepted(self, tmp_path, monkeypatch):
        target = str(tmp_path / "legacy")
        monkeypatch.delenv("OPSAGENT_COMPILE_CACHE_DIR", raising=False)
        monkeypatch.setenv("OPSAGENT_COMPILE_CACHE", target)
        assert enable_compilation_cache() == target

    def test_empty_disables(self, monkeypatch):
        monkeypatch.setenv("OPSAGENT_COMPILE_CACHE_DIR", "")
        assert enable_compilation_cache() is None
        monkeypatch.setenv("OPSAGENT_COMPILE_CACHE_DIR", "0")
        assert enable_compilation_cache() is None


# -- /healthz init block -------------------------------------------------------
class TestHealthzInit:
    def test_init_block_reports_cold_start_provenance(
        self, tmp_path, cache_env, monkeypatch
    ):
        from opsagent_tpu.serving.api import ServingStack, build_engine_app

        eng, snapdir, man = _snap(tmp_path)
        eng2 = _teardown_and_restore(
            eng, snapdir, tmp_path, monkeypatch, warmup=False
        )
        stack = ServingStack(eng2)
        try:
            app = build_engine_app(stack)

            async def _get():
                client = TestClient(TestServer(app))
                await client.start_server()
                try:
                    resp = await client.get("/healthz")
                    return json.loads(await resp.text())
                finally:
                    await client.close()

            import asyncio

            body = asyncio.new_event_loop().run_until_complete(_get())
            init = body["init"]
            assert init["restore_source"] == os.path.abspath(snapdir)
            assert init["snapshot_fingerprint"] == man["fingerprint"]
            assert init["weights_load_s"] >= 0
            assert "warmup_s" in init
        finally:
            stack.close()
