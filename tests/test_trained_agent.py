"""Full-circle capability test: train -> checkpoint -> serve -> agent.

Runs scripts/train_tiny_agent.py end to end: the in-tree train step
fine-tunes the tiny model on ReAct transcripts (generated with the same
serialization code the live loop uses), saves an HF-format safetensors
checkpoint, boots the serving engine from that file, and the REAL agent
loop — tpu:// provider, FSM-constrained decoding, kubectl replay tool —
must produce the correct tool call and final answer from the trained
weights. This is the in-tree replacement for the capability the reference
buys from GPT-4 (reference pkg/handlers/execute.go:205), demonstrated
with actual learned weights rather than canned LLM replies.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_train_serve_agent_roundtrip(tmp_path):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}  # never touch a TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [
            sys.executable, "-u",
            os.path.join(REPO, "scripts", "train_tiny_agent.py"),
            "--steps", "600",
            "--out", str(tmp_path / "ckpt"),
        ],
        capture_output=True, text=True, timeout=1200, env=env, cwd=REPO,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]
    assert "agent PASSED" in out.stdout
    assert (tmp_path / "ckpt" / "model.safetensors").exists()
