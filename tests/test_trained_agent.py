"""Full-circle capability test: train -> checkpoint -> serve -> agent.

Runs scripts/train_tiny_agent.py end to end: the in-tree train step
fine-tunes the tiny model on ReAct transcripts (generated with the same
serialization code the live loop uses), saves an HF-format safetensors
checkpoint, boots the serving engine from that file, and the REAL agent
loop — tpu:// provider, FSM-constrained decoding, kubectl replay tool —
must produce the correct tool call and final answer from the trained
weights. This is the in-tree replacement for the capability the reference
buys from GPT-4 (reference pkg/handlers/execute.go:205), demonstrated
with actual learned weights rather than canned LLM replies.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_train_serve_agent_roundtrip(tmp_path):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}  # never touch a TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [
            sys.executable, "-u",
            os.path.join(REPO, "scripts", "train_tiny_agent.py"),
            "--steps", "600",
            # Extra SERVING passes (training happens once): the same
            # checkpoint re-served under each quantized configuration
            # must reproduce every memorized assertion — greedy
            # faithfulness on LEARNED weights, not random ones. int8 KV
            # and int8 weights gate on the answers; int4 gates on greedy
            # prefix agreement vs fp32 (tiny-test's 64-wide contractions
            # are group-wise int4's worst case, so flipped ANSWERS are
            # expected signal there — but agreement ~0 means a
            # packing/dequant bug and fails the run).
            "--serve-variants", "kv-int8,int8,int4",
            "--out", str(tmp_path / "ckpt"),
        ],
        capture_output=True, text=True, timeout=1800, env=env, cwd=REPO,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]
    assert "agent PASSED" in out.stdout
    assert "[kv-int8]" in out.stderr and "[int8]" in out.stderr
    # int4 ran AND its quantitative gate reported (a floor breach would
    # have failed the returncode assertion above).
    assert "greedy prefix agreement vs fp32" in out.stderr
    assert (tmp_path / "ckpt" / "model.safetensors").exists()


@pytest.mark.slow
def test_train_serve_agent_multi_task(tmp_path):
    """The 7-instruction corpus (5 kubectl episodes + 1 python-tool
    episode + 1 jq episode) trains to memorization and the served agent
    answers EVERY instruction correctly through the real loop — tool
    dispatch across three tools, FSM-constrained decode, replay
    cluster."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [
            sys.executable, "-u",
            os.path.join(REPO, "scripts", "train_tiny_agent.py"),
            "--tasks", "multi",
            "--steps", "3000",
            "--no-probe",  # held-out probes are demo-only wall clock
            "--out", str(tmp_path / "ckpt"),
        ],
        capture_output=True, text=True, timeout=2400, env=env, cwd=REPO,
    )
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]
    assert "agent PASSED (7 tasks)" in out.stdout


def test_multi_task_corpus_valid_under_fsm(tmp_path, monkeypatch):
    """Every multi-task training target must be reachable under the
    ToolPrompt FSM the serving path enforces, and every task's
    observation must match what the REAL tool functions return against
    the replay cluster — the same post-processed strings (noise filter,
    strip, venv interpreter) the agent loop marshals into turn 2, so any
    drift fails here in seconds instead of in the slow e2e run."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from train_tiny_agent import (
            TASKS_MULTI,
            build_convs,
            train_phrasings,
        )
    finally:
        sys.path.remove(os.path.join(REPO, "scripts"))

    from opsagent_tpu.serving.constrained import (
        TOOLPROMPT_SCHEMA,
        json_constraint,
    )
    from opsagent_tpu.serving.tokenizer import ByteTokenizer
    from opsagent_tpu.tools.jq import jq
    from opsagent_tpu.tools.kubectl import kubectl
    from opsagent_tpu.tools.python_tool import python_repl
    from opsagent_tpu.tools.replay import (
        MULTI_TASK_SCRIPT,
        install_replay_kubectl,
    )

    convs = build_convs(TASKS_MULTI)
    # Two convs per TRAINED phrasing (base instruction + all but the
    # held-out alternative): 7 tasks x 4 phrasings x 2 turns.
    assert len(convs) == 2 * sum(
        len(train_phrasings(t)) for t in TASKS_MULTI
    ) == 56
    con = json_constraint(ByteTokenizer(vocab_size=512), TOOLPROMPT_SCHEMA)
    for _, reply in convs:
        dfa = con.fsm.dfa
        state = dfa.run(dfa.start, reply.encode())
        assert state >= 0 and dfa.accept[state], reply

    # monkeypatch records PATH so teardown restores it even though
    # install_replay_kubectl mutates os.environ directly (same pattern
    # as test_real_checkpoint.py's replay fixture).
    monkeypatch.setenv("PATH", os.environ["PATH"])
    install_replay_kubectl(MULTI_TASK_SCRIPT, str(tmp_path / "bin"))
    tools = {"kubectl": kubectl, "python": python_repl, "jq": jq}
    for t in TASKS_MULTI:
        got = tools[t["tool"]](t["tool_input"])
        assert got == t["observation"], (t["tool_input"], got)
