"""Metrics-exposition conformance (fast lane): parse the ``/metrics``
document against the Prometheus text-format grammar.

The registry is hand-rolled (no prometheus_client in the container), so
nothing but this test stands between a formatting bug and a scrape that
silently drops samples. Checks, per the exposition format spec
(``text/plain; version=0.0.4``):

- every line is a valid comment/HELP/TYPE/sample line;
- metric and label names match the allowed charsets; label values are
  properly escaped (no raw newline/quote inside the quotes);
- at most one TYPE per metric family, declared before its samples, and
  each family's samples form one contiguous group;
- histogram families carry ``_bucket``/``_sum``/``_count`` series with
  cumulative non-decreasing ``le`` buckets ending at ``+Inf`` == count;
- the document ends with a newline.

Traffic includes label values that exercise the escaper (quotes,
backslashes, newlines) and every instrument family (counter, gauge,
histogram, the PerfStats bridge, the SLO collector).
"""

import math
import re

from opsagent_tpu import obs
from opsagent_tpu.utils.perf import get_perf_stats

METRIC_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
LABEL_NAME = r"[a-zA-Z_][a-zA-Z0-9_]*"
# Escaped label value: backslash, double quote, and newline must appear
# only in their escaped forms.
LABEL_VALUE = r'"(?:[^"\\\n]|\\\\|\\"|\\n)*"'
LABELS = rf"\{{{LABEL_NAME}={LABEL_VALUE}(?:,{LABEL_NAME}={LABEL_VALUE})*,?\}}"
VALUE = r"(?:[+-]?(?:\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|Inf)|NaN)"
SAMPLE_RE = re.compile(
    rf"^({METRIC_NAME})({LABELS})? ({VALUE})(?: [+-]?\d+)?$"
)
HELP_RE = re.compile(rf"^# HELP ({METRIC_NAME}) .*$")
TYPE_RE = re.compile(
    rf"^# TYPE ({METRIC_NAME}) (counter|gauge|histogram|summary|untyped)$"
)
HISTO_SUFFIX = re.compile(r"_(bucket|sum|count)$")


def _family(sample_name: str, types: dict[str, str]) -> str:
    """The metric family a sample belongs to: histogram samples use the
    suffixed names of their declared family."""
    m = HISTO_SUFFIX.search(sample_name)
    if m:
        base = sample_name[: m.start()]
        if types.get(base) == "histogram":
            return base
    return sample_name


def _generate_traffic():
    obs.TTFT_SECONDS.observe(0.012)
    obs.TTFT_SECONDS.observe(0.7)
    obs.TTFT_SECONDS.observe(3.0)
    obs.ITL_SECONDS.observe(0.004)
    obs.DECODE_TOKENS.inc(42)
    obs.ENGINE_REQUESTS.inc(outcome="completed")
    obs.ENGINE_REQUESTS.inc(outcome="error")
    # Label values that must round-trip through the escaper.
    obs.HTTP_REQUESTS.inc(
        method="GET", path='/weird"path\\with\nnewline', status="200"
    )
    obs.TOOL_CALLS.inc(tool="kubectl", outcome="ok")
    obs.KV_PAGE_UTILIZATION.set(0.375)
    obs.COMPILES.inc(phase="startup")
    # Goodput-ledger families: one priced dispatch (with a synchronous
    # measurement, so the drift gauge + measured histogram render) and
    # the goodput phase counters.
    attr = obs.attribution.Attribution(
        num_params=10_000, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, vocab_size=512, dtype_bytes=4,
    )
    attr.dispatch(
        "single", q_tokens=2, kv_read_tokens=8, kv_write_tokens=2,
        attn_q_ctx=8, measured_s=0.004,
    )
    obs.attribution.record_goodput(0.2, "decode_active")
    obs.attribution.record_goodput(0.1, "tool_blocked")
    obs.attribution.record_goodput(0.05, "queued")
    # PerfStats bridge lines.
    get_perf_stats().record_metric("engine.ttft", 12.5, "ms")
    get_perf_stats().record_metric('series"quote', 1.0, "ms")


def test_metrics_exposition_conforms():
    _generate_traffic()
    text = obs.metrics_text()
    assert text.endswith("\n"), "document must end with a newline"
    lines = text.split("\n")[:-1]
    assert lines, "empty exposition"

    types: dict[str, str] = {}
    sample_values: dict[tuple, float] = {}
    family_order: list[str] = []   # first-seen order of sample families

    for ln in lines:
        if ln.startswith("# HELP "):
            assert HELP_RE.match(ln), f"bad HELP line: {ln!r}"
            continue
        if ln.startswith("# TYPE "):
            m = TYPE_RE.match(ln)
            assert m, f"bad TYPE line: {ln!r}"
            name, kind = m.group(1), m.group(2)
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
            continue
        if ln.startswith("#"):
            continue  # plain comment
        m = SAMPLE_RE.match(ln)
        assert m, f"bad sample line: {ln!r}"
        name = m.group(1)
        fam = _family(name, types)
        family_order.append(fam)
        key = (name, m.group(2) or "")
        assert key not in sample_values, f"duplicate sample: {ln!r}"
        sample_values[key] = float(m.group(3).replace("Inf", "inf"))

    # Contiguity: samples of one family must form one group.
    seen_done: set[str] = set()
    prev = None
    for fam in family_order:
        if fam != prev:
            assert fam not in seen_done, (
                f"family {fam} interleaved with other families"
            )
            if prev is not None:
                seen_done.add(prev)
            prev = fam

    # Histogram semantics.
    for fam, kind in types.items():
        if kind != "histogram":
            continue
        buckets = [
            (k, v) for k, v in sample_values.items()
            if k[0] == f"{fam}_bucket"
        ]
        if not buckets:
            continue  # registered but never observed: no samples at all
        # Group buckets by their non-le labels (histogram children).
        by_child: dict[str, list[tuple[float, float]]] = {}
        for (name, labels), v in buckets:
            le = re.search(rf'le="({VALUE})"', labels)
            assert le, f"bucket without le label: {name}{labels}"
            rest = re.sub(rf',?le="{re.escape(le.group(1))}"', "", labels)
            if rest == "{}":
                rest = ""  # le was the only label
            by_child.setdefault(rest, []).append(
                (float(le.group(1).replace("Inf", "inf")), v)
            )
        for child, series in by_child.items():
            series.sort(key=lambda t: t[0])
            les = [le for le, _ in series]
            counts = [c for _, c in series]
            assert les[-1] == math.inf, f"{fam}{child}: no +Inf bucket"
            assert counts == sorted(counts), (
                f"{fam}{child}: buckets not cumulative: {counts}"
            )
            # +Inf bucket equals the child's _count sample, and _sum
            # exists for it.
            assert sample_values[(f"{fam}_count", child)] == counts[-1], (
                f"{fam}{child}: +Inf bucket != _count"
            )
            assert (f"{fam}_sum", child) in sample_values


def test_goodput_ledger_families_on_the_scrape():
    """The opsagent_attr_* / opsagent_goodput_* families (the goodput
    ledger's contract with dashboards) are present, typed, and conform —
    the main grammar test above already walked them; this pins the names
    so a rename is a visible contract break."""
    _generate_traffic()
    text = obs.metrics_text()
    for family, kind in (
        ("opsagent_attr_bytes_total", "counter"),
        ("opsagent_attr_step_bytes", "gauge"),
        ("opsagent_attr_flops_total", "counter"),
        ("opsagent_attr_dispatches_total", "counter"),
        ("opsagent_attr_modeled_step_seconds", "gauge"),
        ("opsagent_attr_measured_step_seconds", "histogram"),
        ("opsagent_attr_model_drift_ratio", "gauge"),
        ("opsagent_attr_mfu", "gauge"),
        ("opsagent_attr_hbm_utilization", "gauge"),
        ("opsagent_goodput_seconds_total", "counter"),
    ):
        assert f"# TYPE {family} {kind}" in text, family
    # The split's label values are the documented four kinds.
    for k in ("weights", "kv_read", "kv_write", "other"):
        assert f'opsagent_attr_step_bytes{{kind="{k}"}}' in text


def test_fleet_journey_families_on_the_scrape():
    """The fleet-journey families (ISSUE 16's contract with dashboards):
    hop latency histogram, journey shape counter, per-replica clock-skew
    gauge — present and typed once traffic touches them."""
    obs.FLEET_HOP_SECONDS.observe(0.012, hop="route")
    obs.FLEET_HOP_SECONDS.observe(0.034, hop="failover")
    obs.FLEET_JOURNEYS.inc(**{"shape": "direct", "class": "interactive"})
    obs.FLEET_JOURNEYS.inc(**{"shape": "failover", "class": "batch"})
    obs.FLEET_CLOCK_SKEW.set(0.004, replica="r1")
    text = obs.metrics_text()
    for family, kind in (
        ("opsagent_fleet_hop_seconds", "histogram"),
        ("opsagent_fleet_journeys_total", "counter"),
        ("opsagent_fleet_clock_skew_seconds", "gauge"),
    ):
        assert f"# TYPE {family} {kind}" in text, family
    assert 'opsagent_fleet_hop_seconds_count{hop="route"}' in text
    assert ('opsagent_fleet_journeys_total{shape="failover",'
            'class="batch"}') in text
    assert 'opsagent_fleet_clock_skew_seconds{replica="r1"}' in text


def test_class_and_history_families_on_the_scrape():
    """The ISSUE 18 families (SLO classes, tail-based trace retention,
    telemetry history) are present and typed once traffic touches them —
    a rename is a visible contract break."""
    obs.CLASS_REQUESTS.inc(**{"class": "interactive", "outcome": "completed"})
    obs.CLASS_REQUESTS.inc(**{"class": "batch", "outcome": "shed"})
    obs.CLASS_TTFT_SECONDS.observe(0.05, **{"class": "interactive"})
    obs.CLASS_ITL_SECONDS.observe(0.004, **{"class": "interactive"})
    obs.CLASS_GOODPUT_SECONDS.inc(
        0.2, **{"class": "interactive", "phase": "decode_active"}
    )
    obs.TRACE_RETENTION.inc(decision="kept_anomalous")
    obs.TRACE_RETENTION.inc(decision="dropped")
    obs.HISTORY_SAMPLES.inc()
    obs.HISTORY_POINTS.set(12, tier="1s")
    obs.HISTORY_BYTES.set(1440)
    text = obs.metrics_text()
    for family, kind in (
        ("opsagent_class_requests_total", "counter"),
        ("opsagent_class_ttft_seconds", "histogram"),
        ("opsagent_class_itl_seconds", "histogram"),
        ("opsagent_class_goodput_seconds_total", "counter"),
        ("opsagent_trace_retention_total", "counter"),
        ("opsagent_history_samples_total", "counter"),
        ("opsagent_history_points", "gauge"),
        ("opsagent_history_bytes", "gauge"),
    ):
        assert f"# TYPE {family} {kind}" in text, family
    assert ('opsagent_class_requests_total{class="interactive",'
            'outcome="completed"}') in text
    assert 'opsagent_trace_retention_total{decision="dropped"}' in text
    assert 'opsagent_history_points{tier="1s"}' in text


def test_class_labels_are_enum_only():
    """Cardinality guard for the new ``class`` label: every class-labeled
    sample on the scrape must carry one of the three declared SLO
    classes — a scenario name, model name, or request id leaking into
    the class label would be unbounded cardinality."""
    _generate_traffic()
    obs.CLASS_REQUESTS.inc(**{"class": "interactive", "outcome": "completed"})
    obs.FLEET_SHED.inc(**{"class": "batch"})
    obs.FLEET_HEDGES.inc(**{"class": "background"})
    obs.FLEET_JOURNEYS.inc(**{"shape": "direct", "class": "interactive"})
    text = obs.metrics_text()
    cls_re = re.compile(r'class="([^"]*)"')
    found = 0
    for ln in text.splitlines():
        if ln.startswith("#"):
            continue
        for m in cls_re.finditer(ln):
            found += 1
            assert m.group(1) in obs.SLO_CLASSES, (
                f"non-enum class label on the scrape: {ln!r}"
            )
    assert found > 0, "no class-labeled samples rendered"


def test_classify_rejects_unknown_values_to_default():
    """obs.slo.classify is the only writer of the class label: bogus
    explicit values and unknown scenarios must clamp to the enum (the
    upstream half of the cardinality guard above)."""
    from opsagent_tpu.obs import slo as obs_slo

    assert obs_slo.classify({"slo_class": "batch"}) == "batch"
    assert obs_slo.classify({"slo_class": "vip-customer-42"}) \
        == "interactive"
    assert obs_slo.classify(scenario="audit") == "batch"
    assert obs_slo.classify(scenario="diagnose") == "interactive"
    assert obs_slo.classify(scenario="no-such-scenario",
                            default="background") == "background"


def test_no_metric_family_is_keyed_by_raw_request_id():
    """Cardinality guard: request/journey IDs are unbounded, so they may
    appear in flight events and timelines but NEVER as a label value on
    the metrics surface — one leaked id-per-request label melts every
    scrape. Journey traffic runs first so a regression would be ON the
    exposition when we scan it."""
    obs.FLEET_HOP_SECONDS.observe(0.01, hop="route")
    obs.FLEET_JOURNEYS.inc(**{"shape": "direct", "class": "interactive"})
    _generate_traffic()
    text = obs.metrics_text()
    id_like = re.compile(
        r'="(?:chatcmpl|req|cli|tl|e2e)-[0-9a-fA-F]{8,}"'
    )
    for ln in text.splitlines():
        if ln.startswith("#"):
            continue
        assert not id_like.search(ln), (
            f"request-id-shaped label value on the scrape: {ln!r}"
        )


def test_escaped_label_values_roundtrip():
    """The escaper's output must re-parse to the original value."""
    from opsagent_tpu.obs.metrics import escape_label_value

    for raw in ['plain', 'with"quote', "back\\slash", "new\nline",
                'all\\"\nthree']:
        esc = escape_label_value(raw)
        assert "\n" not in esc
        unescaped = (
            esc.replace("\\\\", "\x00")
            .replace('\\"', '"')
            .replace("\\n", "\n")
            .replace("\x00", "\\")
        )
        assert unescaped == raw


def test_engine_servers_expose_same_document_shape():
    """Both servers' /metrics handlers serve the identical registry
    render (one process-wide registry — co-hosted deployments scrape
    either port)."""
    _generate_traffic()
    a = obs.metrics_text()
    b = obs.metrics_text()
    # Modulo the SLO collector's evaluated_at drift, consecutive renders
    # of an idle registry agree line-for-line.
    strip = lambda t: [  # noqa: E731
        ln for ln in t.splitlines() if not ln.startswith("opsagent_slo_")
    ]
    assert strip(a) == strip(b)
