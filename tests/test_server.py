"""REST API tests: auth, JWT guard, execute path with a scripted engine."""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu.server.app import build_app
from opsagent_tpu.server.jwtauth import decode, encode, issue_token, JWTError
from opsagent_tpu.utils.globalstore import set_global

JWT_KEY = "test-key"


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


async def _client():
    app = build_app()
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def test_jwt_roundtrip():
    token = issue_token("admin", JWT_KEY)
    claims = decode(token, JWT_KEY)
    assert claims["username"] == "admin"
    assert claims["exp"] > claims["iat"]


def test_jwt_bad_signature():
    token = issue_token("admin", JWT_KEY)
    try:
        decode(token, "other-key")
        raise AssertionError("expected JWTError")
    except JWTError:
        pass


def test_jwt_expired():
    token = encode({"username": "x", "exp": 1}, JWT_KEY)
    try:
        decode(token, JWT_KEY)
        raise AssertionError("expected JWTError")
    except JWTError:
        pass


def test_login_and_version():
    set_global("jwtKey", JWT_KEY)

    async def scenario():
        client = await _client()
        try:
            r = await client.post(
                "/login", json={"username": "admin", "password": "novastar"}
            )
            assert r.status == 200
            token = (await r.json())["token"]
            assert decode(token, JWT_KEY)["username"] == "admin"

            r = await client.post(
                "/login", json={"username": "admin", "password": "wrong"}
            )
            assert r.status == 401

            r = await client.get("/api/version")
            assert r.status == 200
            assert "version" in await r.json()
        finally:
            await client.close()

    run(scenario())


def test_empty_jwt_key_rejects_all_tokens():
    # With no jwtKey configured the middleware must refuse, not verify
    # against an empty HMAC key (which would let anyone forge tokens).
    forged = issue_token("admin", "")

    async def scenario():
        client = await _client()
        try:
            r = await client.post(
                "/api/execute",
                json={"instructions": "x"},
                headers={"Authorization": f"Bearer {forged}"},
            )
            assert r.status == 500
            assert "not configured" in (await r.json())["error"]
        finally:
            await client.close()

    run(scenario())


def test_protected_route_requires_jwt():
    set_global("jwtKey", JWT_KEY)

    async def scenario():
        client = await _client()
        try:
            r = await client.post("/api/execute", json={"instructions": "x"})
            assert r.status == 401
            r = await client.get("/api/perf/stats")
            assert r.status == 401
        finally:
            await client.close()

    run(scenario())


def test_cors_preflight():
    async def scenario():
        client = await _client()
        try:
            r = await client.options("/api/execute")
            assert r.status == 204
            assert "X-API-Key" in r.headers["Access-Control-Allow-Headers"]
        finally:
            await client.close()

    run(scenario())


def test_execute_end_to_end(scripted_llm, fake_tools):
    set_global("jwtKey", JWT_KEY)
    fake_tools({"kubectl": lambda c: "default\nkube-system"})
    scripted_llm(
        [
            json.dumps(
                {
                    "question": "q",
                    "thought": "list",
                    "action": {"name": "kubectl", "input": "get ns --no-headers"},
                    "observation": "",
                    "final_answer": "",
                }
            ),
            json.dumps(
                {
                    "question": "q",
                    "thought": "count",
                    "action": {"name": "", "input": ""},
                    "observation": "default\nkube-system",
                    "final_answer": "There are 2 namespaces in the cluster.",
                }
            ),
        ]
    )

    async def scenario():
        client = await _client()
        try:
            token = issue_token("admin", JWT_KEY)
            headers = {"Authorization": f"Bearer {token}", "X-API-Key": "k"}
            r = await client.post(
                "/api/execute?show-thought=true",
                json={
                    "instructions": "count namespaces",
                    "args": "",
                    "currentModel": "fake://m",
                },
                headers=headers,
            )
            assert r.status == 200
            data = await r.json()
            assert data["status"] == "success"
            assert data["message"] == "There are 2 namespaces in the cluster."
            assert data["tools_history"][0]["name"] == "kubectl"
            assert "kube-system" in data["tools_history"][0]["observation"]
        finally:
            await client.close()

    run(scenario())


def test_execute_missing_api_key():
    set_global("jwtKey", JWT_KEY)

    async def scenario():
        client = await _client()
        try:
            token = issue_token("admin", JWT_KEY)
            r = await client.post(
                "/api/execute",
                json={"instructions": "x", "args": ""},
                headers={"Authorization": f"Bearer {token}"},
            )
            assert r.status == 400
            assert "API Key" in (await r.json())["error"]
        finally:
            await client.close()

    run(scenario())


def test_perf_endpoints(scripted_llm):
    set_global("jwtKey", JWT_KEY)

    async def scenario():
        client = await _client()
        try:
            token = issue_token("admin", JWT_KEY)
            headers = {"Authorization": f"Bearer {token}"}
            r = await client.get("/api/perf/stats", headers=headers)
            assert r.status == 200
            assert "stats" in await r.json()
            r = await client.post("/api/perf/reset", headers=headers)
            assert r.status == 200
        finally:
            await client.close()

    run(scenario())
