"""Speculative decoding (prompt-lookup / n-gram drafting): exactness and
behavior. Greedy speculation is EXACT — drafts are only accepted where
they equal the model's own argmax — so the speculative engine must emit
bit-identical token streams to the vanilla engine, just in fewer
weight-streaming passes. (No reference counterpart: this is serving-engine
capability the reference outsourced to api.openai.com.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.serving.decode_loop import ngram_draft
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
    num_pages=512, max_pages_per_seq=32, max_batch_size=4,
    prefill_buckets=(16,),
)


def test_ngram_draft_finds_last_match():
    # history: 5 6 1 2 9 9 7 1 2 | next tok completes gram (1, 2)
    hist = np.zeros((1, 32), np.int32)
    hist[0, :9] = [5, 6, 1, 2, 9, 9, 7, 1, 2]
    # written = 8 tokens (indices 0..7), tok = 2 -> trailing gram (1, 2)
    # wait: at=8 means hist[:8] = 5 6 1 2 9 9 7 1 written, tok=2.
    d = ngram_draft(
        jnp.asarray(hist), jnp.asarray([8]), jnp.asarray([2]), k=3, ngram=2
    )
    # LAST full (1,2)-match with 3 written followers is at j=2 -> draft 9 9 7
    assert list(np.asarray(d)[0]) == [9, 9, 7]


def test_ngram_draft_no_match_is_sentinel():
    hist = np.zeros((1, 16), np.int32)
    hist[0, :4] = [1, 2, 3, 4]
    d = ngram_draft(
        jnp.asarray(hist), jnp.asarray([4]), jnp.asarray([9]), k=2, ngram=2
    )
    assert (np.asarray(d) == -1).all()


@pytest.mark.parametrize("k", [2, 4])
def test_speculative_matches_vanilla_greedy(k):
    prompts = [
        [1, 2, 3, 4, 5, 6, 7, 8],
        [9, 8, 7],
        [4, 4, 4, 4, 4, 4],
    ]
    sampling = SamplingParams(temperature=0.0, max_tokens=24)
    want = Engine(EngineConfig(**BASE)).generate(prompts, sampling)
    got = Engine(
        EngineConfig(speculative_k=k, **BASE)
    ).generate(prompts, sampling)
    assert got == want, (got, want)


def test_speculative_accepts_repetitive_continuations():
    """A prompt whose greedy continuation loops (tiny random models settle
    into cycles) must show multi-token acceptance: fewer verify forwards
    than emitted tokens."""
    eng = Engine(EngineConfig(speculative_k=4, **BASE))
    sampling = SamplingParams(temperature=0.0, max_tokens=48)
    out = eng.generate([[1, 2, 3, 4, 1, 2, 3, 4]], sampling)[0]
    assert len(out) == 48 or (
        eng.tokenizer.eos_id in out
    )


def test_speculative_respects_eos_and_budget():
    eng = Engine(EngineConfig(speculative_k=3, **BASE))
    sampling = SamplingParams(temperature=0.0, max_tokens=5)
    out = eng.generate([[1, 2, 3]], sampling)[0]
    vanilla = Engine(EngineConfig(**BASE)).generate(
        [[1, 2, 3]], SamplingParams(temperature=0.0, max_tokens=5)
    )[0]
    assert out == vanilla
    assert len(out) <= 5


def test_speculative_with_sampled_rows_falls_back():
    """A batch containing a temperature>0 row uses the vanilla pipeline
    (speculation is greedy-exact only) — and still completes."""
    eng = Engine(EngineConfig(speculative_k=4, **BASE))
    a = eng.add_request([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=6))
    b = eng.add_request(
        [4, 5, 6], SamplingParams(temperature=0.8, max_tokens=6)
    )
    pending = {a, b}
    while pending:
        eng.step_block(sorted(pending))
        pending = {i for i in pending if not eng.sequences[i].done}
    ta, tb = eng.finish(a), eng.finish(b)
    assert len(ta) >= 1 and len(tb) >= 1


def test_speculative_streaming_and_prefix_cache():
    """Spec engine composes with streaming callbacks and prefix caching:
    a second request sharing the prompt prefix hits cached pages and
    still produces the exact vanilla stream."""
    want = Engine(EngineConfig(**BASE)).generate(
        [[7, 6, 5, 4, 3, 2]], SamplingParams(temperature=0.0, max_tokens=12)
    )[0]
    eng = Engine(EngineConfig(speculative_k=3, **BASE))
    seen: list[int] = []
    sid = eng.add_request(
        [7, 6, 5, 4, 3, 2],
        SamplingParams(temperature=0.0, max_tokens=12),
        stream=seen.append,
    )
    while not eng.sequences[sid].done:
        eng.step_block([sid])
    got = eng.finish(sid)
    assert got == want
    assert seen == got
    got2 = eng.generate(
        [[7, 6, 5, 4, 3, 2]], SamplingParams(temperature=0.0, max_tokens=12)
    )[0]
    assert got2 == want


def test_speculative_booking_drift_does_not_truncate():
    """Regression: unspent speculative bookings (draft misses) must be
    rolled back on pull — with a tight per-seq page cap, drift would
    otherwise hit max_pages_per_seq and truncate the response early."""
    cfg = dict(BASE)
    # Room for prompt(8) + 64 generated + draft slack, and not much more.
    cfg["max_pages_per_seq"] = 12  # 96 tokens at page_size=8
    sampling = SamplingParams(temperature=0.0, max_tokens=64)
    want = Engine(EngineConfig(**cfg)).generate(
        [[3, 1, 4, 1, 5, 9, 2, 6]], sampling
    )[0]
    got = Engine(EngineConfig(speculative_k=3, **cfg)).generate(
        [[3, 1, 4, 1, 5, 9, 2, 6]], sampling
    )[0]
    assert got == want
    assert len(got) == 64 or got[-1] == Engine(
        EngineConfig(**cfg)
    ).tokenizer.eos_id
