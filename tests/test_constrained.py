"""Constrained decoding: regex→DFA compilation, schema masks, engine wiring.

The reference survives malformed LLM JSON with a repair ladder
(pkg/utils/json.go); here we assert malformed JSON is unrepresentable: every
token the mask admits keeps the output inside the schema's language.
"""

import json

import numpy as np
import pytest

from opsagent_tpu.serving.constrained import (
    TOOLPROMPT_SCHEMA,
    ByteDFA,
    JsonConstraint,
    compile_regex,
    json_constraint,
    schema_to_regex,
)
from opsagent_tpu.serving.tokenizer import ByteTokenizer


def accepts(dfa: ByteDFA, s: str) -> bool:
    state = dfa.run(dfa.start, s.encode("utf-8"))
    return state >= 0 and bool(dfa.accept[state])


def prefix_ok(dfa: ByteDFA, s: str) -> bool:
    return dfa.run(dfa.start, s.encode("utf-8")) >= 0


class TestGenericJson:
    @pytest.fixture(scope="class")
    def dfa(self):
        return compile_regex(schema_to_regex(None, depth=3))

    @pytest.mark.parametrize("doc", [
        '"hello"', "42", "-3.5e2", "true", "false", "null",
        '{"a": 1}', '{"a": {"b": [1, 2, 3]}}', "[]", '[{"x": "y"}]',
        '{"s": "with \\"escape\\" and \\u00e9"}', '{ "spaced" : [ 1 , 2 ] }',
    ])
    def test_accepts_valid(self, dfa, doc):
        json.loads(doc)  # sanity: it really is JSON
        assert accepts(dfa, doc)

    @pytest.mark.parametrize("doc", [
        "{", '{"a" 1}', '{"a": 1,}', "[1, ]", "tru", '"unterminated',
        "01", "+1", '{"a": }', "nope",
    ])
    def test_rejects_invalid(self, dfa, doc):
        assert not accepts(dfa, doc)

    def test_prefixes_live(self, dfa):
        # Every prefix of a valid doc must be a live DFA state (else the
        # mask would dead-end generation mid-output).
        doc = '{"key": [1, {"n": -2.5}], "t": true}'
        for i in range(len(doc)):
            assert prefix_ok(dfa, doc[:i]), doc[:i]


class TestToolPromptSchema:
    @pytest.fixture(scope="class")
    def dfa(self):
        return compile_regex(schema_to_regex(TOOLPROMPT_SCHEMA))

    def test_accepts_wire_format(self, dfa):
        doc = json.dumps({
            "question": "count namespaces",
            "thought": "list then count",
            "action": {"name": "kubectl", "input": "kubectl get ns | wc -l"},
            "observation": "",
            "final_answer": "",
        })
        assert accepts(dfa, doc)

    def test_rejects_wrong_keys_and_types(self, dfa):
        assert not accepts(dfa, json.dumps({"question": "q"}))
        assert not accepts(dfa, json.dumps({
            "question": 1, "thought": "t",
            "action": {"name": "n", "input": "i"},
            "observation": "o", "final_answer": "f",
        }))


class TestTokenMasking:
    def test_mask_admits_only_live_tokens(self):
        tok = ByteTokenizer()
        c = json_constraint(tok, None, depth=2)
        mask = c([])  # start state
        assert mask[ord("{")] and mask[ord('"')] and mask[ord("1")]
        assert not mask[ord("}")] and not mask[ord(",")]
        assert not mask[tok.eos_id]  # empty string is not JSON

        toks = list(b'{"a": 1')
        mask = c(toks)
        assert mask[ord("}")] and mask[ord("0")] and mask[ord(",")]
        assert not mask[ord("{")]
        toks += [ord("}")]
        mask = c(toks)
        assert mask[tok.eos_id]  # complete document: EOS admissible

    def test_incremental_state_tracking(self):
        tok = ByteTokenizer()
        c = json_constraint(tok, {"type": "boolean"})
        assert c([])[ord("t")] and c([])[ord("f")]
        m = c(list(b"tr"))
        assert m[ord("u")] and not m[ord("a")]
        m = c(list(b"true"))
        assert m[tok.eos_id]
        assert not m.any() or m.sum() == 1  # only EOS from the accept state

    def test_greedy_generation_yields_valid_json(self):
        """Drive the mask against a hostile 'model' that always proposes the
        lowest-id admissible token: the result must still parse."""
        tok = ByteTokenizer()
        c = json_constraint(tok, TOOLPROMPT_SCHEMA)
        out: list[int] = []
        # Prefer structure-closing bytes so the walk terminates; otherwise
        # the lowest admissible non-whitespace byte (a hostile-but-finite
        # policy: any admissible choice must stay inside the language).
        prefer = [ord(c_) for c_ in '"}]:,']
        ws = {9, 10, 13, 32}
        for _ in range(300):
            mask = c(out)
            if mask[tok.eos_id]:
                break
            ids = np.flatnonzero(mask)
            assert len(ids), "mask dead-ended"
            pick = next((p for p in prefer if p < len(mask) and mask[p]), None)
            if pick is None:
                pick = int(next(i for i in ids if int(i) not in ws))
            out.append(int(pick))
        doc = bytes(t for t in out if t < 256).decode()
        parsed = json.loads(doc)
        assert set(parsed) == {
            "question", "thought", "action", "observation", "final_answer"
        }


class TestEngineWiring:
    def test_response_format_constrains_engine_output(self):
        """tiny-test engine with random weights + json_object response_format
        must emit valid JSON (the whole point: garbage weights, valid wire)."""
        import jax.numpy as jnp

        from opsagent_tpu.serving.api import ServingStack
        from opsagent_tpu.serving.engine import Engine, EngineConfig

        eng = Engine(EngineConfig(
            model="tiny-test", dtype=jnp.float32, num_pages=64, page_size=8,
            max_pages_per_seq=16, max_batch_size=2, prefill_buckets=(32, 64),
        ))
        stack = ServingStack(eng)
        try:
            resp = stack.chat_completion({
                "messages": [{"role": "user", "content": "emit json"}],
                "max_tokens": 64,
                "temperature": 1.0,
                "response_format": {"type": "json_object"},
            })
            text = resp["choices"][0]["message"]["content"]
            if resp["choices"][0]["finish_reason"] == "stop":
                json.loads(text)  # complete → must parse
            else:  # length-capped: still a valid JSON prefix (live DFA state)
                from opsagent_tpu.serving.constrained import (
                    compile_regex, schema_to_regex,
                )
                dfa = compile_regex(schema_to_regex(None))
                assert dfa.run(dfa.start, text.encode()) >= 0
        finally:
            stack.close()

    def test_bad_response_format_is_400(self):
        import jax.numpy as jnp

        from opsagent_tpu.serving.api import ServingStack
        from opsagent_tpu.serving.engine import Engine, EngineConfig
        from opsagent_tpu.serving.scheduler import RequestError

        eng = Engine(EngineConfig(
            model="tiny-test", dtype=jnp.float32, num_pages=32, page_size=8,
            max_pages_per_seq=8, max_batch_size=2, prefill_buckets=(32,),
        ))
        stack = ServingStack(eng)
        try:
            with pytest.raises(RequestError) as ei:
                stack.chat_completion({
                    "messages": [{"role": "user", "content": "x"}],
                    "response_format": {"type": "yaml"},
                })
            assert ei.value.status == 400
        finally:
            stack.close()


class TestFSMCacheBounds:
    """Advisor findings: client-supplied schemas must not grow server memory
    without limit, and pathological schemas must be rejected with a 400-class
    error instead of compiling multi-GB tables."""

    def test_cache_is_lru_bounded(self):
        from opsagent_tpu.serving import constrained as C

        tok = ByteTokenizer()
        for i in range(C.FSM_CACHE_CAPACITY + 4):
            json_constraint(tok, {"type": "object", "properties": {
                f"key{i}": {"type": "string"},
            }})
        cache = tok.__dict__["_fsm_cache"]
        assert len(cache) == C.FSM_CACHE_CAPACITY

    def test_lru_keeps_recently_used(self):
        from opsagent_tpu.serving import constrained as C

        tok = ByteTokenizer()
        first = {"type": "object", "properties": {"keep": {"type": "string"}}}
        json_constraint(tok, first)
        fsm_first = next(iter(tok.__dict__["_fsm_cache"].values()))
        for i in range(C.FSM_CACHE_CAPACITY - 1):
            json_constraint(tok, {"enum": [f"v{i}"]})
        json_constraint(tok, first)  # touch: moves to MRU
        json_constraint(tok, {"enum": ["evictor"]})  # evicts true LRU
        assert fsm_first in tok.__dict__["_fsm_cache"].values()

    def test_oversized_schema_rejected(self, monkeypatch):
        from opsagent_tpu.serving import constrained as C

        monkeypatch.setattr(C, "MAX_DFA_STATES", 10)
        tok = ByteTokenizer()
        with pytest.raises(ValueError, match="DFA states"):
            C.json_constraint(tok, None, depth=3)

    def test_native_tables_gated_on_budget(self, monkeypatch):
        """A DFA whose [states, vocab] tables exceed the budget must stay on
        the lazy numpy path (the eager native precompute at a 131k vocab
        would allocate GBs for the schemaless json_object DFA)."""
        from opsagent_tpu.serving import constrained as C

        monkeypatch.setattr(C, "NATIVE_TABLE_BUDGET", 0)
        tok = ByteTokenizer()
        dfa = C.compile_regex(C.schema_to_regex({"type": "boolean"}))
        tb = [tok.token_bytes(t) for t in range(tok.vocab_size)]
        fsm = C.TokenFSM(dfa, tb, tok.eos_id)
        assert fsm._native is None
        # Masks still work via the lazy path.
        mask = fsm.mask_for_state(dfa.start)
        assert mask[ord("t")] and mask[ord("f")] and not mask[ord("x")]


class TestForcedRuns:
    """Fast-forward precompute: states whose legal-token mask is a singleton
    expose the forced token (and whole forced runs) without a forward pass."""

    STATUS_SCHEMA = {
        "type": "object",
        "properties": {"status": {"enum": ["ok"]}},
        "required": ["status"],
    }

    @pytest.fixture(scope="class")
    def con(self):
        return json_constraint(ByteTokenizer(), self.STATUS_SCHEMA)

    def test_forced_token_singleton_only(self, con):
        fsm, dfa = con.fsm, con.fsm.dfa
        # Object punctuation: '{' is the only way to open the document.
        assert fsm.forced_token(dfa.start) == ord("{")
        # After '{"status"' both ':' and whitespace are live: not forced.
        st = dfa.run(dfa.start, b'{"status"')
        assert fsm.forced_token(st) is None
        assert np.flatnonzero(fsm.mask_for_state(st)).size > 1

    def test_known_key_name_is_forced(self, con):
        fsm, dfa = con.fsm, con.fsm.dfa
        st = dfa.run(dfa.start, b'{"')
        run = fsm.forced_run(st)
        assert bytes(run) == b'status"'
        # Walking the run by hand hits singleton masks at every step.
        for tok_id in run:
            assert fsm.forced_token(st) == tok_id
            st = fsm.advance(st, tok_id)
        assert st >= 0

    def test_enum_close_quote_is_forced(self, con):
        fsm, dfa = con.fsm, con.fsm.dfa
        st = dfa.run(dfa.start, b'{"status": "o')
        assert bytes(fsm.forced_run(st)) == b'k"'

    def test_accept_state_run_terminates_with_eos(self, con):
        tok = ByteTokenizer()
        fsm, dfa = con.fsm, con.fsm.dfa
        st = dfa.run(dfa.start, b'{"status": "ok"}')
        assert dfa.accept[st]
        assert fsm.forced_run(st) == [tok.eos_id]
        assert fsm.forced_token(st) == tok.eos_id

    def test_run_capped_at_forced_run_cap(self):
        from opsagent_tpu.serving import constrained as C

        con = json_constraint(ByteTokenizer(), {"enum": ["a" * 40]})
        fsm, dfa = con.fsm, con.fsm.dfa
        st = dfa.run(dfa.start, b'"a')
        run = fsm.forced_run(st)
        assert len(run) == C.FORCED_RUN_CAP
        assert bytes(run) == b"a" * C.FORCED_RUN_CAP

    def test_forced_run_table_matches_scalar_api(self, con):
        from opsagent_tpu.serving import constrained as C

        fsm = con.fsm
        toks, lens = fsm.forced_run_table()
        n_states = fsm.dfa.next.size // 256
        assert toks.shape == (n_states + 1, C.FORCED_RUN_CAP)
        assert lens.shape == (n_states + 1,)
        assert lens[0] == 0  # row 0 is the FREE sentinel: nothing forced
        for s in range(n_states):  # device row s+1 mirrors DFA state s
            assert list(toks[s + 1, : lens[s + 1]]) == fsm.forced_run(s)

    def test_constraint_level_forced_run_tracks_tokens(self, con):
        toks = list(b'{"status": "o')
        assert bytes(con.forced_run(toks)) == b'k"'
        # Incremental state must survive interleaved mask queries.
        con(toks)
        assert bytes(con.forced_run(toks + [ord("k")])) == b'"'
        assert con.forced_run(list(b'{"status"')) == []
