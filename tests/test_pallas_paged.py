"""Pallas paged-decode-attention kernel vs the XLA gather reference.

Runs the kernel in interpreter mode on CPU (the TPU-lowered path shares the
same trace), asserting numerical equivalence with
``ops.attention.paged_decode_attention`` across ragged lengths, GQA group
sizes, multi-page sequences, and inactive (length-0) batch slots.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from opsagent_tpu.ops.attention import paged_decode_attention
from opsagent_tpu.ops.paged_attention_pallas import (
    paged_decode_attention_pallas,
    paged_decode_attention_pallas_dma,
)

KERNELS = [paged_decode_attention_pallas, paged_decode_attention_pallas_dma]


def _make_case(
    rng, B, H, K, D, P, MaxP, num_pages, lengths,
):
    """Random paged KV state with each sequence owning disjoint pages."""
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k_pages = jnp.asarray(rng.standard_normal((num_pages, P, K, D)), jnp.float32)
    v_pages = jnp.asarray(rng.standard_normal((num_pages, P, K, D)), jnp.float32)
    table = np.full((B, MaxP), -1, np.int32)
    free = list(range(num_pages))
    rng.shuffle(free)
    for b, n in enumerate(lengths):
        need = -(-n // P)
        for i in range(need):
            table[b, i] = free.pop()
    return q, k_pages, v_pages, jnp.asarray(table), jnp.asarray(lengths, jnp.int32)


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize(
    "B,H,K,D,P,MaxP,lengths",
    [
        (2, 4, 2, 64, 8, 4, [5, 17]),          # GQA, ragged, multi-page
        (1, 2, 2, 32, 4, 6, [24]),             # MHA (G=1), exactly full pages
        (3, 8, 2, 16, 8, 3, [1, 8, 20]),       # boundary lengths
        (2, 4, 4, 32, 8, 4, [9, 0]),           # inactive slot (length 0)
    ],
)
def test_pallas_matches_xla_reference(B, H, K, D, P, MaxP, lengths, kernel):
    rng = np.random.default_rng(0)
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B, H, K, D, P, MaxP, num_pages=B * MaxP + 2, lengths=lengths
    )
    ref = paged_decode_attention(q, k_pages, v_pages, table, lens)
    got = kernel(
        q, k_pages, v_pages, table, lens, interpret=True
    )
    # Inactive slots: the kernel defines them as zeros; the reference
    # produces attention over a masked-everything row (softmax of -inf) —
    # compare only active rows, then check the kernel's zeros.
    active = np.asarray(lens) > 0
    np.testing.assert_allclose(
        np.asarray(got)[active], np.asarray(ref)[active], rtol=2e-5, atol=2e-5
    )
    assert not np.isnan(np.asarray(got)).any()
    if (~active).any():
        np.testing.assert_array_equal(np.asarray(got)[~active], 0.0)


@pytest.mark.parametrize("kernel", KERNELS)
def test_pallas_bf16_tolerance(kernel):
    rng = np.random.default_rng(1)
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B=2, H=4, K=2, D=64, P=8, MaxP=4, num_pages=12, lengths=[13, 29]
    )
    q, k_pages, v_pages = (
        x.astype(jnp.bfloat16) for x in (q, k_pages, v_pages)
    )
    ref = paged_decode_attention(q, k_pages, v_pages, table, lens)
    got = kernel(
        q, k_pages, v_pages, table, lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_decode_step_with_pallas_impl_matches_xla():
    """End-to-end: llama.decode_step with attn_impl="pallas" (interpret via
    env is not available, so call through the model with monkeypatched
    dispatcher interpret flag) equals the xla impl."""
    from opsagent_tpu.models import llama
    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.ops import paged_attention_pallas as pp

    cfg = get_config_preset("tiny-test")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    P, NP, MaxP, B = 8, 16, 4, 2
    cache = llama.make_cache(cfg, NP, P, dtype=jnp.float32)

    # Prefill two sequences to populate pages.
    lens = [5, 9]
    table = np.full((B, MaxP), -1, np.int32)
    table[0, :2] = [0, 1]
    table[1, :2] = [2, 3]
    S = 16
    tokens = np.zeros((B, S), np.int32)
    rng = np.random.default_rng(2)
    for b, n in enumerate(lens):
        tokens[b, :n] = rng.integers(1, cfg.vocab_size, n)
    logits, cache = llama.prefill(
        params, cfg, jnp.asarray(tokens), jnp.asarray(lens, jnp.int32),
        cache, jnp.asarray(table), dtype=jnp.float32,
    )

    step_args = (
        jnp.asarray([7, 8], jnp.int32),
        jnp.asarray(lens, jnp.int32),
    )
    out_xla, _ = llama.decode_step(
        params, cfg, step_args[0], step_args[1], cache,
        jnp.asarray(table), jnp.asarray([True, True]),
        dtype=jnp.float32, attn_impl="xla",
    )

    # Force interpret mode inside the pallas path for the CPU test.
    orig = pp.paged_decode_attention_pallas

    def interp(q, k, v, t, ln, interpret=False, layer=None):
        return orig(q, k, v, t, ln, interpret=True, layer=layer)

    pp.paged_decode_attention_pallas = interp
    try:
        out_pl, _ = llama.decode_step(
            params, cfg, step_args[0], step_args[1], cache,
            jnp.asarray(table), jnp.asarray([True, True]),
            dtype=jnp.float32, attn_impl="pallas",
        )
    finally:
        pp.paged_decode_attention_pallas = orig
    np.testing.assert_allclose(
        np.asarray(out_xla), np.asarray(out_pl), rtol=1e-4, atol=1e-4
    )


def test_pallas_under_tp_matches_oracle():
    """VERDICT item: the kernel must run under tensor parallelism. shard_map
    over a tp=2 mesh (q heads + kv heads both tp-sharded) must reproduce the
    unsharded XLA oracle — per-shard GQA needs no collective."""
    from opsagent_tpu.ops.attention import paged_decode_attention_pallas_tp
    from opsagent_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=2, dp=1, sp=1, devices=jax.devices()[:2])
    rng = np.random.default_rng(3)
    # K=2 kv heads (1 per shard), H=4 query heads (2 per shard), G=2.
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B=2, H=4, K=2, D=64, P=8, MaxP=4, num_pages=10,
        lengths=[5, 17],
    )
    ref = paged_decode_attention(q, k_pages, v_pages, table, lens)
    got = paged_decode_attention_pallas_tp(
        q, k_pages, v_pages, table, lens, mesh, interpret=True
    )
    active = np.asarray(lens) > 0
    np.testing.assert_allclose(
        np.asarray(got)[active], np.asarray(ref)[active], rtol=2e-5, atol=2e-5
    )


def test_pallas_under_tp_layer_form():
    """The tp wrapper with the whole-cache [L, N, P, K, D] form + layer
    offset must select the right layer's pages per shard."""
    from opsagent_tpu.ops.attention import paged_decode_attention_pallas_tp
    from opsagent_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=2, dp=1, sp=1, devices=jax.devices()[:2])
    rng = np.random.default_rng(4)
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B=2, H=4, K=2, D=32, P=8, MaxP=3, num_pages=8,
        lengths=[9, 20],
    )
    L = 3
    k_l = jnp.stack([
        jnp.asarray(rng.standard_normal(k_pages.shape), jnp.float32)
        for _ in range(L)
    ])
    v_l = jnp.stack([
        jnp.asarray(rng.standard_normal(v_pages.shape), jnp.float32)
        for _ in range(L)
    ])
    for layer in (0, 2):
        ref = paged_decode_attention(
            q, k_l[layer], v_l[layer], table, lens
        )
        got = paged_decode_attention_pallas_tp(
            q, k_l, v_l, table, lens, mesh,
            layer=jnp.int32(layer), interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_pallas_dma_under_tp_matches_oracle():
    """The manual-DMA kernel under tensor parallelism (impl dispatch)."""
    from opsagent_tpu.ops.attention import paged_decode_attention_pallas_tp
    from opsagent_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=2, dp=1, sp=1, devices=jax.devices()[:2])
    rng = np.random.default_rng(5)
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B=2, H=4, K=2, D=64, P=8, MaxP=4, num_pages=10,
        lengths=[5, 17],
    )
    ref = paged_decode_attention(q, k_pages, v_pages, table, lens)
    got = paged_decode_attention_pallas_tp(
        q, k_pages, v_pages, table, lens, mesh, interpret=True,
        impl="pallas-dma",
    )
    active = np.asarray(lens) > 0
    np.testing.assert_allclose(
        np.asarray(got)[active], np.asarray(ref)[active], rtol=2e-5, atol=2e-5
    )


def test_pallas_dma_layer_form():
    """Whole-cache [L, N, P, K, D] + layer offset on the DMA kernel."""
    rng = np.random.default_rng(6)
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B=2, H=4, K=2, D=32, P=8, MaxP=3, num_pages=8,
        lengths=[9, 20],
    )
    L = 3
    k_l = jnp.stack([
        jnp.asarray(rng.standard_normal(k_pages.shape), jnp.float32)
        for _ in range(L)
    ])
    v_l = jnp.stack([
        jnp.asarray(rng.standard_normal(v_pages.shape), jnp.float32)
        for _ in range(L)
    ])
    for layer in (0, 2):
        ref = paged_decode_attention(q, k_l[layer], v_l[layer], table, lens)
        got = paged_decode_attention_pallas_dma(
            q, k_l, v_l, table, lens, interpret=True, layer=jnp.int32(layer)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


@pytest.mark.slow
def test_pallas_dma_at_bench_8b_decode_shape():
    """Interpret-mode parity at the EXACT bench-8b decode shape (B=32,
    K=8, D=128, P=64, MaxP=12, bf16 pages, ragged lengths): the shape the
    on-chip kernel sweep runs, validated before burning chip time on it.
    Reduced batch rows would hide grid/scratch sizing mistakes that only
    appear at the serving shape."""
    rng = np.random.default_rng(42)
    B, H, K, D, P, MaxP = 32, 32, 8, 128, 64, 12
    lengths = [int(rng.integers(1, MaxP * P + 1)) for _ in range(B)]
    lengths[0] = MaxP * P  # pin the exactly-full boundary the bench reaches
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B, H, K, D, P, MaxP, num_pages=B * MaxP + 2, lengths=lengths
    )
    q = q.astype(jnp.bfloat16)
    k_pages = k_pages.astype(jnp.bfloat16)
    v_pages = v_pages.astype(jnp.bfloat16)
    ref = paged_decode_attention(q, k_pages, v_pages, table, lens)
    got = paged_decode_attention_pallas_dma(
        q, k_pages, v_pages, table, lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=3e-2, atol=3e-2,
    )


def test_pallas_dma_rejects_unaligned_head_dim():
    """Compiled mode refuses head_dim % 128 != 0 up front (Mosaic's
    manual-DMA slices must be 128-aligned on the minormost dim; r04
    on-chip failure) instead of a deep Mosaic error."""
    rng = np.random.default_rng(11)
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B=1, H=4, K=2, D=64, P=8, MaxP=2, num_pages=4, lengths=[8]
    )
    with pytest.raises(ValueError, match="head_dim"):
        paged_decode_attention_pallas_dma(
            q, k_pages, v_pages, table, lens, interpret=False
        )


def test_engine_falls_back_from_pallas_dma_on_small_head_dim(monkeypatch):
    """tiny-test (head_dim 16) + OPSAGENT_PAGED_BACKEND=pallas-dma must
    resolve to the xla gather, not die in Mosaic at first prefill."""
    monkeypatch.setenv("OPSAGENT_PAGED_BACKEND", "pallas-dma")
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    eng = Engine(EngineConfig(
        model="tiny-test", max_batch_size=2, num_pages=16, page_size=8,
        max_pages_per_seq=4, prefill_buckets=(16,), decode_block=4,
    ))
    assert eng.attn_impl == "xla"


def test_pallas_dma_length_beyond_table_clamps():
    """lengths > MaxP*P (tolerated by the grid kernel via clamping) must
    not read the page table out of bounds or leak a prefetch DMA."""
    rng = np.random.default_rng(7)
    q, k_pages, v_pages, table, lens = _make_case(
        rng, B=2, H=4, K=2, D=32, P=8, MaxP=3, num_pages=8,
        lengths=[24, 24],  # exactly fills all 3 pages
    )
    over = jnp.asarray([24, 40], jnp.int32)  # row 1 claims 5 pages of 3
    ref = paged_decode_attention(q, k_pages, v_pages, table, jnp.asarray([24, 24], jnp.int32))
    got = paged_decode_attention_pallas_dma(
        q, k_pages, v_pages, table, over, interpret=True
    )
    # Row 0 is unaffected; row 1 attends over its 3 real pages only (the
    # reference clamps identically), and nothing NaNs.
    np.testing.assert_allclose(
        np.asarray(got)[0], np.asarray(ref)[0], rtol=2e-5, atol=2e-5
    )
    assert not np.isnan(np.asarray(got)).any()


# -- ragged-query kernel (mixed prefill+decode step) -------------------------
def _make_ragged_case(rng, B, S, H, K, D, P, MaxP, num_pages, start, q_lens):
    """Random paged KV state for the ragged kernel: each row owns enough
    pages for start + q_len tokens (the chunk's KV is treated as already
    written, like the engine after write_kv_pages)."""
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    k_pages = jnp.asarray(
        rng.standard_normal((num_pages, P, K, D)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.standard_normal((num_pages, P, K, D)), jnp.float32
    )
    table = np.full((B, MaxP), -1, np.int32)
    free = list(range(num_pages))
    rng.shuffle(free)
    for b in range(B):
        need = -(-(start[b] + q_lens[b]) // P)
        for i in range(need):
            table[b, i] = free.pop()
    return (
        q, k_pages, v_pages, jnp.asarray(table),
        jnp.asarray(start, jnp.int32), jnp.asarray(q_lens, jnp.int32),
    )


@pytest.mark.parametrize(
    "B,S,H,K,D,P,MaxP,start,q_lens",
    [
        # decode row (q_len=1) + prefill chunk + inactive row in one batch
        (3, 8, 4, 2, 32, 4, 8, [9, 4, 0], [1, 6, 0]),
        # fresh prompt chunk from position 0, full S
        (2, 8, 4, 4, 16, 8, 4, [0, 0], [8, 3]),
        # chunk crossing page boundaries with a long cached prefix
        (2, 4, 8, 2, 32, 4, 10, [13, 30], [4, 2]),
    ],
)
def test_ragged_pallas_matches_xla_reference(
    B, S, H, K, D, P, MaxP, start, q_lens
):
    from opsagent_tpu.ops.attention import paged_ragged_attention
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas,
    )

    rng = np.random.default_rng(11)
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B, S, H, K, D, P, MaxP, num_pages=B * MaxP + 2,
        start=start, q_lens=q_lens,
    )
    ref = paged_ragged_attention(q, k_pages, v_pages, table, st, ql)
    got = paged_ragged_attention_pallas(
        q, k_pages, v_pages, table, st, ql, interpret=True
    )
    # Compare only valid query rows; padded rows (s >= q_len) are garbage
    # in both but must stay finite.
    for b in range(B):
        n = q_lens[b]
        if n:
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], np.asarray(ref)[b, :n],
                rtol=2e-5, atol=2e-5,
            )
    assert np.isfinite(np.asarray(got)).all()


def test_ragged_decode_row_matches_decode_kernel_semantics():
    """A q_len=1 ragged row must equal single-token decode attention over
    the same cache state (the mixed step's decode-lane guarantee)."""
    from opsagent_tpu.ops.attention import (
        paged_decode_attention, paged_ragged_attention,
    )

    rng = np.random.default_rng(12)
    B, S, H, K, D, P, MaxP = 2, 4, 4, 2, 32, 4, 6
    start = [7, 14]
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B, S, H, K, D, P, MaxP, num_pages=B * MaxP + 2,
        start=start, q_lens=[1, 1],
    )
    ragged = paged_ragged_attention(q, k_pages, v_pages, table, st, ql)
    dec = paged_decode_attention(
        q[:, 0], k_pages, v_pages, table, st + 1
    )
    np.testing.assert_allclose(
        np.asarray(ragged)[:, 0], np.asarray(dec), rtol=2e-5, atol=2e-5
    )


# -- ragged manual-DMA kernel (the mixed hot path's bytes-diet form) ---------
@pytest.mark.parametrize(
    "B,S,H,K,D,P,MaxP,start,q_lens",
    [
        # decode row (q_len=1) + prefill chunk + inactive row in one batch
        (3, 8, 4, 2, 32, 4, 8, [9, 4, 0], [1, 6, 0]),
        # fresh prompt chunk from position 0, full S
        (2, 8, 4, 4, 16, 8, 4, [0, 0], [8, 3]),
        # chunk crossing page boundaries with a long cached prefix
        (2, 4, 8, 2, 32, 4, 10, [13, 30], [4, 2]),
        # all-decode tick (the steady-state mixed shape) + inactive rows
        (4, 4, 4, 2, 16, 4, 6, [7, 3, 0, 15], [1, 1, 0, 1]),
    ],
)
def test_ragged_dma_matches_xla_reference(
    B, S, H, K, D, P, MaxP, start, q_lens
):
    from opsagent_tpu.ops.attention import paged_ragged_attention
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas_dma,
    )

    rng = np.random.default_rng(21)
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B, S, H, K, D, P, MaxP, num_pages=B * MaxP + 2,
        start=start, q_lens=q_lens,
    )
    ref = paged_ragged_attention(q, k_pages, v_pages, table, st, ql)
    got = paged_ragged_attention_pallas_dma(
        q, k_pages, v_pages, table, st, ql, interpret=True
    )
    for b in range(B):
        n = q_lens[b]
        if n:
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], np.asarray(ref)[b, :n],
                rtol=2e-5, atol=2e-5,
            )
        else:
            # q_len=0 rows stream ZERO pages (n=0 warmup skip) and must
            # come out exactly zero, not garbage.
            assert (np.asarray(got)[b] == 0).all()
    assert np.isfinite(np.asarray(got)).all()


def test_ragged_dma_bf16_tolerance():
    from opsagent_tpu.ops.attention import paged_ragged_attention
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas_dma,
    )

    rng = np.random.default_rng(22)
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B=2, S=8, H=4, K=2, D=32, P=4, MaxP=8,
        num_pages=18, start=[9, 0], q_lens=[1, 8],
    )
    q = q.astype(jnp.bfloat16)
    k_pages = k_pages.astype(jnp.bfloat16)
    v_pages = v_pages.astype(jnp.bfloat16)
    ref = paged_ragged_attention(q, k_pages, v_pages, table, st, ql)
    got = paged_ragged_attention_pallas_dma(
        q, k_pages, v_pages, table, st, ql, interpret=True
    )
    for b, n in enumerate([1, 8]):
        np.testing.assert_allclose(
            np.asarray(got, np.float32)[b, :n],
            np.asarray(ref, np.float32)[b, :n],
            rtol=3e-2, atol=3e-2,
        )


def test_ragged_dma_quantized_matches_xla_reader():
    """int8 QuantizedPages through the ragged DMA kernel (interpret) must
    match the XLA ragged gather on the SAME quantized cache — identical
    dequantize math, pages never materialized full-dtype."""
    from opsagent_tpu.ops.attention import (
        QuantizedPages, paged_ragged_attention, write_kv_pages,
    )
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas_dma,
    )

    rng = np.random.default_rng(23)
    B, S, H, K, D, P, MaxP, N = 3, 8, 4, 2, 32, 4, 8, 26
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B, S, H, K, D, P, MaxP, num_pages=N,
        start=[9, 0, 4], q_lens=[1, 8, 0],
    )
    kq = QuantizedPages(
        jnp.zeros((N, P, K, D), jnp.int8), jnp.ones((N, P, K), jnp.float32)
    )
    vq = QuantizedPages(
        jnp.zeros((N, P, K, D), jnp.int8), jnp.ones((N, P, K), jnp.float32)
    )
    # Fill each row's resident KV (cached prefix + chunk) through the
    # real write path so scales are per-token absmax, like the engine.
    total = int(max(s + l for s, l in zip([9, 0, 4], [1, 8, 0])))
    kw = jnp.asarray(rng.standard_normal((B, total, K, D)), jnp.float32)
    vw = jnp.asarray(rng.standard_normal((B, total, K, D)), jnp.float32)
    kq, vq = write_kv_pages(
        kq, vq, kw, vw, table, jnp.zeros((B,), jnp.int32),
        valid_len=st + ql,
    )
    ref = paged_ragged_attention(q, kq, vq, table, st, ql)
    got = paged_ragged_attention_pallas_dma(
        q, kq, vq, table, st, ql, interpret=True
    )
    for b, n in enumerate([1, 8, 0]):
        if n:
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], np.asarray(ref)[b, :n],
                rtol=2e-5, atol=2e-5,
            )


def test_ragged_dma_layer_form():
    """Whole-cache [L, N, P, K, D] + layer offset on the ragged DMA
    kernel selects the right layer's pages."""
    from opsagent_tpu.ops.attention import paged_ragged_attention
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas_dma,
    )

    rng = np.random.default_rng(24)
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B=2, S=4, H=4, K=2, D=32, P=4, MaxP=6,
        num_pages=14, start=[9, 0], q_lens=[1, 4],
    )
    L = 3
    k_l = jnp.stack([
        jnp.asarray(rng.standard_normal(k_pages.shape), jnp.float32)
        for _ in range(L)
    ])
    v_l = jnp.stack([
        jnp.asarray(rng.standard_normal(v_pages.shape), jnp.float32)
        for _ in range(L)
    ])
    for layer in (0, 2):
        ref = paged_ragged_attention(
            q, k_l[layer], v_l[layer], table, st, ql
        )
        got = paged_ragged_attention_pallas_dma(
            q, k_l, v_l, table, st, ql,
            interpret=True, layer=jnp.int32(layer),
        )
        for b, n in enumerate([1, 4]):
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], np.asarray(ref)[b, :n],
                rtol=2e-5, atol=2e-5,
            )


def test_ragged_dma_under_tp_matches_oracle():
    """The ragged DMA kernel under tensor parallelism (impl dispatch in
    the shared TP wrapper): tp=2 mesh, q + kv heads sharded, no
    collective — must reproduce the unsharded XLA ragged oracle."""
    from opsagent_tpu.ops.attention import (
        paged_ragged_attention, paged_ragged_attention_pallas_tp,
    )
    from opsagent_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=2, dp=1, sp=1, devices=jax.devices()[:2])
    rng = np.random.default_rng(25)
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B=2, S=8, H=4, K=2, D=32, P=4, MaxP=8,
        num_pages=18, start=[9, 0], q_lens=[1, 8],
    )
    ref = paged_ragged_attention(q, k_pages, v_pages, table, st, ql)
    got = paged_ragged_attention_pallas_tp(
        q, k_pages, v_pages, table, st, ql, mesh,
        interpret=True, impl="pallas-dma",
    )
    for b, n in enumerate([1, 8]):
        np.testing.assert_allclose(
            np.asarray(got)[b, :n], np.asarray(ref)[b, :n],
            rtol=2e-5, atol=2e-5,
        )


def test_ragged_dma_rejects_unaligned_head_dim():
    """Compiled mode refuses head_dim % 128 != 0 up front (the same
    Mosaic manual-DMA alignment rule as the decode kernel)."""
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas_dma,
    )

    rng = np.random.default_rng(26)
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B=1, S=4, H=4, K=2, D=64, P=4, MaxP=2,
        num_pages=4, start=[0], q_lens=[4],
    )
    with pytest.raises(ValueError, match="head_dim"):
        paged_ragged_attention_pallas_dma(
            q, k_pages, v_pages, table, st, ql, interpret=False
        )


def test_ragged_dma_length_beyond_table_clamps():
    """start + q_len claiming more pages than the table holds must clamp
    to resident pages (like the decode kernel) — no OOB table read, no
    leaked prefetch DMA, no NaN."""
    from opsagent_tpu.ops.attention import paged_ragged_attention
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas_dma,
    )

    rng = np.random.default_rng(27)
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B=2, S=4, H=4, K=2, D=32, P=4, MaxP=3,
        num_pages=8, start=[11, 11], q_lens=[1, 1],
    )
    over = jnp.asarray([11, 27], jnp.int32)  # row 1 claims 7 pages of 3
    ref = paged_ragged_attention(q, k_pages, v_pages, table, st, ql)
    got = paged_ragged_attention_pallas_dma(
        q, k_pages, v_pages, table, over, ql, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got)[0, :1], np.asarray(ref)[0, :1], rtol=2e-5, atol=2e-5
    )
    assert not np.isnan(np.asarray(got)).any()


def _quantized_case(rng, B, S, H, K, D, P, MaxP, N, start, q_lens):
    """int8 QuantizedPages filled through the real write path (per-token
    absmax scales, like the engine), for the grid-kernel scale tests."""
    from opsagent_tpu.ops.attention import QuantizedPages, write_kv_pages

    q, _, _, table, st, ql = _make_ragged_case(
        rng, B, S, H, K, D, P, MaxP, num_pages=N, start=start, q_lens=q_lens,
    )
    kq = QuantizedPages(
        jnp.zeros((N, P, K, D), jnp.int8), jnp.ones((N, P, K), jnp.float32)
    )
    vq = QuantizedPages(
        jnp.zeros((N, P, K, D), jnp.int8), jnp.ones((N, P, K), jnp.float32)
    )
    total = int(max(s + l for s, l in zip(start, q_lens)))
    kw = jnp.asarray(rng.standard_normal((B, total, K, D)), jnp.float32)
    vw = jnp.asarray(rng.standard_normal((B, total, K, D)), jnp.float32)
    kq, vq = write_kv_pages(
        kq, vq, kw, vw, table, jnp.zeros((B,), jnp.int32), valid_len=st + ql,
    )
    return q, kq, vq, table, st, ql


@pytest.mark.parametrize(
    "start,q_lens",
    [
        ([9, 0, 4], [1, 8, 0]),   # decode row + chunk + inactive row
        ([13, 30, 0], [4, 2, 8]), # page-crossing chunks, fresh prompt
    ],
)
def test_ragged_grid_quantized_matches_xla_reader(start, q_lens):
    """int8 QuantizedPages through the plain-pallas RAGGED GRID kernel
    (interpret): the score-space scale path (k scales multiply scores,
    v scales multiply probabilities) must match the XLA ragged gather on
    the SAME quantized cache — this is the cell the sweep previously
    silently resolved to xla."""
    from opsagent_tpu.ops.attention import paged_ragged_attention
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas,
    )

    rng = np.random.default_rng(31)
    q, kq, vq, table, st, ql = _quantized_case(
        rng, B=3, S=8, H=4, K=2, D=32, P=4, MaxP=10, N=32,
        start=start, q_lens=q_lens,
    )
    ref = paged_ragged_attention(q, kq, vq, table, st, ql)
    got = paged_ragged_attention_pallas(q, kq, vq, table, st, ql,
                                        interpret=True)
    for b, n in enumerate(q_lens):
        if n:
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], np.asarray(ref)[b, :n],
                rtol=2e-5, atol=2e-5,
            )
    assert np.isfinite(np.asarray(got)).all()


def test_decode_grid_quantized_matches_xla_reader():
    """int8 QuantizedPages through the plain-pallas DECODE grid kernel
    (interpret) vs the XLA gather on the same quantized cache."""
    from opsagent_tpu.ops.attention import paged_decode_attention
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_pallas,
    )

    rng = np.random.default_rng(32)
    lengths = [5, 17, 1]
    q, kq, vq, table, st, ql = _quantized_case(
        rng, B=3, S=1, H=4, K=2, D=32, P=4, MaxP=8, N=26,
        start=[n - 1 for n in lengths], q_lens=[1, 1, 1],
    )
    lens = jnp.asarray(lengths, jnp.int32)
    ref = paged_decode_attention(q[:, 0], kq, vq, table, lens)
    got = paged_decode_attention_pallas(
        q[:, 0], kq, vq, table, lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_auto_dispatch_keeps_pallas_backend_for_quantized_pages(monkeypatch):
    """The auto dispatchers no longer demote QuantizedPages to xla: with
    OPSAGENT_PAGED_BACKEND=pallas the grid kernel runs (and matches the
    gather), for both the decode and ragged entry points."""
    from opsagent_tpu.ops.attention import (
        paged_decode_attention, paged_decode_attention_auto,
        paged_ragged_attention, paged_ragged_attention_auto,
    )

    monkeypatch.setenv("OPSAGENT_PAGED_BACKEND", "pallas")
    monkeypatch.setenv("OPSAGENT_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(33)
    q, kq, vq, table, st, ql = _quantized_case(
        rng, B=2, S=8, H=4, K=2, D=32, P=4, MaxP=8, N=18,
        start=[9, 0], q_lens=[1, 8],
    )
    ref = paged_ragged_attention(q, kq, vq, table, st, ql)
    got = paged_ragged_attention_auto(q, kq, vq, table, st, ql)
    for b, n in enumerate([1, 8]):
        np.testing.assert_allclose(
            np.asarray(got)[b, :n], np.asarray(ref)[b, :n],
            rtol=2e-5, atol=2e-5,
        )
    lens = st + ql
    ref_d = paged_decode_attention(q[:, 0], kq, vq, table, lens)
    got_d = paged_decode_attention_auto(q[:, 0], kq, vq, table, lens)
    np.testing.assert_allclose(
        np.asarray(got_d), np.asarray(ref_d), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_ragged_dma_at_bench_8b_mixed_shape():
    """Interpret parity at the EXACT bench-8b mixed decode-tick shape
    (B=32, S=4 bucket, H=32, K=8, D=128, P=64, bf16): all-decode rows at
    ragged positions plus one admitting chunk row — the sweep stage's
    steady-state dispatch, validated before burning chip time."""
    from opsagent_tpu.ops.attention import paged_ragged_attention
    from opsagent_tpu.ops.paged_attention_pallas import (
        paged_ragged_attention_pallas_dma,
    )

    rng = np.random.default_rng(28)
    B, S, H, K, D, P, MaxP = 32, 4, 32, 8, 128, 64, 12
    start = [int(rng.integers(0, MaxP * P - S)) for _ in range(B)]
    q_lens = [1] * B
    q_lens[-1] = S  # one admitting chunk row rides along
    q_lens[5] = 0   # and one inactive slot
    q, k_pages, v_pages, table, st, ql = _make_ragged_case(
        rng, B, S, H, K, D, P, MaxP, num_pages=B * MaxP + 2,
        start=start, q_lens=q_lens,
    )
    q = q.astype(jnp.bfloat16)
    k_pages = k_pages.astype(jnp.bfloat16)
    v_pages = v_pages.astype(jnp.bfloat16)
    ref = paged_ragged_attention(q, k_pages, v_pages, table, st, ql)
    got = paged_ragged_attention_pallas_dma(
        q, k_pages, v_pages, table, st, ql, interpret=True
    )
    for b in range(B):
        n = q_lens[b]
        if n:
            np.testing.assert_allclose(
                np.asarray(got, np.float32)[b, :n],
                np.asarray(ref, np.float32)[b, :n],
                rtol=3e-2, atol=3e-2,
            )
