"""The bench orchestrator's driver contract, tested with fake children.

BENCH_r02.json was rc=124 with zero data; the restructured bench.py must
guarantee: (a) a wedged child cannot eat the whole budget — it is killed
at its stage cap and the cpu fallback still produces a parsed line;
(b) every earned result is flushed immediately; (c) the LAST line printed
is the headline with the other stages folded into extra. These tests run
the orchestrator with OPSAGENT_BENCH_BUDGET tightened and fake children
via a stub bench script, plus the real cpu fallback path.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(env_extra: dict, timeout=420):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def test_single_mode_prints_parseable_json():
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "2",
        "OPSAGENT_BENCH_STEPS": "8",
    })
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["unit"] == "tok/s/chip"
    assert "metric" in parsed and "vs_baseline" in parsed
    assert parsed["extra"]["platform"] == "cpu"


def test_orchestrated_cpu_ends_with_headline_json():
    """On a cpu-only host the orchestrator runs the default child (which
    picks tiny-test), prints its line immediately, and ends with the
    combined headline — parseable as the LAST line, the driver contract."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_BUDGET": "300",
        # Keep the default child fast on one core.
        "OPSAGENT_BENCH_BATCH": "2",
        "OPSAGENT_BENCH_STEPS": "8",
    })
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    assert len(lines) >= 2  # stage line + combined headline
    first, last = json.loads(lines[0]), json.loads(lines[-1])
    assert first["metric"] == last["metric"]
    assert last["unit"] == "tok/s/chip"


def test_wedged_child_killed_and_fallback_lands(tmp_path):
    """A child that hangs forever (the wedged-TPU failure mode) must be
    killed at the stage cap, and the cpu fallback must still produce a
    parsed line within the budget."""
    # Wedge the DEFAULT child only: a sitecustomize that sleeps forever in
    # a bench child with no explicit model — the cpu fallback child sets
    # OPSAGENT_BENCH_MODEL and escapes (the orchestrator's env markers are
    # the only reliable discriminator; conftest pins JAX_PLATFORMS=cpu for
    # the whole process tree).
    site = tmp_path / "sitecustomize.py"
    site.write_text(
        "import os, time\n"
        "if (os.environ.get('_OPSAGENT_BENCH_CHILD')\n"
        "        and not os.environ.get('OPSAGENT_BENCH_MODEL')):\n"
        "    time.sleep(3600)\n"
    )
    # No explicit stage-1 cap: the orchestrator's fallback RESERVE must
    # clamp it (budget 300 -> cap 80), so the wedged child is killed with
    # enough budget left for the cpu fallback to land its line — the
    # regression where a full 390s cap ate the whole budget and the
    # "guaranteed" stage was skipped.
    out = _run_bench({
        "PYTHONPATH": f"{tmp_path}{os.pathsep}{REPO}",
        "OPSAGENT_BENCH_BUDGET": "300",
        "OPSAGENT_BENCH_BATCH": "2",
        "OPSAGENT_BENCH_STEPS": "8",
    }, timeout=420)
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "TIMED OUT" in out.stderr
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["extra"]["platform"] == "cpu"
    assert "cpu fallback" in parsed["extra"].get("note", "")


def test_tiny_budget_goes_straight_to_fallback():
    """A budget too small for device-stage + fallback skips the device
    stage entirely and still produces the guaranteed line."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_BUDGET": "120",
        "OPSAGENT_BENCH_BATCH": "2",
        "OPSAGENT_BENCH_STEPS": "8",
    }, timeout=300)
    assert out.returncode == 0, (out.stdout + out.stderr)[-1500:]
    assert "cpu-pinned only" in out.stderr
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["unit"] == "tok/s/chip"


def test_vs_baseline_null_unless_tpu_and_8b_class():
    """VERDICT r03: a cpu-fallback line carried vs_baseline 2.929 and
    read as a target hit. The ratio must be null unless the number is
    (a) measured on tpu AND (b) from a baseline-class (8B) model."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    assert bench.vs_baseline(5858.9, "tiny-test", "cpu") is None
    assert bench.vs_baseline(187.6, "bench-1b", "tpu") is None  # not 8B-class
    assert bench.vs_baseline(2100.0, "bench-8b", "cpu") is None
    assert bench.vs_baseline(2100.0, "bench-8b", "tpu") == 1.05
    assert bench.vs_baseline(500.0, "llama-3-8b-instruct", "tpu") == 0.25
    # json.dumps renders the None as null, never a number.
    assert json.dumps({"vs_baseline": bench.vs_baseline(1.0, "x", "cpu")}) \
        == '{"vs_baseline": null}'


def test_agent_mode_reports_per_turn_ttft_and_hit_rate():
    """OPSAGENT_BENCH_MODE=agent (the north-star shape: multi-turn ReAct
    sessions, full-history resend, prefix cache on) must complete every
    turn without OutOfPages — the page budget is sized from the final
    turn's history, not the linear-decode guard — and report per-turn
    TTFT plus a nonzero prefix-hit rate."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "agent",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
        "OPSAGENT_BENCH_TURNS": "3",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("agent_turn_ttft[")
    assert parsed["unit"] == "ms"
    assert parsed["vs_baseline"] is None
    e = parsed["extra"]
    assert e["errors"] == 0
    assert e["turns_completed"] == 3 * 3
    assert e["prefix_hit_rate"] > 0  # turn >= 2 prompts must hit the trie
    assert e["turn1_p50_ttft_ms"] > 0


def test_sessions_mixed_mode_reports_both_variants():
    """OPSAGENT_BENCH_MODE=sessions-mixed (the tier-1-safe fast-lane form
    of the on-chip N=32 stage: CPU, tiny model, small N) must run the
    sessions workload with mixed batching ON and OFF against one engine
    and emit BOTH variants in the JSON line, so the
    one-weight-stream-per-tick delta is a first-class artifact."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "sessions-mixed",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("sessions_mixed[")
    assert parsed["unit"] == "tok/s/chip"
    e = parsed["extra"]
    assert e["errors"] == 0
    # Both phases measured and distinguishable.
    assert e["p50_ttft_ms"] > 0 and e["split_p50_ttft_ms"] > 0
    assert "ttft_delta_ms" in e and "tok_s_chip_delta" in e
    # The mixed phase actually dispatched mixed programs.
    assert e["metrics"]['opsagent_decode_dispatches_total{kind="mixed"}'] > 0


def test_sessions_async_mode_reports_overlap_and_identical_text():
    """OPSAGENT_BENCH_MODE=sessions-async (the tier-1-safe fast-lane form
    of the async-tick A/B stage: CPU, tiny model, small N) must run the
    sessions workload with the one-step-lookahead pipeline (depth=2) and
    with synchronous ticks (depth=1) against one engine and emit BOTH
    phases in ONE JSON line. The on-phase must prove the overlap actually
    happened (overlapped commits > 0) and — same prompt seeds — the two
    phases' output text must be byte-identical: the lookahead changes
    WHEN host work runs, never WHAT gets generated."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "sessions-async",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("sessions_async[")
    assert parsed["unit"] == "tok/s/chip"
    e = parsed["extra"]
    assert e["errors"] == 0
    # Both phases measured and distinguishable.
    assert e["p50_ttft_ms"] > 0 and e["sync_p50_ttft_ms"] > 0
    assert "host_gap_p50_ms" in e and "sync_host_gap_p50_ms" in e
    assert "host_gap_delta_ms" in e
    # The on-phase actually overlapped host work with device compute...
    assert e["overlapped_commits"] > 0
    assert e["async_commits"] > 0
    # ...without changing a single output byte.
    assert e["outputs_identical"] is True


def test_sessions_offload_mode_reports_ab_decision_numbers():
    """OPSAGENT_BENCH_MODE=sessions-offload (the tier-1-safe fast-lane
    form of the hierarchical-KV A/B stage: CPU, tiny model, small N) must
    run the sessions workload with the offload tier OFF then ON against
    one engine and emit BOTH phases' admission-wait p50 and re-prefill-
    avoided token counts in ONE JSON line — the decision numbers the
    host-RAM tier exists for."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "sessions-offload",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("sessions_offload[")
    assert parsed["unit"] == "tok/s/chip"
    e = parsed["extra"]
    assert e["errors"] == 0
    # Both phases measured and distinguishable.
    assert e["p50_ttft_ms"] > 0 and e["off_p50_ttft_ms"] > 0
    assert "admission_wait_p50_ms" in e and "off_admission_wait_p50_ms" in e
    assert "admission_wait_delta_ms" in e
    # The ON phase actually restored instead of re-prefilling (inter-round
    # parking guarantees host-pool hits on every round >= 2 comeback); the
    # OFF phase, with the tier detached, cannot have.
    assert e["reprefill_avoided_tokens"] > 0
    assert e["off_reprefill_avoided_tokens"] == 0
    assert e["restored_tokens"] > 0


def test_sessions_ffwd_mode_reports_ab_numbers():
    """OPSAGENT_BENCH_MODE=sessions-ffwd (the tier-1-safe fast-lane form
    of the grammar fast-forward A/B stage: CPU, tiny model, small N) must
    run schema-constrained sessions with the forced-token fast-forward ON
    then OFF against one engine and emit BOTH phases in ONE JSON line.
    The on-phase must actually skip forward passes (skipped dispatches
    and forced fraction are exact counts, not chip-dependent) and — same
    greedy seeds — the two phases' output text must be byte-identical:
    the grammar changes WHEN tokens are computed, never WHICH tokens."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "sessions-ffwd",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("sessions_ffwd[")
    assert parsed["unit"] == "tok/s/chip"
    e = parsed["extra"]
    assert e["errors"] == 0
    # Both phases measured and distinguishable.
    assert e["p50_ttft_ms"] > 0 and e["off_p50_ttft_ms"] > 0
    assert "tok_s_chip_delta" in e
    # The on-phase actually fast-forwarded: whole singleton-mask runs
    # landed without a forward pass; the off-phase cannot have.
    assert e["skipped_dispatches"] > 0
    assert e["ffwd_tokens"] > 0 and e["ffwd_runs"] > 0
    assert 0 < e["forced_fraction"] <= 1
    assert e["off_skipped_dispatches"] == 0
    # ...without changing a single output byte.
    assert e["outputs_identical"] is True


def test_agent_conveyor_mode_reports_ab_numbers():
    """OPSAGENT_BENCH_MODE=agent-conveyor (the CPU-capable conveyor
    tool-overlap A/B stage) must train the tiny agent to memorization,
    run the scripted episode with conveyor launches ON then OFF against
    one warmed engine, and emit both phases in ONE JSON line. The
    on-phase must fire an early launch per tool turn and bank real
    overlap seconds; the off-phase must fire none; transcripts must be
    byte-identical across phases and neither may compile post-warmup."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "agent-conveyor",
        "OPSAGENT_BENCH_AGENT_EPISODES": "3",
    }, timeout=540)
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("agent_conveyor[")
    assert parsed["unit"] == "ms/turn"
    assert parsed["value"] > 0
    e = parsed["extra"]
    assert e["errors"] == 0
    assert e["train_loss"] < 0.05
    # The on-phase launched the tool mid-decode on every scripted turn
    # and hid real tool time behind the stream's tail.
    assert e["early_launches"] >= 3
    assert e["overlap_s_total"] > 0
    assert e["overlap_ms_per_turn"] > 0
    # The off-phase is the classic blocking path.
    assert e["off_early_launches"] == 0
    assert e["off_overlap_s_total"] == 0
    assert e["off_p50_ms"] > 0
    # The launch is a prefix bet: it may move WHEN the tool runs, never
    # what the agent says.
    assert e["outputs_identical"] is True
    # Warmup covered both phases (FSM tables + ffwd programs).
    assert e["post_warmup_compiles_on"] == 0
    assert e["post_warmup_compiles_off"] == 0


def test_fleet_affinity_mode_reports_ab_numbers():
    """OPSAGENT_BENCH_MODE=fleet-affinity (the tier-1-safe fast-lane form
    of the fleet A/B stage: CPU, tiny model, 2 in-process replicas behind
    the FleetRouter) must run the sessions workload with prefix-affinity
    + sticky placement and with stateless round-robin placement, and emit
    BOTH phases' p50 TTFT and re-prefill-avoided token counts in ONE
    JSON line — the decision numbers prefix-affinity routing exists for.
    The affinity phase restores every parked comeback on its owning
    replica; the round-robin phase mis-routes some comebacks, so it can
    never avoid more re-prefill than affinity does."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "fleet-affinity",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("fleet_affinity[")
    assert parsed["unit"] == "tok/s/chip"
    e = parsed["extra"]
    assert e["errors"] == 0
    assert e["replicas"] == 2
    # Both phases measured and distinguishable.
    assert e["p50_ttft_ms"] > 0 and e["off_p50_ttft_ms"] > 0
    assert "ttft_delta_ms" in e
    # The affinity phase actually restored parked sessions on their
    # owning replicas; stateless placement cannot beat it.
    assert e["reprefill_avoided_tokens"] > 0
    assert e["off_reprefill_avoided_tokens"] <= \
        e["reprefill_avoided_tokens"]
    # The router's placement telemetry rode along.
    assert any("pinned" in k for k in e["route_decisions"])
    assert any("round_robin" in k for k in e["route_decisions"])


def test_fleet_global_kv_mode_reports_ab_numbers():
    """OPSAGENT_BENCH_MODE=fleet-global-kv (the tier-1-safe fast-lane
    form of the fleet-global KV A/B stage: CPU, tiny model, 2 replicas
    + 1 standby behind the FleetRouter). The ON phase forces second
    turns onto a NON-owning replica and third turns onto a freshly
    promoted standby: both must restore over the wire (remote_hit_pages
    > 0) with greedy output byte-identical to the never-moved replay.
    The OFF phase (directory disabled) proves the delta: zero remote
    hits, strictly less re-prefill avoided."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "fleet-global-kv",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("fleet_global_kv[")
    assert parsed["unit"] == "tok/s/chip"
    e = parsed["extra"]
    assert e["errors"] == 0
    assert e["replicas"] == 2 and e["standby"] == 1
    # The ON phase faulted pages in peer-to-peer; OFF could not.
    assert e["remote_hit_pages"] > 0
    assert e["off_remote_hit_pages"] == 0
    assert e["fetch_bytes"] > 0
    # Byte-identical on the non-owner AND on the promoted standby.
    assert e["outputs_identical"] is True
    assert e["standby_identical"] is True
    # The directory did the resolving.
    assert e["directory"]["hits"] > 0


def test_fleet_chaos_mode_zero_failed_requests_under_faults():
    """OPSAGENT_BENCH_MODE=fleet-chaos (the tier-1-safe fast-lane form of
    the chaos A/B stage: CPU, tiny model, 2 in-process replicas, seeded
    mid-SSE disconnects) must run the streaming workload fault-free and
    then under the injector, and emit BOTH phases in ONE JSON line. The
    containment claim: the chaos phase ends with ZERO failed requests
    and at least one recorded failover — every injected disconnect was
    absorbed by the router, and greedy outputs match the clean run
    byte-for-byte."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "fleet-chaos",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    parsed = json.loads(lines[-1])
    assert parsed["metric"].startswith("fleet_chaos[")
    assert parsed["unit"] == "failed_requests"
    assert parsed["value"] == 0
    e = parsed["extra"]
    assert e["replicas"] == 2
    # The injector actually fired, and every fault was contained.
    assert e["injected"] >= 1
    assert e["failovers"] >= 1
    assert e["failed_requests"] == 0
    assert e["off_failed_requests"] == 0
    assert e["outputs_identical"] is True
    # Both phases measured the containment cost.
    assert e["p99_ttft_ms"] > 0 and e["off_p99_ttft_ms"] > 0


def test_ragged_sweep_mode_emits_per_backend_identical_rows():
    """OPSAGENT_BENCH_MODE=ragged-sweep (the mixed-hot-path backend
    sweep) on CPU must run every (backend x KV dtype) cell plus the
    weight-stream cells through interpret-mode Pallas, emit one
    tok/s/chip row per cell with the RESOLVED impls in extra, verify
    byte-identical greedy output against each group's xla cell, and end
    with the best-cell summary line."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "ragged-sweep",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "2",
        "OPSAGENT_BENCH_STEPS": "8",
        "OPSAGENT_BENCH_PROMPT": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    rows = []
    for ln in out.stdout.splitlines():
        try:
            parsed = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            rows.append(parsed)
    # 3 backends x 2 KV dtypes (weight quant stays off-chip) + the int8
    # weight-stream pair (xla oracle + pallas-dma prefetch) + summary.
    assert len(rows) == 9, [r["metric"] for r in rows]
    cells = rows[:-1]
    for r in cells:
        assert r["unit"] == "tok/s/chip"
        e = r["extra"]
        assert e["outputs_identical"] is True, r["metric"]
        assert e["post_warmup_compiles"] == 0, r["metric"]
        assert e["interpret"] is True
        # Self-describing: resolved impls + quant modes ride every row.
        assert e["attn_impl"] in ("xla", "pallas", "pallas-dma")
        assert e["weight_stream"] in ("xla", "pallas-dma")
        assert e["kv_quantize"] in ("none", "int8")
    resolved = {(e["requested_backend"], e["kv_quantize"]): e["attn_impl"]
                for e in (r["extra"] for r in cells)}
    # Every Pallas impl carries a score-space scale path now, so the
    # int8-KV cells keep their requested kernel instead of falling back.
    assert resolved[("pallas-dma", "int8")] == "pallas-dma"
    assert resolved[("pallas", "int8")] == "pallas"
    assert resolved[("pallas", "none")] == "pallas"
    # The weight-stream cells: requesting pallas-dma with int8 weights
    # must RESOLVE to pallas-dma (quantized weights, tp=1 — no gate
    # trips) and still be byte-identical to its group's xla oracle.
    ws_rows = [
        r for r in cells
        if r["extra"]["requested_weight_stream"] == "pallas-dma"
    ]
    assert len(ws_rows) == 1, [r["metric"] for r in ws_rows]
    assert ws_rows[0]["extra"]["weight_stream"] == "pallas-dma"
    assert ws_rows[0]["extra"]["quantize"] == "int8"
    assert ",ws-pallas-dma," in ws_rows[0]["metric"]
    # Summary last: best cell's value with the per-cell map folded in.
    summary = rows[-1]
    assert summary["extra"]["cells"] == 8
    assert summary["value"] == max(r["value"] for r in cells)
    assert len(summary["extra"]["cell_tok_s_chip"]) == 8


def test_audit_fanout_mode_reports_numbers():
    """OPSAGENT_BENCH_MODE=audit-fanout must exit 0 and report the
    fan-out's decision numbers: recall 1.0 against the injected ground
    truth, a prefix-hit rate, and a byte-identical reduce across its two
    audit passes — plus the hit rate as its own higher-better row."""
    out = _run_bench({
        "JAX_PLATFORMS": "cpu",
        "OPSAGENT_BENCH_MODE": "audit-fanout",
        "OPSAGENT_BENCH_MODEL": "tiny-test",
        "OPSAGENT_BENCH_BATCH": "3",
        "OPSAGENT_BENCH_STEPS": "16",
    })
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    rows = []
    for ln in out.stdout.splitlines():
        try:
            d = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and "metric" in d:
            rows.append(d)
    main = [r for r in rows if r["metric"].startswith("audit_fanout[")]
    hit = [
        r for r in rows
        if r["metric"].startswith("audit_fanout_prefix_hit[")
    ]
    assert len(main) == 1 and len(hit) == 1
    r = main[0]
    assert r["unit"] == "audit_latency_s" and r["value"] > 0
    e = r["extra"]
    assert e["recall"] == 1.0
    assert e["byte_identical"] is True
    assert e["failed_children"] == 0
    assert 0.0 <= e["prefix_hit_rate"] <= 1.0
    assert e["avoided_children"] >= 0.9 * e["resources"]
    assert e["interactive_probes"] >= 1 and e["probe_errors"] == 0
    assert hit[0]["unit"] == "prefix_hit_rate"
