"""Tests for the ReAct agent loop's robustness ladder — the behaviors the
reference shipped untested (SURVEY.md section 4)."""

import json

import pytest

from opsagent_tpu.agent.react import assistant_with_config, is_template_value
from opsagent_tpu.tools import ToolError


def tp(thought="", name="", input="", observation="", final=""):
    return json.dumps(
        {
            "question": "q",
            "thought": thought,
            "action": {"name": name, "input": input},
            "observation": observation,
            "final_answer": final,
        }
    )


def msgs(instr="count the namespaces"):
    return [
        {"role": "system", "content": "you are a test agent"},
        {"role": "user", "content": instr},
    ]


def test_happy_path_tool_then_final(scripted_llm, fake_tools):
    calls = []

    def fake_kubectl(cmd):
        calls.append(cmd)
        return "default\nkube-system\nkube-public"

    fake_tools({"kubectl": fake_kubectl})
    scripted_llm(
        [
            tp(thought="list them", name="kubectl", input="get ns --no-headers"),
            tp(
                thought="done",
                observation="default\nkube-system\nkube-public",
                final="There are 3 namespaces in the cluster.",
            ),
        ]
    )
    out, history = assistant_with_config("fake://m", msgs())
    # The loop returns the model's raw final reply; callers extract.
    from opsagent_tpu.tools import ToolPrompt

    assert ToolPrompt.from_json(out).final_answer == (
        "There are 3 namespaces in the cluster."
    )
    assert calls == ["get ns --no-headers"]
    # The observation travels back as a *user* message carrying the ToolPrompt.
    user_payloads = [m for m in history if m["role"] == "user"]
    assert any("kube-public" in m["content"] for m in user_payloads)


def test_unparseable_first_reply_is_final_answer(scripted_llm, fake_tools):
    fake_tools({})
    scripted_llm(["Just a plain prose answer with no JSON."])
    out, _ = assistant_with_config("fake://m", msgs())
    assert out == "Just a plain prose answer with no JSON."


def test_template_final_answer_rejected(scripted_llm, fake_tools):
    fake_tools({"kubectl": lambda c: "real data here"})
    scripted_llm(
        [
            tp(name="kubectl", input="get ns", final="<final_answer>"),
            tp(
                observation="real data here",
                final="A real answer with enough length.",
            ),
        ]
    )
    out, _ = assistant_with_config("fake://m", msgs())
    assert "A real answer with enough length." in out


def test_tool_error_becomes_observation(scripted_llm, fake_tools):
    def broken(cmd):
        raise ToolError("connection refused")

    fake_tools({"kubectl": broken})
    fake = scripted_llm(
        [
            tp(name="kubectl", input="get pods"),
            tp(
                observation="noted the failure",
                final="Could not reach the cluster: connection refused.",
            ),
        ]
    )
    out, history = assistant_with_config("fake://m", msgs())
    assert "connection refused" in out
    fed_back = fake.requests[1]["messages"][-1]["content"]
    assert "Tool kubectl failed with error" in fed_back
    assert "connection refused" in fed_back


def test_unknown_tool_observation(scripted_llm, fake_tools):
    fake_tools({})
    fake = scripted_llm(
        [
            tp(name="helm", input="list"),
            tp(observation="ok", final="Helm is not one of my tools, sorry."),
        ]
    )
    out, _ = assistant_with_config("fake://m", msgs())
    fed_back = fake.requests[1]["messages"][-1]["content"]
    assert "Tool helm is not available" in fed_back


def test_mid_loop_unparseable_triggers_summarize(scripted_llm, fake_tools):
    fake_tools({"kubectl": lambda c: "data"})
    fake = scripted_llm(
        [
            tp(name="kubectl", input="get ns"),
            "suddenly plain prose, not JSON",
            json.dumps({"final_answer": "Summarized: there are 3 namespaces."}),
        ]
    )
    out, _ = assistant_with_config("fake://m", msgs())
    assert out == "Summarized: there are 3 namespaces."
    summarize_turn = fake.requests[2]["messages"][-1]["content"]
    assert "Summarize" in summarize_turn


def test_iteration_cap(scripted_llm, fake_tools):
    fake_tools({"kubectl": lambda c: "data"})
    scripted_llm([tp(name="kubectl", input="get ns")] * 4)
    out, _ = assistant_with_config("fake://m", msgs(), max_iterations=3)
    # Loop must terminate and return something rather than spin forever.
    assert isinstance(out, str)


def test_observation_truncated(scripted_llm, fake_tools):
    huge = "\n".join(f"pod-{i} Running" for i in range(20000))
    fake_tools({"kubectl": lambda c: huge})
    fake = scripted_llm(
        [
            tp(name="kubectl", input="get pods -A"),
            tp(observation="tail", final="Way too many pods to list fully."),
        ]
    )
    assistant_with_config("fake://m", msgs())
    fed_back = fake.requests[1]["messages"][-1]["content"]
    from opsagent_tpu.llm.tokens import count_tokens

    # ToolPrompt JSON wrapper + truncated observation stays near the 1024 cap.
    assert count_tokens(fed_back) < 1400
    assert "pod-19999" in fed_back  # tail is kept, head dropped


def test_is_template_value():
    assert is_template_value("")
    assert is_template_value("<final_answer>")
    assert is_template_value("short")
    assert is_template_value("answer with <placeholder> inside")
    assert not is_template_value("There are 3 namespaces in this cluster.")
