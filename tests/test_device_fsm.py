"""Device-side constrained decoding: the FSM steps ON DEVICE inside the
pipelined decode block (table-gather mask + dest advance, no host sync per
token — SURVEY §7's named hard part). Must be token-identical to the
host-stepped constraint path."""

import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.serving.constrained import json_constraint
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams

KW = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
    num_pages=512, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(16,),
)

SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"enum": ["kubectl", "trivy"]},
        "ok": {"type": "boolean"},
    },
}


def _run(engine, prompt, mask_fn, max_tokens=48):
    sid = engine.begin_request(
        prompt, SamplingParams(temperature=0.0, max_tokens=max_tokens),
        mask_fn=mask_fn,
    )
    while not engine.prefill_step(sid):
        pass
    while not engine.sequences[sid].done:
        engine.step_block([sid])
    return engine.finish(sid)


def test_device_fsm_matches_host_stepped():
    prompt = [257, 1, 2, 3]
    eng_host = Engine(EngineConfig(**KW))
    host_con = json_constraint(eng_host.tokenizer, SCHEMA)
    # A plain-function wrapper is NOT a JsonConstraint, so it host-steps.
    want = _run(eng_host, prompt, lambda toks: host_con(toks))

    eng_dev = Engine(EngineConfig(**KW))
    dev_con = json_constraint(eng_dev.tokenizer, SCHEMA)
    got = _run(eng_dev, prompt, dev_con)
    assert got == want, (got, want)
    # The device path actually engaged (tables cached on the engine).
    assert eng_dev._fsm_dev, "device FSM tables were never built"


def test_device_fsm_output_is_grammatical():
    eng = Engine(EngineConfig(**KW))
    con = json_constraint(eng.tokenizer, SCHEMA)
    toks = _run(eng, [257, 9, 8], con, max_tokens=64)
    fsm = con.fsm
    st = fsm.dfa.start
    for t in toks:
        if t == fsm.eos_id:
            break
        st = fsm.advance(st, t)
        assert st >= 0, "device-masked generation left the grammar"


def test_device_fsm_mixed_with_plain_rows():
    prompt_p = [257, 11, 22, 33]
    eng = Engine(EngineConfig(**KW))
    want_plain = eng.generate(
        [prompt_p], SamplingParams(temperature=0.0, max_tokens=8)
    )[0]
    con = json_constraint(eng.tokenizer, SCHEMA)
    a = eng.add_request(
        prompt_p, SamplingParams(temperature=0.0, max_tokens=8)
    )
    b = eng.begin_request(
        [257, 5, 6], SamplingParams(temperature=0.0, max_tokens=48),
        mask_fn=con,
    )
    while not eng.prefill_step(b):
        pass
    pending = {a, b}
    while pending:
        eng.step_block(sorted(pending))
        pending = {i for i in pending if not eng.sequences[i].done}
    ta = eng.finish(a)
    tb = eng.finish(b)
    assert ta == want_plain  # plain neighbor unaffected by the FSM tables
    st = con.fsm.dfa.start
    for t in tb:
        if t == con.fsm.eos_id:
            break
        st = con.fsm.advance(st, t)
        assert st >= 0


def test_two_schemas_one_rides_device_other_hosted():
    eng = Engine(EngineConfig(**KW))
    con1 = json_constraint(eng.tokenizer, SCHEMA)
    con2 = json_constraint(
        eng.tokenizer, {"type": "object",
                        "properties": {"x": {"type": "integer"}}}
    )
    a = eng.begin_request(
        [257, 1], SamplingParams(temperature=0.0, max_tokens=48),
        mask_fn=con1,
    )
    b = eng.begin_request(
        [257, 2], SamplingParams(temperature=0.0, max_tokens=48),
        mask_fn=con2,
    )
    for sid in (a, b):
        while not eng.prefill_step(sid):
            pass
    pending = {a, b}
    while pending:
        eng.step_block(sorted(pending))
        pending = {i for i in pending if not eng.sequences[i].done}
    for sid, con in ((a, con1), (b, con2)):
        toks = eng.finish(sid)
        st = con.fsm.dfa.start
        for t in toks:
            if t == con.fsm.eos_id:
                break
            st = con.fsm.advance(st, t)
            assert st >= 0, (sid, toks)


def test_budget_overflow_falls_back_to_host(monkeypatch):
    from opsagent_tpu.serving import constrained as C

    monkeypatch.setattr(C, "NATIVE_TABLE_BUDGET", 0)
    eng = Engine(EngineConfig(**KW))
    con = json_constraint(eng.tokenizer, SCHEMA)
    assert con.fsm.dense_tables() is None
    toks = _run(eng, [257, 4], con, max_tokens=32)
    assert toks  # host fallback still generates
    assert not eng._fsm_dev
