"""Multi-head Latent Attention (DeepSeek-V2/V3 family, models/llama.py
MLA paths): decode/prefill consistency against the all-positions oracle,
HF-name checkpoint roundtrip, tensor parallelism, and serving.

MLA serves in two layouts (config.MLAConfig): uncompressed per-head k/v
(v zero-padded to the qk head dim so the shared paged-cache machinery is
untouched) and the compressed latent cache with weight-absorbed decode;
both are oracle-tested here.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.models import llama
from opsagent_tpu.models.config import get_config_preset

CFG = get_config_preset("tiny-mla")
DTYPE = jnp.float32


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)


def test_forward_shapes_and_finite(params):
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 8), 0, CFG.vocab_size
    )
    logits = llama.forward_full(params, CFG, tokens, dtype=DTYPE)
    assert logits.shape == (2, 8, CFG.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_decode_chain_matches_forward_full(params):
    """Prefill, then teacher-force decode steps; every step's logits must
    match the all-at-once causal forward — proving the roped shared-key /
    padded-v cache layout reproduces MLA attention exactly."""
    S_total, S_prompt = 10, 4
    tokens = jax.random.randint(
        jax.random.PRNGKey(6), (1, S_total), 0, CFG.vocab_size
    )
    full = llama.forward_full(params, CFG, tokens, dtype=DTYPE)

    cache = llama.make_cache(CFG, num_pages=8, page_size=4, dtype=DTYPE)
    table = jnp.array([[2, 5, 7]], jnp.int32)
    logits, cache = llama.prefill(
        params, CFG, tokens[:, :S_prompt], jnp.array([S_prompt]),
        cache, table, dtype=DTYPE,
    )
    np.testing.assert_allclose(
        logits[0], full[0, S_prompt - 1], rtol=2e-4, atol=2e-4
    )
    for t in range(S_prompt, S_total):
        logits, cache = llama.decode_step(
            params, CFG, tokens[:, t], jnp.array([t]), cache, table,
            active=jnp.array([True]), dtype=DTYPE,
        )
        np.testing.assert_allclose(
            logits[0], full[0, t], rtol=3e-4, atol=3e-4,
            err_msg=f"decode step at position {t}",
        )


def test_checkpoint_roundtrip(tmp_path, params):
    """save_checkpoint (HF deepseek naming: kv_a_proj_with_mqa recombined,
    o_proj unpadded) -> load_checkpoint -> identical logits."""
    from opsagent_tpu.models.loader import load_checkpoint, save_checkpoint

    ckpt = tmp_path / "model.safetensors"
    save_checkpoint(str(ckpt), params, cfg=CFG)
    loaded = load_checkpoint(str(ckpt), CFG, dtype=DTYPE)
    tokens = jnp.array([[1, 2, 3, 4, 5]], jnp.int32)
    l1 = llama.forward_full(params, CFG, tokens, dtype=DTYPE)
    l2 = llama.forward_full(loaded, CFG, tokens, dtype=DTYPE)
    np.testing.assert_allclose(
        np.asarray(l1), np.asarray(l2), rtol=1e-5, atol=1e-5
    )


def test_tp_sharded_prefill_matches_single_device(params):
    """tp=4 (heads shard 4 ways; wuq/wukv column-parallel, wo
    row-parallel) must be numerically equivalent to unsharded."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from opsagent_tpu.parallel.mesh import make_mesh, shard_params

    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (2, 8), 0, CFG.vocab_size
    )
    ref = llama.forward_full(params, CFG, tokens, dtype=DTYPE)

    mesh = make_mesh(tp=4, dp=2, sp=1)
    sharded = shard_params(params, llama.param_specs(CFG), mesh)
    with mesh:
        out = jax.jit(
            lambda p, t: llama.forward_full(p, CFG, t, dtype=DTYPE),
            in_shardings=(None, NamedSharding(mesh, P("dp"))),
        )(sharded, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_engine_serves_mla(tmp_path):
    """The serving engine generates from an MLA model (attention backend
    forced to the shape-agnostic xla gather) and greedy generation is
    deterministic across engines."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    outs = []
    for _ in range(2):
        eng = Engine(EngineConfig(
            model="tiny-mla",
            dtype=DTYPE,
            num_pages=64,
            page_size=8,
            max_pages_per_seq=16,
            max_batch_size=2,
            prefill_buckets=(16,),
        ))
        assert eng.attn_impl == "xla"
        outs.append(eng.generate([[1, 2, 3, 4], [9, 8, 7]], None))
    assert outs[0] == outs[1]
    assert all(len(t) >= 1 for t in outs[0])


def test_deepseek_presets_validate():
    """The real DeepSeek configs construct valid parameter trees (checked
    abstractly — no 671B allocation) with the MLA geometry invariants."""
    for name in ("deepseek-v2-lite", "deepseek-v3"):
        cfg = get_config_preset(name)
        assert cfg.mla is not None
        assert cfg.head_dim_ == cfg.mla.qk_head_dim
        shapes = jax.eval_shape(
            lambda c=cfg: llama.init_params(
                c, jax.random.PRNGKey(0), dtype=jnp.bfloat16
            )
        )
        specs = llama.param_specs(cfg)
        # Every param leaf has a matching spec leaf.
        assert jax.tree.structure(
            shapes, is_leaf=lambda x: hasattr(x, "shape")
        ).num_leaves == jax.tree.structure(specs).num_leaves


def test_mla_geometry_validation():
    bad = dataclasses.replace(CFG, head_dim=32)
    with pytest.raises(ValueError, match="qk_head_dim"):
        llama.init_params(bad, jax.random.PRNGKey(0), dtype=DTYPE)


def test_rope_convention_matches_hf_interleaved():
    """Loading permutes DeepSeek's INTERLEAVED rope columns to half-split;
    attention scores through our (permuted weights + half-split rope)
    path must equal the HF convention (interleaved weights, activations
    de-interleaved before rotate_half). Scores are the invariant —
    per-dim layout cancels when q and k are permuted consistently."""
    from opsagent_tpu.models.loader import _rope_interleave_to_halfsplit
    from opsagent_tpu.ops.rope import apply_rope, rope_table

    rng = np.random.default_rng(0)
    d, dr, S = 12, 8, 5
    x = rng.standard_normal((1, S, d)).astype(np.float32)
    w = rng.standard_normal((d, dr)).astype(np.float32)  # HF layout
    positions = jnp.arange(S)[None, :]
    cos, sin = rope_table(positions, dr, 10000.0)

    # HF convention: project with raw weights, de-interleave activations,
    # then standard half-split rotate (what rotate_half + their transpose
    # trick computes).
    perm = _rope_interleave_to_halfsplit(dr)
    hf_act = (x @ w)[..., perm]            # de-interleave == perm gather
    hf_roped = apply_rope(
        jnp.asarray(hf_act)[:, :, None, :], cos, sin
    )[:, :, 0]

    # Our convention: permute WEIGHT columns at load, then half-split rope.
    ours_act = x @ w[:, perm]
    ours_roped = apply_rope(
        jnp.asarray(ours_act)[:, :, None, :], cos, sin
    )[:, :, 0]

    np.testing.assert_allclose(
        np.asarray(hf_roped), np.asarray(ours_roped), rtol=1e-6, atol=1e-6
    )


def test_engine_rejects_prompt_beyond_context_window():
    from opsagent_tpu.serving.engine import Engine, EngineConfig, InvalidRequest

    eng = Engine(EngineConfig(
        model="tiny-mla", dtype=DTYPE, num_pages=64, page_size=8,
        max_pages_per_seq=400, max_batch_size=1, prefill_buckets=(16,),
    ))
    too_long = list(range(1, CFG.max_position + 2))
    with pytest.raises(InvalidRequest, match="context window"):
        eng.begin_request([t % 500 for t in too_long])


def test_generation_budget_clamped_to_context_window():
    """Admission clamps max_tokens so decode never runs rope positions
    past the model window; the request finishes with reason 'length'."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams

    pages_needed = (CFG.max_position // 8) + 4
    eng = Engine(EngineConfig(
        model="tiny-mla", dtype=DTYPE, num_pages=pages_needed + 8,
        page_size=8, max_pages_per_seq=pages_needed, max_batch_size=1,
        prefill_buckets=(2048,),
    ))
    n = CFG.max_position - 3
    sid = eng.add_request(
        [1 + (i % 400) for i in range(n)],
        SamplingParams(temperature=0.0, max_tokens=500),
    )
    assert eng.sequences[sid].params.max_tokens == 3


LATENT_CFG = dataclasses.replace(
    CFG, mla=dataclasses.replace(CFG.mla, latent_cache=True)
)


def test_latent_cache_decode_chain_matches_oracle(params):
    """The weight-absorbed latent cache (MQA over [c_kv, k_rope] latents)
    must reproduce the materialized attention exactly: prefill + decode
    chain against the forward_full oracle, same weights."""
    S_total, S_prompt = 10, 4
    tokens = jax.random.randint(
        jax.random.PRNGKey(6), (1, S_total), 0, CFG.vocab_size
    )
    full = llama.forward_full(params, LATENT_CFG, tokens, dtype=DTYPE)

    cache = llama.make_cache(LATENT_CFG, num_pages=8, page_size=4, dtype=DTYPE)
    assert cache["k"].shape[-1] == LATENT_CFG.mla.latent_dim
    assert cache["k"].shape[-2] == 1
    table = jnp.array([[2, 5, 7]], jnp.int32)
    logits, cache = llama.prefill(
        params, LATENT_CFG, tokens[:, :S_prompt], jnp.array([S_prompt]),
        cache, table, dtype=DTYPE,
    )
    np.testing.assert_allclose(
        logits[0], full[0, S_prompt - 1], rtol=2e-4, atol=2e-4
    )
    for t in range(S_prompt, S_total):
        logits, cache = llama.decode_step(
            params, LATENT_CFG, tokens[:, t], jnp.array([t]), cache, table,
            active=jnp.array([True]), dtype=DTYPE,
        )
        np.testing.assert_allclose(
            logits[0], full[0, t], rtol=3e-4, atol=3e-4,
            err_msg=f"latent decode step at position {t}",
        )


def test_latent_cache_prefix_admission_matches_oracle(params):
    """prefill_with_prefix over latent pages (tail attends the absorbed
    form against cached latents) equals the oracle."""
    tokens = jax.random.randint(
        jax.random.PRNGKey(8), (1, 12), 0, CFG.vocab_size
    )
    full = llama.forward_full(params, LATENT_CFG, tokens, dtype=DTYPE)
    cache = llama.make_cache(LATENT_CFG, num_pages=8, page_size=4, dtype=DTYPE)
    table = jnp.array([[0, 3, 6]], jnp.int32)
    # Prefill the first 8, then admit the 4-token tail against the prefix.
    _, cache = llama.prefill(
        params, LATENT_CFG, tokens[:, :8], jnp.array([8]),
        cache, table, dtype=DTYPE,
    )
    logits, cache = llama.prefill_with_prefix(
        params, LATENT_CFG, tokens[:, 8:], jnp.array([8]), jnp.array([4]),
        cache, table, dtype=DTYPE,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), np.asarray(full[0, 11]), rtol=3e-4, atol=3e-4
    )


def test_latent_engine_matches_materialized_engine():
    """End to end: the serving engine with latent_cache generates the
    SAME greedy tokens as the uncompressed-cache engine."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    outs = []
    for model_cfg in (CFG, LATENT_CFG):
        eng = Engine(
            EngineConfig(
                model="tiny-mla",
                dtype=DTYPE,
                num_pages=64,
                page_size=8,
                max_pages_per_seq=16,
                max_batch_size=2,
                prefill_buckets=(16,),
            ),
            model_cfg=model_cfg,
        )
        outs.append(eng.generate([[1, 2, 3, 4], [9, 8, 7]], None))
    assert outs[0] == outs[1]


def test_latent_engine_int8_quantized():
    """Weight-only int8 under the latent cache: the absorbed path must
    dequantize wukv before its per-head reshape (regression: QuantizedLinear
    has no reshape)."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig

    eng = Engine(
        EngineConfig(
            model="tiny-mla",
            dtype=DTYPE,
            num_pages=64,
            page_size=8,
            max_pages_per_seq=16,
            max_batch_size=2,
            prefill_buckets=(16,),
            quantize="int8",
        ),
        model_cfg=LATENT_CFG,
    )
    out = eng.generate([[1, 2, 3, 4]], None)
    assert len(out) == 1 and len(out[0]) >= 1


def test_v3_shaped_moe_mla_checkpoint_roundtrip(tmp_path):
    """A scaled-down DeepSeek-V3-shaped config (MLA + q_lora + sigmoid
    noaux_tc MoE with router_bias + shared expert) must roundtrip through
    the HF naming (kv_a_proj_with_mqa, e_score_correction_bias, experts)
    with identical logits."""
    from opsagent_tpu.models.config import MLAConfig, MoEConfig
    from opsagent_tpu.models.loader import load_checkpoint, save_checkpoint

    cfg = dataclasses.replace(
        get_config_preset("tiny-mla"),
        num_layers=3,
        moe=MoEConfig(
            num_experts=4,
            num_experts_per_token=2,
            num_shared_experts=1,
            expert_intermediate_size=32,
            norm_topk_prob=True,
            routed_scaling_factor=2.5,
            scoring_func="sigmoid",
            n_group=2,
            topk_group=1,
        ),
        moe_layer_start=1,
    )
    params = llama.init_params(cfg, jax.random.PRNGKey(3), dtype=DTYPE)
    # Non-zero selection bias so the roundtrip must preserve it to keep
    # routing identical.
    params["moe_layers"]["router_bias"] = jnp.asarray(
        np.linspace(-1, 1, 2 * 4).reshape(2, 4), jnp.float32
    )
    ckpt = tmp_path / "model.safetensors"
    save_checkpoint(str(ckpt), params, cfg=cfg)
    loaded = load_checkpoint(str(ckpt), cfg, dtype=DTYPE)
    tokens = jnp.array([[5, 6, 7, 8, 9, 10]], jnp.int32)
    l1 = llama.forward_full(params, cfg, tokens, dtype=DTYPE)
    l2 = llama.forward_full(loaded, cfg, tokens, dtype=DTYPE)
    np.testing.assert_allclose(
        np.asarray(l1), np.asarray(l2), rtol=1e-5, atol=1e-5
    )


def test_latent_engine_prefix_cache_reuse():
    """Latent pages participate in the prefix cache: a second request
    sharing a prompt prefix gets cache hits and identical greedy output."""
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.serving.sampler import SamplingParams
    from opsagent_tpu.utils.perf import get_perf_stats

    eng = Engine(
        EngineConfig(
            model="tiny-mla",
            dtype=DTYPE,
            num_pages=64,
            page_size=4,
            max_pages_per_seq=16,
            max_batch_size=2,
            prefill_buckets=(16,),
        ),
        model_cfg=LATENT_CFG,
    )
    prompt = list(range(1, 13))  # 12 tokens = 3 full pages
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    get_perf_stats().reset()
    sid1 = eng.add_request(list(prompt), sp)
    out1 = []
    while not eng.sequences[sid1].done and len(out1) < 4:
        out1 += eng.step_block([sid1]).get(sid1, [])
    out1 += [t for v in eng.drain().values() for t in v]
    eng.finish(sid1)  # donates full pages to the prefix trie

    sid2 = eng.add_request(list(prompt), sp)
    stats = get_perf_stats().get_stats()
    hits = stats.get("engine.prefix_hit_tokens", {}).get("count", 0)
    assert hits >= 1, stats.keys()
    out2 = []
    while not eng.sequences[sid2].done and len(out2) < 4:
        out2 += eng.step_block([sid2]).get(sid2, [])
    out2 += [t for v in eng.drain().values() for t in v]
    assert out1[:4] == out2[:4]


def test_mla_ring_attention_prefill_matches_oracle(params):
    """MLA under sequence-parallel ring attention (sp=2): the decoupled-
    rope q/k and padded v ride the ppermute KV ring unchanged."""
    from opsagent_tpu.parallel.mesh import make_mesh
    from opsagent_tpu.parallel.ring import make_ring_attention

    mesh = make_mesh(tp=2, dp=1, sp=2)
    ring = make_ring_attention(mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(2), (1, 16), 0, CFG.vocab_size
    )
    ref = llama.forward_full(params, CFG, tokens, dtype=DTYPE)
    with mesh:
        out = jax.jit(
            lambda p, t: llama.forward_full(
                p, CFG, t, dtype=DTYPE, prefill_attn=ring
            )
        )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=3e-4, atol=3e-4
    )


def test_latent_spec_decoding_deterministic():
    """Speculative decoding over the latent cache is deterministic run to
    run. (k>0 vs k=0 token-for-token equality is NOT asserted: the verify
    and decode programs agree only to float tolerance (~2e-6 logits), and
    random weights produce argmax near-ties that can flip between the two
    programs — with real weights the margins dwarf the noise.)"""
    from opsagent_tpu.serving.engine import Engine, EngineConfig
    from opsagent_tpu.utils.perf import get_perf_stats

    outs = []
    for _ in range(2):
        get_perf_stats().reset()
        eng = Engine(
            EngineConfig(
                model="tiny-mla", dtype=DTYPE, num_pages=64, page_size=8,
                max_pages_per_seq=16, max_batch_size=2,
                prefill_buckets=(16,), speculative_k=2,
            ),
            model_cfg=LATENT_CFG,
        )
        outs.append(eng.generate([[1, 2, 3, 4], [9, 8, 7]], None))
        # The speculative path must actually have engaged (a silent
        # fallback to vanilla decode would keep determinism green).
        stats = get_perf_stats().get_stats()
        assert stats.get("engine.spec_blocks", {}).get("count", 0) >= 1
    assert outs[0] == outs[1]
    assert all(len(t) >= 1 for row in outs for t in row)
