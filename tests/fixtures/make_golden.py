#!/usr/bin/env python
"""Generate the committed checkpoint fixtures + golden outputs.

Run from the repo root: ``python tests/fixtures/make_golden.py``.

Two kinds of fixture (VERDICT round-1 item 6):

- **HF-oracle fixtures** (tiny-llama-hf, tiny-qwen2-hf): a seeded tiny
  checkpoint written by the GENUINE HuggingFace implementation
  (transformers LlamaForCausalLM / Qwen2ForCausalLM on CPU torch),
  together with its own forward logits and greedy continuation. The test
  loads the checkpoint with models.loader and must reproduce HF's numbers
  — an independent oracle that fails if any HF-name mapping, transpose,
  RoPE convention, norm epsilon, or bias handling drifts.
- **Pinned fixture** (tiny-deepseek-moe): transformers has no in-tree
  DeepSeek-MoE implementation, so the DeepSeek naming scheme is pinned as
  a regression fixture: a seeded checkpoint in DeepSeek naming plus the
  outputs computed at fixture-creation time. Catches drift, not initial
  correctness (that is covered by the MoE oracle-equivalence tests).
"""

from __future__ import annotations

import json
import os
import sys

# Fixture generation never needs a TPU; jax may already be imported by the
# interpreter's site hooks, so the config update is the reliable override.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")
ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, ROOT)
HERE = os.path.dirname(os.path.abspath(__file__))

import numpy as np

PROMPT = [257, 72, 101, 108, 108, 111, 44, 32, 119, 111, 114, 108, 100]
GEN_LEN = 8


def make_llama():
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=2048,
        tie_word_embeddings=False, attention_bias=False,
    )
    model = LlamaForCausalLM(cfg).eval()
    out_dir = os.path.join(HERE, "tiny-llama-hf")
    model.save_pretrained(out_dir, safe_serialization=True)
    _golden(model, out_dir)


def make_qwen2():
    import torch
    from transformers import Qwen2Config, Qwen2ForCausalLM

    torch.manual_seed(1)
    cfg = Qwen2Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-6, rope_theta=10000.0, max_position_embeddings=2048,
        tie_word_embeddings=False,
    )
    model = Qwen2ForCausalLM(cfg).eval()
    out_dir = os.path.join(HERE, "tiny-qwen2-hf")
    model.save_pretrained(out_dir, safe_serialization=True)
    _golden(model, out_dir)


def make_qwen3():
    import torch
    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(2)
    # head_dim deliberately != hidden/heads (Qwen3 releases decouple
    # them), exercising the explicit-head_dim path alongside QK-norm.
    cfg = Qwen3Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, rms_norm_eps=1e-6, rope_theta=10000.0,
        max_position_embeddings=2048, tie_word_embeddings=False,
        attention_bias=False, use_sliding_window=False,
    )
    model = Qwen3ForCausalLM(cfg).eval()
    out_dir = os.path.join(HERE, "tiny-qwen3-hf")
    model.save_pretrained(out_dir, safe_serialization=True)
    _golden(model, out_dir)


def make_qwen3_moe():
    import torch
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    torch.manual_seed(3)
    # Every layer MoE (decoder_sparse_step=1), softmax top-k routing with
    # renormalization, no shared experts — the shipped Qwen3-MoE layout.
    cfg = Qwen3MoeConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, rms_norm_eps=1e-6, rope_theta=10000.0,
        max_position_embeddings=2048, tie_word_embeddings=False,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=96,
        norm_topk_prob=True, decoder_sparse_step=1, mlp_only_layers=[],
        use_sliding_window=False,
    )
    model = Qwen3MoeForCausalLM(cfg).eval()
    out_dir = os.path.join(HERE, "tiny-qwen3-moe-hf")
    model.save_pretrained(out_dir, safe_serialization=True)
    _golden(model, out_dir)


def _golden(model, out_dir):
    import torch

    ids = torch.tensor([PROMPT])
    with torch.no_grad():
        logits = model(ids).logits[0, -1].float().numpy()
        gen = model.generate(
            ids, max_new_tokens=GEN_LEN, do_sample=False,
            pad_token_id=0,
        )[0, len(PROMPT):].tolist()
    np.savez(
        os.path.join(out_dir, "golden.npz"),
        prompt=np.asarray(PROMPT, np.int32),
        last_logits=logits,
        greedy=np.asarray(gen, np.int32),
    )
    print(f"{out_dir}: greedy={gen}")


def make_deepseek_moe():
    import jax
    import jax.numpy as jnp

    from opsagent_tpu.models import llama
    from opsagent_tpu.models.config import get_config_preset
    from opsagent_tpu.models.loader import save_checkpoint

    cfg = get_config_preset("tiny-moe")
    params = llama.init_params(cfg, jax.random.PRNGKey(42), dtype=jnp.float32)
    out_dir = os.path.join(HERE, "tiny-deepseek-moe")
    os.makedirs(out_dir, exist_ok=True)
    save_checkpoint(os.path.join(out_dir, "model.safetensors"), params)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({"model_type": "deepseek-moe", "preset": "tiny-moe"}, f)

    toks = jnp.asarray([PROMPT], jnp.int32)
    logits = llama.forward_full(params, cfg, toks, dtype=jnp.float32)
    ids = list(PROMPT)
    gen = []
    for _ in range(GEN_LEN):
        lg = llama.forward_full(
            params, cfg, jnp.asarray([ids], jnp.int32), dtype=jnp.float32
        )
        nxt = int(jnp.argmax(lg[0, -1]))
        gen.append(nxt)
        ids.append(nxt)
    np.savez(
        os.path.join(out_dir, "golden.npz"),
        prompt=np.asarray(PROMPT, np.int32),
        last_logits=np.asarray(logits[0, -1], np.float32),
        greedy=np.asarray(gen, np.int32),
    )
    print(f"{out_dir}: greedy={gen}")


if __name__ == "__main__":
    make_llama()
    make_qwen2()
    make_qwen3()
    make_qwen3_moe()
    make_deepseek_moe()
