"""Elastic fleet autoscaling (serving/fleet/autoscale): the scaling
policy (shed pressure -> one launch in flight, bounded by max_replicas
and cooldown; sustained idleness -> drain + retire), standby promotion
semantics (role="standby" is unroutable until request-ready), and the
acceptance gate (ISSUE 10): a shed burst launches a standby restored
from an engine snapshot which then serves traffic with no failed
requests."""

import gc
import json
import os

import jax
import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu import obs
from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.fleet.autoscale import (
    Autoscaler,
    LocalStackLauncher,
    ReplicaLauncher,
)
from opsagent_tpu.serving.fleet.registry import (
    ReplicaInfo,
    ReplicaRegistry,
)
from opsagent_tpu.serving.fleet.router import (
    FleetRouter,
    OverloadError,
    build_router_app,
)

BASE = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
    num_pages=256, max_pages_per_seq=64, max_batch_size=4,
    prefill_buckets=(16,), decode_block=4, seed=0,
)

CHAT = {
    "messages": [{"role": "user", "content": "hello"}],
    "max_tokens": 4, "temperature": 0,
}


def _router(n=1, **kw):
    """(router, stacks): n in-process decode replicas."""
    router = FleetRouter(**kw)
    stacks = []
    for i in range(n):
        stack = ServingStack(Engine(EngineConfig(**BASE)))
        stacks.append(stack)
        router.add_local(stack, f"r{i}")
    return router, stacks


def _close(stacks):
    for s in stacks:
        s.close()


class FakeLauncher(ReplicaLauncher):
    """Policy-only launcher: registers a standby ReplicaInfo (local with
    no handle, so it is never reaped and never polled) and reports
    request-ready only when told to — so tests control exactly when the
    promote step may fire."""

    def __init__(self, router):
        self.router = router
        self.launched: list[str] = []
        self.stopped: list[str] = []
        self.ready: set[str] = set()

    def launch(self, replica_id: str) -> None:
        self.launched.append(replica_id)
        self.router.registry.register(
            ReplicaInfo(replica_id=replica_id, role="standby", local=True)
        )

    def request_ready(self, replica_id: str) -> bool:
        return replica_id in self.ready

    def stop(self, replica_id: str) -> None:
        self.stopped.append(replica_id)


# -- registry role flips -------------------------------------------------------
class TestSetRole:
    def test_set_role_moves_replica_between_pools(self):
        reg = ReplicaRegistry()
        reg.register(
            ReplicaInfo(replica_id="s", role="standby", local=True)
        )
        assert [i.replica_id for i in reg.alive(role="decode")] == []
        assert reg.set_role("s", "decode")
        assert [i.replica_id for i in reg.alive(role="decode")] == ["s"]
        assert not reg.set_role("ghost", "decode")


# -- scaling policy (no engines involved) --------------------------------------
class TestPolicy:
    def _scaler(self, router, **kw):
        launcher = FakeLauncher(router)
        kw.setdefault("cooldown_s", 0.0)
        return Autoscaler(router, launcher, **kw), launcher

    def test_shed_pressure_launches_one_standby(self):
        router, stacks = _router(1)
        try:
            scaler, launcher = self._scaler(router)
            out = scaler.tick()
            assert out["launched"] is None  # no pressure, no launch
            scaler.note_shed()
            scaler.note_shed()
            out = scaler.tick()
            assert out["launched"] == "scale-1"
            assert launcher.launched == ["scale-1"]
            # The standby is NOT routable yet: route() only considers
            # decode replicas.
            dec = router.registry.alive(role="decode")
            assert [i.replica_id for i in dec] == ["r0"]
            assert obs.FLEET_SCALE_EVENTS.value(direction="up") == 1
        finally:
            _close(stacks)

    def test_one_launch_in_flight_at_a_time(self):
        router, stacks = _router(1)
        try:
            scaler, launcher = self._scaler(router)
            scaler.note_shed()
            assert scaler.tick()["launched"] == "scale-1"
            # Still warming (request_ready False): more shed pressure
            # must not thunder the herd.
            scaler.note_shed()
            out = scaler.tick()
            assert out["launched"] is None and out["promoted"] == []
            # Once ready it is promoted, and only then may another
            # launch happen.
            launcher.ready.add("scale-1")
            scaler.note_shed()
            out = scaler.tick()
            assert out["promoted"] == ["scale-1"]
            assert out["launched"] == "scale-2"
            assert obs.FLEET_SCALE_EVENTS.value(direction="promote") == 1
        finally:
            _close(stacks)

    def test_max_replicas_bounds_the_fleet(self):
        router, stacks = _router(1)
        try:
            scaler, launcher = self._scaler(router, max_replicas=1)
            scaler.note_shed()
            assert scaler.tick()["launched"] == "scale-1"
            launcher.ready.add("scale-1")
            scaler.note_shed()
            out = scaler.tick()
            assert out["promoted"] == ["scale-1"]
            assert out["launched"] is None  # at the bound
        finally:
            _close(stacks)

    def test_cooldown_blocks_back_to_back_launches(self):
        router, stacks = _router(1)
        try:
            scaler, launcher = self._scaler(router, cooldown_s=3600.0)
            scaler.note_shed()
            assert scaler.tick()["launched"] == "scale-1"
            launcher.ready.add("scale-1")
            scaler.note_shed()
            assert scaler.tick()["launched"] is None
        finally:
            _close(stacks)

    def test_snapshot_reports_state(self):
        router, stacks = _router(1)
        try:
            scaler, launcher = self._scaler(router, max_replicas=3)
            scaler.note_shed()
            scaler.tick()
            snap = scaler.snapshot()
            assert snap["pending"] == ["scale-1"]
            assert snap["active"] == []
            assert snap["launched_total"] == 1
            assert snap["max_replicas"] == 3
        finally:
            _close(stacks)


# -- the acceptance gate: shed burst -> snapshot standby serves traffic --------
class TestElasticScaleOut:
    def test_shed_burst_launches_snapshot_standby_no_failed_requests(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("OPSAGENT_COMPILE_CACHE_MIN_S", "0")
        monkeypatch.setenv(
            "OPSAGENT_COMPILE_CACHE_DIR", str(tmp_path / "cache")
        )
        jax.clear_caches()
        router, stacks = _router(1, shed_queue_depth=None)
        snapdir = str(tmp_path / "snap")
        launched_stacks = []

        def factory():
            # What SubprocessLauncher does across a process boundary,
            # in-process: the standby engine comes from the snapshot.
            stack = ServingStack(
                Engine.from_snapshot(snapdir, warmup=False)
            )
            launched_stacks.append(stack)
            return stack

        try:
            stacks[0].engine.snapshot(snapdir)
            scaler = Autoscaler(
                router,
                LocalStackLauncher(router, factory),
                cooldown_s=0.0,
                scale_down_after=2,
            )
            router.autoscaler = scaler  # _check_overload -> note_shed

            # Saturate: watermark 0 means every unforced request sheds.
            router.shed_queue_depth = 0
            with pytest.raises(OverloadError):
                router.complete(dict(CHAT))
            assert sum(
                obs.FLEET_SHED.value(**{"class": c})
                for c in obs.SLO_CLASSES
            ) == 1

            out = scaler.tick()
            assert out["launched"] == "scale-1"
            out = scaler.tick()
            assert out["promoted"] == ["scale-1"]
            ids = {
                i.replica_id
                for i in router.registry.alive(role="decode")
            }
            assert ids == {"r0", "scale-1"}

            # Burst over, watermark back up: traffic flows and every
            # request succeeds — including on the promoted standby.
            router.shed_queue_depth = None
            for _ in range(3):
                resp = router.complete(dict(CHAT))
                assert resp["choices"][0]["message"]["content"]
            forced = router.complete(
                dict(CHAT), force_replica="scale-1"
            )
            assert forced["choices"][0]["message"]["content"]
            assert obs.FLEET_REQUESTS.value(outcome="error") == 0

            # Pressure gone + idle: the standby is drained (graceful)
            # and retired, and the original replica remains.
            retired = []
            for _ in range(4):
                retired += scaler.tick()["retired"]
            assert retired == ["scale-1"]
            ids = {
                i.replica_id
                for i in router.registry.alive(role="decode")
            }
            assert ids == {"r0"}
            # Exactly one standby was ever built, and it came from the
            # snapshot restore path.
            assert len(launched_stacks) == 1
            assert launched_stacks[0].engine.init_stats[
                "restore_source"
            ] == os.path.abspath(snapdir)
            assert obs.FLEET_SCALE_EVENTS.value(direction="down") == 1
        finally:
            _close(stacks)
            gc.collect()


# -- router healthz exposes the scaler -----------------------------------------
class TestHealthzAutoscale:
    def test_router_healthz_carries_autoscale_block(self):
        import asyncio

        router, stacks = _router(1)
        try:
            scaler = Autoscaler(router, FakeLauncher(router))
            router.autoscaler = scaler
            scaler.note_shed()
            app = build_router_app(router)

            async def _get():
                client = TestClient(TestServer(app))
                await client.start_server()
                try:
                    resp = await client.get("/healthz")
                    return json.loads(await resp.text())
                finally:
                    await client.close()

            body = asyncio.new_event_loop().run_until_complete(_get())
            auto = body["autoscale"]
            assert auto["shed_pending"] == 1
            assert auto["active"] == []
            assert auto["max_replicas"] == 4
        finally:
            _close(stacks)
