"""SLO watchdog unit coverage: quantile estimation, verdict arithmetic,
burn rates, throughput rate windows, the scrape collector, bench.py's
extra.slo folding, and the slo-check CLI's three exit codes."""

import json

import pytest

from opsagent_tpu import obs
from opsagent_tpu.cli.main import main as cli_main
from opsagent_tpu.obs.slo import (
    SLOWatchdog,
    declared_slos,
    histogram_quantile,
)


def test_histogram_quantile_interpolates():
    h = obs.get_registry().histogram(
        "test_slo_quantile_seconds", "t", buckets=(0.1, 0.2, 0.4, 0.8)
    )
    for v in (0.05, 0.15, 0.15, 0.3):
        h.observe(v)
    # rank 2 of 4 lands in the (0.1, 0.2] bucket (2 samples, cum 1
    # before): 0.1 + 0.1 * (2 - 1) / 2 = 0.15.
    assert histogram_quantile(h, 0.5) == pytest.approx(0.15)
    # p100 rank 4 -> (0.2, 0.4] bucket upper region.
    assert histogram_quantile(h, 1.0) == pytest.approx(0.4)
    # Overflow clamp: everything past the last finite bound.
    h2 = obs.get_registry().histogram(
        "test_slo_overflow_seconds", "t", buckets=(0.1,)
    )
    h2.observe(5.0)
    assert histogram_quantile(h2, 0.5) == 0.1
    # Empty histogram -> no estimate.
    h3 = obs.get_registry().histogram(
        "test_slo_empty_seconds", "t", buckets=(1.0,)
    )
    assert histogram_quantile(h3, 0.5) is None


def test_declared_targets_env_tunable(monkeypatch):
    monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "250")
    monkeypatch.setenv("OPSAGENT_SLO_TOK_S_CHIP", "2000")
    slos = {s.name: s for s in declared_slos()}
    assert slos["ttft_p50_ms"].target == 250.0
    assert slos["decode_tok_s_chip"].target == 2000.0
    assert slos["decode_tok_s_chip"].direction == "gt"
    monkeypatch.delenv("OPSAGENT_SLO_TOK_S_CHIP")
    assert "decode_tok_s_chip" not in {s.name for s in declared_slos()}


def test_evaluate_no_data_is_not_a_pass():
    res = obs.slo.evaluate()
    for v in res["slos"]:
        assert v["pass"] is None and v["value"] is None
    assert res["pass"] is True  # nothing FAILED (but nothing passed)
    assert cli_main(["slo-check"]) == 2  # the CLI calls that "no data"


def test_evaluate_pass_and_fail_directions(monkeypatch):
    monkeypatch.setenv("OPSAGENT_SLO_TTFT_MS", "500")
    for _ in range(10):
        obs.TTFT_SECONDS.observe(0.05)
    obs.ITL_SECONDS.observe(0.3)   # p50 300 ms-ish > 100 ms target
    obs.ENGINE_REQUESTS.inc(outcome="completed", amount=99)
    res = obs.slo.evaluate()
    by = {v["name"]: v for v in res["slos"]}
    assert by["ttft_p50_ms"]["pass"] is True
    assert by["ttft_p50_ms"]["burn_rate"] < 1.0
    assert by["itl_p50_ms"]["pass"] is False
    assert by["itl_p50_ms"]["burn_rate"] > 1.0
    assert "breached_for_s" in by["itl_p50_ms"]
    assert by["error_rate"]["pass"] is True
    assert res["pass"] is False
    # The breach transition landed in the flight ring.
    breaches = obs.flight.get_recorder().snapshot(kind="slo_breach")
    assert any(e["slo"] == "itl_p50_ms" for e in breaches)
    assert cli_main(["slo-check"]) == 1


def test_throughput_rate_window(monkeypatch):
    # Low target: the 8-device CPU mesh divides the rate by 8 chips.
    monkeypatch.setenv("OPSAGENT_SLO_TOK_S_CHIP", "1")
    w = SLOWatchdog()
    res = w.evaluate()
    tok = next(v for v in res["slos"] if v["name"] == "decode_tok_s_chip")
    assert tok["pass"] is None  # no window yet
    # Fake a 2-second-old snapshot with 100 fewer tokens: 50 tok/s.
    obs.DECODE_TOKENS.inc(100)
    with w._lock:
        w._snaps = [(w._snaps[-1][0] - 2.0, obs.DECODE_TOKENS.value() - 100)]
    res = w.evaluate()
    tok = next(v for v in res["slos"] if v["name"] == "decode_tok_s_chip")
    assert tok["value"] == pytest.approx(50.0 / tok["chips"], rel=0.2)
    assert tok["pass"] is True


def test_scrape_collector_gauges():
    obs.TTFT_SECONDS.observe(2.0)  # breach at the 500 ms default
    text = obs.metrics_text()
    assert 'opsagent_slo_pass{slo="ttft_p50_ms"} 0' in text
    assert 'opsagent_slo_burn_rate{slo="ttft_p50_ms"}' in text
    assert 'opsagent_slo_value{slo="ttft_p50_ms"}' in text
    # No data for ITL in this test: -1, not a fake verdict.
    assert 'opsagent_slo_pass{slo="itl_p50_ms"} -1' in text


def test_slo_check_bench_file(tmp_path):
    ok_line = {
        "metric": "m", "value": 1.0,
        "extra": {"slo": {"slos": [
            {"name": "ttft_p50_ms", "target": 500, "value": 80,
             "burn_rate": 0.16, "pass": True, "unit": "ms"},
        ], "pass": True}},
    }
    bad_line = json.loads(json.dumps(ok_line))
    bad_line["extra"]["slo"]["slos"][0].update(
        value=800, burn_rate=1.6, **{"pass": False}
    )
    p_ok = tmp_path / "ok.jsonl"
    p_ok.write_text(json.dumps(ok_line) + "\n")
    p_bad = tmp_path / "bad.jsonl"
    # Last extra.slo wins (the orchestrator's combined line is printed
    # last).
    p_bad.write_text(json.dumps(ok_line) + "\n" + json.dumps(bad_line) + "\n")
    p_none = tmp_path / "none.jsonl"
    p_none.write_text('{"metric": "m", "value": 1.0}\n')
    assert cli_main(["slo-check", "--bench", str(p_ok)]) == 0
    assert cli_main(["slo-check", "--bench", str(p_bad)]) == 1
    assert cli_main(["slo-check", "--bench", str(p_none)]) == 2
    assert cli_main(["slo-check", "--bench", str(tmp_path / "gone")]) == 2


def test_bench_slo_helpers(monkeypatch):
    import bench

    obs.TTFT_SECONDS.observe(0.05)
    v = bench.slo_verdicts()
    assert {s["name"] for s in v["slos"]} >= {"ttft_p50_ms"}
    # Strict gate: breached SLO exits 3 AFTER the result line.
    monkeypatch.setenv("OPSAGENT_BENCH_SLO_STRICT", "1")
    obs.TTFT_SECONDS.observe(5.0)
    obs.TTFT_SECONDS.observe(5.0)
    with pytest.raises(SystemExit) as ei:
        bench.exit_if_slo_breach(bench.slo_verdicts())
    assert ei.value.code == 3
    monkeypatch.setenv("OPSAGENT_BENCH_SLO_STRICT", "0")
    bench.exit_if_slo_breach(bench.slo_verdicts())  # gate off: no exit
