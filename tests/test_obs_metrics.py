"""Metrics-core tests: exposition golden (parseable Prometheus text,
histogram bucket cumulativity, label escaping), concurrency hammering,
the PerfStats bridge, and the bounded-series / timer-path fixes in
utils/perf.py."""

import re
import threading

import pytest

from opsagent_tpu import obs
from opsagent_tpu.obs.metrics import (
    Histogram,
    Registry,
    escape_label_value,
)
from opsagent_tpu.utils.perf import SERIES_WINDOW, PerfStats, get_perf_stats

# A sample line: name{labels} value — labels optional; value is a number
# ("+Inf" never appears as a VALUE, only inside a le label).
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"          # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\[\\\"n])*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\[\\\"n])*\")*\})?"
    r" -?[0-9.e+-]+$"
)


def parse_exposition(text: str) -> dict[str, float]:
    """Validate every line of the exposition and return {sample: value}."""
    assert text.endswith("\n")
    samples: dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _SAMPLE.match(line), f"malformed exposition line: {line!r}"
        key, val = line.rsplit(" ", 1)
        samples[key] = float(val)
    return samples


def test_exposition_golden():
    r = Registry()
    c = r.counter("req_total", "requests", labelnames=("path",))
    c.inc(path="/a")
    c.inc(2, path="/b")
    g = r.gauge("occupancy", "batch fill")
    g.set(0.5)
    h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    text = r.render()
    assert "# HELP req_total requests\n# TYPE req_total counter" in text
    assert "# TYPE occupancy gauge" in text
    assert "# TYPE lat_seconds histogram" in text
    samples = parse_exposition(text)
    assert samples['req_total{path="/a"}'] == 1
    assert samples['req_total{path="/b"}'] == 2
    assert samples["occupancy"] == 0.5
    # Cumulativity: each bucket includes everything below it; +Inf == count.
    assert samples['lat_seconds_bucket{le="0.1"}'] == 1
    assert samples['lat_seconds_bucket{le="1"}'] == 3
    assert samples['lat_seconds_bucket{le="+Inf"}'] == 4
    assert samples["lat_seconds_count"] == 4
    assert samples["lat_seconds_sum"] == pytest.approx(6.05)


def test_label_escaping():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    r = Registry()
    c = r.counter("esc_total", labelnames=("k",))
    c.inc(k='quo"te\nnl\\bs')
    text = r.render()
    line = [l for l in text.splitlines() if l.startswith("esc_total")][0]
    assert "\n" not in line  # a raw newline would split the sample
    assert '\\"' in line and "\\n" in line and "\\\\" in line
    parse_exposition(text)


def test_histogram_boundary_lands_in_bucket():
    # Prometheus buckets are upper-INCLUSIVE: observe(le) counts in le.
    h = Histogram("h", "", (), buckets=(1.0, 2.0))
    h.observe(1.0)
    h.observe(2.0)
    lines = h.collect()
    assert 'h_bucket{le="1"} 1' in lines
    assert 'h_bucket{le="2"} 2' in lines


def test_registry_idempotent_and_type_conflict():
    r = Registry()
    a = r.counter("same_total", "first help")
    b = r.counter("same_total", "other help")
    assert a is b
    with pytest.raises(ValueError):
        r.gauge("same_total")
    with pytest.raises(ValueError):
        r.counter("0bad name")


def test_counters_and_histograms_under_contention():
    r = Registry()
    c = r.counter("hammer_total", labelnames=("t",))
    h = r.histogram("hammer_seconds", buckets=(0.5,))
    g = r.gauge("hammer_gauge")
    N, T = 500, 8

    def work(i: int) -> None:
        for j in range(N):
            c.inc(t=str(i % 3))
            h.observe(0.25 if j % 2 else 0.75)
            g.set(float(j))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(c.value(t=str(k)) for k in range(3))
    assert total == N * T
    assert h.count() == N * T
    samples = parse_exposition(r.render())
    assert samples['hammer_seconds_bucket{le="+Inf"}'] == N * T
    assert samples['hammer_seconds_bucket{le="0.5"}'] == N * T // 2


def test_perf_bridge_into_default_registry():
    get_perf_stats().record_metric("bridge.test", 12.5, "ms")
    get_perf_stats().set_gauge("bridge.gauge", 3.0)
    text = obs.metrics_text()
    samples = parse_exposition(text)
    assert samples[
        'opsagent_perf{series="bridge.test",stat="count",unit="ms"}'
    ] == 1
    assert samples[
        'opsagent_perf{series="bridge.test",stat="avg",unit="ms"}'
    ] == 12.5
    assert samples[
        'opsagent_perf{series="bridge.gauge",stat="gauge",unit=""}'
    ] == 3.0


def test_snapshot_is_compact_and_json_safe():
    import json

    r = Registry()
    r.counter("snap_total", labelnames=("k",)).inc(3, k="x")
    r.histogram("snap_seconds", buckets=(1.0,)).observe(0.5)
    snap = r.snapshot()
    assert snap['snap_total{k="x"}'] == 3
    assert snap["snap_seconds_count"] == 1
    assert snap["snap_seconds_sum"] == 0.5
    json.dumps(snap)  # must be serializable straight into BENCH_*.json


# -- utils/perf.py satellites -------------------------------------------------
def test_perf_series_memory_is_bounded():
    ps = PerfStats()
    n = SERIES_WINDOW + 500
    for i in range(n):
        ps.record_metric("busy", float(i), "ms")
    s = ps.get_stats()["busy"]
    # count/avg/min/max exact over ALL observations; window bounds memory.
    assert s["count"] == n
    assert s["min"] == 0.0
    assert s["max"] == float(n - 1)
    assert s["avg"] == pytest.approx((n - 1) / 2)
    assert len(ps._series["busy"].values) == SERIES_WINDOW
    # percentiles come from the recent window
    assert s["p50"] >= 500.0


def test_perf_reset_keeps_inflight_timers():
    ps = PerfStats()
    ps.start_timer("op")
    ps.reset()  # lands mid-request
    ms = ps.stop_timer("op")
    assert ms > 0.0
    assert ps.get_stats()["op"]["count"] == 1


def test_perf_timer_paths_unified():
    ps = PerfStats()
    ps.start_timer("op")
    ps.stop_timer("op")
    with ps.timer("op"):
        pass
    s = ps.get_stats()["op"]
    assert s["count"] == 2
    assert s["unit"] == "ms"
    # disabled registry records nothing on ANY path
    ps.enabled = False
    ps.start_timer("op")
    assert ps.stop_timer("op") == 0.0
    assert ps.get_stats()["op"]["count"] == 2
