"""Training checkpoint/resume (training/checkpoint.py, orbax-backed) on the
virtual 8-device mesh: save -> restore must round-trip sharded state
exactly, and a resumed run must continue identically to an uninterrupted
one (SURVEY §5 checkpoint/resume — absent in the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.models.config import get_config_preset
from opsagent_tpu.parallel.mesh import make_mesh
from opsagent_tpu.training import (
    TrainConfig,
    init_train_state,
    latest_step,
    make_train_step,
    restore_train_state,
    save_train_state,
)

CFG = get_config_preset("tiny-test")
TC = TrainConfig(learning_rate=3e-3, remat=False)


def _data(seed=1, B=4, S=16):
    tokens = jnp.asarray(
        np.random.default_rng(seed).integers(1, 500, (B, S)), jnp.int32
    )
    return tokens, jnp.ones((B, S), jnp.float32)


def test_save_restore_roundtrip_and_identical_resume(tmp_path):
    mesh = make_mesh(tp=2, dp=2, sp=2)
    params, opt_state = init_train_state(
        CFG, TC, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    step = make_train_step(CFG, TC, mesh, dtype=jnp.float32)
    tokens, mask = _data()

    # Uninterrupted run: 2 steps, checkpoint after the first. The step
    # donates its inputs, so snapshot host copies before continuing.
    params, opt_state, _ = step(params, opt_state, tokens, mask)
    save_train_state(str(tmp_path), 1, params, opt_state)
    saved_host = [np.asarray(x) for x in jax.tree.leaves(params)]
    saved_shardings = [x.sharding for x in jax.tree.leaves(params)]
    p_cont, o_cont, m_cont = step(params, opt_state, tokens, mask)
    cont_host = [np.asarray(x) for x in jax.tree.leaves(p_cont)]

    # Resume from disk into FRESH sharded state and take the same step.
    p0, o0 = init_train_state(
        CFG, TC, mesh, jax.random.PRNGKey(99), dtype=jnp.float32
    )
    p_res, o_res, got_step = restore_train_state(str(tmp_path), p0, o0)
    assert got_step == 1
    for a, want, sh in zip(
        jax.tree.leaves(p_res), saved_host, saved_shardings
    ):
        # placement restored, not host-side (P() vs P(None) are the same
        # replicated layout, so compare by equivalence)
        assert a.sharding.is_equivalent_to(sh, a.ndim)
        assert np.array_equal(np.asarray(a), want)
    p_res2, o_res2, m_res = step(p_res, o_res, tokens, mask)
    assert float(m_res["loss"]) == float(m_cont["loss"])
    for a, want in zip(jax.tree.leaves(p_res2), cont_host):
        assert np.array_equal(np.asarray(a), want)


def test_restore_onto_different_mesh(tmp_path):
    """Orbax reshards on read: a checkpoint saved on one mesh restores
    onto another topology (elastic resume after a slice-size change)."""
    mesh_a = make_mesh(tp=2, dp=2, sp=2)
    params, opt_state = init_train_state(
        CFG, TC, mesh_a, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    save_train_state(str(tmp_path), 3, params, opt_state)

    mesh_b = make_mesh(tp=4, dp=2, sp=1)
    p0, o0 = init_train_state(
        CFG, TC, mesh_b, jax.random.PRNGKey(7), dtype=jnp.float32
    )
    p_res, _, got = restore_train_state(str(tmp_path), p0, o0)
    assert got == 3
    for a, b in zip(jax.tree.leaves(p_res), jax.tree.leaves(params)):
        assert jnp.array_equal(a, b)


def test_latest_step_and_missing(tmp_path):
    assert latest_step(str(tmp_path)) is None
    with pytest.raises(FileNotFoundError):
        restore_train_state(str(tmp_path), {}, {})
    mesh = make_mesh(tp=2, dp=2, sp=2)
    params, opt_state = init_train_state(
        CFG, TC, mesh, jax.random.PRNGKey(0), dtype=jnp.float32
    )
    save_train_state(str(tmp_path), 1, params, opt_state)
    save_train_state(str(tmp_path), 10, params, opt_state)
    assert latest_step(str(tmp_path)) == 10
