"""Kubernetes client tests against a fake apiserver (the reference shipped
its client-go layer untested; SURVEY.md section 4)."""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest
import yaml

from opsagent_tpu.k8s.client import K8sClient, KubeConfig, K8sError, _load_kubeconfig_file

POD = {
    "apiVersion": "v1",
    "kind": "Pod",
    "metadata": {"name": "mypod", "namespace": "default"},
    "spec": {"containers": [{"name": "c", "image": "nginx:1.25"}]},
}


class FakeAPIServer(BaseHTTPRequestHandler):
    applied = []

    def _json(self, obj, status=200):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass

    def do_GET(self):
        if self.path == "/api/v1":
            self._json(
                {
                    "resources": [
                        {
                            "name": "pods",
                            "singularName": "pod",
                            "kind": "Pod",
                            "namespaced": True,
                            "shortNames": ["po"],
                        },
                        {
                            "name": "pods/log",
                            "singularName": "",
                            "kind": "Pod",
                            "namespaced": True,
                        },
                        {
                            "name": "namespaces",
                            "singularName": "namespace",
                            "kind": "Namespace",
                            "namespaced": False,
                            "shortNames": ["ns"],
                        },
                    ]
                }
            )
        elif self.path == "/apis":
            self._json(
                {
                    "groups": [
                        {
                            "name": "apps",
                            "preferredVersion": {"groupVersion": "apps/v1"},
                        }
                    ]
                }
            )
        elif self.path == "/apis/apps/v1":
            self._json(
                {
                    "resources": [
                        {
                            "name": "deployments",
                            "singularName": "deployment",
                            "kind": "Deployment",
                            "namespaced": True,
                            "shortNames": ["deploy"],
                        }
                    ]
                }
            )
        elif self.path == "/api/v1/namespaces/default/pods/mypod":
            self._json(POD)
        else:
            self._json({"kind": "Status", "message": "not found"}, status=404)

    def do_PATCH(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        FakeAPIServer.applied.append(
            {
                "path": self.path,
                "content_type": self.headers.get("Content-Type"),
                "body": body.decode(),
                "auth": self.headers.get("Authorization", ""),
            }
        )
        self._json({"status": "ok"})


@pytest.fixture
def fake_apiserver():
    FakeAPIServer.applied = []
    server = HTTPServer(("127.0.0.1", 0), FakeAPIServer)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def test_get_yaml(fake_apiserver):
    client = K8sClient(KubeConfig(server=fake_apiserver, token="tok"))
    out = client.get_yaml("pod", "mypod", "default")
    obj = yaml.safe_load(out)
    assert obj["metadata"]["name"] == "mypod"
    assert obj["spec"]["containers"][0]["image"] == "nginx:1.25"


def test_get_yaml_by_shortname_and_plural(fake_apiserver):
    client = K8sClient(KubeConfig(server=fake_apiserver))
    assert "mypod" in client.get_yaml("po", "mypod", "default")
    assert "mypod" in client.get_yaml("pods", "mypod", "default")


def test_unknown_resource(fake_apiserver):
    client = K8sClient(KubeConfig(server=fake_apiserver))
    with pytest.raises(K8sError):
        client.get_yaml("frob", "x", "default")


def test_apply_yaml_server_side(fake_apiserver):
    client = K8sClient(KubeConfig(server=fake_apiserver, token="tok"))
    manifests = """
apiVersion: apps/v1
kind: Deployment
metadata:
  name: web
  namespace: default
spec:
  replicas: 2
---
apiVersion: v1
kind: Namespace
metadata:
  name: staging
"""
    applied = client.apply_yaml(manifests)
    assert applied == ["Deployment/web", "Namespace/staging"]
    first = FakeAPIServer.applied[0]
    assert first["path"].startswith("/apis/apps/v1/namespaces/default/deployments/web")
    assert "fieldManager=application%2Fapply-patch" in first["path"]
    assert "force=true" in first["path"]
    assert first["content_type"] == "application/apply-patch+yaml"
    assert first["auth"] == "Bearer tok"
    second = FakeAPIServer.applied[1]
    assert second["path"].startswith("/api/v1/namespaces/staging")


def test_apply_yaml_missing_fields(fake_apiserver):
    client = K8sClient(KubeConfig(server=fake_apiserver))
    with pytest.raises(K8sError):
        client.apply_yaml("kind: Pod\nmetadata: {}\n")


def test_kubeconfig_file_parse(tmp_path):
    ca = base64.b64encode(b"fake-ca").decode()
    cfg_file = tmp_path / "config"
    cfg_file.write_text(
        yaml.safe_dump(
            {
                "current-context": "ctx",
                "contexts": [
                    {
                        "name": "ctx",
                        "context": {
                            "cluster": "c1",
                            "user": "u1",
                            "namespace": "ops",
                        },
                    }
                ],
                "clusters": [
                    {
                        "name": "c1",
                        "cluster": {
                            "server": "https://k8s.example:6443",
                            "certificate-authority-data": ca,
                        },
                    }
                ],
                "users": [{"name": "u1", "user": {"token": "secret"}}],
            }
        )
    )
    cfg = _load_kubeconfig_file(str(cfg_file))
    assert cfg.server == "https://k8s.example:6443"
    assert cfg.token == "secret"
    assert cfg.namespace == "ops"
    with open(cfg.ca_cert_path, "rb") as f:
        assert f.read() == b"fake-ca"
