"""OpenAI tool_choice (FSM-forced function calls) and n-choices support."""

import json

import jax.numpy as jnp
import pytest

from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.scheduler import RequestError

KW = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
    num_pages=1024, max_pages_per_seq=128, max_batch_size=4,
    prefill_buckets=(16, 64),
)

TOOLS = [
    {"type": "function", "function": {
        "name": "kubectl",
        "parameters": {
            "type": "object",
            "properties": {"command": {"type": "string"}},
        },
    }},
    {"type": "function", "function": {
        "name": "trivy",
        "parameters": {
            "type": "object",
            "properties": {"image": {"type": "string"}},
        },
    }},
]


@pytest.fixture(scope="module")
def stack():
    s = ServingStack(Engine(EngineConfig(**KW)))
    yield s
    s.close()


#: Deterministic structure-closing biases: inside a string the quote wins
#: (closes it immediately — random weights never close quotes on their
#: own), after it '}' then ']' win wherever the grammar allows them, so
#: the envelope completes in tens of tokens no matter the weights. The
#: strict bias ordering also exercises mask+bias composition: a bias must
#: never override a grammar-forbidden position.
CLOSE_BIAS = {str(ord('"')): 100, str(ord('}')): 99, str(ord(']')): 98}


def test_forced_function_emits_valid_call(stack):
    """tool_choice naming a function: even a random tiny model MUST emit a
    parseable tool_calls envelope calling exactly that function — the FSM
    makes it structurally impossible not to. CLOSE_BIAS pins the free-text
    positions so the envelope always completes within the token budget
    (greedy tokens are otherwise weight-dependent)."""
    resp = stack.chat_completion({
        "messages": [{"role": "user", "content": "scan the image"}],
        "tools": TOOLS,
        "tool_choice": {"type": "function", "function": {"name": "trivy"}},
        "logit_bias": dict(CLOSE_BIAS),
        "max_tokens": 512, "temperature": 0,
    })
    choice = resp["choices"][0]
    assert choice["finish_reason"] == "tool_calls"
    calls = choice["message"]["tool_calls"]
    assert calls[0]["function"]["name"] == "trivy"
    json.loads(calls[0]["function"]["arguments"])  # valid JSON args


def test_required_constrains_to_listed_tools(stack):
    resp = stack.chat_completion({
        "messages": [{"role": "user", "content": "do something"}],
        "tools": TOOLS,
        "tool_choice": "required",
        "logit_bias": dict(CLOSE_BIAS),
        "max_tokens": 512, "temperature": 0,
    })
    calls = resp["choices"][0]["message"]["tool_calls"]
    assert calls[0]["function"]["name"] in ("kubectl", "trivy")


def test_tool_choice_validation(stack):
    with pytest.raises(RequestError):
        stack.chat_completion({
            "messages": [{"role": "user", "content": "x"}],
            "tool_choice": "required",      # no tools listed
        })
    with pytest.raises(RequestError):
        stack.chat_completion({
            "messages": [{"role": "user", "content": "x"}],
            "tools": TOOLS,
            "tool_choice": {"type": "function",
                            "function": {"name": "nope"}},
        })
    with pytest.raises(RequestError):
        stack.chat_completion({
            "messages": [{"role": "user", "content": "x"}],
            "tools": TOOLS,
            "tool_choice": "required",
            "response_format": {"type": "json_object"},  # two grammars
        })


def test_n_choices(stack):
    resp = stack.chat_completion({
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 4, "temperature": 0, "n": 3,
    })
    assert [c["index"] for c in resp["choices"]] == [0, 1, 2]
    # Greedy: all choices identical; usage sums completions.
    texts = {c["message"]["content"] for c in resp["choices"]}
    assert len(texts) == 1
    assert resp["usage"]["completion_tokens"] == 12

    with pytest.raises(RequestError):
        stack.chat_completion({
            "messages": [{"role": "user", "content": "x"}], "n": 9,
        })
    gen = stack.chat_completion_stream({
        "messages": [{"role": "user", "content": "x"}],
        "stream": True, "n": 2,
    })
    with pytest.raises(RequestError):
        next(gen)


def test_n_choices_with_constraint_use_distinct_fsm_walkers(stack):
    """Regression: n>1 constrained requests must each get their OWN
    JsonConstraint (the DFA walk is per-sequence state); a shared one
    crosses grammar positions between interleaved rows."""
    resp = stack.chat_completion({
        "messages": [{"role": "user", "content": "go"}],
        "tools": TOOLS,
        "tool_choice": {"type": "function", "function": {"name": "kubectl"}},
        "logit_bias": {str(ord('"')): 100},
        "max_tokens": 512, "temperature": 0, "n": 2,
    })
    for c in resp["choices"]:
        assert c["finish_reason"] == "tool_calls", c
        assert c["message"]["tool_calls"][0]["function"]["name"] == "kubectl"
