"""OpenAI logprobs support: per-token logprob of the sampled token plus
top-N alternatives, end to end (engine -> scheduler -> chat.completions).
Values are pinned against the full-forward oracle's log_softmax."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.models import llama
from opsagent_tpu.serving.api import ServingStack
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams

KW = dict(
    model="tiny-test", dtype=jnp.float32, tp=1, page_size=8,
    num_pages=256, max_pages_per_seq=32, max_batch_size=4,
    prefill_buckets=(16,),
)


@pytest.fixture(scope="module")
def engine():
    return Engine(EngineConfig(**KW))


def test_engine_logprobs_match_oracle(engine):
    prompt = [257, 5, 6, 7]
    sid = engine.add_request(
        prompt,
        SamplingParams(temperature=0.0, max_tokens=4, logprobs=True,
                       top_logprobs=3),
    )
    while not engine.sequences[sid].done:
        engine.step_block([sid])
    seq = engine.sequences[sid]
    data = list(seq.logprob_data)
    toks = engine.finish(sid)
    assert len(data) == len(toks)
    # Oracle: teacher-forced full forward, log_softmax at each position.
    ctx = list(prompt)
    for t, d in zip(toks, data):
        logits = llama.forward_full(
            engine.params, engine.model_cfg, jnp.asarray([ctx]),
            dtype=jnp.float32,
        )
        lp = jax.nn.log_softmax(logits[0, -1])
        assert abs(float(lp[t]) - d["logprob"]) < 1e-3
        assert len(d["top"]) == 3
        # Tops are the true argmax set, sorted descending.
        want_top = np.argsort(-np.asarray(lp))[:3]
        assert [i for i, _ in d["top"]] == [int(x) for x in want_top]
        assert d["top"][0][1] >= d["top"][1][1] >= d["top"][2][1]
        ctx.append(t)


def test_logprobs_without_top(engine):
    sid = engine.add_request(
        [257, 9], SamplingParams(temperature=0.0, max_tokens=2, logprobs=True),
    )
    while not engine.sequences[sid].done:
        engine.step_block([sid])
    data = list(engine.sequences[sid].logprob_data)
    engine.finish(sid)
    assert all(d["top"] == [] for d in data)
    assert all(d["logprob"] <= 0.0 for d in data)


def test_chat_completion_logprobs_shape():
    stack = ServingStack(Engine(EngineConfig(**KW)))
    try:
        resp = stack.chat_completion({
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 3, "temperature": 0,
            "logprobs": True, "top_logprobs": 2,
        })
        lp = resp["choices"][0]["logprobs"]
        assert lp is not None and len(lp["content"]) >= 1
        ent = lp["content"][0]
        assert isinstance(ent["token"], str)
        assert ent["logprob"] <= 0.0
        assert len(ent["top_logprobs"]) == 2
        assert ent["top_logprobs"][0]["logprob"] >= ent["top_logprobs"][1]["logprob"]

        plain = stack.chat_completion({
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 3, "temperature": 0,
        })
        assert "logprobs" not in plain["choices"][0]
    finally:
        stack.close()


def test_top_logprobs_validation():
    stack = ServingStack(Engine(EngineConfig(**KW)))
    try:
        from opsagent_tpu.serving.scheduler import RequestError

        with pytest.raises(RequestError):
            stack.chat_completion({
                "messages": [{"role": "user", "content": "x"}],
                "top_logprobs": 3,   # without logprobs: true
            })
        with pytest.raises(RequestError):
            stack.chat_completion({
                "messages": [{"role": "user", "content": "x"}],
                "logprobs": True, "top_logprobs": 21,
            })
    finally:
        stack.close()


def test_logprobs_row_does_not_block_plain_batch(engine):
    """A logprob row host-steps while plain rows keep block-decoding; both
    finish with correct results."""
    want = engine.generate(
        [[257, 1, 2, 3]], SamplingParams(temperature=0.0, max_tokens=5)
    )[0]
    a = engine.add_request(
        [257, 1, 2, 3], SamplingParams(temperature=0.0, max_tokens=5)
    )
    b = engine.add_request(
        [257, 8, 9],
        SamplingParams(temperature=0.0, max_tokens=5, logprobs=True),
    )
    pending = {a, b}
    while pending:
        engine.step_block(sorted(pending))
        pending = {i for i in pending if not engine.sequences[i].done}
    lp_len = len(engine.sequences[b].logprob_data)
    ta, tb = engine.finish(a), engine.finish(b)
    assert ta == want
    assert lp_len == len(tb)


def test_stream_with_logprobs_rejected():
    from opsagent_tpu.serving.scheduler import RequestError

    stack = ServingStack(Engine(EngineConfig(**KW)))
    try:
        gen = stack.chat_completion_stream({
            "messages": [{"role": "user", "content": "x"}],
            "stream": True, "logprobs": True,
        })
        with pytest.raises(RequestError, match="stream"):
            next(gen)
    finally:
        stack.close()
