"""Weight-only int8 quantization (models.quant): numerical fidelity of the
per-channel scheme, engine integration, and sharded execution — the path
that fits Llama-3-8B onto one 16 GB v5e chip and halves decode's weight
streaming."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from opsagent_tpu.models import llama
from opsagent_tpu.models.config import get_config_preset
from opsagent_tpu.models.quant import (
    QuantizedLinear,
    quantize_params,
    quantize_specs,
    quantize_weight,
)
from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams

CFG = get_config_preset("tiny-test")


def test_quantize_weight_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 128)) * 0.05, jnp.float32)
    q = quantize_weight(w)
    assert q.q.dtype == jnp.int8
    err = np.abs(np.asarray(q.dequantize()) - np.asarray(w))
    # Symmetric per-channel: max error is half a quantization step.
    step = np.asarray(q.scale)[0]
    assert (err <= step / 2 + 1e-7).all()


def test_quantized_forward_close_to_fp():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = quantize_params(params)
    toks = jnp.asarray([[257, 72, 101, 108, 108, 111]], jnp.int32)
    ref = np.asarray(llama.forward_full(params, CFG, toks, dtype=jnp.float32))
    got = np.asarray(llama.forward_full(qparams, CFG, toks, dtype=jnp.float32))
    # Weight-only int8 is near-lossless: logits stay highly correlated and
    # the greedy choice at every position survives.
    corr = np.corrcoef(ref.ravel(), got.ravel())[0, 1]
    assert corr > 0.999
    # Random tiny-model logits are nearly flat, so exact argmax equality
    # everywhere is too strict; most positions must still agree.
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree >= 0.8, agree


def test_specs_tree_matches_params_tree():
    params = llama.init_params(
        get_config_preset("tiny-moe"), jax.random.PRNGKey(0), jnp.float32
    )
    qparams = quantize_params(params)
    qspecs = quantize_specs(
        llama.param_specs(get_config_preset("tiny-moe"))
    )
    # Structures must pair exactly for shard_params' tree.map.
    jax.tree.map(lambda a, b: None, qparams, qspecs)


def test_engine_generate_quantized():
    kwargs = dict(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=64, max_pages_per_seq=16, max_batch_size=2,
        prefill_buckets=(16, 32), prefix_cache=False,
    )
    fp = Engine(EngineConfig(**kwargs))
    want = fp.generate([[257, 5, 6, 7]], SamplingParams(max_tokens=6))[0]
    q = Engine(EngineConfig(quantize="int8", **kwargs))
    got = q.generate([[257, 5, 6, 7]], SamplingParams(max_tokens=6))[0]
    # Tiny random models have near-tied logits, so token-exact agreement
    # with the fp engine is not guaranteed; the quantized engine must
    # still produce a full, well-formed generation (fidelity itself is
    # asserted against logits in test_quantized_forward_close_to_fp).
    assert len(got) >= 1
    assert len(got) == len(want) or got[-1] == q.tokenizer.eos_id


def test_engine_quantized_under_tp_mesh():
    """Quantized params must shard and execute on a tp=2 mesh (int8 weight
    + scale follow the weight's output-axis sharding)."""
    eng = Engine(EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=2, page_size=4,
        num_pages=64, max_pages_per_seq=16, max_batch_size=2,
        prefill_buckets=(16,), quantize="int8",
    ))
    assert eng.mesh.shape["tp"] == 2
    out = eng.generate([[257, 1, 2, 3]], SamplingParams(max_tokens=4))
    assert len(out[0]) >= 1


def test_rejects_unknown_quantize():
    with pytest.raises(ValueError, match="int8"):
        Engine(EngineConfig(
            model="tiny-test", dtype=jnp.float32, quantize="fp4",
            num_pages=16, page_size=4, max_pages_per_seq=4,
            prefill_buckets=(16,),
        ))


# -- int4 (group-wise scales) ------------------------------------------------

def test_quantize_weight4_roundtrip_error_bound():
    from opsagent_tpu.models.quant import quantize_weight4

    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((256, 96)) * 0.05, jnp.float32)
    q = quantize_weight4(w, group=128)
    # Self-packed storage: int8 bytes, two nibbles each, half the rows.
    assert q.q.dtype == jnp.int8
    assert q.q.shape == (128, 96)
    assert q.shape == (256, 96)
    assert q.scale.shape == (2, 1, 96)  # 256 / 128 groups
    err = np.abs(np.asarray(q.dequantize()) - np.asarray(w))
    # Max error is half a step of the group's scale.
    step = np.repeat(np.asarray(q.scale), 128, axis=-2).reshape(256, 96)
    assert (err <= step / 2 + 1e-7).all()


def test_pack_int4_roundtrip_exact():
    """pack -> dequantize(scale=1) must reproduce every value in [-8, 7],
    including the sign-extension of negative nibbles in both positions."""
    from opsagent_tpu.models.quant import QuantizedLinear4, pack_int4

    vals = np.arange(-8, 8, dtype=np.int8)          # every nibble value
    w = np.stack([vals, vals[::-1]], axis=-1)       # [16, 2]
    packed = pack_int4(jnp.asarray(w))
    assert packed.dtype == jnp.int8 and packed.shape == (8, 2)
    q = QuantizedLinear4(packed, jnp.ones((1, 1, 2), jnp.float32))
    np.testing.assert_array_equal(np.asarray(q.dequantize()), w.astype(np.float32))


def test_pack_int4_odd_contraction_dim_rejected():
    from opsagent_tpu.models.quant import pack_int4

    with np.testing.assert_raises(ValueError):
        pack_int4(jnp.zeros((7, 4), jnp.int8))


def test_quantize_weight4_group_fallback_on_indivisible_axis():
    from opsagent_tpu.models.quant import quantize_weight4

    w = jnp.asarray(np.random.default_rng(2).standard_normal((60, 8)),
                    jnp.float32)
    q = quantize_weight4(w, group=128)  # 60 % 128 != 0 -> one group
    assert q.scale.shape == (1, 1, 8)
    assert q.dequantize().shape == (60, 8)


def test_int4_forward_close_to_fp():
    from opsagent_tpu.models.quant import quantize_params

    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = quantize_params(params, mode="int4")
    toks = jnp.asarray([[257, 72, 101, 108, 108, 111]], jnp.int32)
    ref = np.asarray(llama.forward_full(params, CFG, toks, dtype=jnp.float32))
    got = np.asarray(llama.forward_full(qparams, CFG, toks, dtype=jnp.float32))
    # int4 is lossier than int8, and tiny-test's 64-dim contraction axes
    # fall back to ONE whole-axis group (worst case for int4) — real
    # models get 128-wide groups and much tighter error. The logits must
    # still track the fp model strongly.
    corr = np.corrcoef(ref.ravel(), got.ravel())[0, 1]
    assert corr > 0.9, corr


def test_int4_specs_tree_matches_params_tree():
    params = llama.init_params(
        get_config_preset("tiny-moe"), jax.random.PRNGKey(0), jnp.float32
    )
    qparams = quantize_params(params, mode="int4")
    qspecs = quantize_specs(
        llama.param_specs(get_config_preset("tiny-moe")), mode="int4"
    )
    jax.tree.map(lambda a, b: None, qparams, qspecs)


def test_engine_generate_int4():
    kwargs = dict(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=64, max_pages_per_seq=16, max_batch_size=2,
        prefill_buckets=(16, 32), prefix_cache=False,
    )
    q = Engine(EngineConfig(quantize="int4", **kwargs))
    got = q.generate([[257, 5, 6, 7]], SamplingParams(max_tokens=6))[0]
    assert len(got) >= 1


def test_engine_int4_under_tp_mesh():
    """int4 params must shard and execute on a tp=2 mesh (weight keeps
    its spec; replicated group scales sidestep G-divisibility)."""
    eng = Engine(EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=2, page_size=4,
        num_pages=64, max_pages_per_seq=16, max_batch_size=2,
        prefill_buckets=(16,), quantize="int4",
    ))
    assert eng.mesh.shape["tp"] == 2
    out = eng.generate([[257, 1, 2, 3]], SamplingParams(max_tokens=4))
    assert len(out[0]) >= 1


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_engine_quantized_mla_latent(mode):
    """Quantized MLA latent-cache serving (DeepSeek-class): the absorbed
    decode path reshapes wukv per head, so _dense_weight must dequantize
    BOTH quantized classes — int4 regressed here once (review catch)."""
    import dataclasses

    base = get_config_preset("tiny-mla")
    cfg = dataclasses.replace(
        base, mla=dataclasses.replace(base.mla, latent_cache=True)
    )
    eng = Engine(
        EngineConfig(
            model="tiny-mla", dtype=jnp.float32, tp=1, page_size=4,
            num_pages=64, max_pages_per_seq=16, max_batch_size=2,
            prefill_buckets=(16,), quantize=mode,
        ),
        model_cfg=cfg,
    )
    out = eng.generate([[257, 1, 2, 3]], SamplingParams(max_tokens=4))
    assert len(out[0]) >= 1


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_engine_quantizes_loaded_checkpoint(mode, tmp_path):
    """The HOST-side load->quantize->shard branch (the path real 8B
    checkpoints take: the full-precision tree only ever exists on host):
    greedy generation from the quantized engine must match quantizing
    the same weights directly."""
    from opsagent_tpu.models.loader import save_checkpoint

    params = llama.init_params(CFG, jax.random.PRNGKey(5), dtype=jnp.float32)
    ckpt = tmp_path / "model.safetensors"
    save_checkpoint(str(ckpt), params)

    kwargs = dict(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=64, max_pages_per_seq=16, max_batch_size=2,
        prefill_buckets=(16,), prefix_cache=False,
    )
    eng = Engine(EngineConfig(checkpoint=str(ckpt), quantize=mode, **kwargs))
    got = eng.generate([[257, 9, 8, 7]], SamplingParams(max_tokens=5))[0]
    # Oracle: hand the same in-memory fp tree to an engine with the same
    # quantize mode (the engine quantizes caller-provided params too);
    # the f32 save/load roundtrip is lossless, so outputs must be equal.
    oracle = Engine(EngineConfig(quantize=mode, **kwargs), params=params)
    want = oracle.generate([[257, 9, 8, 7]], SamplingParams(max_tokens=5))[0]
    assert got == want


def test_int4_group_size_adapts_to_non_multiples():
    """A contraction dim that 128 does not divide still gets fine-grained
    groups (largest divisor <= 128), not a whole-axis collapse; only
    pathological dims with no usable divisor fall back, with a warning."""
    from opsagent_tpu.models.quant import _group_size, quantize_weight4

    assert _group_size(4544, 128) == 71    # Falcon-7B-style dim (2^6 * 71)
    assert _group_size(192, 128) == 96
    assert _group_size(4096, 128) == 128
    assert _group_size(131, 128) == 131    # prime > group: whole axis

    w = jnp.asarray(
        np.random.default_rng(3).standard_normal((192, 8)), jnp.float32
    )
    q = quantize_weight4(w, group=128)
    assert q.scale.shape == (2, 1, 8)      # 192 / 96 groups
    assert q.dequantize().shape == (192, 8)


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_engine_quantized_moe(mode):
    """Quantized MoE serving: expert stacks ([L, E, in, out] leaves) go
    through the _ein einsum dispatch; both widths must serve."""
    eng = Engine(EngineConfig(
        model="tiny-moe", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=64, max_pages_per_seq=16, max_batch_size=2,
        prefill_buckets=(16,), quantize=mode,
    ))
    out = eng.generate([[257, 1, 2, 3]], SamplingParams(max_tokens=4))
    assert len(out[0]) >= 1
