"""Span-propagation tests: a chat completion (and a full ReAct run) over
the tiny engine yields one connected span tree with queue/prefill/decode/
tool phases whose top-level durations sum (within tolerance) to the
request wall time, retrievable over HTTP, with /metrics reflecting the
same request counts."""

import asyncio
import time

import jax.numpy as jnp
import pytest
from aiohttp.test_utils import TestClient, TestServer

from opsagent_tpu import obs
from opsagent_tpu.serving.api import ServingStack, build_engine_app, _stacks
from opsagent_tpu.serving.engine import Engine, EngineConfig


@pytest.fixture(scope="module")
def stack():
    cfg = EngineConfig(
        model="tiny-test",
        dtype=jnp.float32,
        tp=1,
        # Roomy page budget: the ReAct test's second turn re-sends the
        # grown history (~600 byte-tokens of JSON + template framing).
        page_size=8,
        num_pages=512,
        max_pages_per_seq=128,
        max_batch_size=4,
        prefill_buckets=(32, 64, 128),
        max_new_tokens_default=8,
    )
    s = ServingStack(Engine(cfg))
    _stacks["tiny-test"] = s
    yield s
    s.close()
    _stacks.pop("tiny-test", None)


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


def _children_by_name(node: dict) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for c in node.get("children", []):
        out.setdefault(c["name"], []).append(c)
    return out


def test_chat_completion_span_tree_and_metrics(stack):
    t0 = time.perf_counter()
    resp = stack.chat_completion(
        {"messages": [{"role": "user", "content": "hello"}], "max_tokens": 6}
    )
    wall_ms = (time.perf_counter() - t0) * 1e3
    tr = obs.get_trace(resp["id"])
    assert tr is not None and tr["finished"]
    # One connected tree: request -> generate -> queue_wait/prefill/decode.
    root = tr["root"]
    gen = _children_by_name(root)["generate"][0]
    phases = _children_by_name(gen)
    assert set(phases) >= {"queue_wait", "prefill", "decode"}
    decode = phases["decode"][0]
    assert decode["attrs"]["tokens"] == resp["usage"]["completion_tokens"]
    assert all(c["name"] in ("decode_block", "decode_step")
               for c in decode.get("children", []))
    # Top-level phases of the generate span partition the engine request:
    # queue_wait ends where prefill starts, prefill where decode starts.
    phase_sum = sum(
        p[0]["duration_ms"]
        for p in (phases["queue_wait"], phases["prefill"], phases["decode"])
    )
    assert phase_sum <= gen["duration_ms"] * 1.05
    assert phase_sum >= gen["duration_ms"] * 0.7
    # ... and the trace wall time matches what the client measured.
    assert tr["duration_ms"] <= wall_ms * 1.05
    # /metrics reflects the same request.
    text = obs.metrics_text()
    assert "# TYPE opsagent_ttft_seconds histogram" in text
    assert "# TYPE opsagent_inter_token_latency_seconds histogram" in text
    assert obs.TTFT_SECONDS.count() == 1
    assert obs.ITL_SECONDS.count() == resp["usage"]["completion_tokens"] - 1
    assert obs.DECODE_TOKENS.value() == resp["usage"]["completion_tokens"]
    assert obs.ENGINE_REQUESTS.value(outcome="completed") == 1
    assert 0.0 <= obs.KV_PAGE_UTILIZATION.value() <= 1.0
    assert "opsagent_kv_page_utilization" in text
    assert "opsagent_decode_tokens_total" in text


def test_metrics_and_trace_over_http(stack):
    app = build_engine_app(stack)

    async def scenario():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.post(
                "/v1/chat/completions",
                json={
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4,
                },
            )
            assert r.status == 200
            cid = (await r.json())["id"]
            m = await client.get("/metrics")
            assert m.status == 200
            assert m.headers["Content-Type"].startswith("text/plain")
            body = await m.text()
            assert "opsagent_ttft_seconds_bucket" in body
            assert "opsagent_kv_page_utilization" in body
            t = await client.get(f"/api/trace/{cid}")
            assert t.status == 200
            tree = await t.json()
            assert tree["request_id"] == cid
            assert tree["root"]["children"], "span tree is empty"
            missing = await client.get("/api/trace/nope")
            assert missing.status == 404
        finally:
            await client.close()

    run(scenario())


def test_react_run_yields_connected_span_tree(stack, fake_tools):
    """A full ReAct request: the fake:// provider routes every llm turn
    through the REAL engine stack (so queue/prefill/decode spans are
    live), then overwrites the reply text with scripted ToolPrompt JSON
    so the loop exercises a tool call. One trace, all phases, sums to the
    wall time, and /metrics counts the same engine requests."""
    import json

    from opsagent_tpu.agent.react import assistant_with_config
    from opsagent_tpu.llm import client as llm_client

    replies = [
        json.dumps({
            "question": "q", "thought": "look at pods",
            "action": {"name": "kubectl", "input": "get pods"},
            "observation": "", "final_answer": "",
        }),
        json.dumps({
            "question": "q", "thought": "done",
            "action": {"name": "", "input": ""},
            "observation": "1 pod running",
            "final_answer": "the cluster is healthy and serving",
        }),
    ]

    def provider(body):
        resp = stack.chat_completion(dict(body, max_tokens=4))
        resp["choices"][0]["message"]["content"] = replies.pop(0)
        return resp

    llm_client.register_provider("fake", lambda target: provider)
    try:
        fake_tools({"kubectl": lambda cmd: f"ran {cmd}: 1 pod"})
        t0 = time.perf_counter()
        final, _ = assistant_with_config(
            "fake://m",
            [
                {"role": "system", "content": "sys"},
                {"role": "user", "content": "check the pods"},
            ],
            max_tokens=64,
        )
        wall_ms = (time.perf_counter() - t0) * 1e3
    finally:
        llm_client._provider_factories.pop("fake", None)
    assert "healthy" in final

    # The loop self-minted the trace (no ambient span): find it by the
    # log-free route — the store holds exactly the traces this test made.
    store = obs.get_store()
    with store._lock:
        traces = list(store._traces.values())
    agent_traces = [t for t in traces if t.request_id.startswith("agent-")]
    assert len(agent_traces) == 1
    tr = agent_traces[0].to_dict()
    assert tr["finished"]
    root = tr["root"]
    top = _children_by_name(root)
    assert len(top["llm_turn"]) == 2
    assert len(top["tool_exec"]) == 1
    assert top["tool_exec"][0]["attrs"]["tool"] == "kubectl"
    # Engine spans nest under each llm_turn: one connected tree from the
    # agent loop down to the decode blocks.
    for turn in top["llm_turn"]:
        gen = _children_by_name(turn)["generate"][0]
        phases = _children_by_name(gen)
        assert set(phases) >= {"queue_wait", "prefill", "decode"}
    # Top-level phases sum to the request wall time (within tolerance:
    # JSON parse/marshal between turns is the only untraced work).
    phase_sum = sum(
        c["duration_ms"] for cs in top.values() for c in cs
    )
    assert phase_sum <= tr["duration_ms"] * 1.05
    assert phase_sum >= tr["duration_ms"] * 0.6
    assert tr["duration_ms"] <= wall_ms * 1.05
    # /metrics saw the same two engine requests and the tool call.
    assert obs.ENGINE_REQUESTS.value(outcome="completed") == 2
    assert obs.TTFT_SECONDS.count() == 2
    assert obs.TOOL_CALLS.value(tool="kubectl", outcome="ok") == 1
    assert obs.AGENT_ITERATIONS.value() == 2


def test_agent_server_metrics_and_trace_endpoints(scripted_llm):
    """The agent REST server: /metrics is public, every response carries
    X-Request-Id, and /api/trace/{id} returns the execute request's span
    tree behind the JWT guard."""
    from opsagent_tpu.server.app import build_app
    from opsagent_tpu.server.jwtauth import issue_token
    from opsagent_tpu.utils.globalstore import set_global

    set_global("jwtKey", "test-key")
    set_global("allowAnonymousLLM", True)
    # Unparseable-as-ToolPrompt first reply: the loop treats it as the
    # final answer, so one llm_turn span and a clean 200.
    scripted_llm(["the deployment looks healthy, nothing to do"])
    token = issue_token("admin", "test-key")

    async def scenario():
        client = TestClient(TestServer(build_app()))
        await client.start_server()
        try:
            m = await client.get("/metrics")  # public: no bearer token
            assert m.status == 200
            assert "X-Request-Id" in m.headers
            r = await client.post(
                "/api/execute",
                json={"instructions": "hi", "currentModel": "fake://m"},
                headers={"Authorization": f"Bearer {token}"},
            )
            assert r.status == 200
            body = await r.json()
            rid = body["request_id"]
            assert rid == r.headers["X-Request-Id"]
            t = await client.get(
                f"/api/trace/{rid}",
                headers={"Authorization": f"Bearer {token}"},
            )
            assert t.status == 200
            tree = await t.json()
            assert tree["request_id"] == rid
            names = {c["name"] for c in tree["root"]["children"]}
            assert "llm_turn" in names
            # the guard still applies to the trace endpoint
            denied = await client.get(f"/api/trace/{rid}")
            assert denied.status == 401
            m2 = await client.get("/metrics")
            text = await m2.text()
            assert 'opsagent_http_requests_total{method="POST"' in text
        finally:
            await client.close()

    run(scenario())
