"""Tests for token accounting (reference pkg/llms/tokens_test.go is the model)."""

from opsagent_tpu.llm.tokens import (
    constrict_messages,
    constrict_prompt,
    count_tokens,
    get_token_limits,
    num_tokens_from_messages,
)


def test_token_limits_table():
    assert get_token_limits("gpt-4") == 8192
    assert get_token_limits("gpt-4-32k") == 32768
    assert get_token_limits("gpt-3.5-turbo") == 16384
    assert get_token_limits("qwen-plus") == 131072
    assert get_token_limits("tpu://llama3-8b") == 131072
    assert get_token_limits("unknown-model") == 4096


def test_longest_prefix_wins():
    assert get_token_limits("gpt-4-turbo-2024") == 128000


def test_count_tokens_monotone():
    assert count_tokens("hello world") < count_tokens("hello world " * 50)


def test_num_tokens_from_messages_overhead():
    msgs = [{"role": "user", "content": "hi"}]
    # 3 per message + 3 priming + content tokens
    assert num_tokens_from_messages(msgs) >= 6


def test_constrict_messages_evicts_oldest_non_system():
    msgs = [{"role": "system", "content": "sys"}]
    for i in range(50):
        msgs.append({"role": "user", "content": f"message {i} " + "filler " * 200})
    out = constrict_messages(msgs, "unknown-model", max_tokens=1024)
    assert out[0]["role"] == "system"
    assert len(out) < len(msgs)
    # the newest message survives
    assert out[-1]["content"] == msgs[-1]["content"]


def test_constrict_prompt_keeps_tail():
    lines = [f"line {i}" for i in range(3000)]
    text = "\n".join(lines)
    out = constrict_prompt(text, 100)
    assert count_tokens(out) <= 100
    assert out.endswith("line 2999")


def test_constrict_prompt_single_long_line():
    text = "x" * 100000
    out = constrict_prompt(text, 50)
    assert count_tokens(out) <= 60  # small tolerance for char-based cut


def test_constrict_prompt_small_input_unchanged():
    assert constrict_prompt("short", 100) == "short"


def test_tpu_model_limit_follows_preset_window():
    """tpu://<preset> budgets against the preset's max_position — the
    same number the engine enforces at admission — not the generic table."""
    from opsagent_tpu.llm.tokens import get_token_limits
    from opsagent_tpu.models.config import get_config_preset

    for name in ("qwen2.5-7b-instruct", "llama-3-8b-instruct", "tiny-test"):
        assert get_token_limits(f"tpu://{name}") == (
            get_config_preset(name).max_position
        )
    # Unknown tpu targets keep the generic tpu window.
    assert get_token_limits("tpu://custom-model") == 131072


def test_tpu_model_limit_follows_installed_stack():
    """ADVICE r03 (medium): stacks are installed under ARBITRARY names
    (tpu://real, tpu://tiny-agent). The constrictor must budget against
    the installed engine's max_position — the number admission enforces —
    not the generic 131072 'tpu' fallback, or long agent histories get
    hard-rejected instead of constricted."""
    from types import SimpleNamespace

    from opsagent_tpu.llm.tokens import get_token_limits
    from opsagent_tpu.serving import api

    fake = SimpleNamespace(
        engine=SimpleNamespace(model_cfg=SimpleNamespace(max_position=8192))
    )
    api.install_stack("real", fake)
    try:
        assert get_token_limits("tpu://real") == 8192
        assert get_token_limits("tpu://REAL") == 8192  # case-tolerant
    finally:
        api.uninstall_stack("real")
    # Back to the generic fallback once uninstalled.
    assert get_token_limits("tpu://real") == 131072
