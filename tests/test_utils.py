"""Tests for perf stats, config, global store, yaml/term utils."""

import threading

from opsagent_tpu.utils.config import load_config, reset_config
from opsagent_tpu.utils.globalstore import get_global, set_global, delete_global
from opsagent_tpu.utils.perf import PerfStats
from opsagent_tpu.utils.term import render_markdown
from opsagent_tpu.utils.yamlutil import extract_yaml


def test_global_store():
    set_global("k", 42)
    assert get_global("k") == 42
    delete_global("k")
    assert get_global("k", "gone") == "gone"


def test_perf_timer_and_summary():
    ps = PerfStats()
    for _ in range(10):
        ps.start_timer("op")
        ps.stop_timer("op")
    ps.record_metric("tokens", 100, "tok")
    ps.set_gauge("tok_per_sec", 1234.5)
    stats = ps.get_stats()
    assert stats["op"]["count"] == 10
    assert stats["op"]["p95"] >= stats["op"]["min"]
    assert stats["tokens"]["unit"] == "tok"
    assert stats["gauges"]["tok_per_sec"] == 1234.5
    table = ps.format_table()
    assert "op" in table
    ps.reset()
    assert ps.get_stats() == {}


def test_perf_thread_safety():
    ps = PerfStats()

    def work(i):
        for j in range(200):
            ps.record_metric(f"m{i % 3}", j)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(s["count"] for s in ps.get_stats().values())
    assert total == 8 * 200


def test_config_defaults(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    reset_config()
    cfg = load_config()
    assert cfg["server"]["port"] == 8080
    assert cfg["perf"]["enabled"] is True
    assert cfg["serving"]["page_size"] == 16


def test_config_file_overrides(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "configs").mkdir()
    (tmp_path / "configs" / "config.yaml").write_text(
        "server:\n  port: 9999\njwt:\n  key: custom\n"
    )
    reset_config()
    cfg = load_config()
    assert cfg["server"]["port"] == 9999
    assert cfg["jwt"]["key"] == "custom"
    assert cfg["log"]["level"] == "info"  # defaults preserved
    reset_config()


def test_extract_yaml():
    text = "Here:\n```yaml\nkind: Pod\nmetadata:\n  name: x\n```\ndone"
    assert extract_yaml(text) == "kind: Pod\nmetadata:\n  name: x\n"
    assert extract_yaml("no fence") == "no fence"


def test_render_markdown_plain():
    out = render_markdown("# Title\n- item\n`code`\n", color=False)
    assert "TITLE" in out
    assert "• item" in out
