"""Tests for perf stats, config, global store, yaml/term utils."""

import threading

from opsagent_tpu.utils.config import load_config, reset_config
from opsagent_tpu.utils.globalstore import get_global, set_global, delete_global
from opsagent_tpu.utils.perf import PerfStats
from opsagent_tpu.utils.term import render_markdown
from opsagent_tpu.utils.yamlutil import extract_yaml


def test_global_store():
    set_global("k", 42)
    assert get_global("k") == 42
    delete_global("k")
    assert get_global("k", "gone") == "gone"


def test_perf_timer_and_summary():
    ps = PerfStats()
    for _ in range(10):
        ps.start_timer("op")
        ps.stop_timer("op")
    ps.record_metric("tokens", 100, "tok")
    ps.set_gauge("tok_per_sec", 1234.5)
    stats = ps.get_stats()
    assert stats["op"]["count"] == 10
    assert stats["op"]["p95"] >= stats["op"]["min"]
    assert stats["tokens"]["unit"] == "tok"
    assert stats["gauges"]["tok_per_sec"] == 1234.5
    table = ps.format_table()
    assert "op" in table
    ps.reset()
    assert ps.get_stats() == {}


def test_perf_thread_safety():
    ps = PerfStats()

    def work(i):
        for j in range(200):
            ps.record_metric(f"m{i % 3}", j)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(s["count"] for s in ps.get_stats().values())
    assert total == 8 * 200


def test_config_defaults(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    reset_config()
    cfg = load_config()
    assert cfg["server"]["port"] == 8080
    assert cfg["perf"]["enabled"] is True
    assert cfg["serving"]["page_size"] == 16


def test_config_file_overrides(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "configs").mkdir()
    (tmp_path / "configs" / "config.yaml").write_text(
        "server:\n  port: 9999\njwt:\n  key: custom\n"
    )
    reset_config()
    cfg = load_config()
    assert cfg["server"]["port"] == 9999
    assert cfg["jwt"]["key"] == "custom"
    assert cfg["log"]["level"] == "info"  # defaults preserved
    reset_config()


def test_extract_yaml():
    text = "Here:\n```yaml\nkind: Pod\nmetadata:\n  name: x\n```\ndone"
    assert extract_yaml(text) == "kind: Pod\nmetadata:\n  name: x\n"
    assert extract_yaml("no fence") == "no fence"


def test_render_markdown_plain():
    out = render_markdown("# Title\n- item\n`code`\n", color=False)
    assert "TITLE" in out
    assert "• item" in out


def test_logger_daily_rotation(tmp_path):
    """Day change switches the log to a new date-stamped file (reference
    logger.go:70-98 parity) and prunes artifacts past retention."""
    import json
    import logging
    import os
    import time

    from opsagent_tpu.utils.logger import DailyRotatingFileHandler, JSONFormatter

    base = str(tmp_path / "opsagent.log")
    h = DailyRotatingFileHandler(base, retention_days=7)
    h.setFormatter(JSONFormatter())
    logger = logging.getLogger("test-daily")
    logger.handlers = [h]
    logger.setLevel(logging.INFO)
    logger.propagate = False

    logger.info("day one")
    today = time.strftime("%Y-%m-%d")
    assert os.path.exists(str(tmp_path / f"opsagent-{today}.log"))

    # Simulate a date change: the handler's recorded day disagrees with
    # the wall clock, so the next emit must roll to the new day's file.
    h._day = "2000-01-01"
    h.baseFilename = os.path.abspath(h._dated())
    logger.info("day two")
    assert os.path.exists(str(tmp_path / f"opsagent-{today}.log"))
    with open(str(tmp_path / f"opsagent-{today}.log")) as f:
        lines = [json.loads(ln) for ln in f]
    assert any(e["msg"] == "day two" for e in lines)

    # Retention: a file stamped old enough gets pruned — but ONLY this
    # handler's date-stamped artifacts. An unrelated same-prefix log
    # (ADVICE r03: opsagent-http.log next to opsagent.log) must survive
    # even when older than retention.
    stale = tmp_path / "opsagent-2000-01-01.log"
    stale.write_text("old\n")
    stale_gz = tmp_path / "opsagent-2000-01-01.log.2.gz"
    stale_gz.write_text("old backup\n")
    other = tmp_path / "opsagent-http.log"
    other.write_text("another subsystem\n")
    old = time.time() - 30 * 86400
    for p in (stale, stale_gz, other):
        os.utime(p, (old, old))
    h.prune()
    assert not stale.exists()
    assert not stale_gz.exists()
    assert other.exists()
    h.close()


def test_logger_size_rotation_compresses(tmp_path):
    """Same-day size rotation keeps backups, gzip-compressed (lumberjack
    Compress parity, reference logger.go:66)."""
    import glob
    import logging

    from opsagent_tpu.utils.logger import DailyRotatingFileHandler

    base = str(tmp_path / "opsagent.log")
    h = DailyRotatingFileHandler(
        base, max_bytes=512, backup_count=3, compress=True
    )
    logger = logging.getLogger("test-size-rot")
    logger.handlers = [h]
    logger.setLevel(logging.INFO)
    logger.propagate = False
    for i in range(100):
        logger.info("x" * 64 + str(i))
    h.close()
    gz = sorted(glob.glob(str(tmp_path / "opsagent-*.log.*.gz")))
    # The shift chain must preserve MULTIPLE backups (.1.gz .2.gz .3.gz),
    # not overwrite a single one — 100 records at 512B cap rotate far
    # more than 3 times, so all backup slots must be occupied.
    assert len(gz) == 3, f"expected 3 gzip backups, got {gz}"
    import gzip as gzmod

    total = sum(
        len(gzmod.open(p, "rt").read().splitlines()) for p in gz
    )
    live = str(tmp_path / f"opsagent-{__import__('time').strftime('%Y-%m-%d')}.log")
    with open(live) as f:
        total += len(f.read().splitlines())
    # backup_count bounds retention; with 3 slots of ~7 records plus the
    # live file we must hold well over one rotation's worth.
    assert total >= 20, f"only {total} records survived rotation"


def test_logger_retention_zero_never_prunes(tmp_path):
    """max_age_days <= 0 = keep forever (lumberjack MaxAge=0 idiom); it
    must NOT mean 'prune everything on startup'."""
    import os
    import time

    from opsagent_tpu.utils.logger import DailyRotatingFileHandler

    stale = tmp_path / "opsagent-2000-01-01.log"
    stale.write_text("ancient\n")
    old = time.time() - 3650 * 86400
    os.utime(stale, (old, old))
    h = DailyRotatingFileHandler(str(tmp_path / "opsagent.log"), retention_days=0)
    h.prune()
    assert stale.exists()
    h.close()
