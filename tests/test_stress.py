"""Concurrency stress: hammer the serving stack from several threads with
mixed admissions, sampled/constrained/raising-stream requests, and chunked
long prompts, asserting the page-conservation invariant throughout — the
Python answer to the reference's missing `go test -race` (SURVEY §5; the
reference CI runs plain `go test`, .github/workflows/test.yaml:23)."""

import random
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Request, Scheduler

NUM_PAGES = 96


@pytest.fixture(scope="module")
def engine():
    return Engine(EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=NUM_PAGES, max_pages_per_seq=24, max_batch_size=4,
        prefill_buckets=(8, 16), decode_block=4,
    ))


def assert_conservation(engine):
    acc = engine.alloc.accounting()
    assert acc["total"] == NUM_PAGES, acc


def test_concurrent_mixed_load_conserves_pages(engine):
    sched = Scheduler(engine)
    sched.start()
    errors: list[str] = []
    lock = threading.Lock()

    def mask_fn_all(generated):
        return np.ones((engine.model_cfg.vocab_size,), bool)

    def client(tid: int):
        rng = random.Random(tid)
        for i in range(6):
            n = rng.randint(3, 40)
            prompt = [257] + [rng.randint(1, 500) for _ in range(n - 1)]
            kind = (tid + i) % 4
            on_token = None
            mask_fn = None
            sampling = SamplingParams(max_tokens=rng.randint(2, 10))
            if kind == 1:
                sampling = SamplingParams(
                    max_tokens=6, temperature=0.9, top_k=8
                )
            elif kind == 2:
                mask_fn = mask_fn_all
            elif kind == 3:
                calls = []

                def boom(tok, calls=calls):  # "client went away"
                    calls.append(tok)
                    if len(calls) >= 2:
                        raise RuntimeError("gone")

                on_token = boom
            req = Request(prompt, sampling, mask_fn=mask_fn, on_token=on_token)
            sched.submit(req)
            if not req.done.wait(120):
                with lock:
                    errors.append(f"t{tid} r{i}: timed out")
                return
            if kind == 3:
                # Raising streams must fail ONLY their own request.
                if not req.error:
                    with lock:
                        errors.append(f"t{tid} r{i}: raising stream not failed")
            elif req.error:
                with lock:
                    errors.append(f"t{tid} r{i}: {req.error}")
            elif not req.tokens:
                with lock:
                    errors.append(f"t{tid} r{i}: no tokens")
            # Invariant under load (snapshot under the engine lock).
            with engine.lock:
                acc = engine.alloc.accounting()
            if acc["total"] != NUM_PAGES:
                with lock:
                    errors.append(f"t{tid} r{i}: page leak {acc}")

    threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "stress client hung"
    finally:
        sched.stop()
    assert errors == []
    # Quiesced: nothing running, nothing leaked, everything conserved.
    assert engine.sequences == {}
    assert_conservation(engine)
    assert engine.alloc.accounting()["owned"] == 0


def test_admissions_race_allocation_against_decode(engine):
    """Direct engine API from racing threads: begin/prefill/step/finish
    interleavings must never break conservation."""
    results: list[list[int]] = []
    errs: list[BaseException] = []

    def worker(seed: int):
        rng = random.Random(seed)
        try:
            for _ in range(4):
                n = rng.randint(3, 30)
                prompt = [257] + [rng.randint(1, 500) for _ in range(n - 1)]
                out = engine.generate(
                    [prompt], SamplingParams(max_tokens=rng.randint(2, 8))
                )
                results.append(out[0])
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive()
    assert errs == []
    assert len(results) == 12 and all(len(r) >= 1 for r in results)
    assert engine.sequences == {}
    assert_conservation(engine)
