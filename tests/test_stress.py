"""Concurrency stress: hammer the serving stack from several threads with
mixed admissions, sampled/constrained/raising-stream requests, and chunked
long prompts, asserting the page-conservation invariant throughout — the
Python answer to the reference's missing `go test -race` (SURVEY §5; the
reference CI runs plain `go test`, .github/workflows/test.yaml:23)."""

import random
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from opsagent_tpu.serving.engine import Engine, EngineConfig
from opsagent_tpu.serving.sampler import SamplingParams
from opsagent_tpu.serving.scheduler import Request, Scheduler

NUM_PAGES = 96


@pytest.fixture(scope="module")
def engine():
    return Engine(EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=NUM_PAGES, max_pages_per_seq=24, max_batch_size=4,
        prefill_buckets=(8, 16), decode_block=4,
    ))


def assert_conservation(engine):
    acc = engine.alloc.accounting()
    assert acc["total"] == NUM_PAGES, acc


def test_concurrent_mixed_load_conserves_pages(engine):
    sched = Scheduler(engine)
    sched.start()
    errors: list[str] = []
    lock = threading.Lock()

    def mask_fn_all(generated):
        return np.ones((engine.model_cfg.vocab_size,), bool)

    def client(tid: int):
        rng = random.Random(tid)
        for i in range(6):
            n = rng.randint(3, 40)
            prompt = [257] + [rng.randint(1, 500) for _ in range(n - 1)]
            kind = (tid + i) % 4
            on_token = None
            mask_fn = None
            sampling = SamplingParams(max_tokens=rng.randint(2, 10))
            if kind == 1:
                sampling = SamplingParams(
                    max_tokens=6, temperature=0.9, top_k=8
                )
            elif kind == 2:
                mask_fn = mask_fn_all
            elif kind == 3:
                calls = []

                def boom(tok, calls=calls):  # "client went away"
                    calls.append(tok)
                    if len(calls) >= 2:
                        raise RuntimeError("gone")

                on_token = boom
            req = Request(prompt, sampling, mask_fn=mask_fn, on_token=on_token)
            sched.submit(req)
            if not req.done.wait(120):
                with lock:
                    errors.append(f"t{tid} r{i}: timed out")
                return
            if kind == 3:
                # Raising streams must fail ONLY their own request.
                if not req.error:
                    with lock:
                        errors.append(f"t{tid} r{i}: raising stream not failed")
            elif req.error:
                with lock:
                    errors.append(f"t{tid} r{i}: {req.error}")
            elif not req.tokens:
                with lock:
                    errors.append(f"t{tid} r{i}: no tokens")
            # Invariant under load (snapshot under the engine lock).
            with engine.lock:
                acc = engine.alloc.accounting()
            if acc["total"] != NUM_PAGES:
                with lock:
                    errors.append(f"t{tid} r{i}: page leak {acc}")

    threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive(), "stress client hung"
    finally:
        sched.stop()
    assert errors == []
    # Quiesced: nothing running, nothing leaked, everything conserved.
    assert engine.sequences == {}
    assert_conservation(engine)
    assert engine.alloc.accounting()["owned"] == 0


def test_admissions_race_allocation_against_decode(engine):
    """Direct engine API from racing threads: begin/prefill/step/finish
    interleavings must never break conservation."""
    results: list[list[int]] = []
    errs: list[BaseException] = []

    def worker(seed: int):
        rng = random.Random(seed)
        try:
            for _ in range(4):
                n = rng.randint(3, 30)
                prompt = [257] + [rng.randint(1, 500) for _ in range(n - 1)]
                out = engine.generate(
                    [prompt], SamplingParams(max_tokens=rng.randint(2, 8))
                )
                results.append(out[0])
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
        assert not t.is_alive()
    assert errs == []
    assert len(results) == 12 and all(len(r) >= 1 for r in results)
    assert engine.sequences == {}
    assert_conservation(engine)


AGENT_TURNS = 3
AGENT_PAGES = 40


def test_agent_loop_prefix_reuse_under_pressure():
    """The agent-loop shape under page pressure: racing multi-turn
    sessions, each turn re-sending its grown history (prefix-trie
    borrowing on every turn >= 2), on a DEDICATED tightly-sized engine
    so the run does not depend on trie state other tests left behind.
    Invariants: no page leaks at any point; the trie was actually HIT
    (hit_tokens grew — a regression that silently disables matching
    cannot stay green); eviction actually FIRED (a post-phase squeeze
    prompt demands more pages than the free list holds, so the LRU
    branch must run); and greedy outputs are IDENTICAL to a quiesced
    serial replay of the same histories — trie hits, evictions, and
    admission interleavings must never change what a session decodes
    (the restart test's bit-identical guarantee, extended to
    cross-session cache churn)."""
    engine = Engine(EngineConfig(
        model="tiny-test", dtype=jnp.float32, tp=1, page_size=4,
        num_pages=AGENT_PAGES, max_pages_per_seq=24, max_batch_size=4,
        prefill_buckets=(8, 16), decode_block=4,
    ))
    sched = Scheduler(engine)
    sched.start()
    errors: list[str] = []
    lock = threading.Lock()
    recorded: dict[int, list[list[int]]] = {}

    def histories(sid: int):
        """Per-session deterministic inputs: the turn-1 prompt and one
        observation-marker token per turn (appended after each reply to
        grow the history, like the ReAct loop's tool observation)."""
        rng = random.Random(900 + sid)
        base = [257] + [rng.randint(1, 500) for _ in range(7)]
        markers = [rng.randint(1, 500) for _ in range(AGENT_TURNS)]
        return base, markers

    def session(sid: int) -> None:
        base, obs_markers = histories(sid)
        history = list(base)
        outs: list[list[int]] = []
        for turn in range(AGENT_TURNS):
            req = Request(list(history), SamplingParams(max_tokens=4))
            sched.submit(req)
            if not req.done.wait(180):
                with lock:
                    errors.append(f"s{sid} t{turn}: timeout")
                return
            if req.error:
                with lock:
                    errors.append(f"s{sid} t{turn}: {req.error}")
                return
            outs.append(list(req.tokens))
            history += req.tokens + [obs_markers[turn]]
            with engine.lock:
                acc = engine.alloc.accounting()
            if acc["total"] != AGENT_PAGES:
                with lock:
                    errors.append(f"s{sid} t{turn}: page leak {acc}")
                return
        with lock:
            recorded[sid] = outs

    threads = [
        threading.Thread(target=session, args=(s,)) for s in range(4)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
            assert not t.is_alive(), "agent-loop stress session hung"
    finally:
        sched.stop()
    assert errors == [], errors
    assert sorted(recorded) == [0, 1, 2, 3]
    assert engine.sequences == {}
    assert engine.alloc.accounting()["total"] == AGENT_PAGES
    # Turn >= 2 prompts extend turn-1 histories the trie has seen: reuse
    # must have actually happened, not just produced correct output.
    assert engine.alloc.hit_tokens > 0

    # Deterministic eviction squeeze: 4 sessions x 6+ full pages donated
    # > AGENT_PAGES - 22, so an 80-token prompt (20 pages + lookahead)
    # cannot be served from the free list alone — the LRU eviction
    # branch MUST run for this to succeed.
    before = engine.alloc.evictions
    squeeze_rng = random.Random(7)
    squeeze = [257] + [squeeze_rng.randint(1, 500) for _ in range(79)]
    out = engine.generate([squeeze], SamplingParams(max_tokens=2))[0]
    assert len(out) >= 1
    assert engine.alloc.evictions > before, "squeeze did not force eviction"
    assert engine.alloc.accounting()["total"] == AGENT_PAGES

    # Quiesced serial replay: same histories, no concurrency, whatever
    # trie state survived the squeeze. Greedy outputs must match turn
    # for turn.
    for sid in range(4):
        base, obs_markers = histories(sid)
        history = list(base)
        for turn in range(AGENT_TURNS):
            out = engine.generate([history], SamplingParams(max_tokens=4))[0]
            assert out == recorded[sid][turn], (
                f"s{sid} t{turn}: concurrent {recorded[sid][turn]} "
                f"!= serial {out}"
            )
            history += out + [obs_markers[turn]]
    assert engine.sequences == {}
    assert engine.alloc.accounting()["total"] == AGENT_PAGES
